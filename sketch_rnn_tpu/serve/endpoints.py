"""Multi-task serving endpoints: completion, reconstruction, interpolation.

ISSUE 15 tentpole. The paper's model is a seq2seq VAE whose whole point
is CONDITIONAL use — encode a sketch (or a prefix) to z, then decode —
yet the serving fleet exposed exactly one workload: unconditional
generation. This module opens the workload up as first-class endpoints
over the existing engine/fleet/admission/cache machinery:

- ``generate``     — the engine's native path, untouched (a pure-
  generate burst compiles and runs the exact pre-endpoint program).
- ``complete``     — encode a stroke-3 ``prefix`` with the
  bidirectional encoder (posterior mean, deterministic), seed the
  decoder carry by REPLAYING the prefix teacher-forced, then decode the
  continuation through the normal chunked pool (the carry + last
  prefix row ride the pool's new init leaves, serve/engine.py).
- ``reconstruct``  — encode a full sketch -> z = mu -> a plain decode
  conditioned on it: the round trip the reference notebook demos.
- ``interpolate``  — encode TWO sketches, slerp a ``frames``-latent
  grid (sample/interpolate.py — the same function the offline path
  uses, so parity is structural), and decode the grid as a batch of
  child rows; the parent books ONE result carrying the frame list.

**The fixed-geometry encode program.** Prefix lengths vary per request,
and a shape-per-length encode would compile per prefix — poison for a
server (the exact failure bucketed execution solved for training).
:class:`EncodeProgram` therefore pads every prefix to a small ladder of
bucket edges (``hps.serve_prefix_edges``, default
:func:`default_prefix_edges`) and a FIXED row count (the engine's slot
width), so the JitCompileProbe sees exactly one ``serve_encode``
compile per (pool rows, edge) geometry — the PR 4/8 house discipline.
Padding is bitwise-invisible to the outputs: the encoder's final states
are gathered at ``seq_len`` (pad steps past it contribute exact zeros
through the one-hot contraction), and the replay scan masks carry
updates at ``t < seq_len``, so a prefix encodes identically at every
edge that fits it and in every batch composition — the invariance the
test suite pins.

**Planning contract.** Everything here is a pure function of (prefix,
params): the planner stamps derived decode state onto requests
(``z`` / ``init_carry`` / ``init_prev``) and expands interpolations
into child rows with ``fold_in(parent_key, frame)`` keys, then the
engine's per-request RNG takes over. Scheduling still changes WHEN,
never WHAT — completion/reconstruction/interpolation strokes are
bitwise independent of batch composition, replica placement and
arrival order, exactly like generation.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.runtime.scheduler import default_scheduler
from sketch_rnn_tpu.utils.telemetry import (
    JitCompileProbe,
    critical_path_segments,
    endpoint_series,
    get_telemetry,
    request_span_id,
    request_trace_id,
    span_link,
)

ENDPOINTS = ("generate", "complete", "reconstruct", "interpolate")
ENCODER_ENDPOINTS = ("complete", "reconstruct", "interpolate")

# default latent-grid size of an interpolate request (the notebook's
# canonical 10-frame strip); Request.frames overrides per request
DEFAULT_FRAMES = 10

# interpolation FRAME rows get engine uids far above any real request
# uid: child_uid = CHILD_UID_BASE + parent_uid * CHILD_UID_STRIDE +
# frame. Pure in (parent uid, frame) — no shared allocator, the
# utils/faults no-RNG-stream discipline — and collision-free for
# parent uids < 2**28 at frames < 4096.
CHILD_UID_BASE = 1 << 40
CHILD_UID_STRIDE = 4096


def default_prefix_edges(max_seq_len: int) -> Tuple[int, ...]:
    """The small prefix-pad ladder used when ``hps.serve_prefix_edges``
    is unset: powers of two below ``max_seq_len`` plus the terminal
    edge — a handful of compiled encode geometries covering QuickDraw's
    length range."""
    return tuple(e for e in (32, 64, 128) if e < max_seq_len) \
        + (int(max_seq_len),)


def prefix_edges(hps: HParams) -> Tuple[int, ...]:
    """The effective prefix bucket ladder (configured or default)."""
    edges = tuple(hps.serve_prefix_edges) or \
        default_prefix_edges(hps.max_seq_len)
    if edges[-1] < hps.max_seq_len:
        edges = edges + (hps.max_seq_len,)
    return edges


def prefix_edge_of(length: int, edges: Sequence[int]) -> int:
    """Smallest edge that fits a ``length``-row prefix."""
    for e in edges:
        if length <= e:
            return int(e)
    raise ValueError(f"prefix length {length} exceeds the terminal "
                     f"edge {edges[-1]}")


def _check_prefix(prefix, edges: Sequence[int], what: str) -> np.ndarray:
    try:
        p = np.asarray(prefix, np.float32)
    except (ValueError, TypeError) as e:
        raise ValueError(f"{what}: prefix is not a stroke-3 array "
                         f"({e})") from None
    if p.ndim != 2 or p.shape[1] != 3 or len(p) < 1:
        raise ValueError(f"{what}: prefix must be a stroke-3 "
                         f"[n >= 1, 3] array, got shape {p.shape}")
    if len(p) > edges[-1]:
        raise ValueError(f"{what}: prefix has {len(p)} rows but the "
                         f"terminal prefix edge is {edges[-1]} "
                         f"(= max_seq_len)")
    if not np.isfinite(p).all():
        raise ValueError(f"{what}: prefix contains non-finite values")
    return p


def validate_request(req, hps: HParams, pool_cap: int = 0) -> None:
    """Fail-fast endpoint/shape validation — the door check the fleet
    (and ``cli serve-bench``'s pre-restore spec validation) runs.

    Raises ``ValueError`` with one actionable line; notably,
    unconditional checkpoints reject every encoder endpoint naming
    ``hps.conditional`` (the satellite contract)."""
    ep = req.endpoint or "generate"
    if ep not in ENDPOINTS:
        raise ValueError(f"unknown endpoint {ep!r}; this server "
                         f"speaks {ENDPOINTS}")
    if ep == "generate":
        if req.prefix is not None:
            raise ValueError(
                "generate requests carry no prefix (use endpoint="
                "'complete' to continue a stroke prefix)")
        return
    if not hps.conditional:
        raise ValueError(
            f"endpoint {ep!r} needs the bidirectional encoder but "
            f"this checkpoint is unconditional (hps.conditional="
            f"false)")
    edges = prefix_edges(hps)
    if ep == "interpolate":
        pair = req.prefix
        if pair is None or isinstance(pair, np.ndarray) or \
                len(pair) != 2:
            raise ValueError(
                "interpolate requests carry prefix=(sketch_a, "
                "sketch_b) — exactly two stroke-3 arrays")
        frames = int(req.frames) or DEFAULT_FRAMES
        if frames < 2:
            raise ValueError(f"interpolate needs frames >= 2, got "
                             f"{frames}")
        if pool_cap and frames > pool_cap:
            raise ValueError(
                f"interpolate frames {frames} exceed the fleet's "
                f"pool_cap {pool_cap} — the grid must fit one "
                f"micro-burst")
        for side, p in zip("ab", pair):
            _check_prefix(p, edges, f"interpolate prefix {side}")
    else:
        _check_prefix(req.prefix, edges, ep)


def pool_rows_of(req) -> int:
    """Decode-pool rows one request occupies (the fleet's cost-aware
    micro-burst chop): an interpolation decodes ``frames`` child rows,
    everything else exactly one."""
    if (req.endpoint or "generate") == "interpolate":
        return int(req.frames) or DEFAULT_FRAMES
    return 1


# -- the fixed-geometry encode + prefix-replay program ------------------------


def pad_prefixes(prefixes: Sequence[np.ndarray], edge: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Stroke-3 prefixes -> the loader's batch layout at pad ``edge``:
    ``strokes [B, edge + 1, 5]`` (start token at t=0) + ``seq_len [B]``.
    Delegates to the ONE shared layout implementation
    (``data.native_batcher.pad_batch_numpy`` — also behind
    ``DataLoader._pad_batch``), which is what makes serve-path encodes
    bitwise equal to the offline loader-batch path by construction."""
    from sketch_rnn_tpu.data.native_batcher import pad_batch_numpy

    return pad_batch_numpy(list(prefixes), edge)


def make_encode_step(model, hps: HParams, params, edge: int,
                     kernel: str = "scan", param_args: bool = False):
    """Build the jitted encode + prefix-replay program for one edge.

    ``kernel`` (ISSUE 17) selects the teacher-forced replay core:
    ``"scan"`` is the `lax.scan` below (the bitwise fallback pin);
    ``"pallas"`` runs the replay as one fused cache-resident program
    (`ops.pallas_decode.replay_chunk`) — the carry stays in VMEM for
    all ``edge`` steps with the same ``t < seq_len`` row masking. The
    encoder pass and the mu/prev extraction are identical jnp either
    way; only the replay loop changes flavor.

    ``fn(strokes [B, edge+1, 5], seq_len [B], labels [B]?) ->
    (mu [B, Nz], carry_flat [B, C], prev [B, 5])``:

    - ``mu``: the deterministic posterior mean of each prefix (the
      encoder consumes ``strokes[1:]`` exactly like training /
      ``sample.interpolate.encode_mu``; pad steps past ``seq_len``
      cannot reach the gathered final states, so mu is bitwise
      pad-invariant across edges).
    - ``carry_flat``: the decoder carry after teacher-forcing the
      prefix — ``decoder_initial_carry(mu)`` advanced through inputs
      ``START, S_1 .. S_{p-1}`` with per-row masking at ``t <
      seq_len`` (rows past their length keep their carry, so batch
      padding is inert).
    - ``prev``: each row's LAST prefix stroke ``S_p`` — the decode
      loop's first input, so the continuation's first MDN draw is the
      model's prediction of ``S_{p+1}``.

    ``param_args=True`` (ISSUE 19): the weights ride as a traced
    TRAILING argument (``fn(strokes, seq_len, labels, params)``)
    instead of baked constants, so a multi-tenant value swap reuses
    the compiled program — the encode-side twin of
    ``make_chunk_step``'s value-paged mode.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    e = int(edge)

    if kernel not in ("scan", "pallas"):
        raise ValueError(
            f"kernel must be 'scan' or 'pallas', got {kernel!r}")
    if kernel == "pallas":
        from sketch_rnn_tpu.ops.pallas_decode import check_cell_kind
        check_cell_kind(hps.dec_model)

    def encode_impl(params, strokes, seq_len, labels):
        b = strokes.shape[0]
        x_tm = jnp.transpose(strokes, (1, 0, 2))       # [E+1, B, 5]
        mu, _ = model.encode(params, x_tm[1:], seq_len, train=False)
        carry0 = model.decoder_initial_carry(params, mu, b)
        inputs = x_tm[:-1]                             # [E, B, 5]

        if kernel == "pallas":
            from sketch_rnn_tpu.ops.pallas_decode import replay_chunk
            extra = model._decoder_extra(params, mu, labels)
            carry = replay_chunk(
                params["dec"], carry0[0], carry0[1], inputs, extra,
                seq_len, cell_kind=hps.dec_model,
                forget_bias=model.dec.forget_bias,
                compute_dtype=model.dec.compute_dtype)
        else:
            def step(carry, tx):
                t, x_prev = tx
                new_carry, _ = model.decode_step(params, carry, x_prev,
                                                 mu, labels)
                live = t < seq_len
                carry = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(
                        live.reshape((-1,) + (1,) * (new.ndim - 1)),
                        new, old),
                    new_carry, carry)
                return carry, None

            carry, _ = lax.scan(step, carry0,
                                (jnp.arange(e), inputs))
        flat = jnp.concatenate(jax.tree_util.tree_leaves(carry),
                               axis=-1)
        prev = jnp.take_along_axis(
            strokes,
            jnp.broadcast_to(seq_len[:, None, None].astype(jnp.int32),
                             (b, 1, 5)),
            axis=1)[:, 0]
        return mu, flat, prev

    if param_args:
        def fn(strokes, seq_len, labels, p):
            return encode_impl(p, strokes, seq_len, labels)
    else:
        baked = params

        def fn(strokes, seq_len, labels):
            return encode_impl(baked, strokes, seq_len, labels)
    return jax.jit(fn)


class EncodeProgram:
    """Per-device fixed-geometry endpoint encoder (the pre-decode burst
    phase).

    One compiled program per (``rows``, edge) geometry, each wrapped in
    a :class:`JitCompileProbe` named ``serve_encode`` so compile
    accounting (when/where/how long, flops/peak bytes) rides the ISSUE
    8 machinery — the acceptance pin is exactly one compile per
    geometry and ZERO inside a measured window (warm first, like the
    chunk program). ``device`` pins params and every input to one
    replica's device, the fleet's collective-free discipline.
    """

    # encode-phase parameter subset: encoder stacks + posterior
    # heads + decoder (replay) + the z->carry projection. presig
    # and the MDN projection are computed-then-discarded (XLA DCE
    # drops them from the compiled program) but model.encode /
    # decode_step read the leaves at trace time, so they ride along.
    _KEEP = ("enc_fwd", "enc_bwd", "mu_w", "mu_b", "presig_w",
             "presig_b", "dec", "dec_init_w", "dec_init_b",
             "class_embed", "out_w", "out_b")

    def __init__(self, model, hps: HParams, params, rows: int,
                 edges: Optional[Sequence[int]] = None, device=None,
                 replica_id: Optional[int] = None,
                 decode_kernel: Optional[str] = None,
                 param_dtype: Optional[str] = None,
                 param_args: bool = False):
        import jax

        if not hps.conditional:
            raise ValueError(
                "EncodeProgram needs a conditional model "
                "(hps.conditional=false has no encoder)")
        self.model = model
        self.hps = hps
        self.rows = int(rows)
        if self.rows < 1:
            raise ValueError(f"rows must be >= 1, got {rows}")
        self.edges = tuple(edges) if edges else prefix_edges(hps)
        self.device = device
        self.replica_id = replica_id
        # replay-kernel flavor + param precision label (ISSUE 17):
        # part of each edge program's probe geometry, like the chunk
        # program's — a flavor or precision change is a new compile in
        # the ledger, never a silent hit (defaults thread from hps)
        self.decode_kernel = str(decode_kernel
                                 or getattr(hps, "decode_kernel", "scan"))
        self.param_dtype = str(
            param_dtype or getattr(hps, "serve_quantize", "float32"))
        # value-paged params (ISSUE 19): like the chunk program, the
        # encode programs take the weights as a traced trailing
        # argument so a congruent tenant swap is a pure device_put —
        # the per-edge probes and their warm compile caches survive
        self.param_args = bool(param_args)
        self.params = jax.device_put(
            {k: params[k] for k in self._KEEP if k in params}, device)
        self._fns: Dict[int, JitCompileProbe] = {}

    def swap_params(self, params) -> None:
        """Value-swap the encode-phase weights (ISSUE 19). Requires
        ``param_args=True`` and a congruent tree — the compiled edge
        programs are reused, so the swap is compile-free."""
        import jax

        if not self.param_args:
            raise ValueError(
                "EncodeProgram.swap_params needs param_args=True (the "
                "baked-constant programs cannot take new values)")
        new = {k: params[k] for k in self._KEEP if k in params}
        old_leaves, old_tree = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_tree = jax.tree_util.tree_flatten(new)
        if old_tree != new_tree or any(
                getattr(o, "shape", None) != np.asarray(n).shape
                for o, n in zip(old_leaves, new_leaves)):
            raise ValueError(
                "EncodeProgram.swap_params needs a congruent param "
                "tree (same structure and leaf shapes)")
        self.params = jax.device_put(new, self.device)

    def _fn(self, edge: int) -> JitCompileProbe:
        if edge not in self._fns:
            self._fns[edge] = JitCompileProbe(
                make_encode_step(self.model, self.hps, self.params,
                                 edge, kernel=self.decode_kernel,
                                 param_args=self.param_args),
                "serve_encode",
                key_of=lambda a: (tuple(a[0].shape),
                                  self.decode_kernel, self.param_dtype),
                label_of=lambda a: (f"(B{a[0].shape[0]},"
                                    f"E{a[0].shape[1] - 1},"
                                    f"{self.decode_kernel},"
                                    f"{self.param_dtype})"))
            # ISSUE 20: edge programs join the unified runtime's
            # compile accounting alongside the chunk/train programs
            default_scheduler().register(self._fns[edge])
        return self._fns[edge]

    def warm(self) -> None:
        """Compile every edge program outside the measured window (one
        zero-prefix batch per edge, the prefix sized to hit exactly
        that edge's bucket) — the fleet's warm-then-measure order; the
        probe then reports measured-window calls as cache hits."""
        for edge in self.edges:
            self.encode([np.zeros((edge, 3), np.float32)],
                        [0] if self.hps.num_classes > 0 else None)

    def encode(self, prefixes: Sequence[np.ndarray],
               labels: Optional[Sequence[int]] = None
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Encode ``prefixes`` (stroke-3 arrays) through the bucketed
        fixed-geometry programs; returns ``(mu [n, Nz], carry_flat
        [n, C], prev [n, 5])`` aligned to the input order.

        Prefixes are grouped by their bucket edge, each group is padded
        to ``rows`` (pad rows are inert — per-row masking), and groups
        larger than ``rows`` run in chunks — so every call dispatches
        only the (rows, edge) geometries that were compiled once. The
        grouping rule itself lives on the unified dispatch runtime
        (ISSUE 20): :meth:`GeometryRunScheduler.bucket_runs` is the
        frozen port of the by-edge/fixed-rows loop, and every fetch is
        an accounted host sync on the shared ledger.
        """
        import jax

        n = len(prefixes)
        if n == 0:
            return (np.zeros((0, self.hps.z_size), np.float32),
                    np.zeros((0, self.model.dec.carry_size),
                             np.float32),
                    np.zeros((0, 5), np.float32))
        tel = get_telemetry()
        t0 = time.perf_counter()
        mu = np.zeros((n, self.hps.z_size), np.float32)
        carry = np.zeros((n, self.model.dec.carry_size), np.float32)
        prev = np.zeros((n, 5), np.float32)
        sched = default_scheduler()
        edges_seen: set = set()
        for edge, chunk in sched.bucket_runs(
                n, lambda i: prefix_edge_of(len(prefixes[i]),
                                            self.edges), self.rows):
            edges_seen.add(edge)
            fn = self._fn(edge)
            group = [prefixes[i] for i in chunk]
            pad = self.rows - len(group)
            if pad:
                group = group + [np.zeros((1, 3), np.float32)] * pad
            strokes, lens = pad_prefixes(group, edge)
            labs = None
            if self.hps.num_classes > 0:
                labs = np.zeros((self.rows,), np.int32)
                if labels is not None:
                    for j, i in enumerate(chunk):
                        labs[j] = int(labels[i])
            args = jax.device_put((strokes, lens, labs),
                                  self.device)
            if self.param_args:
                out = fn(*args, self.params)
            else:
                out = fn(*args)
            # one dispatch carried len(chunk) real rows (pad rows are
            # inert geometry filler, not scheduled work)
            sched.ledger.record_run(len(chunk), 1)
            g_mu, g_carry, g_prev = sched.fetch(out)
            for j, i in enumerate(chunk):
                mu[i] = g_mu[j]
                carry[i] = g_carry[j]
                prev[i] = g_prev[j]
        if tel.enabled:
            tel.emit_span(
                "encode_phase", "serve", t0, time.perf_counter(),
                args={"n_prefixes": n,
                      "edges": sorted(edges_seen),
                      **({"replica": self.replica_id}
                         if self.replica_id is not None else {})})
        return mu, carry, prev


# -- planning & assembly ------------------------------------------------------


@dataclasses.dataclass
class BatchPlan:
    """One micro-burst's endpoint plan: the decode-pool request list
    (originals stamped with derived state, interpolations replaced by
    their frame children) plus the parent assembly map."""

    engine_requests: List[Any]
    # parent_uid -> {"request": parent, "child_uids": [uid...]}
    parents: Dict[int, Dict[str, Any]]


def child_uid(parent_uid: int, frame: int) -> int:
    return CHILD_UID_BASE + int(parent_uid) * CHILD_UID_STRIDE \
        + int(frame)


def _encode_with_reuse(engine, encoder, index, jobs, labels_of):
    """Run one burst's encode phase through a shared
    :class:`~sketch_rnn_tpu.serve.tenants.PrefixReuseIndex` (ISSUE 19).

    Jobs are grouped by their radix key — ``(tenant, prefix-hash,
    edge, label)`` — BEFORE touching the index, so within-burst
    duplicates claim one compute (and can never self-deadlock on their
    own in-flight entry). Index hits stamp the stored host rows; the
    remaining distinct keys run through ``encoder.encode`` exactly
    once each and publish their rows, coalescing racing workers on
    other replicas. The encode program is deterministic in (prefix,
    params), so a stamped reuse is bitwise what recomputing would
    produce — which is what makes **encode computes == distinct
    (tenant, prefix, edge)** a safe identity rather than an
    approximation.
    """
    n = len(jobs)
    hps = engine.hps
    mu = np.zeros((n, hps.z_size), np.float32)
    carry = np.zeros((n, engine.model.dec.carry_size), np.float32)
    prev = np.zeros((n, 5), np.float32)
    tenant = getattr(engine, "serving_tenant", "") or engine.ckpt_id
    groups: Dict[tuple, List[int]] = {}
    order: List[tuple] = []
    for pos, (r, _side, prefix) in enumerate(jobs):
        key = index.key(tenant, prefix,
                        prefix_edge_of(len(prefix), encoder.edges),
                        int(r.label or 0))
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(pos)
    compute_keys: List[tuple] = []
    for key in order:
        status, rows = index.acquire(key)
        if status == "hit":
            for pos in groups[key]:
                mu[pos], carry[pos], prev[pos] = rows
        else:
            compute_keys.append(key)
    try:
        if compute_keys:
            reps = [jobs[groups[key][0]] for key in compute_keys]
            c_mu, c_carry, c_prev = encoder.encode(
                [j[2] for j in reps], labels_of(reps))
            for i, key in enumerate(compute_keys):
                rows = (c_mu[i].copy(), c_carry[i].copy(),
                        c_prev[i].copy())
                index.fill(key, rows)
                for pos in groups[key]:
                    mu[pos], carry[pos], prev[pos] = rows
    except BaseException:
        # release unfilled claims so a coalesced waiter can take over
        # (fill already popped the successful ones — abandon is a
        # no-op for those)
        for key in compute_keys:
            index.abandon(key)
        raise
    # within-burst duplicates beyond each group's representative also
    # avoided an encode; fold them into the index's reuse ledger (the
    # acquire-hit path counted the cross-burst ones)
    index.note_reuses(n - len(compute_keys)
                      - (len(order) - len(compute_keys)))
    tel = get_telemetry()
    if tel.enabled:
        tel.counter("encode_compute", len(compute_keys), cat="serve")
        tel.counter("encode_reuse", n - len(compute_keys), cat="serve")
    return mu, carry, prev


def plan_batch(engine, requests: Sequence[Any]) -> BatchPlan:
    """Run the encode phase for one burst and build its decode plan.

    Pure-generate bursts short-circuit to an identity plan (zero
    overhead on the legacy path). Encoder-endpoint requests are stamped
    IN PLACE with their derived decode state — deterministic in
    (prefix, params), so a failover re-plan on a surviving replica
    restamps identical values. Interpolations expand into ``frames``
    child rows keyed ``fold_in(parent_key, frame)``; the parent books
    one result at :func:`assemble_results`.
    """
    import jax

    needs = [r for r in requests
             if (r.endpoint or "generate") != "generate"
             and r.parent_uid is None]
    if not needs:
        return BatchPlan(list(requests), {})
    for r in needs:
        validate_request(r, engine.hps)
        if r.uid is None:
            raise ValueError(
                "endpoint requests need explicit uids before planning "
                "(the fleet/serve_requests allocators assign them)")
    encoder = engine.encoder
    jobs: List[Tuple[Any, int, np.ndarray]] = []  # (req, side, prefix)
    for r in needs:
        if r.endpoint == "interpolate":
            jobs.append((r, 0, np.asarray(r.prefix[0], np.float32)))
            jobs.append((r, 1, np.asarray(r.prefix[1], np.float32)))
        else:
            jobs.append((r, 0, np.asarray(r.prefix, np.float32)))
    labels_of = (lambda js: [j[0].label for j in js]) \
        if engine.hps.num_classes > 0 else (lambda js: None)
    index = getattr(engine, "encode_reuse", None)
    if index is None:
        mu, carry, prev = encoder.encode([j[2] for j in jobs],
                                         labels_of(jobs))
    else:
        mu, carry, prev = _encode_with_reuse(engine, encoder, index,
                                             jobs, labels_of)
    enc_of: Dict[Tuple[int, int], int] = {
        (id(j[0]), j[1]): k for k, j in enumerate(jobs)}

    engine_requests: List[Any] = []
    parents: Dict[int, Dict[str, Any]] = {}
    for r in requests:
        ep = r.endpoint or "generate"
        if ep == "generate" or r.parent_uid is not None:
            engine_requests.append(r)
            continue
        if ep == "reconstruct":
            r.z = mu[enc_of[(id(r), 0)]]
            engine_requests.append(r)
        elif ep == "complete":
            k = enc_of[(id(r), 0)]
            r.z = mu[k]
            r.init_carry = carry[k]
            r.init_prev = prev[k]
            engine_requests.append(r)
        else:  # interpolate
            from sketch_rnn_tpu.sample.interpolate import \
                interpolate_latents

            frames = int(r.frames) or DEFAULT_FRAMES
            mu0 = mu[enc_of[(id(r), 0)]]
            mu1 = mu[enc_of[(id(r), 1)]]
            grid = np.asarray(
                interpolate_latents(mu0, mu1, n=frames), np.float32)
            kids = []
            for f in range(frames):
                cuid = child_uid(r.uid, f)
                kids.append(dataclasses.replace(
                    r, uid=cuid, key=jax.random.fold_in(r.key, f),
                    z=grid[f], prefix=None, frames=0,
                    parent_uid=r.uid, cls=None, queue_pos=None))
                engine_requests.append(kids[-1])
            parents[r.uid] = {"request": r,
                              "child_uids": [k.uid for k in kids]}
    return BatchPlan(engine_requests, parents)


def assemble_results(plan: BatchPlan, engine_results: Sequence[Any],
                     slo=None) -> List[Any]:
    """Fold one burst's engine results back to request-level results.

    Non-interpolate results pass through (the engine already stamped
    their endpoint); each interpolate parent books ONE result whose
    ``frames`` hold the per-frame strokes (``strokes5`` is their
    concatenation), whose latency clock spans arrival -> last frame,
    and whose ``attributed_steps`` is the exact integer sum of its
    frames' — the cost identity stays closed. The parent's telemetry
    (root span + complete instant + per-endpoint series) and its SLO
    observation (``slo`` — the single-engine path's tracker; the
    engine skips frame children so attainment counts REQUESTS) are
    emitted here, since the engine only ever saw the children."""
    from sketch_rnn_tpu.serve.engine import Result

    if not plan.parents:
        return list(engine_results)
    child_parent: Dict[int, int] = {}
    for puid, rec in plan.parents.items():
        for cuid in rec["child_uids"]:
            child_parent[cuid] = puid
    by_uid = {r.uid: r for r in engine_results}
    tel = get_telemetry()
    out: List[Any] = []
    done_parents = set()
    for r in engine_results:
        puid = child_parent.get(r.uid)
        if puid is None:
            out.append(r)
            continue
        if puid in done_parents:
            continue
        rec = plan.parents[puid]
        kids = [by_uid.get(c) for c in rec["child_uids"]]
        if any(k is None for k in kids):
            continue  # a later result completes the grid
        done_parents.add(puid)
        parent = rec["request"]
        frames = [k.strokes5 for k in kids]
        queue_wait = min(k.queue_wait_s for k in kids)
        latency = max(k.latency_s for k in kids)
        res = Result(
            uid=puid,
            strokes5=np.concatenate(frames),
            length=sum(k.length for k in kids),
            steps=sum(k.steps for k in kids),
            queue_wait_s=queue_wait,
            decode_s=latency - queue_wait,
            latency_s=latency,
            attributed_steps=sum(k.attributed_steps for k in kids),
            endpoint="interpolate",
            frames=frames,
            # all frames of one interpolation decode on one engine
            # (coherent-placement contract), so the parent inherits a
            # single version stamp (ISSUE 16)
            ckpt_id=kids[0].ckpt_id)
        out.append(res)
        if slo is not None:
            slo.observe("interpolate", {
                "queue_wait_s": res.queue_wait_s,
                "decode_s": res.decode_s,
                "latency_s": res.latency_s})
        if tel.enabled:
            now = time.perf_counter()
            trace_id = request_trace_id(puid)
            root_id = request_span_id("request", puid)
            tel.emit_span(
                "request", "serve", now - res.latency_s, now,
                args={"uid": puid, "endpoint": "interpolate"},
                trace=span_link(trace_id, root_id))
            tel.instant(
                "complete", cat="serve", ts=now,
                args={"uid": puid, "endpoint": "interpolate",
                      "steps": res.steps, "length": res.length,
                      "queue_wait_s": res.queue_wait_s,
                      "decode_s": res.decode_s,
                      "latency_s": res.latency_s,
                      "segments": [
                          [k, v] for k, v in critical_path_segments(
                              res.queue_wait_s, res.latency_s)],
                      "attributed_steps": res.attributed_steps,
                      "frames": len(frames),
                      **({"class": parent.cls} if parent.cls else {})},
                trace=span_link(trace_id,
                                request_span_id("complete", puid),
                                root_id))
            tel.counter(endpoint_series("requests_completed",
                                        "interpolate"), 1.0,
                        cat="serve")
            tel.observe(endpoint_series("latency_s", "interpolate"),
                        res.latency_s, cat="serve")
            if parent.cls is not None:
                from sketch_rnn_tpu.utils.telemetry import class_series
                tel.observe(class_series("latency_s", parent.cls),
                            res.latency_s, cat="serve")
    return out


def serve_requests(model, hps: HParams, params, requests: List[Any],
                   slots: int = 0, chunk: int = 0,
                   max_len: Optional[int] = None, greedy: bool = False,
                   recycle: bool = True, pool_pad: int = 0, slo=None,
                   engine=None) -> Dict[str, Any]:
    """One-call multi-task API: plan the endpoint batch, serve it
    through a (given or fresh) engine, assemble request-level results.

    This is THE offline reference path the serve-vs-offline parity
    pins compare against: the fleet's per-replica workers run exactly
    this plan/run/assemble sequence, so fleet strokes equal these
    bitwise — and ``cli sample --interpolate/--reconstruct`` ride it
    too, which is what makes the CLI's strokes bitwise equal to the
    serve endpoint's on the same checkpoint/key."""
    from sketch_rnn_tpu.serve.engine import ServeEngine

    eng = engine or ServeEngine(model, hps, params, slots=slots,
                                chunk=chunk, max_len=max_len,
                                greedy=greedy)
    for i, req in enumerate(requests):
        if req.uid is None:
            req.uid = i
        validate_request(req, hps)
    plan = plan_batch(eng, requests)
    out = eng.run(plan.engine_requests, recycle=recycle,
                  pool_pad=pool_pad, slo=slo)
    results = assemble_results(plan, out["results"], slo=slo)
    if slo is not None:
        # re-snapshot AFTER assembly so interpolate parents' SLO
        # observations (booked there, not in the engine) are in the
        # returned summary
        out["metrics"]["slo"] = slo.summary()
    return {"results": results, "metrics": out["metrics"],
            "engine": eng}


def build_mix_requests(hps: HParams, mix, n: int, seed: int, kreq,
                       z, pool, pool_labels, frames: int,
                       temperature: float, caps=None,
                       default_label: int = 0) -> List[Any]:
    """THE seeded mixed-endpoint request recipe, shared by ``cli
    serve-bench --endpoints`` and ``scripts/serve_bench.py
    --endpoints`` so the two workloads can never drift: endpoint per
    arrival from the weighted ``mix`` (``loadgen.endpoint_mix_ids`` —
    the stream a trace replay draws), per-request keys
    ``fold_in(kreq, i)``, prefixes deterministically indexed from
    ``pool`` with a 7919 stride, completions continuing the first half
    of their sketch, interpolations pairing a sketch with its stride-5
    partner. ``z [n, Nz]`` feeds generate requests (None for
    unconditional models); ``caps`` (optional ``[n]``) sets per-request
    ``max_len``."""
    import jax

    from sketch_rnn_tpu.serve.engine import Request
    from sketch_rnn_tpu.serve.loadgen import endpoint_mix_ids

    names = [m[0] for m in mix]
    ids = endpoint_mix_ids(n, mix, seed)
    requests: List[Any] = []
    for i in range(n):
        ep = names[int(ids[i])]
        key_i = jax.random.fold_in(kreq, i)
        cap = None if caps is None else int(caps[i])
        if ep == "generate":
            requests.append(Request(
                key=key_i, z=None if z is None else z[i],
                label=default_label, temperature=temperature,
                max_len=cap, endpoint="generate"))
            continue
        j = (i * 7919) % len(pool)
        label = (int(pool_labels[j]) if hps.num_classes > 0
                 else default_label)
        if ep == "interpolate":
            requests.append(Request(
                key=key_i, endpoint="interpolate",
                prefix=(pool[j], pool[(j + 5) % len(pool)]),
                frames=frames, label=label, temperature=temperature,
                max_len=cap))
        elif ep == "complete":
            p = pool[j]
            requests.append(Request(
                key=key_i, endpoint="complete",
                prefix=p[:max(1, len(p) // 2)], label=label,
                temperature=temperature, max_len=cap))
        else:
            requests.append(Request(
                key=key_i, endpoint="reconstruct", prefix=pool[j],
                label=label, temperature=temperature, max_len=cap))
    return requests


# -- endpoint -> admission-class mapping --------------------------------------


def parse_endpoint_specs(specs: Sequence[str], classes=None
                         ) -> Tuple[Dict[str, str], Dict[str, Any]]:
    """Parse ``--endpoints`` specs into (endpoint -> class name, class
    table).

    Grammar, riding the existing ``parse_slo`` class grammar:

    - ``complete=interactive:p95<=250ms`` — declare class
      ``interactive`` (a latency SLO, the ``--classes`` grammar) and
      route ``complete`` requests to it.
    - ``interpolate=batch`` — route to class ``batch``; declared as a
      no-deadline class if ``--classes`` did not already declare it.

    ``classes`` seeds the table (spec order = priority, the
    ``parse_admission_classes`` contract); endpoint-declared classes
    append after it. Unknown endpoints and duplicate routes fail with
    one actionable line — ``cli serve-bench`` runs this BEFORE the
    checkpoint restore (the ``--slo``/``--classes`` precedent).
    """
    from sketch_rnn_tpu.serve.admission import AdmissionClass
    from sketch_rnn_tpu.serve.slo import SLO, parse_slo

    table: Dict[str, Any] = dict(classes) if classes else {}
    ep_map: Dict[str, str] = {}
    for spec in specs:
        if "=" not in spec:
            raise ValueError(
                f"bad endpoint spec {spec!r}: want ENDPOINT=CLASS "
                f"(e.g. 'complete=interactive:p95<=250ms' or "
                f"'interpolate=batch')")
        ep, _, right = spec.partition("=")
        ep, right = ep.strip(), right.strip()
        if ep not in ENDPOINTS:
            raise ValueError(f"unknown endpoint {ep!r} in {spec!r}; "
                             f"want one of {ENDPOINTS}")
        if ep in ep_map:
            raise ValueError(f"duplicate endpoint route for {ep!r} "
                             f"(from {spec!r})")
        if not right:
            raise ValueError(f"empty class in endpoint spec {spec!r}")
        if "<=" in right:
            slo = parse_slo(right)
            name = slo.endpoint
            if name in table:
                # a re-declaration must MATCH the existing class: a
                # conflicting objective silently judged by the other
                # spec is exactly the operator error this parser
                # exists to catch
                have = table[name].slo
                if (have.objective_s, have.target, have.metric) != \
                        (slo.objective_s, slo.target, slo.metric):
                    raise ValueError(
                        f"endpoint spec {spec!r} re-declares class "
                        f"{name!r} with a different objective "
                        f"({slo.key} vs the declared {have.key}) — "
                        f"drop one or make them agree")
            else:
                table[name] = AdmissionClass(name=name, slo=slo,
                                             priority=len(table))
        else:
            name = right
            if name not in table:
                # a bare class reference declares a no-deadline class
                # (the batch-style default) when --classes did not
                table[name] = AdmissionClass(
                    name=name,
                    slo=SLO(objective_s=math.inf, target=0.95,
                            endpoint=name),
                    priority=len(table))
        ep_map[ep] = name
    return ep_map, table
