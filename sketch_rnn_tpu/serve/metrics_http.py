"""Live ``/metrics`` + ``/healthz`` endpoint over the telemetry core.

ISSUE 7 tentpole piece 1: PR 6's telemetry core already maintains every
number an operator needs — monotonic counters, sampled gauges, exact
span aggregates and streaming log-bucket histograms — but only exports
them at process exit. This module puts a stdlib ``http.server`` thread
in front of the LIVE core, so a serve-bench (and later the serving
fleet) can be scraped mid-run:

- ``GET /metrics`` — Prometheus text exposition (version 0.0.4)
  rendered straight from one consistent :meth:`Telemetry.snapshot`;
  NO new bookkeeping exists here — every series is a view of a store
  the runtime already maintains. Streaming histograms export their
  log buckets as cumulative ``le=`` buckets, so any Prometheus stack
  recovers the same p50/p95/p99 the in-process summary reports (within
  one geometric bucket, the documented <=~4.5% relative error).
- ``GET /healthz`` — JSON liveness + the SLO verdict: ``ok`` while
  every tracked SLO (serve/slo.py) with enough observations is in
  compliance, ``degraded`` otherwise (HTTP 200 either way — health
  probes distinguish by body; a refused connection means dead).

The server resolves :func:`get_telemetry` per request, so it follows a
late ``configure()`` / ``disable()`` exactly like every other probe
site; with telemetry disabled ``/metrics`` serves the meta series only
(``sketch_rnn_telemetry_enabled 0``) rather than erroring, which keeps
scrape pipelines alive across un-traced runs.

OFF by default, like the core: nothing in the runtime starts a server
unless asked (``cli serve-bench --metrics_port=...``). Every started
server registers in a module-level set so the tier-1 conftest guard can
prove no test leaks a listening socket (:func:`stop_all`).
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from sketch_rnn_tpu.utils.telemetry import (
    Telemetry,
    get_telemetry,
    json_safe,
)

PREFIX = "sketch_rnn"
_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

# every live server, for the conftest no-stray-sockets guard
_LIVE: set = set()
_LIVE_LOCK = threading.Lock()


def _metric_name(cat: str, name: str, suffix: str = "") -> str:
    """``sketch_rnn_<cat>_<name><suffix>`` with Prometheus-legal chars."""
    base = f"{PREFIX}_{cat}_{name}{suffix}"
    return _NAME_RE.sub("_", base)


def _label_escape(v: str) -> str:
    """Prometheus exposition label-value escaping (backslash, quote,
    newline): run_id comes verbatim from SKETCH_RNN_RUN_ID, and an
    unescaped quote would invalidate the WHOLE scrape."""
    return (v.replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt(v: float) -> str:
    """Prometheus sample value: integers without a trailing .0 (exact
    counts must scrape as exact counts), floats via repr (no rounding).
    Non-finite values use the exposition-format literals — a p100 SLO's
    infinite burn rate must not 500 every scrape (int(inf) raises)."""
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(tel: Telemetry,
                      slo: Optional[object] = None,
                      health: Optional[object] = None) -> str:
    """Render the core's live state as Prometheus text exposition.

    Pure function of one :meth:`Telemetry.snapshot` (single lock
    acquisition — a scrape is internally consistent) plus an optional
    :class:`~sketch_rnn_tpu.serve.slo.SLOTracker`. Series:

    - counters  -> ``<prefix>_<cat>_<name>_total`` (counter)
    - gauges    -> ``<prefix>_<cat>_<name>`` (gauge, latest sample)
    - span aggs -> ``..._seconds_total`` + ``..._spans_total``
    - histograms -> ``..._bucket{le=...}`` / ``_sum`` / ``_count``
    - SLOs      -> ``<prefix>_slo_*{slo="endpoint:metric:pNN"}``
    - meta      -> ``<prefix>_up``, ``_telemetry_enabled``,
      ``_telemetry_dropped_events_total``, ``_uptime_seconds``

    ``health`` (an optional ``ServeFleet.health`` callable, ISSUE 16)
    adds the ``<prefix>_serving_ckpt_info`` label series — the info-
    metric idiom (like ``run_info``): value 1, the serving checkpoint
    identity in the ``ckpt_id`` label, so a scrape can alert on a
    version change without parsing /healthz.
    """
    lines = []

    def emit(name: str, mtype: str, samples, help_: str = ""):
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            lines.append(f"{name}{labels} {_fmt(value)}")

    snap = tel.snapshot()
    emit(f"{PREFIX}_up", "gauge", [("", 1)],
         "process is serving metrics")
    # run identity (ISSUE 8): the labels that join a scrape to the
    # run's trace shards, bench rows and RUN.json manifest
    run_lab = (f'{{run_id="{_label_escape(tel.run_id or "")}",'
               f'host="{tel.process_index}",'
               f'host_count="{tel.host_count}"}}')
    emit(f"{PREFIX}_run_info", "gauge", [(run_lab, 1)],
         "run_id + fleet coordinate of this process")
    if health is not None:
        hx = health() if callable(health) else dict(health)
        ckpt_lab = (f'{{ckpt_id='
                    f'"{_label_escape(hx.get("serving_ckpt_id") or "")}'
                    f'"}}')
        emit(f"{PREFIX}_serving_ckpt_info", "gauge", [(ckpt_lab, 1)],
             "which params checkpoint the fleet currently serves")
    emit(f"{PREFIX}_telemetry_enabled", "gauge",
         [("", int(tel.enabled))],
         "1 when the telemetry core records events")
    emit(f"{PREFIX}_telemetry_dropped_events_total", "counter",
         [("", snap["dropped"])],
         "ring-buffer drops (aggregates stay exact)")
    emit(f"{PREFIX}_uptime_seconds", "gauge",
         [("", time.perf_counter() - tel.origin_perf)],
         "seconds since the telemetry core was constructed")
    for (cat, name), v in sorted(snap["counters"].items()):
        emit(_metric_name(cat, name, "_total"), "counter", [("", v)])
    for (cat, name), v in sorted(snap["gauges"].items()):
        emit(_metric_name(cat, name), "gauge", [("", v)])
    for (cat, name), (n, total) in sorted(snap["aggregates"].items()):
        emit(_metric_name(cat, name, "_seconds_total"), "counter",
             [("", total)], f"exact accumulated span seconds ({cat})")
        emit(_metric_name(cat, name, "_spans_total"), "counter",
             [("", n)])
    for (cat, name), h in sorted(snap["hists"].items()):
        base = _metric_name(cat, name)
        s = h["summary"]
        samples = [(f'{{le="{edge:.9g}"}}', cum)
                   for edge, cum in h["buckets"]]
        samples.append(('{le="+Inf"}', s["count"]))
        lines.append(f"# TYPE {base} histogram")
        for labels, value in samples:
            lines.append(f"{base}_bucket{labels} {_fmt(value)}")
        lines.append(f"{base}_sum {_fmt(h['total'])}")
        lines.append(f"{base}_count {_fmt(s['count'])}")
    if slo is not None:
        series: Dict[str, list] = {
            "objective_seconds": [], "target": [], "requests_total": [],
            "breaches_total": [], "compliance": [], "met": [],
            "burn_rate": [], "burn_rate_total": [],
        }
        for key, rec in sorted(slo.summary().items()):
            lab = f'{{slo="{key}"}}'
            series["objective_seconds"].append((lab, rec["objective_s"]))
            series["target"].append((lab, rec["target"]))
            series["requests_total"].append((lab, rec["total"]))
            series["breaches_total"].append((lab, rec["breaches"]))
            series["compliance"].append((lab, rec["compliance"]))
            series["met"].append((lab, int(rec["met"])))
            series["burn_rate"].append((lab, rec["burn_rate"]))
            series["burn_rate_total"].append((lab, rec["burn_rate_total"]))
        helps = {
            "breaches_total": "requests over their latency objective",
            "burn_rate": "rolling-window error-budget burn "
                         "(1.0 = spending exactly the budget)",
        }
        for suffix, samples in series.items():
            # only the request/breach tallies are monotonic; burn_rate_
            # total is a lifetime RATIO and must scrape as a gauge
            mtype = ("counter" if suffix in ("requests_total",
                                             "breaches_total")
                     else "gauge")
            emit(f"{PREFIX}_slo_{suffix}", mtype, samples,
                 helps.get(suffix, ""))
    return "\n".join(lines) + "\n"


def health_payload(tel: Telemetry,
                   slo: Optional[object] = None,
                   health: Optional[object] = None) -> Dict:
    """The ``/healthz`` body: liveness + the SLO verdict (+ the fleet
    failover verdict, ISSUE 10).

    ``health`` is an optional callable returning a dict with a
    ``healthy`` bool (``ServeFleet.health``): ``status`` reports
    ``degraded`` when EITHER a tracked SLO is out of compliance or the
    health source says so (dead replicas, failed requests), with the
    source's block included as evidence. A healthy fleet mid-resize
    (``scaling`` in the health block — an elastic retire still
    draining, ISSUE 12) reports ``scaling`` instead of flapping
    ok/degraded: an intentional topology change is not an incident.
    Likewise a fleet mid-model-rollout (``rolling``, ISSUE 16) reports
    ``rolling`` — which outranks ``scaling``, because the rollout
    walk's own retire/rejoin churn would otherwise masquerade as an
    autoscale — with the controller's evidence (from/to ckpt_id,
    replicas swapped/total) in the fleet block."""
    degraded = slo is not None and not slo.healthy()
    extra = None
    scaling = False
    rolling = False
    if health is not None:
        extra = health() if callable(health) else dict(health)
        degraded = degraded or not extra.get("healthy", True)
        scaling = bool(extra.get("scaling"))
        rolling = bool(extra.get("rolling"))
    return {
        "status": ("degraded" if degraded
                   else "rolling" if rolling
                   else "scaling" if scaling else "ok"),
        "telemetry_enabled": bool(tel.enabled),
        "dropped_events": tel.dropped,
        "uptime_s": round(time.perf_counter() - tel.origin_perf, 3),
        "slo": None if slo is None else json_safe(slo.summary()),
        "fleet": None if extra is None else json_safe(extra),
    }


class MetricsServer:
    """Background ``/metrics`` + ``/healthz`` HTTP server.

    ``port=0`` binds an ephemeral port (read :attr:`port` after
    :meth:`start`). Binds ``127.0.0.1`` by default — this is an
    operator/scraper surface, not a public one. ``telemetry`` defaults
    to resolving the process core per request (the probe-site
    discipline); pass an instance to pin one (tests).
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 slo: Optional[object] = None,
                 telemetry: Optional[Telemetry] = None,
                 health_source: Optional[object] = None):
        self.host = host
        self._requested_port = port
        self.slo = slo
        # optional health callable (ServeFleet.health) consulted per
        # /healthz request; assignable AFTER start() — the cli binds
        # the port before the (expensive) fleet build, then attaches
        self.health_source = health_source
        self._telemetry = telemetry
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def _resolve_telemetry(self) -> Telemetry:
        return self._telemetry if self._telemetry is not None \
            else get_telemetry()

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # no per-scrape stderr chatter
                pass

            def _send(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = render_prometheus(
                        server._resolve_telemetry(),
                        server.slo,
                        health=server.health_source).encode()
                    self._send(200, "text/plain; version=0.0.4;"
                                    " charset=utf-8", body)
                elif path == "/healthz":
                    body = json.dumps(health_payload(
                        server._resolve_telemetry(),
                        server.slo,
                        server.health_source)).encode()
                    self._send(200, "application/json", body)
                else:
                    self._send(
                        404, "text/plain",
                        b"not found; try /metrics or /healthz\n")

        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="metrics-http", daemon=True)
        self._thread.start()
        with _LIVE_LOCK:
            _LIVE.add(self)
        return self

    def stop(self) -> None:
        httpd, thread = self._httpd, self._thread
        self._httpd = self._thread = None
        with _LIVE_LOCK:
            _LIVE.discard(self)
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5)

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()

    def __repr__(self) -> str:
        state = ("down" if self._httpd is None
                 else f"http://{self.host}:{self.port}")
        return f"MetricsServer({state})"


def live_servers() -> Tuple["MetricsServer", ...]:
    with _LIVE_LOCK:
        return tuple(_LIVE)


def stop_all() -> Tuple[str, ...]:
    """Stop every live server; returns their reprs (the conftest guard
    asserts this is empty — a non-empty return names the leaker)."""
    leaked = live_servers()
    names = tuple(repr(s) for s in leaked)
    for s in leaked:
        s.stop()
    return names
