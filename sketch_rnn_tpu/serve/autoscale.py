"""SLO-driven elastic autoscaling: pure decisions, deterministic plans.

ISSUE 12 tentpole piece 2. The fleet (serve/fleet.py) can now grow and
shrink — ``add_replica`` / ``retire_replica`` are the PR 10 failover
primitives turned elastic (retire = drain + leave the placement set;
spawn = the rejoin path) — and this module decides WHEN. Two layers,
deliberately separated:

- :class:`Autoscaler` is a PURE controller: each decision epoch it is
  fed one :class:`AutoscaleSignals` snapshot — an estimated queueing
  wait (admission's backlog x EWMA service estimate) and an SLO
  error-budget burn rate (the SLOTracker's breach_frac over its
  budget) — and emits a :class:`Decision`. No clocks, no threads, no
  jax: the decision sequence is a deterministic function of the signal
  sequence, which is what makes it testable and replayable. The rule
  is the standard error-budget ladder: scale UP when the estimated
  wait exceeds ``up_wait_s`` (or the burn rate exceeds ``up_burn``),
  scale DOWN only after ``down_epochs`` consecutive quiet epochs, and
  hold through a ``cooldown_epochs`` refractory window after any
  action so the controller cannot flap.
- :func:`plan_decisions` is the DETERMINISTIC feeder for benchmarks:
  on this box wall-clock latencies are noise (the measured
  no-CPU-parallelism ceiling, see ROADMAP), so live SLO signals would
  make scale decisions unreproducible. Instead the traffic bench runs
  the same pure :meth:`Autoscaler.decide` over a fluid-queue model of
  the TRACE itself: per epoch, offered work (sum of arriving requests'
  decode steps, cache hits excluded) accumulates into a backlog that
  drains at ``policy.rate_hint_steps_per_s`` per live replica, and the
  modeled wait feeds the controller. Every input is a pure function of
  (trace seed, policy), so the emitted spawn/retire schedule is
  REPRODUCIBLE FROM THE TRACE SEED ALONE — the ISSUE 12 acceptance —
  and the fleet applies it at exact arrival indices during replay.

Live integration: :func:`fleet_signals` extracts the same signal shape
from a live SLOTracker + AdmissionController pair, so a production
loop can drive the identical controller from real measurements (the
decisions are then deterministic given the measurements, which is all
a wall-clock world can promise).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The scale-decision rule's knobs (all pure numbers — the policy
    is part of the experiment config, so decisions stay reproducible).

    ``rate_hint_steps_per_s`` is the provisioning model: slot-steps of
    decode work one replica is assumed to retire per second. The
    deterministic planner uses it to convert backlog steps into an
    estimated wait; a live loop ignores it (admission's EWMA measures
    the real thing).
    """

    min_replicas: int = 1
    max_replicas: int = 4
    up_wait_s: float = 1.0          # est wait above this -> scale up
    up_burn: float = 1.0            # burn rate above this -> scale up
    down_wait_s: float = 0.25       # est wait below this is "quiet"
    down_epochs: int = 3            # consecutive quiet epochs to retire
    cooldown_epochs: int = 2        # refractory window after any action
    step: int = 1                   # replicas per decision
    epoch_s: float = 0.25           # decision epoch (virtual seconds)
    rate_hint_steps_per_s: float = 0.0

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}/{self.max_replicas}")
        if self.step < 1 or self.down_epochs < 1 or self.epoch_s <= 0:
            raise ValueError("step/down_epochs must be >= 1 and "
                             "epoch_s > 0")


@dataclasses.dataclass(frozen=True)
class AutoscaleSignals:
    """One decision epoch's inputs. ``est_wait_s`` may be None (cold
    admission has no service estimate yet — never scale on nothing);
    ``burn_rate`` is the worst tracked SLO's window burn (0 when no
    SLO is tracked)."""

    est_wait_s: Optional[float] = None
    burn_rate: float = 0.0
    backlog: int = 0
    n_live: int = 1


@dataclasses.dataclass(frozen=True)
class Decision:
    """One epoch's verdict. ``action`` is ``up`` / ``down`` / ``hold``;
    ``target`` is the replica count AFTER applying it."""

    epoch: int
    action: str
    target: int
    reason: str
    est_wait_s: Optional[float] = None
    burn_rate: float = 0.0


class Autoscaler:
    """Pure scale controller; state = (cooldown, quiet-epoch streak).

    Feed :meth:`decide` once per decision epoch. The caller applies
    ``Decision.target`` (the fleet's ``set_target_replicas``); the
    controller assumes it was applied — it tracks its own intended
    replica count so the decision sequence is a function of the signal
    sequence alone, not of how fast the fleet resized.
    """

    def __init__(self, policy: AutoscalePolicy,
                 replicas: Optional[int] = None):
        self.policy = policy
        self.replicas = int(replicas if replicas is not None
                            else policy.min_replicas)
        if not (policy.min_replicas <= self.replicas
                <= policy.max_replicas):
            raise ValueError(
                f"start replicas {self.replicas} outside "
                f"[{policy.min_replicas}, {policy.max_replicas}]")
        self._cooldown = 0
        self._quiet = 0
        self._epoch = 0

    def decide(self, signals: AutoscaleSignals) -> Decision:
        p = self.policy
        epoch = self._epoch
        self._epoch += 1
        wait = signals.est_wait_s
        burn = float(signals.burn_rate)
        action, reason = "hold", "steady"
        target = self.replicas
        hot = ((wait is not None and wait > p.up_wait_s)
               or burn > p.up_burn)
        # a None wait (cold admission, no service estimate yet) is
        # ABSENCE of signal, not quiet: it must neither trigger a
        # scale-up nor count toward the retire streak — never scale
        # on nothing, in either direction
        quiet = (wait is not None and wait < p.down_wait_s
                 and burn <= p.up_burn)
        self._quiet = self._quiet + 1 if quiet else 0
        if self._cooldown > 0:
            self._cooldown -= 1
            reason = "cooldown"
        elif hot and self.replicas < p.max_replicas:
            target = min(p.max_replicas, self.replicas + p.step)
            action = "up"
            reason = (f"est_wait {wait:.3f}s > {p.up_wait_s}s"
                      if wait is not None and wait > p.up_wait_s
                      else f"burn {burn:.2f} > {p.up_burn}")
            self._cooldown = p.cooldown_epochs
            self._quiet = 0
        elif (self._quiet >= p.down_epochs
              and self.replicas > p.min_replicas):
            target = max(p.min_replicas, self.replicas - p.step)
            action = "down"
            reason = f"quiet for {self._quiet} epochs"
            self._cooldown = p.cooldown_epochs
            self._quiet = 0
        self.replicas = target
        return Decision(epoch=epoch, action=action, target=target,
                        reason=reason,
                        est_wait_s=(None if wait is None
                                    else round(float(wait), 6)),
                        burn_rate=round(burn, 4))


def fleet_signals(slo_tracker, admission, n_live: int
                  ) -> AutoscaleSignals:
    """Live signal extraction: the WORST tracked SLO's window burn rate
    plus admission's least-loaded estimated wait — the same shape the
    deterministic planner feeds, from real measurements."""
    burn = 0.0
    if slo_tracker is not None:
        for rec in slo_tracker.summary().values():
            b = rec.get("burn_rate", 0.0)
            if not math.isfinite(b):
                b = 1e9  # a p100 breach burns "infinitely": cap, act
            burn = max(burn, float(b))
    waits = [admission.est_wait_s(r) for r in admission.live_replicas]
    waits = [w for w in waits if w is not None]
    return AutoscaleSignals(
        est_wait_s=min(waits) if waits else None,
        burn_rate=burn,
        backlog=sum(admission.backlog),
        n_live=int(n_live))


def simulate_traffic(arrivals: Sequence[float],
                     content_ids: Sequence[int],
                     content_work: Sequence[float],
                     policy: AutoscalePolicy, *,
                     cache: bool = False,
                     autoscale: bool = True,
                     shed_wait_s: Optional[float] = None,
                     replicas: Optional[int] = None) -> Dict:
    """Deterministic fluid-queue replay of one traffic arm — THE
    scheduling-math engine behind every ISSUE 12 acceptance signal.

    On this box wall-clock latencies are noise (the measured
    no-CPU-parallelism ceiling, see ROADMAP), so the traffic bench's
    latency-vs-offered-load curves, shed fractions and scale decisions
    all come from this pure virtual-time model instead: arrival ``i``
    carries content ``content_ids[i]`` costing ``content_work[c]``
    decode steps. Per ``policy.epoch_s`` epoch, arrivals are processed
    in order against the current backlog — a ``cache`` arm serves a
    repeat of an already-admitted content at the door for zero work
    and zero modeled wait (the result cache's contract; a shed primary
    caches nothing, so the NEXT occurrence pays full work exactly like
    the real fleet) and a ``shed_wait_s`` arm sheds any arrival whose
    modeled wait ``backlog / (live x rate_hint)`` exceeds the deadline
    — then the backlog drains at ``live x rate_hint_steps_per_s`` and
    the epoch's closing estimated wait feeds the pure controller
    (``autoscale=False`` holds the fleet at its starting size, the
    fixed-provisioning baseline).

    Everything is a function of (arrivals, contents, policy, flags):
    two calls over the same realized trace return identical decisions,
    shed masks and modeled waits — the ISSUE 12 "reproducible from the
    trace seed alone" acceptance.

    Returns ``{decisions, admitted, cached, wait_s, shed_frac,
    device_steps, fleet_size_by_epoch, ...}`` where ``wait_s[i]`` is
    arrival ``i``'s modeled latency (queue wait + its own service;
    0 for a cache hit) and ``device_steps`` the admitted device work.
    """
    arrivals = np.asarray(arrivals, np.float64)
    content_ids = np.asarray(content_ids, np.int64)
    work = np.asarray(content_work, np.float64)
    if arrivals.shape != content_ids.shape:
        raise ValueError(f"arrivals {arrivals.shape} and content_ids "
                         f"{content_ids.shape} must align")
    rate = policy.rate_hint_steps_per_s
    if rate <= 0:
        raise ValueError("simulate_traffic needs policy."
                         "rate_hint_steps_per_s > 0 (the provisioning "
                         "model the modeled wait is derived from)")
    scaler = Autoscaler(policy, replicas=replicas)
    n = len(arrivals)
    horizon = float(arrivals[-1]) if n else 0.0
    # trailing quiet epochs sized so a fully scaled-up fleet can walk
    # all the way back down (one cooldown + quiet streak per retire
    # step), not just one epoch
    n_epochs = (int(horizon // policy.epoch_s) + 2
                + (policy.cooldown_epochs + policy.down_epochs + 1)
                * (policy.max_replicas - policy.min_replicas))
    backlog = 0.0
    stored: set = set()            # contents an admitted primary fills
    admitted = np.zeros(n, bool)
    hit = np.zeros(n, bool)
    wait_s = np.zeros(n, np.float64)
    decisions: List[Decision] = []
    i = 0
    for k in range(n_epochs):
        t1 = (k + 1) * policy.epoch_s
        live = scaler.replicas
        while i < n and arrivals[i] < t1:
            c = int(content_ids[i])
            if cache and c in stored:
                # served at the door: zero work, zero modeled wait
                admitted[i] = hit[i] = True
                i += 1
                continue
            est = backlog / (live * rate)
            if shed_wait_s is not None and est > shed_wait_s:
                i += 1              # shed: stores nothing
                continue
            w = float(work[c])
            backlog += w
            wait_s[i] = est + w / rate
            admitted[i] = True
            if cache:
                stored.add(c)
            i += 1
        backlog = max(0.0, backlog - live * rate * policy.epoch_s)
        est_wait = backlog / (live * rate)
        sig = AutoscaleSignals(est_wait_s=est_wait, burn_rate=0.0,
                               backlog=int(round(backlog)), n_live=live)
        if autoscale:
            decisions.append(scaler.decide(sig))
        else:
            decisions.append(Decision(
                epoch=k, action="hold", target=live, reason="fixed",
                est_wait_s=round(est_wait, 6)))
    n_adm = int(admitted.sum())
    lat = np.sort(wait_s[admitted]) if n_adm else np.zeros(1)
    pct = lambda p: round(  # noqa: E731
        float(lat[min(len(lat) - 1, int(p * len(lat)))]), 6)
    return {
        "decisions": decisions,
        "admitted": admitted,
        "cached": hit,
        "wait_s": wait_s,
        "n": n,
        "completed": n_adm,
        "shed": n - n_adm,
        "shed_frac": round((n - n_adm) / max(n, 1), 4),
        "hit_frac": round(float(hit.sum()) / max(n, 1), 4),
        "device_steps": int(work[content_ids[admitted & ~hit]].sum()),
        "latency_p50_s": pct(0.50),
        "latency_p95_s": pct(0.95),
        "latency_p99_s": pct(0.99),
        "fleet_size_by_epoch": [d.target for d in decisions],
    }


def plan_decisions(arrivals: Sequence[float],
                   work_steps: Sequence[float],
                   policy: AutoscalePolicy,
                   replicas: Optional[int] = None) -> List[Decision]:
    """The deterministic scale plan for a trace: the no-shed fluid
    replay of :func:`simulate_traffic` reduced to its decision list.

    ``arrivals`` are the trace's cumulative virtual-time offsets and
    ``work_steps[i]`` the decode steps arrival ``i`` will cost (0 for
    a predicted cache hit — repeats never touch a device, so they must
    not inflate the modeled backlog). Everything is a function of
    (trace, policy), so two calls with the same trace seed return the
    IDENTICAL decision list — the ISSUE 12 reproducibility acceptance
    — and the traffic bench applies it at exact arrival indices.

    Returns one :class:`Decision` per epoch covering the whole trace
    (plus one trailing epoch so a final quiet window can retire).
    """
    arrivals = np.asarray(arrivals, np.float64)
    work = np.asarray(work_steps, np.float64)
    if arrivals.shape != work.shape:
        raise ValueError(f"arrivals {arrivals.shape} and work_steps "
                         f"{work.shape} must align")
    return simulate_traffic(
        arrivals, np.arange(len(arrivals)), work, policy,
        cache=False, autoscale=True, shed_wait_s=None,
        replicas=replicas)["decisions"]


def decisions_summary(decisions: Sequence[Decision]) -> Dict:
    """Compact record for bench rows / RUN.json: the action timeline
    (hold epochs elided) plus the per-epoch fleet size."""
    actions = [dataclasses.asdict(d) for d in decisions
               if d.action != "hold"]
    return {
        "epochs": len(decisions),
        "actions": actions,
        "n_actions": len(actions),
        "fleet_size_by_epoch": [d.target for d in decisions],
        "final_replicas": (decisions[-1].target if decisions else None),
        "max_replicas_reached": max(
            (d.target for d in decisions), default=None),
    }
