"""SLA-aware admission: deadline/priority classes, least-loaded placement,
shed-on-overload.

ISSUE 9 tentpole piece: the fleet scheduler (serve/fleet.py) fronts R
replica engines with per-replica queues, and this module is the pure
decision layer between an arriving request and those queues. The design
follows the standard serving-SLO playbook (the Gemma-on-TPU comparison
in PAPERS.md reports exactly these knobs): every request carries an
**admission class** — a named latency deadline plus a priority — and
the controller answers one question per arrival: *which replica queue,
or shed now?*

- **Classes reuse the ``parse_slo`` grammar** (serve/slo.py): an
  admission class IS a latency SLO whose endpoint field names the
  class — ``interactive:p95<=250ms`` declares class ``interactive``
  with a 250 ms deadline. Priority is spec order (first = most
  important); the replica worker drains its queues in priority order,
  and each class's completions feed a per-class latency histogram and
  (optionally) an :class:`~sketch_rnn_tpu.serve.slo.SLOTracker` keyed
  by class name.
- **Least-loaded placement**: the controller tracks per-replica backlog
  (queued + running requests) and routes to the minimum (ties break to
  the lowest replica index — deterministic). Backlog is the ONLY
  placement signal, which is what makes replica placement provably
  invisible to outputs: it picks WHERE, never WHAT (the engine's
  per-request fold_in RNG already guarantees the rest).
- **Shed-on-overload**: a request is refused at the door when its
  class deadline is already unmeetable — estimated wait (backlog x
  the observed per-request service time / slots) exceeds the deadline
  — or when every replica's queue is at the hard cap. Shedding early
  is the point: a request that will blow its deadline anyway should
  cost zero device steps (open-loop load does not slow down because
  the server is slow — see serve/loadgen.py). Sheds are counted
  (``requests_shed_total`` + per-class) by the fleet.

The controller is deliberately PURE host-side state (no jax, no
threads, no clock reads): the fleet serializes calls under its own
lock and injects completion observations, so every decision is a
deterministic function of the arrival/completion history — which is
what the placement-invariance tests rely on.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

from sketch_rnn_tpu.serve.slo import SLO, parse_slo

# the class every request lands in when no classes are configured: no
# deadline (never shed on latency), lowest priority is irrelevant with
# one class
DEFAULT_CLASS = "default"


@dataclasses.dataclass(frozen=True)
class AdmissionClass:
    """One admission class: a named deadline + drain priority.

    ``slo`` carries the deadline (``objective_s``) and the quantile
    target the class is judged by; ``priority`` orders queue draining
    (0 = most important = drained first).
    """

    name: str
    slo: SLO
    priority: int = 0

    @property
    def deadline_s(self) -> float:
        return self.slo.objective_s


def parse_admission_classes(specs: Sequence[str]
                            ) -> Dict[str, AdmissionClass]:
    """Parse ``--classes`` specs into an ordered class table.

    Each spec uses the ``parse_slo`` grammar with the endpoint field
    naming the class (``interactive:p95<=250ms``,
    ``batch:latency_s:p99<=2``); priority is spec order. An empty list
    yields the single no-deadline :data:`DEFAULT_CLASS`.
    """
    out: Dict[str, AdmissionClass] = {}
    for i, spec in enumerate(specs):
        slo = parse_slo(spec)
        if slo.endpoint in out:
            raise ValueError(f"duplicate admission class "
                             f"{slo.endpoint!r} (from {spec!r})")
        out[slo.endpoint] = AdmissionClass(name=slo.endpoint, slo=slo,
                                           priority=i)
    if not out:
        out[DEFAULT_CLASS] = AdmissionClass(
            name=DEFAULT_CLASS,
            slo=SLO(objective_s=math.inf, target=0.95,
                    endpoint=DEFAULT_CLASS),
            priority=0)
    return out


def parse_tenant_slos(specs: Sequence[str]) -> Dict[str, List[SLO]]:
    """Parse ``--tenant_slo`` specs into per-tenant SLO lists (ISSUE
    19).

    Grammar: ``tenant:class:pNN<=VALUE`` — the leading segment names
    the tenant, the remainder is exactly the :func:`parse_slo` /
    :func:`parse_admission_classes` grammar with the class in the
    endpoint slot (``acme:interactive:p95<=250ms``). A two-segment
    spec (``acme:p95<=250ms``) applies to the :data:`DEFAULT_CLASS`.
    The fleet judges each tenant with its own
    :class:`~sketch_rnn_tpu.serve.slo.SLOTracker`, so attainment is
    reported per tenant, never pooled.
    """
    out: Dict[str, List[SLO]] = {}
    seen = set()
    for spec in specs:
        left, sep, _ = spec.partition("<=")
        segs = [s.strip() for s in left.strip().split(":")]
        if not sep or len(segs) < 2 or not segs[0]:
            raise ValueError(
                f"bad tenant SLO spec {spec!r}: want "
                f"tenant:class:pNN<=SECONDS (e.g. "
                f"'acme:interactive:p95<=250ms')")
        tenant = segs[0]
        slo = parse_slo(spec.partition(":")[2])
        if len(segs) == 2:
            # no class segment: judge the tenant's default class
            slo = dataclasses.replace(slo, endpoint=DEFAULT_CLASS)
        if (tenant, slo.key) in seen:
            raise ValueError(
                f"duplicate tenant SLO {tenant}:{slo.key} "
                f"(from {spec!r})")
        seen.add((tenant, slo.key))
        out.setdefault(tenant, []).append(slo)
    return out


@dataclasses.dataclass(frozen=True)
class Placement:
    """One admission decision. ``replica`` is None iff shed."""

    replica: Optional[int]
    queue_pos: int = 0            # requests ahead on the chosen replica
    est_wait_s: Optional[float] = None
    shed_reason: Optional[str] = None

    @property
    def shed(self) -> bool:
        return self.replica is None


class AdmissionController:
    """Pure least-loaded + shed-on-overload placement over R replicas.

    NOT internally locked — the fleet serializes ``place``/``note_done``
    under its scheduler lock. ``queue_cap`` bounds per-replica backlog
    (0 = unbounded); ``shed_margin`` scales the deadline before the
    estimated-wait comparison (1.0 = shed exactly when the estimate
    exceeds the deadline; >1 sheds later, <1 earlier). The service-time
    estimate is an EWMA over completed requests' ``decode_s``; until
    the first completion lands there is no estimate and only the hard
    queue cap sheds (a cold fleet must not refuse its first burst).
    """

    def __init__(self, classes: Dict[str, AdmissionClass],
                 n_replicas: int, slots: int, queue_cap: int = 0,
                 shed_margin: float = 1.0, ewma: float = 0.2,
                 tenant_cap: int = 0):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if not 0.0 < ewma <= 1.0:
            raise ValueError(f"ewma must be in (0, 1], got {ewma}")
        self.classes = dict(classes)
        self.n_replicas = n_replicas
        self.slots = slots
        self.queue_cap = int(queue_cap)
        self.shed_margin = float(shed_margin)
        self._ewma = float(ewma)
        self._backlog: List[int] = [0] * n_replicas
        self._dead: set = set()
        # elastically RETIRED replicas (ISSUE 12): out of the placement
        # set like the dead, but gracefully — their queued work drains
        # (backlog kept, note_done still decrements) and rejoin()
        # brings them back; mark_dead stays the crash path
        self._retired: set = set()
        self.service_s: Optional[float] = None   # EWMA decode_s
        self.admitted = 0
        self.shed: Dict[str, int] = {c: 0 for c in self.classes}
        # tenant fair-share (ISSUE 19): cap a single tenant's
        # OUTSTANDING decode-pool rows (queued + running, fleet-wide)
        # at ``tenant_cap`` so one tenant's flash crowd sheds its own
        # excess ("tenant_cap" reason) instead of filling every queue
        # and starving the rest. 0 disables the check; outstanding rows
        # are tracked either way so the summary can report them.
        self.tenant_cap = int(tenant_cap)
        self._tenant_out: Dict[str, int] = {}
        self.shed_by_tenant: Dict[str, int] = {}

    @property
    def backlog(self) -> List[int]:
        return list(self._backlog)

    @property
    def dead(self) -> List[int]:
        return sorted(self._dead)

    @property
    def retired(self) -> List[int]:
        return sorted(self._retired)

    @property
    def live_replicas(self) -> List[int]:
        return [r for r in range(self.n_replicas)
                if r not in self._dead and r not in self._retired]

    def retire(self, replica: int) -> None:
        """Gracefully remove ``replica`` from the placement set (ISSUE
        12 elastic scale-down): unlike :meth:`mark_dead` its backlog is
        KEPT — the replica drains what it already owns, completions
        still free backlog through note_done — but no new arrival is
        ever placed on it. Idempotent."""
        if not 0 <= replica < self.n_replicas:
            raise ValueError(f"replica {replica} out of range "
                             f"0..{self.n_replicas - 1}")
        self._retired.add(replica)

    def rejoin(self, replica: int) -> None:
        """Return a retired replica to the placement set (the elastic
        spawn path — a rejoined replica starts at its current tracked
        backlog, usually 0 after its drain)."""
        if replica in self._dead:
            raise ValueError(f"replica {replica} is dead, not retired "
                             f"— the crash path cannot rejoin")
        self._retired.discard(replica)

    def mark_dead(self, replica: int) -> int:
        """Shrink capacity: ``replica`` leaves the placement set (ISSUE
        10 failover). Its tracked backlog is dropped (returned, so the
        fleet can re-place exactly those requests) — every estimate
        from here on (least-loaded min, queue-cap shed, est_wait) sees
        only surviving replicas. Idempotent."""
        if not 0 <= replica < self.n_replicas:
            raise ValueError(f"replica {replica} out of range "
                             f"0..{self.n_replicas - 1}")
        if replica in self._dead:
            return 0
        self._dead.add(replica)
        self._retired.discard(replica)  # dead outranks retired
        dropped, self._backlog[replica] = self._backlog[replica], 0
        return dropped

    @property
    def shed_total(self) -> int:
        return sum(self.shed.values())

    def est_wait_s(self, replica: int) -> Optional[float]:
        """Expected queueing delay on ``replica``: its backlog worked
        off at ``slots`` concurrent units of the observed service
        time (None until a completion calibrates the estimate).
        Backlog is counted in decode-pool ROWS (ISSUE 15: an
        interpolation occupies ``frames`` rows), so a grid request
        weighs its true device cost; the EWMA sample is each
        completion's slot-occupancy duration (see :meth:`note_done`)."""
        if self.service_s is None:
            return None
        return self._backlog[replica] * self.service_s / self.slots

    def place(self, cls_name: str, force: bool = False,
              requeue: bool = False, cost: int = 1,
              tenant: str = "") -> Placement:
        """Decide one arrival: least-loaded replica, or shed.

        ``force`` admits unconditionally (same least-loaded placement,
        shed checks skipped) — the bench's parity/capacity arms use it
        so a completion racing the submit loop can never shed a request
        those arms must complete. ``requeue`` (failover, ISSUE 10)
        additionally skips the ``admitted`` count: a requeued request
        was already admitted once, and re-counting it would report
        admitted > submitted on exactly the degraded runs operators
        read the admission summary on. ``cost`` (ISSUE 15) is the
        request's decode-pool row count — ``frames`` for an
        interpolation, 1 otherwise — so backlog, the queue cap and
        the deadline shed estimate see the real work a grid request
        queues, not "one request". ``tenant`` (ISSUE 19) charges the
        request's rows to that tenant's fair share — the tenant-cap
        shed fires BEFORE the queue/deadline checks, because a tenant
        over its share must shed even when the fleet has room (that is
        the fairness rule: its excess never occupies capacity another
        tenant could use).
        """
        cls = self.classes.get(cls_name)
        if cls is None:
            raise KeyError(
                f"unknown admission class {cls_name!r}; configured: "
                f"{sorted(self.classes)}")
        if cost < 1:
            raise ValueError(f"cost must be >= 1, got {cost}")
        live = self.live_replicas
        if not live:
            raise RuntimeError(
                "no live replicas to place on — every replica was "
                "marked dead (the fleet stops accepting before this)")
        # least-loaded among SURVIVORS, ties to the lowest index
        # (deterministic; dead replicas left the placement set)
        replica = min(live, key=lambda r: (self._backlog[r], r))
        depth = self._backlog[replica]
        wait = self.est_wait_s(replica)
        tenant = str(tenant or "")
        if not force and not requeue:
            if (self.tenant_cap
                    and self._tenant_out.get(tenant, 0) + cost
                    > self.tenant_cap):
                self.shed[cls_name] += 1
                self.shed_by_tenant[tenant] = \
                    self.shed_by_tenant.get(tenant, 0) + 1
                return Placement(replica=None,
                                 shed_reason="tenant_cap")
            if self.queue_cap and depth >= self.queue_cap:
                self.shed[cls_name] += 1
                if tenant:
                    self.shed_by_tenant[tenant] = \
                        self.shed_by_tenant.get(tenant, 0) + 1
                return Placement(replica=None, shed_reason="queue_full")
            if (wait is not None and math.isfinite(cls.deadline_s)
                    and wait > cls.deadline_s * self.shed_margin):
                self.shed[cls_name] += 1
                if tenant:
                    self.shed_by_tenant[tenant] = \
                        self.shed_by_tenant.get(tenant, 0) + 1
                return Placement(replica=None, est_wait_s=wait,
                                 shed_reason="deadline")
        if not requeue:
            self.admitted += 1
            # a requeued request's rows are still outstanding from its
            # original placement — re-charging would double-count
            self._tenant_out[tenant] = \
                self._tenant_out.get(tenant, 0) + int(cost)
        self._backlog[replica] += int(cost)
        return Placement(replica=replica, queue_pos=depth,
                         est_wait_s=wait)

    def drop_tenant(self, tenant: str, cost: int = 1) -> None:
        """Release a tenant's outstanding rows WITHOUT a completion —
        the fleet's terminal-failure path (retry budget exhausted), so
        a failed request cannot leak fair-share capacity forever."""
        tenant = str(tenant or "")
        self._tenant_out[tenant] = max(
            0, self._tenant_out.get(tenant, 0) - int(cost))

    def note_done(self, replica: int, decode_s: float,
                  cost: int = 1, tenant: str = "") -> None:
        """Feed one completion: frees its ``cost`` backlog rows (the
        same count :meth:`place` charged), calibrates the service-time
        EWMA the shed estimate runs on. The sample is ``decode_s``
        itself even for grid requests: an interpolation's rows decode
        CONCURRENTLY in pool slots, so each row occupies a slot for
        ~the whole decode duration — dividing by ``cost`` would drag
        the estimate down by frames-x and re-open exactly the shed
        underestimate the row-cost accounting closes."""
        if self._backlog[replica] < cost:
            raise RuntimeError(
                f"replica {replica} completed a cost-{cost} request "
                f"with only {self._backlog[replica]} tracked backlog "
                f"rows — placement/completion accounting desynced")
        self._backlog[replica] -= int(cost)
        tenant = str(tenant or "")
        self._tenant_out[tenant] = max(
            0, self._tenant_out.get(tenant, 0) - int(cost))
        d = float(decode_s)
        self.service_s = (d if self.service_s is None
                          else (1 - self._ewma) * self.service_s
                          + self._ewma * d)

    def summary(self) -> Dict:
        """Aggregate admission state for reports and /metrics."""
        return {
            "admitted": self.admitted,
            "shed_total": self.shed_total,
            "shed_by_class": dict(self.shed),
            "backlog": self.backlog,
            "dead_replicas": self.dead,
            "retired_replicas": self.retired,
            "live_replicas": len(self.live_replicas),
            "service_est_s": (None if self.service_s is None
                              else round(self.service_s, 6)),
            "queue_cap": self.queue_cap,
            "tenant_cap": self.tenant_cap,
            "shed_by_tenant": dict(self.shed_by_tenant),
            "tenant_outstanding": {t: v for t, v
                                   in self._tenant_out.items() if v},
            "classes": {c.name: {"deadline_s": c.deadline_s,
                                 "target": c.slo.target,
                                 "priority": c.priority}
                        for c in self.classes.values()},
        }
