"""Int8/bf16 parameter quantization for inference (ISSUE 17).

The serving fleet's params are inference-only constants: they are
device-put once per swap and baked into the chunk program. Quantizing
them shrinks the checkpoint-admission transfer and the resident
parameter bytes ~4x (int8) / ~2x (bf16) per replica — the Gemma-on-TPU
serving recipe — at a *bounded, tested* accuracy cost:

- **int8** — per-tensor symmetric quantization: ``scale = max|w| /
  127``, ``q = round(w / scale)`` clipped to ``[-127, 127]``,
  dequant-on-load ``w' = q * scale``. The round-trip error is
  mathematically ``<= scale / 2`` per element (`max_error_bound`;
  asserted by tests/test_quantize.py), and — the loader's int16
  exact-transfer idiom (`data/loader.py` scale_factor machinery), one
  octave coarser — EXACT for tensors whose values already lie on the
  int8 grid ``scale * {-127..127}``.
- **bfloat16** — round-through-bf16 (storage halves; the dequantized
  f32 value is the bf16 rounding of the original, relative error
  ``<= 2^-8``).

Dequant-on-load keeps every downstream consumer untouched: the engine,
the chunk program and the Pallas decode kernel all see float32 arrays
— the QUANTIZED float32 arrays, so the canary gate's bitwise burst
(`serve/rollout.py`) still holds exactly (reference and replica both
serve the dequantized weights). `stamp_ckpt_id` marks the serving
identity (``ckpt_00000042:int8``) so every Result names not just which
checkpoint produced its strokes but at which precision — mixed-
precision serving stays as honest as mixed-version serving.

Scalars and integer leaves pass through untouched; so do float leaves
quantization would zero out entirely (all-zero tensors get scale 1).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Tuple

import numpy as np

QUANT_MODES = ("float32", "bfloat16", "int8")

# short serving-identity tags (ckpt_id suffixes)
_TAGS = {"int8": "int8", "bfloat16": "bf16"}


@dataclasses.dataclass
class QTensor:
    """One quantized tensor: integer (or bf16) storage + dequant scale."""

    q: np.ndarray          # int8 storage (or bf16 for mode=bfloat16)
    scale: float           # dequant step; 1.0 for bfloat16

    def dequantize(self) -> np.ndarray:
        return (np.asarray(self.q, np.float32) * np.float32(self.scale)
                ).astype(np.float32)


def check_mode(mode: str) -> None:
    if mode not in QUANT_MODES:
        raise ValueError(
            f"quantization mode must be one of {QUANT_MODES}, got "
            f"{mode!r}")


def _quantize_leaf(w: np.ndarray, mode: str) -> QTensor:
    if mode == "bfloat16":
        import jax.numpy as jnp

        return QTensor(q=np.asarray(jnp.asarray(w, jnp.bfloat16)),
                       scale=1.0)
    amax = float(np.max(np.abs(w))) if w.size else 0.0
    scale = amax / 127.0 if amax > 0.0 else 1.0
    q = np.clip(np.rint(np.asarray(w, np.float64) / scale),
                -127, 127).astype(np.int8)
    return QTensor(q=q, scale=scale)


def _is_quantizable(leaf: Any) -> bool:
    a = np.asarray(leaf)
    return a.ndim >= 1 and np.issubdtype(a.dtype, np.floating)


def quantize_params(params: Dict[str, Any], mode: str
                    ) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Pack a param tree for storage/transfer at ``mode`` precision.

    Returns ``(packed, report)``: ``packed`` mirrors the nested dict
    structure with quantizable float leaves replaced by
    :class:`QTensor`; ``report`` has one row per quantized tensor —
    ``{path, shape, scale, bound, max_err}`` where ``bound`` is the
    guaranteed per-element error bound (``scale/2`` for int8,
    ``max|w| * 2^-8`` for bf16) and ``max_err`` the measured round-trip
    ``max|w - dequant|`` (always ``<= bound``; the tested budget).
    """
    check_mode(mode)
    report: List[Dict[str, Any]] = []

    def walk(node, path):
        if isinstance(node, dict):
            return {k: walk(v, f"{path}/{k}" if path else k)
                    for k, v in node.items()}
        if mode == "float32" or not _is_quantizable(node):
            return node
        w = np.asarray(node, np.float32)
        qt = _quantize_leaf(w, mode)
        err = float(np.max(np.abs(w - qt.dequantize()))) if w.size \
            else 0.0
        bound = qt.scale / 2.0 if mode == "int8" \
            else float(np.max(np.abs(w)) * 2.0 ** -8) if w.size else 0.0
        report.append({"path": path, "shape": tuple(w.shape),
                       "scale": qt.scale, "bound": bound,
                       "max_err": err})
        return qt
    return walk(params, ""), report


def dequantize_params(packed: Dict[str, Any]) -> Dict[str, Any]:
    """Unpack a `quantize_params` tree back to float32 arrays."""
    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, QTensor):
            return node.dequantize()
        return node
    return walk(packed)


def quantize_for_serving(params: Dict[str, Any], mode: str
                         ) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """The swap/admission entry point: round params through ``mode``.

    Returns ``(params', report)`` where ``params'`` is the float32
    tree the engine actually serves (the dequantized quantized
    weights — identical structure, every consumer unchanged) and
    ``report`` the per-tensor error budget. ``float32`` is the
    identity (empty report) so call sites need no branching.
    """
    check_mode(mode)
    if mode == "float32":
        return params, []
    packed, report = quantize_params(params, mode)
    return dequantize_params(packed), report


def quantize_delta(base: np.ndarray, target: np.ndarray) -> QTensor:
    """Symmetric-int8 encode of ``target - base`` (ISSUE 19 adapters).

    The multi-tenant parameter pages store a tenant checkpoint as an
    int8 *diff* against the fleet's shared base tree. Same machinery and
    same proof as :func:`_quantize_leaf`: the decoded delta is within
    ``scale/2`` per element of the true delta (`max_error_bound` on the
    delta), and an all-zero delta encodes to ``q == 0, scale == 1``.
    (The tenant store never stores zero pages at all — an unchanged
    leaf is served as the base array object itself, which is how the
    "zero-delta tenant is bitwise the base" guarantee avoids even the
    ``-0.0 + 0.0`` sign-bit edge of IEEE-754 addition.)
    """
    base = np.asarray(base, np.float32)
    target = np.asarray(target, np.float32)
    if base.shape != target.shape:
        raise ValueError(
            f"adapter delta needs congruent leaves, got base "
            f"{base.shape} vs tenant {target.shape}")
    return _quantize_leaf(np.asarray(target, np.float64)
                          - np.asarray(base, np.float64), "int8")


def apply_delta(base: np.ndarray, delta: QTensor) -> np.ndarray:
    """Decode one adapter page entry: ``base + dequant(delta)`` in
    float32. The inverse of :func:`quantize_delta` up to the documented
    ``scale/2`` per-element budget; exact for a zero delta."""
    return (np.asarray(base, np.float32) + delta.dequantize()
            ).astype(np.float32)


def stamp_ckpt_id(ckpt_id: str, mode: str) -> str:
    """Serving identity of a quantized checkpoint: ``<id>:int8`` /
    ``<id>:bf16``; float32 (and empty ids) pass through unchanged."""
    check_mode(mode)
    if mode == "float32" or not ckpt_id:
        return ckpt_id
    return f"{ckpt_id}:{_TAGS[mode]}"


def max_error_bound(w: np.ndarray, mode: str) -> float:
    """The guaranteed per-element round-trip error bound for ``w``."""
    check_mode(mode)
    if mode == "float32" or not np.asarray(w).size:
        return 0.0
    amax = float(np.max(np.abs(w)))
    if mode == "int8":
        return (amax / 127.0 if amax > 0.0 else 1.0) / 2.0
    return amax * 2.0 ** -8
