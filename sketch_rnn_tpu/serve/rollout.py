"""Zero-downtime model rollout: validated hot-swap, canary, rollback.

ISSUE 16 tentpole. The fleet (serve/fleet.py) already has the elastic
primitive — retire a replica, drain it, rejoin it warm — but no way to
change WHAT a live replica serves, so until this PR a new checkpoint
meant a cold fleet restart and a serving gap. This module is the
TF-system "one runtime" pattern (PAPERS.md): serving follows training's
checkpoint lineage through a four-phase state machine that keeps
``/healthz`` in ok/rolling the whole way:

1. **ADMIT** — the candidate is fully validated BEFORE any engine sees
   it (``train/checkpoint.validate_checkpoint``: both files complete,
   sidecar parses, shape manifest matches the fleet's compiled
   geometry, every float leaf finite). A failed candidate is MOVED to
   ``quarantine/`` with a one-line ``.reason.txt`` naming the file and
   field, the ``ckpt_quarantined`` counter ticks, and the fleet keeps
   serving the old params — a torn or NaN checkpoint can never take
   traffic, and can never be re-admitted by the watcher (it left the
   checkpoint dir).
2. **CANARY** — one replica (a retired pre-warmed spare when the fleet
   has headroom, else the highest live index retired for the duration)
   swaps to the new params OFF-placement and must reproduce a seeded
   offline reference burst — ``serve_requests`` on a fresh engine with
   the same key/geometry — **bitwise** before it rejoins. The engine's
   determinism contract (strokes are a pure function of params + key,
   scheduling moves WHEN, never WHAT) is what makes bitwise the right
   bar: any diff means the swap corrupted state.
3. **WALK** — replica by replica: retire, wait for the drain-exit,
   swap params in place (``ServeEngine.swap_params`` rebuilds the
   chunk program — a compile — which is exactly why it only ever runs
   on a retired, drained engine), re-prove the canary burst bitwise on
   the swapped engine (doubling as the warm-up, so the rejoin never
   compiles in the measured window), rejoin. Survivors keep draining
   throughout; mixed-version serving stays honest because every Result
   carries its producing engine's ``ckpt_id`` and the cache stores
   under the producing version's namespace (serve/fleet.py). Retired
   spares are walked too — a later autoscale rejoin must never
   resurrect old params. The fleet's authoritative
   ``serving_ckpt_id`` flips old→new only after the LAST swap (the
   PROMOTE instant, recorded in the lineage).
4. **ROLLBACK** — a canary mismatch, a swap failure (injected:
   ``rollout.swap.rNN`` / ``rollout.canary`` fault sites), or a
   post-swap SLO burn (``slo.healthy()`` false after a rejoin) reverses
   the walk deterministically: every already-swapped replica swaps
   back to the held old params through the same retire/drain/swap/
   rejoin sequence, ``serving_ckpt_id`` never flips, and the
   ``rollout_rollbacks`` counter + ``rollout_log`` record the reversal.
   Post-rollback strokes are bitwise the pre-rollout fleet's — pinned
   by tests/test_rollout.py and the resilience bench's rollout cell.

``CheckpointWatcher`` (thread ``rollout-watcher``) is the continuous-
training glue: it polls a checkpoint dir and rolls the fleet to each
new complete step — ``cli serve-bench --watch_ckpt`` picks up each
checkpoint the trainer writes, live. ``lineage()`` is the RUN.json
contract: which ckpt_id served which admitted-uid window.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from sketch_rnn_tpu.train.checkpoint import (CheckpointValidationError,
                                             _complete_steps, _paths,
                                             ckpt_id_of,
                                             validate_checkpoint)
from sketch_rnn_tpu.utils.faults import fault_point
from sketch_rnn_tpu.utils.telemetry import (
    get_telemetry,
    suppressed as telemetry_suppressed,
)


def _clones(requests: List[Any]) -> List[Any]:
    """Fresh unscheduled copies of the canary burst: uids are assigned
    per run (`serve_requests` numbers them 0..n-1), scheduling fields
    cleared — the two runs being compared must differ in params ONLY."""
    return [dataclasses.replace(r, uid=None, cls=None, queue_pos=None,
                                enqueue_ts=None, attempt=0)
            for r in requests]


def _strokes_of(out: Dict[str, Any]) -> List[np.ndarray]:
    return [r.strokes5 for r in
            sorted(out["results"], key=lambda r: r.uid)]


def _bitwise(a: List[np.ndarray], b: List[np.ndarray]) -> bool:
    return (len(a) == len(b)
            and all(x.shape == y.shape and x.dtype == y.dtype
                    and np.array_equal(x, y) for x, y in zip(a, b)))


class RolloutController:
    """Drive one ServeFleet through validated checkpoint rollouts.

    Construction registers the controller on the fleet
    (``fleet._rollout``) so ``/healthz`` can report the rolling state
    and ``fleet.close()`` joins an in-flight walk instead of orphaning
    a half-swapped spare. One controller per fleet; ``roll_to`` is
    serialized by an internal lock (the watcher thread and a manual
    caller cannot interleave walks).

    ``template_state`` is the shape manifest candidates are validated
    against (any TrainState of the serving architecture —
    ``make_train_state(model, hps, key)`` works; values are ignored).
    ``canary_requests`` is the seeded burst every swap must reproduce
    bitwise; keep it small (it runs twice per rollout plus once per
    swapped replica) but representative (conditional models should
    exercise z).
    """

    def __init__(self, fleet, model, hps, template_state,
                 canary_requests: List[Any],
                 quarantine_dir: Optional[str] = None,
                 slo=None) -> None:
        if not canary_requests:
            raise ValueError("canary_requests must be non-empty: the "
                             "canary gate cannot prove a swap with an "
                             "empty burst")
        self.fleet = fleet
        self.model = model
        self.hps = hps
        self.template_state = template_state
        self.canary_requests = list(canary_requests)
        self.quarantine_dir = quarantine_dir
        self.slo = slo
        self.rollout_log: List[Dict[str, Any]] = []
        self._walk_lock = threading.Lock()
        self._watcher: Optional["CheckpointWatcher"] = None
        # evidence is REPLACED wholesale (never mutated in place) so
        # fleet.health() — which runs under the fleet lock — can read
        # it without taking any controller lock (no lock-order edge)
        self._evidence: Dict[str, Any] = {"active": False}
        # lineage: which ckpt_id served which admitted-uid window
        # (RUN.json contract); the open window has to_uid None
        self._lineage: List[Dict[str, Any]] = [{
            "ckpt_id": fleet.serving_ckpt_id,
            "from_uid": 0, "to_uid": None}]
        fleet._rollout = self

    # -- evidence / reporting ----------------------------------------------

    def evidence(self) -> Dict[str, Any]:
        """The /healthz rollout block: {active, from, to, swapped,
        total} while a walk is in flight. Lock-free by design (see
        __init__) — callers get a consistent snapshot dict."""
        return dict(self._evidence)

    def lineage(self) -> List[Dict[str, Any]]:
        """Checkpoint lineage for RUN.json: ordered serving windows
        ``{ckpt_id, from_uid, to_uid}`` (the last window is open,
        ``to_uid`` None). A request's stamped ckpt_id and its uid's
        window agree for every request admitted OUTSIDE a walk; during
        a walk the Result stamp is the finer-grained truth."""
        return [dict(w) for w in self._lineage]

    def _log(self, event: str, **kv: Any) -> Dict[str, Any]:
        entry = {"event": event, **kv}
        self.rollout_log.append(entry)
        return entry

    def _uid_watermark(self) -> int:
        with self.fleet._lock:
            return self.fleet._next_uid

    # -- phase 1: admission gate -------------------------------------------

    def admit(self, path: str):
        """Validate one candidate; quarantine on failure.

        Returns ``(state, scale_factor, meta)`` on success. On any
        validation failure the candidate pair is MOVED to the
        quarantine dir (sibling ``quarantine/`` of the checkpoint by
        default) with a one-line ``.reason.txt``, ``ckpt_quarantined``
        ticks, and the CheckpointValidationError re-raises — the
        caller's fleet never touched the bytes."""
        try:
            # (the ckpt.load.corrupt fault site lives INSIDE
            # validate_checkpoint, so training-resume restores share
            # the same injected-corruption surface)
            return validate_checkpoint(path, self.template_state)
        except CheckpointValidationError as e:
            self._quarantine(path, e.reason)
            raise
        except Exception as e:  # an injected ckpt.load.corrupt raise
            reason = f"{type(e).__name__}: {e}"
            self._quarantine(path, reason)
            raise CheckpointValidationError(path, reason) from e

    def _quarantine(self, path: str, reason: str) -> None:
        base = path
        for ext in (".msgpack", ".json"):
            if base.endswith(ext):
                base = base[:-len(ext)]
        qdir = self.quarantine_dir or os.path.join(
            os.path.dirname(base) or ".", "quarantine")
        os.makedirs(qdir, exist_ok=True)
        moved = []
        for ext in (".msgpack", ".json"):
            src = base + ext
            if os.path.exists(src):
                shutil.move(src, os.path.join(qdir,
                                              os.path.basename(src)))
                moved.append(os.path.basename(src))
        line = (f"{os.path.basename(base)}: {reason}".splitlines()
                or [reason])[0]
        with open(os.path.join(
                qdir, os.path.basename(base) + ".reason.txt"),
                "w") as f:
            f.write(line + "\n")
        tel = get_telemetry()
        if tel.enabled:
            tel.counter("ckpt_quarantined", 1.0, cat="serve")
        self._log("quarantine", candidate=os.path.basename(base),
                  reason=line, moved=moved, quarantine_dir=qdir)

    # -- the reference / canary bursts -------------------------------------

    def _reference(self, params) -> List[np.ndarray]:
        """The seeded offline reference: `serve_requests` on a FRESH
        single engine at the fleet's exact serving geometry. Suppressed
        telemetry — the burst's auto-uids must not collide with live
        request traces."""
        from sketch_rnn_tpu.serve.endpoints import serve_requests

        with telemetry_suppressed():
            out = serve_requests(
                self.model, self.hps, params,
                _clones(self.canary_requests),
                slots=self.fleet.slots, chunk=self.fleet.chunk,
                pool_pad=self.fleet.pool_cap)
        return _strokes_of(out)

    def _burst_on(self, replica: int) -> List[np.ndarray]:
        """Run the canary burst on a retired replica's own engine (the
        in-place path every serving burst takes, same pool geometry —
        so a bitwise match here both PROVES the swap and WARMS the
        rebuilt chunk program outside the measured window)."""
        import jax

        from sketch_rnn_tpu.serve.endpoints import serve_requests

        rep = self.fleet._replicas[replica]
        with telemetry_suppressed(), jax.default_device(rep.device):
            out = serve_requests(
                self.model, self.hps, rep.engine._full_params,
                _clones(self.canary_requests),
                pool_pad=self.fleet.pool_cap, engine=rep.engine)
        return _strokes_of(out)

    # -- phases 2+3+4: canary, walk, rollback ------------------------------

    def roll_to(self, path: str) -> Dict[str, Any]:
        """Upgrade the whole fleet to the checkpoint at ``path``.

        Returns a report dict: ``{"ok": bool, "phase": ..., "from":
        ..., "to": ..., "swapped": int, "rolled_back": bool, ...}``.
        Never raises for a bad CANDIDATE (quarantine / rollback are the
        handled outcomes); re-raises only non-Exception escapes (an
        injected ``kind=exit`` SystemExit must keep crashing the
        process — that is its contract)."""
        with self._walk_lock:
            return self._roll_to_locked(path)

    def _roll_to_locked(self, path: str) -> Dict[str, Any]:
        fleet = self.fleet
        tel = get_telemetry()
        old_id = fleet.serving_ckpt_id

        # ---- ADMIT
        try:
            state, _scale, meta = self.admit(path)
        except CheckpointValidationError as e:
            return {"ok": False, "phase": "admit", "from": old_id,
                    "to": None, "swapped": 0, "rolled_back": False,
                    "reason": e.reason}
        new_params = state.params
        new_id = ckpt_id_of(int(meta.get("step", 0)))
        # quantized admission (ISSUE 17): round the admitted params
        # through the fleet's serving precision BEFORE the reference
        # burst, so the canary's bitwise gate proves the QUANTIZED
        # weights (reference and replicas both serve the dequantized
        # tree) and every Result stamps the precision it was served at
        quant_mode = str(getattr(self.hps, "serve_quantize", "float32"))
        if quant_mode != "float32":
            from sketch_rnn_tpu.serve.quantize import (quantize_for_serving,
                                                       stamp_ckpt_id)

            new_params, qreport = quantize_for_serving(new_params,
                                                       quant_mode)
            new_id = stamp_ckpt_id(new_id, quant_mode)
            self._log("quantize", ckpt_id=new_id, mode=quant_mode,
                      tensors=len(qreport),
                      max_err=max((r["max_err"] for r in qreport),
                                  default=0.0))
        if new_id == old_id:
            return {"ok": True, "phase": "noop", "from": old_id,
                    "to": new_id, "swapped": 0, "rolled_back": False,
                    "reason": "already serving this checkpoint"}
        self._log("admit_ok", ckpt_id=new_id, path=path)

        # the held rollback image: every engine shares the same
        # host-side params object, so any non-dead replica donates it
        donors = [r for r in fleet._replicas if not r.dead]
        if not donors:
            return {"ok": False, "phase": "admit", "from": old_id,
                    "to": new_id, "swapped": 0, "rolled_back": False,
                    "reason": "no live replica to roll"}
        old_params = donors[0].engine._full_params

        # the walk set, captured once: live replicas old->new one at a
        # time, retired spares too (an autoscale rejoin must never
        # resurrect old params), dead ones never
        live_idx = [r.idx for r in fleet._replicas
                    if not r.dead and not r.retired]
        spare_idx = [r.idx for r in fleet._replicas
                     if not r.dead and r.retired]
        pre_live = set(live_idx)  # the placement set rollback restores
        total = len(live_idx) + len(spare_idx)
        self._evidence = {"active": True, "from": old_id, "to": new_id,
                          "swapped": 0, "total": total}

        t0 = time.perf_counter()
        swapped: List[int] = []  # replicas holding new params
        try:
            # ---- CANARY: prove the new params on one replica
            # off-placement before ANY serving traffic sees them
            reference = self._reference(new_params)
            if spare_idx:
                canary = spare_idx[0]
            else:
                canary = fleet.retire_replica(reason="rollout-canary")
                live_idx.remove(canary)
                if not fleet.wait_replica_drained(canary):
                    raise RuntimeError(
                        f"canary replica {canary} did not drain")
            fault_point("rollout.canary")
            fleet.swap_params_retired(canary, new_params,
                                      ckpt_id=new_id,
                                      param_dtype=quant_mode)
            swapped.append(canary)
            got = self._burst_on(canary)
            if not _bitwise(reference, got):
                raise RuntimeError(
                    f"canary replica {canary} failed the bitwise "
                    f"reference burst for {new_id}")
            self._log("canary_ok", replica=canary, ckpt_id=new_id,
                      n_requests=len(self.canary_requests))

            # ---- WALK: canary rejoins first (placement never shrinks
            # below its pre-rollout size while an old replica retires),
            # then each live replica, then the remaining spares
            fleet.rejoin_replica(canary, reason="rollout")
            self._bump_swapped(1)
            if tel.enabled:
                tel.counter("rollout_swaps", 1.0, cat="serve")
            self._log("swap", replica=canary, ckpt_id=new_id,
                      canary=True)
            self._check_slo_burn(canary)

            for idx in live_idx + spare_idx[1:]:
                fault_point(f"rollout.swap.r{idx}")
                is_spare = idx in spare_idx
                if not is_spare:
                    fleet.retire_replica(idx, reason="rollout")
                if not fleet.wait_replica_drained(idx):
                    raise RuntimeError(
                        f"replica {idx} did not drain for its swap")
                fleet.swap_params_retired(idx, new_params,
                                          ckpt_id=new_id,
                                          param_dtype=quant_mode)
                swapped.append(idx)
                got = self._burst_on(idx)
                if not _bitwise(reference, got):
                    raise RuntimeError(
                        f"replica {idx} failed the bitwise reference "
                        f"burst for {new_id}")
                if not is_spare:
                    # spares stay retired (warm headroom at the NEW
                    # version); live replicas rejoin where they were
                    fleet.rejoin_replica(idx, reason="rollout")
                self._bump_swapped(1)
                if tel.enabled:
                    tel.counter("rollout_swaps", 1.0, cat="serve")
                self._log("swap", replica=idx, ckpt_id=new_id,
                          canary=False)
                if not is_spare:
                    self._check_slo_burn(idx)
        except Exception as e:  # noqa: BLE001 — SystemExit passes
            self._rollback(swapped, pre_live, old_params, old_id,
                           new_id, repr(e))
            self._evidence = {"active": False}
            if tel.enabled:
                tel.counter("rollout_rollbacks", 1.0, cat="serve")
            return {"ok": False, "phase": "rollback", "from": old_id,
                    "to": new_id, "swapped": 0, "rolled_back": True,
                    "reason": repr(e)}

        # ---- PROMOTE: flip the authoritative serving version — new
        # submissions now fingerprint (and future rollbacks anchor)
        # under new_id; close the lineage window at the flip watermark
        watermark = self._uid_watermark()
        fleet.serving_ckpt_id = new_id
        if self._lineage and self._lineage[-1]["to_uid"] is None:
            self._lineage[-1]["to_uid"] = watermark - 1
        self._lineage.append({"ckpt_id": new_id,
                              "from_uid": watermark, "to_uid": None})
        self._evidence = {"active": False}
        self._log("promote", ckpt_id=new_id, swapped=total,
                  wall_s=round(time.perf_counter() - t0, 3))
        return {"ok": True, "phase": "promote", "from": old_id,
                "to": new_id, "swapped": total, "rolled_back": False}

    def _bump_swapped(self, n: int) -> None:
        ev = dict(self._evidence)
        ev["swapped"] = ev.get("swapped", 0) + n
        self._evidence = ev

    def _check_slo_burn(self, replica: int) -> None:
        """Post-swap SLO gate: a rejoined replica that burns the error
        budget reverses the walk (raises into the rollback handler)."""
        if self.slo is not None and not self.slo.healthy():
            raise RuntimeError(
                f"SLO burn after swapping replica {replica}: "
                f"{self.slo.summary()}")

    def _rollback(self, swapped: List[int], pre_live: set,
                  old_params, old_id: str, new_id: str,
                  reason: str) -> None:
        """Reverse the walk: every replica holding new params swaps
        back through the same retire/drain/swap/warm sequence (LIFO —
        the most recently swapped reverts first), then the pre-rollout
        PLACEMENT set is restored (live replicas rejoin, borrowed
        spares return to retirement). Best-effort per replica — one
        stuck revert must not strand the rest at the new version.
        Deterministic: the same failure point reverses the same
        prefix."""
        fleet = self.fleet
        for idx in reversed(swapped):
            rep = fleet._replicas[idx]
            if rep.dead:
                continue  # a dead replica serves nothing at any version
            try:
                if not rep.retired:
                    fleet.retire_replica(idx, reason="rollback")
                if not fleet.wait_replica_drained(idx):
                    continue
                fleet.swap_params_retired(idx, old_params,
                                          ckpt_id=old_id)
                self._burst_on(idx)  # re-warm the old program
            except Exception as e:  # noqa: BLE001
                self._log("rollback_skip", replica=idx, error=repr(e))
        # restore the pre-rollout placement set (this also un-retires
        # a canary that was retired from live but failed BEFORE its
        # swap — it still holds old params and just rejoins)
        for idx in sorted(pre_live):
            rep = fleet._replicas[idx]
            if not rep.dead and rep.retired:
                try:
                    fleet.rejoin_replica(idx, reason="rollback")
                except RuntimeError as e:
                    self._log("rollback_skip", replica=idx,
                              error=repr(e))
        self._log("rollback", from_ckpt=new_id, to_ckpt=old_id,
                  replicas=list(reversed(swapped)), reason=reason)

    # -- watcher / lifecycle -----------------------------------------------

    def watch(self, ckpt_dir: str,
              poll_s: float = 0.5) -> "CheckpointWatcher":
        """Start the continuous-training follower: roll to each new
        complete checkpoint step appearing in ``ckpt_dir``."""
        if self._watcher is not None:
            raise RuntimeError("already watching")
        self._watcher = CheckpointWatcher(self, ckpt_dir,
                                          poll_s=poll_s)
        self._watcher.start()
        return self._watcher

    def join(self, timeout: float = 30.0) -> bool:
        """Stop the watcher (if any) and wait out an in-flight walk.
        Called by ``fleet.close()`` so a shutdown never orphans a
        half-swapped spare. True iff the walk finished in time."""
        if self._watcher is not None:
            self._watcher.stop(timeout=timeout)
            self._watcher = None
        got = self._walk_lock.acquire(timeout=timeout)
        if got:
            self._walk_lock.release()
        return got


class CheckpointWatcher:
    """Poll a checkpoint dir; roll the fleet to each new complete step.

    The thread is named ``rollout-watcher`` (the conftest thread guard
    whitelists the ``rollout-`` prefix). Steps at or below the high-
    water mark at start are considered already served — only NEW
    checkpoints trigger a walk. A quarantined candidate disappears from
    the dir (admit() moved it), so it can never retrigger."""

    def __init__(self, controller: RolloutController, ckpt_dir: str,
                 poll_s: float = 0.5) -> None:
        self.controller = controller
        self.ckpt_dir = ckpt_dir
        self.poll_s = float(poll_s)
        self.reports: List[Dict[str, Any]] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="rollout-watcher",
                                        daemon=True)
        steps = _complete_steps(ckpt_dir) \
            if os.path.isdir(ckpt_dir) else []
        self._seen = max(steps) if steps else -1

    def start(self) -> "CheckpointWatcher":
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def poll_once(self) -> Optional[Dict[str, Any]]:
        """One poll step (also the test seam): roll to the next unseen
        complete step, oldest first, or None if nothing new."""
        steps = sorted(s for s in _complete_steps(self.ckpt_dir)
                       if s > self._seen) \
            if os.path.isdir(self.ckpt_dir) else []
        if not steps:
            return None
        step = steps[0]
        self._seen = step
        data_path, _ = _paths(self.ckpt_dir, step)
        report = self.controller.roll_to(data_path)
        self.reports.append(report)
        return report

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001
                # a poll crash must not kill the follower; the next
                # checkpoint gets a fresh attempt (roll_to itself
                # already converts candidate failures into reports)
                pass
            self._stop.wait(self.poll_s)
