"""Paged multi-tenant parameters + shared-prefix encode reuse (ISSUE 19).

Production sketch serving is per-category / per-user fine-tunes, not
one checkpoint. Serving N checkpoints as N fleets costs N× resident
params, N× compiles and zero cross-tenant capacity sharing. This module
makes N tenants fit ONE fleet:

- :class:`TenantStore` — one shared float32 *base* tree plus a sparse,
  delta-encoded *adapter page* per tenant. A page stores only the
  leaves that differ from the base, as symmetric-int8 diffs
  (`serve/quantize.py`'s machinery: decoded delta within ``scale/2``
  per element of the true delta). Leaves bitwise equal to the base are
  not stored at all — ``materialize()`` returns the base array objects
  themselves for those paths, so a tenant whose fine-tune touched
  nothing is *bitwise* the base, and adapter-resident memory is
  ``base + Σ page_bytes`` instead of ``N × full``.
- :class:`PrefixReuseIndex` — a radix index over stroke-prefix hashes
  in front of the :class:`~sketch_rnn_tpu.serve.endpoints.EncodeProgram`:
  identical prefixes across ``complete``/``reconstruct`` requests
  (templated UIs) reuse one encode output instead of re-encoding. The
  encode program is a pure function of (prefix, params), so a reused
  ``(mu, carry, prev)`` is bitwise what a recompute would produce; the
  index coalesces concurrent misses (cache-style in-flight events) so
  **encode computes == distinct (tenant, prefix, edge) exactly**, even
  across racing replica workers.

Adapter apply is shape-invariant by construction (`register` rejects
non-congruent trees), which is what lets the fleet page a replica
between tenants with a pure value swap — the chunk/encode programs'
``JitCompileProbe`` geometry keys never see a tenant dimension, so
tenant swaps show **zero compiles** in the measured window
(serve/engine.py's value-paged mode; asserted by scripts/serve_bench.py
``--tenants``).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from sketch_rnn_tpu.serve.quantize import (
    QTensor,
    apply_delta,
    quantize_delta,
)

BASE_TENANT = ""  # requests with no tenant serve the base tree


def _walk(tree: Any, path: str = "") -> Iterator[Tuple[str, Any]]:
    """Yield ``(path, leaf)`` in deterministic (insertion) order, the
    same ``a/b/c`` path grammar as quantize_params."""
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _walk(v, f"{path}/{k}" if path else str(k))
    else:
        yield path, tree


def tree_nbytes(tree: Any) -> int:
    """Total leaf bytes of a param tree (numpy/JAX arrays + scalars)."""
    total = 0
    for _, leaf in _walk(tree):
        total += int(np.asarray(leaf).nbytes)
    return total


def _page_nbytes(entry: Any) -> int:
    if isinstance(entry, QTensor):
        return int(entry.q.nbytes) + 8  # int8 payload + float64 scale
    return int(np.asarray(entry).nbytes)


class TenantStore:
    """Base param tree + sparse int8-delta adapter pages per tenant.

    ``register(tenant, params)`` diffs ``params`` against the base:
    bitwise-equal leaves are skipped, quantizable float leaves become
    :class:`QTensor` int8 deltas, anything else (scalars, int arrays)
    is stored raw. ``materialize(tenant)`` rebuilds the tenant's float32
    tree — base leaf objects where the page is silent, ``base +
    dequant(delta)`` where it is not — which is exactly the tree the
    fleet serves AND the tree single-tenant parity references must
    serve (the raw fine-tune differs from its page decode by up to
    ``scale/2`` per element; the page decode is the serving truth).
    """

    def __init__(self, base_params: Dict[str, Any],
                 base_ckpt_id: str = "base"):
        if not isinstance(base_params, dict) or not base_params:
            raise ValueError("TenantStore needs a non-empty base param "
                             "tree (nested dict of arrays)")
        self.base = base_params
        self.base_ckpt_id = str(base_ckpt_id or "base")
        self.base_nbytes = tree_nbytes(base_params)
        self._base_leaves: Dict[str, Any] = dict(_walk(base_params))
        # tenant -> {"pages": {path: QTensor|ndarray}, "ckpt_id": str,
        #            "nbytes": int, "report": [rows]}
        self._adapters: Dict[str, Dict[str, Any]] = {}

    # -- registration -------------------------------------------------

    def register(self, tenant: str, params: Dict[str, Any],
                 ckpt_id: str = "") -> Dict[str, Any]:
        """Encode ``params`` as a delta page against the base.

        Returns the adapter report: per-leaf rows ({path, shape, scale,
        bound, max_err}) plus page/byte totals. Raises if the tree is
        not congruent with the base (paged serving is value-swap only —
        a new geometry would mean a recompile, which multi-tenant
        serving forbids).
        """
        tenant = str(tenant)
        if not tenant:
            raise ValueError("tenant name must be non-empty (the empty "
                             "string names the base tree)")
        if tenant in self._adapters:
            raise ValueError(f"tenant {tenant!r} already registered")
        leaves = dict(_walk(params))
        if set(leaves) != set(self._base_leaves):
            missing = sorted(set(self._base_leaves) - set(leaves))
            extra = sorted(set(leaves) - set(self._base_leaves))
            raise ValueError(
                f"tenant {tenant!r} tree is not congruent with the "
                f"base: missing={missing[:4]} extra={extra[:4]}")
        pages: Dict[str, Any] = {}
        report: List[Dict[str, Any]] = []
        for path, base_leaf in self._base_leaves.items():
            leaf = leaves[path]
            b = np.asarray(base_leaf)
            t = np.asarray(leaf)
            if b.shape != t.shape:
                raise ValueError(
                    f"tenant {tenant!r} leaf {path!r} shape {t.shape} "
                    f"!= base {b.shape}: adapters must be "
                    f"shape-invariant")
            if b.dtype == t.dtype and np.array_equal(
                    b, t) and not np.any(np.isnan(b)):
                continue  # bitwise the base: no page entry
            if t.ndim >= 1 and np.issubdtype(t.dtype, np.floating):
                qt = quantize_delta(b, t)
                err = float(np.max(np.abs(
                    np.asarray(t, np.float32) - apply_delta(b, qt)))
                ) if t.size else 0.0
                pages[path] = qt
                report.append({"path": path, "shape": tuple(t.shape),
                               "scale": qt.scale,
                               "bound": qt.scale / 2.0, "max_err": err})
            else:
                pages[path] = np.array(t)  # raw page (scalars, ints)
                report.append({"path": path, "shape": tuple(t.shape),
                               "scale": None, "bound": 0.0,
                               "max_err": 0.0})
        nbytes = sum(_page_nbytes(p) for p in pages.values())
        self._adapters[tenant] = {
            "pages": pages,
            "ckpt_id": str(ckpt_id or f"{self.base_ckpt_id}+{tenant}"),
            "nbytes": nbytes,
            "report": report,
        }
        return {"tenant": tenant, "pages": len(pages), "nbytes": nbytes,
                "report": report}

    # -- lookup -------------------------------------------------------

    @property
    def tenants(self) -> List[str]:
        return list(self._adapters)

    def __contains__(self, tenant: str) -> bool:
        return tenant == BASE_TENANT or tenant in self._adapters

    def ckpt_id_of(self, tenant: str) -> str:
        """The serving identity a tenant's Results (and cache
        fingerprints) carry — distinct per tenant, so the result
        cache's ckpt_id namespace isolates tenants for free."""
        if tenant == BASE_TENANT:
            return self.base_ckpt_id
        return str(self._adapters[tenant]["ckpt_id"])

    def adapter_report(self, tenant: str) -> List[Dict[str, Any]]:
        return list(self._adapters[tenant]["report"])

    def materialize(self, tenant: str) -> Dict[str, Any]:
        """The float32 tree served for ``tenant``: base + decoded page.

        Paths without a page entry return the base array OBJECTS (no
        copy — this is both the memory story and the bitwise story);
        a tenant with an empty page materializes a tree whose every
        leaf is the base leaf itself.
        """
        if tenant == BASE_TENANT:
            return self.base
        pages = self._adapters[tenant]["pages"]

        def build(node, path=""):
            if isinstance(node, dict):
                return {k: build(v, f"{path}/{k}" if path else str(k))
                        for k, v in node.items()}
            entry = pages.get(path)
            if entry is None:
                return node
            if isinstance(entry, QTensor):
                return apply_delta(np.asarray(node), entry)
            return entry
        return build(self.base)

    # -- accounting ---------------------------------------------------

    def memory_table(self) -> Dict[str, Any]:
        """The adapter-memory-vs-N×full comparison SERVE_BENCH commits.

        ``resident_bytes`` = one base tree + every adapter page;
        ``full_bytes`` = what N separate full trees would cost
        (tenants are congruent with the base, so each is
        ``base_nbytes``). ``ratio`` is the acceptance number: < 0.5 at
        N >= 4 because pages are sparse int8.
        """
        n = len(self._adapters)
        adapters = {t: int(a["nbytes"])
                    for t, a in self._adapters.items()}
        resident = self.base_nbytes + sum(adapters.values())
        full = n * self.base_nbytes
        return {
            "tenants": n,
            "base_bytes": int(self.base_nbytes),
            "adapter_bytes": adapters,
            "resident_bytes": int(resident),
            "full_bytes": int(full),
            "ratio": (resident / full) if full else None,
        }


class PrefixReuseIndex:
    """Radix index over stroke-prefix hashes: encode-once per distinct
    ``(tenant, prefix, edge, label)``.

    ``acquire(key)`` either returns a stored ``(mu, carry, prev)`` (a
    *reuse*) or claims the key for computation (a *compute*); a second
    worker racing on the same key blocks on an in-flight event instead
    of recomputing — the same coalescing idiom as the result cache's
    ``_pending`` map, moved to the encode layer. ``fill`` publishes the
    computed rows; ``abandon`` releases a claim after a failure so a
    waiter can take over (the failed claim is not counted).

    The index is host-side numpy and fleet-shared: rows computed on one
    replica's device are reused when planning bursts on any other.
    Bitwise safety rests on the encode program being deterministic in
    (prefix, params) — asserted end-to-end by the ``--tenants`` bench,
    which recomputes a sample of reused rows and compares bytes.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._entries: Dict[tuple, tuple] = {}
        self._inflight: Dict[tuple, bool] = {}
        self.computes = 0
        self.reuses = 0

    @staticmethod
    def key(tenant: str, prefix: np.ndarray, edge: int,
            label: int = 0) -> tuple:
        """Hash a stroke prefix into the index key. Shape is folded in
        before the bytes so ``[2,3]`` content can never collide with a
        ``[3,2]`` reshape of the same bytes."""
        a = np.ascontiguousarray(np.asarray(prefix, np.float32))
        h = hashlib.blake2b(digest_size=16)
        h.update(repr(a.shape).encode("utf-8"))
        h.update(a.tobytes())
        return (str(tenant), h.hexdigest(), int(edge), int(label))

    def acquire(self, key: tuple
                ) -> Tuple[str, Optional[tuple]]:
        """Returns ``("hit", rows)`` or ``("compute", None)``; blocks
        while another thread holds an in-flight claim on ``key``."""
        with self._cond:
            while True:
                if key in self._entries:
                    self.reuses += 1
                    return "hit", self._entries[key]
                if key not in self._inflight:
                    self._inflight[key] = True
                    self.computes += 1
                    return "compute", None
                self._cond.wait()

    def fill(self, key: tuple, rows: tuple) -> None:
        with self._cond:
            self._entries[key] = rows
            self._inflight.pop(key, None)
            self._cond.notify_all()

    def note_reuses(self, n: int) -> None:
        """Fold ``n`` additional avoided encodes into the reuse ledger
        (within-burst duplicates the planner stamped from one
        compute)."""
        if n:
            with self._lock:
                self.reuses += int(n)

    def abandon(self, key: tuple) -> None:
        """Release a claim without publishing (compute failed); the
        claim is uncounted so ``computes`` only counts successes."""
        with self._cond:
            if self._inflight.pop(key, None):
                self.computes -= 1
            self._cond.notify_all()

    @property
    def distinct(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"computes": self.computes, "reuses": self.reuses,
                    "distinct": len(self._entries)}
