"""Hyperparameter / config system.

TPU-native equivalent of the reference's ``get_default_hparams()`` +
``tf.app.flags`` hparams-string override machinery (SURVEY.md §2 component 14,
§5 "Config / flag system"; reference unreadable — canonical defaults follow
the sketch-rnn paper, arXiv:1704.03477, and BASELINE.json's fixed values:
enc_rnn_size=256, dec_rnn_size=512, z_size=128, num_mixture=20).

Design: a frozen dataclass (hashable, so it can ride as a static argument
through ``jax.jit``) plus a ``parse()`` string-override path mirroring the
reference's ``--hparams=key=value,key=value`` CLI contract.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Tuple

CELL_TYPES = ("lstm", "layer_norm", "hyper")


@dataclasses.dataclass(frozen=True)
class HParams:
    """All knobs for data, model, loss, optimizer and parallelism."""

    # --- data (SURVEY §2 component 1) ---
    data_dir: str = ""
    data_set: Tuple[str, ...] = ("cat.npz",)
    max_seq_len: int = 250
    batch_size: int = 100
    random_scale_factor: float = 0.15  # stroke augmentation scale jitter
    augment_stroke_prob: float = 0.10  # prob of dropping a point (train only)
    bucket_edges: Tuple[int, ...] = ()  # length-bucketed execution (off
    #   when empty — the exact-parity default): training batches are
    #   assembled from sequences binned by length and padded only to
    #   their bucket's edge Tb instead of max_seq_len, and each (B, Tb)
    #   geometry gets its own compiled step executable (train/step.py).
    #   Edges are strictly ascending pad lengths, e.g. "64;128;250";
    #   max_seq_len is always an implicit terminal edge. The masked GMM
    #   loss term is EXACTLY preserved (normalization stays max_seq_len
    #   * B); the canonical unmasked-to-Nmax train pen CE loses its
    #   truncated [Tb, Nmax) all-padding tail — see ops/mdn.py. Masked
    #   eval losses are bitwise independent of bucketing. Single-host
    #   only (a coordinated multi-host plan is future work); composes
    #   with steps_per_call=K via the bucket-run scheduler (geometry
    #   runs ride stacked [K, B, Tb, ...] transfers, run remainders
    #   replay as single micro-steps — see data/loader.py next_stack).
    bucket_shuffle_window: int = 256   # seeded shuffle window applied
    #   to the bucketed epoch's batch order so binning by length does
    #   not introduce a length-curriculum bias; windows >= the epoch's
    #   batch (or run) count give a full shuffle (tf.data-style
    #   windowed-shuffle semantics, deterministic per (seed, epoch)).
    #   With bucket_run_len > 0 the window counts RUNS, not batches —
    #   the run-aware mode shuffles geometry runs as units instead of
    #   splitting them.
    bucket_run_len: int = 8            # geometry-run granularity of the
    #   bucketed epoch plan (ISSUE 5): each bucket's batches are grouped
    #   into runs of up to this many consecutive batches sharing one
    #   (B, Tb) geometry, and the windowed shuffle permutes runs as
    #   units. Long runs are what stacked execution (steps_per_call=K)
    #   amortizes: K consecutive same-geometry batches ride ONE stacked
    #   [K, B, Tb, ...] transfer + one compiled K-step scan. Purely an
    #   ORDERING knob — coverage, per-batch contents and the per-step
    #   RNG stream are unchanged — and independent of steps_per_call,
    #   so the plan stays a pure function of (seed, epoch) at every K.
    #   0 = legacy per-batch shuffle (runs emerge only by chance;
    #   stacked dispatch then degenerates to per-batch replay).

    # --- model (components 2-10) ---
    conditional: bool = True           # seq2seq VAE vs decoder-only
    enc_model: str = "lstm"            # encoder cell: lstm | layer_norm | hyper
    dec_model: str = "lstm"            # decoder cell: lstm | layer_norm | hyper
    enc_rnn_size: int = 256            # per-direction encoder width
    dec_rnn_size: int = 512
    z_size: int = 128
    num_mixture: int = 20
    # HyperLSTM sub-network (component 4)
    hyper_rnn_size: int = 256
    hyper_embed_size: int = 32
    # class-conditional decoding (BASELINE configs 4-5; flagged UNVERIFIED in
    # SURVEY §3.5 — implemented as an optional learned class embedding
    # concatenated to the decoder input)
    num_classes: int = 0
    class_embed_size: int = 64

    # --- regularization ---
    use_recurrent_dropout: bool = True
    recurrent_dropout_keep: float = 0.90
    use_input_dropout: bool = False
    input_dropout_keep: float = 0.90
    use_output_dropout: bool = False
    output_dropout_keep: float = 0.90

    # --- VAE loss (component 10) ---
    kl_weight: float = 0.5
    kl_weight_start: float = 0.01
    kl_decay_rate: float = 0.99995
    kl_tolerance: float = 0.20

    # --- optimizer (component 11) ---
    learning_rate: float = 1e-3
    decay_rate: float = 0.9999
    min_learning_rate: float = 1e-5
    grad_clip: float = 1.0

    # --- training loop (component 12) ---
    num_steps: int = 100000
    save_every: int = 500
    eval_every: int = 500
    log_every: int = 20
    prefetch_depth: int = 2            # input-pipeline overlap (0 = sync feed)
    steps_per_call: int = 1            # micro-steps per jitted train call:
    #   K>1 runs K optimizer steps as ONE lax.scan'd XLA program fed a
    #   stacked [K, ...] batch — one host->device dispatch per K steps.
    #   Classic TPU host-loop amortization: when per-launch latency is
    #   comparable to step compute (remote/tunneled runtimes, small
    #   models), dispatch cost drops by K x. Logging/eval granularity
    #   coarsens to every K steps.
    eval_steps_per_call: int = 8       # eval-sweep analogue of
    #   steps_per_call: the sweep scans K eval batches per jitted call
    #   (one dispatch + one host fetch per K batches). Same per-index
    #   keys and weighting as the per-batch sweep; results agree to
    #   ~1e-6 float reassociation noise. 1 restores the per-batch path.
    async_checkpoint: bool = True      # save_every checkpoints commit on
    #   a background writer thread (train/async_ckpt.py): the loop only
    #   snapshots device state (async HBM copy + early D2H) and moves
    #   on, instead of blocking on fetch + msgpack write. Byte-identical
    #   files and restore states vs the sync path (same commit code on
    #   an already-fetched snapshot); at most ONE save in flight (the
    #   next save joins the previous). false = the synchronous save.
    metrics_defer: bool = True         # log_every metrics convert to
    #   host floats one window LATE (train/metrics.py MetricsDrain), by
    #   when that window's compute has long finished — logging then
    #   never synchronizes the step-dispatch chain. Values are bitwise
    #   identical (late fetch, not lossy); check_finite stops training
    #   at most one window after a divergence. false = convert eagerly
    #   at the window (the pre-r6 synchronous behavior).
    ckpt_retries: int = 2              # bounded retries for a TRANSIENT
    #   checkpoint-commit I/O failure (ISSUE 10): the commit is
    #   idempotent (tmp + rename per file), so a torn first attempt is
    #   simply rewritten. A failure that survives the budget still
    #   stops training loudly — sync saves immediately, async saves one
    #   cadence late (train/async_ckpt.py). 0 = fail on first error
    #   (the pre-resilience behavior).
    ckpt_retry_backoff_s: float = 0.05  # base of the deterministic
    #   exponential backoff between checkpoint-commit retries
    #   (min(2s, base * 2**attempt) — utils/faults.backoff_s). 0 =
    #   retry immediately (tests).
    resume_align: bool = True          # crash-equivalent resume (ISSUE
    #   10): on resume from step R, fast-forward the training feed by R
    #   batches so the resumed run consumes EXACTLY the batches the
    #   uninterrupted run would have from step R on — combined with the
    #   per-step fold_in(key, step) RNG this makes kill+resume
    #   reproduce the uninterrupted final state leaf-bitwise
    #   (scripts/resilience_bench.py proves it). Costs R host batch
    #   assemblies at startup (~ms each; minutes at step ~500k) —
    #   false restores the legacy fresh-stream resume, which converges
    #   to the same loss but is not bitwise replayable.

    # --- TPU / parallelism (component 18) ---
    transfer_dtype: str = "float32"    # host->device dtype of the TRAIN
    #   batch's strokes: "bfloat16" halves the per-step transfer bytes
    #   (measured +3% flagship throughput in a fast tunnel window, more
    #   when transfer-bound). Loss math stays f32 (the model upcasts on
    #   entry); the semantic delta is bf16 rounding of the inputs and
    #   MDN targets — smaller than the augmentation jitter, but not
    #   bit-parity: eval sweeps always feed float32. "int16" moves the
    #   same 2 bytes/element as bfloat16 but is EXACT for integer-origin
    #   corpora like QuickDraw — the on-device dequant reproduces host
    #   normalization bit-for-bit at measured throughput parity
    #   (data/prefetch.py) — the recommended mode for real data.
    #   (Exact for unaugmented feeds; train-time random-scale jitter
    #   makes offsets non-integer first, so the jittered feed rounds by
    #   <=0.5 raw units — augmentation noise, not data.) The
    #   quantization step is 1 raw data unit, so the path REFUSES
    #   corpora whose normalization scale makes that coarse
    #   (float-natured data, e.g. the legacy float synthetic corpus).
    compute_dtype: str = "float32"     # "bfloat16" for MXU-friendly matmuls
    fused_rnn: bool = False            # Pallas recompute-backward kernels for
    #   ALL three cells (ops/pallas_fused.py): measured fwd+bwd at the
    #   flagship decoder shape (T=250 B=128 H=512, f32) on v5e vs scan:
    #   lstm 10.6->6.6 ms, layer_norm 15.0->7.3 ms, hyper 29.0->12.5 ms.
    fused_residual_dtype: str = "float32"  # storage dtype of the fused
    #   kernels' saved streams (hs + pre-step carries): "bfloat16" halves
    #   residual HBM footprint/bandwidth — the difference between batch
    #   4096 fitting and OOM for the hyper decoder on a 16G chip. The
    #   in-kernel recurrence stays f32, but hs (the RNN's OUTPUT) is
    #   stored rounded, so downstream activations/losses shift by bf16
    #   rounding and gradients pick up ~0.4-1% relative recompute noise.
    remat: bool = False                # jax.checkpoint the RNN scan steps
    #   (trades ~30% step time for the per-step residual memory; enables
    #   global batches >=1024 at max_seq_len=250 on a 16G-HBM chip)
    mesh_shape: Tuple[int, ...] = (-1,)  # -1 = all devices on the data axis
    mesh_axes: Tuple[str, ...] = ("data",)

    # --- serving (serve/engine.py: continuous-batching generation) ---
    serve_slots: int = 64              # decoder slots B: requests resident
    #   in the chunked decode program at once; finished slots are
    #   recycled to queued requests between chunks
    serve_chunk: int = 8               # decode steps K per dispatch: the
    #   sampler analogue of steps_per_call (one compiled program
    #   advances all slots K steps; higher K amortizes launch latency,
    #   lower K admits faster — finished slots idle at most K-1 steps)
    decode_kernel: str = "scan"        # serve-chunk program flavor
    #   (ISSUE 17): "scan" = the lax.scan chunk program (the bitwise
    #   fallback pin — decode_kernel=scan + float32 params is the
    #   pre-kernel engine, byte for byte); "pallas" = the fused
    #   cache-resident decode kernel (ops/pallas_decode.py): one
    #   pallas_call per K-step chunk with the (c, h) carry, prev
    #   stroke and t/done state resident in VMEM — no HBM carry
    #   round-trip per step — fusing cell + projection + MDN head +
    #   sampler per step. Interpret-mode off-TPU (the CPU tier-1
    #   path), where its strokes are bitwise the scan program's;
    #   lstm/layer_norm decoders only (the hyper cell refuses with a
    #   pointer back to scan). Also selects the fused teacher-forced
    #   prefix replay in the endpoint encode phase.
    serve_quantize: str = "float32"    # inference param quantization
    #   (serve/quantize.py): "int8" = per-tensor symmetric int8 with
    #   dequant-on-load (~4x smaller params; error <= scale/2 =
    #   max|w|/254 per tensor — the loader's int16 exact-transfer
    #   idiom one octave coarser, EXACT for weights already on the
    #   int8 grid); "bfloat16" = round-through-bf16 (~2x, relative
    #   error <= 2^-8). Serving compute stays float32 — the quantized
    #   engine runs the dequantized weights, and every Result's
    #   ckpt_id is stamped ":int8"/":bf16" so mixed-precision serving
    #   is honest. float32 = off (the bitwise pin).
    serve_prefix_edges: Tuple[int, ...] = ()  # prefix bucket edges of
    #   the multi-task endpoint encode phase (serve/endpoints.py): an
    #   encoder-endpoint request's stroke prefix is padded to the
    #   smallest edge that fits it, so the fixed-geometry encode
    #   program compiles once per (pool rows, edge) — the bucketed-
    #   execution discipline applied to serving (ISSUE 15). Strictly
    #   ascending, terminal edge <= max_seq_len (max_seq_len is always
    #   an implicit terminal edge). Empty (default) = the small
    #   power-of-two ladder serve/endpoints.default_prefix_edges picks.
    draft_rnn_size: int = 64           # hidden size of the speculative
    #   draft decoder (ISSUE 18): a 1-layer narrow LSTM distilled from
    #   the full decoder (`cli distill`) that proposes the next stroke
    #   one combined-scan position ahead of the verifier. Small enough
    #   that riding along with the full cell adds marginal FLOPs.
    draft_num_mixture: int = 0         # GMM components of the draft MDN
    #   head; 0 (default) inherits num_mixture. A truncated mixture
    #   shrinks the draft head further at some acceptance-rate cost.
    draft_depth: int = 32              # D: speculative positions per
    #   verify dispatch. Each dispatch commits up to D accepted rows
    #   plus the verifier's own correction row, so one program launch
    #   can advance a slot D+1 steps instead of serve_chunk.
    draft_tol: float = 0.35            # acceptance tolerance on the
    #   continuous GMM draw: a proposal is accepted iff its pen one-hot
    #   matches the verifier's EXACTLY (rejection over the pen-state
    #   CDF — both samplers invert the same uniform) and |Δx|,|Δy|
    #   deviate from the verifier's draw by <= draft_tol (data units).
    #   Emitted rows are ALWAYS the verifier's draws, so draft_tol
    #   trades acceptance rate against nothing — output is bitwise the
    #   full model's at any tolerance.

    def __post_init__(self):
        if self.enc_model not in CELL_TYPES or self.dec_model not in CELL_TYPES:
            raise ValueError(
                f"cell types must be one of {CELL_TYPES}, got "
                f"enc={self.enc_model!r} dec={self.dec_model!r}")
        if self.batch_size <= 0 or self.max_seq_len <= 0:
            raise ValueError("batch_size and max_seq_len must be positive")
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"compute_dtype must be 'float32' or 'bfloat16', got "
                f"{self.compute_dtype!r}")
        if self.transfer_dtype not in ("float32", "bfloat16", "int16"):
            raise ValueError(
                f"transfer_dtype must be 'float32', 'bfloat16' or "
                f"'int16', got {self.transfer_dtype!r}")
        if self.fused_residual_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"fused_residual_dtype must be 'float32' or 'bfloat16', "
                f"got {self.fused_residual_dtype!r}")
        if self.steps_per_call < 1:
            raise ValueError(
                f"steps_per_call must be >= 1, got {self.steps_per_call}")
        if self.eval_steps_per_call < 1:
            raise ValueError(f"eval_steps_per_call must be >= 1, got "
                             f"{self.eval_steps_per_call}")
        if self.serve_slots < 1 or self.serve_chunk < 1:
            raise ValueError(
                f"serve_slots and serve_chunk must be >= 1, got "
                f"{self.serve_slots}/{self.serve_chunk}")
        if self.decode_kernel not in ("scan", "pallas"):
            raise ValueError(
                f"decode_kernel must be 'scan' or 'pallas', got "
                f"{self.decode_kernel!r}")
        if self.serve_quantize not in ("float32", "bfloat16", "int8"):
            raise ValueError(
                f"serve_quantize must be 'float32', 'bfloat16' or "
                f"'int8', got {self.serve_quantize!r}")
        if self.bucket_edges:
            edges = self.bucket_edges
            if any(e <= 0 for e in edges):
                raise ValueError(f"bucket_edges must be positive pad "
                                 f"lengths, got {edges}")
            if list(edges) != sorted(set(edges)):
                raise ValueError(f"bucket_edges must be strictly "
                                 f"ascending, got {edges}")
            if edges[-1] > self.max_seq_len:
                raise ValueError(
                    f"bucket_edges {edges} exceed max_seq_len="
                    f"{self.max_seq_len}; a bucket longer than the padded "
                    f"maximum can never be filled")
        if self.serve_prefix_edges:
            edges = self.serve_prefix_edges
            if any(e <= 0 for e in edges):
                raise ValueError(f"serve_prefix_edges must be positive "
                                 f"pad lengths, got {edges}")
            if list(edges) != sorted(set(edges)):
                raise ValueError(f"serve_prefix_edges must be strictly "
                                 f"ascending, got {edges}")
            if edges[-1] > self.max_seq_len:
                raise ValueError(
                    f"serve_prefix_edges {edges} exceed max_seq_len="
                    f"{self.max_seq_len}; a prefix longer than the "
                    f"padded maximum can never be encoded")
        if self.draft_rnn_size < 1:
            raise ValueError(
                f"draft_rnn_size must be >= 1, got {self.draft_rnn_size}")
        if self.draft_num_mixture < 0:
            raise ValueError(
                f"draft_num_mixture must be >= 0 (0 = inherit "
                f"num_mixture), got {self.draft_num_mixture}")
        if self.draft_depth < 1:
            raise ValueError(
                f"draft_depth must be >= 1, got {self.draft_depth}")
        if self.draft_tol < 0:
            raise ValueError(
                f"draft_tol must be >= 0, got {self.draft_tol}")
        if self.bucket_shuffle_window < 1:
            raise ValueError(f"bucket_shuffle_window must be >= 1, got "
                             f"{self.bucket_shuffle_window}")
        if self.bucket_run_len < 0:
            raise ValueError(f"bucket_run_len must be >= 0, got "
                             f"{self.bucket_run_len}")
        if self.ckpt_retries < 0 or self.ckpt_retry_backoff_s < 0:
            raise ValueError(
                f"ckpt_retries and ckpt_retry_backoff_s must be >= 0, "
                f"got {self.ckpt_retries}/{self.ckpt_retry_backoff_s}")

    # -- overrides ---------------------------------------------------------

    def replace(self, **kw: Any) -> "HParams":
        return dataclasses.replace(self, **kw)

    def parse(self, spec: str) -> "HParams":
        """Apply a reference-style ``key=value,key=value`` override string."""
        if not spec:
            return self
        fields = {f.name: f for f in dataclasses.fields(self)}
        out: dict = {}
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise ValueError(f"bad hparam override {item!r} (want key=value)")
            key, val = item.split("=", 1)
            key = key.strip()
            if key not in fields:
                raise ValueError(f"unknown hparam {key!r}")
            out[key] = _coerce(val.strip(), self.__getattribute__(key))
        return self.replace(**out)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "HParams":
        raw = json.loads(text)
        for k, v in raw.items():
            if isinstance(v, list):
                raw[k] = tuple(v)
        return cls(**raw)


def _coerce(val: str, like: Any) -> Any:
    """Coerce a string override to the type of the current field value."""
    if isinstance(like, bool):  # before int: bool is an int subclass
        low = val.lower()
        if low in ("1", "true", "t", "yes"):
            return True
        if low in ("0", "false", "f", "no"):
            return False
        raise ValueError(f"bad bool {val!r}")
    if isinstance(like, int):
        return int(val)
    if isinstance(like, float):
        return float(val)
    if isinstance(like, tuple):
        items = [s for s in val.split(";") if s]
        if like and isinstance(like[0], int):
            return tuple(int(s) for s in items)
        if not like and all(_is_int(s) for s in items):
            # empty-tuple defaults (bucket_edges=()) carry no element
            # type to copy; all-integer literals coerce to ints so
            # "bucket_edges=64;128" does not silently become strings
            return tuple(int(s) for s in items)
        return tuple(items)
    return val


def _is_int(s: str) -> bool:
    try:
        int(s)
        return True
    except ValueError:
        return False


def get_default_hparams() -> HParams:
    """Reference-parity defaults (SURVEY §5 'Config / flag system')."""
    return HParams()
