"""SVG export of stroke sketches (SURVEY.md §2 component 17).

TPU-native-framework equivalent of the reference notebook's
``draw_strokes`` (reference unreadable — canonical behavior: render the
stroke-3 polylines as an SVG path, pen-lifts splitting subpaths).
Dependency-free string assembly; no drawing library needed.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from sketch_rnn_tpu.data import strokes as S


def strokes_to_svg(stroke3: np.ndarray, factor: float = 0.2,
                   padding: float = 10.0, stroke_width: float = 1.0,
                   color: str = "black",
                   path: Optional[str] = None) -> str:
    """Render one stroke-3 sketch to an SVG document string.

    ``factor`` scales data units to pixels (canonical default 0.2 for
    QuickDraw-scale data). Writes to ``path`` as well when given.
    """
    lines = S.strokes_to_lines(np.asarray(stroke3, np.float32))
    pts = [p for line in lines for p in line]
    if not pts:
        pts = [(0.0, 0.0)]
    xs = [p[0] for p in pts]
    ys = [p[1] for p in pts]
    min_x, max_x = min(xs), max(xs)
    min_y, max_y = min(ys), max(ys)
    sx = lambda x: (x - min_x) / factor + padding
    sy = lambda y: (y - min_y) / factor + padding
    w = (max_x - min_x) / factor + 2 * padding
    h = (max_y - min_y) / factor + 2 * padding

    parts = []
    for line in lines:
        if not line:
            continue
        x0, y0 = line[0]
        d = [f"M{sx(x0):.2f},{sy(y0):.2f}"]
        d += [f"L{sx(x):.2f},{sy(y):.2f}" for x, y in line[1:]]
        parts.append(
            f'<path d="{" ".join(d)}" fill="none" stroke="{color}" '
            f'stroke-width="{stroke_width}" stroke-linecap="round" '
            f'stroke-linejoin="round"/>')
    svg = (f'<svg xmlns="http://www.w3.org/2000/svg" '
           f'width="{w:.0f}" height="{h:.0f}" '
           f'viewBox="0 0 {w:.2f} {h:.2f}">\n'
           + "\n".join(parts) + "\n</svg>\n")
    if path:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            f.write(svg)
    return svg


def svg_grid(sketches: Sequence[np.ndarray], cols: int = 5,
             cell: float = 160.0,
             path: Optional[str] = None) -> str:
    """Render many sketches in a grid (the notebook's side-by-side view).

    Each sketch is auto-scaled to fit its cell (no ``factor``: grid cells
    normalize scale per sketch by design).
    """
    n = len(sketches)
    cols = max(1, min(cols, n))
    rows = (n + cols - 1) // cols
    w, h = cols * cell, rows * cell
    out = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{w:.0f}" '
           f'height="{h:.0f}" viewBox="0 0 {w:.2f} {h:.2f}">']
    for i, sk in enumerate(sketches):
        lines = S.strokes_to_lines(np.asarray(sk, np.float32))
        pts = [p for line in lines for p in line]
        if not pts:
            continue
        xs = [p[0] for p in pts]
        ys = [p[1] for p in pts]
        span = max(max(xs) - min(xs), max(ys) - min(ys), 1e-6)
        scale = (cell * 0.85) / span
        ox = (i % cols) * cell + cell * 0.075 - min(xs) * scale
        oy = (i // cols) * cell + cell * 0.075 - min(ys) * scale
        for line in lines:
            if not line:
                continue
            d = [f"M{line[0][0] * scale + ox:.2f},{line[0][1] * scale + oy:.2f}"]
            d += [f"L{x * scale + ox:.2f},{y * scale + oy:.2f}"
                  for x, y in line[1:]]
            out.append(f'<path d="{" ".join(d)}" fill="none" stroke="black" '
                       f'stroke-width="1.5" stroke-linecap="round"/>')
    out.append("</svg>\n")
    svg = "\n".join(out)
    if path:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            f.write(svg)
    return svg
