"""On-device autoregressive sampler: the whole loop is one XLA program.

TPU-native equivalent of the reference's ``sample()`` (SURVEY.md §2
component 15, §3.3; reference unreadable — semantics per the canonical
host loop: per step, temperature-scale the mixture logits, draw a
component, draw (dx, dy) from the chosen bivariate Gaussian with sigma
scaled by sqrt(temperature), draw the pen state, stop at p3 or max_len).

The reference crosses the host↔device boundary EVERY step; here the loop
is a ``lax.while_loop`` inside one jitted computation — no host sync until
the finished batch of sketches is fetched (BASELINE.json: "runs as an
on-device lax.while_loop so generation needs no host sync"). The loop
early-exits as soon as every sketch in the batch has drawn its
end-of-sketch pen state; finished rows within a still-running batch are
frozen to the end token.

Sampling is batched: one call draws B sketches in parallel — B small MXU
matmuls per step become one batched matmul, which is how an RNN sampler
keeps a TPU busy.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.data import strokes as S
from sketch_rnn_tpu.ops import mdn
from sketch_rnn_tpu.utils.compat import shard_map

END_TOKEN = jnp.array([0.0, 0.0, 0.0, 0.0, 1.0], jnp.float32)
START_TOKEN = jnp.array([0.0, 0.0, 1.0, 0.0, 0.0], jnp.float32)


def sample_from_mixture(mp: mdn.MixtureParams, key: jax.Array,
                        temperature: jax.Array, greedy: bool = False
                        ) -> jax.Array:
    """Draw one stroke-5 row per batch element from MDN parameters ``[B,·]``.

    Temperature ``tau`` scales the component/pen logits by ``1/tau`` and the
    Gaussian stds by ``sqrt(tau)`` (canonical semantics). ``greedy`` takes
    the argmax component, its mean, and the argmax pen state (tau ignored).
    """
    kc, kg, kp = jax.random.split(key, 3)
    tau = jnp.asarray(temperature, jnp.float32)
    if greedy:
        idx = jnp.argmax(mp.log_pi, axis=-1)
        pen_idx = jnp.argmax(mp.pen_logits, axis=-1)
    else:
        idx = jax.random.categorical(kc, mp.log_pi / tau, axis=-1)
        pen_idx = jax.random.categorical(kp, mp.pen_logits / tau, axis=-1)

    take = lambda a: jnp.take_along_axis(a, idx[..., None], axis=-1)[..., 0]
    mu1, mu2 = take(mp.mu1), take(mp.mu2)
    s1, s2 = jnp.exp(take(mp.log_s1)), jnp.exp(take(mp.log_s2))
    rho = take(mp.rho)
    if greedy:
        dx, dy = mu1, mu2
    else:
        e = jax.random.normal(kg, (*mu1.shape, 2), jnp.float32)
        sq = jnp.sqrt(tau)
        dx = mu1 + s1 * sq * e[..., 0]
        dy = mu2 + s2 * sq * (rho * e[..., 0]
                              + jnp.sqrt(1.0 - jnp.square(rho)) * e[..., 1])
    pen = jax.nn.one_hot(pen_idx, 3, dtype=jnp.float32)
    return jnp.concatenate([dx[..., None], dy[..., None], pen], axis=-1)


def make_sampler(model, hps: HParams, max_len: Optional[int] = None,
                 greedy: bool = False, mesh=None):
    """Cached wrapper around :func:`_build_sampler`.

    The compiled sampler is memoized on the model instance so repeated
    ``sample()`` calls (per temperature, per interpolation frame) reuse one
    XLA program instead of re-tracing.

    ``mesh``: shard generation over the mesh's ``data`` axis — each
    device runs the whole autoregressive while_loop on its own batch
    shard (the loop body is collective-free, so shards draw and
    early-exit independently); per-shard PRNG streams fold in the axis
    index. The batch must be divisible by the axis size.
    """
    cache = getattr(model, "_sampler_cache", None)
    if cache is None:
        cache = model._sampler_cache = {}
    ckey = (int(max_len or hps.max_seq_len), bool(greedy), mesh)
    if ckey not in cache:
        cache[ckey] = _build_sampler(model, hps, max_len, greedy, mesh)
    return cache[ckey]


def _row_done(stroke: jax.Array, done: jax.Array, t: jax.Array,
              max_steps: Optional[jax.Array]) -> jax.Array:
    """Per-row done update: end-of-sketch pen state, plus the optional
    per-row step cap (rows freeze after emitting ``max_steps`` strokes —
    the serving benchmark's controlled-length mix rides on this)."""
    new_done = done | (stroke[:, 4] > 0.5)
    if max_steps is not None:
        new_done = new_done | (t + 1 >= max_steps)
    return new_done


def _build_sampler(model, hps: HParams, max_len: Optional[int] = None,
                   greedy: bool = False, mesh=None):
    """Build the jitted batched sampler.

    Returns ``fn(params, key, batch_size, z, labels, temperature) ->
    (strokes5 [B, max_len, 5], lengths [B])``. ``z`` is required when the
    model is conditional (``[B, Nz]``) and must be None otherwise;
    ``labels`` likewise for class-conditional models. ``batch_size`` is
    static (one compile per B); ``temperature`` is a runtime scalar.
    ``lengths`` counts rows before the end-of-sketch pen state (or
    ``max_len`` if it never fired); rows past each sketch's end are end
    tokens, so the buffer is valid stroke-5 padding.

    ``max_steps`` (optional, ``[B]`` int32): per-row step cap — row ``i``
    freezes to end tokens once it has emitted ``max_steps[i]`` strokes,
    even without drawing the end-of-sketch pen state (its ``length`` is
    then ``max_steps[i]``: every emitted stroke is real). The while_loop
    still runs until EVERY row is done, i.e. ``max(max_steps)`` steps
    when the pen state never fires — this is exactly the
    freeze-until-batch-done cost profile the serving engine's
    continuous batching is benchmarked against.
    """
    t_max = int(max_len or hps.max_seq_len)

    def _sample_shard(params, key, batch_size: int, z=None, labels=None,
                      temperature=1.0, max_steps=None):
        carry0 = model.decoder_initial_carry(params, z, batch_size)
        prev0 = jnp.broadcast_to(START_TOKEN, (batch_size, 5))
        done0 = jnp.zeros((batch_size,), bool)
        len0 = jnp.zeros((batch_size,), jnp.int32)
        out0 = jnp.broadcast_to(END_TOKEN, (t_max, batch_size, 5))

        def cond(st):
            t, _, _, done, _, _, _ = st
            return (t < t_max) & ~jnp.all(done)

        def body(st):
            t, carry, prev, done, length, out, key = st
            key, k = jax.random.split(key)
            new_carry, raw = model.decode_step(params, carry, prev, z, labels)
            mp = mdn.get_mixture_params(raw, hps.num_mixture)
            stroke = sample_from_mixture(mp, k, temperature, greedy=greedy)
            # freeze finished rows: emit end tokens, keep the old carry
            stroke = jnp.where(done[:, None], END_TOKEN[None], stroke)
            carry = jax.tree_util.tree_map(
                lambda new, old: jnp.where(
                    done.reshape((-1,) + (1,) * (new.ndim - 1)), old, new),
                new_carry, carry)
            new_done = _row_done(stroke, done, t, max_steps)
            # length counts real strokes: live steps that did not draw
            # the end-of-sketch pen state. (Counting ~new_done instead
            # would also drop the LAST real stroke of cap-terminated
            # rows — the serving engine counts that stroke, and the two
            # paths must agree on the same event.)
            length = length + (~done & ~(stroke[:, 4] > 0.5))\
                .astype(jnp.int32)
            out = lax.dynamic_update_index_in_dim(out, stroke, t, axis=0)
            return (t + 1, carry, stroke, new_done, length, out, key)

        # under shard_map the folded key (and z-derived carry) vary over
        # the data axis while the zero/broadcast parts do not; widen so
        # the while_loop carry types match (no-op off-mesh)
        from sketch_rnn_tpu.ops.rnn import _match_vma
        init = _match_vma(
            (jnp.int32(0), carry0, prev0, done0, len0, out0, key), key)
        _, _, _, done, length, out, _ = lax.while_loop(cond, body, init)
        # sketches that never drew p3 run the full buffer
        length = jnp.where(done, length, t_max)
        return jnp.transpose(out, (1, 0, 2)), length

    if mesh is None:
        return jax.jit(_sample_shard, static_argnames=("batch_size",))

    from jax.sharding import PartitionSpec as P

    from sketch_rnn_tpu.parallel.mesh import DATA_AXIS, check_batch_divisible

    n_dev = mesh.shape[DATA_AXIS]

    @functools.partial(jax.jit, static_argnames=("batch_size",))
    def sharded(params, key, batch_size: int, z=None, labels=None,
                temperature=1.0, max_steps=None):
        check_batch_divisible(batch_size, mesh)

        def per_device(params, key, z, labels, temperature, max_steps):
            key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
            return _sample_shard(params, key, batch_size // n_dev, z,
                                 labels, temperature, max_steps)

        # z/labels/max_steps may be None (empty pytrees) — specs unused.
        # 0.4.x's check_rep has no rule for the sampling while_loop;
        # 0.9's vma tracking does (see _match_vma), so the check stays
        # live exactly where it can run.
        from sketch_rnn_tpu.utils.compat import VMA_TRACKING
        return shard_map(
            per_device, mesh=mesh,
            in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS), P(),
                      P(DATA_AXIS)),
            out_specs=P(DATA_AXIS),
            check_vma=VMA_TRACKING,
        )(params, key, z, labels, temperature, max_steps)

    return sharded


def sample(model, params, hps: HParams, key: jax.Array, n: int = 1,
           temperature: float = 1.0, z: Optional[jax.Array] = None,
           labels: Optional[jax.Array] = None,
           max_len: Optional[int] = None, greedy: bool = False,
           scale_factor: float = 1.0, mesh=None) -> Tuple[list, np.ndarray]:
    """Convenience wrapper: draw ``n`` sketches, return host stroke-3 list.

    For conditional models with no ``z`` given, draws z ~ N(0, I) (the
    prior), matching the reference's unconditional-generation mode of a
    trained VAE. Offsets are multiplied back by ``scale_factor`` so the
    output is in data units. ``mesh``: shard generation over the data
    axis (see :func:`make_sampler`).
    """
    kz, ks = jax.random.split(key)
    if hps.conditional and z is None:
        z = jax.random.normal(kz, (n, hps.z_size), jnp.float32)
    if hps.num_classes > 0 and labels is None:
        labels = jnp.zeros((n,), jnp.int32)
    sampler = make_sampler(model, hps, max_len=max_len, greedy=greedy,
                           mesh=mesh)
    strokes5, lengths = sampler(params, ks, n, z, labels,
                                jnp.float32(temperature))
    strokes5 = np.asarray(strokes5)
    out = []
    for i in range(n):
        s3 = S.to_normal_strokes(strokes5[i])
        s3[:, 0:2] *= scale_factor
        out.append(s3)
    return out, np.asarray(lengths)
