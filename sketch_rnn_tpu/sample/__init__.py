"""Sampling / generation subsystem (SURVEY.md §2 components 15 and 17)."""

from sketch_rnn_tpu.sample.sampler import (
    make_sampler,
    sample,
    sample_from_mixture,
)
from sketch_rnn_tpu.sample.interpolate import (
    encode_mu,
    interpolate_latents,
    lerp,
    slerp,
)
from sketch_rnn_tpu.sample.svg import strokes_to_svg, svg_grid

__all__ = [
    "make_sampler",
    "sample",
    "sample_from_mixture",
    "slerp",
    "lerp",
    "interpolate_latents",
    "encode_mu",
    "strokes_to_svg",
    "svg_grid",
]
