"""Latent-space utilities: encode, interpolate (SURVEY.md §2 component 17).

TPU-native-framework equivalent of the reference notebook's latent
interpolation demo (reference unreadable — canonical behavior: encode two
sketches, spherically interpolate between their latents, decode each).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from sketch_rnn_tpu.config import HParams


def encode_mu(model, params, batch) -> jax.Array:
    """Posterior mean for a loader batch — the deterministic embedding.

    ``batch`` is a loader dict (``strokes [B, Nmax+1, 5]``, ``seq_len``).
    The encoder consumes the sequence without the start token, as in
    training (SURVEY §3.2: the encoder sees S_1..S_Nmax).
    """
    strokes = jnp.transpose(jnp.asarray(batch["strokes"]), (1, 0, 2))[1:]
    mu, _ = model.encode(params, strokes, jnp.asarray(batch["seq_len"]),
                         train=False)
    return mu


def lerp(z0: jax.Array, z1: jax.Array, t: jax.Array) -> jax.Array:
    return (1.0 - t) * z0 + t * z1


def slerp(z0: jax.Array, z1: jax.Array, t: jax.Array) -> jax.Array:
    """Spherical interpolation (canonical for VAE latents on ~N(0,I))."""
    z0 = jnp.asarray(z0, jnp.float32)
    z1 = jnp.asarray(z1, jnp.float32)
    dot = jnp.sum(z0 * z1) / (jnp.linalg.norm(z0) * jnp.linalg.norm(z1))
    omega = jnp.arccos(jnp.clip(dot, -1.0 + 1e-7, 1.0 - 1e-7))
    so = jnp.sin(omega)
    return jnp.where(
        so < 1e-6,
        lerp(z0, z1, t),
        (jnp.sin((1.0 - t) * omega) / so) * z0
        + (jnp.sin(t * omega) / so) * z1)


def interpolate_latents(z0: jax.Array, z1: jax.Array, n: int = 10,
                        mode: str = "slerp") -> jax.Array:
    """``n`` latents from z0 to z1 inclusive, stacked ``[n, Nz]``."""
    if mode not in ("slerp", "lerp"):
        raise ValueError(f"mode must be slerp|lerp, got {mode!r}")
    f = slerp if mode == "slerp" else lerp
    ts = jnp.linspace(0.0, 1.0, n)
    return jnp.stack([f(z0, z1, t) for t in ts])
