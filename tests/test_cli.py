"""CLI and driver-entry tests (train -> eval -> sample via main())."""

import json
import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sketch_rnn_tpu.cli import main

HP = ("batch_size=8,max_seq_len=48,enc_rnn_size=12,dec_rnn_size=16,"
      "z_size=6,num_mixture=3,hyper_rnn_size=8,hyper_embed_size=4,"
      "num_steps=3,save_every=3,eval_every=50,log_every=2")


@pytest.mark.slow
def test_cli_train_eval_sample(tmp_path, capsys):
    wd = str(tmp_path / "work")
    assert main(["train", "--synthetic", f"--workdir={wd}",
                 f"--hparams={HP}"]) == 0
    assert os.path.exists(os.path.join(wd, "train_metrics.csv"))

    # eval reads hparams back from the checkpoint meta (no --hparams)
    assert main(["eval", "--synthetic", f"--workdir={wd}",
                 "--split=valid"]) == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    ev = json.loads(line)
    assert ev["step"] == 3 and np.isfinite(ev["recon"])

    out = str(tmp_path / "s.svg")
    assert main(["sample", "--synthetic", f"--workdir={wd}", "-n", "4",
                 f"--output={out}", "--temperature=0.4"]) == 0
    assert open(out).read().startswith("<svg")


@pytest.mark.slow
def test_cli_interpolate_sample(tmp_path):
    wd = str(tmp_path / "work")
    main(["train", "--synthetic", f"--workdir={wd}", f"--hparams={HP}"])
    out = str(tmp_path / "i.svg")
    assert main(["sample", "--synthetic", f"--workdir={wd}", "-n", "3",
                 "--interpolate", f"--output={out}"]) == 0
    assert os.path.exists(out)


@pytest.mark.slow
def test_cli_reconstruct_sample(tmp_path, capsys):
    wd = str(tmp_path / "work")
    main(["train", "--synthetic", f"--workdir={wd}", f"--hparams={HP}"])
    out = str(tmp_path / "r.svg")
    assert main(["sample", "--synthetic", f"--workdir={wd}", "-n", "3",
                 "--reconstruct", f"--output={out}"]) == 0
    assert "input|reconstruction pairs" in capsys.readouterr().out
    assert open(out).read().startswith("<svg")


@pytest.mark.slow
def test_cli_temperature_sweep(tmp_path, capsys):
    wd = str(tmp_path / "work")
    main(["train", "--synthetic", f"--workdir={wd}", f"--hparams={HP}"])
    out = str(tmp_path / "t.svg")
    assert main(["sample", "--synthetic", f"--workdir={wd}", "-n", "2",
                 "--temperatures=0.3,0.8", f"--output={out}"]) == 0
    assert "2 temperature rows" in capsys.readouterr().out
    assert open(out).read().startswith("<svg")
    # malformed sweep strings are usage errors, not tracebacks
    assert main(["sample", "--synthetic", f"--workdir={wd}",
                 "--temperatures=0.3,,abc"]) == 2
    assert main(["sample", "--synthetic", f"--workdir={wd}",
                 "--temperatures=0.3", "--reconstruct"]) == 2


def test_cli_reconstruct_and_interpolate_exclusive(tmp_path):
    # argparse rejects the combination at parse time (SystemExit 2),
    # before any checkpoint restore
    with pytest.raises(SystemExit) as e:
        main(["sample", "--synthetic", f"--workdir={tmp_path}",
              "--reconstruct", "--interpolate"])
    assert e.value.code == 2


def test_cli_preset_uncond(tmp_path, capsys):
    # BASELINE config 1 as a one-flag preset; --hparams overrides on top
    wd = str(tmp_path / "work")
    assert main(["train", "--synthetic", f"--workdir={wd}",
                 "--preset=uncond_lstm", f"--hparams={HP}"]) == 0
    assert main(["eval", "--synthetic", f"--workdir={wd}",
                 "--split=valid"]) == 0
    ev = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert ev["kl_raw"] == 0.0  # unconditional: no latent, no KL


def test_cli_rejects_unknown_hparam(tmp_path):
    with pytest.raises(ValueError, match="unknown hparam"):
        main(["train", "--synthetic", f"--workdir={tmp_path}",
              "--hparams=bogus=1"])


# -- driver contract --------------------------------------------------------


def test_cli_serve_bench_random_init(tmp_path, capsys):
    """serve-bench without a checkpoint: random init, JSON metrics out,
    per-request JSONL written into the workdir — and with --trace_dir
    (ISSUE 6) a telemetry JSONL + Chrome trace whose event-derived
    latency percentiles match the engine summary."""
    wd = str(tmp_path / "serve_wd")
    td = str(tmp_path / "serve_trace")
    assert main(["serve-bench", "--random_init", "-n", "6",
                 "--slots", "3", "--chunk", "2", "--log_metrics",
                 f"--workdir={wd}", f"--trace_dir={td}",
                 f"--hparams={HP},serve_slots=3,serve_chunk=2"]) == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["kind"] == "serve_bench_cli"
    assert rep["completed"] == 6
    assert rep["slots"] == 3 and rep["chunk"] == 2
    assert rep["sketches_per_sec"] > 0
    assert rep["latency_p50_s"] <= rep["latency_p99_s"]
    assert os.path.exists(os.path.join(wd, "serve_metrics.jsonl"))
    with open(os.path.join(wd, "serve_metrics.jsonl")) as f:
        assert len(f.readlines()) == 6
    # telemetry export: chrome trace loads; trace_report's exact
    # per-request percentiles reconcile with the printed summary
    assert json.load(open(os.path.join(td, "trace.json")))["traceEvents"]
    from scripts import trace_report
    rr = trace_report.report(trace_report.load(td))
    lat = {r["metric"]: r for r in rr["latency"]}
    assert lat["latency_s"]["count"] == 6
    for p in (50, 95, 99):
        assert round(lat["latency_s"][f"p{p}_s"], 6) == \
            rep[f"latency_p{p}_s"]
    # run manifest (ISSUE 8): RUN.json beside the trace indexes the
    # run's artifacts under the SAME run_id the report and the
    # telemetry meta line carry — the join key
    from sketch_rnn_tpu.utils import runinfo
    man = runinfo.read_manifest(td)
    assert man is not None and man["kind"] == "serve_bench"
    assert man["run_id"] == rep["run_id"]
    assert man["config_hash"]
    meta = json.loads(open(os.path.join(td, "telemetry.jsonl"))
                      .readline())
    assert meta["run_id"] == rep["run_id"]
    assert os.path.basename(man["artifacts"]["jsonl"]) == \
        "telemetry.jsonl"


def test_cli_serve_bench_bad_slo_is_usage_error(tmp_path, capsys):
    # fails fast (before any model build/compile), one line on stderr
    assert main(["serve-bench", "--random_init", "--slo", "nope",
                 f"--workdir={tmp_path}"]) == 2
    assert "SLO spec" in capsys.readouterr().err


def test_cli_serve_bench_metrics_port_composes_with_trace_dir(tmp_path,
                                                              capsys):
    """ISSUE 7 satellite: --trace_dir + --metrics_port compose — the
    run serves a live /metrics endpoint, archives its final scrape as
    metrics.prom beside the trace, and the scrape's request counter +
    latency histogram series reconcile with the printed summary."""
    wd = str(tmp_path / "serve_wd")
    td = str(tmp_path / "serve_trace")
    assert main(["serve-bench", "--random_init", "-n", "6",
                 "--slots", "3", "--chunk", "2", "--metrics_port", "0",
                 "--slo", "p95<=30", f"--workdir={wd}",
                 f"--trace_dir={td}",
                 f"--hparams={HP},serve_slots=3,serve_chunk=2"]) == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["completed"] == 6
    assert rep["metrics_port"] > 0
    # SLO summary rides in the report (engine fed the tracker the
    # exact Result latencies; a 30s objective on a smoke run is met)
    slo = rep["slo"]["generate:latency_s:p95"]
    assert slo["total"] == 6 and slo["met"] is True
    # the archived scrape is real exposition text with the request
    # counter and a latency histogram series matching the summary
    prom = rep["metrics_prom"]
    assert prom == os.path.join(td, "metrics.prom")
    text = open(prom).read()
    assert ("sketch_rnn_serve_requests_completed_total 6" in text)
    assert "# TYPE sketch_rnn_serve_latency_s histogram" in text
    assert "sketch_rnn_serve_latency_s_count 6" in text
    assert 'sketch_rnn_serve_latency_s_bucket{le="+Inf"} 6' in text
    assert 'sketch_rnn_slo_requests_total{slo="generate:latency_s:p95"} 6' \
        in text
    # no server outlives the cli call (the conftest guard also checks)
    from sketch_rnn_tpu.serve import metrics_http
    assert metrics_http.live_servers() == ()


def test_cli_serve_bench_metrics_port_without_trace_dir(tmp_path,
                                                        capsys):
    """--metrics_port alone still serves real data: the core is
    enabled for the run (counters/histograms feed /metrics) but no
    telemetry files are exported — metrics.prom lands in the workdir."""
    wd = str(tmp_path / "serve_wd")
    assert main(["serve-bench", "--random_init", "-n", "4",
                 "--slots", "2", "--chunk", "2", "--metrics_port", "0",
                 f"--workdir={wd}",
                 f"--hparams={HP},serve_slots=2,serve_chunk=2"]) == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["completed"] == 4
    text = open(os.path.join(wd, "metrics.prom")).read()
    assert "sketch_rnn_serve_requests_completed_total 4" in text
    assert "sketch_rnn_serve_latency_s_count 4" in text
    assert not os.path.exists(os.path.join(wd, "telemetry.jsonl"))
    # the core was restored to the process default
    from sketch_rnn_tpu.utils import telemetry as tele
    assert not tele.get_telemetry().enabled


def test_cli_serve_bench_fleet(tmp_path, capsys):
    """ISSUE 9: serve-bench --fleet serves the burst through R
    device-pinned replica engines behind the SLA-aware scheduler; the
    report carries the fleet summary (per-class percentiles, shed
    accounting, per-replica occupancy), the per-request metrics rows
    carry replica + class, and the trace renders a PER-REPLICA
    occupancy timeline."""
    wd = str(tmp_path / "serve_wd")
    td = str(tmp_path / "serve_trace")
    assert main(["serve-bench", "--random_init", "-n", "8",
                 "--fleet", "2", "--rate", "500",
                 "--classes", "interactive:p95<=10",
                 "--classes", "batch:p99<=60",
                 "--log_metrics", f"--workdir={wd}",
                 f"--trace_dir={td}",
                 f"--hparams={HP},serve_slots=2,serve_chunk=2"]) == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["kind"] == "serve_bench_cli"
    assert rep["completed"] == 8 and rep["requests_shed"] == 0
    f = rep["fleet"]
    assert f["replicas"] == 2 and f["offered_rate"] == 500.0
    assert f["submitted"] == 8
    assert set(f["latency_by_class"]) == {"interactive", "batch"}
    assert len(f["per_replica"]) == 2
    assert f["total_device_steps"] > 0
    assert f["admission"]["admitted"] == 8
    # per-request rows carry the admission metadata
    with open(os.path.join(wd, "serve_metrics.jsonl")) as fh:
        rows = [json.loads(line) for line in fh]
    assert len(rows) == 8
    assert {r["replica"] for r in rows} <= {0, 1}
    assert {r["class"] for r in rows} == {"interactive", "batch"}
    # the trace shows one occupancy timeline per replica
    from scripts import trace_report
    rr = trace_report.report(trace_report.load(td))
    occ = rr["occupancy_replicas"]
    assert [o["replica"] for o in occ] == [0, 1]
    assert all(o["samples"] > 0 for o in occ)
    # manifest extras record the fleet shape
    from sketch_rnn_tpu.utils import runinfo
    man = runinfo.read_manifest(td)
    assert man["replicas"] == 2
    assert man["offered_rate"] == 500.0


def test_cli_serve_bench_fleet_usage_errors(tmp_path, capsys):
    # --rate/--classes without --fleet: one line, before any compile
    assert main(["serve-bench", "--random_init", "--rate", "100",
                 f"--workdir={tmp_path}"]) == 2
    assert "--fleet" in capsys.readouterr().err
    # bad class spec fails fast like a bad --slo
    assert main(["serve-bench", "--random_init", "--fleet", "2",
                 "--classes", "nope", f"--workdir={tmp_path}"]) == 2
    assert "SLO spec" in capsys.readouterr().err


def test_cli_serve_bench_endpoint_usage_errors(tmp_path, capsys):
    """ISSUE 15 satellite: endpoint/class spec validation fails fast
    (rc 2) BEFORE the checkpoint restore, matching the --slo/--classes
    precedent; unconditional checkpoints reject encoder endpoints with
    one line naming hps.conditional."""
    # --endpoints without --fleet
    assert main(["serve-bench", "--random_init",
                 "--endpoints", "complete=interactive:p95<=250ms",
                 f"--workdir={tmp_path}", f"--hparams={HP}"]) == 2
    assert "--fleet" in capsys.readouterr().err
    # unknown endpoint name
    assert main(["serve-bench", "--random_init", "--fleet", "1",
                 "--endpoints", "bogus=batch",
                 f"--workdir={tmp_path}", f"--hparams={HP}"]) == 2
    assert "unknown endpoint" in capsys.readouterr().err
    # malformed route (no '=')
    assert main(["serve-bench", "--random_init", "--fleet", "1",
                 "--endpoints", "complete",
                 f"--workdir={tmp_path}", f"--hparams={HP}"]) == 2
    assert "ENDPOINT=CLASS" in capsys.readouterr().err
    # a mix endpoint with no class route (several classes declared)
    assert main(["serve-bench", "--random_init", "--fleet", "1",
                 "--endpoints", "complete=interactive:p95<=250ms",
                 "--endpoints", "generate=batch",
                 "--endpoint_mix", "generate:1,reconstruct:1",
                 f"--workdir={tmp_path}", f"--hparams={HP}"]) == 2
    assert "no class route" in capsys.readouterr().err
    # unconditional checkpoint rejects encoder endpoints, naming
    # hps.conditional — before any restore/compile
    assert main(["serve-bench", "--random_init", "--fleet", "1",
                 "--endpoints", "complete=interactive:p95<=250ms",
                 f"--workdir={tmp_path}",
                 f"--hparams={HP},conditional=false"]) == 2
    assert "hps.conditional" in capsys.readouterr().err
    # --strokes_out outside the endpoint demos is a usage error too
    assert main(["sample", "--synthetic", f"--workdir={tmp_path}",
                 "--strokes_out", str(tmp_path / "s.npz")]) == 2
    assert "--strokes_out" in capsys.readouterr().err
    # a one-frame interpolation is a usage error before the restore
    # (the endpoint contract needs both ends of the grid)
    assert main(["sample", "--synthetic", f"--workdir={tmp_path}",
                 "--interpolate", "-n", "1"]) == 2
    assert "-n >= 2" in capsys.readouterr().err


def test_cli_serve_bench_mixed_endpoint_fleet(tmp_path, capsys):
    """ISSUE 15: serve-bench --fleet --endpoints serves a seeded mixed-
    endpoint workload, routes each endpoint to its admission class,
    and reports the per-endpoint latency table."""
    wd = str(tmp_path / "serve_wd")
    assert main(["serve-bench", "--random_init", "-n", "10",
                 "--fleet", "1", "--slots", "3", "--chunk", "2",
                 "--frames", "3",
                 "--endpoints", "generate=batch",
                 "--endpoints", "complete=interactive:p95<=10",
                 "--endpoints", "reconstruct=interactive",
                 "--endpoints", "interpolate=batch",
                 "--endpoint_mix",
                 "generate:1,complete:1,reconstruct:1,interpolate:1",
                 "--slo", "interactive:p95<=10",
                 f"--workdir={wd}",
                 f"--hparams={HP},serve_slots=3,serve_chunk=2"]) == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["completed"] == 10
    by_ep = rep["latency_by_endpoint"]
    assert sum(v["completed"] for v in by_ep.values()) == 10
    assert set(by_ep) <= {"generate", "complete", "reconstruct",
                          "interpolate"}
    f = rep["fleet"]
    assert f["endpoint_classes"]["complete"] == "interactive"
    assert set(f["latency_by_class"]) <= {"interactive", "batch"}
    # SLO verdict keyed on the admission class the endpoints route to
    assert "interactive:latency_s:p95" in rep["slo"]


def test_cli_interpolate_parity_with_serve_endpoint(tmp_path):
    """THE serve-vs-offline parity pin (ISSUE 15 satellite): `cli
    sample --interpolate --strokes_out` produces stroke-5 frames
    bitwise equal to the `interpolate` endpoint served through the
    fleet on the same checkpoint/key/serving geometry — and
    --reconstruct likewise equals the `reconstruct` endpoint."""
    import dataclasses

    import jax as _jax

    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.data.loader import synthetic_loader
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.serve import Request, ServeFleet
    from sketch_rnn_tpu.train import make_train_state, save_checkpoint

    hps = HParams.from_json(json.dumps(dict(
        batch_size=8, max_seq_len=48, enc_rnn_size=12, dec_rnn_size=16,
        z_size=6, num_mixture=3, serve_slots=4, serve_chunk=2)))
    wd = str(tmp_path / "work")
    os.makedirs(wd, exist_ok=True)
    model = SketchRNN(hps)
    state = make_train_state(model, hps, _jax.random.key(0))
    scale = 1.0
    save_checkpoint(wd, state, scale, hps)

    out_npz = str(tmp_path / "interp.npz")
    assert main(["sample", "--synthetic", f"--workdir={wd}", "-n", "3",
                 "--interpolate", "--seed", "7",
                 f"--output={tmp_path / 'i.svg'}",
                 f"--strokes_out={out_npz}"]) == 0
    cli_frames = np.load(out_npz)
    cli_frames = [cli_frames[k] for k in sorted(cli_frames.files)]
    assert len(cli_frames) == 3

    # the serve side: the SAME prefixes the cli's synthetic valid
    # loader holds (seed 2, checkpoint scale, the cli's integer grid),
    # same key/frames/temperature, same serving geometry
    valid_l, _ = synthetic_loader(hps, 2 * hps.batch_size, seed=2,
                                  scale_factor=scale,
                                  integer_grid=255.0)
    req = Request(key=_jax.random.key(7), endpoint="interpolate",
                  prefix=(valid_l.strokes[0], valid_l.strokes[1]),
                  frames=3, temperature=0.5, uid=0)
    rec_req = Request(key=_jax.random.fold_in(_jax.random.key(7), 0),
                      endpoint="reconstruct",
                      prefix=valid_l.strokes[0], temperature=0.5,
                      uid=1)
    fleet = ServeFleet(model, hps, state.params, replicas=1)
    fleet.warm(req, endpoints=True)
    try:
        fleet.submit(dataclasses.replace(req))
        fleet.submit(dataclasses.replace(rec_req))
        fleet.start()
        assert fleet.drain(timeout=300)
        res = fleet.results
    finally:
        fleet.close()
    for f, frame in enumerate(res[0]["result"].frames):
        np.testing.assert_array_equal(
            frame, cli_frames[f],
            err_msg=f"interpolation frame {f} differs cli vs serve")

    # reconstruct: cli --strokes_out vs the reconstruct endpoint
    rec_npz = str(tmp_path / "rec.npz")
    assert main(["sample", "--synthetic", f"--workdir={wd}", "-n", "1",
                 "--reconstruct", "--seed", "7",
                 f"--output={tmp_path / 'r.svg'}",
                 f"--strokes_out={rec_npz}"]) == 0
    cli_rec = np.load(rec_npz)
    np.testing.assert_array_equal(cli_rec[cli_rec.files[0]],
                                  res[1]["result"].strokes5)


def test_graft_entry_compiles():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    # the driver compile-checks exactly this: jit and lower the fn
    lowered = jax.jit(fn).lower(*args)
    assert lowered is not None


@pytest.mark.slow
def test_graft_entry_dryrun_multichip():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)


@pytest.mark.slow
def test_graft_entry_dryrun_multichip_clean_subprocess():
    """Exercise the dryrun exactly as the driver does: a plain environment
    with NO pre-set JAX_PLATFORMS / XLA_FLAGS (conftest.py pre-configures
    them in-process, which is the one environment the driver does NOT
    provide). dryrun_multichip must self-configure the virtual platform.
    """
    import subprocess

    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_ENABLE_X64")}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as ge; ge.dryrun_multichip(8)"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"dryrun failed in clean env:\nstdout: {proc.stdout}\n"
        f"stderr: {proc.stderr}")
    assert "8 devices OK" in proc.stdout


@pytest.mark.slow
def test_cli_eval_per_class(tmp_path, capsys):
    wd = str(tmp_path / "workpc")
    hp = HP + ",num_classes=3"
    assert main(["train", "--synthetic", f"--workdir={wd}",
                 f"--hparams={hp}"]) == 0
    assert main(["eval", "--synthetic", f"--workdir={wd}",
                 "--split=valid", "--per_class"]) == 0
    ev = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    per = ev["per_class"]
    assert set(per) == {"0", "1", "2"}
    present = [v for v in per.values() if v is not None]
    assert present, "synthetic valid split should contain some class"
    for v in present:
        assert np.isfinite(v["recon"])


@pytest.mark.slow
def test_cli_eval_per_class_needs_classes(tmp_path, capsys):
    wd = str(tmp_path / "worknc")
    assert main(["train", "--synthetic", f"--workdir={wd}",
                 f"--hparams={HP}"]) == 0
    assert main(["eval", "--synthetic", f"--workdir={wd}",
                 "--per_class"]) == 2


@pytest.mark.slow
def test_cli_train_no_resume(tmp_path, capsys):
    wd = str(tmp_path / "worknr")
    assert main(["train", "--synthetic", f"--workdir={wd}",
                 f"--hparams={HP}"]) == 0
    # resume (default): continues from step 3 -> no new training happens
    assert main(["train", "--synthetic", f"--workdir={wd}",
                 f"--hparams={HP}"]) == 0
    out = capsys.readouterr().out
    assert "resumed from step 3" in out
    # --no_resume: starts at step 0 and retrains to 3
    assert main(["train", "--synthetic", f"--workdir={wd}",
                 "--no_resume", f"--hparams={HP}"]) == 0
    out = capsys.readouterr().out
    assert "resumed" not in out


def test_cli_bad_fault_plan_is_usage_error(tmp_path, capsys):
    """ISSUE 10: a malformed --fault_plan fails fast (rc 2, one stderr
    line) BEFORE any data load / restore / compile, for both chaos
    entry points."""
    rc = main(["train", "--synthetic", f"--workdir={tmp_path}",
               "--fault_plan=train.step@@oops"])
    assert rc == 2
    assert "bad --fault_plan" in capsys.readouterr().err
    rc = main(["serve-bench", "--random_init", "-n", "2",
               "--fault_plan=:kind=raise"])
    assert rc == 2
    assert "bad --fault_plan" in capsys.readouterr().err
    # and a well-formed plan never leaks out of the cli (armed plans
    # are process-global; the finally disarms even on the rc-2 path)
    from sketch_rnn_tpu.utils import faults
    assert faults.get_injector() is None
    # ...including when setup fails AFTER arming (bad data_dir raises
    # inside _load_data with the plan already armed)
    with pytest.raises(FileNotFoundError):
        main(["train", f"--workdir={tmp_path}", "--data_dir=/nonexist",
              "--fault_plan=train.step@5", f"--hparams={HP}"])
    assert faults.get_injector() is None
