"""Shared fixture code for the 2-process multi-host test: both the worker
processes and the in-process single-process reference must build EXACTLY
the same model, corpus striping, batch sequence and PRNG keys, so any
parameter divergence isolates the multi-process mechanics."""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes

HPS = HParams(batch_size=8, max_seq_len=24, enc_rnn_size=8, dec_rnn_size=12,
              z_size=4, num_mixture=2, hyper_rnn_size=8, hyper_embed_size=4,
              use_recurrent_dropout=False, prefetch_depth=0)

CORPUS_SIZE = 24


def make_striped_loader(hps: HParams, host_id: int,
                        num_hosts: int) -> DataLoader:
    """Deterministic stripe of a fixed synthetic corpus (no augmentation,
    ordered get_batch access — no RNG involved in batch composition)."""
    seqs, labels = make_synthetic_strokes(CORPUS_SIZE, min_len=8,
                                          max_len=20, seed=0)
    return DataLoader(seqs[host_id::num_hosts], hps,
                      labels=labels[host_id::num_hosts],
                      global_size=CORPUS_SIZE, num_hosts=num_hosts, seed=0)


PC_CLASSES = 3


def make_striped_class_loader(hps: HParams, host_id: int,
                              num_hosts: int) -> DataLoader:
    """Labeled (3-class) variant of the striped corpus for the
    multi-host per-class eval check (VERDICT r2 #4)."""
    seqs, labels = make_synthetic_strokes(CORPUS_SIZE,
                                          num_classes=PC_CLASSES,
                                          min_len=8, max_len=20, seed=1)
    return DataLoader(seqs[host_id::num_hosts], hps,
                      labels=labels[host_id::num_hosts],
                      global_size=CORPUS_SIZE, num_hosts=num_hosts, seed=0)


def dump_per_class(per: dict, path: str) -> None:
    """Flatten an ``evaluate_per_class`` result to a keyed npz."""
    flat = {}
    for c, m in per.items():
        if m is None:
            flat[f"{c}/__none__"] = np.float64(1.0)
        else:
            for k, v in m.items():
                flat[f"{c}/{k}"] = np.float64(v)
    np.savez(path, **flat)


def step_keys(n: int) -> Iterator:
    import jax

    root = jax.random.key(42)
    return (jax.random.fold_in(root, i) for i in range(n))


def dump_params(params, path: str, extra: Optional[dict] = None) -> None:
    """Flatten a params pytree to a keyed npz (replicated arrays: take the
    first addressable shard)."""
    import jax

    flat = {}
    for kp, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in kp)
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_data"):
            leaf = leaf.addressable_data(0)
        flat[name] = np.asarray(leaf)
    for k, v in (extra or {}).items():
        flat[f"__extra__/{k}"] = np.asarray(v)
    np.savez(path, **flat)
