"""Fleet shard-merge tests (ISSUE 8).

The load-bearing contract: a merged fleet stream's GLOBAL summary
reconciles EXACTLY with the per-shard summaries — span counts/totals
and monotonic counters bitwise (sums in host order), histograms merged
on their shared log-bucket lattice (counts/totals exact, quantiles
within one geometric bucket of the pooled-exact value). Proven both
in-process and through REAL subprocesses (two `_multihost_worker.py
shard` workers exporting into one shared trace_dir, exactly the
multi-controller layout), plus the committed-shard --smoke self-check
that wires the reconciliation into tier-1 CI.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts import trace_merge, trace_report  # noqa: E402
from sketch_rnn_tpu.utils import telemetry as tele  # noqa: E402
from sketch_rnn_tpu.utils.telemetry import Histogram  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_shard(tmp_path, rank, nproc, spans=10, lat_scale=1.0):
    """Export one in-process shard with a deterministic workload."""
    tel = tele.configure(trace_dir=str(tmp_path), process_index=rank,
                         host_count=nproc, run_id="t")
    for i in range(spans):
        tel.emit_span("dispatch", "train", 0.01 * i,
                      0.01 * i + 0.003 + 1e-4 * rank)
    tel.counter("micro_steps", 5.0 + rank, cat="data")
    tel.gauge("slots_live", 3 + rank, cat="serve")
    for i in range(25):
        tel.observe("latency_s", lat_scale * 0.01 * (i + 1), cat="serve")
    paths = tel.export()
    tele.disable()
    return paths["jsonl"]


# -- shard naming ------------------------------------------------------------


def test_shard_names_collision_free_and_single_host_legacy():
    assert tele.shard_jsonl_name(0, 1) == "telemetry.jsonl"
    assert tele.shard_chrome_name(0, 1) == "trace.json"
    names = {tele.shard_jsonl_name(i, 4) for i in range(4)}
    assert len(names) == 4
    assert tele.shard_jsonl_name(2, 4) == "telemetry.p0002.jsonl"
    assert tele.shard_chrome_name(2, 4) == "trace.p0002.json"


def test_export_writes_per_host_shard_and_stamped_meta(tmp_path):
    path = _make_shard(tmp_path, rank=1, nproc=2)
    assert os.path.basename(path) == "telemetry.p0001.jsonl"
    meta = json.loads(open(path).readline())
    assert meta["process_index"] == 1 and meta["host_count"] == 2
    assert meta["run_id"] == "t"


# -- exact merge reconciliation ----------------------------------------------


def test_merge_reconciles_exactly_in_process(tmp_path):
    """Merged agg/counters are BITWISE the host-order sums of the
    shards'; merged histogram count/total exact; merged quantiles
    within one log bucket of the pooled-exact percentile."""
    p0 = _make_shard(tmp_path, 0, 2, spans=10, lat_scale=1.0)
    p1 = _make_shard(tmp_path, 1, 2, spans=17, lat_scale=3.0)
    shards = [trace_merge.load_shard(p) for p in (p0, p1)]
    merged = trace_merge.merge_shards(shards)

    k = ("train", "dispatch")
    n = shards[0]["agg"][k][0] + shards[1]["agg"][k][0]
    total = shards[0]["agg"][k][1] + shards[1]["agg"][k][1]
    assert merged["agg"][k] == (n, total)  # bitwise
    assert merged["counters"][("data", "micro_steps")] == 5.0 + 6.0
    # gauges are never summed: per-host samples + max
    assert merged["gauges"][("serve", "slots_live")] == {0: 3.0, 1: 4.0}

    h = merged["hists"][("serve", "latency_s")]
    assert h.count == 50
    tot = (shards[0]["hists"][("serve", "latency_s")]["raw"]["total"]
           + shards[1]["hists"][("serve", "latency_s")]["raw"]["total"])
    assert h.total == tot  # bitwise
    # quantiles within one geometric bucket of the pooled exact value
    pooled = np.concatenate([0.01 * np.arange(1, 26),
                             0.03 * np.arange(1, 26)])
    for q in (0.5, 0.95, 0.99):
        exact = np.percentile(pooled, 100 * q)
        assert exact / Histogram.GROWTH <= h.quantile(q) \
            <= exact * Histogram.GROWTH

    # the module's own reconciliation cross-check agrees
    assert trace_merge._reconcile(shards, merged) == []


def test_merge_outputs_and_report_over_merged_stream(tmp_path):
    _make_shard(tmp_path, 0, 2, spans=4)
    _make_shard(tmp_path, 1, 2, spans=6)
    assert trace_merge.main([str(tmp_path), "--quiet"]) == 0
    jsonl = os.path.join(str(tmp_path), trace_merge.MERGED_JSONL)
    chrome = os.path.join(str(tmp_path), trace_merge.MERGED_CHROME)
    assert os.path.exists(jsonl) and os.path.exists(chrome)

    # per-host track groups in the Chrome trace
    doc = json.load(open(chrome))
    evs = doc["traceEvents"]
    assert sorted({e["pid"] for e in evs}) == [0, 1]
    pnames = [e for e in evs if e.get("name") == "process_name"]
    assert {e["args"]["name"].split(" (")[0] for e in pnames} == \
        {"host 0", "host 1"}

    # trace_report reads the merged stream; agg totals are global
    data = trace_report.load(jsonl)
    assert data["meta"]["merged"] and data["meta"]["host_count"] == 2
    rows = {(r["cat"], r["name"]): r
            for r in trace_report.span_breakdown(data)}
    assert rows[("train", "dispatch")]["count"] == 10

    # --host filters one host's events back out
    host1 = trace_report.load(jsonl, host=1)
    rows1 = {(r["cat"], r["name"]): r
             for r in trace_report.span_breakdown(host1)}
    assert rows1[("train", "dispatch")]["count"] == 6


def test_merge_rejects_growth_mismatch_and_duplicate_hosts(tmp_path):
    p0 = _make_shard(tmp_path, 0, 2)
    p1 = _make_shard(tmp_path, 1, 2)
    s0, s1 = trace_merge.load_shard(p0), trace_merge.load_shard(p1)
    bad = trace_merge.load_shard(p1)
    for k in bad["hists"]:
        bad["hists"][k] = dict(bad["hists"][k])
        bad["hists"][k]["raw"] = dict(bad["hists"][k]["raw"],
                                      growth=2.0)
    with pytest.raises(ValueError, match="growth"):
        trace_merge.merge_shards([s0, bad])
    with pytest.raises(ValueError, match="duplicate process_index"):
        trace_merge.merge_shards([s1, trace_merge.load_shard(p1)])


def test_histogram_merge_exact_totals_and_edge_cases():
    """Histogram.merge (ISSUE 8 satellite): exact totals, empty/single
    shards well-defined, mismatched growth rejected."""
    rng = np.random.default_rng(3)
    xs, ys = rng.lognormal(size=500), rng.lognormal(size=700) * 4.0
    a, b = Histogram(), Histogram()
    for x in xs:
        a.observe(float(x))
    for y in ys:
        b.observe(float(y))
    merged = Histogram().merge(a).merge(b)  # empty-base merge works
    assert merged.count == 1200
    assert merged.total == a.total + b.total  # bitwise
    assert merged.vmin == min(a.vmin, b.vmin)
    assert merged.vmax == max(a.vmax, b.vmax)
    pooled = np.concatenate([xs, ys])
    for q in (0.5, 0.95, 0.99):
        assert merged.quantile(q) == pytest.approx(
            np.percentile(pooled, 100 * q), rel=0.05)
    # single-observation and empty shards
    single = Histogram()
    single.observe(0.25)
    m2 = Histogram().merge(single).merge(Histogram())
    assert m2.count == 1 and m2.quantile(0.99) == 0.25
    # round-trip through the serialized raw form is loss-free
    rt = Histogram.from_dict(json.loads(json.dumps(merged.to_dict())))
    assert rt.count == merged.count and rt.total == merged.total
    assert rt.summary() == merged.summary()
    with pytest.raises(ValueError, match="growth"):
        Histogram().merge(Histogram(growth=1.5))


# -- real subprocesses (the multi-controller layout) -------------------------


def test_two_subprocess_shard_merge_reconciles(tmp_path):
    """THE tier-1 fleet acceptance: two REAL worker processes (the
    `_multihost_worker.py shard` mode) export shards into one shared
    trace_dir — no path collision — and the merged global summary
    reconciles exactly with the per-shard summaries."""
    worker = os.path.join(REPO, "tests", "_multihost_worker.py")
    outdir = str(tmp_path)
    procs = [subprocess.Popen(
        [sys.executable, worker, "shard", str(rank), "2", outdir, "sub"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for rank in range(2)]
    for rank, p in enumerate(procs):
        out, _ = p.communicate(timeout=120)
        assert p.returncode == 0, f"shard worker {rank} failed:\n{out}"

    paths = trace_merge.find_shards(outdir)
    assert [os.path.basename(p) for p in paths] == [
        "telemetry.p0000.jsonl", "telemetry.p0001.jsonl"]
    assert trace_merge.main([outdir, "--quiet"]) == 0
    shards = [trace_merge.load_shard(p) for p in paths]
    merged = trace_merge.merge_shards(shards)
    assert trace_merge._reconcile(shards, merged) == []
    # counts follow the worker's rank-seeded workload: 20+5*rank spans
    assert merged["agg"][("train", "dispatch")][0] == 20 + 25
    assert merged["counters"][("serve", "requests_completed")] == 9.0
    assert merged["hists"][("serve", "latency_s")].count == 60
    assert merged["meta"]["run_id"] == "sub"
    # drop accounting surfaces in the report over the merged stream
    rep = trace_report.report(trace_report.load(
        os.path.join(outdir, trace_merge.MERGED_JSONL)))
    assert rep["ring_dropped"] == {"total": 0,
                                   "per_host": {"0": 0, "1": 0}}


# -- host death annotation (ISSUE 14 satellite) ------------------------------


SMOKE_DIR = os.path.join(REPO, "tests", "data", "fleet_shards")


def test_truncated_shard_merges_with_host_died_annotation(tmp_path):
    """A killed host's torn export (the tail — summary lines and end
    sentinel — cut off) must still MERGE, with an explicit host_died
    annotation in merged meta instead of only an undercount warning;
    exercised over a truncated COMMITTED shard."""
    import shutil

    paths = sorted(trace_merge.find_shards(SMOKE_DIR))
    assert len(paths) >= 2
    keep = os.path.join(str(tmp_path), os.path.basename(paths[0]))
    shutil.copy(paths[0], keep)
    # truncate the second shard right after its meta + a few events —
    # exactly what a hard kill mid-export leaves behind
    lines = open(paths[1]).read().splitlines()
    torn = os.path.join(str(tmp_path), os.path.basename(paths[1]))
    with open(torn, "w") as f:
        f.write("\n".join(lines[:4]) + "\n")
    shards = [trace_merge.load_shard(p) for p in (keep, torn)]
    assert shards[0]["complete"] and not shards[1]["complete"]
    merged = trace_merge.merge_shards(shards)
    died = merged["meta"]["host_died"]
    assert died == [shards[1]["meta"]["process_index"]]
    by_host = {h["process_index"]: h for h in merged["meta"]["hosts"]}
    assert by_host[died[0]]["truncated"] is True
    assert by_host[shards[0]["meta"]["process_index"]][
        "truncated"] is False
    # the annotation rides the merged stream into trace_report
    out = os.path.join(str(tmp_path), "merged")
    assert trace_merge.main([keep, torn, "--out", out,
                             "--quiet"]) == 0
    rep = trace_report.report(trace_report.load(
        os.path.join(out, trace_merge.MERGED_JSONL)))
    assert rep["host_died"] == died


def test_missing_shard_annotated_as_missing_not_dead(tmp_path):
    """A host whose shard is simply ABSENT from the merge is
    ambiguous — killed before any export, or a partial shard list
    handed to the merge — so it lands in ``missing_hosts`` (review
    fix: a healthy host must never be recorded as DEAD just because
    its shard wasn't passed in); only a truncated shard is positive
    death evidence."""
    p0 = _make_shard(tmp_path, 0, 3, spans=4)
    p2 = _make_shard(tmp_path, 2, 3, spans=5)
    merged = trace_merge.merge_shards(
        [trace_merge.load_shard(p) for p in (p0, p2)])
    assert merged["meta"]["host_count"] == 3
    assert merged["meta"]["missing_hosts"] == [1]
    assert merged["meta"]["host_died"] == []


def test_fresh_export_carries_end_sentinel(tmp_path):
    path = _make_shard(tmp_path, 0, 1)
    last = json.loads(open(path).read().splitlines()[-1])
    assert last["type"] == "end"
    assert trace_merge.load_shard(path)["complete"]


def test_sentinel_era_shard_torn_mid_summary_is_incomplete(tmp_path):
    """Review fix: the meta line announces the sentinel, so a modern
    shard torn INSIDE the summary block (past the first agg line but
    before the end sentinel) is still flagged truncated — the case a
    bare summaries-present fallback would miss."""
    path = _make_shard(tmp_path, 0, 2)
    lines = open(path).read().splitlines()
    agg_at = next(i for i, l in enumerate(lines)
                  if json.loads(l).get("type") == "agg")
    with open(path, "w") as f:
        f.write("\n".join(lines[:agg_at + 1]) + "\n")
    shard = trace_merge.load_shard(path)
    assert shard["agg"] and not shard["complete"]
    merged = trace_merge.merge_shards(
        [shard, trace_merge.load_shard(_make_shard(tmp_path, 1, 2))])
    assert merged["meta"]["host_died"] == [0]


def test_committed_shards_not_flagged_dead():
    """Pre-sentinel committed shards have summary lines — complete."""
    shards = [trace_merge.load_shard(p)
              for p in trace_merge.find_shards(SMOKE_DIR)]
    assert all(s["complete"] for s in shards)
    meta = trace_merge.merge_shards(shards)["meta"]
    assert meta["host_died"] == [] and meta["missing_hosts"] == []


# -- CI wiring ---------------------------------------------------------------


def test_trace_merge_smoke_over_committed_shards(capsys):
    """The committed-shards self-check wired into tier-1 (ISSUE 8
    satellite): `trace_merge --smoke` must reconcile exactly."""
    assert trace_merge.main(["--smoke"]) == 0
    assert "reconcile exactly" in capsys.readouterr().out


def test_trace_merge_usage_errors(tmp_path, capsys):
    assert trace_merge.main([str(tmp_path)]) == 2
    assert "no shards" in capsys.readouterr().err
