"""Worker process for the 2-process multi-host CPU test.

Launched by tests/test_multihost.py as::

    python _multihost_worker.py <rank> <nproc> <coordinator> <outdir> [fused]

``fused=1`` runs the production config (Pallas fused kernels, interpret
mode on CPU, bf16 residuals) through the same sharded step.

Each worker joins the ``jax.distributed`` cluster (the DCN path of
SURVEY.md §2 component 18 — the reference's NCCL multi-node equivalent),
contributes 2 virtual CPU devices, runs 3 deterministic data-parallel
training steps over the global 4-device mesh feeding only its OWN stripe
of the corpus, and dumps its replicated parameters for the test to
compare across processes and against a single-process run.

Telemetry-shard mode (ISSUE 8, tier-1)::

    python _multihost_worker.py shard <rank> <nproc> <outdir> [run_id]

A LIGHT worker — no jax, no cluster — that plays one host of a fleet:
it configures the telemetry core with its ``(rank, nproc)`` fleet
coordinate, records a deterministic rank-seeded workload (spans,
counters, gauges, histogram observations), and exports its per-host
shard into the shared ``outdir``. tests/test_trace_merge.py launches
two of these as REAL subprocesses and requires the merged global
summary to reconcile exactly with the per-shard summaries.
"""

import os
import sys


def shard_main() -> int:
    rank, nproc = int(sys.argv[2]), int(sys.argv[3])
    outdir = sys.argv[4]
    run_id = sys.argv[5] if len(sys.argv) > 5 else "shard-test"

    # runnable directly (no PYTHONPATH needed): the repo root is one
    # level up from tests/
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from sketch_rnn_tpu.utils import telemetry as tele

    tel = tele.configure(trace_dir=outdir, process_index=rank,
                         host_count=nproc, run_id=run_id)
    # deterministic per-rank workload: ranks record DIFFERENT counts
    # and values, so an exact merged reconciliation cannot pass by
    # symmetry. Pre-computed t0/t1 pairs (not timers) make the span
    # totals reproducible floats; anchoring them to the core's own
    # origin makes the exported ts values the intended small offsets.
    base = tel.origin_perf
    for i in range(20 + 5 * rank):
        t0 = base + 0.010 * i
        tel.emit_span("dispatch", "train", t0, t0 + 0.002 + 1e-4 * rank)
    for i in range(7 + rank):
        t0 = base + 0.025 * i
        tel.emit_span("assemble", "data", t0, t0 + 0.001)
    tel.counter("micro_steps", 10.0 + rank, cat="data")
    tel.counter("requests_completed", 3.0 * (rank + 1), cat="serve")
    tel.gauge("slots_live", 4 + rank, cat="serve")
    tel.instant("enqueue", cat="serve", args={"uid": rank},
                ts=base + 0.5)
    for i in range(30):
        tel.observe("latency_s", 0.01 * (i + 1) * (rank + 1),
                    cat="serve")
    paths = tel.export()
    print(f"[shard {rank}/{nproc}] exported {paths['jsonl']}",
          flush=True)
    return 0


def main() -> int:
    rank, nproc = int(sys.argv[1]), int(sys.argv[2])
    coordinator, outdir = sys.argv[3], sys.argv[4]
    fused = len(sys.argv) > 5 and sys.argv[5] == "1"

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=nproc, process_id=rank)
    assert jax.process_count() == nproc
    assert jax.device_count() == 2 * nproc
    assert jax.local_device_count() == 2

    import numpy as np

    from sketch_rnn_tpu.parallel import multihost as mh
    from sketch_rnn_tpu.parallel.mesh import make_mesh, shard_batch
    from sketch_rnn_tpu.train import make_train_state, make_train_step
    from tests._multihost_common import (
        HPS, dump_params, make_striped_loader, step_keys)
    from sketch_rnn_tpu.models.vae import SketchRNN

    hps = HPS.replace(fused_rnn=True, fused_residual_dtype="bfloat16") \
        if fused else HPS
    assert mh.process_index() == rank and not mh.is_primary() == bool(rank)
    lhps = mh.local_batch_hps(hps)
    assert lhps.batch_size == hps.batch_size // nproc
    loader = make_striped_loader(lhps, host_id=rank, num_hosts=nproc)

    model = SketchRNN(hps)
    mesh = make_mesh(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh)
    for i, key in enumerate(step_keys(3)):
        local = loader.get_batch(i % max(loader.num_batches, 1))
        state, metrics = step(state, shard_batch(local, mesh), key)
    loss = float(metrics["loss"])
    assert np.isfinite(loss)

    dump_params(state.params, os.path.join(outdir, f"params_{rank}.npz"),
                extra={"loss": loss})

    # per-class eval across hosts (VERDICT r2 #4): ONE masked sweep whose
    # batch schedule is identical on every host — no per-class filtering
    # that could desynchronize the SPMD program count. Deterministic
    # config (non-conditional, labeled corpus) so the test can require
    # exact agreement across processes and vs a single-process sweep.
    from sketch_rnn_tpu.train import make_per_class_eval_step
    from sketch_rnn_tpu.train.loop import evaluate_per_class
    from tests._multihost_common import (
        PC_CLASSES, dump_per_class, make_striped_class_loader)

    pc_hps = hps.replace(num_classes=PC_CLASSES, conditional=False)
    pc_loader = make_striped_class_loader(mh.local_batch_hps(pc_hps),
                                          host_id=rank, num_hosts=nproc)
    pc_model = SketchRNN(pc_hps)
    pc_params = pc_model.init_params(jax.random.key(7))
    pc_step = make_per_class_eval_step(pc_model, pc_hps, mesh)
    per = evaluate_per_class(pc_params, pc_loader, pc_step, PC_CLASSES,
                             mesh)
    dump_per_class(per, os.path.join(outdir, f"pc_{rank}.npz"))

    # --- mesh-sharded sampler across processes (VERDICT r3 #7) ---------
    # the generation path must run under the SAME global mesh as
    # training: fixed z/key so the only allowed variation is transport.
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    from sketch_rnn_tpu.sample.sampler import make_sampler

    n = hps.batch_size  # divisible by the 4-device mesh
    z = jax.random.normal(jax.random.key(11), (n, hps.z_size),
                          jnp.float32)
    # fixed INIT params (identical on every transport bitwise) so the
    # test can demand bitwise sampler equality — trained params differ
    # across transports by reassociation noise, which the categorical
    # pen draws would amplify chaotically
    sample_params = model.init_params(jax.random.key(21))
    sampler = make_sampler(model, hps, mesh=mesh)
    s5, lengths = sampler(sample_params, jax.random.key(12), n, z, None,
                          0.7)
    # gather the sharded outputs so every process can dump the GLOBAL
    # result (the test then requires bitwise cross-process equality)
    s5_all = multihost_utils.process_allgather(s5, tiled=True)
    len_all = multihost_utils.process_allgather(lengths, tiled=True)
    np.savez(os.path.join(outdir, f"sample_{rank}.npz"),
             s5=np.asarray(s5_all), lengths=np.asarray(len_all))

    # --- checkpoint save -> resume across processes (VERDICT r3 #7) ----
    # the documented shared-workdir contract (train/loop.py): ONLY the
    # primary writes; every process restores from the same directory.
    from sketch_rnn_tpu.train.checkpoint import (restore_checkpoint,
                                                 save_checkpoint)

    ckpt_dir = os.path.join(outdir, "ckpt")
    if mh.is_primary():
        save_checkpoint(ckpt_dir, state, scale_factor=1.25, hps=hps)
    multihost_utils.sync_global_devices("ckpt written")
    template = make_train_state(model, hps, jax.random.key(0))
    restored, scale2, meta = restore_checkpoint(ckpt_dir, template)
    assert scale2 == 1.25 and meta["step"] == int(state.step)

    # round-trip fidelity: the restored params are bitwise the params
    # the primary saved (on rank 1 this also proves the cross-process
    # read of the primary's file)
    def _host_leaf(leaf):
        if hasattr(leaf, "addressable_data"):
            leaf = leaf.addressable_data(0)
        return np.asarray(leaf)

    jax.tree_util.tree_map(
        lambda got, want: np.testing.assert_array_equal(
            _host_leaf(got), _host_leaf(want)),
        restored.params, state.params)

    # continue training from the restored state: 2 more steps with the
    # continuing key stream (fold_in(root, 3), fold_in(root, 4))
    state2 = restored
    for i, key in list(enumerate(step_keys(5)))[3:]:
        local = loader.get_batch(i % max(loader.num_batches, 1))
        state2, m2 = step(state2, shard_batch(local, mesh), key)
    assert np.isfinite(float(m2["loss"]))
    dump_params(state2.params,
                os.path.join(outdir, f"params_resumed_{rank}.npz"))

    print(f"[worker {rank}] done, loss={loss:.5f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(shard_main() if sys.argv[1:2] == ["shard"] else main())
