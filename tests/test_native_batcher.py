"""Native C++ batcher vs numpy golden equality (SURVEY.md §4)."""

import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.data import native_batcher as NB
from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes


@pytest.fixture(scope="module")
def native_available():
    if not NB.available():
        pytest.skip("native batcher unavailable (no g++?)")


def test_native_matches_numpy(native_available):
    hps = HParams(batch_size=8, max_seq_len=64)
    seqs, _ = make_synthetic_strokes(8, min_len=5, max_len=60, seed=3)
    seqs = [np.asarray(s, np.float32) for s in seqs]
    out = NB.assemble_batch(seqs, hps.max_seq_len)
    assert out is not None
    strokes, seq_len = out

    loader = DataLoader([s.copy() for s in seqs], hps)
    ref = loader._pad_batch(seqs)
    np.testing.assert_array_equal(strokes, ref)
    np.testing.assert_array_equal(seq_len,
                                  np.array([len(s) for s in seqs], np.int32))


def test_native_rejects_overlong():
    seqs = [np.zeros((10, 3), np.float32)]
    assert NB.assemble_batch(seqs, 5) is None


def test_loader_uses_native_transparently(native_available, monkeypatch):
    """Batches must be identical whether or not the native path is active."""
    hps = HParams(batch_size=4, max_seq_len=48)
    seqs, labels = make_synthetic_strokes(8, min_len=5, max_len=40, seed=1)
    l1 = DataLoader([np.array(s) for s in seqs], hps, labels=labels, seed=7)
    b1 = l1.get_batch(0)
    monkeypatch.setenv("SKETCH_RNN_TPU_NO_NATIVE", "1")
    monkeypatch.setattr(NB, "_lib", None)
    monkeypatch.setattr(NB, "_tried", False)
    l2 = DataLoader([np.array(s) for s in seqs], hps, labels=labels, seed=7)
    b2 = l2.get_batch(0)
    for k in b1:
        np.testing.assert_array_equal(b1[k], b2[k])


# -- augmented (train-path) native assembly ---------------------------------


def _aug_setup(n=32, seed=5):
    seqs, _ = make_synthetic_strokes(n, min_len=20, max_len=60, seed=seed)
    return [np.asarray(s, np.float32) for s in seqs]


def test_aug_no_op_matches_plain(native_available):
    # scale_factor=0, drop_prob=0 must be bit-exact the non-augmented path
    seqs = _aug_setup()
    a = NB.assemble_batch_aug(seqs, 64, 0.0, 0.0, seed=1)
    b = NB.assemble_batch(seqs, 64)
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])


def test_aug_deterministic_and_seed_dependent(native_available):
    seqs = _aug_setup()
    a = NB.assemble_batch_aug(seqs, 64, 0.15, 0.1, seed=42)
    b = NB.assemble_batch_aug(seqs, 64, 0.15, 0.1, seed=42)
    c = NB.assemble_batch_aug(seqs, 64, 0.15, 0.1, seed=43)
    np.testing.assert_array_equal(a[0], b[0])
    assert not np.array_equal(a[0], c[0])


def test_aug_thread_count_invariant(native_available):
    # per-sequence counter-based RNG: results must not depend on threading
    seqs = _aug_setup(n=96)
    a = NB.assemble_batch_aug(seqs, 64, 0.15, 0.1, seed=9, n_threads=1)
    b = NB.assemble_batch_aug(seqs, 64, 0.15, 0.1, seed=9, n_threads=4)
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])


def test_aug_dropout_preserves_drawing(native_available):
    # point dropout merges offsets: per-sequence total displacement and
    # pen-lift count are invariant; lengths shrink by roughly drop_prob
    # of eligible points
    seqs = _aug_setup(n=48)
    out, lens = NB.assemble_batch_aug(seqs, 64, 0.0, 0.3, seed=11)
    orig_lens = np.array([len(s) for s in seqs])
    assert (lens <= orig_lens).all() and (lens < orig_lens).any()
    for i, s in enumerate(seqs):
        got = out[i, 1:1 + lens[i]]
        np.testing.assert_allclose(got[:, :2].sum(0), s[:, :2].sum(0),
                                   rtol=1e-5, atol=1e-5)
        assert int(got[:, 3].sum()) == int(s[:, 2].sum())


def test_aug_scale_is_per_axis_uniform(native_available):
    # with dropout off, each sequence's offsets are an exact per-axis
    # rescale of the originals; scales must lie in [1-f, 1+f] and vary
    seqs = _aug_setup(n=64)
    f = 0.15
    out, lens = NB.assemble_batch_aug(seqs, 64, f, 0.0, seed=3)
    scales = []
    for i, s in enumerate(seqs):
        got = out[i, 1:1 + lens[i], :2]
        nz = np.abs(s[:, 0]) > 1e-6
        sx = np.median(got[nz, 0] / s[nz, 0])
        nz = np.abs(s[:, 1]) > 1e-6
        sy = np.median(got[nz, 1] / s[nz, 1])
        np.testing.assert_allclose(got[:, 0], s[:, 0] * sx, rtol=1e-4,
                                   atol=1e-6)
        np.testing.assert_allclose(got[:, 1], s[:, 1] * sy, rtol=1e-4,
                                   atol=1e-6)
        assert 1 - f - 1e-5 <= sx <= 1 + f + 1e-5
        assert 1 - f - 1e-5 <= sy <= 1 + f + 1e-5
        scales.append((sx, sy))
    scales = np.array(scales)
    assert scales.std(0).min() > 0.01  # actually random per sequence


def test_aug_length_reduction_tracks_prob(native_available):
    # eligible points (pen-down runs past the 3rd point) drop at ~prob
    rng = np.random.default_rng(0)
    n, L = 64, 60
    seqs = []
    for _ in range(n):
        s = np.zeros((L, 3), np.float32)
        s[:, :2] = rng.normal(size=(L, 2)).astype(np.float32)
        s[-1, 2] = 1.0  # single stroke: all interior points eligible
        seqs.append(s)
    prob = 0.25
    _, lens = NB.assemble_batch_aug(seqs, L, 0.0, prob, seed=17)
    dropped = (L - lens).sum()
    eligible = (L - 3) * n  # count>2 requires 3 pen-down predecessors
    rate = dropped / eligible
    assert abs(rate - prob) < 0.05


def test_loader_train_batch_uses_native_aug(native_available):
    # augment=True loader must produce valid augmented batches through the
    # native path: stroke-5 one-hot rows, plausible lengths, finite values
    hps = HParams(batch_size=16, max_seq_len=64, augment_stroke_prob=0.2,
                  random_scale_factor=0.15)
    seqs, labels = make_synthetic_strokes(32, min_len=20, max_len=60, seed=2)
    loader = DataLoader([np.array(s) for s in seqs], hps, labels=labels,
                        augment=True, seed=3)
    b = loader.random_batch()
    assert b["strokes"].shape == (16, 65, 5)
    assert np.isfinite(b["strokes"]).all()
    onehot = b["strokes"][:, :, 2:].sum(-1)
    np.testing.assert_array_equal(onehot, np.ones_like(onehot))
    assert (b["seq_len"] >= 1).all() and (b["seq_len"] <= 64).all()
    # augmentation varies across draws
    b2 = loader.random_batch()
    assert not np.array_equal(b["strokes"], b2["strokes"])


def test_i16_assembler_matches_numpy_quantization(native_available):
    """The native int16 assembler must be BIT-identical to quantizing
    the float32 native output with np.rint (both round half-even):
    non-aug exactly, and aug with the same seed (same jitter stream)."""
    seqs, _ = make_synthetic_strokes(24, min_len=10, max_len=40, seed=5)
    seqs = [np.array(s) for s in seqs]
    quant = 12.25
    for sf, dp, seed in ((0.0, 0.0, 0), (0.15, 0.2, 99)):
        f32, lens_f = NB.assemble_batch_aug(seqs, 48, sf, dp, seed=seed)
        i16, lens_q = NB.assemble_batch_aug_i16(seqs, 48, sf, dp,
                                                seed=seed, quant=quant)
        np.testing.assert_array_equal(lens_f, lens_q)
        assert i16.dtype == np.int16
        want = np.empty(f32.shape, np.int16)
        np.clip(np.rint(f32[..., :2] * quant), -32767, 32767,
                out=want[..., :2], casting="unsafe")
        want[..., 2:] = f32[..., 2:]
        np.testing.assert_array_equal(i16, want)


def test_loader_int16_fallback_matches_native(native_available,
                                              monkeypatch):
    """The loader's numpy int16 fallback must be bit-equal to the
    native int16 path (non-aug: both reduce to half-even-rounding the
    bit-exact f32 assembly)."""
    from sketch_rnn_tpu.data import loader as L

    hps = HParams(batch_size=8, max_seq_len=40)
    seqs, _ = make_synthetic_strokes(16, min_len=10, max_len=38, seed=7)
    a = DataLoader([np.array(s) for s in seqs], hps, seed=1)
    a.normalize(4.5)
    b = DataLoader([np.array(s) for s in seqs], hps, seed=1)
    b.normalize(4.5)
    got = a.random_batch(int16_scale=a.scale_factor)      # native path
    monkeypatch.setattr(L.NB, "assemble_batch_aug_i16",
                        lambda *a, **k: None)
    want = b.random_batch(int16_scale=b.scale_factor)     # numpy fallback
    assert got["strokes"].dtype == want["strokes"].dtype == np.int16
    for k in got:
        np.testing.assert_array_equal(got[k], want[k])
