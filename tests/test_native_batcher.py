"""Native C++ batcher vs numpy golden equality (SURVEY.md §4)."""

import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.data import native_batcher as NB
from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes


@pytest.fixture(scope="module")
def native_available():
    if not NB.available():
        pytest.skip("native batcher unavailable (no g++?)")


def test_native_matches_numpy(native_available):
    hps = HParams(batch_size=8, max_seq_len=64)
    seqs, _ = make_synthetic_strokes(8, min_len=5, max_len=60, seed=3)
    seqs = [np.asarray(s, np.float32) for s in seqs]
    out = NB.assemble_batch(seqs, hps.max_seq_len)
    assert out is not None
    strokes, seq_len = out

    loader = DataLoader([s.copy() for s in seqs], hps)
    ref = loader._pad_batch(seqs)
    np.testing.assert_array_equal(strokes, ref)
    np.testing.assert_array_equal(seq_len,
                                  np.array([len(s) for s in seqs], np.int32))


def test_native_rejects_overlong():
    seqs = [np.zeros((10, 3), np.float32)]
    assert NB.assemble_batch(seqs, 5) is None


def test_loader_uses_native_transparently(native_available, monkeypatch):
    """Batches must be identical whether or not the native path is active."""
    hps = HParams(batch_size=4, max_seq_len=48)
    seqs, labels = make_synthetic_strokes(8, min_len=5, max_len=40, seed=1)
    l1 = DataLoader([np.array(s) for s in seqs], hps, labels=labels, seed=7)
    b1 = l1.get_batch(0)
    monkeypatch.setenv("SKETCH_RNN_TPU_NO_NATIVE", "1")
    monkeypatch.setattr(NB, "_lib", None)
    monkeypatch.setattr(NB, "_tried", False)
    l2 = DataLoader([np.array(s) for s in seqs], hps, labels=labels, seed=7)
    b2 = l2.get_batch(0)
    for k in b1:
        np.testing.assert_array_equal(b1[k], b2[k])
