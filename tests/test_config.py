import pytest

from sketch_rnn_tpu.config import HParams, get_default_hparams


def test_defaults_match_baseline_fixed_values():
    hps = get_default_hparams()
    # fixed by BASELINE.json
    assert hps.enc_rnn_size == 256
    assert hps.dec_rnn_size == 512
    assert hps.z_size == 128
    assert hps.num_mixture == 20
    # canonical (SURVEY §5)
    assert hps.batch_size == 100
    assert hps.max_seq_len == 250
    assert hps.grad_clip == 1.0


def test_parse_overrides():
    hps = get_default_hparams().parse(
        "dec_rnn_size=64, kl_weight=0.25,conditional=false,"
        "data_set=a.npz;b.npz,dec_model=hyper")
    assert hps.dec_rnn_size == 64
    assert hps.kl_weight == 0.25
    assert hps.conditional is False
    assert hps.data_set == ("a.npz", "b.npz")
    assert hps.dec_model == "hyper"


def test_parse_rejects_unknown_and_bad_cells():
    with pytest.raises(ValueError):
        get_default_hparams().parse("nonexistent=3")
    with pytest.raises(ValueError):
        get_default_hparams().replace(dec_model="gru")


def test_json_roundtrip():
    hps = get_default_hparams().replace(num_classes=75, dec_model="layer_norm")
    again = HParams.from_json(hps.to_json())
    assert again == hps


def test_hashable_for_jit_static_args():
    assert hash(get_default_hparams()) == hash(get_default_hparams())


def test_serve_hparams():
    hps = get_default_hparams()
    assert hps.serve_slots >= 1 and hps.serve_chunk >= 1
    hps = hps.parse("serve_slots=128,serve_chunk=16")
    assert hps.serve_slots == 128 and hps.serve_chunk == 16
    with pytest.raises(ValueError, match="serve_slots and serve_chunk"):
        get_default_hparams().replace(serve_slots=0)
    with pytest.raises(ValueError, match="serve_slots and serve_chunk"):
        get_default_hparams().replace(serve_chunk=-1)
