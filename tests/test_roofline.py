"""Pins the analytic roofline geometry (utils/roofline.py) on CPU.

The reconciliation table in ARCHITECTURE.md is only as good as this
arithmetic: the grid counts and tile sizes must track the kernels'
actual tile functions (imported, not copied), the matmul sets must
match the kernels' per-step dataflow, and the padded-pass model must
penalize the K=5 input projection the way the 128x128 systolic array
does.
"""

import jax.numpy as jnp
import pytest

from sketch_rnn_tpu.config import get_default_hparams
from sketch_rnn_tpu.utils import roofline as R


@pytest.fixture
def hps():
    return get_default_hparams().replace(
        batch_size=4096, max_seq_len=250, compute_dtype="bfloat16",
        fused_rnn=True, fused_residual_dtype="bfloat16")


def test_matmul_padding_model():
    mm = R.Matmul(1024, 5, 1024)
    assert mm.flops == 2 * 1024 * 5 * 1024
    # K=5 burns a full 128-wide pass on the systolic array
    assert mm.padded_flops == 2 * 1024 * 128 * 1024
    # M packs to 8 sublanes: the dwx matmul's M=5 rounds to 8
    assert R.Matmul(5, 1024, 2048).padded_flops == 2 * 8 * 1024 * 2048
    # aligned shapes pay nothing
    assert R.Matmul(256, 512, 2048).padded_flops == \
        R.Matmul(256, 512, 2048).flops


def test_encoder_geometry_tracks_kernel_tiles(hps):
    from sketch_rnn_tpu.ops.pallas_fused import _batch_tile_seq

    g = R.encoder_geometry(hps)
    assert g.tile_fwd == g.tile_bwd == _batch_tile_seq(4096, 256)
    # 2 directions x 250 steps x (4096 / tile) batch tiles
    assert g.grid_fwd == 2 * 250 * (4096 // g.tile_fwd)
    # per fwd step: input projection + recurrent matmul
    assert [(m.k, m.n) for m in g.mm_fwd] == [(5, 1024), (256, 1024)]
    # bwd: recompute both + dwx + dh + dwh
    assert len(g.mm_bwd) == 5
    # residual streams: hs+cs out (fwd) and cs+h_prev+dhs in (bwd), bf16
    t, b, h = 250, 4096, 256
    assert g.hbm_bytes_fwd == 2 * t * b * (5 * 2 + 2 * h * 2)
    assert g.hbm_bytes_bwd == 2 * t * b * (5 * 2 + 3 * h * 2)


def test_decoder_geometry_bwd_tile_halves(hps):
    from sketch_rnn_tpu.ops.pallas_fused import _batch_tile

    g = R.decoder_geometry(hps)
    assert g.tile_fwd == _batch_tile(4096, 512)
    assert g.tile_bwd == _batch_tile(4096, 512, xb_bwd=True)
    assert g.tile_bwd * 2 == g.tile_fwd  # the xb budget-halving
    assert g.grid_bwd == 2 * g.grid_fwd
    # bwd adds dx to the seq-kernel set: 6 matmuls
    assert len(g.mm_bwd) == 6
    # the dxs stream the decoder writes back is f32
    t, b = 250, 4096
    assert g.hbm_bytes_bwd - (t * b * (5 * 2 + 3 * 512 * 2)) == \
        t * b * 5 * 4 + 2 * b * 4 * 512 * 4


def test_mxu_and_hbm_seconds_scale(hps):
    g = R.encoder_geometry(hps)
    f1, b1 = g.mxu_seconds(197e12)
    f2, b2 = g.mxu_seconds(2 * 197e12)
    assert f1 == pytest.approx(2 * f2) and b1 == pytest.approx(2 * b2)
    hf, hb = g.hbm_seconds(800.0)
    assert hf == pytest.approx(g.hbm_bytes_fwd / 8e11)
    assert hb > hf  # bwd reads three streams vs fwd's two writes


def test_geometry_follows_hparams_not_constants():
    """A non-flagship shape must flow through (the model is not a table
    of flagship numbers)."""
    hps = get_default_hparams().replace(
        batch_size=512, max_seq_len=100, enc_rnn_size=128,
        dec_rnn_size=256, fused_rnn=True,
        fused_residual_dtype="float32", compute_dtype="float32")
    g = R.encoder_geometry(hps)
    assert g.hidden == 128 and g.seq_len == 100 and g.batch == 512
    # f32 everywhere: xs 4B, residuals 4B
    assert g.hbm_bytes_fwd == 2 * 100 * 512 * (5 * 4 + 2 * 128 * 4)
