"""Host-side unit tests for the multi-tenant layer (ISSUE 19).

TenantStore page encoding (sparse int8 deltas, the zero-delta
bitwise-base guarantee, registration validation, the memory table),
the PrefixReuseIndex exact ledger (hit/compute/abandon/coalescing,
shape-folded keys), the per-tenant SLO / mix parse grammars, and the
AdmissionController tenant fair-share cap. Everything here is pure
numpy + threads — the fleet-level end-to-end proofs (zero tenant-swap
compiles, bitwise single-tenant parity, the reuse recheck) live in
tests/test_serve_bench.py's ``--tenants`` run.
"""

import threading

import numpy as np
import pytest

from sketch_rnn_tpu.serve.admission import (
    DEFAULT_CLASS,
    AdmissionController,
    parse_admission_classes,
    parse_tenant_slos,
)
from sketch_rnn_tpu.serve.loadgen import parse_tenant_mix, tenant_mix_ids
from sketch_rnn_tpu.serve.quantize import QTensor
from sketch_rnn_tpu.serve.tenants import (
    PrefixReuseIndex,
    TenantStore,
    tree_nbytes,
)


def _base_tree(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "enc": {"w": rng.standard_normal((8, 16)).astype(np.float32),
                "b": np.zeros((16,), np.float32)},
        "out_w": rng.standard_normal((16, 6)).astype(np.float32),
        "out_b": rng.standard_normal((6,)).astype(np.float32),
        "steps": np.int64(1000),
    }


# -- TenantStore --------------------------------------------------------


def test_store_zero_delta_tenant_is_bitwise_the_base_objects():
    base = _base_tree()
    store = TenantStore(base, base_ckpt_id="ck7")
    rep = store.register("acme", {k: (dict(v) if isinstance(v, dict)
                                      else v) for k, v in base.items()})
    assert rep["pages"] == 0 and rep["nbytes"] == 0
    tree = store.materialize("acme")
    # the base array OBJECTS, not copies: no -0.0 + 0.0 sign-bit edge
    assert tree["enc"]["w"] is base["enc"]["w"]
    assert tree["out_w"] is base["out_w"]
    assert tree["steps"] is base["steps"]
    # the base tenant "" materializes the base tree itself
    assert store.materialize("") is base


def test_store_sparse_page_round_trip_within_scale_half():
    base = _base_tree()
    store = TenantStore(base)
    rng = np.random.default_rng(3)
    tuned = {**base, "out_w": (base["out_w"]
                               + 0.01 * rng.standard_normal(
                                   base["out_w"].shape)
                               ).astype(np.float32)}
    rep = store.register("acme", tuned)
    # only the touched leaf gets a page
    assert rep["pages"] == 1
    (row,) = rep["report"]
    assert row["path"] == "out_w"
    assert row["max_err"] <= row["bound"] + 1e-12
    assert row["bound"] == row["scale"] / 2.0
    tree = store.materialize("acme")
    err = np.max(np.abs(tree["out_w"] - tuned["out_w"]))
    assert err <= row["bound"] + 1e-12
    # untouched leaves are still the base objects
    assert tree["enc"]["w"] is base["enc"]["w"]
    assert tree["out_b"] is base["out_b"]


def test_store_non_float_leaf_pages_raw_and_exact():
    base = _base_tree()
    store = TenantStore(base)
    tuned = {**base, "steps": np.int64(2000)}
    rep = store.register("acme", tuned)
    assert rep["pages"] == 1
    assert store.materialize("acme")["steps"] == 2000


def test_store_register_validation():
    base = _base_tree()
    store = TenantStore(base)
    with pytest.raises(ValueError, match="non-empty"):
        store.register("", base)
    store.register("acme", base)
    with pytest.raises(ValueError, match="already registered"):
        store.register("acme", base)
    missing = {k: v for k, v in base.items() if k != "out_b"}
    with pytest.raises(ValueError, match="not congruent"):
        store.register("t2", missing)
    bad_shape = {**base, "out_w": np.zeros((4, 6), np.float32)}
    with pytest.raises(ValueError, match="shape-invariant"):
        store.register("t3", bad_shape)
    with pytest.raises(ValueError, match="non-empty base"):
        TenantStore({})


def test_store_ckpt_ids_and_contains():
    store = TenantStore(_base_tree(), base_ckpt_id="seed42")
    store.register("acme", _base_tree())
    store.register("globex", _base_tree(), ckpt_id="globex_v3")
    assert store.ckpt_id_of("") == "seed42"
    assert store.ckpt_id_of("acme") == "seed42+acme"
    assert store.ckpt_id_of("globex") == "globex_v3"
    assert "" in store and "acme" in store and "initech" not in store
    assert store.tenants == ["acme", "globex"]


def test_store_memory_table_sparse_pages_beat_full_trees():
    base = _base_tree()
    store = TenantStore(base)
    rng = np.random.default_rng(9)
    for i in range(4):
        tuned = {**base, "out_b": (base["out_b"]
                                   + 0.01 * rng.standard_normal((6,))
                                   ).astype(np.float32)}
        store.register(f"tn{i}", tuned)
    mem = store.memory_table()
    assert mem["tenants"] == 4
    assert mem["base_bytes"] == tree_nbytes(base)
    assert mem["full_bytes"] == 4 * mem["base_bytes"]
    assert mem["resident_bytes"] == (mem["base_bytes"]
                                     + sum(mem["adapter_bytes"].values()))
    assert mem["ratio"] < 0.5


# -- PrefixReuseIndex ---------------------------------------------------


def test_index_key_folds_shape_tenant_edge_and_label():
    a = np.arange(6, dtype=np.float32)
    k = PrefixReuseIndex.key("t", a.reshape(2, 3), 12)
    assert k != PrefixReuseIndex.key("t", a.reshape(3, 2), 12)
    assert k != PrefixReuseIndex.key("u", a.reshape(2, 3), 12)
    assert k != PrefixReuseIndex.key("t", a.reshape(2, 3), 24)
    assert k != PrefixReuseIndex.key("t", a.reshape(2, 3), 12, label=1)
    assert k == PrefixReuseIndex.key("t", a.reshape(2, 3).copy(), 12)


def test_index_ledger_compute_fill_hit_abandon():
    idx = PrefixReuseIndex()
    k = PrefixReuseIndex.key("t", np.ones((3, 5), np.float32), 12)
    status, rows = idx.acquire(k)
    assert status == "compute" and rows is None
    payload = (np.zeros(4), np.ones(4), np.zeros(5))
    idx.fill(k, payload)
    status, rows = idx.acquire(k)
    assert status == "hit" and rows is payload
    idx.note_reuses(2)
    assert idx.stats() == {"computes": 1, "reuses": 3, "distinct": 1}
    # a failed compute releases its claim uncounted
    k2 = PrefixReuseIndex.key("t", np.zeros((2, 5), np.float32), 24)
    assert idx.acquire(k2)[0] == "compute"
    idx.abandon(k2)
    assert idx.stats()["computes"] == 1
    # the key is free again: the next worker claims it
    assert idx.acquire(k2)[0] == "compute"
    assert idx.distinct == 1


def test_index_coalesces_racing_miss_into_one_compute():
    idx = PrefixReuseIndex()
    k = PrefixReuseIndex.key("t", np.ones((2, 5), np.float32), 12)
    assert idx.acquire(k)[0] == "compute"  # main thread holds the claim
    got = []

    def waiter():
        got.append(idx.acquire(k))

    th = threading.Thread(target=waiter)
    th.start()
    th.join(timeout=0.2)
    assert th.is_alive() and not got  # blocked on the in-flight claim
    idx.fill(k, ("rows",))
    th.join(timeout=5)
    assert not th.is_alive()
    assert got == [("hit", ("rows",))]
    assert idx.stats() == {"computes": 1, "reuses": 1, "distinct": 1}


# -- parse grammars -----------------------------------------------------


def test_parse_tenant_slos_grammar():
    out = parse_tenant_slos(["acme:interactive:p95<=250ms",
                             "acme:p99<=5",
                             "globex:batch:p50<=2"])
    assert set(out) == {"acme", "globex"}
    by_key = {s.endpoint: s for s in out["acme"]}
    assert by_key["interactive"].objective_s == pytest.approx(0.25)
    # a two-segment spec judges the tenant's default class
    assert by_key[DEFAULT_CLASS].objective_s == pytest.approx(5.0)
    for bad in ("p95<=250ms",          # no tenant segment
                "acme:interactive",    # no <= objective
                ":p95<=1"):            # empty tenant name
        with pytest.raises(ValueError, match="bad tenant SLO"):
            parse_tenant_slos([bad])
    with pytest.raises(ValueError, match="duplicate tenant SLO"):
        parse_tenant_slos(["acme:p95<=1", "acme:default:p95<=2"])


def test_parse_tenant_mix_and_ids():
    mix = parse_tenant_mix("acme:4,globex:2,initech")
    assert mix == (("acme", 4.0), ("globex", 2.0), ("initech", 1.0))
    # the endpoint-mix grammar quirk: ":1" is the base tenant ""
    assert parse_tenant_mix(":1") == (("", 1.0),)
    with pytest.raises(ValueError, match="bad tenant_mix weight"):
        parse_tenant_mix("acme:heavy")
    with pytest.raises(ValueError, match="empty tenant mix"):
        parse_tenant_mix(" , ")
    ids = tenant_mix_ids(64, mix, seed=7)
    assert ids.shape == (64,) and set(np.unique(ids)) <= {0, 1, 2}
    assert np.array_equal(ids, tenant_mix_ids(64, mix, seed=7))
    assert not np.array_equal(ids, tenant_mix_ids(64, mix, seed=8))
    assert tenant_mix_ids(64, (), seed=7) is None


# -- AdmissionController tenant fair share ------------------------------


def _controller(**kw):
    return AdmissionController(parse_admission_classes([]),
                               n_replicas=2, slots=4, **kw)


def test_tenant_cap_sheds_own_excess_not_other_tenants():
    ctrl = _controller(tenant_cap=3)
    for _ in range(3):
        assert not ctrl.place(DEFAULT_CLASS, tenant="acme").shed
    p = ctrl.place(DEFAULT_CLASS, tenant="acme")
    assert p.shed and p.shed_reason == "tenant_cap"
    assert ctrl.shed_by_tenant == {"acme": 1}
    # the cap is per tenant: another tenant (and the base "") admit fine
    assert not ctrl.place(DEFAULT_CLASS, tenant="globex").shed
    assert not ctrl.place(DEFAULT_CLASS, tenant="").shed
    # cost counts rows, not requests: a 3-row grid blows the cap alone
    p = ctrl.place(DEFAULT_CLASS, cost=3, tenant="globex")
    assert p.shed and p.shed_reason == "tenant_cap"


def test_tenant_cap_fires_before_queue_checks():
    # the fleet has room (empty queues, queue_cap far away) but the
    # tenant is over its share: the shed reason must say so
    ctrl = _controller(tenant_cap=1, queue_cap=100)
    assert not ctrl.place(DEFAULT_CLASS, tenant="acme").shed
    p = ctrl.place(DEFAULT_CLASS, tenant="acme")
    assert p.shed and p.shed_reason == "tenant_cap"


def test_tenant_outstanding_released_by_done_and_drop_not_requeue():
    ctrl = _controller(tenant_cap=2)
    a = ctrl.place(DEFAULT_CLASS, tenant="acme")
    ctrl.place(DEFAULT_CLASS, tenant="acme")
    assert ctrl.summary()["tenant_outstanding"] == {"acme": 2}
    # a failover requeue was already charged once: no double count
    ctrl.place(DEFAULT_CLASS, requeue=True, tenant="acme")
    assert ctrl.summary()["tenant_outstanding"] == {"acme": 2}
    # completion frees the fair share
    ctrl.note_done(a.replica, 0.01, tenant="acme")
    assert ctrl.summary()["tenant_outstanding"] == {"acme": 1}
    assert not ctrl.place(DEFAULT_CLASS, tenant="acme").shed
    # terminal failure releases without a completion (no leak)
    ctrl.drop_tenant("acme", cost=2)
    assert ctrl.summary()["tenant_outstanding"] == {}


def test_tenant_cap_bypassed_by_force():
    ctrl = _controller(tenant_cap=1)
    ctrl.place(DEFAULT_CLASS, tenant="acme")
    assert not ctrl.place(DEFAULT_CLASS, tenant="acme",
                          force=True).shed
    assert ctrl.shed_by_tenant == {}
