"""Health & SLO layer tests (ISSUE 7): /metrics, /healthz, SLO tracker.

Load-bearing contracts:

1. **No new bookkeeping**: every /metrics series is a pure render of
   one ``Telemetry.snapshot()`` — counters scrape as exact totals,
   histograms as cumulative log buckets whose recovered quantiles
   agree with the in-process summary within one geometric bucket.
2. **Scrape == summary**: a scrape taken while (and after) a serve run
   reconciles with ``ServeEngine.run()``'s end-of-run metrics — counts
   equal exactly, percentiles within one log bucket (THE acceptance).
3. **SLO math is deterministic**: compliance/burn-rate from a known
   request stream is exact, and /healthz flips to degraded on a
   violated objective.
"""

import json
import math
import re
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.serve import metrics_http
from sketch_rnn_tpu.serve.metrics_http import (
    MetricsServer,
    health_payload,
    render_prometheus,
)
from sketch_rnn_tpu.serve.slo import SLO, SLOTracker, parse_slo
from sketch_rnn_tpu.utils import telemetry as tele
from sketch_rnn_tpu.utils.telemetry import Histogram, Telemetry


def _get(url: str) -> tuple:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode()


def _series(text: str) -> dict:
    """Parse exposition text into {sample_line_name{labels}: float}."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        out[name] = float(value)
    return out


def _hist_quantile(text: str, base: str, q: float) -> float:
    """Recover a quantile from the scraped cumulative buckets — what a
    Prometheus ``histogram_quantile`` would see."""
    pat = re.compile(re.escape(base) + r'_bucket\{le="([^"]+)"\} (\S+)')
    buckets = [(float(le) if le != "+Inf" else math.inf, float(v))
               for le, v in pat.findall(text)]
    count = buckets[-1][1]
    rank = q * (count - 1)
    prev_edge = 0.0
    for le, cum in buckets:
        if rank < cum:
            if le == 0.0 or math.isinf(le):
                return prev_edge
            # geometric midpoint of (le/G, le] — the Histogram's answer
            return le / (Histogram.GROWTH ** 0.5)
        prev_edge = le
    return prev_edge


# -- SLO tracker -------------------------------------------------------------


def test_parse_slo_specs():
    s = parse_slo("p95<=0.25")
    assert (s.endpoint, s.metric, s.target, s.objective_s) == \
        ("generate", "latency_s", 0.95, 0.25)
    s = parse_slo("gen2:p99<=400ms")
    assert (s.endpoint, s.target, s.objective_s) == ("gen2", 0.99, 0.4)
    s = parse_slo("generate:decode_s:p50<=0.1")
    assert (s.metric, s.target) == ("decode_s", 0.5)
    for bad in ("p95", "q95<=0.1", "p95<=fast", "a:b:c:p95<=1"):
        with pytest.raises(ValueError):
            parse_slo(bad)


def test_slo_tracker_compliance_and_burn_rate():
    # p80 <= 0.1s, budget 0.2; feed 10 requests, 3 over objective
    tr = SLOTracker([SLO(objective_s=0.1, target=0.8)], window=8)
    lats = [0.05] * 7 + [0.2, 0.3, 0.4]
    for v in lats:
        tr.observe("generate", {"latency_s": v})
    rec = tr.summary()["generate:latency_s:p80"]
    assert rec["total"] == 10 and rec["breaches"] == 3
    assert rec["compliance"] == pytest.approx(0.7)
    assert rec["met"] is False
    # exact totals: 3/10 breach over 0.2 budget = 1.5x burn
    assert rec["burn_rate_total"] == pytest.approx(1.5)
    # rolling window (last 8): 3 breaches / 8 = 0.375 / 0.2
    assert rec["window_n"] == 8
    assert rec["burn_rate"] == pytest.approx(0.375 / 0.2)
    assert not tr.healthy()


def test_slo_tracker_healthy_paths():
    tr = SLOTracker([SLO(objective_s=0.1, target=0.5)], min_requests=8)
    assert tr.healthy()  # no data = healthy
    for _ in range(4):
        tr.observe("generate", {"latency_s": 1.0})
    # violated but under min_requests: still healthy (warmup noise)
    assert not tr.summary()["generate:latency_s:p50"]["met"]
    assert tr.healthy()
    for _ in range(4):
        tr.observe("generate", {"latency_s": 1.0})
    assert not tr.healthy()
    # observations for other endpoints / missing metrics don't count
    tr2 = SLOTracker([SLO(objective_s=0.1, endpoint="other")])
    tr2.observe("generate", {"latency_s": 9.0})
    tr2.observe("other", {"decode_s": 9.0})  # metric absent
    assert tr2.summary()["other:latency_s:p95"]["total"] == 0


def test_slo_zero_budget_burns_infinitely():
    tr = SLOTracker([SLO(objective_s=0.1, target=1.0)])
    tr.observe("generate", {"latency_s": 0.01})
    key = "generate:latency_s:p100"
    assert tr.summary()[key]["burn_rate_total"] == 0.0
    tr.observe("generate", {"latency_s": 0.5})
    assert tr.summary()[key]["burn_rate_total"] == math.inf
    # an infinite burn rate must not break either surface: /metrics
    # renders the exposition +Inf literal, /healthz stays strict JSON
    text = render_prometheus(Telemetry(enabled=False), slo=tr)
    assert 'sketch_rnn_slo_burn_rate_total{slo="' + key + '"} +Inf' \
        in text
    body = json.dumps(health_payload(Telemetry(enabled=False), slo=tr))
    assert "Infinity" not in body
    assert json.loads(body)["slo"][key]["burn_rate_total"] == "inf"
    # the engine summary path (what serve-bench's report embeds) stays
    # strict-JSON too once sanitized the same way
    from sketch_rnn_tpu.utils.telemetry import json_safe
    strict = json.dumps(json_safe({"slo": tr.summary()}),
                        allow_nan=False)
    assert json.loads(strict)["slo"][key]["burn_rate"] == "inf"


def test_parse_slo_rejects_label_breaking_names():
    # endpoint/metric become Prometheus label values and Result field
    # lookups: junk must fail at parse time, not corrupt a scrape or
    # silently track nothing
    for bad in ('foo"bar:p95<=1', "generate::p95<=1", ":p95<=1",
                "generate:la tency:p95<=1"):
        with pytest.raises(ValueError, match="SLO"):
            parse_slo(bad)
    assert parse_slo("my-end.point:p95<=1").endpoint == "my-end.point"
    # a typo'd metric would track nothing and report vacuous
    # compliance forever — rejected against the Result latency fields
    with pytest.raises(ValueError, match="decod_s"):
        parse_slo("generate:decod_s:p95<=1")


# -- histogram exposition (satellite: edge-case hardening) -------------------


def test_histogram_buckets_cumulative_and_edges():
    h = Histogram()
    assert h.buckets() == []          # empty: well-defined, no error
    assert h.quantile(0.5) == 0.0
    assert h.quantile(-3.0) == 0.0 and h.quantile(7.0) == 0.0  # clamped
    h.observe(0.0)
    h.observe(0.5)
    h.observe(0.5)
    bks = h.buckets()
    assert bks[0] == (0.0, 1)          # zero bucket exports edge 0.0
    assert bks[-1][1] == 3             # cumulative reaches count
    edges = [e for e, _ in bks]
    assert edges == sorted(edges)
    # single-sample histogram answers every quantile with the sample
    h1 = Histogram()
    h1.observe(0.125)
    assert h1.quantile(0.0) == h1.quantile(1.0) == 0.125
    assert h1.quantile(2.5) == 0.125   # out-of-range q clamps, no error
    assert h1.buckets()[-1][1] == 1


def test_render_prometheus_counters_gauges_hists_spans():
    tel = Telemetry()
    tel.counter("requests_completed", 3, cat="serve")
    tel.gauge("slots_live", 7, cat="serve")
    with tel.span("dispatch", cat="train"):
        pass
    for v in (0.1, 0.2, 0.4):
        tel.observe("latency_s", v, cat="serve")
    text = render_prometheus(tel)
    s = _series(text)
    # counters exact, typed counter; gauges typed gauge
    assert s["sketch_rnn_serve_requests_completed_total"] == 3
    assert "# TYPE sketch_rnn_serve_requests_completed_total counter" \
        in text
    assert s["sketch_rnn_serve_slots_live"] == 7
    assert "# TYPE sketch_rnn_serve_slots_live gauge" in text
    # span aggregates as seconds + count
    assert s["sketch_rnn_train_dispatch_spans_total"] == 1
    assert s["sketch_rnn_train_dispatch_seconds_total"] >= 0
    # histogram: cumulative buckets end at count; sum exact
    assert s["sketch_rnn_serve_latency_s_count"] == 3
    assert s["sketch_rnn_serve_latency_s_sum"] == pytest.approx(0.7)
    assert s['sketch_rnn_serve_latency_s_bucket{le="+Inf"}'] == 3
    assert "# TYPE sketch_rnn_serve_latency_s histogram" in text
    assert s["sketch_rnn_telemetry_enabled"] == 1
    # recovered quantile within one log bucket of the live summary
    got = _hist_quantile(text, "sketch_rnn_serve_latency_s", 0.5)
    assert got == pytest.approx(tel.histogram("latency_s", "serve")["p50"],
                                rel=1e-9)


def test_render_prometheus_disabled_core_serves_meta_only():
    text = render_prometheus(tele.get_telemetry())  # process default: off
    s = _series(text)
    assert s["sketch_rnn_up"] == 1
    assert s["sketch_rnn_telemetry_enabled"] == 0


def test_render_prometheus_run_info_labels_escaped():
    """ISSUE 8: run_info carries the run identity; run_id comes
    verbatim from SKETCH_RNN_RUN_ID so exposition-format specials must
    be escaped or the whole scrape is invalid."""
    tel = Telemetry(process_index=1, host_count=4, run_id="exp-1")
    text = render_prometheus(tel)
    assert ('sketch_rnn_run_info{run_id="exp-1",host="1",'
            'host_count="4"} 1') in text
    evil = Telemetry(run_id='a"b\\c\nd')
    line = [l for l in render_prometheus(evil).splitlines()
            if l.startswith("sketch_rnn_run_info")][0]
    assert line == ('sketch_rnn_run_info{run_id="a\\"b\\\\c\\nd",'
                    'host="0",host_count="1"} 1')


def test_render_prometheus_slo_series():
    tr = SLOTracker([SLO(objective_s=0.1, target=0.8)])
    for v in (0.05, 0.05, 0.3):
        tr.observe("generate", {"latency_s": v})
    text = render_prometheus(Telemetry(enabled=False), slo=tr)
    s = _series(text)
    lab = '{slo="generate:latency_s:p80"}'
    assert s[f"sketch_rnn_slo_requests_total{lab}"] == 3
    assert s[f"sketch_rnn_slo_breaches_total{lab}"] == 1
    assert s[f"sketch_rnn_slo_objective_seconds{lab}"] == 0.1
    assert s[f"sketch_rnn_slo_compliance{lab}"] == pytest.approx(2 / 3)


# -- the HTTP server ---------------------------------------------------------


def test_server_healthz_metrics_and_404():
    tel = tele.configure(trace_dir=None)
    tel.counter("requests_completed", 5, cat="serve")
    tr = SLOTracker([SLO(objective_s=10.0)])
    with MetricsServer(port=0, slo=tr) as srv:
        base = f"http://127.0.0.1:{srv.port}"
        code, body = _get(f"{base}/healthz")
        assert code == 200
        h = json.loads(body)
        assert h["status"] == "ok" and h["telemetry_enabled"] is True
        assert "generate:latency_s:p95" in h["slo"]
        code, body = _get(f"{base}/metrics")
        assert code == 200
        assert _series(body)[
            "sketch_rnn_serve_requests_completed_total"] == 5
        with pytest.raises(urllib.request.HTTPError) as e:
            _get(f"{base}/nope")
        assert e.value.code == 404
    assert metrics_http.live_servers() == ()
    tele.disable()


def test_healthz_degrades_on_violated_slo():
    tr = SLOTracker([SLO(objective_s=0.01, target=0.99)], min_requests=4)
    for _ in range(6):
        tr.observe("generate", {"latency_s": 1.0})
    h = health_payload(Telemetry(enabled=False), slo=tr)
    assert h["status"] == "degraded"


def test_stop_all_reports_leaked_servers():
    srv = MetricsServer(port=0).start()
    assert metrics_http.live_servers() == (srv,)
    leaked = metrics_http.stop_all()
    assert len(leaked) == 1 and str(srv.port) in leaked[0]
    assert metrics_http.live_servers() == ()
    srv.stop()  # idempotent after stop_all


# -- engine integration: scrape reconciles with run() summary ----------------


@pytest.fixture(scope="module")
def tiny_engine():
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.serve import ServeEngine

    hps = HParams(batch_size=8, max_seq_len=24, enc_rnn_size=12,
                  dec_rnn_size=16, z_size=6, num_mixture=3,
                  serve_slots=4, serve_chunk=2)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    return hps, ServeEngine(model, hps, params)


def _requests(hps, n):
    from sketch_rnn_tpu.serve import Request

    def req(i, cap):
        rng = np.random.default_rng(i)
        return Request(key=jax.random.key(1000 + i),
                       z=rng.standard_normal(hps.z_size).astype(np.float32),
                       temperature=0.8, max_len=cap)

    return [req(i, 4 + (3 * i) % 15) for i in range(n)]


def test_scrape_mid_and_post_serve_reconciles_with_summary(tiny_engine):
    """THE acceptance pin: /metrics scraped during and after a serve
    run reconciles with run()'s end-of-run summary — request counts
    equal exactly, histogram-recovered percentiles within one log
    bucket of the exact np.percentile values."""
    hps, eng = tiny_engine
    reqs = _requests(hps, 12)
    tele.configure(trace_dir=None)
    tr = SLOTracker([SLO(objective_s=120.0, target=0.95)])
    out = {}
    scrapes = []
    with MetricsServer(port=0, slo=tr) as srv:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        done = threading.Event()
        scrapes.append(_get(url))  # at least one pre-run scrape

        def scraper():
            while not done.is_set():
                code, text = _get(url)
                scrapes.append((code, text))
                time.sleep(0.02)

        t = threading.Thread(target=scraper)
        t.start()
        try:
            out.update(eng.run(list(reqs), slo=tr))
        finally:
            done.set()
            t.join()
        # every mid-run scrape answered 200 with parseable exposition
        assert scrapes
        for code, text in scrapes:
            assert code == 200
            assert "sketch_rnn_up 1" in text
        _, final = _get(url)
    m = out["metrics"]
    s = _series(final)
    assert s["sketch_rnn_serve_requests_enqueued_total"] == 12
    assert s["sketch_rnn_serve_requests_completed_total"] == \
        m["completed"] == 12
    assert s["sketch_rnn_serve_latency_s_count"] == 12
    lab = '{slo="generate:latency_s:p95"}'
    assert s[f"sketch_rnn_slo_requests_total{lab}"] == 12
    assert s[f"sketch_rnn_slo_breaches_total{lab}"] == 0
    assert m["slo"]["generate:latency_s:p95"]["met"] is True
    # percentiles: scrape-recovered quantile within one log bucket
    # (growth 2^(1/8) ~ 9%, plus min/max clamping slack) of the exact
    # end-of-run numbers
    for q, key in ((0.5, "latency_p50_s"), (0.95, "latency_p95_s"),
                   (0.99, "latency_p99_s")):
        got = _hist_quantile(final, "sketch_rnn_serve_latency_s", q)
        assert got == pytest.approx(m[key], rel=0.15), key
    tele.disable()


def test_render_prometheus_cost_counters_close_identity():
    """ISSUE 11 acceptance: per-class device-step cost lands on
    /metrics as counters, and the scrape itself closes the identity
    attributed + idle == dispatched (plus per-class series summing to
    the aggregate) — straight from the telemetry core, no new
    bookkeeping in the exposition layer."""
    tel = Telemetry()
    tel.counter("device_steps_dispatched", 40, cat="serve")
    tel.counter("device_steps_idle", 4, cat="serve")
    tel.counter("device_steps_attributed", 36, cat="serve")
    from sketch_rnn_tpu.utils.telemetry import class_series
    tel.counter(class_series("device_steps_attributed", "interactive"),
                20, cat="serve")
    tel.counter(class_series("device_steps_attributed", "batch"),
                16, cat="serve")
    s = _series(render_prometheus(tel))
    attr = s["sketch_rnn_serve_device_steps_attributed_total"]
    idle = s["sketch_rnn_serve_device_steps_idle_total"]
    disp = s["sketch_rnn_serve_device_steps_dispatched_total"]
    assert attr + idle == disp == 40
    per_class = (
        s["sketch_rnn_serve_device_steps_attributed_interactive_total"],
        s["sketch_rnn_serve_device_steps_attributed_batch_total"])
    assert sum(per_class) == attr == 36


def test_render_prometheus_cache_and_fleet_replica_series():
    """ISSUE 12 satellite: the result cache's hit/miss/evict/byte
    counters and the current replica count ride the existing
    counter/gauge exposition for free — tick a ResultCache under an
    enabled core and the sketch_rnn_serve_cache_* series (and the
    fleet_replicas gauge) appear on /metrics."""
    import numpy as np

    from sketch_rnn_tpu.serve import ResultCache

    tel = tele.configure(trace_dir=None)
    try:
        cache = ResultCache(max_entries=1)
        mk = lambda u: type("R", (), {  # noqa: E731
            "strokes5": np.zeros((2, 5), np.float32),
            "length": 2, "steps": 2, "uid": u})()
        cache.put(b"a", mk(0))
        cache.get(b"a")          # hit
        cache.get(b"b")          # miss
        cache.put(b"b", mk(1))   # evicts a
        tel.gauge("fleet_replicas", 3, cat="serve")
        text = render_prometheus(tel)
    finally:
        tele.disable()
    s = _series(text)
    assert s["sketch_rnn_serve_cache_hit_total"] == 1
    assert s["sketch_rnn_serve_cache_miss_total"] == 1
    assert s["sketch_rnn_serve_cache_evict_total"] == 1
    assert s["sketch_rnn_serve_cache_bytes"] == 40
    assert "# TYPE sketch_rnn_serve_cache_bytes gauge" in text
    assert s["sketch_rnn_serve_fleet_replicas"] == 3
    assert "# TYPE sketch_rnn_serve_fleet_replicas gauge" in text


def test_render_prometheus_per_endpoint_series():
    """ISSUE 15 satellite: per-endpoint request/latency series ride the
    class_series naming contract (``..._ep_<endpoint>``) — tick them on
    an enabled core and the exposition renders them as counters +
    histograms with no new bookkeeping."""
    from sketch_rnn_tpu.utils.telemetry import endpoint_series

    assert endpoint_series("latency_s", "complete") == \
        "latency_s_ep_complete"
    assert endpoint_series("latency_s", None) == "latency_s"
    tel = Telemetry()
    for ep, lat in (("generate", 0.1), ("complete", 0.2),
                    ("complete", 0.3), ("interpolate", 0.4)):
        tel.counter(endpoint_series("requests_completed", ep), 1.0,
                    cat="serve")
        tel.observe(endpoint_series("latency_s", ep), lat, cat="serve")
    text = render_prometheus(tel)
    s = _series(text)
    assert s["sketch_rnn_serve_requests_completed_ep_generate_total"] \
        == 1
    assert s["sketch_rnn_serve_requests_completed_ep_complete_total"] \
        == 2
    assert s[
        "sketch_rnn_serve_requests_completed_ep_interpolate_total"] == 1
    assert s["sketch_rnn_serve_latency_s_ep_complete_count"] == 2
    assert "# TYPE sketch_rnn_serve_latency_s_ep_complete histogram" \
        in text


def test_healthz_reports_scaling_during_resize_not_degraded():
    """ISSUE 12 satellite: an in-flight elastic resize is intentional —
    /healthz must report `scaling`, not flap ok/degraded; a genuinely
    degraded fleet still wins over `scaling`."""
    tel = tele.get_telemetry()
    ok = {"healthy": True, "scaling": False}
    mid = {"healthy": True, "scaling": True}
    bad = {"healthy": False, "scaling": True}
    assert health_payload(tel, None, lambda: ok)["status"] == "ok"
    assert health_payload(tel, None, lambda: mid)["status"] == "scaling"
    # degradation outranks an in-flight resize
    assert health_payload(tel, None, lambda: bad)["status"] == "degraded"


def test_healthz_reports_rolling_during_rollout():
    """ISSUE 16 satellite: an in-flight model rollout reports
    `rolling` — which outranks `scaling` (the walk's own retire/rejoin
    churn must not masquerade as an autoscale) but never degradation —
    with the controller's evidence in the fleet block."""
    tel = tele.get_telemetry()
    ev = {"active": True, "from": "ckpt_00000010",
          "to": "ckpt_00000020", "swapped": 1, "total": 2}
    roll = {"healthy": True, "scaling": True, "rolling": True,
            "rollout": ev, "serving_ckpt_id": "ckpt_00000010"}
    bad = dict(roll, healthy=False)
    body = health_payload(tel, None, lambda: roll)
    assert body["status"] == "rolling"
    assert body["fleet"]["rollout"] == ev
    assert health_payload(tel, None, lambda: bad)["status"] == "degraded"


def test_render_prometheus_rollout_series():
    """ISSUE 16 satellite pins: the rollout counters render through
    the generic counter path, and the health source adds the
    serving_ckpt_info label series (the run_info idiom)."""
    tel = tele.configure(trace_dir=None)
    try:
        tel.counter("rollout_swaps", 3, cat="serve")
        tel.counter("rollout_rollbacks", 1, cat="serve")
        tel.counter("ckpt_quarantined", 2, cat="serve")
        health = {"healthy": True, "serving_ckpt_id": "ckpt_00000020"}
        text = render_prometheus(tel, None, health=lambda: health)
        s = _series(text)
        assert s["sketch_rnn_serve_rollout_swaps_total"] == 3
        assert s["sketch_rnn_serve_rollout_rollbacks_total"] == 1
        assert s["sketch_rnn_serve_ckpt_quarantined_total"] == 2
        assert s['sketch_rnn_serving_ckpt_info'
                 '{ckpt_id="ckpt_00000020"}'] == 1
        # without a health source the info series is absent (the
        # single-engine serve-bench path is unchanged)
        assert "serving_ckpt_info" not in render_prometheus(tel, None)
    finally:
        tele.disable()


def test_render_prometheus_per_tenant_series_and_residency_gauge():
    """ISSUE 19 satellite: per-tenant request/latency/shed series ride
    the class_series contract with a ``tn_`` marker (a tenant can never
    collide with a class or endpoint of the same name), and a
    multi-tenant fleet's start() publishes the paged-adapter residency
    gauge ``tenant_adapters_resident`` — scraped here off a real fleet
    (never warmed: the gauge is start-time state, not decode work)."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.serve import ServeFleet, TenantStore
    from sketch_rnn_tpu.utils.telemetry import (
        class_series,
        tenant_series,
    )

    assert tenant_series("requests_completed", "acme") == \
        "requests_completed_tn_acme"
    assert tenant_series("latency_s", None) == "latency_s"
    # the tn_ marker keeps namespaces apart: a tenant NAMED like a
    # class renders a different series than the class itself
    assert tenant_series("latency_s", "interactive") != \
        class_series("latency_s", "interactive")

    hps = HParams(batch_size=8, max_seq_len=24, enc_rnn_size=12,
                  dec_rnn_size=16, z_size=6, num_mixture=3,
                  serve_slots=2, serve_chunk=2)
    model = SketchRNN(hps)
    params = jax.tree_util.tree_map(
        np.asarray, model.init_params(jax.random.key(0)))
    store = TenantStore(params, base_ckpt_id="ck")
    store.register("acme", params)
    store.register("globex", params)

    tel = tele.configure(trace_dir=None)
    try:
        fleet = ServeFleet(model, hps, params, replicas=1,
                           tenants=store)
        try:
            fleet.start()
        finally:
            fleet.close()
        for t, lat in (("acme", 0.1), ("acme", 0.3), ("globex", 0.2)):
            tel.counter(tenant_series("requests_completed", t), 1.0,
                        cat="serve")
            tel.observe(tenant_series("latency_s", t), lat, cat="serve")
        tel.counter(tenant_series("requests_shed", "globex"), 1.0,
                    cat="serve")
        text = render_prometheus(tel)
    finally:
        tele.disable()
    s = _series(text)
    assert s["sketch_rnn_serve_tenant_adapters_resident"] == 2
    assert "# TYPE sketch_rnn_serve_tenant_adapters_resident gauge" \
        in text
    assert s["sketch_rnn_serve_requests_completed_tn_acme_total"] == 2
    assert s["sketch_rnn_serve_requests_completed_tn_globex_total"] == 1
    assert s["sketch_rnn_serve_requests_shed_tn_globex_total"] == 1
    assert s["sketch_rnn_serve_latency_s_tn_acme_count"] == 2
    assert "# TYPE sketch_rnn_serve_latency_s_tn_acme histogram" in text
