"""Run-identity & manifest tests (ISSUE 8 tentpole piece 3)."""

import json
import os

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.utils import runinfo


def test_run_id_stable_env_override_and_reset(monkeypatch):
    runinfo.set_run_id(None)
    a = runinfo.get_run_id()
    assert a == runinfo.get_run_id()  # minted once per process
    assert len(a.split("-")) == 3 and len(a.split("-")[-1]) == 6
    runinfo.set_run_id(None)
    monkeypatch.setenv(runinfo.RUN_ID_ENV, "launcher-42")
    assert runinfo.get_run_id() == "launcher-42"  # fleet-shared id
    runinfo.set_run_id(None)
    monkeypatch.delenv(runinfo.RUN_ID_ENV)
    b = runinfo.get_run_id()
    assert b != a  # fresh mint after reset
    runinfo.set_run_id(None)


def test_config_hash_stable_and_discriminating():
    h1 = runinfo.config_hash(HParams(batch_size=16))
    assert h1 == runinfo.config_hash(HParams(batch_size=16))
    assert h1 != runinfo.config_hash(HParams(batch_size=32))
    assert len(h1) == 12
    assert runinfo.config_hash(None) is None


def test_host_topology_shape():
    topo = runinfo.host_topology()
    assert topo["process_index"] == 0 and topo["host_count"] == 1
    assert topo["device_count"] >= 1  # the 8-virtual-device test mesh


def test_manifest_write_merge_and_replace(tmp_path):
    d = str(tmp_path)
    p = runinfo.write_manifest(d, kind="train", run_id="r1",
                               hps=HParams(batch_size=16),
                               artifacts={"metrics": ["a.csv"]})
    man = runinfo.read_manifest(d)
    assert man["run_id"] == "r1" and man["kind"] == "train"
    assert man["config_hash"] and man["artifacts"] == {
        "metrics": ["a.csv"]}
    created = man["created_unix"]
    # SAME run_id: artifact index merges, identity fields stay
    runinfo.write_manifest(d, kind="train", run_id="r1",
                           artifacts={"trace": "t.jsonl"},
                           extra={"final_step": 4})
    man = runinfo.read_manifest(d)
    assert man["artifacts"] == {"metrics": ["a.csv"],
                                "trace": "t.jsonl"}
    assert man["created_unix"] == created
    assert man["final_step"] == 4
    # DIFFERENT run_id (directory reuse): the stale index is replaced
    runinfo.write_manifest(d, kind="serve_bench", run_id="r2",
                           artifacts={"prom": "m.prom"})
    man = runinfo.read_manifest(d)
    assert man["run_id"] == "r2" and man["kind"] == "serve_bench"
    assert man["artifacts"] == {"prom": "m.prom"}
    # strict JSON on disk, no tmp litter
    assert json.load(open(p))
    assert not [f for f in os.listdir(d) if f.endswith(".tmp")]


def test_manifest_read_missing_and_torn(tmp_path):
    assert runinfo.read_manifest(str(tmp_path)) is None
    with open(runinfo.manifest_path(str(tmp_path)), "w") as f:
        f.write('{"torn": ')
    assert runinfo.read_manifest(str(tmp_path)) is None
    # a torn manifest is replaced cleanly on the next write
    runinfo.write_manifest(str(tmp_path), kind="train", run_id="x")
    assert runinfo.read_manifest(str(tmp_path))["run_id"] == "x"
