"""Recompute-backward fused kernels vs the lax.scan reference.

Forward values AND custom-VJP gradients must match scan autodiff for BOTH
cell types (SURVEY.md §4: golden-value testing of the performance core;
VERDICT r1 next #3 mandates gradient-testing like tests/test_pallas_lstm).
Includes a batch-tiling case (B > tile) exercising the outer grid axis
and the cross-tile weight-gradient accumulation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.ops.cells import (HyperLSTMCell, LayerNormLSTMCell,
                                      LSTMCell)
from sketch_rnn_tpu.ops.pallas_fused import fused_lstm, fused_ln_lstm
from sketch_rnn_tpu.ops.rnn import make_dropout_masks, run_rnn

# interpret-mode / subprocess heavy: excluded from the quick loop
pytestmark = pytest.mark.slow

T, B, H, D = 5, 8, 128, 16
BIG_B = 24  # > _batch_tile(24)=8 -> 3 batch tiles
HYPER_HH, HYPER_E = 32, 8


def _setup(cell_cls, b=B, seed=0):
    cell = cell_cls(H)
    params = cell.init_params(jax.random.key(seed), D)
    xs = jax.random.normal(jax.random.key(seed + 1), (T, b, D))
    c0 = jax.random.normal(jax.random.key(seed + 2), (b, H)) * 0.3
    h0 = jax.random.normal(jax.random.key(seed + 3), (b, H)) * 0.3
    return cell, params, xs, c0, h0


def _call_fused(cell, params, xs, c0, h0, masks=None):
    if isinstance(cell, LayerNormLSTMCell):
        return fused_ln_lstm(xs, params["wx"], params["wh"],
                             params["ln_gamma"], params["ln_beta"],
                             params["lnc_gamma"], params["lnc_beta"],
                             c0, h0, 1.0, masks)
    return fused_lstm(xs, params["wx"], params["b"], params["wh"],
                      c0, h0, 1.0, masks)


@pytest.mark.parametrize("cell_cls", [LSTMCell, LayerNormLSTMCell])
@pytest.mark.parametrize("use_mask", [False, True])
def test_forward_matches_scan(cell_cls, use_mask):
    cell, params, xs, c0, h0 = _setup(cell_cls)
    masks = (make_dropout_masks(jax.random.key(9), 0.8, T, B, H)
             if use_mask else None)
    final, hs_ref = run_rnn(cell, params, xs, carry0=(c0, h0),
                            rdrop_masks=masks)
    hs, (cT, hT) = _call_fused(cell, params, xs, c0, h0, masks)
    np.testing.assert_allclose(np.asarray(hs), np.asarray(hs_ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(cT), np.asarray(final[0]),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(hT), np.asarray(final[1]),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("cell_cls", [LSTMCell, LayerNormLSTMCell])
def test_forward_batch_tiled(cell_cls):
    cell, params, xs, c0, h0 = _setup(cell_cls, b=BIG_B)
    _, hs_ref = run_rnn(cell, params, xs, carry0=(c0, h0))
    hs, _ = _call_fused(cell, params, xs, c0, h0)
    np.testing.assert_allclose(np.asarray(hs), np.asarray(hs_ref),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("cell_cls", [LSTMCell, LayerNormLSTMCell])
@pytest.mark.parametrize("use_mask", [False, True])
def test_gradients_match_scan(cell_cls, use_mask):
    cell, params, xs, c0, h0 = _setup(cell_cls)
    masks = (make_dropout_masks(jax.random.key(9), 0.8, T, B, H)
             if use_mask else None)
    wtgt = jax.random.normal(jax.random.key(7), (T, B, H)) * 0.1

    def loss_fused(params_, xs_, c0_, h0_):
        hs, (cT, hT) = _call_fused(cell, params_, xs_, c0_, h0_, masks)
        return jnp.sum(hs * wtgt) + jnp.sum(cT) + 0.5 * jnp.sum(hT)

    def loss_scan(params_, xs_, c0_, h0_):
        (cT, hT), hs = run_rnn(cell, params_, xs_, carry0=(c0_, h0_),
                               rdrop_masks=masks)
        return jnp.sum(hs * wtgt) + jnp.sum(cT) + 0.5 * jnp.sum(hT)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(params, xs, c0, h0)
    gs = jax.grad(loss_scan, argnums=(0, 1, 2, 3))(params, xs, c0, h0)
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_flatten_with_path(gf)[0],
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_flatten_with_path(gs)[0],
                   key=lambda kv: str(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5,
                                   err_msg=f"{ka} vs {kb}")


@pytest.mark.parametrize("cell_cls", [LSTMCell, LayerNormLSTMCell])
def test_gradients_batch_tiled(cell_cls):
    # weight grads accumulate across batch tiles; compare vs scan at BIG_B
    cell, params, xs, c0, h0 = _setup(cell_cls, b=BIG_B)

    def loss_fused(params_):
        hs, _ = _call_fused(cell, params_, xs, c0, h0)
        return jnp.mean(hs ** 2)

    def loss_scan(params_):
        _, hs = run_rnn(cell, params_, xs, carry0=(c0, h0))
        return jnp.mean(hs ** 2)

    gf = jax.grad(loss_fused)(params)
    gs = jax.grad(loss_scan)(params)
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_flatten_with_path(gf)[0],
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_flatten_with_path(gs)[0],
                   key=lambda kv: str(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5,
                                   err_msg=f"{ka} vs {kb}")


def test_bf16_weights_compile_and_are_finite():
    # mixed precision contract: weights pre-cast to bf16, f32 accumulation;
    # cotangents come back in the primal dtype
    cell, params, xs, c0, h0 = _setup(LSTMCell)

    def loss(wx, b, wh):
        hs, _ = fused_lstm(xs, wx, b, wh, c0, h0, 1.0, None)
        return jnp.mean(hs ** 2)

    wx = params["wx"].astype(jnp.bfloat16)
    wh = params["wh"].astype(jnp.bfloat16)
    v, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(wx, params["b"], wh)
    assert np.isfinite(float(v))
    assert g[0].dtype == jnp.bfloat16 and g[2].dtype == jnp.bfloat16
    for x in g:
        assert np.isfinite(np.asarray(x, np.float32)).all()


@pytest.mark.parametrize("cell_cls", [LSTMCell, LayerNormLSTMCell])
def test_prng_dropout_deterministic_and_distributed(cell_cls):
    # same seed -> identical output; the dropout must actually drop
    # (keep<1 changes the output vs no dropout)
    cell, params, xs, c0, h0 = _setup(cell_cls)
    seed = jnp.int32(1234)

    def call(s, keep):
        if isinstance(cell, LayerNormLSTMCell):
            return fused_ln_lstm(xs, params["wx"], params["wh"],
                                 params["ln_gamma"], params["ln_beta"],
                                 params["lnc_gamma"], params["lnc_beta"],
                                 c0, h0, 1.0, None, s, keep)[0]
        return fused_lstm(xs, params["wx"], params["b"], params["wh"],
                          c0, h0, 1.0, None, s, keep)[0]

    a = np.asarray(call(seed, 0.8))
    b = np.asarray(call(seed, 0.8))
    np.testing.assert_array_equal(a, b)
    c = np.asarray(call(jnp.int32(77), 0.8))
    assert not np.allclose(a, c)  # different seed -> different masks
    d = np.asarray(call(None, 1.0))
    assert not np.allclose(a, d)  # dropout actually drops


def test_prng_dropout_bwd_uses_fwd_masks():
    # finite differences prove the backward regenerates EXACTLY the
    # forward's masks (a mismatched mask would show up as a wrong grad)
    cell, params, xs, c0, h0 = _setup(LSTMCell)
    seed = jnp.int32(42)

    def loss(wh):
        hs, _ = fused_lstm(xs, params["wx"], params["b"], wh, c0, h0,
                           1.0, None, seed, 0.8)
        return jnp.sum(hs ** 2)

    g = np.asarray(jax.grad(loss)(params["wh"]))
    # directional derivative along g (f32 losses are too coarse for
    # single-coordinate or random directions — the signal must dominate
    # the ~1e-5-relative loss quantization). If the backward regenerated
    # DIFFERENT masks than the forward, g would not be the true gradient
    # and the measured slope along g would disagree with |g|.
    eps = 3e-3
    v = g / np.linalg.norm(g)
    fd = (float(loss(params["wh"] + eps * v)) -
          float(loss(params["wh"] - eps * v))) / (2 * eps)
    assert float(np.sum(g * v)) == pytest.approx(fd, rel=2e-2)


def test_prng_dropout_keep_statistics():
    # the realized drop rate over the candidate-gate mask should be ~keep
    cell, params, xs, c0, h0 = _setup(LSTMCell)
    keep = 0.7
    # with x=0, b=0, h0=0: g_u = tanh(0 + 0) = 0, so probe via output
    # variance instead: run with large T*B and compare against the scan
    # with outside masks — statistics only, so just check mean output
    # magnitude ratio is within a loose band of 1.0
    hs_drop, _ = fused_lstm(xs, params["wx"], params["b"], params["wh"],
                            c0, h0, 1.0, None, jnp.int32(5), keep)
    hs_ref, _ = fused_lstm(xs, params["wx"], params["b"], params["wh"],
                           c0, h0, 1.0, None, None, 1.0)
    ratio = float(jnp.mean(jnp.abs(hs_drop)) / jnp.mean(jnp.abs(hs_ref)))
    assert 0.7 < ratio < 1.3


# ---------------------------------------------------------------------------
# HyperLSTM kernel (nested carry; dispatched through run_rnn(fused=True)).
#
# Tolerances are looser than the LSTM/LN kernels': the kernel's dense
# block-diagonal scale matmul and the cell's [4, e, h] einsum accumulate
# in different SIMD orders, and per-gate layer-norm gradients amplify that
# ~1e-6 forward reassociation noise into ~1e-3-relative gradient noise. A
# real missing gradient path shows up as 10-100% error (measured while
# building the kernel), so these bands still catch logic bugs; the
# directional-FD test below pins the fused gradient to the true slope.
# ---------------------------------------------------------------------------


def _setup_hyper(b=B, seed=0):
    cell = HyperLSTMCell(H, hyper_size=HYPER_HH, embed_size=HYPER_E)
    params = cell.init_params(jax.random.key(seed), D)
    # perturb the zero/constant-init hyper projections so every gradient
    # path is exercised with non-degenerate weights
    for i, k in enumerate(("w_hz_x", "w_hz_h", "w_zd_x", "w_zd_h",
                           "w_zd_b")):
        params[k] = params[k] + 0.05 * jax.random.normal(
            jax.random.key(100 + i), params[k].shape)
    xs = jax.random.normal(jax.random.key(seed + 1), (T, b, D))
    c0 = jax.random.normal(jax.random.key(seed + 2), (b, H)) * 0.3
    h0 = jax.random.normal(jax.random.key(seed + 3), (b, H)) * 0.3
    hc0 = jax.random.normal(jax.random.key(seed + 4), (b, HYPER_HH)) * 0.3
    hh0 = jax.random.normal(jax.random.key(seed + 5), (b, HYPER_HH)) * 0.3
    return cell, params, xs, ((c0, h0), (hc0, hh0))


@pytest.mark.parametrize("use_mask", [False, True])
def test_hyper_forward_matches_scan(use_mask):
    cell, params, xs, carry0 = _setup_hyper()
    masks = (make_dropout_masks(jax.random.key(9), 0.8, T, B, H)
             if use_mask else None)
    fin_ref, hs_ref = run_rnn(cell, params, xs, carry0=carry0,
                              rdrop_masks=masks)
    fin, hs = run_rnn(cell, params, xs, carry0=carry0, rdrop_masks=masks,
                      fused=True)
    np.testing.assert_allclose(np.asarray(hs), np.asarray(hs_ref),
                               rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(fin),
                    jax.tree_util.tree_leaves(fin_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("use_mask", [False, True])
def test_hyper_gradients_match_scan(use_mask):
    cell, params, xs, carry0 = _setup_hyper()
    masks = (make_dropout_masks(jax.random.key(9), 0.8, T, B, H)
             if use_mask else None)
    wtgt = jax.random.normal(jax.random.key(7), (T, B, H)) * 0.1

    def make_loss(fused):
        def f(params_, xs_, carry_):
            fin, hs = run_rnn(cell, params_, xs_, carry0=carry_,
                              rdrop_masks=masks, fused=fused)
            return (jnp.sum(hs * wtgt)
                    + sum(0.3 * jnp.sum(l)
                          for l in jax.tree_util.tree_leaves(fin)))
        return f

    gf = jax.grad(make_loss(True), argnums=(0, 1, 2))(params, xs, carry0)
    gs = jax.grad(make_loss(False), argnums=(0, 1, 2))(params, xs, carry0)
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_flatten_with_path(gf)[0],
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_flatten_with_path(gs)[0],
                   key=lambda kv: str(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-3,
                                   err_msg=f"{ka} vs {kb}")


def test_hyper_gradients_batch_tiled():
    cell, params, xs, carry0 = _setup_hyper(b=BIG_B)

    def make_loss(fused):
        def f(params_):
            _, hs = run_rnn(cell, params_, xs, carry0=carry0, fused=fused)
            return jnp.mean(hs ** 2)
        return f

    gf = jax.grad(make_loss(True))(params)
    gs = jax.grad(make_loss(False))(params)
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_flatten_with_path(gf)[0],
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_flatten_with_path(gs)[0],
                   key=lambda kv: str(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-3,
                                   err_msg=f"{ka} vs {kb}")


def test_hyper_forward_non_divisible_batch():
    # regression: B=20 has no divisor in {64..} below the tile cap except
    # 20 itself via the largest-divisor search — a tile that does not
    # divide B would silently drop the trailing rows (found in review)
    cell, params, xs, carry0 = _setup_hyper(b=20)
    _, hs_ref = run_rnn(cell, params, xs, carry0=carry0)
    _, hs = run_rnn(cell, params, xs, carry0=carry0, fused=True)
    np.testing.assert_allclose(np.asarray(hs), np.asarray(hs_ref),
                               rtol=1e-4, atol=1e-5)


def test_hyper_gradient_is_true_slope():
    # directional finite difference along the fused gradient: guards
    # against a plausible-but-wrong backward that still matches scan's
    # numerics-noise band (and vice versa)
    cell, params, xs, carry0 = _setup_hyper()

    def loss(wh):
        p = dict(params)
        p["wh"] = wh
        _, hs = run_rnn(cell, p, xs, carry0=carry0, fused=True)
        return jnp.sum(hs ** 2)

    g = np.asarray(jax.grad(loss)(params["wh"]))
    eps = 3e-3
    v = g / np.linalg.norm(g)
    fd = (float(loss(params["wh"] + eps * v)) -
          float(loss(params["wh"] - eps * v))) / (2 * eps)
    assert float(np.sum(g * v)) == pytest.approx(fd, rel=2e-2)


def test_hyper_prng_dropout_deterministic():
    cell, params, xs, carry0 = _setup_hyper()

    def call(seed, keep):
        gen = None if seed is None else (jax.random.key(seed), keep)
        _, hs = run_rnn(cell, params, xs, carry0=carry0, rdrop_gen=gen,
                        fused=True)
        return np.asarray(hs)

    a = call(1234, 0.8)
    b = call(1234, 0.8)
    np.testing.assert_array_equal(a, b)
    c = call(77, 0.8)
    assert not np.allclose(a, c)   # different seed -> different masks
    d = call(None, 1.0)
    assert not np.allclose(a, d)   # dropout actually drops


def test_hyper_fused_model_loss_matches_scan_eval():
    # full VAE forward with a hyper decoder, fused on vs off, eval mode
    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
    from sketch_rnn_tpu.models.vae import SketchRNN

    base = dict(batch_size=8, max_seq_len=24, enc_rnn_size=16,
                dec_rnn_size=128, z_size=6, num_mixture=3,
                dec_model="hyper", hyper_rnn_size=32, hyper_embed_size=8)
    seqs, labels = make_synthetic_strokes(16, min_len=8, max_len=20, seed=0)
    h_off = HParams(**base, fused_rnn=False)
    h_on = HParams(**base, fused_rnn=True)
    batch = DataLoader(seqs, h_off, labels=labels).get_batch(0)
    m_off, m_on = SketchRNN(h_off), SketchRNN(h_on)
    params = m_off.init_params(jax.random.key(0))
    key = jax.random.key(1)
    t_off, _ = m_off.loss(params, batch, key, kl_weight=1.0, train=False)
    t_on, _ = m_on.loss(params, batch, key, kl_weight=1.0, train=False)
    np.testing.assert_allclose(float(t_on), float(t_off),
                               rtol=1e-4, atol=1e-5)


def test_hyper_fused_train_step_decreases_loss():
    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train import make_train_state, make_train_step

    hps = HParams(batch_size=8, max_seq_len=24, enc_rnn_size=16,
                  dec_rnn_size=128, z_size=6, num_mixture=3,
                  dec_model="hyper", hyper_rnn_size=32, hyper_embed_size=8,
                  fused_rnn=True)
    seqs, labels = make_synthetic_strokes(16, min_len=8, max_len=20, seed=0)
    loader = DataLoader(seqs, hps, labels=labels)
    model = SketchRNN(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh=None)
    batch = loader.get_batch(0)
    losses = []
    for i in range(8):
        state, metrics = step(state, batch, jax.random.key(i))
        losses.append(float(metrics["loss"]))
        assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0]


def test_long_sequence_fused_matches_scan():
    """Sequence scaling is just scan length (SURVEY §5 'Long-context'):
    the kernels handle T far beyond the reference's 250 cap. Recurrent
    dynamics are chaotic — ~1e-6 reassociation noise amplifies
    exponentially with depth — so the testable contract is: close match
    over a prefix, then bounded, finite trajectories. (Whole-horizon
    statistics of two diverged chaotic trajectories are a seed lottery,
    not a kernel property; short-T exactness is covered exhaustively by
    the other tests in this file.)"""
    T, B, H, D = 512, 8, 32, 5
    cell = LayerNormLSTMCell(H)
    params = cell.init_params(jax.random.key(0), D)
    xs = jax.random.normal(jax.random.key(1), (T, B, D))
    _, hs_ref = run_rnn(cell, params, xs)
    _, hs = run_rnn(cell, params, xs, fused=True)
    hs, hs_ref = np.asarray(hs), np.asarray(hs_ref)
    np.testing.assert_allclose(hs[:50], hs_ref[:50], rtol=1e-3, atol=1e-4)
    assert np.isfinite(hs).all()
    assert np.abs(hs).max() <= 1.0 + 1e-6  # tanh-bounded output


# ---------------------------------------------------------------------------
# per-example input bias (x_extra): time-invariant features (z, class
# embedding) projected once instead of streamed through every step's xs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cell_cls", [LSTMCell, LayerNormLSTMCell])
@pytest.mark.parametrize("use_mask", [False, True])
def test_x_extra_matches_concat(cell_cls, use_mask):
    # run_rnn(x_extra=e) with wx covering [x; e] rows must equal the scan
    # over concatenated inputs — forward AND gradients (incl. d extra)
    E = 8
    cell = cell_cls(H)
    params = cell.init_params(jax.random.key(0), D + E)
    xs = jax.random.normal(jax.random.key(1), (T, B, D))
    extra = jax.random.normal(jax.random.key(2), (B, E))
    c0 = jax.random.normal(jax.random.key(3), (B, H)) * 0.3
    h0 = jax.random.normal(jax.random.key(4), (B, H)) * 0.3
    masks = (make_dropout_masks(jax.random.key(9), 0.8, T, B, H)
             if use_mask else None)
    wtgt = jax.random.normal(jax.random.key(7), (T, B, H)) * 0.1

    def make_loss(fused):
        def f(params_, xs_, extra_):
            fin, hs = run_rnn(cell, params_, xs_, carry0=(c0, h0),
                              rdrop_masks=masks, fused=fused,
                              x_extra=extra_)
            return (jnp.sum(hs * wtgt)
                    + sum(0.5 * jnp.sum(l)
                          for l in jax.tree_util.tree_leaves(fin)))
        return f

    vf, gf = jax.value_and_grad(make_loss(True), argnums=(0, 1, 2))(
        params, xs, extra)
    vs, gs = jax.value_and_grad(make_loss(False), argnums=(0, 1, 2))(
        params, xs, extra)
    np.testing.assert_allclose(float(vf), float(vs), rtol=1e-5)
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_flatten_with_path(gf)[0],
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_flatten_with_path(gs)[0],
                   key=lambda kv: str(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4,
                                   err_msg=f"{ka} vs {kb}")


@pytest.mark.parametrize("use_mask", [False, True])
def test_hyper_x_extra_matches_concat(use_mask):
    # hyper: BOTH the main gates and the aux LSTM get a per-example bias
    E = 8
    cell = HyperLSTMCell(H, hyper_size=HYPER_HH, embed_size=HYPER_E)
    params = cell.init_params(jax.random.key(0), D + E)
    for i, k in enumerate(("w_hz_x", "w_hz_h", "w_zd_x", "w_zd_h",
                           "w_zd_b")):
        params[k] = params[k] + 0.05 * jax.random.normal(
            jax.random.key(100 + i), params[k].shape)
    xs = jax.random.normal(jax.random.key(1), (T, B, D))
    extra = jax.random.normal(jax.random.key(2), (B, E))
    carry0 = ((jax.random.normal(jax.random.key(3), (B, H)) * 0.3,
               jax.random.normal(jax.random.key(4), (B, H)) * 0.3),
              (jax.random.normal(jax.random.key(5), (B, HYPER_HH)) * 0.3,
               jax.random.normal(jax.random.key(6), (B, HYPER_HH)) * 0.3))
    masks = (make_dropout_masks(jax.random.key(9), 0.8, T, B, H)
             if use_mask else None)
    wtgt = jax.random.normal(jax.random.key(7), (T, B, H)) * 0.1

    def make_loss(fused):
        def f(params_, xs_, extra_):
            fin, hs = run_rnn(cell, params_, xs_, carry0=carry0,
                              rdrop_masks=masks, fused=fused,
                              x_extra=extra_)
            return (jnp.sum(hs * wtgt)
                    + sum(0.3 * jnp.sum(l)
                          for l in jax.tree_util.tree_leaves(fin)))
        return f

    vf, gf = jax.value_and_grad(make_loss(True), argnums=(0, 1, 2))(
        params, xs, extra)
    vs, gs = jax.value_and_grad(make_loss(False), argnums=(0, 1, 2))(
        params, xs, extra)
    np.testing.assert_allclose(float(vf), float(vs), rtol=1e-4)
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_flatten_with_path(gf)[0],
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_flatten_with_path(gs)[0],
                   key=lambda kv: str(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-3,
                                   err_msg=f"{ka} vs {kb}")


def test_x_extra_model_decode_matches_concat_eval():
    # conditional model, fused on: decode routes z through the bias path;
    # the scan path concatenates — same loss in eval mode
    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
    from sketch_rnn_tpu.models.vae import SketchRNN

    base = dict(batch_size=8, max_seq_len=24, enc_rnn_size=16,
                dec_rnn_size=128, z_size=6, num_mixture=3, num_classes=2,
                dec_model="layer_norm")
    seqs, labels = make_synthetic_strokes(16, num_classes=2, min_len=8,
                                          max_len=20, seed=0)
    h_off = HParams(**base, fused_rnn=False)
    h_on = HParams(**base, fused_rnn=True)
    batch = DataLoader(seqs, h_off, labels=labels).get_batch(0)
    m_off, m_on = SketchRNN(h_off), SketchRNN(h_on)
    params = m_off.init_params(jax.random.key(0))
    key = jax.random.key(1)
    t_off, _ = m_off.loss(params, batch, key, kl_weight=1.0, train=False)
    t_on, _ = m_on.loss(params, batch, key, kl_weight=1.0, train=False)
    np.testing.assert_allclose(float(t_on), float(t_off),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("cell_kind", ["lstm", "layer_norm", "hyper"])
def test_bf16_residuals_train_and_match_f32(cell_kind):
    # bfloat16 residual storage: forward values must match the f32-residual
    # kernel to bf16 rounding (the forward math is identical — only the
    # saved streams are rounded), gradients to ~1% (backward recomputes
    # from rounded residuals)
    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
    from sketch_rnn_tpu.models.vae import SketchRNN

    hps16 = HParams(batch_size=8, max_seq_len=24, enc_rnn_size=16,
                    dec_rnn_size=128, z_size=6, num_mixture=3,
                    dec_model=cell_kind, hyper_rnn_size=32,
                    hyper_embed_size=8, fused_rnn=True,
                    fused_residual_dtype="bfloat16")
    hps32 = hps16.replace(fused_residual_dtype="float32")
    seqs, labels = make_synthetic_strokes(16, min_len=8, max_len=20, seed=0)
    batch = DataLoader(seqs, hps16, labels=labels).get_batch(0)
    m16, m32 = SketchRNN(hps16), SketchRNN(hps32)
    params = m32.init_params(jax.random.key(0))
    key = jax.random.key(1)
    t16, _ = m16.loss(params, batch, key, kl_weight=1.0, train=False)
    t32, _ = m32.loss(params, batch, key, kl_weight=1.0, train=False)
    np.testing.assert_allclose(float(t16), float(t32), rtol=2e-2)

    g16 = jax.grad(lambda p: m16.loss(p, batch, key, 1.0, train=False)[0])(
        params)
    g32 = jax.grad(lambda p: m32.loss(p, batch, key, 1.0, train=False)[0])(
        params)
    n16 = jnp.sqrt(sum(jnp.sum(l.astype(jnp.float32) ** 2)
                       for l in jax.tree_util.tree_leaves(g16)))
    n32 = jnp.sqrt(sum(jnp.sum(l.astype(jnp.float32) ** 2)
                       for l in jax.tree_util.tree_leaves(g32)))
    assert float(n16) == pytest.approx(float(n32), rel=5e-2)
    # (training convergence with bf16 residuals is covered by
    # test_train.py::test_mesh_train_fused_production_config)


def test_model_loss_matches_scan_path_eval():
    # full VAE forward (encoder + decoder) with fused_rnn on vs off must
    # agree in eval mode (no dropout -> identical math, kernel vs scan)
    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
    from sketch_rnn_tpu.models.vae import SketchRNN

    base = dict(batch_size=8, max_seq_len=24, enc_rnn_size=16,
                dec_rnn_size=128, z_size=6, num_mixture=3,
                dec_model="layer_norm")
    seqs, labels = make_synthetic_strokes(16, min_len=8, max_len=20, seed=0)
    h_off = HParams(**base, fused_rnn=False)
    h_on = HParams(**base, fused_rnn=True)
    batch = DataLoader(seqs, h_off, labels=labels).get_batch(0)
    m_off, m_on = SketchRNN(h_off), SketchRNN(h_on)
    params = m_off.init_params(jax.random.key(0))
    key = jax.random.key(1)
    t_off, _ = m_off.loss(params, batch, key, kl_weight=1.0, train=False)
    t_on, _ = m_on.loss(params, batch, key, kl_weight=1.0, train=False)
    np.testing.assert_allclose(float(t_on), float(t_off),
                               rtol=1e-4, atol=1e-5)


def test_train_step_with_fused_rnn():
    # dropout on (masks generated outside the kernel): one step must run,
    # produce finite loss/grads and decrease the loss over a few steps
    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train import make_train_state, make_train_step

    hps = HParams(batch_size=8, max_seq_len=24, enc_rnn_size=16,
                  dec_rnn_size=128, z_size=6, num_mixture=3,
                  dec_model="layer_norm", fused_rnn=True)
    seqs, labels = make_synthetic_strokes(16, min_len=8, max_len=20, seed=0)
    loader = DataLoader(seqs, hps, labels=labels)
    model = SketchRNN(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh=None)
    batch = loader.get_batch(0)
    losses = []
    for i in range(8):
        state, metrics = step(state, batch, jax.random.key(i))
        losses.append(float(metrics["loss"]))
        assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0]


def test_masks_traced_under_jit():
    cell, params, xs, c0, h0 = _setup(LayerNormLSTMCell)

    @jax.jit
    def f(key, params_):
        masks = make_dropout_masks(key, 0.8, T, B, H)

        def loss(p):
            hs, _ = _call_fused(cell, p, xs, c0, h0, masks)
            return jnp.mean(hs ** 2)
        return jax.value_and_grad(loss)(params_)

    v, g = f(jax.random.key(3), params)
    assert np.isfinite(float(v))
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


def test_batch_tile_xb_bwd_budget():
    """The x_bias backward adds two [tile, 4H] f32 blocks; at H=512 the
    tile-256 backward sat exactly AT the 16M scoped-VMEM line and
    compiled or OOM'd depending on graph context (measured on v5e) —
    the backward must halve its tile budget, the forward keeps full."""
    from sketch_rnn_tpu.ops.pallas_fused import _batch_tile

    assert _batch_tile(4096, 512) == 256            # fwd, flagship decoder
    assert _batch_tile(4096, 512, xb_bwd=True) == 128
    assert _batch_tile(1024, 512, xb_bwd=True) == 128
    assert _batch_tile(4096, 256) == 512            # encoder (no x_bias)
    assert _batch_tile(4096, 256, xb_bwd=True) == 256


def test_seq_lstm_matches_full_kernel():
    """fused_lstm_seq (the encoder's weights-only-gradient variant) must
    equal fused_lstm in outputs and all WEIGHT gradients (its xs/carry
    cotangents are zero by contract), including the in-kernel PRNG
    dropout and bf16-residual modes."""
    import jax.numpy as jnp
    from sketch_rnn_tpu.ops.pallas_fused import fused_lstm, fused_lstm_seq

    k = jax.random.key(3)
    ks = jax.random.split(k, 6)
    T, B, D, H = 10, 8, 5, 12
    xs = jax.random.normal(ks[0], (T, B, D))
    wx = jax.random.normal(ks[1], (D, 4 * H)) * 0.3
    b = jax.random.normal(ks[2], (4 * H,)) * 0.1
    wh = jax.random.normal(ks[3], (H, 4 * H)) * 0.2
    c0 = jnp.zeros((B, H))
    h0 = jnp.zeros((B, H))
    seed = jnp.int32(7)

    for rd in (jnp.float32, jnp.bfloat16):
        def loss_full(args):
            xs, wx, b, wh = args
            hs, _ = fused_lstm(xs, wx, b, wh, c0, h0, dropout_seed=seed,
                               keep_prob=0.9, residual_dtype=rd)
            return jnp.sum(jnp.sin(hs.astype(jnp.float32)))

        def loss_seq(args):
            xs, wx, b, wh = args
            hs = fused_lstm_seq(xs, wx, b, wh, c0, h0, dropout_seed=seed,
                                keep_prob=0.9, residual_dtype=rd)
            return jnp.sum(jnp.sin(hs.astype(jnp.float32)))

        v1, g1 = jax.value_and_grad(loss_full)((xs, wx, b, wh))
        v2, g2 = jax.value_and_grad(loss_seq)((xs, wx, b, wh))
        assert float(v1) == float(v2)
        # weight grads match; the xs cotangent is zero BY CONTRACT
        for a, bb in zip(g1[1:], g2[1:]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                       rtol=1e-5, atol=1e-6)
        assert not np.any(np.asarray(g2[0]))


def test_batch_tile_seq_doubles_budget():
    from sketch_rnn_tpu.ops.pallas_fused import _batch_tile, _batch_tile_seq

    assert _batch_tile_seq(4096, 256) == 1024   # encoder: 2x the full 512
    assert _batch_tile(4096, 256) == 512
    assert _batch_tile_seq(4096, 512) == 512


@pytest.mark.parametrize("t_len", [1, 2])
def test_gradients_match_scan_short_sequences(t_len):
    """T=1 / T=2 edge of the reversed-index backward layout: the
    clamped previous-step index map (max(T-2-it, 0)) degenerates at
    these lengths (every block index is 0) and the h0 override must
    carry the whole recurrence."""
    cell, params, xs, c0, h0 = _setup(LayerNormLSTMCell)
    xs = xs[:t_len]

    def loss_fused(p, c, hh):
        hs, (cT, hT) = _call_fused(cell, p, xs, c, hh)
        return jnp.sum(hs * 1.3) + jnp.sum(cT) + 2.0 * jnp.sum(hT)

    def loss_scan(p, c, hh):
        (cT, hT), hs = run_rnn(cell, p, xs, carry0=(c, hh))
        return jnp.sum(hs * 1.3) + jnp.sum(cT) + 2.0 * jnp.sum(hT)

    vf, gf = jax.value_and_grad(loss_fused, argnums=(0, 1, 2))(
        params, c0, h0)
    vs, gs = jax.value_and_grad(loss_scan, argnums=(0, 1, 2))(
        params, c0, h0)
    np.testing.assert_allclose(vf, vs, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)
