"""Sampler / SVG / interpolation tests (SURVEY.md §4 test pyramid).

The sampler's stop-on-p3 semantics, temperature behavior, and the mixture
draw itself are unit-tested; end-to-end sampling runs on every cell type.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.ops import mdn
from sketch_rnn_tpu.sample import (
    encode_mu,
    interpolate_latents,
    lerp,
    make_sampler,
    sample,
    sample_from_mixture,
    slerp,
    strokes_to_svg,
    svg_grid,
)

TINY = dict(batch_size=8, max_seq_len=24, enc_rnn_size=12, dec_rnn_size=16,
            z_size=6, num_mixture=3, hyper_rnn_size=8, hyper_embed_size=4)


def tiny_hps(**kw) -> HParams:
    return HParams(**{**TINY, **kw})


def _mixture(b=4, m=3, mean=(2.0, -1.0), pen_idx=0):
    """A mixture massively favoring component 0 at `mean`, pen `pen_idx`."""
    logits = jnp.full((b, m), -50.0).at[:, 0].set(50.0)
    mu1 = jnp.zeros((b, m)).at[:, 0].set(mean[0])
    mu2 = jnp.zeros((b, m)).at[:, 0].set(mean[1])
    pen = jnp.full((b, 3), -50.0).at[:, pen_idx].set(50.0)
    return mdn.MixtureParams(
        log_pi=jax.nn.log_softmax(logits),
        mu1=mu1, mu2=mu2,
        log_s1=jnp.full((b, m), -3.0), log_s2=jnp.full((b, m), -3.0),
        rho=jnp.zeros((b, m)), pen_logits=pen)


def test_sample_from_mixture_concentrates():
    mp = _mixture(mean=(2.0, -1.0), pen_idx=1)
    s = sample_from_mixture(mp, jax.random.key(0), temperature=0.01)
    s = np.asarray(s)
    assert s.shape == (4, 5)
    np.testing.assert_allclose(s[:, 0], 2.0, atol=0.05)
    np.testing.assert_allclose(s[:, 1], -1.0, atol=0.05)
    np.testing.assert_array_equal(s[:, 2:], np.tile([0, 1, 0], (4, 1)))


def test_sample_from_mixture_greedy_is_exact():
    mp = _mixture(mean=(0.7, 0.3), pen_idx=2)
    s = np.asarray(sample_from_mixture(mp, jax.random.key(3),
                                       temperature=1.0, greedy=True))
    np.testing.assert_allclose(s[:, 0], 0.7, rtol=1e-6)
    np.testing.assert_allclose(s[:, 1], 0.3, rtol=1e-6)
    assert (s[:, 4] == 1.0).all()


def test_temperature_widens_spread():
    mp = _mixture(b=256)
    lo = np.asarray(sample_from_mixture(mp, jax.random.key(0), 0.1)[:, 0])
    hi = np.asarray(sample_from_mixture(mp, jax.random.key(0), 1.0)[:, 0])
    assert np.std(hi) > 2.0 * np.std(lo)


@pytest.mark.parametrize("dec", ["lstm", "layer_norm", "hyper"])
def test_sampler_end_to_end(dec):
    hps = tiny_hps(dec_model=dec)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    z = jax.random.normal(jax.random.key(1), (4, hps.z_size))
    sampler = make_sampler(model, hps)
    strokes, lengths = sampler(params, jax.random.key(2), 4, z, None,
                               jnp.float32(0.8))
    strokes, lengths = np.asarray(strokes), np.asarray(lengths)
    assert strokes.shape == (4, hps.max_seq_len, 5)
    assert np.isfinite(strokes).all()
    # pen state is one-hot everywhere
    np.testing.assert_allclose(strokes[:, :, 2:].sum(-1), 1.0)
    for i in range(4):
        n = lengths[i]
        assert 0 <= n <= hps.max_seq_len
        # row n is the end-of-sketch row (sampled offsets, p3 pen state);
        # every row after it is a frozen zero-offset end token
        if n < hps.max_seq_len:
            assert (strokes[i, n:, 4] == 1.0).all()
            assert (strokes[i, n + 1:, 0:2] == 0.0).all()


def test_sampler_per_row_max_steps():
    """The optional [B] step cap: row i freezes to end tokens after
    emitting max_steps[i] strokes (the serving benchmark's controlled
    freeze-until-batch-done baseline rides on this)."""
    hps = tiny_hps()
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    # suppress the end-of-sketch pen state so caps are the only stop
    params["out_b"] = params["out_b"].at[2].set(-1e9)
    z = jax.random.normal(jax.random.key(1), (3, hps.z_size))
    sampler = make_sampler(model, hps)
    caps = jnp.array([3, 7, 12], jnp.int32)
    strokes, lengths = sampler(params, jax.random.key(2), 3, z, None,
                               jnp.float32(0.8), caps)
    strokes, lengths = np.asarray(strokes), np.asarray(lengths)
    for i, cap in enumerate([3, 7, 12]):
        # frozen rows after the cap are end tokens
        assert (strokes[i, cap:, 4] == 1.0).all()
        assert (strokes[i, cap:, 0:2] == 0.0).all()
        # rows before the cap are live samples (pen suppressed -> p3=0)
        assert (strokes[i, :cap, 4] == 0.0).all()
    # capped rows never drew p3, so every emitted stroke is real and
    # length == cap (matching the serving engine's accounting)
    np.testing.assert_array_equal(lengths, [3, 7, 12])
    # without caps the same call runs the full buffer
    s2, l2 = sampler(params, jax.random.key(2), 3, z, None,
                     jnp.float32(0.8))
    assert (np.asarray(l2) == hps.max_seq_len).all()


def test_sampler_deterministic_same_key():
    hps = tiny_hps()
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    z = jnp.zeros((2, hps.z_size))
    sampler = make_sampler(model, hps)
    a, la = sampler(params, jax.random.key(7), 2, z, None, jnp.float32(1.0))
    b, lb = sampler(params, jax.random.key(7), 2, z, None, jnp.float32(1.0))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_sampler_sharded_over_mesh():
    """Distributed generation: each device runs the whole while_loop on
    its batch shard (collective-free; per-shard PRNG streams). Valid
    stroke-5 output, deterministic per key, varying across shards."""
    from sketch_rnn_tpu.parallel.mesh import make_mesh

    hps = tiny_hps()
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    mesh = make_mesh(hps)
    n = 16  # 2 sketches per virtual device
    z = jax.random.normal(jax.random.key(1), (n, hps.z_size))
    sampler = make_sampler(model, hps, mesh=mesh)
    s5, lengths = sampler(params, jax.random.key(2), n, z, None,
                          jnp.float32(0.8))
    s5, lengths = np.asarray(s5), np.asarray(lengths)
    assert s5.shape == (n, hps.max_seq_len, 5)
    assert np.isfinite(s5).all()
    np.testing.assert_allclose(s5[:, :, 2:].sum(-1), 1.0)
    assert ((0 <= lengths) & (lengths <= hps.max_seq_len)).all()
    # deterministic per key
    s5b, lb = sampler(params, jax.random.key(2), n, z, None,
                      jnp.float32(0.8))
    np.testing.assert_array_equal(s5, np.asarray(s5b))
    # shards draw independently: with distinct z, sketches differ
    assert not np.array_equal(s5[0], s5[2])
    # batch must be divisible by the axis size
    with pytest.raises(ValueError, match="divisible"):
        sampler(params, jax.random.key(2), 12,
                jax.random.normal(jax.random.key(3), (12, hps.z_size)),
                None, jnp.float32(0.8))


def test_unconditional_sample_wrapper():
    hps = tiny_hps(conditional=False)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    sketches, lengths = sample(model, params, hps, jax.random.key(1), n=3,
                               temperature=0.5, scale_factor=2.0)
    assert len(sketches) == 3
    for s3, n in zip(sketches, lengths):
        assert s3.shape == (n, 3)


def test_class_conditional_sample():
    hps = tiny_hps(num_classes=4)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    sketches, _ = sample(model, params, hps, jax.random.key(1), n=2,
                         labels=jnp.array([1, 3]))
    assert len(sketches) == 2


# -- svg --------------------------------------------------------------------


def test_svg_writer(tmp_path):
    s3 = np.array([[1, 0, 0], [0, 1, 1], [1, 1, 0], [-1, 2, 1]], np.float32)
    p = str(tmp_path / "out.svg")
    svg = strokes_to_svg(s3, path=p)
    assert svg.startswith("<svg") and svg.count("<path") == 2
    assert open(p).read() == svg


def test_svg_grid(tmp_path):
    s3 = np.array([[1, 0, 0], [0, 1, 1]], np.float32)
    svg = svg_grid([s3, s3, s3], cols=2, path=str(tmp_path / "g.svg"))
    assert svg.count("<path") == 3


# -- interpolation ----------------------------------------------------------


def test_slerp_endpoints_and_lerp():
    z0 = jnp.array([1.0, 0.0, 0.0])
    z1 = jnp.array([0.0, 1.0, 0.0])
    np.testing.assert_allclose(np.asarray(slerp(z0, z1, 0.0)), z0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(slerp(z0, z1, 1.0)), z1, atol=1e-5)
    mid = np.asarray(slerp(z0, z1, 0.5))
    np.testing.assert_allclose(np.linalg.norm(mid), 1.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lerp(z0, z1, 0.5)),
                               [0.5, 0.5, 0.0])


def test_interpolate_latents_shape():
    z0 = jnp.ones((6,))
    z1 = -jnp.ones((6,))
    zs = interpolate_latents(z0, z1, n=5)
    assert zs.shape == (5, 6)
    with pytest.raises(ValueError):
        interpolate_latents(z0, z1, mode="cubic")


def test_encode_mu_roundtrip():
    hps = tiny_hps()
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    strokes = np.zeros((2, hps.max_seq_len + 1, 5), np.float32)
    strokes[:, 0] = [0, 0, 1, 0, 0]
    strokes[:, 1:, 0] = 0.1
    strokes[:, 1:, 2] = 1.0
    strokes[:, -1, :] = [0, 0, 0, 0, 1]
    batch = {"strokes": strokes,
             "seq_len": np.array([10, 20], np.int32)}
    mu = encode_mu(model, params, batch)
    assert mu.shape == (2, hps.z_size)
    assert np.isfinite(np.asarray(mu)).all()
