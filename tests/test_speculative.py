"""Speculative decoding tests (ISSUE 18): the acceptance rule, the
draft+verify engine program, and its accounting.

The load-bearing contract: emitted rows are ALWAYS the verifier's own
draws — the draft decides only how MANY rows a dispatch commits — so a
speculative engine is bitwise the legacy engine for every draft, and
the accept/reject sequence is a pure function of (request key, draft
params, verifier params): deterministic, replayable from the trace
seed, invariant to slot count and batch composition. The rejection rule
is exact over the pen-state CDF (both samplers invert the SAME uniform)
plus ``draft_tol`` on the continuous GMM draw.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.models.draft import (DraftDecoder, draft_mixture_count,
                                         self_draft_params)
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.ops import mdn
from sketch_rnn_tpu.serve.engine import (Request, ServeEngine,
                                         sample_mixture_rows)

TINY = dict(batch_size=4, max_seq_len=48, enc_rnn_size=12,
            dec_rnn_size=16, z_size=6, num_mixture=3, serve_slots=4,
            serve_chunk=4, draft_rnn_size=16, draft_num_mixture=0)


# -- the acceptance rule, at the sampler level -------------------------------


def _pen_mp(pen_probs, n):
    """[n, ·] MixtureParams with the given pen distribution and a
    deterministic continuous head (one component, sigma ~ 0)."""
    p = jnp.log(jnp.asarray(pen_probs, jnp.float32))
    return mdn.MixtureParams(
        log_pi=jnp.zeros((n, 1)), mu1=jnp.zeros((n, 1)),
        mu2=jnp.zeros((n, 1)),
        log_s1=jnp.full((n, 1), -30.0), log_s2=jnp.full((n, 1), -30.0),
        rho=jnp.zeros((n, 1)),
        pen_logits=jnp.broadcast_to(p, (n, 3)))


def test_pen_rejection_is_exact_cdf_inversion():
    """The unit matrix behind 'exact rejection over the pen-state CDF':
    verifier and draft invert the SAME uniform u[1], so their pen
    one-hots disagree exactly when u[1] falls where the two CDFs
    bracket different categories — at temperature 1 with verifier pen
    probs (.5,.3,.2) vs draft (.3,.4,.3) that is u in (.3,.5] u
    (.7,.8], nowhere else."""
    grid = np.array([0.05, 0.15, 0.25, 0.31, 0.40, 0.49, 0.51, 0.60,
                     0.69, 0.71, 0.75, 0.79, 0.81, 0.90, 0.95],
                    np.float32)
    n = len(grid)
    u = jnp.stack([jnp.full((n,), 0.5), jnp.asarray(grid),
                   jnp.full((n,), 0.5), jnp.full((n,), 0.5)], axis=-1)
    temps = jnp.ones((n,))
    v = sample_mixture_rows(_pen_mp([0.5, 0.3, 0.2], n), u, temps)
    d = sample_mixture_rows(_pen_mp([0.3, 0.4, 0.3], n), u, temps)
    # both draws ARE the inverse CDF of their own pen distribution
    cat = lambda cdf: np.minimum(  # noqa: E731
        (grid[:, None] > np.asarray(cdf)[None, :]).sum(-1), 2)
    np.testing.assert_array_equal(np.argmax(np.asarray(v[:, 2:]), -1),
                                  cat([0.5, 0.8, 1.0]))
    np.testing.assert_array_equal(np.argmax(np.asarray(d[:, 2:]), -1),
                                  cat([0.3, 0.7, 1.0]))
    # the engine's pen_ok predicate == analytic CDF-disagreement set
    pen_ok = np.all(np.asarray(d[:, 2:] == v[:, 2:]), axis=-1)
    disagree = ((grid > 0.3) & (grid <= 0.5)) | ((grid > 0.7)
                                                 & (grid <= 0.8))
    np.testing.assert_array_equal(pen_ok, ~disagree)


def test_identical_pen_cdfs_always_accept():
    """Exactness: a draft matching the verifier's pen distribution can
    never be pen-rejected, for ANY uniform — the rule has no epsilon."""
    u = jax.random.uniform(jax.random.key(0), (256, 4))
    temps = jnp.full((256,), 0.7)
    probs = [0.25, 0.6, 0.15]
    v = sample_mixture_rows(_pen_mp(probs, 256), u, temps)
    d = sample_mixture_rows(_pen_mp(probs, 256), u, temps)
    np.testing.assert_array_equal(np.asarray(v[:, 2:]),
                                  np.asarray(d[:, 2:]))


# -- engine-level fixtures ---------------------------------------------------


@pytest.fixture(scope="module")
def setup():
    hps = HParams(**TINY).replace(dec_model="lstm", conditional=True)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    # pen suppression (the bench trick): request lengths are exactly
    # the drawn caps, so dispatch/step accounting is pure scheduling
    # math and the multi-dispatch geometry below is guaranteed
    params["out_b"] = params["out_b"].at[2].set(-1e9)
    dnoisy = self_draft_params(params, hps, key=jax.random.key(7),
                               noise=0.05)
    return hps, model, params, dnoisy


def _reqs(hps, caps):
    return [Request(key=jax.random.key(100 + i),
                    z=np.asarray(jax.random.normal(jax.random.key(i),
                                                   (hps.z_size,))),
                    temperature=0.8, max_len=int(c), uid=i)
            for i, c in enumerate(caps)]


CAPS = (18, 24, 7, 32, 12, 24)


def _by_uid(out):
    return {r.uid: r for r in out["results"]}


@pytest.fixture(scope="module")
def legacy_out(setup):
    hps, model, params, _ = setup
    return ServeEngine(model, hps, params).run(_reqs(hps, CAPS))


@pytest.fixture(scope="module")
def spec_eng(setup):
    hps, model, params, dnoisy = setup
    return ServeEngine(model, hps, params, draft_params=dnoisy,
                       draft_depth=4)


@pytest.fixture(scope="module")
def spec_out(spec_eng, setup):
    hps = setup[0]
    return spec_eng.run(_reqs(hps, CAPS))


# -- bitwise parity + mixed accept lengths -----------------------------------


def test_mixed_accept_lengths_bitwise_vs_legacy(setup, legacy_out,
                                                spec_out):
    """THE tentpole pin: a noisy draft yields partial acceptance —
    mixed accept lengths across slots and dispatches (the 32-cap
    request spans >= 4 dispatches at D=4) — and the emitted strokes
    are STILL bitwise the legacy engine's, per uid."""
    hps, model, params, dnoisy = setup
    legacy, spec = legacy_out, spec_out
    ref, got = _by_uid(legacy), _by_uid(spec)
    assert set(ref) == set(got)
    for u in ref:
        assert ref[u].steps == got[u].steps == CAPS[u]
        np.testing.assert_array_equal(ref[u].strokes5, got[u].strokes5)
    sp = spec["metrics"]["speculative"]
    assert sp["draft_depth"] == 4
    assert sp["draft_steps_proposed"] > 0
    # genuinely MIXED: neither all-accept nor all-reject
    assert 0 < sp["draft_steps_accepted"] < sp["draft_steps_proposed"]
    assert sp["acceptance_rate"] == round(
        sp["draft_steps_accepted"] / sp["draft_steps_proposed"], 4)
    assert spec["metrics"]["chunks"] >= 3
    # the legacy engine advances at most K rows per engaged K steps
    assert legacy["metrics"]["accepted_steps_per_device_step"] <= 1.0
    assert "speculative" not in legacy["metrics"]
    assert not ServeEngine(model, hps, params).speculative


def test_exact_self_draft_hits_the_commit_ceiling(setup, legacy_out):
    """noise=0 self-draft: every judged proposal accepted (acceptance
    1.0 bitwise — the accounting pin), every dispatch commits D+1 rows
    to a live slot, and the commit rate beats the legacy engine's."""
    hps, model, params, _ = setup
    dself = self_draft_params(params, hps)
    legacy = legacy_out
    spec = ServeEngine(model, hps, params, draft_params=dself,
                       draft_depth=4).run(_reqs(hps, CAPS))
    ref, got = _by_uid(legacy), _by_uid(spec)
    for u in ref:
        np.testing.assert_array_equal(ref[u].strokes5, got[u].strokes5)
    sp = spec["metrics"]["speculative"]
    assert sp["acceptance_rate"] == 1.0
    assert sp["draft_steps_accepted"] == sp["draft_steps_proposed"] > 0
    assert (spec["metrics"]["accepted_steps_per_device_step"]
            > legacy["metrics"]["accepted_steps_per_device_step"])
    assert (spec["metrics"]["device_steps"]
            < legacy["metrics"]["device_steps"])


# -- purity / determinism ----------------------------------------------------


def test_accept_schedule_is_per_slot_pure(setup, spec_eng, spec_out):
    """The accept length is a pure function of (request key, draft
    params, verifier params): strokes AND the aggregate accept/reject
    ledger are invariant to slot count and submission order — batch
    composition can never leak into a slot's accept schedule."""
    hps, model, params, dnoisy = setup
    outs = [
        spec_out,  # slots=4, submission order
        ServeEngine(model, hps, params, slots=2, draft_params=dnoisy,
                    draft_depth=4).run(_reqs(hps, CAPS)),
        spec_eng.run(_reqs(hps, CAPS)[::-1]),  # reversed order
    ]
    base = _by_uid(outs[0])
    sp0 = outs[0]["metrics"]["speculative"]
    for out in outs[1:]:
        got = _by_uid(out)
        assert set(got) == set(base)
        for u in base:
            np.testing.assert_array_equal(base[u].strokes5,
                                          got[u].strokes5)
        sp = out["metrics"]["speculative"]
        assert sp["draft_steps_proposed"] == sp0["draft_steps_proposed"]
        assert sp["draft_steps_accepted"] == sp0["draft_steps_accepted"]


def test_accept_reject_sequence_replays_from_trace_seed(setup, spec_eng,
                                                        spec_out):
    """ISSUE 18 acceptance: a rerun of the same engine AND a fresh
    request list rebuilt from the trace seed (the per-request keys)
    reproduce the accept/reject accounting and the strokes exactly."""
    hps = setup[0]
    out1 = spec_out
    out2 = spec_eng.run(_reqs(hps, CAPS))
    assert (out1["metrics"]["speculative"]
            == out2["metrics"]["speculative"])
    assert (out1["metrics"]["device_steps"]
            == out2["metrics"]["device_steps"])
    a, b = _by_uid(out1), _by_uid(out2)
    for u in a:
        np.testing.assert_array_equal(a[u].strokes5, b[u].strokes5)


# -- draft geometry + construction-time validation ---------------------------


def test_truncated_draft_head_geometry():
    hps = HParams(**TINY).replace(num_mixture=5, draft_num_mixture=2)
    assert draft_mixture_count(hps) == 2
    draft = DraftDecoder(hps)
    assert draft.out_dim == 6 * 2 + 3
    p = draft.init_params(jax.random.key(0))
    assert p["draft_out_w"].shape == (hps.draft_rnn_size, 15)
    assert all(k.startswith("draft_") for k in p)
    # inherit when unset
    assert draft_mixture_count(hps.replace(draft_num_mixture=0)) == 5


def test_self_draft_params_validation(setup):
    hps, model, params, _ = setup
    with pytest.raises(ValueError, match="dec_model"):
        self_draft_params(params, hps.replace(dec_model="layer_norm"))
    with pytest.raises(ValueError, match="draft_rnn_size"):
        self_draft_params(params, hps.replace(draft_rnn_size=8))
    with pytest.raises(ValueError, match="mixture"):
        self_draft_params(params, hps.replace(draft_num_mixture=2))
    with pytest.raises(ValueError, match="key"):
        self_draft_params(params, hps, noise=0.1)
    # noise=0 is the teacher's own weights, bitwise
    dp = self_draft_params(params, hps)
    np.testing.assert_array_equal(np.asarray(dp["draft_out_w"]),
                                  np.asarray(params["out_w"]))


def test_engine_refuses_bad_speculative_configs(setup):
    hps, model, params, dnoisy = setup
    with pytest.raises(ValueError, match="scan-only"):
        ServeEngine(model, hps, params, draft_params=dnoisy,
                    draft_depth=4, decode_kernel="pallas")
    with pytest.raises(ValueError, match="depth"):
        ServeEngine(model, hps, params, draft_params=dnoisy,
                    draft_depth=-1)
    # depth/tol default from hps when unset
    eng = ServeEngine(model, hps, params, draft_params=dnoisy)
    assert eng.draft_depth == hps.draft_depth
    assert eng.draft_tol == hps.draft_tol
