import numpy as np
import pytest

from sketch_rnn_tpu.config import get_default_hparams
from sketch_rnn_tpu.data import DataLoader, load_dataset, make_synthetic_strokes
from sketch_rnn_tpu.data.loader import write_synthetic_npz


@pytest.fixture
def hps():
    return get_default_hparams().replace(
        batch_size=8, max_seq_len=100, data_set=("synth.npz",))


def test_synthetic_generator_shapes():
    seqs, labels = make_synthetic_strokes(20, num_classes=4, seed=1)
    assert len(seqs) == 20 and labels.shape == (20,)
    assert set(np.unique(labels)).issubset(set(range(4)))
    for s in seqs:
        assert s.ndim == 2 and s.shape[1] == 3
        assert s[-1, 2] == 1.0  # sketch ends with a pen lift


def test_synthetic_generator_deterministic():
    a, la = make_synthetic_strokes(5, seed=7)
    b, lb = make_synthetic_strokes(5, seed=7)
    np.testing.assert_array_equal(la, lb)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_loader_batch_contract(hps):
    seqs, labels = make_synthetic_strokes(32, num_classes=3, max_len=90)
    dl = DataLoader(seqs, hps, labels=labels, augment=False)
    batch = dl.random_batch()
    st = batch["strokes"]
    assert st.shape == (8, hps.max_seq_len + 1, 5)
    assert st.dtype == np.float32
    # start token at t=0
    np.testing.assert_array_equal(st[:, 0, :],
                                  np.tile([0, 0, 1, 0, 0], (8, 1)))
    # one-hot pen states everywhere
    np.testing.assert_allclose(st[:, :, 2:].sum(-1), 1.0)
    # seq_len matches the first end-of-sketch row (offset by start token)
    for i in range(8):
        n = batch["seq_len"][i]
        assert st[i, n, 4] == 0.0 or n == 0
        assert np.all(st[i, n + 1:, 4] == 1.0)
    assert batch["labels"].shape == (8,)


def test_get_batch_covers_dataset_in_order(hps):
    seqs, labels = make_synthetic_strokes(24, num_classes=2)
    dl = DataLoader(seqs, hps, labels=labels)
    assert dl.num_batches == 3
    b0 = dl.get_batch(0)
    np.testing.assert_array_equal(b0["labels"], labels[:8])
    with pytest.raises(IndexError):
        dl.get_batch(3)


def test_eval_tail_wrap_fill(hps):
    # 19 examples, batch 8: 2 full batches + a wrap-filled tail batch
    seqs, labels = make_synthetic_strokes(19, num_classes=2)
    dl = DataLoader(seqs, hps, labels=labels)
    assert dl.num_batches == 2
    assert dl.num_eval_batches == 3
    tail = dl.get_batch(2)
    # rows 16..18 are the real tail; rows 3.. wrap to the corpus start
    np.testing.assert_array_equal(
        tail["labels"], np.concatenate([labels[16:19], labels[:5]]))
    with pytest.raises(IndexError):
        dl.get_batch(3)


def test_split_smaller_than_batch_still_evaluable(hps):
    # fewer examples than one batch: num_batches floors to 0 but the eval
    # sweep must still cover the split (VERDICT r1 'no silent empty eval')
    seqs, labels = make_synthetic_strokes(5, num_classes=1)
    dl = DataLoader(seqs, hps, labels=labels)
    assert dl.num_batches == 0
    assert dl.num_eval_batches == 1
    batch = dl.get_batch(0)
    assert batch["strokes"].shape[0] == hps.batch_size
    np.testing.assert_array_equal(
        batch["labels"], labels[np.arange(8) % 5])


def test_common_batch_count_across_hosts(hps):
    # 19 global examples striped over 2 hosts -> local sizes 10 and 9;
    # both hosts must report IDENTICAL batch counts (common length 9) or
    # an SPMD eval sweep deadlocks on mismatched collective launches
    seqs, labels = make_synthetic_strokes(19, num_classes=1)
    hps2 = hps.replace(batch_size=4)
    h0 = DataLoader(seqs[0::2], hps2, labels=labels[0::2],
                    global_size=19, num_hosts=2)
    h1 = DataLoader(seqs[1::2], hps2, labels=labels[1::2],
                    global_size=19, num_hosts=2)
    assert len(h0) == 10 and len(h1) == 9
    assert h0.num_batches == h1.num_batches == 2
    assert h0.num_eval_batches == h1.num_eval_batches == 3
    # the host holding the striping remainder still uses its 10th example
    tail = h0.get_batch(2)
    assert tail["strokes"].shape[0] == 4


def test_striping_remainder_covered_at_exact_batch_multiple(hps):
    # 17 global examples over 2 hosts, batch 4: common floor 8 is an exact
    # batch multiple, but host 0 holds 9 examples — the sweep length must
    # come from the ceil so its 9th example is still evaluated
    seqs, labels = make_synthetic_strokes(17, num_classes=1)
    hps2 = hps.replace(batch_size=4)
    h0 = DataLoader(seqs[0::2], hps2, labels=labels[0::2],
                    global_size=17, num_hosts=2)
    h1 = DataLoader(seqs[1::2], hps2, labels=labels[1::2],
                    global_size=17, num_hosts=2)
    assert h0.num_batches == h1.num_batches == 2
    assert h0.num_eval_batches == h1.num_eval_batches == 3
    tail = h0.get_batch(2)  # idx 8,0,1,2 over the 9-example local corpus
    np.testing.assert_array_equal(tail["labels"],
                                  labels[0::2][[8, 0, 1, 2]])


def test_empty_host_stripe_gives_zero_eval_batches(hps):
    # global corpus smaller than the host count: some stripe is empty, so
    # EVERY host must consistently report an un-evaluable split
    seqs, labels = make_synthetic_strokes(1, num_classes=1)
    full = DataLoader(seqs, hps, labels=labels, global_size=1, num_hosts=2)
    empty = DataLoader([], hps, global_size=1, num_hosts=2)
    assert full.num_eval_batches == empty.num_eval_batches == 0


def test_load_dataset_end_to_end(tmp_path, hps):
    write_synthetic_npz(str(tmp_path / "synth.npz"), num_train=40,
                        num_valid=10, num_test=10, max_len=90)
    train, valid, test, scale = load_dataset(hps, data_dir=str(tmp_path))
    assert scale > 0
    # train split normalized to unit offset std
    np.testing.assert_allclose(
        train.calculate_normalizing_scale_factor(), 1.0, rtol=1e-5)
    assert len(train) == 40 and len(valid) == 10 and len(test) == 10
    assert train.augment and not valid.augment


def test_load_dataset_multi_category_labels(tmp_path):
    hps = get_default_hparams().replace(
        batch_size=4, max_seq_len=100, data_set=("a.npz", "b.npz"))
    for name in ("a.npz", "b.npz"):
        write_synthetic_npz(str(tmp_path / name), num_train=10, num_valid=4,
                            num_test=4, max_len=90)
    train, _, _, _ = load_dataset(hps, data_dir=str(tmp_path))
    assert set(np.unique(train.labels)) == {0, 1}


def test_load_dataset_host_sharding(tmp_path, hps):
    write_synthetic_npz(str(tmp_path / "synth.npz"), num_train=40,
                        num_valid=10, num_test=10, max_len=90)
    t0, _, _, _ = load_dataset(hps, data_dir=str(tmp_path),
                               host_id=0, num_hosts=2)
    t1, _, _, _ = load_dataset(hps, data_dir=str(tmp_path),
                               host_id=1, num_hosts=2)
    assert len(t0) == 20 and len(t1) == 20


def test_missing_file_raises(hps, tmp_path):
    with pytest.raises(FileNotFoundError):
        load_dataset(hps, data_dir=str(tmp_path))


def test_filter_by_label():
    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes

    hps = HParams(batch_size=4, max_seq_len=64, num_classes=3)
    seqs, labels = make_synthetic_strokes(30, num_classes=3, min_len=8,
                                          max_len=60, seed=4)
    dl = DataLoader(seqs, hps, labels=labels)
    total = 0
    for c in range(3):
        sub = dl.filter_by_label(c)
        total += len(sub)
        assert np.all(sub.labels == c)
        assert all(np.shares_memory(a, b) for a, b in
                   zip(sub.strokes, [seqs[i] for i in
                                     np.flatnonzero(labels == c)]))
    assert total == len(dl)


def test_filter_by_label_rejects_host_striped_loader():
    """ADVICE r2: a striped loader's per-class batch count differs across
    hosts, so filtering one must raise at the API layer, not deadlock the
    SPMD sweep later."""
    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes

    hps = HParams(batch_size=4, max_seq_len=64, num_classes=3)
    seqs, labels = make_synthetic_strokes(30, num_classes=3, min_len=8,
                                          max_len=60, seed=4)
    dl = DataLoader(seqs[0::2], hps, labels=labels[0::2],
                    global_size=30, num_hosts=2)
    with pytest.raises(RuntimeError, match="host-striped"):
        dl.filter_by_label(0)


def test_random_batch_rejects_nonpositive_int16_scale(hps):
    """Direct random_batch callers bypass the prefetch guard; a scale
    of 0 would quantize every offset to zero AND ship transfer_scale=0
    (device-side divide-by-zero in the dequant) via the numpy fallback
    (ADVICE r4)."""
    seqs, labels = make_synthetic_strokes(16, max_len=90)
    dl = DataLoader(seqs, hps, labels=labels, augment=False)
    for bad in (0.0, -2.5):
        with pytest.raises(ValueError, match="int16_scale"):
            dl.random_batch(int16_scale=bad)


def test_augment_seed_drawn_once_per_batch(hps, monkeypatch):
    """The augmentation stream must not depend on which native
    assemblers are available: the int16 path draws ONE batch seed and
    reuses it for the float retry, so a loader's RNG state after a
    batch is identical whether or not the native i16 assembler exists
    (ADVICE r4)."""
    from sketch_rnn_tpu.data import native_batcher as NB

    seqs, labels = make_synthetic_strokes(16, max_len=90)

    def state_after_batch(i16_available):
        dl = DataLoader([s.copy() for s in seqs], hps, labels=labels,
                        augment=True, seed=123)
        dl.normalize(0.1)  # big scale_factor-normalized ints not needed
        if not i16_available:
            monkeypatch.setattr(NB, "assemble_batch_aug_i16",
                                lambda *a, **k: None)
        dl.random_batch(int16_scale=10.0)
        return dl.rng.integers(0, 2 ** 63)

    assert state_after_batch(True) == state_after_batch(False)


def test_integer_grid_corpus_is_integer_origin(hps):
    """VERDICT r4 #2: the integer-grid synthetic corpus must behave
    like QuickDraw — integer offsets, normalization scale factor in
    the int16-accepted range (> 5), and no cumulative drift (deltas
    sum back to the snapped absolute path)."""
    from sketch_rnn_tpu.data.loader import synthetic_loader
    from sketch_rnn_tpu.data import strokes as S

    seqs, _ = make_synthetic_strokes(64, num_classes=3, seed=3,
                                     integer_grid=255.0)
    for s in seqs:
        np.testing.assert_array_equal(s[:, :2], np.rint(s[:, :2]))
    scale = S.calculate_normalizing_scale_factor(seqs)
    assert scale > 5.0, scale

    loader, lscale = synthetic_loader(hps, 64, seed=3,
                                      integer_grid=255.0)
    assert lscale > 5.0  # single-class hps corpus differs from above
    # quantizing a normalized batch back by the scale factor recovers
    # exact integers: the int16 transfer invariant — check the VALUES
    # round-trip (dequant == an f32 batch of the same draw), not just
    # the dtype
    b = loader.random_batch(int16_scale=lscale)
    assert b["strokes"].dtype == np.int16
    ref_loader, _ = synthetic_loader(hps, 64, seed=3, integer_grid=255.0)
    bf = ref_loader.random_batch()
    np.testing.assert_array_equal(
        b["strokes"][..., :2].astype(np.float32) / np.float32(lscale),
        bf["strokes"][..., :2])

    # default stays the legacy float corpus
    legacy, _ = make_synthetic_strokes(8, seed=3)
    assert not np.allclose(legacy[0][:, :2], np.rint(legacy[0][:, :2]))


def test_integer_grid_int16_feed_bitwise_equals_f32(hps):
    """On the integer corpus the int16 feed must reproduce the f32
    feed bit-for-bit after dequantization (augment off)."""
    from sketch_rnn_tpu.data.loader import synthetic_loader

    a, scale = synthetic_loader(hps, 32, seed=5, integer_grid=255.0)
    b, _ = synthetic_loader(hps, 32, seed=5, integer_grid=255.0)
    bq = a.random_batch(int16_scale=scale)
    bf = b.random_batch()
    dq = bq["strokes"][..., :2].astype(np.float32) / scale
    np.testing.assert_array_equal(dq, bf["strokes"][..., :2])
    np.testing.assert_array_equal(
        bq["strokes"][..., 2:].astype(np.float32), bf["strokes"][..., 2:])


def test_purify_drops_empty_records_without_flagging_corrupt():
    """ISSUE 10 review fix: an empty record is DROPPED (the
    pre-hardening filter contract), never reported as corrupt — only
    malformed non-empty records fail."""
    from sketch_rnn_tpu.data.loader import _purify

    good = np.ones((4, 3), np.float32)
    out = _purify([good, np.zeros((0,)), [], good], 10)
    assert len(out) == 2
    with pytest.raises(ValueError, match="record 1"):
        _purify([good, np.ones((4, 7), np.float32)], 10, source="x")
