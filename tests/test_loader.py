import numpy as np
import pytest

from sketch_rnn_tpu.config import get_default_hparams
from sketch_rnn_tpu.data import DataLoader, load_dataset, make_synthetic_strokes
from sketch_rnn_tpu.data.loader import write_synthetic_npz


@pytest.fixture
def hps():
    return get_default_hparams().replace(
        batch_size=8, max_seq_len=100, data_set=("synth.npz",))


def test_synthetic_generator_shapes():
    seqs, labels = make_synthetic_strokes(20, num_classes=4, seed=1)
    assert len(seqs) == 20 and labels.shape == (20,)
    assert set(np.unique(labels)).issubset(set(range(4)))
    for s in seqs:
        assert s.ndim == 2 and s.shape[1] == 3
        assert s[-1, 2] == 1.0  # sketch ends with a pen lift


def test_synthetic_generator_deterministic():
    a, la = make_synthetic_strokes(5, seed=7)
    b, lb = make_synthetic_strokes(5, seed=7)
    np.testing.assert_array_equal(la, lb)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_loader_batch_contract(hps):
    seqs, labels = make_synthetic_strokes(32, num_classes=3, max_len=90)
    dl = DataLoader(seqs, hps, labels=labels, augment=False)
    batch = dl.random_batch()
    st = batch["strokes"]
    assert st.shape == (8, hps.max_seq_len + 1, 5)
    assert st.dtype == np.float32
    # start token at t=0
    np.testing.assert_array_equal(st[:, 0, :],
                                  np.tile([0, 0, 1, 0, 0], (8, 1)))
    # one-hot pen states everywhere
    np.testing.assert_allclose(st[:, :, 2:].sum(-1), 1.0)
    # seq_len matches the first end-of-sketch row (offset by start token)
    for i in range(8):
        n = batch["seq_len"][i]
        assert st[i, n, 4] == 0.0 or n == 0
        assert np.all(st[i, n + 1:, 4] == 1.0)
    assert batch["labels"].shape == (8,)


def test_get_batch_covers_dataset_in_order(hps):
    seqs, labels = make_synthetic_strokes(24, num_classes=2)
    dl = DataLoader(seqs, hps, labels=labels)
    assert dl.num_batches == 3
    b0 = dl.get_batch(0)
    np.testing.assert_array_equal(b0["labels"], labels[:8])
    with pytest.raises(IndexError):
        dl.get_batch(3)


def test_load_dataset_end_to_end(tmp_path, hps):
    write_synthetic_npz(str(tmp_path / "synth.npz"), num_train=40,
                        num_valid=10, num_test=10, max_len=90)
    train, valid, test, scale = load_dataset(hps, data_dir=str(tmp_path))
    assert scale > 0
    # train split normalized to unit offset std
    np.testing.assert_allclose(
        train.calculate_normalizing_scale_factor(), 1.0, rtol=1e-5)
    assert len(train) == 40 and len(valid) == 10 and len(test) == 10
    assert train.augment and not valid.augment


def test_load_dataset_multi_category_labels(tmp_path):
    hps = get_default_hparams().replace(
        batch_size=4, max_seq_len=100, data_set=("a.npz", "b.npz"))
    for name in ("a.npz", "b.npz"):
        write_synthetic_npz(str(tmp_path / name), num_train=10, num_valid=4,
                            num_test=4, max_len=90)
    train, _, _, _ = load_dataset(hps, data_dir=str(tmp_path))
    assert set(np.unique(train.labels)) == {0, 1}


def test_load_dataset_host_sharding(tmp_path, hps):
    write_synthetic_npz(str(tmp_path / "synth.npz"), num_train=40,
                        num_valid=10, num_test=10, max_len=90)
    t0, _, _, _ = load_dataset(hps, data_dir=str(tmp_path),
                               host_id=0, num_hosts=2)
    t1, _, _, _ = load_dataset(hps, data_dir=str(tmp_path),
                               host_id=1, num_hosts=2)
    assert len(t0) == 20 and len(t1) == 20


def test_missing_file_raises(hps, tmp_path):
    with pytest.raises(FileNotFoundError):
        load_dataset(hps, data_dir=str(tmp_path))
