"""Pallas fused-LSTM kernel vs the lax.scan reference (interpret mode).

Forward values AND custom-VJP gradients must match the autodiff of the
scan path (SURVEY.md §4: golden-value testing of the performance core).
Shapes use (8, 128)-aligned dims as on real hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.ops.cells import LSTMCell
from sketch_rnn_tpu.ops.pallas_lstm import lstm_seq
from sketch_rnn_tpu.ops.rnn import make_dropout_masks, run_rnn

T, B, H, D = 6, 8, 128, 16


def _setup(seed=0):
    cell = LSTMCell(H)
    params = cell.init_params(jax.random.key(seed), D)
    xs = jax.random.normal(jax.random.key(seed + 1), (T, B, D))
    xp = cell.precompute_inputs(params, xs)
    c0 = jnp.zeros((B, H))
    h0 = jnp.zeros((B, H))
    return cell, params, xs, xp, c0, h0


@pytest.mark.parametrize("use_mask", [False, True])
def test_forward_matches_scan(use_mask):
    cell, params, xs, xp, c0, h0 = _setup()
    masks = (make_dropout_masks(jax.random.key(9), 0.8, T, B, H)
             if use_mask else None)
    hs_ref_out = run_rnn(cell, params, xs, rdrop_masks=masks)[1]
    hs, (cT, hT) = lstm_seq(xp, params["wh"], c0, h0, 1.0, masks)
    np.testing.assert_allclose(np.asarray(hs), np.asarray(hs_ref_out),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(hT), np.asarray(hs_ref_out[-1]),
                               rtol=2e-5, atol=2e-6)


def test_forward_nonzero_carry():
    cell, params, xs, xp, _, _ = _setup()
    c0 = jax.random.normal(jax.random.key(5), (B, H))
    h0 = jax.random.normal(jax.random.key(6), (B, H))
    final, hs_scan = run_rnn(cell, params, xs, carry0=(c0, h0))
    hs, (cT, hT) = lstm_seq(xp, params["wh"], c0, h0)
    np.testing.assert_allclose(np.asarray(hs), np.asarray(hs_scan),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(cT), np.asarray(final[0]),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("use_mask", [False, True])
def test_gradients_match_scan(use_mask):
    cell, params, xs, xp, c0, h0 = _setup()
    masks = (make_dropout_masks(jax.random.key(9), 0.8, T, B, H)
             if use_mask else None)
    wtgt = jax.random.normal(jax.random.key(7), (T, B, H)) * 0.1

    def loss_pallas(xp_, wh_, c0_, h0_):
        hs, (cT, hT) = lstm_seq(xp_, wh_, c0_, h0_, 1.0, masks)
        return jnp.sum(hs * wtgt) + jnp.sum(cT) + 0.5 * jnp.sum(hT)

    def loss_scan(xp_, wh_, c0_, h0_):
        p = dict(params, wh=wh_)

        def step(carry, inp):
            xpt, m = inp
            carry, h = cell.step_pre(p, carry, xpt,
                                     rdrop_mask=m if use_mask else None)
            return carry, h
        m_in = masks if use_mask else jnp.zeros((T, 0))
        (cT, hT), hs = jax.lax.scan(step, (c0_, h0_), (xp_, m_in))
        return jnp.sum(hs * wtgt) + jnp.sum(cT) + 0.5 * jnp.sum(hT)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2, 3))(xp, params["wh"],
                                                     c0, h0)
    gs = jax.grad(loss_scan, argnums=(0, 1, 2, 3))(xp, params["wh"],
                                                   c0, h0)
    names = ["dxp", "dwh", "dc0", "dh0"]
    for n, a, b in zip(names, gp, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=n)


def test_value_and_grad_under_jit():
    _, params, _, xp, c0, h0 = _setup()

    @jax.jit
    def f(xp_, wh_):
        hs, _ = lstm_seq(xp_, wh_, c0, h0)
        return jnp.mean(hs ** 2)

    v, g = jax.value_and_grad(f, argnums=1)(xp, params["wh"])
    assert np.isfinite(float(v))
    assert np.isfinite(np.asarray(g)).all()


def test_masks_traced_under_jit():
    """Masks drawn from a key INSIDE jit (the realistic training usage)
    must work — they are a regular operand, not a static argnum."""
    cell, params, xs, xp, c0, h0 = _setup()

    @jax.jit
    def f(key, wh):
        masks = make_dropout_masks(key, 0.8, T, B, H)

        def loss(wh_):
            hs, _ = lstm_seq(xp, wh_, c0, h0, 1.0, masks)
            return jnp.mean(hs ** 2)
        return jax.value_and_grad(loss)(wh)

    v, g = f(jax.random.key(3), params["wh"])
    assert np.isfinite(float(v))
    assert np.isfinite(np.asarray(g)).all()
