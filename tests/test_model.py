"""SketchRNN model tests: shapes, jit, grads, conditioning modes."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sketch_rnn_tpu.config import get_default_hparams
from sketch_rnn_tpu.data import DataLoader, make_synthetic_strokes
from sketch_rnn_tpu.models import SketchRNN


def tiny_hps(**kw):
    base = dict(batch_size=4, max_seq_len=32, enc_rnn_size=16,
                dec_rnn_size=24, z_size=8, num_mixture=3,
                hyper_rnn_size=12, hyper_embed_size=4)
    base.update(kw)
    return get_default_hparams().replace(**base)


def make_batch(hps, num_classes=1, seed=0):
    seqs, labels = make_synthetic_strokes(
        max(8, hps.batch_size), num_classes=num_classes, min_len=8,
        max_len=hps.max_seq_len - 2, seed=seed)
    dl = DataLoader(seqs, hps, labels=labels)
    b = dl.random_batch()
    return {k: jnp.asarray(v) for k, v in b.items()}


def finite(tree):
    return all(jax.tree.leaves(
        jax.tree.map(lambda a: bool(np.all(np.isfinite(a))), tree)))


@pytest.mark.parametrize("dec_model", ["lstm", "layer_norm", "hyper"])
@pytest.mark.slow
def test_loss_and_grads_all_cells(dec_model):
    hps = tiny_hps(dec_model=dec_model)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    batch = make_batch(hps)

    @jax.jit
    def loss_fn(p, batch, key):
        return model.loss(p, batch, key, kl_weight=jnp.float32(0.5))

    (total, metrics), grads = jax.value_and_grad(
        lambda p: loss_fn(p, batch, jax.random.key(1)), has_aux=True)(params)
    assert np.isfinite(float(total))
    assert finite(grads)
    assert float(metrics["kl_raw"]) >= 0.0
    assert float(metrics["recon"]) == pytest.approx(
        float(metrics["offset_nll"]) + float(metrics["pen_ce"]), rel=1e-5)


def test_unconditional_mode_has_no_encoder():
    hps = tiny_hps(conditional=False)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    assert "enc_fwd" not in params and "dec_init_w" not in params
    total, metrics = model.loss(params, make_batch(hps), jax.random.key(1),
                                kl_weight=jnp.float32(0.5))
    assert float(metrics["kl_raw"]) == 0.0
    assert float(metrics["kl"]) == 0.0
    # no latent -> loss is pure reconstruction (no kl_tolerance constant)
    np.testing.assert_allclose(float(total), float(metrics["recon"]),
                               rtol=1e-5)


def test_eval_is_deterministic_train_is_not():
    hps = tiny_hps()
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    batch = make_batch(hps)
    e1, _ = model.loss(params, batch, jax.random.key(5), jnp.float32(1.0),
                       train=False)
    e2, _ = model.loss(params, batch, jax.random.key(5), jnp.float32(1.0),
                       train=False)
    np.testing.assert_allclose(float(e1), float(e2), rtol=1e-6)
    t1, _ = model.loss(params, batch, jax.random.key(5), jnp.float32(1.0),
                       train=True)
    t2, _ = model.loss(params, batch, jax.random.key(6), jnp.float32(1.0),
                       train=True)
    assert float(t1) != float(t2)  # dropout + z noise differ across keys


def test_class_conditional_embedding_used():
    hps = tiny_hps(num_classes=3)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    assert params["class_embed"].shape == (3, hps.class_embed_size)
    batch = make_batch(hps, num_classes=3)
    l0, _ = model.loss(params, batch, jax.random.key(1), jnp.float32(0.5),
                       train=False)
    batch2 = dict(batch)
    batch2["labels"] = (batch["labels"] + 1) % 3
    l1, _ = model.loss(params, batch2, jax.random.key(1), jnp.float32(0.5),
                       train=False)
    assert float(l0) != float(l1)


def test_encoder_ignores_padding():
    """Changing strokes after seq_len must not change mu/presig."""
    hps = tiny_hps()
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    batch = make_batch(hps)
    x = jnp.transpose(batch["strokes"], (1, 0, 2))[1:]
    mu1, ps1 = model.encode(params, x, batch["seq_len"])
    x_messed = np.asarray(x).copy()
    for i in range(x.shape[1]):
        n = int(batch["seq_len"][i])
        x_messed[n:, i, 0:2] = 99.0  # scribble on the padding
    mu2, ps2 = model.encode(params, jnp.asarray(x_messed), batch["seq_len"])
    np.testing.assert_allclose(np.asarray(mu1), np.asarray(mu2), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ps1), np.asarray(ps2), atol=1e-5)


def test_decoder_initial_carry_from_z():
    hps = tiny_hps(dec_model="hyper")
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    z = jnp.ones((4, hps.z_size))
    carry = model.decoder_initial_carry(params, z, 4)
    (c, h), (hc, hh) = carry
    assert c.shape == (4, hps.dec_rnn_size)
    assert hc.shape == (4, hps.hyper_rnn_size)
    # distinct z -> distinct initial state
    carry2 = model.decoder_initial_carry(params, 2.0 * z, 4)
    assert not np.allclose(np.asarray(carry[0][0]), np.asarray(carry2[0][0]))


def test_loss_accepts_bf16_strokes():
    """hps.transfer_dtype feeds bf16 strokes; the model must upcast on
    entry so the loss stays f32 and close to the f32-fed value."""
    import jax.numpy as jnp

    hps = tiny_hps()
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    batch = make_batch(hps)
    key = jax.random.key(1)
    t32, m32 = model.loss(params, batch, key, kl_weight=0.5, train=False)
    b16 = dict(batch)
    b16["strokes"] = batch["strokes"].astype(jnp.bfloat16)
    t16, m16 = model.loss(params, b16, key, kl_weight=0.5, train=False)
    assert t16.dtype == jnp.float32
    assert float(t16) == pytest.approx(float(t32), rel=2e-2)


def test_early_reversal_gather_bitwise_equals_device_gather():
    """_forward gathers the encoder's length-aware-reversed inputs on
    the compact batch-major raw strokes (cheap layout); the result must
    be bitwise what the in-encode time-major device gather produces,
    for both exact transfer modes (the gather commutes with
    dequant/upcast/transpose)."""
    import numpy as np

    from sketch_rnn_tpu.data.loader import synthetic_loader
    from sketch_rnn_tpu.ops.rnn import length_reverse_indices

    for transfer in ("float32", "int16"):
        hps = tiny_hps().replace(conditional=True,
                                 use_recurrent_dropout=False)
        model = SketchRNN(hps)
        loader, scale = synthetic_loader(hps, 2 * hps.batch_size, seed=1,
                                         integer_grid=255.0)
        b = loader.random_batch(
            int16_scale=scale if transfer == "int16" else None)
        params = model.init_params(jax.random.key(0))
        kenc = jax.random.key(2)
        raw = jnp.asarray(b["strokes"])
        seq_len = jnp.asarray(b["seq_len"])
        if raw.dtype == jnp.int16:
            sc = jnp.asarray(b["transfer_scale"], jnp.float32)
            f = raw.astype(jnp.float32)
            bm = jnp.concatenate(
                [f[..., :2] / sc[:, None, None], f[..., 2:]], -1)
        else:
            bm = raw
        x_target = jnp.transpose(bm, (1, 0, 2)).astype(jnp.float32)[1:]
        # device-gather path (x_rev_tm=None)
        mu_dev, ps_dev = model.encode(params, x_target, seq_len, key=kenc,
                                      train=False)
        # early batch-major raw gather (what _forward does)
        rev_bm = length_reverse_indices(raw.shape[1] - 1, seq_len).T
        raw_rev = jnp.take_along_axis(raw[:, 1:], rev_bm[:, :, None],
                                      axis=1)
        if raw.dtype == jnp.int16:
            f = raw_rev.astype(jnp.float32)
            raw_rev = jnp.concatenate(
                [f[..., :2] / sc[:, None, None], f[..., 2:]], -1)
        x_rev_tm = jnp.transpose(raw_rev, (1, 0, 2)).astype(jnp.float32)
        mu_e, ps_e = model.encode(params, x_target, seq_len, key=kenc,
                                  train=False, x_rev_tm=x_rev_tm)
        np.testing.assert_array_equal(np.asarray(mu_dev), np.asarray(mu_e))
        np.testing.assert_array_equal(np.asarray(ps_dev), np.asarray(ps_e))


def test_bidirectional_rejects_xs_rev_without_seq_len():
    """The no-seq_len path runs a plain reverse scan and would silently
    ignore a caller's length-aware-reversed inputs; it must refuse."""
    from sketch_rnn_tpu.ops.cells import LSTMCell
    from sketch_rnn_tpu.ops.rnn import bidirectional_rnn

    cell_f, cell_b = LSTMCell(8), LSTMCell(8)
    pf = cell_f.init_params(jax.random.key(0), 5)
    pb = cell_b.init_params(jax.random.key(1), 5)
    xs = jax.random.normal(jax.random.key(2), (4, 2, 5))
    with pytest.raises(ValueError, match="xs_rev"):
        bidirectional_rnn(cell_f, cell_b, pf, pb, xs, seq_len=None,
                          xs_rev=jnp.flip(xs, axis=0))
