"""Tests for bench.py's recorded-number policy (VERDICT r2 #1).

The bench's trial loop must not honor its no-improvement early-stop in a
uniformly slow tunnel window — the history-informed plausibility gate is
the mechanism, so the history lookup and the input validation are the
parts worth pinning. The loop itself runs on the real chip only (the
driver invokes bench.py directly); here we test the pure pieces.
"""

import json

import pytest

import bench


def _write_hist(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


_BASE = {
    "kind": "train", "dec_model": "layer_norm", "batch_size": 4096,
    "seq_len": 250, "dtype": "bfloat16", "remat": True, "fused_rnn": True,
    "resid_dtype": "bfloat16", "device_kind": "TPU v5 lite", "n_chips": 1,
    "prefetch_depth": 2,
}


def test_hist_best_pools_across_feed_knobs(tmp_path, monkeypatch):
    """K=1 and K=5 rows of the same physical config share one best: the
    retry target is what the chip can sustain, not how it was fed."""
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    _write_hist(hist, [
        {**_BASE, "steps_per_call": 1, "transfer_dtype": "float32",
         "strokes_per_sec_per_chip": 4.0e6},
        {**_BASE, "steps_per_call": 5, "transfer_dtype": "bfloat16",
         "strokes_per_sec_per_chip": 3.6e6},
        # different physical config must NOT pool in
        {**_BASE, "resid_dtype": "float32",
         "strokes_per_sec_per_chip": 9.9e6},
        {**_BASE, "dec_model": "lstm",
         "strokes_per_sec_per_chip": 9.9e6},
        # a faster accelerator generation must NOT set the target
        {**_BASE, "device_kind": "TPU v6 lite",
         "strokes_per_sec_per_chip": 9.9e6},
        # same global batch on a different chip count is a different
        # per-chip workload — must NOT pool
        {**_BASE, "n_chips": 8, "strokes_per_sec_per_chip": 9.9e6},
        # synchronous-feed (depth 0) rows are a different measurement —
        # and conversely a depth-0 run must not be gated on depth-2 bests
        {**_BASE, "prefetch_depth": 0, "strokes_per_sec_per_chip": 9.9e6},
        # sampler rows and junk lines are skipped
        {"kind": "sampler", "batch_size": 1, "sketches_per_sec": 77},
    ])
    with open(hist, "a") as f:
        f.write("not json\n")
    monkeypatch.setattr(bench, "_hist_path", lambda: str(hist))
    best = bench._hist_best_strokes("layer_norm", 4096, 250, "bfloat16",
                                    True, True, "bfloat16", "TPU v5 lite", 1, 2)
    assert best == 4.0e6


def test_hist_best_missing_file_and_no_match(tmp_path, monkeypatch):
    monkeypatch.setattr(
        bench, "_hist_path", lambda: str(tmp_path / "absent.jsonl"))
    assert bench._hist_best_strokes("layer_norm", 4096, 250, "bfloat16",
                                    True, True, "bfloat16",
                                    "TPU v5 lite", 1, 2) is None
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    _write_hist(hist, [{**_BASE, "strokes_per_sec_per_chip": 1.0}])
    monkeypatch.setattr(bench, "_hist_path", lambda: str(hist))
    assert bench._hist_best_strokes("hyper", 4096, 250, "bfloat16",
                                    True, True, "bfloat16",
                                    "TPU v5 lite", 1, 2) is None


def test_bench_train_rejects_non_divisible_steps():
    """ADVICE r2: steps % steps_per_call != 0 must raise, not silently
    run fewer optimizer steps while computing throughput over `steps`."""
    with pytest.raises(ValueError, match="positive multiple"):
        bench.bench_train("layer_norm", steps=7, batch_per_chip=64,
                          seq_len=16, dtype="float32", remat=False,
                          prefetch_depth=0, steps_per_call=5)
    with pytest.raises(ValueError, match="positive multiple"):
        bench.bench_train("layer_norm", steps=10, batch_per_chip=64,
                          seq_len=16, dtype="float32", remat=False,
                          prefetch_depth=0, steps_per_call=0)
