"""Tests for bench.py's recorded-number policy (VERDICT r2 #1).

The bench's trial loop must not honor its no-improvement early-stop in a
uniformly slow tunnel window — the history-informed plausibility gate is
the mechanism, so the history lookup and the input validation are the
parts worth pinning. The loop itself runs on the real chip only (the
driver invokes bench.py directly); here we test the pure pieces.
"""

import json

import pytest

import bench


def _write_hist(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


_BASE = {
    "kind": "train", "dec_model": "layer_norm", "batch_size": 4096,
    "seq_len": 250, "dtype": "bfloat16", "remat": True, "fused_rnn": True,
    "resid_dtype": "bfloat16", "device_kind": "TPU v5 lite", "n_chips": 1,
    "prefetch_depth": 2, "steps": 25,
}


def test_hist_best_pools_across_feed_knobs(tmp_path, monkeypatch):
    """K=1 and K=5 rows of the same physical config share one best: the
    retry target is what the chip can sustain, not how it was fed."""
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    _write_hist(hist, [
        {**_BASE, "steps_per_call": 1, "transfer_dtype": "float32",
         "strokes_per_sec_per_chip": 4.0e6},
        {**_BASE, "steps_per_call": 5, "transfer_dtype": "bfloat16",
         "strokes_per_sec_per_chip": 3.6e6},
        # different physical config must NOT pool in
        {**_BASE, "resid_dtype": "float32",
         "strokes_per_sec_per_chip": 9.9e6},
        {**_BASE, "dec_model": "lstm",
         "strokes_per_sec_per_chip": 9.9e6},
        # a faster accelerator generation must NOT set the target
        {**_BASE, "device_kind": "TPU v6 lite",
         "strokes_per_sec_per_chip": 9.9e6},
        # same global batch on a different chip count is a different
        # per-chip workload — must NOT pool
        {**_BASE, "n_chips": 8, "strokes_per_sec_per_chip": 9.9e6},
        # synchronous-feed (depth 0) rows are a different measurement —
        # and conversely a depth-0 run must not be gated on depth-2 bests
        {**_BASE, "prefetch_depth": 0, "strokes_per_sec_per_chip": 9.9e6},
        # sampler rows and junk lines are skipped
        {"kind": "sampler", "batch_size": 1, "sketches_per_sec": 77},
    ])
    with open(hist, "a") as f:
        f.write("not json\n")
    monkeypatch.setattr(bench, "_hist_path", lambda: str(hist))
    best = bench._hist_best_strokes(
        "layer_norm", 4096, 250, "bfloat16", True, True, "bfloat16",
        "TPU v5 lite", 1, 2, 25)
    assert best == 4.0e6


def test_hist_best_keyed_by_steps(tmp_path, monkeypatch):
    """VERDICT r4 #7 (by construction): 50-step rows let less host-
    assembly cost escape the timed window than 25-step rows, so the
    plausibility gate must only compare same-``steps`` history."""
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    _write_hist(hist, [
        {**_BASE, "steps": 25, "strokes_per_sec_per_chip": 4.0e6},
        {**_BASE, "steps": 50, "strokes_per_sec_per_chip": 9.9e6},
    ])
    monkeypatch.setattr(bench, "_hist_path", lambda: str(hist))
    args = ("layer_norm", 4096, 250, "bfloat16", True, True,
            "bfloat16", "TPU v5 lite", 1, 2)
    assert bench._hist_best_strokes(*args, 25) == 4.0e6
    assert bench._hist_best_strokes(*args, 50) == 9.9e6
    assert bench._hist_best_strokes(*args, 15) is None


def test_bench_summary_keys_by_steps():
    """bench_summary must not report a 50-step best as the record for
    the 25-step configuration."""
    from scripts.bench_summary import key_of

    assert key_of({**_BASE, "steps": 25}) != key_of({**_BASE, "steps": 50})
    # same steps but a differing non-key field must still pool together
    assert key_of({**_BASE, "steps": 25}) == key_of(
        {**_BASE, "steps": 25, "plausible": False, "time_s": 9.9})


def test_hist_best_legacy_rows_default_resid_dtype(tmp_path, monkeypatch):
    """Rows predating the resid_dtype knob ran the then-default float32
    residuals; they must still arm the plausibility gate for float32
    queries and must NOT pool into bfloat16 ones (ADVICE r3)."""
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    legacy = {k: v for k, v in _BASE.items() if k != "resid_dtype"}
    _write_hist(hist, [{**legacy, "strokes_per_sec_per_chip": 3.0e6}])
    monkeypatch.setattr(bench, "_hist_path", lambda: str(hist))
    args = ("layer_norm", 4096, 250, "bfloat16", True, True)
    tail = ("TPU v5 lite", 1, 2, 25)
    assert bench._hist_best_strokes(*args, "float32", *tail) == 3.0e6
    assert bench._hist_best_strokes(*args, "bfloat16", *tail) is None


def test_hist_best_ignores_resid_dtype_when_not_fused(tmp_path,
                                                      monkeypatch):
    """resid_dtype only affects the fused kernels; on the scan path a
    row must pool regardless of its (inert) resid label — else the gate
    silently disarms for non-fused configs (r4 review finding)."""
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    legacy = {k: v for k, v in _BASE.items() if k != "resid_dtype"}
    _write_hist(hist, [{**legacy, "fused_rnn": False,
                        "strokes_per_sec_per_chip": 2.0e6}])
    monkeypatch.setattr(bench, "_hist_path", lambda: str(hist))
    best = bench._hist_best_strokes("layer_norm", 4096, 250, "bfloat16",
                                    True, False, "bfloat16",
                                    "TPU v5 lite", 1, 2, 25)
    assert best == 2.0e6


def test_hist_best_missing_file_and_no_match(tmp_path, monkeypatch):
    monkeypatch.setattr(
        bench, "_hist_path", lambda: str(tmp_path / "absent.jsonl"))
    assert bench._hist_best_strokes("layer_norm", 4096, 250, "bfloat16",
                                    True, True, "bfloat16",
                                    "TPU v5 lite", 1, 2, 25) is None
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    _write_hist(hist, [{**_BASE, "strokes_per_sec_per_chip": 1.0}])
    monkeypatch.setattr(bench, "_hist_path", lambda: str(hist))
    assert bench._hist_best_strokes("hyper", 4096, 250, "bfloat16",
                                    True, True, "bfloat16",
                                    "TPU v5 lite", 1, 2, 25) is None


def test_should_stop_policy_matrix():
    """The trial-loop stop policy (VERDICT r2 #1): the early-stop and
    trial cap are honored only while best-of is plausible; below the
    70%-of-history line only the budget stops the loop."""
    stop = bench._should_stop
    P, IMP = 10.0, 100.0  # best_t values: plausible / implausible vs plaus_t=20

    # plausible: classic early-stop after >=4 trials with 3 non-improving
    assert stop(4, 3, P, 20.0, 60.0, 480.0, 8) == "early-stop"
    assert stop(3, 3, P, 20.0, 60.0, 480.0, 8) is None   # too few trials
    assert stop(4, 2, P, 20.0, 60.0, 480.0, 8) is None   # still improving
    # plausible: trial cap
    assert stop(8, 0, P, 20.0, 60.0, 480.0, 8) == "max-trials"
    # implausible: early-stop and cap are DISABLED...
    assert stop(6, 5, IMP, 20.0, 60.0, 480.0, 8) is None
    assert stop(12, 9, IMP, 20.0, 60.0, 480.0, 8) is None
    # ...only the budget stops it, and labels the slow window
    assert stop(12, 9, IMP, 20.0, 500.0, 480.0, 8) == "budget-implausible"
    # budget in the plausible regime keeps the plain label
    assert stop(3, 1, P, 20.0, 500.0, 480.0, 8) == "budget"
    # budget never fires before 2 trials (a record needs a best-of)
    assert stop(1, 1, IMP, 20.0, 500.0, 480.0, 8) is None
    # no history -> plaus_t is +inf -> always plausible
    inf = float("inf")
    assert stop(4, 3, IMP, inf, 60.0, 480.0, 8) == "early-stop"


def test_bench_summary_skips_diagnostic_rows(tmp_path, capsys):
    """Diagnostic rows carrying metric keys must not print as phantom
    train configurations (r3 review finding) — every non-train/sampler
    kind is filtered even when its record holds a metric key the
    summary would otherwise pick up."""
    from scripts import bench_summary

    hist = tmp_path / "h.jsonl"
    _write_hist(hist, [
        {**_BASE, "steps_per_call": 5, "transfer_dtype": "bfloat16",
         "strokes_per_sec_per_chip": 4.0e6, "mfu": 0.27},
        {"kind": "profile_breakdown", "dec_model": "layer_norm",
         "batch_size": 4096, "seq_len": 250,
         "strokes_per_sec_per_chip": 2.2e6},
        # a probe row that (hypothetically) gained a metric key must
        # still be filtered by kind, not by accident of schema
        {"kind": "probe_dual_encoder", "speedup": 0.997,
         "strokes_per_sec_per_chip": 2.2e6},
        {"kind": "sampler", "dec_model": "layer_norm", "batch_size": 64,
         "full_len": True, "sketches_per_sec": 3500.0},
    ])
    assert bench_summary.main([str(hist)]) == 0
    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if l.strip()]
    assert len(lines) == 2  # one train row + one sampler row
    assert not any("2,200,000" in l for l in lines)


def test_unavailable_classification():
    """ADVICE r5: the 2x120s outage backoff must key on exception type
    / anchored backend-init phrasing, not a bare 'UNAVAILABLE'
    substring of str(err)."""

    class XlaRuntimeError(Exception):  # matched by NAME, as jaxlib's
        pass

    class WrappedXla(XlaRuntimeError):  # subclasses classify too
        pass

    assert bench._unavailable(
        XlaRuntimeError("UNAVAILABLE: socket closed"))
    assert bench._unavailable(
        WrappedXla("UNAVAILABLE: connection reset"))
    assert bench._unavailable(
        RuntimeError("Unable to initialize backend 'tpu': ..."))
    # an XLA error of a DIFFERENT status class: quick retry
    assert not bench._unavailable(
        XlaRuntimeError("INTERNAL: something broke"))
    # unrelated errors merely quoting the word must NOT earn the
    # outage budget
    assert not bench._unavailable(
        RuntimeError("step failed (prior status: UNAVAILABLE: x)"))
    assert not bench._unavailable(
        RuntimeError("log said 'Unable to initialize backend' earlier"))
    assert not bench._unavailable(OSError("UNAVAILABLE"))


def test_hist_append_routes_smoke_and_cpu_rows(tmp_path, monkeypatch):
    """VERDICT r5 weak #4: smoke/CPU rows go to BENCH_SMOKE_HISTORY so
    the canonical history only accumulates accelerator rows."""
    canon = tmp_path / "canon.jsonl"
    smoke = tmp_path / "smoke.jsonl"
    monkeypatch.setattr(bench, "_hist_path", lambda: str(canon))
    monkeypatch.setattr(bench, "_smoke_hist_path", lambda: str(smoke))
    bench._hist_append({**_BASE, "strokes_per_sec_per_chip": 1.0})
    bench._hist_append({**_BASE, "device_kind": "cpu",
                        "strokes_per_sec_per_chip": 2.0})
    bench._hist_append({"kind": "serve_bench", "smoke": True,
                        "device_kind": "TPU v5 lite"})
    bench._hist_append({"kind": "goodput_bench", "smoke": False,
                        "device_kind": "TPU v5 lite"})
    canon_rows = [json.loads(l) for l in open(canon)]
    smoke_rows = [json.loads(l) for l in open(smoke)]
    assert [r.get("kind") for r in canon_rows] == ["train",
                                                   "goodput_bench"]
    assert len(smoke_rows) == 2
    assert all("wall_time" in r for r in canon_rows + smoke_rows)
    # and the committed canonical history holds no smoke/cpu rows
    for line in open(bench.__file__.replace("bench.py",
                                            "BENCH_HISTORY.jsonl")):
        assert not bench._is_smoke_record(json.loads(line))


def test_hist_append_stamps_run_id_and_topology(tmp_path, monkeypatch):
    """ISSUE 8 satellite: every appended row carries run_id + host
    topology (the trace/bench join key), rows of one process share one
    run_id, and the new fields are TOLERATED by every consumer — old
    rows (no stamp) and new rows key and pool identically."""
    from scripts.bench_summary import key_of, metric_of
    from sketch_rnn_tpu.utils import runinfo

    canon = tmp_path / "canon.jsonl"
    monkeypatch.setattr(bench, "_hist_path", lambda: str(canon))
    monkeypatch.setattr(bench, "_smoke_hist_path",
                        lambda: str(tmp_path / "smoke.jsonl"))
    r1 = bench._hist_append({**_BASE, "strokes_per_sec_per_chip": 1.0})
    r2 = bench._hist_append({**_BASE, "strokes_per_sec_per_chip": 2.0})
    assert r1["run_id"] and r1["run_id"] == r2["run_id"]
    assert r1["run_id"] == runinfo.get_run_id()
    assert r1["host_count"] >= 1 and r1["process_index"] == 0
    # an explicit caller-provided run_id wins over the stamp
    r3 = bench._hist_append({**_BASE, "run_id": "mine",
                             "strokes_per_sec_per_chip": 3.0})
    assert r3["run_id"] == "mine"
    # old (unstamped) and new rows are the same summary/regress cell
    old = {**_BASE, "strokes_per_sec_per_chip": 4.0}
    assert key_of(old) == key_of(r1)
    assert metric_of(r1) == 1.0
    # bench_regress's collection walks the same key_of over stamped
    # rows: one cell despite mixed stamping
    from scripts import bench_regress
    _write_hist(tmp_path / "mixed.jsonl",
                [r1, r2, r3, old])
    cells = bench_regress.collect([str(tmp_path / "mixed.jsonl")])
    assert len(cells) == 1
    assert sorted(next(iter(cells.values()))) == [1.0, 2.0, 3.0, 4.0]


def test_bench_summary_aggregates_partial_streamed_log(tmp_path, capsys):
    """VERDICT r5 weak #1: a driver-captured log from a run that died
    mid-matrix — streamed rows interleaved with progress chatter, a
    '# '-prefixed stderr echo, and a torn final line — must still
    aggregate."""
    from scripts import bench_summary

    log = tmp_path / "captured.log"
    row = {**_BASE, "steps_per_call": 5, "transfer_dtype": "int16",
           "strokes_per_sec_per_chip": 5.0e6}
    log.write_text(
        "#   history best for this config: 4,000,000 strokes/s/chip\n"
        + json.dumps(row) + "\n"
        + "# " + json.dumps({**row, "dec_model": "lstm"}) + "\n"
        + "#   trial 3: 8.1s\n"
        + json.dumps({**row, "dec_model": "hyper"})[:40] + "\n")  # torn
    assert bench_summary.main([str(log)]) == 0
    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if l.strip()]
    assert len(lines) == 2  # layer_norm + unwrapped lstm; torn row skipped
    assert any("layer_norm" in l for l in lines)
    assert any("lstm" in l for l in lines)


def test_bench_summary_cpu_rows_cannot_shadow_accelerator(tmp_path,
                                                          capsys):
    """With the smoke history aggregated alongside the canonical one, a
    CPU row of the same config shape must key separately — never
    pooling into (or shadowing) the accelerator record."""
    from scripts import bench_summary

    hist = tmp_path / "h.jsonl"
    _write_hist(hist, [
        {**_BASE, "strokes_per_sec_per_chip": 4.0e6},
        {**_BASE, "device_kind": "cpu",
         "strokes_per_sec_per_chip": 9.9e6},
    ])
    assert bench_summary.main([str(hist)]) == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 2  # distinct keys, two rows
    tpu = next(l for l in lines if "TPU v5 lite" in l)
    assert "4,000,000" in tpu


def test_bench_main_streams_rows_to_stdout(monkeypatch, capsys,
                                           tmp_path):
    """Streaming emission: each completed cell prints its own JSON row
    on stdout BEFORE the final summary line, so a later-cell outage
    still leaves parseable partial results."""
    rows = iter([
        {"kind": "train", "dec_model": "lstm", "device_kind": "x",
         "strokes_per_sec_per_chip": 100.0},
        {"kind": "train", "dec_model": "layer_norm", "device_kind": "x",
         "strokes_per_sec_per_chip": 200.0},
        {"kind": "train", "dec_model": "hyper", "device_kind": "x",
         "strokes_per_sec_per_chip": 300.0},
    ])
    monkeypatch.setattr(bench, "bench_train", lambda *a, **k: next(rows))
    monkeypatch.setattr(bench, "_hist_path",
                        lambda: str(tmp_path / "h.jsonl"))
    monkeypatch.setenv("BENCH_MATRIX", "1")
    monkeypatch.setenv("BENCH_STEPS", "5")
    monkeypatch.setenv("BENCH_SPC", "5")
    assert bench.main() == 0
    out_lines = [json.loads(l)
                 for l in capsys.readouterr().out.splitlines() if l]
    assert [r.get("kind") for r in out_lines[:-1]] == ["train"] * 3
    # streamed rows carry the history's wall_time stamp: a captured
    # stdout log may be the only surviving record of the run
    assert all("wall_time" in r for r in out_lines[:-1])
    assert out_lines[-1]["metric"] == "train_strokes_per_sec_per_chip"
    assert out_lines[-1]["value"] == 200.0  # flagship = layer_norm


def test_retry_decision_caps_sleep_by_remaining_deadline():
    """ISSUE 4 satellite: the 120s unavailable backoff must shrink to
    fit the remaining cell deadline instead of sleeping the matrix into
    the driver's outer timeout (BENCH_r05: rc=124, parsed null)."""
    used = {"unavail": 0, "other": 0}
    # plenty of deadline left: full backoff
    assert bench._retry_decision(used, "unavail", 10.0, 900.0) == \
        ("retry", 120.0)
    # deadline nearly consumed: the sleep is capped to what fits
    action, sleep = bench._retry_decision(used, "unavail", 800.0, 900.0)
    assert action == "retry"
    assert 0 < sleep <= 900.0 - 800.0 - bench._RETRY_MARGIN_S + 1e-9
    # not enough room for a sleep plus a meaningful attempt: give up
    assert bench._retry_decision(used, "unavail", 870.0, 900.0) == \
        ("give_up", 0.0)
    assert bench._retry_decision(used, "other", 895.0, 900.0) == \
        ("give_up", 0.0)


def test_retry_decision_budgets_still_raise():
    """Class budgets are unchanged: 2 unavailable retries, 1 other."""
    assert bench._retry_decision({"unavail": 2}, "unavail", 0.0,
                                 900.0) == ("raise", 0.0)
    assert bench._retry_decision({"other": 1}, "other", 0.0, 900.0) == \
        ("raise", 0.0)
    # under budget, the quick class keeps its 10s backoff
    assert bench._retry_decision({"other": 0}, "other", 0.0, 900.0) == \
        ("retry", 10.0)


def test_bench_main_emits_unavailable_row_before_deadline(
        monkeypatch, capsys, tmp_path):
    """A cell facing a dead backend with no deadline room must stream
    an ``unavailable`` row (and a parseable null summary) instead of
    raising or sleeping into the outer timeout."""
    def dead(*a, **k):
        raise RuntimeError("Unable to initialize backend 'axon': "
                           "UNAVAILABLE: TPU backend setup error")

    monkeypatch.setattr(bench, "bench_train", dead)
    monkeypatch.setattr(bench, "_hist_path",
                        lambda: str(tmp_path / "h.jsonl"))
    monkeypatch.setattr(bench, "_smoke_hist_path",
                        lambda: str(tmp_path / "s.jsonl"))
    monkeypatch.setenv("BENCH_CELL_DEADLINE", "1")  # no room: no sleeps
    monkeypatch.setenv("BENCH_STEPS", "5")
    monkeypatch.setenv("BENCH_SPC", "5")
    monkeypatch.delenv("BENCH_MATRIX", raising=False)
    assert bench.main() == 1  # degraded round, but a parseable one
    out_lines = [json.loads(l)
                 for l in capsys.readouterr().out.splitlines() if l]
    row, summary = out_lines[0], out_lines[-1]
    assert row["kind"] == "unavailable"
    assert "Unable to initialize backend" in row["error"]
    assert "wall_time" in row  # streamed rows carry the history stamp
    assert summary["value"] is None and summary["unavailable"] is True
    # the outage row landed in the history for round triage...
    hist = [json.loads(l) for l in open(tmp_path / "h.jsonl")]
    assert [r["kind"] for r in hist] == ["unavailable"]
    # ...where the plausibility gate and the summary must ignore it
    assert bench._hist_best_strokes(
        "layer_norm", 4096, 250, "bfloat16", True, True, "bfloat16",
        "TPU v5 lite", 1, 2, 25) is None
    from scripts import bench_summary
    assert bench_summary.main([str(tmp_path / "h.jsonl")]) == 0
    assert capsys.readouterr().out.strip() == ""


def test_bench_summary_aggregates_bucket_bench_rows(tmp_path, capsys):
    """ISSUE 4 satellite: bucket_bench rows surface with their
    padding-waste columns and speedup metric, keyed separately per
    edge-set and device."""
    from scripts import bench_summary

    hist = tmp_path / "h.jsonl"
    row = {"kind": "bucket_bench", "dec_model": "lstm", "batch_size": 32,
           "max_seq_len": 128, "bucket_edges": [16, 32, 64, 128],
           "device_kind": "cpu", "speedup_steps_per_sec": 2.76,
           "fixed": {"padded_frac": 0.81},
           "bucketed": {"padded_frac": 0.34}}
    _write_hist(hist, [row,
                       {**row, "bucket_edges": [64, 128],
                        "speedup_steps_per_sec": 1.4}])
    assert bench_summary.main([str(hist)]) == 0
    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if l.strip()]
    assert len(lines) == 2  # distinct edge sets key separately
    full = next(l for l in lines if "16;32;64;128" in l)
    assert "2.76" in full and "0.81" in full and "0.34" in full


def test_bench_summary_bucket_stacked_columns(tmp_path, capsys):
    """ISSUE 5 satellite: grid-bearing bucket_bench rows additionally
    print the dispatch-amortization columns — best stacked gain over
    K=1, realized mean_run_len and dispatches_saved; legacy rows
    without a grid print none."""
    from scripts import bench_summary

    hist = tmp_path / "h.jsonl"
    legacy = {"kind": "bucket_bench", "dec_model": "lstm",
              "batch_size": 32, "max_seq_len": 128,
              "bucket_edges": [16, 32], "device_kind": "cpu",
              "speedup_steps_per_sec": 2.0,
              "fixed": {"padded_frac": 0.8},
              "bucketed": {"padded_frac": 0.3}}
    stacked = {**legacy, "bucket_edges": [16, 32, 64],
               "best_stacked_gain": 1.21,
               "grid": {
                   "bucketed_k1": {"steps_per_sec": 50.0},
                   "bucketed_k4": {"steps_per_sec": 57.0,
                                   "mean_run_len": 6.4,
                                   "dispatches_saved": 60},
                   "bucketed_k8": {"steps_per_sec": 60.5,
                                   "mean_run_len": 6.4,
                                   "dispatches_saved": 78}}}
    _write_hist(hist, [legacy, stacked])
    assert bench_summary.main([str(hist)]) == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    with_grid = next(l for l in lines if "16;32;64" in l)
    assert ("stacked=1.21x@K8" in with_grid
            and "run_len=6.4" in with_grid and "saved=78" in with_grid)
    without = next(l for l in lines if "16;32 " in l)
    assert "stacked=" not in without


def test_bench_summary_serve_rows_with_latency_percentiles(tmp_path,
                                                           capsys):
    """ISSUE 6 satellite: serve_bench rows surface with sketches/sec,
    the p50/p95/p99 latency columns (the SLA surface) and the speedup
    over the legacy sampler; rows predating the percentile keys still
    print, just without the latency block; distinct (B, K, n, dist)
    configs key separately."""
    from scripts import bench_summary

    hist = tmp_path / "h.jsonl"
    row = {"kind": "serve_bench", "dec_model": "lstm", "slots": 32,
           "chunk": 8, "n_requests": 512, "len_dist": "bimodal",
           "device_kind": "cpu", "engine_sketches_per_sec": 61.5,
           "engine_latency_p50_s": 0.120, "engine_latency_p95_s": 0.480,
           "engine_latency_p99_s": 0.910, "speedup": 2.41}
    legacy = {k: v for k, v in row.items()
              if not k.startswith("engine_latency")}
    legacy.update(slots=64, engine_sketches_per_sec=50.0)
    _write_hist(hist, [row, legacy])
    assert bench_summary.main([str(hist)]) == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 2  # B=32 and B=64 key separately
    full = next(l for l in lines if "B=32" in l)
    assert "61.50 sk/s" in full
    assert "lat[ms] 120/480/910" in full
    assert "2.41x vs sampler" in full
    old = next(l for l in lines if "B=64" in l)
    assert "lat[ms]" not in old


def test_bench_train_rejects_non_divisible_steps():
    """ADVICE r2: steps % steps_per_call != 0 must raise, not silently
    run fewer optimizer steps while computing throughput over `steps`."""
    with pytest.raises(ValueError, match="positive multiple"):
        bench.bench_train("layer_norm", steps=7, batch_per_chip=64,
                          seq_len=16, dtype="float32", remat=False,
                          prefetch_depth=0, steps_per_call=5)
    with pytest.raises(ValueError, match="positive multiple"):
        bench.bench_train("layer_norm", steps=10, batch_per_chip=64,
                          seq_len=16, dtype="float32", remat=False,
                          prefetch_depth=0, steps_per_call=0)


def test_bench_summary_fleet_rows(tmp_path, capsys):
    """ISSUE 9 satellite: serve_fleet rows key per (replicas, offered
    rate) cell and print the offered-load column, per-class p99, shed
    fraction and — on capacity rows — the scaling efficiency +
    deterministic step-parallel speedup."""
    from scripts import bench_summary

    hist = tmp_path / "h.jsonl"
    base = {"kind": "serve_fleet", "dec_model": "lstm", "slots": 32,
            "chunk": 8, "n_requests": 512, "len_dist": "bimodal",
            "device_kind": "cpu"}
    cap2 = {**base, "replicas": 2, "offered_rate": 0.0,
            "sketches_per_sec": 367.1, "shed_frac": 0.0,
            "scaling": 0.711, "step_parallel": 1.971,
            "by_class": {"interactive": {"p99_s": 0.61},
                         "batch": {"p99_s": 1.43}}}
    load2 = {**base, "replicas": 2, "offered_rate": 300.0,
             "sketches_per_sec": 204.2, "shed_frac": 0.113,
             "by_class": {"interactive": {"p99_s": 0.42},
                          "batch": {"p99_s": 0.61}}}
    cap1 = {**base, "replicas": 1, "offered_rate": 0.0,
            "sketches_per_sec": 258.1, "shed_frac": 0.0,
            "scaling": 1.0, "step_parallel": 1.0, "by_class": {}}
    _write_hist(hist, [cap2, load2, cap1])
    assert bench_summary.main([str(hist)]) == 0
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.strip()]
    assert len(lines) == 3  # three distinct (R, rate) cells
    c2 = next(l for l in lines if "R=2 rate=0" in l)
    assert "367.10 sk/s" in c2
    assert "scaling=0.711" in c2 and "steps||=1.971x" in c2
    assert "interactive=610" in c2 and "batch=1430" in c2
    l2 = next(l for l in lines if "R=2 rate=300" in l)
    assert "shed=11.3%" in l2 and "scaling=" not in l2
