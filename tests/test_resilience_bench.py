"""Resilience harness tests (ISSUE 10 satellite: CI wiring).

``test_resilience_smoke`` runs the REAL fault matrix on the CPU smoke
config and asserts every cell's expected outcome — the tier-1 proof
that crash+resume is bitwise-equivalent, torn saves fall back, a
permanent writer failure halts loudly one save late, the watchdog
attributes injected NaNs, and fleet failover drains with chaos parity.
The regression-gate tests are pure: they pin that a future ``ok:
false`` resilience row actually gates (bench_regress) and that
bench_summary keys the rows per (site, mode).
"""

import json

import pytest

import scripts.bench_regress as bench_regress
import scripts.resilience_bench as resilience_bench
from scripts.bench_summary import key_of, metric_of


def test_resilience_smoke(tmp_path):
    out = tmp_path / "RESILIENCE.json"
    rc = resilience_bench.main(["--smoke", f"--out={out}",
                                f"--workdir={tmp_path / 'work'}"])
    assert rc == 0
    rec = json.loads(out.read_text())
    assert rec["all_ok"] is True
    by_site = {c["site"]: c for c in rec["cells"]}
    assert by_site["train.step"]["outcome"] == "recovered"
    assert by_site["train.step"]["final_state_bitwise_equal"] is True
    assert by_site["train.step"]["recovery_cost_steps"] == \
        by_site["train.step"]["crash_step"] - \
        by_site["train.step"]["resumed_from_step"]
    assert by_site["ckpt.commit"]["outcome"] == "recovered"
    assert by_site["ckpt.commit"]["retries_used"] == 1
    assert by_site["ckpt.torn"]["outcome"] == "recovered"
    assert by_site["ckpt.torn"]["resumed_from_step"] == \
        rec["config"]["save_every"]
    assert by_site["ckpt.writer"]["outcome"] == "clean-halt"
    assert by_site["ckpt.writer"]["no_checkpoint_left"] is True
    assert by_site["metrics.row"]["outcome"] == "clean-halt"
    assert by_site["metrics.row"]["fault_site_in_evidence"] is True
    assert by_site["fleet.worker"]["outcome"] == "degraded"
    assert by_site["fleet.worker"]["strokes_bitwise_equal"] is True
    # the ISSUE 16 rollout cell: three arms, each a bitwise proof —
    # promote under a killed replica, canary rejection rolled back,
    # corrupt candidate quarantined
    ro = by_site["rollout"]
    assert ro["outcome"] == "recovered" and ro["ok"] is True
    by_arm = {a["site"]: a for a in ro["arms"]}
    assert by_arm["rollout.swap"]["outcome"] == "promoted"
    assert by_arm["rollout.swap"]["post_swap_bitwise_cold_fleet"] is True
    assert by_arm["rollout.swap"]["healthz_degraded"] is True
    assert by_arm["rollout.canary"]["outcome"] == "rolled-back"
    assert by_arm["rollout.canary"]["post_rollback_bitwise"] is True
    assert by_arm["ckpt.load.corrupt"]["outcome"] == "quarantined"
    assert by_arm["ckpt.load.corrupt"]["candidate_quarantined"] is True
    assert by_arm["ckpt.load.corrupt"]["fleet_kept_old_bitwise"] is True
    # the ISSUE 14 elastic chaos cell: two real subprocess hosts, one
    # hard-killed mid-run; the survivor recovers bitwise at the new
    # topology with ZERO device steps re-executed (the consistent
    # checkpoint lands AT the death step)
    hk = by_site["host.kill"]
    assert hk["outcome"] == "recovered" and hk["mode"] == "elastic"
    assert hk["hard_killed"] is True
    assert hk["final_ckpt_bytes_equal"] is True
    assert hk["recovery_cost_steps"] == 0
    assert hk["run_manifest_topology"]["hosts"] == [0]
    # recovery costs are deterministic step counts, never wall-clock
    assert all("wall" not in k
               for c in rec["cells"] for k in c
               if k.startswith("recovery_cost"))
    # the run-manifest clock: one stamp for the whole invocation
    from sketch_rnn_tpu.utils import runinfo

    assert rec["wall_time"] == runinfo.run_wall_time()


def _row(ok, site="train.step", mode="raise"):
    return {"kind": "resilience", "site": site, "mode": mode,
            "device_kind": "cpu", "smoke": True, "ok": ok,
            "expected": "recovered",
            "outcome": "recovered" if ok else "FAILED"}


def test_bench_summary_keys_resilience_per_site_and_mode():
    a, b = _row(True), _row(True, mode="subprocess-exit")
    assert key_of(a) != key_of(b)          # modes never pool
    assert key_of(a) == key_of(_row(False))
    assert metric_of(_row(True)) == 1.0
    assert metric_of(_row(False)) == 0.0
    # the elastic host-kill cell keys as its own (site, mode) cell
    hk = _row(True, site="host.kill", mode="elastic")
    assert key_of(hk) not in {key_of(a), key_of(b)}
    assert metric_of(hk) == 1.0


def _roll_row(ok, site="rollout.swap"):
    return {"kind": "rollout", "site": site, "device_kind": "cpu",
            "smoke": True, "ok": ok, "expected": "promoted",
            "outcome": "promoted" if ok else "FAILED"}


def test_rollout_rows_key_and_gate_like_binary_kinds(tmp_path, capsys):
    """ISSUE 16 satellite (CI wiring): kind=rollout rows are a binary
    kind — keyed per fault site, metric 1.0/0.0 from ok, and a future
    ok=false row gates via bench_regress with no new plumbing."""
    a = _roll_row(True)
    assert key_of(a) == key_of(_roll_row(False))
    assert key_of(a) != key_of(_roll_row(True, site="rollout.canary"))
    assert key_of(a) != key_of(_row(True))     # never pools with
    assert metric_of(a) == 1.0                 # resilience cells
    assert metric_of(_roll_row(False)) == 0.0
    hist = tmp_path / "hist.jsonl"
    hist.write_text("".join(json.dumps(_roll_row(True)) + "\n"
                            for _ in range(4)))
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps(_roll_row(False)) + "\n")
    assert bench_regress.main([f"--fresh={bad}",
                               f"--history={hist}"]) == 1
    assert "REGRESS" in capsys.readouterr().out


def test_bench_regress_gates_broken_host_kill_cell(tmp_path, capsys):
    """ISSUE 14 satellite (CI wiring): a future ok=false host-kill row
    gates exactly like the other binary resilience cells — BINARY_KINDS
    already centralizes the metric, key_of the cell identity."""
    hk = lambda ok: _row(ok, site="host.kill", mode="elastic")  # noqa: E731
    hist = tmp_path / "hist.jsonl"
    hist.write_text("".join(json.dumps(hk(True)) + "\n"
                            for _ in range(4)))
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps(hk(False)) + "\n")
    assert bench_regress.main([f"--fresh={bad}",
                               f"--history={hist}"]) == 1
    assert "REGRESS" in capsys.readouterr().out


def test_bench_regress_gates_broken_resilience_cell(tmp_path, capsys):
    hist = tmp_path / "hist.jsonl"
    hist.write_text("".join(json.dumps(_row(True)) + "\n"
                            for _ in range(4)))
    ok_fresh = tmp_path / "ok.jsonl"
    ok_fresh.write_text(json.dumps(_row(True)) + "\n")
    bad_fresh = tmp_path / "bad.jsonl"
    bad_fresh.write_text(json.dumps(_row(False)) + "\n")
    assert bench_regress.main([f"--fresh={ok_fresh}",
                               f"--history={hist}"]) == 0
    capsys.readouterr()
    assert bench_regress.main([f"--fresh={bad_fresh}",
                               f"--history={hist}"]) == 1
    assert "REGRESS" in capsys.readouterr().out
    # a RECORDED failure must not poison the baseline: with an ok=false
    # row already in history, a fresh failure still gates (the failed
    # row is evidence, not a baseline — without the filter the cell's
    # band blows to 1.0 and the gate is disabled forever)
    poisoned = tmp_path / "poisoned.jsonl"
    poisoned.write_text(hist.read_text() + json.dumps(_row(False))
                        + "\n")
    assert bench_regress.main([f"--fresh={bad_fresh}",
                               f"--history={poisoned}"]) == 1
    capsys.readouterr()
    # and the --smoke self-check fails on a history ENDING in a failure
    assert bench_regress.main(["--smoke",
                               f"--history={poisoned}"]) == 1


def test_committed_smoke_history_self_check():
    """The committed smoke history's resilience rows must themselves
    end in-band — the same self-check tier-1 already runs for the perf
    rows (bench_regress --smoke), now covering recovery outcomes."""
    rc = bench_regress.main(["--smoke", "--json"])
    assert rc == 0


@pytest.mark.slow
def test_resilience_full_matches_committed(tmp_path):
    """The full matrix (subprocess hard-kill included) — slow tier."""
    out = tmp_path / "RESILIENCE.json"
    rc = resilience_bench.main([f"--out={out}",
                                f"--workdir={tmp_path / 'work'}"])
    assert rc == 0
    rec = json.loads(out.read_text())
    subs = [c for c in rec["cells"] if c["mode"] == "subprocess-exit"]
    assert subs and subs[0]["hard_killed"] is True
    assert subs[0]["final_ckpt_bytes_equal"] is True
