"""Multi-task serving endpoint tests (ISSUE 15).

The load-bearing invariants, extending the engine/fleet suites to the
conditional workloads:

1. **Offline parity**: the fleet's complete/reconstruct/interpolate
   strokes are bitwise the single-engine ``serve_requests`` path's —
   and generation served in a MIXED burst is bitwise the legacy
   pure-generate program's (the endpoint machinery is invisible to the
   old workload).
2. **Geometry discipline**: prefixes encode bitwise-identically at
   every bucket edge that fits them, in every batch composition and
   slot position — and the JitCompileProbe sees exactly one
   ``serve_encode`` compile per (pool rows, edge) geometry.
3. **Semantics**: the completion replay is checked against the
   INDEPENDENT teacher-forced ``model.decode`` path, and the
   interpolation grid against ``sample/interpolate.interpolate_latents``
   on the encoded posterior means.
"""

import dataclasses

import jax
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.serve import (
    EncodeProgram,
    Request,
    ServeEngine,
    ServeFleet,
    parse_endpoint_specs,
    serve_requests,
    validate_request,
)
from sketch_rnn_tpu.serve import endpoints as EP

TINY = dict(batch_size=8, max_seq_len=24, enc_rnn_size=12,
            dec_rnn_size=16, z_size=6, num_mixture=3, hyper_rnn_size=8,
            hyper_embed_size=4, serve_slots=4, serve_chunk=2,
            serve_prefix_edges=(8, 16, 24))


def tiny_hps(**kw) -> HParams:
    return HParams(**{**TINY, **kw})


def _prefix(i: int, n: int) -> np.ndarray:
    rng = np.random.default_rng(500 + i)
    p = rng.standard_normal((n, 3)).astype(np.float32)
    p[:, 2] = (rng.random(n) < 0.2)
    p[-1, 2] = 1.0
    return p


@pytest.fixture(scope="module")
def setup():
    hps = tiny_hps()
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    return hps, model, params


def _mk(i: int, hps: HParams, endpoint: str, **kw) -> Request:
    key = jax.random.key(2000 + i)
    if endpoint == "generate":
        rng = np.random.default_rng(i)
        return Request(key=key, endpoint="generate",
                       z=rng.standard_normal(hps.z_size).astype(
                           np.float32),
                       temperature=0.8, **kw)
    if endpoint == "interpolate":
        return Request(key=key, endpoint="interpolate",
                       prefix=(_prefix(i, 3 + i % 5),
                               _prefix(i + 50, 4 + i % 7)),
                       frames=kw.pop("frames", 3), temperature=0.8,
                       **kw)
    return Request(key=key, endpoint=endpoint,
                   prefix=_prefix(i, 3 + i % 9), temperature=0.8, **kw)


def _mixed(hps, n=8):
    eps = ("generate", "complete", "reconstruct", "interpolate")
    return [_mk(i, hps, eps[i % 4], max_len=4 + i % 5)
            for i in range(n)]


# -- validation ---------------------------------------------------------------


def test_validate_request_endpoint_rules(setup):
    hps, model, params = setup
    with pytest.raises(ValueError, match="unknown endpoint"):
        validate_request(_mk(0, hps, "generate").__class__(
            key=jax.random.key(0), endpoint="translate"), hps)
    with pytest.raises(ValueError, match="no prefix"):
        validate_request(Request(key=jax.random.key(0),
                                 prefix=_prefix(0, 3)), hps)
    # interpolate needs exactly two prefixes and frames >= 2
    with pytest.raises(ValueError, match="exactly two"):
        validate_request(Request(key=jax.random.key(0),
                                 endpoint="interpolate",
                                 prefix=_prefix(0, 3)), hps)
    with pytest.raises(ValueError, match="frames >= 2"):
        validate_request(Request(key=jax.random.key(0),
                                 endpoint="interpolate",
                                 prefix=(_prefix(0, 3), _prefix(1, 3)),
                                 frames=1), hps)
    with pytest.raises(ValueError, match="pool_cap"):
        validate_request(Request(key=jax.random.key(0),
                                 endpoint="interpolate",
                                 prefix=(_prefix(0, 3), _prefix(1, 3)),
                                 frames=9), hps, pool_cap=8)
    # prefix shape / length / finiteness rules
    with pytest.raises(ValueError, match=r"\[n >= 1, 3\]"):
        validate_request(Request(key=jax.random.key(0),
                                 endpoint="complete",
                                 prefix=np.zeros((0, 3), np.float32)),
                         hps)
    with pytest.raises(ValueError, match="terminal prefix edge"):
        validate_request(Request(key=jax.random.key(0),
                                 endpoint="complete",
                                 prefix=_prefix(0, 25)), hps)


def test_unconditional_rejects_encoder_endpoints_naming_conditional():
    """The satellite contract: the one-line error NAMES
    hps.conditional."""
    hps = tiny_hps(conditional=False)
    with pytest.raises(ValueError, match="hps.conditional"):
        validate_request(Request(key=jax.random.key(0),
                                 endpoint="complete",
                                 prefix=_prefix(0, 3)), hps)
    # and the fleet door check rejects the same way
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    fleet = ServeFleet(model, hps, params, replicas=1)
    try:
        with pytest.raises(ValueError, match="hps.conditional"):
            fleet.submit(Request(key=jax.random.key(0),
                                 endpoint="reconstruct",
                                 prefix=_prefix(0, 3)))
    finally:
        fleet.close()


def test_prefix_edges_and_bucketing():
    assert EP.default_prefix_edges(250) == (32, 64, 128, 250)
    assert EP.default_prefix_edges(24) == (24,)
    hps = tiny_hps()
    assert EP.prefix_edges(hps) == (8, 16, 24)
    assert EP.prefix_edge_of(3, (8, 16, 24)) == 8
    assert EP.prefix_edge_of(8, (8, 16, 24)) == 8
    assert EP.prefix_edge_of(9, (8, 16, 24)) == 16
    with pytest.raises(ValueError, match="exceeds"):
        EP.prefix_edge_of(25, (8, 16, 24))
    with pytest.raises(ValueError, match="ascending"):
        tiny_hps(serve_prefix_edges=(16, 8))
    with pytest.raises(ValueError, match="max_seq_len"):
        tiny_hps(serve_prefix_edges=(8, 64))


def test_parse_endpoint_specs_grammar():
    ep_map, classes = parse_endpoint_specs(
        ["complete=interactive:p95<=250ms", "reconstruct=interactive",
         "interpolate=batch", "generate=batch"])
    assert ep_map == {"complete": "interactive",
                      "reconstruct": "interactive",
                      "interpolate": "batch", "generate": "batch"}
    assert classes["interactive"].deadline_s == pytest.approx(0.25)
    assert np.isinf(classes["batch"].deadline_s)  # bare name: no SLA
    assert classes["interactive"].priority < classes["batch"].priority
    for bad, msg in (("nope=batch", "unknown endpoint"),
                     ("complete", "ENDPOINT=CLASS"),
                     ("complete=", "empty class"),
                     ("complete=x:p95<=bad", "SLO")):
        with pytest.raises(ValueError, match=msg):
            parse_endpoint_specs([bad])
    with pytest.raises(ValueError, match="duplicate endpoint"):
        parse_endpoint_specs(["complete=a", "complete=b"])
    # routes must name declared classes at fleet construction
    hps = tiny_hps()
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    with pytest.raises(ValueError, match="undeclared admission"):
        ServeFleet(model, hps, params, replicas=1,
                   endpoint_classes={"complete": "ghost"})


def test_engine_guards_unplanned_endpoint_requests(setup):
    hps, model, params = setup
    eng = ServeEngine(model, hps, params)
    with pytest.raises(ValueError, match="plan_batch"):
        eng.run([_mk(0, hps, "complete", max_len=3)])
    with pytest.raises(ValueError, match="plan_batch"):
        eng.run([_mk(0, hps, "reconstruct", max_len=3)])
    with pytest.raises(ValueError, match="expanded into frame rows"):
        eng.run([_mk(0, hps, "interpolate", max_len=3)])


# -- the serve path -----------------------------------------------------------


def test_serve_requests_all_endpoints_complete(setup):
    hps, model, params = setup
    reqs = _mixed(hps, 8)
    out = serve_requests(model, hps, params, reqs)
    res = {r.uid: r for r in out["results"]}
    assert set(res) == set(range(8))
    for uid, r in res.items():
        assert r.endpoint == reqs[uid].endpoint
        assert r.strokes5.shape[1] == 5
        assert np.isfinite(r.strokes5).all()
        if r.endpoint == "interpolate":
            assert len(r.frames) == reqs[uid].frames
            np.testing.assert_array_equal(np.concatenate(r.frames),
                                          r.strokes5)
        else:
            assert r.frames is None


def test_solo_vs_mixed_bitwise_every_endpoint(setup):
    """THE acceptance invariant, extended: an endpoint request's
    strokes are bitwise identical served solo or inside a mixed
    burst."""
    hps, model, params = setup
    reqs = _mixed(hps, 8)
    ref = {r.uid: r for r in
           serve_requests(model, hps, params, reqs)["results"]}
    for probe in (1, 2, 3):   # complete, reconstruct, interpolate
        solo_req = _mk(probe, hps, reqs[probe].endpoint,
                       max_len=reqs[probe].max_len,
                       **({"frames": reqs[probe].frames}
                          if reqs[probe].endpoint == "interpolate"
                          else {}))
        solo = serve_requests(model, hps, params,
                              [solo_req])["results"][0]
        np.testing.assert_array_equal(
            solo.strokes5, ref[probe].strokes5,
            err_msg=f"{reqs[probe].endpoint} diverged solo vs mixed")


def test_generate_in_mixed_burst_matches_legacy_program(setup):
    """Generation served next to endpoint requests rides the
    init-capable chunk program; its strokes must still be bitwise the
    LEGACY pure-generate program's — the endpoint machinery is
    invisible to the old workload."""
    hps, model, params = setup
    reqs = _mixed(hps, 8)
    ref = {r.uid: r for r in
           serve_requests(model, hps, params, reqs)["results"]}
    eng = ServeEngine(model, hps, params)
    for uid in (0, 4):   # the generate members
        legacy = eng.run([dataclasses.replace(
            _mk(uid, hps, "generate", max_len=reqs[uid].max_len),
            uid=None)])["results"][0]
        np.testing.assert_array_equal(legacy.strokes5,
                                      ref[uid].strokes5)


def test_encode_edge_and_composition_invariance(setup):
    """A prefix encodes bitwise-identically at EVERY bucket edge that
    fits it, in every batch composition and slot position (pad rows
    inert) — the fixed-geometry discipline's correctness half."""
    hps, model, params = setup
    pfx = _prefix(7, 7)
    outs = []
    for edges in ((8, 24), (16, 24), (24,)):
        enc = EncodeProgram(model, hps, params, rows=4, edges=edges)
        outs.append(enc.encode([pfx]))
    for got in outs[1:]:
        for a, b in zip(outs[0], got):
            np.testing.assert_array_equal(a[0], b[0])
    enc = EncodeProgram(model, hps, params, rows=4)
    a = enc.encode([pfx, _prefix(1, 3), _prefix(2, 5), _prefix(3, 7)])
    b = enc.encode([_prefix(4, 6), pfx, _prefix(5, 2)])
    for part_a, part_b in zip(a, b):
        np.testing.assert_array_equal(part_a[0], part_b[1])
    # prev really is the last prefix row (stroke-5)
    from sketch_rnn_tpu.data import strokes as S
    np.testing.assert_array_equal(
        a[2][0], S.to_big_strokes(pfx, 24)[len(pfx) - 1])


def test_complete_replay_matches_teacher_forced_decode(setup):
    """Semantic cross-check against the INDEPENDENT training-path
    decoder: a greedy completion's first continuation row equals the
    argmax of the teacher-forced ``model.decode`` distribution at the
    prefix boundary."""
    import jax.numpy as jnp

    from sketch_rnn_tpu.ops import mdn

    hps, model, params = setup
    pfx = _prefix(11, 6)
    p = len(pfx)
    out = serve_requests(model, hps, params,
                         [Request(key=jax.random.key(5),
                                  endpoint="complete", prefix=pfx,
                                  max_len=3)],
                         greedy=True)
    row0 = out["results"][0].strokes5[0]
    strokes, lens = EP.pad_prefixes([pfx], hps.max_seq_len)
    x_tm = jnp.transpose(jnp.asarray(strokes), (1, 0, 2))
    mu, _, _ = out["engine"].encoder.encode([pfx])
    raw = np.asarray(model.decode(params, x_tm[:p + 1],
                                  jnp.asarray(mu), None))[p, 0]
    mp = mdn.get_mixture_params(jnp.asarray(raw)[None],
                                hps.num_mixture)
    idx = int(np.argmax(np.asarray(mp.log_pi)[0]))
    pen = int(np.argmax(np.asarray(mp.pen_logits)[0]))
    want = np.array([np.asarray(mp.mu1)[0, idx],
                     np.asarray(mp.mu2)[0, idx],
                     pen == 0, pen == 1, pen == 2], np.float32)
    np.testing.assert_allclose(row0, want, rtol=2e-5, atol=2e-5)
    # and a completion is NOT a plain generation from the same z:
    # the replayed carry must matter
    gen = serve_requests(model, hps, params,
                         [Request(key=jax.random.key(5),
                                  z=np.asarray(mu[0]), max_len=3)],
                         greedy=True)["results"][0]
    assert not np.array_equal(gen.strokes5, out["results"][0].strokes5)


def test_interpolate_grid_matches_offline_latents(setup):
    """The interpolation endpoint's frames are bitwise the decode of
    ``interpolate_latents(mu_a, mu_b)`` with per-frame
    ``fold_in(key, frame)`` keys — the exact construction
    ``cli sample --interpolate`` now runs."""
    from sketch_rnn_tpu.sample.interpolate import interpolate_latents

    hps, model, params = setup
    a, b = _prefix(20, 5), _prefix(21, 9)
    key = jax.random.key(77)
    out = serve_requests(model, hps, params,
                         [Request(key=key, endpoint="interpolate",
                                  prefix=(a, b), frames=4,
                                  temperature=0.8, max_len=5)])
    parent = out["results"][0]
    assert len(parent.frames) == 4
    enc = out["engine"].encoder
    mu, _, _ = enc.encode([a, b])
    grid = np.asarray(interpolate_latents(mu[0], mu[1], n=4),
                      np.float32)
    kids = [Request(key=jax.random.fold_in(key, f), z=grid[f],
                    temperature=0.8, max_len=5) for f in range(4)]
    ref = serve_requests(model, hps, params, kids)["results"]
    for f, r in enumerate(sorted(ref, key=lambda r: r.uid)):
        np.testing.assert_array_equal(parent.frames[f], r.strokes5)


# -- fleet integration --------------------------------------------------------


def test_fleet_mixed_endpoints_placement_and_arrival_invariance(setup):
    """ISSUE 15 acceptance: mixed-endpoint strokes bitwise independent
    of replica placement and arrival order, equal to the offline
    serve_requests reference; endpoint->class routing and the
    per-endpoint latency table land in the summary."""
    hps, model, params = setup
    reqs = _mixed(hps, 10)
    ref = {r.uid: r for r in serve_requests(
        model, hps, params,
        [dataclasses.replace(r, uid=i)
         for i, r in enumerate(_mixed(hps, 10))])["results"]}
    ep_map, classes = parse_endpoint_specs(
        ["generate=batch", "complete=interactive:p95<=5",
         "reconstruct=interactive", "interpolate=batch"])

    def run_fleet(R, order=None):
        fleet = ServeFleet(model, hps, params, replicas=R,
                           classes=classes, endpoint_classes=ep_map)
        fleet.warm(reqs[0], endpoints=True)
        try:
            for i in (order if order is not None else range(10)):
                fleet.submit(dataclasses.replace(_mixed(hps, 10)[i],
                                                 uid=i))
            fleet.start()
            assert fleet.drain(timeout=300)
            return fleet.results, fleet.summary()
        finally:
            fleet.close()

    for R in (1, 2):
        got, summ = run_fleet(R)
        assert len(got) == 10
        for uid, r in ref.items():
            np.testing.assert_array_equal(
                got[uid]["result"].strokes5, r.strokes5,
                err_msg=f"uid {uid} ({r.endpoint}) diverged at R={R}")
        by_ep = summ["latency_by_endpoint"]
        assert set(by_ep) == {"generate", "complete", "reconstruct",
                              "interpolate"}
        assert sum(v["completed"] for v in by_ep.values()) == 10
        # class routing applied per endpoint
        assert got[1]["class"] == "interactive"   # complete
        assert got[3]["class"] == "batch"         # interpolate
        assert got[0]["class"] == "batch"         # generate
    order = list(range(10))
    np.random.default_rng(9).shuffle(order)
    got, _ = run_fleet(2, order=order)
    for uid, r in ref.items():
        np.testing.assert_array_equal(
            got[uid]["result"].strokes5, r.strokes5,
            err_msg=f"uid {uid} diverged under shuffled arrival")


def test_fleet_interpolate_cache_hit_carries_frames(setup):
    """The cache-key extension end to end: repeated interpolate content
    hits (bitwise, frames intact, zero device steps), while a
    different frame count is a different content."""
    from sketch_rnn_tpu.serve import ResultCache

    hps, model, params = setup
    cache = ResultCache(config_hash="c", ckpt_id="k")

    def req(uid, frames=3):
        return Request(key=jax.random.key(42), endpoint="interpolate",
                       prefix=(_prefix(30, 4), _prefix(31, 6)),
                       frames=frames, temperature=0.8, max_len=4,
                       uid=uid)

    fleet = ServeFleet(model, hps, params, replicas=1, cache=cache)
    fleet.warm(req(None), endpoints=True)
    try:
        fleet.submit(req(0))
        fleet.start()
        assert fleet.drain(timeout=300)
        fleet.submit(req(1))              # store hit
        fleet.submit(req(2, frames=4))    # different content: miss
        assert fleet.drain(timeout=300)
        res = fleet.results
    finally:
        fleet.close()
    hit = res[1]["result"]
    assert hit.cached and hit.endpoint == "interpolate"
    assert hit.attributed_steps == 0 and len(hit.frames) == 3
    np.testing.assert_array_equal(hit.strokes5,
                                  res[0]["result"].strokes5)
    assert not res[2]["result"].cached
    assert len(res[2]["result"].frames) == 4
    assert cache.stats()["hits"] == 1


def test_encode_compile_accounting(setup):
    """The acceptance pin: exactly one ``serve_encode`` compile per
    (pool rows, prefix-edge) geometry, repeats are cache hits, and a
    warm-before-telemetry engine reports ZERO compiles in the measured
    window."""
    from sketch_rnn_tpu.utils import telemetry as tele

    hps, model, params = setup
    tel = tele.configure(trace_dir=None)
    try:
        prog = EncodeProgram(model, hps, params, rows=4)
        prog.warm()
        spans = [e for e in tel.events() if e.get("type") == "span"
                 and e.get("name") == "serve_encode"]
        assert len(spans) == 3          # edges (8, 16, 24)
        geoms = [e["args"]["geometry"] for e in spans]
        # r17: the key carries the decode-kernel flavor + param dtype
        assert sorted(geoms) == ["(B4,E16,scan,float32)",
                                 "(B4,E24,scan,float32)",
                                 "(B4,E8,scan,float32)"]
        prog.warm()                     # all hits, no new compiles
        spans2 = [e for e in tel.events() if e.get("type") == "span"
                  and e.get("name") == "serve_encode"]
        assert len(spans2) == 3
        counters = tel.counters()
        assert counters[("compile", "jit_cache_miss")] == 3
        assert counters[("compile", "jit_cache_hit")] >= 3
    finally:
        tele.disable()
    # measured window: warm while telemetry is OFF, then trace a burst
    # — the probes must report hits only
    eng = ServeEngine(model, hps, params)
    serve_requests(model, hps, params, _mixed(hps, 8), engine=eng)
    tel = tele.configure(trace_dir=None)
    try:
        serve_requests(model, hps, params, _mixed(hps, 8), engine=eng)
        counters = tel.counters()
        assert counters.get(("compile", "jit_cache_miss"), 0) == 0
        assert not [e for e in tel.events()
                    if e.get("cat") == "compile"
                    and e.get("type") == "span"]
        # per-endpoint request/latency series landed (the satellite's
        # /metrics contract rides these exact names)
        assert counters[("serve",
                         "requests_completed_ep_generate")] == 2
        assert counters[("serve",
                         "requests_completed_ep_complete")] == 2
        assert counters[("serve",
                         "requests_completed_ep_interpolate")] == 2
        assert tel.histogram("latency_s_ep_reconstruct",
                             cat="serve")["count"] == 2
    finally:
        tele.disable()


def test_parse_endpoint_specs_rejects_conflicting_redeclaration():
    """A spec that re-declares an existing class with a DIFFERENT
    objective fails loudly instead of being silently judged by the
    other spec; an agreeing re-declaration is fine."""
    from sketch_rnn_tpu.serve.admission import parse_admission_classes

    base = parse_admission_classes(["interactive:p95<=100ms"])
    with pytest.raises(ValueError, match="re-declares"):
        parse_endpoint_specs(["complete=interactive:p95<=500ms"],
                             classes=base)
    ep_map, _ = parse_endpoint_specs(
        ["complete=interactive:p95<=100ms"], classes=base)
    assert ep_map == {"complete": "interactive"}


def test_admission_backlog_is_pool_row_cost_aware():
    """An interpolation charges its frame count against backlog, the
    queue cap and the wait estimate — not 'one request' (the review's
    frames-x shed-underestimate fix)."""
    from sketch_rnn_tpu.serve.admission import (AdmissionController,
                                                parse_admission_classes)

    adm = AdmissionController(parse_admission_classes([]),
                              n_replicas=1, slots=2, queue_cap=8)
    d = adm.place("default", cost=6)
    assert d.replica == 0 and adm.backlog == [6]
    # the 6-row grid plus one unit crosses the 8-row cap for the next
    adm.place("default", cost=2)
    assert adm.place("default").shed_reason == "queue_full"
    # completion frees the full cost; the EWMA sample stays decode_s
    # (grid rows decode concurrently — each occupies a slot for ~the
    # whole duration, so per-row service is NOT decode_s / frames)
    adm.note_done(0, decode_s=1.2, cost=6)
    assert adm.backlog == [2]
    assert adm.service_s == pytest.approx(1.2)
    assert not adm.place("default").shed
    with pytest.raises(RuntimeError, match="cost-9"):
        adm.note_done(0, decode_s=0.1, cost=9)
    with pytest.raises(ValueError, match="cost"):
        adm.place("default", cost=0)


def test_cache_entry_counts_frame_bytes():
    """Interpolate cache entries hold frames COPIES next to the
    concatenated strokes — nbytes must count both so max_bytes stays
    an honest bound."""
    from sketch_rnn_tpu.serve.cache import CacheEntry

    frames = [np.zeros((2, 5), np.float32), np.zeros((3, 5),
                                                     np.float32)]
    entry = CacheEntry(np.concatenate(frames), length=5, steps=5,
                       origin_uid=0, endpoint="interpolate",
                       frames=frames)
    assert entry.nbytes == 5 * 5 * 4 * 2  # concat + the frame copies
    plain = CacheEntry(np.zeros((4, 5), np.float32), 4, 4, 0)
    assert plain.nbytes == 4 * 5 * 4


def test_pool_rows_and_burst_chop(setup):
    """An interpolation occupies ``frames`` pool rows; the micro-burst
    chop never overflows pool_cap and never reorders priorities."""
    hps, model, params = setup
    assert EP.pool_rows_of(_mk(0, hps, "generate")) == 1
    assert EP.pool_rows_of(_mk(0, hps, "interpolate", frames=5)) == 5
    # a too-large grid is refused at the fleet door
    fleet = ServeFleet(model, hps, params, replicas=1, pool_cap=4)
    try:
        with pytest.raises(ValueError, match="pool_cap"):
            fleet.submit(_mk(0, hps, "interpolate", frames=5))
    finally:
        fleet.close()
