"""Training watchdog tests (ISSUE 7).

The detector is pure, so the acceptance scenario is pinned directly: a
synthetic loss-spike corpus trips at exactly the injected step and the
incident.json names the offending metric. The monitor/train() layers
are pinned for artifacts (incident.json, telemetry incident events,
the forced post-mortem checkpoint) and for the extended invisibility
contract: a warn-only watchdog on a healthy run changes NOTHING — the
metrics CSV is bitwise identical to a watchdog-off run and no incident
file appears.
"""

import csv
import json
import math
import os

import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.train import watchdog as wdog
from sketch_rnn_tpu.train.watchdog import (
    AnomalyHalt,
    Watchdog,
    WatchdogMonitor,
)
from sketch_rnn_tpu.utils import telemetry as tele

TINY = dict(batch_size=16, max_seq_len=32, enc_rnn_size=16,
            dec_rnn_size=24, z_size=8, num_mixture=3, hyper_rnn_size=8,
            hyper_embed_size=4)


def rows_with_spike(n=40, spike_at=25, base=2.0, spike=40.0):
    """A synthetic loss corpus: gently decaying noisy loss with one
    injected spike."""
    rows = []
    for i in range(n):
        loss = base - 0.01 * i + 0.02 * ((i * 7919) % 13 - 6) / 6
        if i == spike_at:
            loss = spike
        rows.append({"loss": loss, "grad_norm": 1.0 + 0.01 * (i % 5),
                     "steps_per_sec": 10.0})
    return rows


# -- pure detector -----------------------------------------------------------


def test_loss_spike_trips_at_injected_step():
    wd = Watchdog()
    corpus = rows_with_spike(spike_at=25)
    trips = {}
    for i, row in enumerate(corpus):
        anomalies = wd.feed(step=i * 20, row=row)
        if anomalies:
            trips[i] = anomalies
    assert list(trips) == [25]                    # exactly the injection
    (a,) = trips[25]
    assert a.kind == "spike" and a.metric == "loss"
    assert a.step == 25 * 20 and a.value == 40.0


def test_clean_noisy_stream_never_trips():
    wd = Watchdog()
    for i, row in enumerate(rows_with_spike(n=60, spike_at=10**9)):
        assert wd.feed(i, row) == []


def test_detection_precedes_absorption():
    """A spike is judged against PRIOR rows only — feeding the spike
    row twice trips twice (the first trip did not soften the z)."""
    wd = Watchdog(min_history=4)
    for i in range(8):
        wd.feed(i, {"loss": 1.0 + 0.001 * i})
    assert wd.feed(8, {"loss": 50.0})
    assert wd.feed(9, {"loss": 50.0})  # median still ~1.0 (MAD robust)


def test_nonfinite_named_per_metric():
    wd = Watchdog()
    out = wd.feed(5, {"loss": float("nan"), "grad_norm": float("inf"),
                      "recon": 1.0, "wall_time": float("nan")})
    kinds = {(a.kind, a.metric) for a in out}
    assert ("nonfinite", "loss") in kinds
    assert ("nonfinite", "grad_norm") in kinds
    assert all(m != "wall_time" for _, m in kinds)
    # NaN never enters the rolling baselines
    assert len(wd._hist["loss"]) == 0


def test_stall_detection_from_goodput_columns():
    wd = Watchdog(min_history=4, stall_min_s=0.5, stall_frac=0.75)
    starved = {"t_dispatch_s": 0.1, "t_feeder_wait_s": 4.0,
               "t_ckpt_wait_s": 0.5, "loss": 1.0}
    # startup gate: even a fully starved FIRST window cannot trip (the
    # prefetch queue filling at cold start legitimately looks stalled)
    assert wd.feed(0, dict(starved)) == []
    # healthy warmup windows: dispatch dominates
    for i in range(1, 4):
        assert wd.feed(i * 20, {"t_dispatch_s": 5.0,
                                "t_feeder_wait_s": 0.2,
                                "t_ckpt_wait_s": 0.0, "loss": 1.0}) == []
    # past min_history, a starved window trips and names the worst phase
    (a,) = wd.feed(80, dict(starved))
    assert a.kind == "stall" and a.metric == "t_feeder_wait_s"
    # below the absolute floor nothing fires (idle-but-fast windows)
    assert wd.feed(100, {"t_dispatch_s": 0.001,
                         "t_feeder_wait_s": 0.01}) == []


def test_throughput_collapse():
    wd = Watchdog(min_history=4, collapse_frac=0.25)
    for i in range(6):
        assert wd.feed(i, {"steps_per_sec": 10.0 + (i % 3)}) == []
    (a,) = wd.feed(6, {"steps_per_sec": 1.0})
    assert a.kind == "throughput" and a.metric == "steps_per_sec"
    # a moderate dip stays quiet
    wd2 = Watchdog(min_history=4, collapse_frac=0.25)
    for i in range(6):
        wd2.feed(i, {"steps_per_sec": 10.0})
    assert wd2.feed(6, {"steps_per_sec": 5.0}) == []


def test_last_rows_ring_bounded():
    wd = Watchdog(keep_rows=4)
    for i in range(10):
        wd.feed(i, {"loss": 1.0})
    rows = wd.last_rows()
    assert len(rows) == 4 and rows[-1]["step"] == 9


# -- monitor: incident artifacts ---------------------------------------------


def test_monitor_writes_incident_json_naming_metric(tmp_path):
    mon = WatchdogMonitor(str(tmp_path))
    for i, row in enumerate(rows_with_spike(spike_at=25)):
        mon(row, i * 20)   # drain check signature: (scalars, step)
    path = os.path.join(tmp_path, "incident.json")
    assert mon.incident_path == path and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["step"] == 500 and doc["halt"] is False
    (a,) = doc["anomalies"]
    assert a["kind"] == "spike" and a["metric"] == "loss"
    assert a["value"] == 40.0
    # the post-mortem carries the offending row and its predecessors
    assert doc["last_rows"][-1]["loss"] == 40.0
    assert len(doc["last_rows"]) > 1
    assert doc["telemetry"] is None   # tracing was off


def test_monitor_emits_telemetry_incident_and_snapshot(tmp_path):
    tel = tele.configure(trace_dir=str(tmp_path))
    mon = WatchdogMonitor(str(tmp_path))
    for i, row in enumerate(rows_with_spike(spike_at=25)):
        mon(row, i)
    assert tel.counters()[("watchdog", "incidents")] == 1
    evs = [e for e in tel.events() if e["type"] == "instant"
           and e["name"] == "incident"]
    assert len(evs) == 1 and evs[0]["args"]["metric"] == "loss"
    doc = json.load(open(mon.incident_path))
    assert doc["telemetry"]["counters"]["watchdog/incidents"] == 1
    tele.disable()


def test_monitor_halt_raises_and_serializes_nonfinite(tmp_path):
    mon = WatchdogMonitor(str(tmp_path), halt=True)
    with pytest.raises(AnomalyHalt) as e:
        mon({"loss": float("nan")}, 7)
    assert e.value.step == 7
    assert "loss" in str(e.value)
    # the post-mortem must be STRICT JSON even though the offending
    # row's raw NaN rides in last_rows (parse_constant fires on the
    # non-standard NaN/Infinity tokens lenient loaders accept)
    text = open(os.path.join(tmp_path, "incident.json")).read()
    doc = json.loads(text, parse_constant=lambda s: pytest.fail(
        f"non-strict JSON token {s} in incident.json"))
    assert doc["halt"] is True
    assert doc["anomalies"][0]["value"] == "nan"  # strict-JSON safe
    assert doc["last_rows"][-1]["loss"] == "nan"


def test_monitor_history_is_bounded_on_persistent_anomaly(tmp_path):
    """A condition that trips every window must not grow memory or the
    incident file without bound: the retained/serialized history caps
    at KEEP_ANOMALIES while the exact lifetime count stays exact."""
    mon = WatchdogMonitor(str(tmp_path))
    n = WatchdogMonitor.KEEP_ANOMALIES + 40
    for i in range(n):
        mon({"loss": float("nan")}, i)
    assert mon.total_anomalies == n
    assert len(mon.incidents) == WatchdogMonitor.KEEP_ANOMALIES
    doc = json.load(open(mon.incident_path))
    assert doc["total_anomalies"] == n
    assert len(doc["recent_anomalies"]) == WatchdogMonitor.KEEP_ANOMALIES
    assert doc["recent_anomalies"][-1]["step"] == n - 1


def test_monitor_without_workdir_warns_only(capsys):
    mon = WatchdogMonitor(None)
    mon({"loss": float("inf")}, 3)
    assert mon.incident_path is None
    assert "[watchdog] WARNING" in capsys.readouterr().out


# -- train() integration -----------------------------------------------------


def tiny_hps(**kw) -> HParams:
    return HParams(**{**TINY, **kw})


def make_loader(hps, n=64, seed=0):
    from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes

    seqs, labels = make_synthetic_strokes(
        n, num_classes=max(hps.num_classes, 1),
        min_len=10, max_len=hps.max_seq_len - 2, seed=seed)
    return DataLoader(seqs, hps, labels=labels, seed=seed)


def _run_smoke(tmp_path, name, **train_kw):
    from sketch_rnn_tpu.train.loop import train

    hps = tiny_hps(num_steps=4, log_every=2, save_every=10**9,
                   eval_every=10**9)
    d = str(tmp_path / name)
    train(hps, make_loader(hps), workdir=d, use_mesh=False,
          resume=False, **train_kw)
    with open(os.path.join(d, "train_metrics.csv")) as f:
        return d, list(csv.reader(f))


def test_warn_only_watchdog_is_bitwise_invisible_on_healthy_run(tmp_path):
    """The extended PR 6 pin: a healthy run with the watchdog armed
    (warn-only) logs a CSV bitwise identical to the watchdog-off run
    — same keys, same values except wall-clock columns — and leaves no
    incident artifacts."""
    d_off, rows_off = _run_smoke(tmp_path, "off")
    d_on, rows_on = _run_smoke(tmp_path, "on", watchdog=True)
    header_off, header_on = rows_off[0], rows_on[0]
    assert header_on == header_off       # watchdog adds NO columns
    timing_idx = {i for i, k in enumerate(header_off)
                  if k in ("wall_time", "steps_per_sec",
                           "strokes_per_sec", "strokes_per_sec_per_chip")
                  or k.startswith("t_")}
    assert len(rows_off) == len(rows_on)
    for ro, rn in zip(rows_off[1:], rows_on[1:]):
        for i, (vo, vn) in enumerate(zip(ro, rn)):
            if i not in timing_idx:
                assert vo == vn, header_off[i]
    for d in (d_off, d_on):
        assert not [f for f in os.listdir(d) if "incident" in f]
    assert wdog.armed_monitors() == ()   # train() disarmed in finally


def test_halt_on_anomaly_forces_incident_checkpoint(tmp_path, monkeypatch):
    """--halt_on_anomaly end to end: a tripping detector stops train()
    via AnomalyHalt, incident.json lands in the workdir, and the forced
    post-mortem checkpoint lands in <workdir>/incident/ — NOT the
    resume directory."""

    class TripOnSecondRow(Watchdog):
        def feed(self, step, row):
            super().feed(step, row)
            if step >= 4:
                return [wdog.Anomaly(
                    kind="spike", metric="loss", step=step,
                    value=float(row.get("loss", 0.0)), threshold=8.0,
                    detail="injected trip")]
            return []

    monkeypatch.setattr(wdog, "Watchdog", TripOnSecondRow)
    from sketch_rnn_tpu.train.checkpoint import latest_checkpoint
    from sketch_rnn_tpu.train.loop import train

    hps = tiny_hps(num_steps=6, log_every=2, save_every=10**9,
                   eval_every=10**9, metrics_defer=False)
    d = str(tmp_path / "halt")
    with pytest.raises(AnomalyHalt):
        train(hps, make_loader(hps), workdir=d, use_mesh=False,
              resume=False, halt_on_anomaly=True)
    doc = json.load(open(os.path.join(d, "incident.json")))
    assert doc["halt"] is True
    assert doc["anomalies"][0]["metric"] == "loss"
    # forced checkpoint: in incident/, and the resume dir holds none
    inc = os.path.join(d, "incident")
    assert latest_checkpoint(inc) is not None
    assert latest_checkpoint(d) is None
    assert wdog.armed_monitors() == ()
