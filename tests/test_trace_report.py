"""trace_report.py tests (ISSUE 6 CI satellite).

The report is the human surface of the telemetry contract, so its
numbers must RECONCILE with the authoritative sources: span-breakdown
totals with ``GoodputLedger.summary()`` (within rounding), and the
serve latency table with ``ServeEngine.run()``'s summary dict
(exactly — same ``np.percentile`` over the same floats).
"""

import json
import os
import sys
import time

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts import trace_report
from sketch_rnn_tpu.utils import telemetry as tele
from sketch_rnn_tpu.utils.profiling import GoodputLedger


def test_report_smoke_on_generated_jsonl(tmp_path, capsys):
    """End-to-end smoke: build a core, export, run main() — tables for
    spans, occupancy and latency all render; --json round-trips."""
    tel = tele.configure(trace_dir=str(tmp_path))
    with tel.span("dispatch", cat="train"):
        time.sleep(0.001)
    for i, v in enumerate((2, 4, 3, 4)):
        tel.gauge("slots_live", v, cat="serve", ts=tel.origin_perf + i)
    for uid, lat in enumerate((0.2, 0.4, 0.9)):
        tel.instant("complete", cat="serve",
                    args={"uid": uid, "queue_wait_s": lat / 4,
                          "decode_s": lat / 2, "latency_s": lat})
        tel.observe("latency_s", lat, cat="serve")
    paths = tel.export()

    assert trace_report.main([paths["jsonl"]]) == 0
    out = capsys.readouterr().out
    assert "span breakdown" in out and "dispatch" in out
    assert "slot occupancy" in out and "latency percentiles" in out

    # dir form + --json
    assert trace_report.main([str(tmp_path), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["occupancy"]["max"] == 4.0
    assert rep["occupancy"]["mean"] == pytest.approx(13 / 4)
    lat = {r["metric"]: r for r in rep["latency"]}
    assert lat["latency_s"]["count"] == 3
    assert lat["latency_s"]["p50_s"] == pytest.approx(0.4)
    # streaming-histogram approximations ride along
    assert lat["latency_s"]["hist_p50_s"] == pytest.approx(0.4, rel=0.05)


def test_span_breakdown_reconciles_with_goodput_ledger(tmp_path):
    """THE stall-attribution acceptance: per-phase totals printed from
    the JSONL equal GoodputLedger.summary()'s totals within rounding
    (identical floats accumulated in identical order on both sides)."""
    tel = tele.configure(trace_dir=str(tmp_path))
    led = GoodputLedger(("dispatch", "feeder_wait", "ckpt_wait"))
    for _ in range(3):
        with led.span("dispatch"):
            time.sleep(0.001)
    with led.span("feeder_wait"):
        pass
    with led.span("eval"):
        time.sleep(0.001)
    paths = tel.export()

    rows = {(r["cat"], r["name"]): r
            for r in trace_report.span_breakdown(trace_report.load(
                paths["jsonl"]))}
    s = led.summary()
    fired = {k: v for k, v in s.items() if v["count"]}
    assert set(fired) == {n for (c, n) in rows if c == "train"}
    for name, rec in fired.items():
        row = rows[("train", name)]
        assert row["count"] == rec["count"]
        assert row["total_s"] == pytest.approx(rec["total_s"], abs=1e-6)
        # ring events present -> per-event sum agrees with the agg line
        assert row["event_total_s"] == pytest.approx(row["total_s"],
                                                     abs=1e-9)


def test_load_tolerates_torn_tail_and_junk_lines(tmp_path):
    tel = tele.configure(trace_dir=str(tmp_path))
    with tel.span("x", cat="t"):
        pass
    paths = tel.export()
    with open(paths["jsonl"], "a") as f:
        f.write("not json at all\n")
        f.write('{"type": "span", "name": "torn…')  # killed mid-write
    data = trace_report.load(paths["jsonl"])
    assert ("t", "x") in data["agg"]
    assert all(e["name"] != "torn…" for e in data["events"])


def test_missing_path_is_one_line_error_not_traceback(tmp_path, capsys):
    """ISSUE 7 satellite: pointing the report at a missing/empty dir
    exits 2 with ONE actionable stderr line (no traceback)."""
    # missing dir / file
    assert trace_report.main([str(tmp_path / "nope")]) == 2
    err = capsys.readouterr().err
    assert "no telemetry stream" in err and "--trace_dir" in err
    assert "Traceback" not in err
    # a dir without a telemetry.jsonl inside
    assert trace_report.main([str(tmp_path)]) == 2
    err = capsys.readouterr().err
    assert "telemetry.jsonl" in err


def test_empty_and_meta_only_streams_are_one_line_errors(tmp_path,
                                                         capsys):
    empty = tmp_path / "telemetry.jsonl"
    empty.write_text("")
    assert trace_report.main([str(empty)]) == 2
    err = capsys.readouterr().err
    assert "no parseable telemetry lines" in err
    # meta-line-only stream (a run that configured but recorded nothing)
    tel = tele.configure(trace_dir=str(tmp_path))
    paths = tel.export()
    tele.disable()
    assert trace_report.main([paths["jsonl"]]) == 2
    err = capsys.readouterr().err
    assert "only its meta line" in err and "recorded no events" in err
    assert "Traceback" not in err


def test_report_warns_on_ring_drops(tmp_path, capsys):
    tel = tele.configure(trace_dir=str(tmp_path), capacity=4)
    for _ in range(10):
        with tel.span("s", cat="c"):
            pass
    paths = tel.export()
    assert trace_report.main([paths["jsonl"]]) == 0
    out = capsys.readouterr().out
    assert "dropped 6 events" in out
    # agg totals stay exact despite the drops
    data = trace_report.load(paths["jsonl"])
    assert data["agg"][("c", "s")][0] == 10
    # ISSUE 8 satellite: the drop count is a first-class --json field
    assert trace_report.main([paths["jsonl"], "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["ring_dropped"] == {"total": 6}


def test_host_filter_on_single_shard(tmp_path, capsys):
    """--host over a single shard matches the shard's own
    process_index; a miss is a one-line error, not an empty report."""
    tel = tele.configure(trace_dir=str(tmp_path), process_index=1,
                         host_count=2)
    with tel.span("dispatch", cat="train"):
        pass
    paths = tel.export()
    assert trace_report.main([paths["jsonl"], "--host", "1",
                              "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["host_filter"] == 1
    assert [s["name"] for s in rep["spans"]] == ["dispatch"]
    assert trace_report.main([paths["jsonl"], "--host", "0"]) == 2
    assert "no events for host 0" in capsys.readouterr().err


@pytest.fixture(scope="module")
def served_trace(tmp_path_factory):
    """One tiny traced serve run shared by the reconciliation tests
    (the chunk-program compile is the expensive part)."""
    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.serve import Request, ServeEngine

    hps = HParams(batch_size=8, max_seq_len=24, enc_rnn_size=12,
                  dec_rnn_size=16, z_size=6, num_mixture=3,
                  serve_slots=4, serve_chunk=2)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    eng = ServeEngine(model, hps, params)

    def req(i, cap):
        rng = np.random.default_rng(i)
        return Request(key=jax.random.key(1000 + i),
                       z=rng.standard_normal(hps.z_size).astype(np.float32),
                       temperature=0.8, max_len=cap)

    reqs = [req(i, 3 + (5 * i) % 13) for i in range(12)]
    d = tmp_path_factory.mktemp("serve_trace")
    tel = tele.configure(trace_dir=str(d))
    out = eng.run(list(reqs))
    paths = tel.export()
    tele.disable()
    return paths, out["metrics"]


def test_serve_latency_table_matches_engine_summary(served_trace):
    """Per-request event-derived p50/p95/p99 MATCH the engine's summary
    dict — the acceptance pin that streaming telemetry and the
    end-of-run aggregate can never tell different stories."""
    paths, metrics = served_trace
    rep = trace_report.report(trace_report.load(paths["jsonl"]))
    lat = {r["metric"]: r for r in rep["latency"]}
    assert lat["latency_s"]["count"] == metrics["completed"]
    for p in (50, 95, 99):
        assert round(lat["latency_s"][f"p{p}_s"], 6) == \
            metrics[f"latency_p{p}_s"]
    assert lat["queue_wait_s"]["mean_s"] == pytest.approx(
        metrics["queue_wait_mean_s"], abs=1e-6)


def test_serve_occupancy_timeline_present(served_trace):
    paths, metrics = served_trace
    rep = trace_report.report(trace_report.load(paths["jsonl"]))
    occ = rep["occupancy"]
    assert occ is not None
    # one occupancy sample per COLLECTED chunk; the final drained
    # in-flight (all-frozen) chunk counts in `chunks` but is never
    # collected, so it carries no sample
    assert occ["samples"] == metrics["chunks"] - 1
    assert 0 < occ["mean"] <= 4
    assert len(occ["sparkline"]) == min(60, occ["samples"])


def test_per_replica_occupancy_timelines(tmp_path, capsys):
    """ISSUE 9 satellite: a fleet trace records one slots_live_rNN
    gauge per replica engine and the report renders one timeline each,
    ordered by replica index; the bare single-engine gauge never leaks
    into the per-replica list (and vice versa)."""
    d = str(tmp_path / "fleet_trace")
    tel = tele.configure(trace_dir=d)
    for i in range(6):
        tel.gauge("slots_live_r01", 2 + (i % 2), cat="serve")
        tel.gauge("slots_live_r00", 1 + (i % 3), cat="serve")
    tel.gauge("slots_live", 3, cat="serve")   # a single-engine series
    paths = tel.export()
    tele.disable()
    rep = trace_report.report(trace_report.load(paths["jsonl"]))
    occ = rep["occupancy_replicas"]
    assert [o["replica"] for o in occ] == [0, 1]
    assert occ[0]["name"] == "slots_live_r00"
    assert occ[0]["samples"] == 6 and occ[1]["samples"] == 6
    assert occ[1]["max"] == 3.0
    # the aggregate timeline still reports the bare series only
    assert rep["occupancy"]["samples"] == 1
    # and the human rendering prints one sparkline per replica
    assert trace_report.main([paths["jsonl"]]) == 0
    out = capsys.readouterr().out
    assert "per replica" in out
    assert "replica 0:" in out and "replica 1:" in out
