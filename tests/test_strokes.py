import numpy as np
import pytest

from sketch_rnn_tpu.data import strokes as S


def _sketch(n=10, seed=0):
    rng = np.random.default_rng(seed)
    s = rng.normal(size=(n, 3)).astype(np.float32)
    s[:, 2] = 0
    s[4, 2] = 1
    s[-1, 2] = 1
    return s


def test_stroke5_roundtrip():
    s3 = _sketch(12)
    big = S.to_big_strokes(s3, max_len=20)
    assert big.shape == (20, 5)
    # one-hot pen state everywhere
    assert np.allclose(big[:, 2:].sum(axis=1), 1.0)
    # padding marked end-of-sketch
    assert np.all(big[12:, 4] == 1.0)
    back = S.to_normal_strokes(big)
    np.testing.assert_allclose(back, s3, rtol=1e-6)


def test_to_big_strokes_rejects_overflow():
    with pytest.raises(ValueError):
        S.to_big_strokes(_sketch(30), max_len=20)


def test_scale_factor_and_normalize():
    seqs = [_sketch(10, i) for i in range(5)]
    f = S.calculate_normalizing_scale_factor(seqs)
    normed = S.normalize_strokes(seqs, f)
    assert f > 0
    np.testing.assert_allclose(
        S.calculate_normalizing_scale_factor(normed), 1.0, rtol=1e-5)
    # pen states untouched
    np.testing.assert_array_equal(normed[0][:, 2], seqs[0][:, 2])


def test_random_scale_bounds():
    s = _sketch(50)
    rng = np.random.default_rng(0)
    out = S.random_scale(s, 0.15, rng)
    ratio_x = out[:, 0] / s[:, 0]
    assert np.all(np.abs(ratio_x - ratio_x[0]) < 1e-5)  # single factor per axis
    assert 0.85 <= ratio_x[0] <= 1.15
    np.testing.assert_array_equal(out[:, 2], s[:, 2])


def test_augment_preserves_total_displacement():
    s = _sketch(60, 3)
    rng = np.random.default_rng(1)
    out = S.augment_strokes(s, prob=0.5, rng=rng)
    assert len(out) < len(s)  # something was merged at prob=0.5, n=60
    np.testing.assert_allclose(out[:, 0:2].sum(0), s[:, 0:2].sum(0), atol=1e-4)
    # pen-lift structure preserved
    assert out[:, 2].sum() == s[:, 2].sum()


def test_augment_prob_zero_identity():
    s = _sketch(30, 4)
    out = S.augment_strokes(s, prob=0.0, rng=np.random.default_rng(0))
    np.testing.assert_array_equal(out, s)


def test_strokes_to_lines():
    s = np.array([[1, 0, 0], [1, 0, 1], [0, 1, 0], [0, 1, 1]], np.float32)
    lines = S.strokes_to_lines(s)
    assert len(lines) == 2
    assert lines[0] == [(1.0, 0.0), (2.0, 0.0)]
    assert lines[1] == [(2.0, 1.0), (2.0, 2.0)]
