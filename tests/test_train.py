"""Training subsystem tests: schedules, step, DP mesh, checkpoint, loop.

SURVEY.md §4 test pyramid: 1-step train test (loss decrease + finite
grads), 8-way virtual-CPU-mesh DP test, checkpoint roundtrip/resume.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.parallel.mesh import make_mesh, shard_batch
from sketch_rnn_tpu.train import (
    kl_weight_schedule,
    lr_schedule,
    make_eval_step,
    make_train_state,
    make_train_step,
)
from sketch_rnn_tpu.train.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from sketch_rnn_tpu.train.loop import evaluate, train

TINY = dict(batch_size=16, max_seq_len=32, enc_rnn_size=16, dec_rnn_size=24,
            z_size=8, num_mixture=3, hyper_rnn_size=8, hyper_embed_size=4)


def tiny_hps(**kw) -> HParams:
    return HParams(**{**TINY, **kw})


def make_loader(hps, n=64, seed=0, augment=False):
    seqs, labels = make_synthetic_strokes(
        n, num_classes=max(hps.num_classes, 1),
        min_len=10, max_len=hps.max_seq_len - 2, seed=seed)
    return DataLoader(seqs, hps, labels=labels, augment=augment, seed=seed)


# -- schedules --------------------------------------------------------------


def test_lr_schedule_endpoints():
    hps = tiny_hps()
    lr0 = float(lr_schedule(hps, 0))
    assert lr0 == pytest.approx(hps.learning_rate, rel=1e-6)
    lr_inf = float(lr_schedule(hps, 10**7))
    assert lr_inf == pytest.approx(hps.min_learning_rate, rel=1e-3)
    assert float(lr_schedule(hps, 100)) < lr0


def test_kl_weight_schedule_endpoints():
    hps = tiny_hps()
    w0 = float(kl_weight_schedule(hps, 0))
    assert w0 == pytest.approx(hps.kl_weight_start, rel=1e-5)
    w_inf = float(kl_weight_schedule(hps, 10**7))
    assert w_inf == pytest.approx(hps.kl_weight, rel=1e-4)
    # monotone rising
    ws = [float(kl_weight_schedule(hps, s)) for s in (0, 10, 100, 10000)]
    assert ws == sorted(ws)


# -- single-device training -------------------------------------------------


def test_train_step_decreases_loss_and_grads_finite():
    hps = tiny_hps()
    model = SketchRNN(hps)
    loader = make_loader(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh=None)
    key = jax.random.key(1)
    batch = loader.get_batch(0)
    first = None
    for i in range(30):
        key, k = jax.random.split(key)
        state, metrics = step(state, batch, k)
        assert np.isfinite(float(metrics["loss"])), f"step {i} non-finite"
        assert np.isfinite(float(metrics["grad_norm"]))
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first
    assert int(state.step) == 30


def test_unconditional_train_step():
    hps = tiny_hps(conditional=False)
    model = SketchRNN(hps)
    loader = make_loader(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh=None)
    state, metrics = step(state, loader.get_batch(0), jax.random.key(1))
    assert float(metrics["kl"]) == 0.0
    assert np.isfinite(float(metrics["loss"]))


# -- data-parallel mesh -----------------------------------------------------


def test_mesh_has_8_devices():
    mesh = make_mesh(tiny_hps())
    assert mesh.shape["data"] == 8


def test_mesh_train_matches_single_device():
    """8-way DP on the virtual mesh must be numerically equivalent to
    single-device training (same global batch, same key).

    Deterministic config (unconditional, dropout off): the shard_map step
    draws per-shard randomness (dropout masks, the z reparameterization
    noise) from fold_in(key, axis_index) — distributionally identical
    to, but bit-different from, the single-device draws (covered by the
    test below); with no randomness in the loss the math is identical
    and parity is exact.
    """
    hps = tiny_hps(use_recurrent_dropout=False, conditional=False)
    model = SketchRNN(hps)
    loader = make_loader(hps)
    mesh = make_mesh(hps)

    batch = loader.get_batch(0)
    key = jax.random.key(1)

    s1 = make_train_state(model, hps, jax.random.key(0))
    s2 = jax.tree_util.tree_map(jnp.copy, s1)

    step_single = make_train_step(model, hps, mesh=None)
    step_mesh = make_train_step(model, hps, mesh=mesh)

    s1, m1 = step_single(s1, batch, key)
    s2, m2 = step_mesh(s2, shard_batch(batch, mesh), key)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=2e-5)
    leaves1 = jax.tree_util.tree_leaves(s1.params)
    leaves2 = jax.tree_util.tree_leaves(s2.params)
    for a, b in zip(leaves1, leaves2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)


@pytest.mark.slow
def test_mesh_kl_metrics_match_single_device():
    """The psum'd-global KL path: with dropout off the encoder (and thus
    mu/presig, kl_raw and the free-bits floor) is deterministic, so the
    sharded step's KL metrics must equal the single-device step's exactly
    — this is the one term that is WRONG if floored per shard and
    averaged instead of floored on the global-batch mean."""
    hps = tiny_hps(use_recurrent_dropout=False)
    assert hps.conditional
    model = SketchRNN(hps)
    loader = make_loader(hps)
    mesh = make_mesh(hps)
    batch = loader.get_batch(0)
    key = jax.random.key(1)
    s1 = make_train_state(model, hps, jax.random.key(0))
    s2 = jax.tree_util.tree_map(jnp.copy, s1)
    _, m1 = make_train_step(model, hps, mesh=None)(s1, batch, key)
    _, m2 = make_train_step(model, hps, mesh=mesh)(
        s2, shard_batch(batch, mesh), key)
    np.testing.assert_allclose(float(m2["kl_raw"]), float(m1["kl_raw"]),
                               rtol=2e-5)
    np.testing.assert_allclose(float(m2["kl"]), float(m1["kl"]), rtol=2e-5)


@pytest.mark.slow
def test_mesh_train_with_dropout_learns():
    """With dropout on, the sharded step still trains (finite metrics,
    decreasing loss); exact single-device parity is impossible by design
    (per-shard iid mask draws)."""
    hps = tiny_hps()
    assert hps.use_recurrent_dropout
    model = SketchRNN(hps)
    loader = make_loader(hps)
    mesh = make_mesh(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh=mesh)
    losses = []
    for i in range(8):
        batch = shard_batch(loader.get_batch(i % loader.num_batches), mesh)
        state, m = step(state, batch, jax.random.key(i))
        losses.append(float(m["loss"]))
        assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_mesh_train_fused_production_config():
    """The PRODUCTION config — fused Pallas kernels + bf16 residuals +
    mesh DP — must compile and train under shard_map (pallas_call cannot
    be partitioned by GSPMD; explicit SPMD is what makes this legal).
    Runs in interpret mode on the virtual CPU mesh."""
    hps = tiny_hps(fused_rnn=True, fused_residual_dtype="bfloat16")
    model = SketchRNN(hps)
    loader = make_loader(hps)
    mesh = make_mesh(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh=mesh)
    losses = []
    for i in range(6):
        batch = shard_batch(loader.get_batch(i % loader.num_batches), mesh)
        state, m = step(state, batch, jax.random.key(i))
        losses.append(float(m["loss"]))
        assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0]


def test_mesh_train_fused_matches_single_device():
    """Fused kernels, deterministic config: sharded vs single-device."""
    hps = tiny_hps(use_recurrent_dropout=False, conditional=False,
                   fused_rnn=True)
    model = SketchRNN(hps)
    loader = make_loader(hps)
    mesh = make_mesh(hps)
    batch = loader.get_batch(0)
    key = jax.random.key(1)
    s1 = make_train_state(model, hps, jax.random.key(0))
    s2 = jax.tree_util.tree_map(jnp.copy, s1)
    s1, m1 = make_train_step(model, hps, mesh=None)(s1, batch, key)
    s2, m2 = make_train_step(model, hps, mesh=mesh)(
        s2, shard_batch(batch, mesh), key)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)


def test_mesh_batch_not_divisible_raises():
    hps = tiny_hps(batch_size=12)  # 12 % 8 != 0
    model = SketchRNN(hps)
    mesh = make_mesh(hps)
    with pytest.raises(ValueError, match="divisible"):
        make_train_step(model, hps, mesh=mesh)


def test_mesh_shape_validation():
    hps = tiny_hps(mesh_shape=(3,))
    with pytest.raises(ValueError):
        make_mesh(hps)
    mesh = make_mesh(tiny_hps(mesh_shape=(2, -1),
                              mesh_axes=("model", "data")))
    assert mesh.shape == {"model": 2, "data": 4}


# -- eval -------------------------------------------------------------------


def test_mesh_evaluate_matches_single_device():
    """The sharded eval sweep (psum'd weighted sums) must reproduce the
    single-device sweep exactly for a deterministic (unconditional)
    model — including the zero-weight wrap rows, however they fall
    across shards. Corpus of 40 with batch 16 -> a wrapped final batch."""
    hps = tiny_hps(conditional=False)
    model = SketchRNN(hps)
    loader = make_loader(hps, n=40)
    params = model.init_params(jax.random.key(0))
    mesh = make_mesh(hps)
    ev1 = evaluate(params, loader, make_eval_step(model, hps, mesh=None),
                   mesh=None)
    ev2 = evaluate(params, loader, make_eval_step(model, hps, mesh=mesh),
                   mesh)
    assert set(ev1) == set(ev2)
    for k in ev1:
        np.testing.assert_allclose(ev2[k], ev1[k], rtol=2e-5,
                                   err_msg=k)


def test_mesh_evaluate_fused_kl_matches_single_device():
    """Fused kernels on the mesh (f32 residuals so the comparison is
    exact): the deterministic KL metrics (encoder has no dropout in
    eval) must match the single-device sweep."""
    hps = tiny_hps(fused_rnn=True)
    model = SketchRNN(hps)
    loader = make_loader(hps, n=32)
    params = model.init_params(jax.random.key(0))
    mesh = make_mesh(hps)
    ev1 = evaluate(params, loader, make_eval_step(model, hps, mesh=None),
                   mesh=None)
    ev2 = evaluate(params, loader, make_eval_step(model, hps, mesh=mesh),
                   mesh)
    np.testing.assert_allclose(ev2["kl_raw"], ev1["kl_raw"], rtol=2e-5)
    np.testing.assert_allclose(ev2["kl"], ev1["kl"], rtol=2e-5)
    assert np.isfinite(ev2["loss"])


def test_eval_step_deterministic_and_masked():
    hps = tiny_hps()
    model = SketchRNN(hps)
    loader = make_loader(hps)
    params = model.init_params(jax.random.key(0))
    ev = make_eval_step(model, hps, mesh=None)
    batch = loader.get_batch(0)
    m1 = ev(params, batch, jax.random.key(5))
    m2 = ev(params, batch, jax.random.key(5))
    assert float(m1["loss"]) == float(m2["loss"])
    assert float(m1["kl_weight"]) == 1.0


def test_evaluate_sweep():
    hps = tiny_hps()
    model = SketchRNN(hps)
    loader = make_loader(hps, n=48)
    params = model.init_params(jax.random.key(0))
    ev = make_eval_step(model, hps, mesh=None)
    out = evaluate(params, loader, ev)
    assert "recon" in out and np.isfinite(out["recon"])


def test_evaluate_split_smaller_than_batch():
    # VERDICT r1 'no silent empty eval': a split smaller than one batch
    # must still produce metrics via the wrap-filled tail batch
    hps = tiny_hps()  # batch_size=16
    model = SketchRNN(hps)
    loader = make_loader(hps, n=5)
    assert loader.num_batches == 0
    params = model.init_params(jax.random.key(0))
    ev = make_eval_step(model, hps, mesh=None)
    out = evaluate(params, loader, ev)
    assert "recon" in out and np.isfinite(out["recon"])


def test_eval_weight_zero_rows_cannot_affect_metrics():
    # the wrap-filled tail rows carry weight 0; corrupting them must not
    # change any eval metric (this is the bias-free weighted-mean contract)
    hps = tiny_hps()  # batch_size=16
    model = SketchRNN(hps)
    loader = make_loader(hps, n=5)
    params = model.init_params(jax.random.key(0))
    ev = make_eval_step(model, hps, mesh=None)
    batch = loader.get_batch(0)
    np.testing.assert_array_equal(batch["weights"],
                                  (np.arange(16) < 5).astype(np.float32))
    m_ref = ev(params, batch, jax.random.key(7))

    bad = {k: np.array(v) for k, v in batch.items()}
    bad["strokes"][5:] = bad["strokes"][5:] * 1000.0 + 3.0  # garbage rows
    bad["seq_len"][5:] = hps.max_seq_len
    m_bad = ev(params, bad, jax.random.key(7))
    for k in m_ref:
        assert float(m_ref[k]) == pytest.approx(float(m_bad[k]), rel=1e-6), k
    assert float(m_ref["weight_sum"]) == 5.0


def test_evaluate_weighted_mean_over_split():
    # sweep weighting: metrics combine by real-row count, so the result is
    # the exact split mean — duplicated wrap rows add nothing
    hps = tiny_hps()
    model = SketchRNN(hps)
    loader = make_loader(hps, n=21)  # 1 full batch + wrapped tail of 5
    params = model.init_params(jax.random.key(0))
    ev = make_eval_step(model, hps, mesh=None)
    out = evaluate(params, loader, ev, key=jax.random.key(3))
    # manual: weighted average of the two batch results
    b0, b1 = loader.get_batch(0), loader.get_batch(1)
    m0 = ev(params, b0, jax.random.fold_in(jax.random.key(3), 0))
    m1 = ev(params, b1, jax.random.fold_in(jax.random.key(3), 1))
    want = (float(m0["recon"]) * 16 + float(m1["recon"]) * 5) / 21
    assert out["recon"] == pytest.approx(want, rel=1e-6)


def test_evaluate_multi_matches_per_batch():
    """The K-batch chunked sweep (one dispatch per K batches, VERDICT r3
    #5) must reproduce the per-batch sweep exactly — same per-index
    keys, same weighting — including a sub-K remainder (5 batches, K=2:
    two chunks + a single-batch tail) and a wrap-filled final batch."""
    from sketch_rnn_tpu.train.step import make_multi_eval_step

    hps = tiny_hps()
    model = SketchRNN(hps)
    loader = make_loader(hps, n=70)  # 5 eval batches at batch 16
    assert loader.num_eval_batches == 5
    params = model.init_params(jax.random.key(0))
    ev = make_eval_step(model, hps, mesh=None)
    mev = make_multi_eval_step(model, hps, mesh=None)
    base = evaluate(params, loader, ev, key=jax.random.key(3))
    for k in (2, 3, 8):  # remainder 1, remainder 2, k > n
        out = evaluate(params, loader, ev, key=jax.random.key(3),
                       multi=(mev, k))
        assert set(out) == set(base)
        for m in base:
            np.testing.assert_allclose(out[m], base[m], rtol=1e-6,
                                       err_msg=f"k={k} {m}")


def test_evaluate_multi_matches_on_mesh():
    from sketch_rnn_tpu.train.step import make_multi_eval_step

    hps = tiny_hps(conditional=False)
    model = SketchRNN(hps)
    loader = make_loader(hps, n=40)
    params = model.init_params(jax.random.key(0))
    mesh = make_mesh(hps)
    base = evaluate(params, loader, make_eval_step(model, hps, mesh), mesh)
    out = evaluate(params, loader, make_eval_step(model, hps, mesh), mesh,
                   multi=(make_multi_eval_step(model, hps, mesh), 2))
    for m in base:
        np.testing.assert_allclose(out[m], base[m], rtol=2e-5, err_msg=m)


def test_evaluate_per_class_multi_matches():
    from sketch_rnn_tpu.train.loop import evaluate_per_class
    from sketch_rnn_tpu.train.step import (make_multi_per_class_eval_step,
                                           make_per_class_eval_step)

    hps = tiny_hps(num_classes=3)
    model = SketchRNN(hps)
    loader = make_loader(hps, n=53)
    params = model.init_params(jax.random.key(0))
    step = make_per_class_eval_step(model, hps, mesh=None)
    mstep = make_multi_per_class_eval_step(model, hps, mesh=None)
    base = evaluate_per_class(params, loader, step, 3,
                              key=jax.random.key(5))
    out = evaluate_per_class(params, loader, step, 3,
                             key=jax.random.key(5), multi=(mstep, 2))
    for c in range(3):
        assert (base[c] is None) == (out[c] is None)
        if base[c] is not None:
            for m in base[c]:
                np.testing.assert_allclose(out[c][m], base[c][m],
                                           rtol=1e-6, err_msg=f"{c}/{m}")


def test_evaluate_empty_loader_raises_loudly():
    hps = tiny_hps()
    model = SketchRNN(hps)
    loader = DataLoader([], hps)
    params = model.init_params(jax.random.key(0))
    ev = make_eval_step(model, hps, mesh=None)
    with pytest.raises(ValueError, match="no common batches"):
        evaluate(params, loader, ev)


# -- checkpoint -------------------------------------------------------------


def test_checkpoint_roundtrip(tmp_path):
    hps = tiny_hps()
    model = SketchRNN(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    state = state._replace(step=jnp.asarray(7, jnp.int32))
    d = str(tmp_path)
    save_checkpoint(d, state, scale_factor=3.5, hps=hps)
    assert latest_checkpoint(d) == 7

    template = make_train_state(model, hps, jax.random.key(99))
    restored, scale, meta = restore_checkpoint(d, template)
    assert scale == 3.5
    assert int(restored.step) == 7
    assert meta["hps"]["dec_rnn_size"] == hps.dec_rnn_size
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_version_stamped_and_future_rejected(tmp_path):
    """VERDICT r4 #8: the sidecar carries format_version; a checkpoint
    from a FUTURE format must fail loudly, not half-restore."""
    import json

    from sketch_rnn_tpu.train.checkpoint import FORMAT_VERSION, _paths

    hps = tiny_hps()
    model = SketchRNN(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    d = str(tmp_path)
    save_checkpoint(d, state, scale_factor=1.0, hps=hps)
    step = latest_checkpoint(d)
    _, meta_path = _paths(d, step)
    with open(meta_path) as f:
        meta = json.load(f)
    assert meta["format_version"] == FORMAT_VERSION

    meta["format_version"] = FORMAT_VERSION + 1
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    with pytest.raises(RuntimeError, match="format_version"):
        restore_checkpoint(d, state)


def test_checkpoint_missing_version_is_v1(tmp_path):
    """Pre-versioning sidecars (rounds 1-4, the committed demo) must
    keep restoring: absence of the field means version 1."""
    import json

    from sketch_rnn_tpu.train.checkpoint import _paths

    hps = tiny_hps()
    model = SketchRNN(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    state = state._replace(step=jnp.asarray(4, jnp.int32))
    d = str(tmp_path)
    save_checkpoint(d, state, scale_factor=2.0, hps=hps)
    _, meta_path = _paths(d, 4)
    with open(meta_path) as f:
        meta = json.load(f)
    del meta["format_version"]
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    restored, scale, _ = restore_checkpoint(d, state)
    assert int(restored.step) == 4 and scale == 2.0


def test_checkpoint_truncated_msgpack_fails_loudly(tmp_path):
    """A torn/corrupt msgpack (outside the atomic-rename path: disk
    damage, manual copy) must raise a loud RuntimeError naming the
    file, never a silent partial restore."""
    hps = tiny_hps()
    model = SketchRNN(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    d = str(tmp_path)
    path = save_checkpoint(d, state, scale_factor=1.0, hps=hps)
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[:len(raw) // 3])
    with pytest.raises(RuntimeError, match="cannot restore"):
        restore_checkpoint(d, state)
    with open(path, "wb") as f:
        f.write(b"\x00garbage\xff" * 100)
    with pytest.raises(RuntimeError, match="cannot restore"):
        restore_checkpoint(d, state)


def test_checkpoint_prune_keeps_latest(tmp_path):
    hps = tiny_hps()
    model = SketchRNN(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    d = str(tmp_path)
    for s in (1, 2, 3, 4, 5):
        save_checkpoint(d, state._replace(step=jnp.asarray(s, jnp.int32)),
                        1.0, hps, keep=2)
    names = sorted(os.listdir(d))
    assert latest_checkpoint(d) == 5
    assert sum(n.endswith(".msgpack") for n in names) == 2


def test_checkpoint_orphan_files_skipped(tmp_path):
    # a crash mid-save leaves an incomplete pair; resume must fall back to
    # the previous COMPLETE checkpoint (ADVICE r1: sidecar crash window)
    hps = tiny_hps()
    model = SketchRNN(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    d = str(tmp_path)
    save_checkpoint(d, state._replace(step=jnp.asarray(3, jnp.int32)),
                    1.5, hps)
    # orphan msgpack without sidecar (legacy crash ordering)
    open(os.path.join(d, "ckpt_00000009.msgpack"), "wb").write(b"junk")
    assert latest_checkpoint(d) == 3
    restored, scale, _ = restore_checkpoint(d, state)
    assert int(restored.step) == 3 and scale == 1.5
    # orphan sidecar without msgpack (current crash ordering) is inert too
    open(os.path.join(d, "ckpt_00000011.json"), "w").write("{}")
    assert latest_checkpoint(d) == 3


def test_metrics_csv_resume_alignment(tmp_path):
    # ADVICE r1: on resume into an existing CSV the original header must
    # govern column order; new keys are dropped, missing keys left empty
    import csv

    from sketch_rnn_tpu.train.metrics import MetricsWriter
    d = str(tmp_path)
    w1 = MetricsWriter(d, "train")
    w1.write(1, {"loss": 1.0, "recon": 2.0})
    w2 = MetricsWriter(d, "train")  # fresh process, e.g. after resume
    w2.write(2, {"loss": 0.5, "grad_norm": 3.0})
    with open(os.path.join(d, "train_metrics.csv"), newline="") as f:
        rows = list(csv.DictReader(f))
    assert rows[0]["loss"] == "1.0" and rows[0]["recon"] == "2.0"
    assert rows[1]["loss"] == "0.5" and rows[1]["recon"] == ""
    assert "grad_norm" not in rows[1]


def test_metrics_csv_headerless_file_recovers(tmp_path):
    # a crash can leave a created-but-empty CSV; the writer must rewrite
    # the header instead of appending headerless data rows
    import csv

    from sketch_rnn_tpu.train.metrics import MetricsWriter
    d = str(tmp_path)
    open(os.path.join(d, "train_metrics.csv"), "w").close()
    w = MetricsWriter(d, "train")
    w.write(1, {"loss": 1.0})
    rows = list(csv.DictReader(
        open(os.path.join(d, "train_metrics.csv"), newline="")))
    assert rows[0]["loss"] == "1.0"


def test_checkpoint_prune_removes_orphans(tmp_path):
    hps = tiny_hps()
    model = SketchRNN(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    d = str(tmp_path)
    save_checkpoint(d, state._replace(step=jnp.asarray(3, jnp.int32)),
                    1.0, hps)
    # crashed-save debris: a lone sidecar, a lone msgpack, and a .tmp
    open(os.path.join(d, "ckpt_00000005.json"), "w").write("{}")
    open(os.path.join(d, "ckpt_00000007.msgpack"), "wb").write(b"junk")
    open(os.path.join(d, "ckpt_00000008.msgpack.tmp"), "wb").write(b"junk")
    save_checkpoint(d, state._replace(step=jnp.asarray(9, jnp.int32)),
                    1.0, hps, keep=2)
    names = set(os.listdir(d))
    assert "ckpt_00000005.json" not in names
    assert "ckpt_00000007.msgpack" not in names
    assert "ckpt_00000008.msgpack.tmp" not in names
    assert latest_checkpoint(d) == 9


def test_train_fails_fast_on_unevaluable_valid_split(tmp_path):
    hps = tiny_hps(num_steps=4, eval_every=2)
    loader = make_loader(hps, n=32)
    with pytest.raises(ValueError, match="not evaluable"):
        train(hps, loader, valid_loader=DataLoader([], hps),
              workdir=str(tmp_path), use_mesh=False)


# -- end-to-end loop --------------------------------------------------------


@pytest.mark.slow
def test_train_loop_end_to_end_with_resume(tmp_path):
    hps = tiny_hps(num_steps=6, save_every=3, eval_every=3, log_every=2)
    loader = make_loader(hps, n=32, augment=True)
    valid = make_loader(hps, n=16, seed=9)
    d = str(tmp_path)
    state = train(hps, loader, valid_loader=valid, scale_factor=2.0,
                  workdir=d, use_mesh=True)
    assert int(state.step) == 6
    assert latest_checkpoint(d) == 6
    assert os.path.exists(os.path.join(d, "train_metrics.csv"))
    assert os.path.exists(os.path.join(d, "valid_metrics.jsonl"))
    # resume continues, does not restart
    state2 = train(hps.replace(num_steps=8), loader, workdir=d,
                   use_mesh=True)
    assert int(state2.step) == 8


# -- goodput runtime (deferred metrics, async checkpoints) ------------------


def test_train_loop_no_host_sync_between_dispatches(tmp_path, monkeypatch):
    """Tier-1 goodput guard (ISSUE 3): with metrics_defer on, the loop
    must never convert a window's device metrics eagerly — the counting
    shim around the ONE device->host conversion seam
    (metrics.scalars_from_device) proves every window drains exactly one
    window late, i.e. only after the next window's compute has been
    dispatched."""
    import sketch_rnn_tpu.train.metrics as M

    events = []
    real_convert = M.scalars_from_device
    real_push = M.MetricsDrain.push

    def counting_convert(metrics):
        events.append(("convert",))
        return real_convert(metrics)

    def recording_push(self, step, device_metrics, extras=None):
        events.append(("push", step))
        return real_push(self, step, device_metrics, extras)

    monkeypatch.setattr(M, "scalars_from_device", counting_convert)
    monkeypatch.setattr(M.MetricsDrain, "push", recording_push)

    hps = tiny_hps(num_steps=8, log_every=2, eval_every=10**9,
                   save_every=10**9)
    assert hps.metrics_defer  # default ON
    loader = make_loader(hps)
    train(hps, loader, workdir=str(tmp_path), use_mesh=False)

    pushes = [e[1] for e in events if e[0] == "push"]
    assert pushes == [2, 4, 6, 8]
    # exactly one conversion per window — and NONE before the second
    # push: window W's floats materialize only once window W+1 has been
    # dispatched (deferral depth 1 honored; the tail drains at flush)
    assert events.count(("convert",)) == 4
    first_convert = events.index(("convert",))
    assert events.index(("push", 4)) < first_convert < \
        events.index(("push", 6))


def test_train_loop_sync_vs_overlapped_identical(tmp_path):
    """The overlapped runtime is semantics-preserving end to end: the
    fully synchronous loop and the async/deferred loop produce
    byte-identical final checkpoints and identical logged metric values
    (wall-clock columns excluded) from the same seed."""
    import json

    from sketch_rnn_tpu.train.checkpoint import _paths

    hps = tiny_hps(num_steps=6, save_every=2, eval_every=10**9,
                   log_every=2)
    rows = {}
    for mode, overlapped in (("sync", False), ("async", True)):
        d = str(tmp_path / mode)
        run_hps = hps.replace(async_checkpoint=overlapped,
                              metrics_defer=overlapped)
        train(run_hps, make_loader(hps, seed=3), workdir=d,
              use_mesh=False, resume=False)
        assert latest_checkpoint(d) == 6
        with open(os.path.join(d, "train_metrics.jsonl")) as f:
            rows[mode] = [json.loads(l) for l in f]
    # msgpack bytes: the async writer runs the same commit code on the
    # same host values. Step 4 is the load-bearing comparison — written
    # ONLY by the in-loop path (async vs sync); the final step could be
    # rewritten by the post-loop synchronous save in both runs
    for s in (4, 6):
        pa = _paths(str(tmp_path / "sync"), s)[0]
        pb = _paths(str(tmp_path / "async"), s)[0]
        assert open(pa, "rb").read() == open(pb, "rb").read(), s
    wall = ("wall_time", "steps_per_sec", "strokes_per_sec",
            "strokes_per_sec_per_chip")
    strip = lambda r: {k: v for k, v in r.items()
                       if k not in wall and not k.startswith("t_")}
    assert [strip(r) for r in rows["sync"]] == \
        [strip(r) for r in rows["async"]]


def test_train_loop_divergence_stops_one_window_late(tmp_path):
    """check_finite still stops training on the drained values: a NaN in
    window W raises by window W+1, and window W's row IS persisted first
    (the divergence-leaves-its-record discipline)."""
    import json

    import sketch_rnn_tpu.train.loop as L

    hps = tiny_hps(num_steps=20, log_every=2, eval_every=10**9,
                   save_every=10**9)
    loader = make_loader(hps)

    real_step = L.make_multi_train_step

    def poisoned(model, hps_, mesh, **kw):
        fn = real_step(model, hps_, mesh, **kw)

        def wrapped(state, batch, key):
            state, metrics = fn(state, batch, key)
            # poison from step 6 on: first poisoned window is step 6
            metrics = dict(metrics)
            metrics["loss"] = jax.lax.cond(
                state.step >= 6, lambda l: l * jnp.nan, lambda l: l,
                metrics["loss"])
            return state, metrics

        return wrapped

    orig = L.make_multi_train_step
    L.make_multi_train_step = poisoned
    try:
        with pytest.raises(FloatingPointError, match="step 6"):
            train(hps, loader, workdir=str(tmp_path), use_mesh=False)
    finally:
        L.make_multi_train_step = orig
    with open(os.path.join(str(tmp_path), "train_metrics.jsonl")) as f:
        steps = [json.loads(l)["step"] for l in f]
    assert 6 in steps  # the diagnostic row landed before the raise


def test_train_loop_final_save_overwrites_stale_same_step_ckpt(tmp_path):
    """--no_resume reruns into a used workdir: when no cadenced save
    lands on the final step, the final write must still happen even
    though a STALE checkpoint of that step exists from the previous run
    — the skip-redundant-final-save optimization may only trust saves
    THIS run made."""
    hps = tiny_hps(num_steps=4, save_every=10**9, eval_every=10**9,
                   log_every=10**9)
    d = str(tmp_path)
    loader = make_loader(hps)
    train(hps, loader, workdir=d, use_mesh=False, seed=0, resume=False)
    from sketch_rnn_tpu.train.checkpoint import _paths
    path = _paths(d, 4)[0]
    first = open(path, "rb").read()
    train(hps, loader, workdir=d, use_mesh=False, seed=1, resume=False)
    assert open(path, "rb").read() != first  # fresh weights, not stale


def test_train_loop_skips_redundant_final_save(tmp_path, monkeypatch):
    """When the last cadenced save already committed the final step,
    the post-loop save must not re-fetch and rewrite the same bytes."""
    import sketch_rnn_tpu.train.checkpoint as C

    writes = []
    real = C.write_checkpoint

    def counting(ckpt_dir, host_state, *a, **k):
        writes.append(int(host_state.step))
        return real(ckpt_dir, host_state, *a, **k)

    monkeypatch.setattr(C, "write_checkpoint", counting)
    # the sync in-loop path routes through checkpoint.write_checkpoint;
    # async routes through its own import — pin the sync path here
    hps = tiny_hps(num_steps=4, save_every=2, eval_every=10**9,
                   log_every=10**9, async_checkpoint=False)
    train(hps, make_loader(hps), workdir=str(tmp_path), use_mesh=False)
    assert writes == [2, 4]  # no duplicate final write of step 4
    assert latest_checkpoint(str(tmp_path)) == 4


def test_train_loop_never_checkpoints_a_diverged_window(tmp_path):
    """A NaN in the save step's own log window must raise BEFORE the
    checkpoint commits (the drain flushes ahead of every save):
    otherwise the diverged state becomes latest_checkpoint and
    resume-from-latest restores NaN weights."""
    import sketch_rnn_tpu.train.loop as L

    hps = tiny_hps(num_steps=8, log_every=2, save_every=4,
                   eval_every=10**9)
    loader = make_loader(hps)

    real_step = L.make_multi_train_step

    def poisoned(model, hps_, mesh, **kw):
        fn = real_step(model, hps_, mesh, **kw)

        def wrapped(state, batch, key):
            state, metrics = fn(state, batch, key)
            metrics = dict(metrics)
            metrics["loss"] = jax.lax.cond(
                state.step >= 4, lambda l: l * jnp.nan, lambda l: l,
                metrics["loss"])
            return state, metrics

        return wrapped

    L.make_multi_train_step = poisoned
    try:
        with pytest.raises(FloatingPointError, match="step 4"):
            train(hps, loader, workdir=str(tmp_path), use_mesh=False)
    finally:
        L.make_multi_train_step = real_step
    # the step-4 save never committed: no checkpoint carries NaN state
    assert latest_checkpoint(str(tmp_path)) is None


def test_train_loop_pending_window_persisted_on_crash(tmp_path,
                                                      monkeypatch):
    """An unrelated raise (eval failure here) must not lose the pending
    deferred window: the finally-block best-effort flush writes it, so
    a post-mortem sees the last metrics before the crash — the
    synchronous loop's every-window-persisted discipline."""
    import json

    import sketch_rnn_tpu.train.loop as L

    hps = tiny_hps(num_steps=8, log_every=2, eval_every=4,
                   save_every=10**9)
    loader = make_loader(hps)
    valid = make_loader(hps, n=16, seed=9)

    def boom(*a, **k):
        raise RuntimeError("eval exploded")

    monkeypatch.setattr(L, "evaluate", boom)
    with pytest.raises(RuntimeError, match="eval exploded"):
        train(hps, loader, valid_loader=valid, workdir=str(tmp_path),
              use_mesh=False)
    with open(os.path.join(str(tmp_path), "train_metrics.jsonl")) as f:
        steps = [json.loads(l)["step"] for l in f]
    # eval raised at step 4, right after window 4 was pushed (still
    # pending): both windows must be on disk
    assert steps == [2, 4]


def test_train_loop_async_ckpt_failure_stops_training(tmp_path,
                                                      monkeypatch):
    """A background save failure must stop the run (at the next save or
    the final wait), not be silently dropped."""
    import sketch_rnn_tpu.train.async_ckpt as AC

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(AC, "write_checkpoint", boom)
    hps = tiny_hps(num_steps=4, save_every=2, eval_every=10**9,
                   log_every=10**9)
    with pytest.raises(RuntimeError, match="async checkpoint"):
        train(hps, make_loader(hps), workdir=str(tmp_path),
              use_mesh=False)


# -- multi-host helpers (single-process semantics) --------------------------


def test_multihost_helpers_single_process():
    from sketch_rnn_tpu.parallel import multihost as mh
    assert mh.process_count() == 1
    assert mh.process_index() == 0
    assert mh.is_primary()
    hps = tiny_hps(batch_size=16)
    assert mh.local_batch_hps(hps).batch_size == 16
    mh.initialize()  # no-op without cluster env


def test_loader_host_striping(tmp_path):
    """load_dataset host striping: disjoint shards, identical scale."""
    from sketch_rnn_tpu.data.loader import load_dataset, write_synthetic_npz
    hps = tiny_hps(batch_size=4, max_seq_len=100)
    path = str(tmp_path / "cat.npz")
    write_synthetic_npz(path, num_train=40, num_valid=8, num_test=8,
                        max_len=90)
    t0, _, _, s0 = load_dataset(hps.replace(data_set=("cat.npz",)),
                                data_dir=str(tmp_path), host_id=0,
                                num_hosts=2)
    t1, _, _, s1 = load_dataset(hps.replace(data_set=("cat.npz",)),
                                data_dir=str(tmp_path), host_id=1,
                                num_hosts=2)
    assert s0 == s1  # scale from the FULL pre-shard split on every host
    assert len(t0) + len(t1) == 40


def test_e2e_overfit_tiny_corpus(tmp_path):
    """SURVEY §4: end-to-end overfit on a tiny synthetic stroke set —
    recon loss must drop substantially from its initial value."""
    hps = tiny_hps(batch_size=8, max_seq_len=24, num_steps=120,
                   save_every=10000, eval_every=10000, log_every=60,
                   use_recurrent_dropout=False, augment_stroke_prob=0.0)
    seqs, labels = make_synthetic_strokes(8, min_len=8, max_len=20, seed=4)
    loader = DataLoader(seqs, hps, labels=labels, seed=0)
    loader.normalize(loader.calculate_normalizing_scale_factor())

    model = SketchRNN(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh=None)
    batch = loader.get_batch(0)
    first = None
    for i in range(hps.num_steps):
        state, m = step(state, batch, jax.random.fold_in(jax.random.key(1), i))
        if first is None:
            first = float(m["recon"])
    last = float(m["recon"])
    assert last < 0.55 * first, f"no overfit: {first:.3f} -> {last:.3f}"


# -- per-class eval (masked sweep) ------------------------------------------


def test_per_class_eval_matches_filter_by_label():
    """On a deterministic (non-conditional) model the masked per-class
    sweep must reproduce the filter_by_label per-class sweep exactly:
    both are weighted means over the same class examples."""
    from sketch_rnn_tpu.train import make_per_class_eval_step
    from sketch_rnn_tpu.train.loop import evaluate_per_class

    hps = tiny_hps(num_classes=3, conditional=False, kl_tolerance=0.0)
    model = SketchRNN(hps)
    loader = make_loader(hps, n=48, seed=3)
    eval_step = make_eval_step(model, hps, mesh=None)
    pc_step = make_per_class_eval_step(model, hps, mesh=None)
    state = make_train_state(model, hps, jax.random.key(0))

    per = evaluate_per_class(state.params, loader, pc_step,
                             hps.num_classes, mesh=None)
    for c in range(hps.num_classes):
        sub = loader.filter_by_label(c)
        if sub.num_eval_batches == 0:
            assert per[c] is None
            continue
        ref = evaluate(state.params, sub, eval_step, mesh=None)
        for k in ("offset_nll", "pen_ce", "recon", "loss"):
            assert per[c][k] == pytest.approx(ref[k], rel=2e-4), \
                f"class {c} metric {k}"


def test_per_class_eval_mesh_consistent_with_overall():
    """On the 8-device mesh (conditional model, stochastic z): per-class
    metrics combined weighted by class counts must equal the overall
    eval sweep for every linear metric — both sweeps share the same
    batch schedule and key discipline, so even the z draws coincide."""
    from sketch_rnn_tpu.train import make_per_class_eval_step
    from sketch_rnn_tpu.train.loop import evaluate_per_class

    hps = tiny_hps(num_classes=3)
    model = SketchRNN(hps)
    loader = make_loader(hps, n=48, seed=4)
    mesh = make_mesh(hps)
    eval_step = make_eval_step(model, hps, mesh)
    pc_step = make_per_class_eval_step(model, hps, mesh)
    state = make_train_state(model, hps, jax.random.key(0))

    overall = evaluate(state.params, loader, eval_step, mesh)
    per = evaluate_per_class(state.params, loader, pc_step,
                             hps.num_classes, mesh)
    counts = np.array([np.sum(loader.labels == c)
                       for c in range(hps.num_classes)], np.float64)
    assert counts.sum() == len(loader)
    for k in ("offset_nll", "pen_ce", "kl_raw", "recon"):
        combined = sum(per[c][k] * counts[c] for c in range(hps.num_classes)
                       if per[c] is not None) / counts.sum()
        assert combined == pytest.approx(overall[k], rel=2e-4), k


# -- multi-step train calls (steps_per_call) --------------------------------


@pytest.mark.slow
def test_multi_step_equals_k_single_steps():
    """One K=3 scan call must be step-for-step identical to 3 single-step
    calls on the same micro-batches with keys fold_in(call_key, i)."""
    from sketch_rnn_tpu.data.prefetch import prefetch_batches
    from sketch_rnn_tpu.train import make_multi_train_step

    hps = tiny_hps(steps_per_call=3)
    model = SketchRNN(hps)
    loader = make_loader(hps)
    mesh = make_mesh(hps)
    feeder = prefetch_batches(loader, mesh, depth=1, stack=3)
    try:
        stacked = feeder.get()
    finally:
        feeder.close()
    key = jax.random.key(7)

    s_multi = make_train_state(model, hps, jax.random.key(0))
    s_multi, m_multi = make_multi_train_step(model, hps, mesh)(
        s_multi, stacked, key)

    s_single = make_train_state(model, hps, jax.random.key(0))
    single = make_train_step(model, hps, mesh)
    singles = []
    for i in range(3):
        b = jax.tree_util.tree_map(lambda x: x[i], stacked)
        s_single, m_single = single(s_single, b,
                                    jax.random.fold_in(key, i))
        singles.append(m_single)

    assert int(s_multi.step) == int(s_single.step) == 3
    for a, b in zip(jax.tree_util.tree_leaves(s_multi.params),
                    jax.tree_util.tree_leaves(s_single.params)):
        # the scan is a different XLA program than 3 single steps, so
        # f32 reassociation noise up to ~1.3e-6 is expected (observed
        # to straddle a 1e-6 bound depending on how many programs the
        # process compiled before this one — the isolation-run flake)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-6, rtol=3e-6)
    # returned metrics are the K-MEAN over micro-steps, plus the window's
    # max grad_norm; lr is the last micro-step's schedule value
    assert float(m_multi["loss"]) == pytest.approx(
        np.mean([float(m["loss"]) for m in singles]), rel=1e-5)
    assert float(m_multi["grad_norm"]) == pytest.approx(
        np.mean([float(m["grad_norm"]) for m in singles]), rel=1e-5)
    assert float(m_multi["grad_norm_max"]) == pytest.approx(
        max(float(m["grad_norm"]) for m in singles), rel=1e-5)
    assert float(m_multi["lr"]) == pytest.approx(
        float(singles[-1]["lr"]), rel=1e-6)


@pytest.mark.slow
def test_multi_step_k1_is_single_step():
    from sketch_rnn_tpu.train import make_multi_train_step

    hps = tiny_hps()  # steps_per_call defaults to 1
    model = SketchRNN(hps)
    loader = make_loader(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_multi_train_step(model, hps, mesh=None)
    state, metrics = step(state, loader.get_batch(0), jax.random.key(1))
    assert int(state.step) == 1
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow
def test_train_loop_steps_per_call_with_remainder(tmp_path):
    """num_steps=5 with K=2: two K-calls + a 1-step remainder replay;
    cadence triggers fire on crossings and the final state is step 5."""
    hps = tiny_hps(steps_per_call=2, num_steps=5, log_every=2,
                   eval_every=4, save_every=4)
    loader = make_loader(hps)
    valid = make_loader(hps, n=16, seed=9)
    state = train(hps, loader, valid_loader=valid,
                  workdir=str(tmp_path), seed=0, use_mesh=True)
    assert int(state.step) == 5
    assert latest_checkpoint(str(tmp_path)) is not None


@pytest.mark.slow
def test_train_loop_profile_trace(tmp_path):
    """--profile captures a jax.profiler trace of steps ~10-20 (normal
    in-loop stop path; the error path is covered by the test below)."""
    hps = tiny_hps(num_steps=25, log_every=10, eval_every=100,
                   save_every=100)
    loader = make_loader(hps, n=32)
    state = train(hps, loader, workdir=str(tmp_path), seed=0,
                  use_mesh=False, profile=True)
    assert int(state.step) == 25
    trace_dir = os.path.join(str(tmp_path), "trace")
    assert os.path.isdir(trace_dir) and os.listdir(trace_dir)


@pytest.mark.slow
def test_train_loop_profile_trace_closed_on_error(tmp_path, monkeypatch):
    """A raise while a --profile trace is open must close the session in
    train()'s finally (ADVICE r1: a leaked session poisons any later
    start_trace in the process)."""
    import sketch_rnn_tpu.train.loop as L

    # sync saves: the monkeypatched save_checkpoint must be the one the
    # loop calls at step 12 (the async path routes through
    # AsyncCheckpointer and would only raise after the loop)
    hps = tiny_hps(num_steps=30, log_every=10, eval_every=1000,
                   save_every=12, async_checkpoint=False)
    loader = make_loader(hps, n=32)

    def boom(*a, **k):
        raise RuntimeError("save failed")

    monkeypatch.setattr(L, "save_checkpoint", boom)
    # save fires at step 12, inside the (10, 20) profile span
    with pytest.raises(RuntimeError, match="save failed"):
        train(hps, loader, workdir=str(tmp_path), seed=0,
              use_mesh=False, profile=True)
    # the finally path must have closed the trace: a fresh session then
    # starts (and stops) cleanly instead of raising "already started"
    jax.profiler.start_trace(os.path.join(str(tmp_path), "t2"))
    jax.profiler.stop_trace()


# -- crash-equivalent resume (ISSUE 10) -------------------------------------


def test_resume_align_reproduces_uninterrupted_state_bitwise(tmp_path):
    """THE crash-equivalence pin, independent of the bench harness:
    stop at the save cadence, resume with a FRESH loader, and the final
    state must equal the uninterrupted run's leaf-bitwise —
    ``resume_align`` fast-forwards the feed and the per-step
    fold_in(key, step) RNG does the rest. The negative control proves
    the pin bites: with ``resume_align=false`` (the legacy fresh-stream
    resume) the states diverge."""
    hps = tiny_hps(num_steps=8, save_every=4, log_every=4,
                   eval_every=10 ** 9, prefetch_depth=2)

    def leaves(state):
        return [np.asarray(x) for x in jax.tree_util.tree_leaves(state)]

    base = train(hps, make_loader(hps, augment=True), workdir=None,
                 use_mesh=False, seed=0)

    def interrupted(sub, align):
        h = hps.replace(resume_align=align)
        d = str(tmp_path / sub)
        train(h.replace(num_steps=4), make_loader(h, augment=True),
              workdir=d, use_mesh=False, seed=0, resume=False)
        # a fresh identically-seeded loader, exactly like a new process
        return train(h, make_loader(h, augment=True), workdir=d,
                     use_mesh=False, seed=0, resume=True)

    aligned = interrupted("aligned", True)
    assert all(np.array_equal(a, b)
               for a, b in zip(leaves(base), leaves(aligned)))
    legacy = interrupted("legacy", False)
    assert not all(np.array_equal(a, b)
                   for a, b in zip(leaves(base), leaves(legacy)))


def test_loader_fast_forward_aligns_stream():
    hps = tiny_hps()
    a = make_loader(hps, augment=True)
    b = make_loader(hps, augment=True)
    skipped = [a.random_batch() for _ in range(3)]
    del skipped
    b.fast_forward(3)
    for _ in range(2):
        x, y = a.random_batch(), b.random_batch()
        assert np.array_equal(x["strokes"], y["strokes"])
        assert np.array_equal(x["seq_len"], y["seq_len"])
    with pytest.raises(ValueError, match="n_batches"):
        b.fast_forward(-1)
