"""Smoke test for scripts/bucket_bench.py (ISSUE 4/5 acceptance surface).

Runs a shrunk version of the ``--smoke`` grid end-to-end on CPU: the
record must report padded-timestep fractions and the run-length /
dispatch-amortization columns for EVERY grid arm, a positive K=1
speedup, and the semantics checks (masked-eval bitwise parity, exact
per-example GMM, stacked RNG parity, buckets-off bitwise pin) must
pass — the speedup ACCEPTANCE numbers themselves (>= 1.3x bucketed
over fixed; bucketed K>1 strictly over K=1) are asserted by the real
``--smoke`` run that produces the committed BUCKET_BENCH.json, not
here, where trials are cut to the bone for suite runtime.

History routing: the row carries ``smoke: true`` so it takes the
BENCH_SMOKE_HISTORY path, which conftest's autouse fixture redirects to
the test's tmp dir — committed history files stay clean.
"""

import json

import bench
from scripts import bucket_bench


def test_bucket_bench_smoke(tmp_path, capsys):
    out = tmp_path / "BUCKET_BENCH.json"
    rc = bucket_bench.main([
        "--smoke", "--steps", "8", "--trials", "1", "--ks", "1,4",
        "--corpus_n", "192", "--out", str(out)])
    assert rc == 0
    rec = json.loads(out.read_text())
    assert rec["kind"] == "bucket_bench" and rec["smoke"] is True
    assert rec["ks"] == [1, 4]
    # every grid arm carries throughput, padding AND the run-length /
    # dispatch-amortization columns (ISSUE 5 acceptance: present in
    # every metrics row)
    assert set(rec["grid"]) == {"fixed_k1", "fixed_k4",
                                "bucketed_k1", "bucketed_k4"}
    for arm in rec["grid"].values():
        assert arm["steps_per_sec"] > 0
        assert 0.0 <= arm["padded_frac"] < 1.0
        for col in ("runs_per_epoch", "mean_run_len", "dispatches_saved"):
            assert col in arm, col
    # fixed-T pads everything to max_seq_len; bucketing must waste less
    assert rec["fixed"]["padded_frac"] > rec["bucketed"]["padded_frac"]
    assert rec["bucketed"]["bucket_batches"]  # per-bucket dispatch counts
    assert rec["speedup_steps_per_sec"] > 0
    # the bucketed plan has run structure; stacked arms save dispatches
    assert rec["grid"]["bucketed_k4"]["runs_per_epoch"] > 0
    assert rec["grid"]["bucketed_k4"]["mean_run_len"] >= 1.0
    assert rec["grid"]["fixed_k4"]["dispatches_saved"] > 0
    assert "k4" in rec["stacked_gain_bucketed"]
    # the semantics half of the acceptance criteria, on every backend
    assert rec["eval_parity"]["bitwise_equal"] is True
    assert rec["eval_parity"]["loss_fixed"] == rec["eval_parity"][
        "loss_bucketed"]
    assert rec["train_tail"]["gmm_nll_exact"] is True
    assert rec["train_tail"]["train_pen_ce_tail_delta"] >= 0
    # ISSUE 5 in-run parity assertions
    assert rec["parity"]["stacked"]["params_match"] is True
    assert rec["parity"]["stacked"]["same_step"] is True
    assert rec["parity"]["buckets_off_bitwise"]["bitwise_equal"] is True
    # smoke row routed through the (fixture-redirected) smoke history
    smoke_hist = tmp_path / "BENCH_SMOKE_HISTORY.jsonl"
    assert smoke_hist.exists()
    rows = [json.loads(l) for l in open(smoke_hist)]
    assert any(r.get("kind") == "bucket_bench" for r in rows)
    assert all(bench._is_smoke_record(r) for r in rows
               if r.get("kind") == "bucket_bench")


def test_bucket_bench_rejects_bad_ks(tmp_path, capsys):
    # the K=1 baseline arm is the comparison anchor; a grid without it
    # (or with a nonsense K) is a usage error, not a measurement
    assert bucket_bench.main(["--smoke", "--ks", "4,8"]) == 2
    assert bucket_bench.main(["--smoke", "--ks", "0,1"]) == 2


def test_committed_bucket_bench_meets_acceptance():
    """The committed BUCKET_BENCH.json (produced by a real --smoke run)
    must show the >= 1.3x bucketed-over-fixed speedup, the strict
    stacked improvement (some bucketed K>1 beats bucketed K=1), and
    every parity bit."""
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BUCKET_BENCH.json")
    rec = json.load(open(path))
    assert rec["meets_1p3x"] is True
    assert rec["speedup_steps_per_sec"] >= 1.3
    assert rec["eval_parity"]["bitwise_equal"] is True
    assert rec["train_tail"]["gmm_nll_exact"] is True
    # ISSUE 5 acceptance: stacked execution strictly improves the
    # bucketed runtime, with the parity assertions green in-run
    assert rec["stacked_strictly_improves"] is True
    assert rec["best_stacked_gain"] > 1.0
    assert rec["parity"]["stacked"]["params_match"] is True
    assert rec["parity"]["buckets_off_bitwise"]["bitwise_equal"] is True
    for arm in rec["grid"].values():
        for col in ("runs_per_epoch", "mean_run_len", "dispatches_saved"):
            assert col in arm, col
