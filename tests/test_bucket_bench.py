"""Smoke test for scripts/bucket_bench.py (ISSUE 4 acceptance surface).

Runs a shrunk version of the ``--smoke`` measurement end-to-end on CPU:
the record must report padded-timestep fractions for both modes, the
per-bucket dispatch counts, a positive speedup, and the semantics
checks (masked-eval bitwise parity, exact per-example GMM) must pass —
the speedup ACCEPTANCE number itself (>= 1.3x) is asserted by the real
``--smoke`` run that produces the committed BUCKET_BENCH.json, not
here, where trials are cut to the bone for suite runtime.

History routing: the row carries ``smoke: true`` so it takes the
BENCH_SMOKE_HISTORY path, which conftest's autouse fixture redirects to
the test's tmp dir — committed history files stay clean.
"""

import json

import bench
from scripts import bucket_bench


def test_bucket_bench_smoke(tmp_path, capsys):
    out = tmp_path / "BUCKET_BENCH.json"
    rc = bucket_bench.main([
        "--smoke", "--steps", "6", "--trials", "1",
        "--corpus_n", "128", "--out", str(out)])
    assert rc == 0
    rec = json.loads(out.read_text())
    assert rec["kind"] == "bucket_bench" and rec["smoke"] is True
    for mode in ("fixed", "bucketed"):
        assert 0.0 <= rec[mode]["padded_frac"] < 1.0
        assert rec[mode]["steps_per_sec"] > 0
    # fixed-T pads everything to max_seq_len; bucketing must waste less
    assert rec["fixed"]["padded_frac"] > rec["bucketed"]["padded_frac"]
    assert rec["bucketed"]["bucket_batches"]  # per-bucket dispatch counts
    assert rec["speedup_steps_per_sec"] > 0
    # the semantics half of the acceptance criteria, on every backend
    assert rec["eval_parity"]["bitwise_equal"] is True
    assert rec["eval_parity"]["loss_fixed"] == rec["eval_parity"][
        "loss_bucketed"]
    assert rec["train_tail"]["gmm_nll_exact"] is True
    assert rec["train_tail"]["train_pen_ce_tail_delta"] >= 0
    # smoke row routed through the (fixture-redirected) smoke history
    smoke_hist = tmp_path / "BENCH_SMOKE_HISTORY.jsonl"
    assert smoke_hist.exists()
    rows = [json.loads(l) for l in open(smoke_hist)]
    assert any(r.get("kind") == "bucket_bench" for r in rows)
    assert all(bench._is_smoke_record(r) for r in rows
               if r.get("kind") == "bucket_bench")


def test_committed_bucket_bench_meets_acceptance():
    """The committed BUCKET_BENCH.json (produced by a real --smoke run)
    must show the >= 1.3x steps/sec acceptance and the parity bits."""
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BUCKET_BENCH.json")
    rec = json.load(open(path))
    assert rec["meets_1p3x"] is True
    assert rec["speedup_steps_per_sec"] >= 1.3
    assert rec["eval_parity"]["bitwise_equal"] is True
    assert rec["train_tail"]["gmm_nll_exact"] is True
