"""QuickDraw ndjson -> stroke-3 conversion tests (data/quickdraw.py)."""

import json
import os

import numpy as np
import pytest

from sketch_rnn_tpu.data.quickdraw import (
    convert_ndjson,
    drawing_to_stroke3,
    iter_ndjson,
    rdp,
    stream_categories,
    stream_stroke3,
)


def _write_ndjson(path, n, seed, word="cat", min_pts=4, max_pts=20):
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n):
            k = int(rng.integers(min_pts, max_pts))
            xs = np.cumsum(rng.integers(-5, 6, k)) + 128
            ys = np.cumsum(rng.integers(-5, 6, k)) + 128
            f.write(json.dumps({
                "word": word, "recognized": True,
                "drawing": [[xs.tolist(), ys.tolist()]]}) + "\n")


def test_rdp_drops_collinear_keeps_corners():
    # a right angle sampled densely: all interior collinear points drop
    xs = np.linspace(0, 10, 11)
    leg1 = np.stack([xs, np.zeros(11)], axis=1)
    leg2 = np.stack([np.full(10, 10.0), np.linspace(1, 10, 10)], axis=1)
    line = np.concatenate([leg1, leg2])
    out = rdp(line, epsilon=0.5)
    np.testing.assert_array_equal(out, [[0, 0], [10, 0], [10, 10]])


def test_rdp_epsilon_zero_is_identity():
    pts = np.array([[0, 0], [1, 0.4], [2, 0], [3, 0.4]])
    np.testing.assert_array_equal(rdp(pts, 0.0), pts)


def test_rdp_keeps_significant_deviation():
    pts = np.array([[0.0, 0], [5, 3], [10, 0]])
    out = rdp(pts, epsilon=1.0)
    np.testing.assert_array_equal(out, pts)


def test_rdp_degenerate_closed_chord():
    # first == last point: must not divide by zero, keeps the far point
    pts = np.array([[0.0, 0], [5, 5], [0, 0]])
    out = rdp(pts, epsilon=1.0)
    assert [5, 5] in out.tolist()


def test_drawing_to_stroke3_deltas_and_pen():
    drawing = [[[0, 10, 10], [0, 0, 10]],      # L-stroke
               [[20, 30], [20, 20]]]           # second stroke
    s3 = drawing_to_stroke3(drawing, epsilon=0)
    # deltas reconstruct the absolute points; pen lifts end each stroke
    assert s3.shape == (4, 3)
    np.testing.assert_array_equal(s3[:, 2], [0, 1, 0, 1])
    abs_pts = np.cumsum(s3[:, :2], axis=0)
    np.testing.assert_allclose(abs_pts[1], [10, 10])   # end of stroke 1
    np.testing.assert_allclose(abs_pts[3], [30, 20])   # end of stroke 2


def test_drawing_to_stroke3_max_points_truncates_with_pen_end():
    drawing = [[list(range(50)), [0] * 50]]
    s3 = drawing_to_stroke3(drawing, epsilon=0, max_points=10)
    assert len(s3) == 10
    assert s3[-1, 2] == 1.0


def test_iter_ndjson_filters_unrecognized():
    lines = [
        json.dumps({"word": "cat", "recognized": True,
                    "drawing": [[[0, 1], [0, 1]]]}),
        json.dumps({"word": "cat", "recognized": False,
                    "drawing": [[[0, 1], [0, 1]]]}),
        "",
    ]
    got = list(iter_ndjson(lines))
    assert len(got) == 1 and got[0][0] == "cat"


def test_convert_ndjson_roundtrips_into_loader(tmp_path):
    # synthesize an ndjson category, convert, then load through the real
    # dataset path
    rng = np.random.default_rng(0)
    path = tmp_path / "cat.ndjson"
    with open(path, "w") as f:
        for _ in range(30):
            n = int(rng.integers(4, 20))
            xs = np.cumsum(rng.integers(-5, 6, n)) + 128
            ys = np.cumsum(rng.integers(-5, 6, n)) + 128
            f.write(json.dumps({
                "word": "cat", "recognized": True,
                "drawing": [[xs.tolist(), ys.tolist()]]}) + "\n")
    sizes = convert_ndjson(str(path), str(tmp_path / "cat.npz"),
                           epsilon=0.5, num_valid=5, num_test=5)
    assert sizes == {"train": 20, "valid": 5, "test": 5}

    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.data.loader import load_dataset
    hps = HParams(batch_size=4, max_seq_len=32)
    train_l, valid_l, test_l, scale = load_dataset(
        hps, data_dir=str(tmp_path))
    assert len(train_l) > 0 and scale > 0
    batch = train_l.random_batch()
    assert batch["strokes"].shape == (4, 33, 5)


def test_convert_ndjson_too_small_raises(tmp_path):
    path = tmp_path / "cat.ndjson"
    with open(path, "w") as f:
        f.write(json.dumps({"word": "cat", "recognized": True,
                            "drawing": [[[0, 1, 2], [0, 1, 2]]]}) + "\n")
    with pytest.raises(ValueError, match="usable drawings"):
        convert_ndjson(str(path), str(tmp_path / "cat.npz"),
                       num_valid=5, num_test=5)


def test_drawing_to_stroke3_resolution_independent():
    """Raw captures at any resolution normalize into the 0-255 box before
    RDP, so a uniformly scaled drawing converts identically (canonical
    epsilon=2.0 is defined in box coordinates)."""
    rng = np.random.default_rng(2)
    n = 40
    xs = np.cumsum(rng.integers(-9, 10, n)).astype(float)
    ys = np.cumsum(rng.integers(-9, 10, n)).astype(float)
    base = [[xs.tolist(), ys.tolist()]]
    scaled = [[(xs * 6.5).tolist(), (ys * 6.5).tolist()]]
    a = drawing_to_stroke3(base, epsilon=2.0)
    b = drawing_to_stroke3(scaled, epsilon=2.0)
    np.testing.assert_allclose(a, b, atol=1e-9)
    # and the offsets live in box units: per-axis extent <= 255
    abs_pts = np.cumsum(a[:, :2], axis=0)
    assert float(np.ptp(abs_pts, axis=0).max()) <= 255.0 + 1e-6


def test_quantize_exact_integer_deltas_no_drift():
    """quantize=True rounds ABSOLUTE coords before diffing: deltas are
    exact integers and reconstructed positions equal the rounded
    originals (no cumulative drift)."""
    rng = np.random.default_rng(3)
    n = 200
    xs = np.cumsum(rng.random(n) * 3.7)
    ys = np.cumsum(rng.random(n) * 2.3)
    s3 = drawing_to_stroke3([[xs.tolist(), ys.tolist()]], epsilon=0,
                            quantize=True)
    np.testing.assert_array_equal(s3[:, :2], np.round(s3[:, :2]))
    recon = np.cumsum(s3[:, :2], axis=0)
    want = np.stack([np.round(xs), np.round(ys)], axis=1)
    # reconstruction starts at the (dropped) first point's rounded pos
    np.testing.assert_allclose(recon + want[0], want[1:] if len(recon) ==
                               n - 1 else want, atol=0)


# -- streaming ingestion (ISSUE 15) ------------------------------------------


def test_stream_stroke3_matches_converter_pipeline(tmp_path):
    """The streaming reader IS the converter's pipeline: the streamed
    stroke-3 arrays equal the .npz conversion's pre-split sequences
    byte-for-byte (int16-cast), so the two paths can never drift."""
    path = tmp_path / "cat.ndjson"
    _write_ndjson(path, 20, seed=0)
    streamed = list(stream_stroke3(str(path), epsilon=0.5,
                                   max_points=32))
    assert streamed and all(s.dtype == np.float32 and s.shape[1] == 3
                            for s in streamed)
    # exact integer deltas (the quantize=True layout)
    for s in streamed:
        np.testing.assert_array_equal(s[:, :2], np.round(s[:, :2]))
    convert_ndjson(str(path), str(tmp_path / "cat.npz"), epsilon=0.5,
                   max_points=32, num_valid=5, num_test=5, seed=3)
    npz = np.load(tmp_path / "cat.npz", allow_pickle=True,
                  encoding="latin1")
    pooled = sorted(
        (a.tobytes() for split in ("train", "valid", "test")
         for a in npz[split]))
    assert sorted(s.astype(np.int16).tobytes()
                  for s in streamed) == pooled
    # limit bounds the stream
    assert len(list(stream_stroke3(str(path), epsilon=0.5,
                                   max_points=32, limit=4))) == 4


def test_stream_stroke3_corrupt_lines(tmp_path):
    path = tmp_path / "bad.ndjson"
    _write_ndjson(path, 3, seed=1)
    with open(path, "a") as f:
        f.write("{torn json\n")
    with pytest.raises(ValueError, match="corrupt ndjson"):
        list(stream_stroke3(str(path)))
    assert len(list(stream_stroke3(str(path), skip_bad=True))) == 3


def test_stream_categories_interleaves_with_file_order_labels(tmp_path):
    _write_ndjson(tmp_path / "cat.ndjson", 4, seed=2, word="cat")
    _write_ndjson(tmp_path / "dog.ndjson", 6, seed=3, word="dog")
    pairs = list(stream_categories(str(tmp_path), ["cat", "dog"]))
    labels = [label for label, _ in pairs]
    assert len(pairs) == 10
    assert labels[:8] == [0, 1] * 4        # round-robin while both live
    assert labels[8:] == [1, 1]            # dog's tail drains alone
    seq = list(stream_categories(str(tmp_path), ["cat", "dog"],
                                 interleave=False))
    assert [label for label, _ in seq] == [0] * 4 + [1] * 6


def test_stream_batches_feeds_loader_layout(tmp_path):
    """ISSUE 15: ndjson stream -> native batcher -> loader-layout
    stroke-5 batches with no materialized corpus; native and numpy
    fallback paths agree bit-for-bit."""
    from sketch_rnn_tpu.data import native_batcher as NB

    _write_ndjson(tmp_path / "cat.ndjson", 5, seed=4, word="cat")
    _write_ndjson(tmp_path / "dog.ndjson", 5, seed=5, word="dog")
    pairs = list(stream_categories(str(tmp_path), ["cat", "dog"],
                                   max_points=32))
    batches = list(NB.stream_batches(iter(pairs), batch_size=4,
                                     max_len=32))
    assert [len(b["seq_len"]) for b in batches] == [4, 4, 2]
    for b in batches:
        assert b["strokes"].shape[1:] == (33, 5)
        assert b["strokes"].dtype == np.float32
        # start token at t=0, row lengths honored
        np.testing.assert_array_equal(b["strokes"][:, 0, :],
                                      [[0, 0, 1, 0, 0]] * len(b["seq_len"]))
        assert set(b["labels"].tolist()) <= {0, 1}
    # the numpy fallback is bit-exact to the native path on this batch
    seqs = [s for _, s in pairs[:4]]
    ref = NB.pad_batch_numpy(seqs, 32)
    native = NB.assemble_batch(seqs, 32)
    if native is not None:
        np.testing.assert_array_equal(ref[0], native[0])
        np.testing.assert_array_equal(ref[1], native[1])
    # over-length sequences are dropped, not crashed on
    long = np.zeros((40, 3), np.float32)
    out = list(NB.stream_batches(iter([long] + seqs), batch_size=4,
                                 max_len=32))
    assert [len(b["seq_len"]) for b in out] == [4]
    # drop_last drops the ragged tail
    assert [len(b["seq_len"]) for b in NB.stream_batches(
        iter(pairs), batch_size=4, max_len=32, drop_last=True)] \
        == [4, 4]


def test_convert_npz_is_1d_object_array_even_when_uniform(tmp_path):
    path = tmp_path / "u.ndjson"
    rng = np.random.default_rng(4)
    with open(path, "w") as f:
        for _ in range(12):
            xs = (np.cumsum(rng.integers(-5, 6, 30)) + 128).tolist()
            ys = (np.cumsum(rng.integers(-5, 6, 30)) + 128).tolist()
            f.write(json.dumps({"word": "u", "recognized": True,
                                "drawing": [[xs, ys]]}) + "\n")
    convert_ndjson(str(path), str(tmp_path / "u.npz"), epsilon=0,
                   max_points=8, num_valid=3, num_test=3)
    npz = np.load(tmp_path / "u.npz", allow_pickle=True, encoding="latin1")
    for split in ("train", "valid", "test"):
        arr = npz[split]
        assert arr.ndim == 1 and arr.dtype == object
        assert all(a.dtype == np.int16 and a.shape[1] == 3 for a in arr)
