"""Test harness setup: force an 8-device CPU platform BEFORE jax imports.

This is the multi-chip-without-cluster mechanism from SURVEY.md §4: all
sharding/DP tests run on 8 virtual CPU devices so the full mesh path is
exercised without TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The machine's site customization (PYTHONPATH=.axon_site) force-resets
# JAX_PLATFORMS to the axon TPU plugin at jax import; the config update wins
# over that, pinning tests to the 8-device virtual CPU platform.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _hermetic_telemetry():
    """The telemetry core is process-global (ISSUE 6) and DISABLED by
    default; a test that configures it (trace_dir runs) must not leak
    an enabled core into later tests — the off-by-default invisibility
    contract is itself under test."""
    yield
    from sketch_rnn_tpu.utils import telemetry

    telemetry.disable()


@pytest.fixture(autouse=True)
def _no_leaked_memory_samplers():
    """ISSUE 8 guard: the device-memory sampler runs on a daemon
    thread and registers process-wide (utils/telemetry.py _SAMPLERS);
    a test that starts one must stop it — a leaked sampler keeps
    recording gauges into whatever core later tests configure. Leaks
    are drained AND failed loudly, naming the leaker."""
    yield
    from sketch_rnn_tpu.utils import telemetry

    leaked = telemetry.stop_all_samplers()
    assert not leaked, f"test leaked live memory samplers: {leaked}"


@pytest.fixture(autouse=True)
def _no_stray_health_surfaces():
    """ISSUE 7 guard: the health/SLO layer is OFF by default — no test
    may leak a listening /metrics socket or an armed watchdog monitor
    into later tests (the serve/metrics_http.py registry and the
    train/watchdog.py armed set exist for exactly this check). A leak
    is shut down AND failed loudly, naming the leaker via the fixture's
    teardown error. Incident files are covered separately: train()
    builds no monitor unless asked, and the watchdog tests assert a
    clean default run writes no incident.json."""
    yield
    from sketch_rnn_tpu.serve import metrics_http
    from sketch_rnn_tpu.train import watchdog

    leaked_servers = metrics_http.stop_all()
    leaked_monitors = watchdog.armed_monitors()
    for m in leaked_monitors:
        m.disarm()
    assert not leaked_servers, (
        f"test leaked live metrics servers: {leaked_servers}")
    assert not leaked_monitors, (
        f"test leaked armed watchdog monitors: {leaked_monitors}")


@pytest.fixture(autouse=True)
def _no_leaked_fleet_threads():
    """ISSUE 9 guard: fleet replica workers and open-loop load
    generators run on their own threads and register process-wide
    (serve/fleet.py and serve/loadgen.py registries) — a leaked worker
    keeps dispatching into whatever device/telemetry state later tests
    set up, exactly like a leaked metrics server. Leaks are drained AND
    failed loudly, naming the leaker.

    ISSUE 10 extends the guard below the registries: after the
    registry drain, NO fleet/loadgen/ckpt-writer THREAD may survive
    the test — a faulted test (injected replica death, crashed async
    save) must not leave a runtime thread behind even when its owning
    object already unregistered. A short grace window covers threads
    that are mid-exit (a ckpt writer finishing its last commit).

    ISSUE 14 extends it to the elastic runtime: coordinator/heartbeat
    registries (train/elastic.py, parallel/multihost.py) are drained
    and no ``host-heartbeat-*`` thread may survive a test — a leaked
    heartbeat keeps a dead test's host looking ALIVE to any later
    test's failure detector."""
    yield
    import threading
    import time as _time

    from sketch_rnn_tpu.parallel import multihost
    from sketch_rnn_tpu.serve import fleet, loadgen
    from sketch_rnn_tpu.train import elastic

    leaked_gens = loadgen.stop_all()
    leaked_fleets = fleet.stop_all()
    leaked_coords = elastic.stop_all()
    leaked_beats = multihost.stop_all_heartbeats()
    assert not leaked_gens, (
        f"test leaked live load generators: {leaked_gens}")
    assert not leaked_fleets, (
        f"test leaked live serve fleets: {leaked_fleets}")
    assert not leaked_coords, (
        f"test leaked live elastic coordinators: {leaked_coords}")
    assert not leaked_beats, (
        f"test leaked live host heartbeats: {leaked_beats}")

    def _runtime_threads():
        return sorted(t.name for t in threading.enumerate()
                      if t.is_alive() and t.name.startswith(
                          ("fleet-replica-", "loadgen", "ckpt-writer",
                           "host-heartbeat-", "rollout-",
                           "coresident-")))

    deadline = _time.monotonic() + 5.0
    survivors = _runtime_threads()
    while survivors and _time.monotonic() < deadline:
        _time.sleep(0.05)
        survivors = _runtime_threads()
    assert not survivors, (
        f"test left runtime thread(s) alive after drain: {survivors}")


@pytest.fixture(autouse=True)
def _hermetic_fault_injector():
    """ISSUE 10 guard: the fault injector is process-global and OFF by
    default, like the telemetry core — a chaos test that arms a plan
    must not leak it into later tests (an armed plan fires on exact
    invocation counts, so a leak would corrupt arbitrary later
    tests)."""
    yield
    from sketch_rnn_tpu.utils import faults

    faults.disable()


@pytest.fixture(autouse=True)
def _hermetic_bench_history(tmp_path, monkeypatch):
    """Tests must never append to the repo's COMMITTED bench history
    files — the r5 review found test-suite smoke rows accumulated in
    BENCH_HISTORY.jsonl exactly this way. Route both history paths to
    the test's temp dir; tests that pin their own path monkeypatch over
    this (their setattr runs later and wins).

    This also covers every scripts/ probe that appends through
    ``bench._hist_append`` / ``scripts._measure.hist_append`` — incl.
    the bucket-bench smoke rows (ISSUE 4), which carry ``smoke: true``
    or ``device_kind == "cpu"`` and therefore take the
    BENCH_SMOKE_HISTORY routing, here redirected to the temp dir.
    (Bucket-bench's BUCKET_BENCH.json is written to ``--out``, which
    tests must point into their tmp_path.)"""
    import bench

    monkeypatch.setattr(
        bench, "_hist_path",
        lambda: str(tmp_path / "BENCH_HISTORY.jsonl"))
    monkeypatch.setattr(
        bench, "_smoke_hist_path",
        lambda: str(tmp_path / "BENCH_SMOKE_HISTORY.jsonl"))
