"""Test harness setup: force an 8-device CPU platform BEFORE jax imports.

This is the multi-chip-without-cluster mechanism from SURVEY.md §4: all
sharding/DP tests run on 8 virtual CPU devices so the full mesh path is
exercised without TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
