"""trace_query.py tests (ISSUE 11): causal span trees, critical-path
attribution and per-class device-step cost over a real traced chaos
fleet run.

The acceptance pins live here:

- a seeded ``fleet.worker`` fault run yields ONE orphan-free tree per
  request — retry spans linked under the request root, re-served hops
  under the retry span;
- every request's critical-path segments sum BITWISE to the Result's
  ``latency_s``, and the percentile table reconciles with the fleet
  summary (same ``np.percentile`` over the same floats);
- per-class attributed device steps reconcile EXACTLY with the fleet's
  dispatched counters (attributed + idle == dispatched, in integers)
  and agree with the fleet summary's own cost block;
- ``--smoke`` (the tier-1 wiring) holds over the committed fixture.

The chaos run is expensive (two fleets, jax), so it is built ONCE per
module and shared.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts import trace_query
from scripts.trace_report import load
from sketch_rnn_tpu.utils import faults
from sketch_rnn_tpu.utils import telemetry as tele


@pytest.fixture(scope="module")
def serve_setup():
    """Model + params shared by every traced fleet run in this module
    (the runs are the expensive part; the model is tiny)."""
    import jax

    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.models.vae import SketchRNN

    hps = HParams(batch_size=8, max_seq_len=24, enc_rnn_size=12,
                  dec_rnn_size=16, z_size=6, num_mixture=3,
                  serve_slots=2, serve_chunk=2)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    return hps, model, params


def _traced_run(serve_setup, plan, out_dir, **fleet_kw):
    """One traced fleet run (2 replicas, 8 requests over 2 admission
    classes) under fault plan ``plan`` -> (jsonl, summary, results)."""
    import jax

    from sketch_rnn_tpu.serve import Request, ServeFleet
    from sketch_rnn_tpu.serve.admission import parse_admission_classes

    hps, model, params = serve_setup

    def req(i, cap=5):
        rng = np.random.default_rng(i)
        return Request(key=jax.random.key(1000 + i),
                       z=rng.standard_normal(hps.z_size).astype(
                           np.float32),
                       temperature=0.8, max_len=cap, uid=i)

    classes = parse_admission_classes(
        ["interactive:p95<=5", "batch:p99<=30"])
    fleet = ServeFleet(model, hps, params, replicas=2,
                       classes=classes, retry_backoff_s=0.0,
                       **fleet_kw)
    fleet.warm(req(0))
    tel = tele.configure(trace_dir=str(out_dir))
    if plan:
        faults.configure(plan)
    try:
        for i in range(8):
            fleet.submit(req(i),
                         cls=("interactive", "batch")[i % 2])
        with fleet:
            assert fleet.drain(timeout=120)
            summary = fleet.summary()
            results = {uid: rec["result"]
                       for uid, rec in fleet.results.items()}
        paths = tel.export()
    finally:
        faults.disable()
        tele.disable()
    return paths["jsonl"], summary, results


@pytest.fixture(scope="module")
def chaos_run(serve_setup, tmp_path_factory):
    """One traced seeded ``fleet.worker.r0@0`` chaos run (2 replicas,
    8 requests over 2 admission classes, replica 0 killed on its first
    burst) plus the matching no-fault run — exported shards, fleet
    summaries and per-request Results for both."""
    base = tmp_path_factory.mktemp("trace_query_chaos")
    fault = _traced_run(serve_setup, "fleet.worker.r0@0", base / "fault")
    clean = _traced_run(serve_setup, None, base / "clean")
    assert fault[1]["requeues"] > 0 and fault[1]["completed"] == 8
    return {"fault": fault, "clean": clean}


@pytest.fixture(scope="module")
def midburst_run(serve_setup, tmp_path_factory):
    """A crash AFTER completions: replica 0 dies on its 4th loop
    iteration (``serve.chunk.r0@3``) — one past the 3 chunks a 5-step
    request needs — so requests that already completed inside the
    dying burst (complete event + attributed counters emitted) are
    re-served whole by the failover. The duplicate-emission path."""
    base = tmp_path_factory.mktemp("trace_query_midburst")
    out = _traced_run(serve_setup, "serve.chunk.r0@3", base / "fault")
    assert out[1]["requeues"] > 0 and out[1]["completed"] == 8
    return out


def test_chaos_trees_complete_orphan_free_and_retry_linked(chaos_run):
    """THE orphan-free acceptance pin: every request of the chaos run
    reconstructs as one complete tree; the killed replica's requests
    carry linked retry spans; no span is parentless."""
    jsonl, summary, _ = chaos_run["fault"]
    rep = trace_query.report(load(jsonl))
    assert rep["requests"] == 8
    assert rep["complete"] == 8 and rep["incomplete"] == 0
    assert rep["shed"] == 0
    assert rep["retried"] >= 1          # replica 0's burst failed over
    assert rep["orphan_spans"] == 0
    assert rep["exact_sum_violations"] == 0
    # the killed replica books no burst span (it died mid-burst), so
    # only the survivor's bursts appear
    assert rep["bursts"] >= 1
    assert trace_query.verdict(rep) == []

    # the retried trees carry the whole causal story: retry span
    # parented under the request root, attempt-1 hops under the retry
    trees = trace_query.request_trees(
        trace_query.build_traces(load(jsonl)))
    retried = [t for t in trees.values() if t["retries"]]
    assert len(retried) >= 1
    for t in retried:
        assert t["complete"]["attempt"] >= 1
        for rid in t["retries"]:
            ev = t["spans"][rid]
            assert ev["trace"]["parent"] == f"request-{t['uid']}"
            assert ev["args"]["from_replica"] == 0
            assert ev["args"]["to_replica"] == 1


def test_chaos_percentiles_reconcile_with_fleet_summary(chaos_run):
    """The latency table is the same np.percentile math over the same
    exact Result floats as the fleet summary — rounded to the
    summary's own 6 digits they must agree exactly."""
    jsonl, summary, results = chaos_run["fault"]
    rep = trace_query.report(load(jsonl))
    by_metric = {r["metric"]: r for r in rep["latency"]}
    row = by_metric["latency_s"]
    assert row["count"] == summary["completed"] == 8
    for p in ("p50", "p95", "p99"):
        assert round(row[f"{p}_s"], 6) == summary["latency"][f"{p}_s"]
    # and the event floats ARE the Result floats, bitwise
    trees = trace_query.request_trees(
        trace_query.build_traces(load(jsonl)))
    for uid, res in results.items():
        comp = trees[uid]["complete"]
        assert comp["latency_s"] == res.latency_s
        assert comp["queue_wait_s"] == res.queue_wait_s
        assert comp["attributed_steps"] == res.attributed_steps


def test_chaos_segments_sum_bitwise_to_latency(chaos_run):
    """Per-request critical-path segments sum EXACTLY (left-to-right
    float add) to latency_s — the acceptance identity, re-verified
    here directly rather than through report()'s counter."""
    jsonl, _, _ = chaos_run["fault"]
    trees = trace_query.request_trees(
        trace_query.build_traces(load(jsonl)))
    assert len(trees) == 8
    for t in trees.values():
        segs = t["complete"]["segments"]
        assert [s[0] for s in segs] == ["queue_wait_s", "decode_s"]
        total = 0.0
        for _, v in segs:
            total += v
        assert total == t["complete"]["latency_s"]
        assert t["exact_sum"] is True


def test_chaos_cost_reconciles_exactly_with_summary(chaos_run):
    """Per-class device-step attribution: event-derived per-class sums
    equal the fleet summary's cost block, and attributed + idle ==
    dispatched in integers — on the DEGRADED run too (the dead
    replica's unbooked burst never enters the identity)."""
    jsonl, summary, results = chaos_run["fault"]
    rep = trace_query.report(load(jsonl))
    cost = rep["cost"]
    assert cost is not None and cost["exact"]
    assert cost["steps_by_class"] == summary["cost"]["steps_by_class"]
    assert cost["steps_attributed"] == summary["cost"]["steps_attributed"]
    assert (cost["steps_attributed"] + cost["steps_idle"]
            == cost["steps_dispatched"])
    assert cost["steps_dispatched"] == summary["cost"]["steps_dispatched"]
    assert sum(cost["steps_by_class"].values()) == sum(
        r.attributed_steps for r in results.values())
    assert set(cost["steps_by_class"]) == {"interactive", "batch"}


def test_cost_attribution_deterministic_across_fault_and_clean(chaos_run):
    """Attribution is pure scheduling math in (seed, placement): the
    no-fault run — same requests, same admission order — reproduces
    its own exact identity, and both runs attribute every step they
    dispatched."""
    for key in ("fault", "clean"):
        _, summary, _ = chaos_run[key]
        cost = summary["cost"]
        assert cost["exact"], (key, cost)
        assert (cost["steps_attributed"] + cost["steps_idle"]
                == cost["steps_dispatched"] ==
                summary["total_device_steps"])


def test_midburst_crash_dedups_trees_and_keeps_cost_exact(midburst_run):
    """A replica that dies AFTER emitting completions re-serves the
    whole burst (the dying ``engine.run`` books nothing), so the
    stream holds TWO complete emissions for every pre-crash finisher.
    Trees and the percentile table must keep the booked (last) one;
    cost accounting counts both — both were real device work — and
    the dying run's abort ledger keeps attributed + idle ==
    dispatched exact across the crash."""
    from collections import Counter

    jsonl, summary, results = midburst_run
    data = load(jsonl)
    rep = trace_query.report(data)
    assert trace_query.verdict(rep) == []
    assert rep["requests"] == 8 and rep["complete"] == 8
    assert rep["retried"] >= 1 and rep["orphan_spans"] == 0
    assert rep["exact_sum_violations"] == 0

    # the duplicate path actually ran: at least one request completed
    # in the dying burst and again on the survivor
    dupes = [uid for uid, n in Counter(
        ev["args"]["uid"] for ev in data["events"]
        if ev["type"] == "instant" and ev["name"] == "complete"
        and ev["cat"] == "serve").items() if n > 1]
    assert dupes, "fault fired before any completion — move the @N"

    # trees keep the BOOKED completion (bitwise the fleet Result),
    # not the dead run's first emission
    trees = trace_query.request_trees(trace_query.build_traces(data))
    for uid in dupes:
        assert trees[uid]["complete"]["attempt"] >= 1
    for uid, res in results.items():
        comp = trees[uid]["complete"]
        assert comp["latency_s"] == res.latency_s
        assert comp["queue_wait_s"] == res.queue_wait_s
        assert comp["attributed_steps"] == res.attributed_steps

    # cost counts EMISSIONS, in lockstep with the counters: the dead
    # run's completions and its abort-ledger dispatched/idle are in,
    # so the identity survives the crash while the booked summary —
    # which never sees the dying burst — stays strictly below
    cost = rep["cost"]
    booked = sum(r.attributed_steps for r in results.values())
    assert cost["steps_attributed"] > booked
    assert cost["steps_attributed"] == cost["counter_attributed"]
    assert cost["exact"] and cost["exact_counters"]
    assert (cost["steps_attributed"] + cost["steps_idle"]
            == cost["steps_dispatched"])
    assert cost["steps_dispatched"] > summary["cost"]["steps_dispatched"]

    # the percentile table dedups to one completion per request and
    # reconciles with the fleet summary despite the duplicates
    row = {r["metric"]: r for r in rep["latency"]}["latency_s"]
    assert row["count"] == 8
    for p in ("p50", "p95", "p99"):
        assert round(row[f"{p}_s"], 6) == summary["latency"][f"{p}_s"]


def test_retry_budget_exhaustion_is_a_named_terminal_state(
        serve_setup, tmp_path_factory):
    """A request the fleet deliberately gave up on (retry budget
    exhausted) must read as FAILED, not as a torn export: the fleet
    emits the root span plus a terminal `failed` instant, so the tree
    is terminal, orphan-free, and carries the give-up evidence."""
    base = tmp_path_factory.mktemp("trace_query_failed")
    jsonl, summary, results = _traced_run(
        serve_setup, "fleet.worker.r0@0", base / "fault",
        retry_budget=0)
    assert summary["failed"] > 0
    assert summary["completed"] == 8 - summary["failed"]

    rep = trace_query.report(load(jsonl))
    assert trace_query.verdict(rep) == []
    assert rep["requests"] == 8
    assert rep["failed"] == summary["failed"]
    assert rep["incomplete"] == 0          # failed != torn export
    assert rep["orphan_spans"] == 0

    trees = trace_query.request_trees(
        trace_query.build_traces(load(jsonl)))
    failed = [t for t in trees.values() if t["failed"] is not None]
    assert len(failed) == summary["failed"]
    for t in failed:
        assert t["complete"] is None and not t["incomplete"]
        assert t["root"] is not None       # full-clock root emitted
        assert "retry budget" in t["failed"]["reason"]
        assert t["uid"] not in results


def test_chaos_p99_decomposition_groups_and_tail(chaos_run):
    """The p99 decomposition reports a verdict overall and per
    class/replica, from the shared segment schema — and it agrees with
    the fleet summary's own tail block (same tail_attribution math)."""
    jsonl, summary, _ = chaos_run["fault"]
    rep = trace_query.report(load(jsonl))
    dec = rep["p99_decomposition"]
    assert dec["all"] is not None
    assert dec["all"]["dom"] in ("queue", "decode")
    assert set(dec["by_class"]) == {"interactive", "batch"}
    # chaos run: every request completed on the survivor (replica 1)
    assert set(dec["by_replica"]) == {"1"}
    tail = summary["tail"]
    assert tail["dom"] == dec["all"]["dom"]
    assert tail["p99_s"] == pytest.approx(dec["all"]["p99_s"])
    assert tail["tail_n"] == dec["all"]["tail_n"]


def test_cli_json_report_and_tree_printer(chaos_run, capsys):
    """main() end to end: table mode exits 0 on a verified stream,
    --json round-trips, --request prints one retried request's tree
    (retry + re-served hops), unknown uid is a one-line rc 2."""
    jsonl, _, _ = chaos_run["fault"]
    assert trace_query.main([jsonl]) == 0
    out = capsys.readouterr().out
    assert "request trees" in out and "p99 decomposition" in out
    assert "device-step cost" in out

    assert trace_query.main([jsonl, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["orphan_spans"] == 0 and rep["cost"]["exact"]

    trees = trace_query.request_trees(
        trace_query.build_traces(load(jsonl)))
    uid = next(t["uid"] for t in trees.values() if t["retries"])
    assert trace_query.main([jsonl, "--request", str(uid)]) == 0
    out = capsys.readouterr().out
    assert f"request uid={uid}" in out
    assert "retry" in out and "critical path" in out
    assert "sum exact: True" in out

    assert trace_query.main([jsonl, "--request", "9999"]) == 2
    assert "no trace for request uid 9999" in capsys.readouterr().err


def test_usage_errors_are_one_liners(tmp_path, capsys):
    """Missing stream and trace-free stream are actionable rc-2
    one-liners, not tracebacks."""
    assert trace_query.main([str(tmp_path / "nope")]) == 2
    assert "no telemetry stream" in capsys.readouterr().err

    # a train-only export carries no trace-stamped events
    tel = tele.configure(trace_dir=str(tmp_path))
    with tel.span("dispatch", cat="train"):
        time.sleep(0.001)
    paths = tel.export()
    tele.disable()
    assert trace_query.main([paths["jsonl"]]) == 2
    assert "no trace-stamped events" in capsys.readouterr().err


def test_smoke_self_check_over_committed_fixture(capsys):
    """The tier-1 wiring: --smoke verifies the committed chaos fixture
    (orphan-free retried trees, bitwise sums, exact cost)."""
    assert trace_query.main(["--smoke"]) == 0
    out = capsys.readouterr().out
    assert "trace_query --smoke OK" in out
    assert "retried" in out and "cost exact" in out


def test_verdict_flags_violations():
    """verdict() fails loudly on a doctored report: orphans, inexact
    sums, broken cost identity."""
    rep = {"orphan_spans": 2, "exact_sum_violations": 1,
           "cost": {"exact": False, "steps_attributed": 5,
                    "steps_idle": 1, "steps_dispatched": 7}}
    problems = trace_query.verdict(rep)
    assert len(problems) == 3
    assert any("orphan" in p for p in problems)
    assert any("bitwise" in p for p in problems)
    assert any("inexact" in p for p in problems)
