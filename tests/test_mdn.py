"""Closed-form MDN math tests (SURVEY.md §4: hand-built mixtures)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sketch_rnn_tpu.ops import mdn


def _raw_from(mixture, num_mixture):
    """Build a raw [.., 6M+3] projection realizing the given parameters."""
    logits, mu1, mu2, s1, s2, rho, pen = mixture
    body = np.stack([logits, mu1, mu2, np.log(s1), np.log(s2),
                     np.arctanh(rho)], axis=-2)  # [..., 6, M]
    flat = body.reshape(*body.shape[:-2], 6 * num_mixture)
    return jnp.asarray(np.concatenate([pen, flat], axis=-1), jnp.float32)


def test_get_mixture_params_shapes_and_normalization():
    m = 4
    raw = jnp.asarray(np.random.default_rng(0).normal(size=(7, 3, 6 * m + 3)),
                      jnp.float32)
    mp = mdn.get_mixture_params(raw, m)
    assert mp.log_pi.shape == (7, 3, m)
    np.testing.assert_allclose(np.exp(np.asarray(mp.log_pi)).sum(-1), 1.0,
                               rtol=1e-5)
    assert np.all(np.abs(np.asarray(mp.rho)) < 1.0)
    with pytest.raises(ValueError):
        mdn.get_mixture_params(raw, m + 1)


def test_single_gaussian_closed_form():
    # one dominant component, rho=0: NLL = log(2*pi*s1*s2) + z/2
    m = 3
    logits = np.array([50.0, 0.0, 0.0])  # all weight on comp 0
    mu1 = np.array([0.5, 9.0, 9.0])
    mu2 = np.array([-0.25, 9.0, 9.0])
    s1 = np.array([2.0, 1.0, 1.0])
    s2 = np.array([0.5, 1.0, 1.0])
    rho = np.zeros(3)
    pen = np.zeros(3)
    raw = _raw_from((logits, mu1, mu2, s1, s2, rho, pen), m)
    mp = mdn.get_mixture_params(raw, m)
    dx, dy = jnp.float32(1.5), jnp.float32(0.25)
    nll = float(mdn.gmm_nll(dx, dy, mp))
    zx = (1.5 - 0.5) / 2.0
    zy = (0.25 + 0.25) / 0.5
    expected = np.log(2 * np.pi * 2.0 * 0.5) + 0.5 * (zx**2 + zy**2)
    np.testing.assert_allclose(nll, expected, rtol=1e-5)


def test_correlated_gaussian_matches_numpy_density():
    m = 1
    rho_val = 0.7
    raw = _raw_from((np.zeros(1), np.array([0.3]), np.array([-0.2]),
                     np.array([1.5]), np.array([0.8]), np.array([rho_val]),
                     np.zeros(3)), m)
    mp = mdn.get_mixture_params(raw, m)
    dx, dy = 0.9, 0.1
    logpdf = float(mdn.bivariate_normal_logpdf(
        jnp.float32(dx), jnp.float32(dy), mp)[..., 0])
    # numpy reference via covariance matrix
    cov = np.array([[1.5**2, rho_val * 1.5 * 0.8],
                    [rho_val * 1.5 * 0.8, 0.8**2]])
    diff = np.array([dx - 0.3, dy + 0.2])
    expected = (-0.5 * diff @ np.linalg.inv(cov) @ diff
                - 0.5 * np.log((2 * np.pi) ** 2 * np.linalg.det(cov)))
    np.testing.assert_allclose(logpdf, expected, rtol=1e-5)


def test_mixture_weighting():
    # two equal components at different means: pdf = average of the two
    m = 2
    raw = _raw_from((np.zeros(2), np.array([0.0, 2.0]), np.zeros(2),
                     np.ones(2), np.ones(2), np.zeros(2), np.zeros(3)), m)
    mp = mdn.get_mixture_params(raw, m)
    nll = float(mdn.gmm_nll(jnp.float32(1.0), jnp.float32(0.0), mp))

    def pdf(mu):
        return np.exp(-0.5 * (1.0 - mu) ** 2) / (2 * np.pi)

    np.testing.assert_allclose(np.exp(-nll), 0.5 * pdf(0) + 0.5 * pdf(2),
                               rtol=1e-5)


def _target_with_len(t, b, n_valid):
    """stroke-5 target whose sequences end (p3=1) after n_valid steps."""
    rng = np.random.default_rng(0)
    tgt = np.zeros((t, b, 5), np.float32)
    tgt[:, :, 0:2] = rng.normal(size=(t, b, 2))
    tgt[:, :, 2] = 1.0
    for i in range(b):
        tgt[n_valid:, i, 2] = 0.0
        tgt[n_valid:, i, 0:2] = 0.0
        tgt[n_valid:, i, 4] = 1.0
    return tgt


def test_reconstruction_masking_semantics():
    t, b, m = 10, 2, 3
    rng = np.random.default_rng(1)
    raw = jnp.asarray(rng.normal(size=(t, b, 6 * m + 3)), jnp.float32)
    mp = mdn.get_mixture_params(raw, m)
    tgt_full = jnp.asarray(_target_with_len(t, b, t))
    tgt_short = jnp.asarray(_target_with_len(t, b, 4))

    off_full, _ = mdn.reconstruction_loss(mp, tgt_full, t)
    off_short, _ = mdn.reconstruction_loss(mp, tgt_short, t)
    # masked-out steps contribute nothing -> shorter sequences, smaller sum
    assert float(off_short) < float(off_full)

    # offset term only counts the first 4 steps: recompute by truncation
    mp4 = mdn.get_mixture_params(raw[:4], m)
    off_manual, _ = mdn.reconstruction_loss(mp4, tgt_short[:4], t)
    np.testing.assert_allclose(float(off_short), float(off_manual), rtol=1e-5)

    # pen CE: unmasked by default (train), masked when mask_pen=True (eval)
    _, pen_train = mdn.reconstruction_loss(mp, tgt_short, t, mask_pen=False)
    _, pen_eval = mdn.reconstruction_loss(mp, tgt_short, t, mask_pen=True)
    assert float(pen_eval) < float(pen_train)


def test_normalization_is_by_max_seq_len():
    t, b, m = 8, 3, 2
    raw = jnp.asarray(np.random.default_rng(2).normal(size=(t, b, 6 * m + 3)),
                      jnp.float32)
    mp = mdn.get_mixture_params(raw, m)
    tgt = jnp.asarray(_target_with_len(t, b, t))
    off_a, pen_a = mdn.reconstruction_loss(mp, tgt, max_seq_len=t)
    off_b, pen_b = mdn.reconstruction_loss(mp, tgt, max_seq_len=2 * t)
    np.testing.assert_allclose(float(off_a) / 2, float(off_b), rtol=1e-6)
    np.testing.assert_allclose(float(pen_a) / 2, float(pen_b), rtol=1e-6)


def test_kl_loss_closed_form():
    # q == prior -> 0
    z = jnp.zeros((4, 8))
    assert float(mdn.kl_loss(z, z)) == 0.0
    # known case: mu=1, presig=0 -> 0.5 * mean(mu^2) = 0.5
    np.testing.assert_allclose(float(mdn.kl_loss(jnp.ones((4, 8)), z)), 0.5,
                               rtol=1e-6)
    # floor
    assert float(mdn.kl_cost_with_floor(jnp.float32(0.01), 0.2)) == \
        pytest.approx(0.2)
    assert float(mdn.kl_cost_with_floor(jnp.float32(0.5), 0.2)) == \
        pytest.approx(0.5)


def test_gmm_nll_gradients_finite_at_extremes():
    m = 2
    raw = jnp.zeros((6 * m + 3,))

    def f(raw):
        mp = mdn.get_mixture_params(raw, m)
        return mdn.gmm_nll(jnp.float32(100.0), jnp.float32(-100.0), mp)

    g = jax.grad(f)(raw)
    assert np.all(np.isfinite(np.asarray(g)))
