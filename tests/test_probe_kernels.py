"""Smoke tests for the arm-split probe kernels (CPU interpret mode).

The decomposition tables in ARCHITECTURE.md rest on
scripts/probe_dec_bwd_split.py and scripts/probe_enc_pocket.py; these
tests keep the probes' kernel variants building and running against
the production operand layout (which round 5 changed under them once
already — the reversed-index backward specs), so the measurement
tooling cannot silently rot between rounds. Numbers are NOT asserted
(timing is chip-only); only that every arm traces, compiles in
interpret mode, and produces finite outputs of the right shape.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sketch_rnn_tpu.ops import pallas_fused as PF

pytestmark = pytest.mark.slow

B, T, H, D = 16, 5, 512, 5


def _setup():
    key = jax.random.key(0)
    bf = jnp.bfloat16

    def w(shape, scale, dtype=bf, k=1):
        return (scale * jax.random.normal(jax.random.fold_in(key, k),
                                          shape)).astype(dtype)

    wx, wh = w((D, 4 * H), 0.3, k=1), w((H, 4 * H), 0.05, k=2)
    gam = jnp.ones((4, H), jnp.float32)
    bet = jnp.zeros((4, H), jnp.float32)
    gc2 = jnp.ones((1, H), jnp.float32)
    bc2 = jnp.zeros((1, H), jnp.float32)
    xs = w((T, B, D), 1.0, k=3)
    xb = w((B, 4 * H), 0.1, jnp.float32, k=4)
    c0 = jnp.zeros((B, H), jnp.float32)
    return bf, wx, wh, gam, bet, gc2, bc2, xs, xb, c0


@pytest.mark.parametrize("arm", ["no_lnbwd", "no_ln", "no_gates",
                                 "no_gradmm", "floor"])
def test_bwd_arm_kernels_run(arm):
    from scripts.probe_dec_bwd_split import make_bwd_kernel

    bf, wx, wh, gam, bet, gc2, bc2, xs, xb, c0 = _setup()
    seed = jnp.asarray(5, jnp.int32)
    hs, cT, hT, cs = PF._lnlstm_fwd_call(
        xs, wx, wh, gam, bet, gc2[0], bc2[0], c0, c0, 1.0, None, seed,
        0.9, bf, xb)
    h00 = c0.astype(hs.dtype)
    dhs = jnp.ones_like(hs).astype(jnp.float32)
    bt = PF._batch_tile(B, H, xb_bwd=True)
    mode, mask_arg, seed_arg = PF._mask_args(None, seed)
    step, tile, whole, mask_spec, seed_spec = PF._specs(
        bt, H, mode, mask_arg.shape)
    rstep, rprev, rmask = PF._rev_specs(T, bt, H, mode, mask_arg.shape)
    xb_mode, xb_arg, xb_spec = PF._xb_args(xb, bt, tile, whole)
    kern = functools.partial(make_bwd_kernel(arm), forget_bias=1.0,
                             mask_mode=mode, keep_prob=0.9,
                             xb_mode=xb_mode)
    outs = pl.pallas_call(
        kern,
        grid=(B // bt, T),
        in_specs=[rstep((bt, D)), xb_spec, whole(wx.shape),
                  whole(wh.shape), whole(gam.shape), whole(bet.shape),
                  whole(gc2.shape), whole(bc2.shape), rstep((bt, H)),
                  rprev((bt, H)), tile((bt, H)), rmask, seed_spec,
                  rstep((bt, H)), tile((bt, H)), tile((bt, H))],
        out_specs=(rstep((bt, D)), xb_spec, whole(wx.shape),
                   whole(wh.shape), whole(gam.shape), whole(bet.shape),
                   whole(gc2.shape), whole(bc2.shape), tile((bt, H)),
                   tile((bt, H))),
        out_shape=(
            jax.ShapeDtypeStruct((T, B, D), jnp.float32),
            jax.ShapeDtypeStruct(xb_arg.shape, jnp.float32),
            jax.ShapeDtypeStruct(wx.shape, jnp.float32),
            jax.ShapeDtypeStruct(wh.shape, jnp.float32),
            jax.ShapeDtypeStruct(gam.shape, jnp.float32),
            jax.ShapeDtypeStruct(bet.shape, jnp.float32),
            jax.ShapeDtypeStruct(gc2.shape, jnp.float32),
            jax.ShapeDtypeStruct(bc2.shape, jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ),
        scratch_shapes=[pltpu.VMEM((bt, H), jnp.float32),
                        pltpu.VMEM((bt, H), jnp.float32)],
        interpret=True,
    )(xs, xb_arg, wx, wh, gam, bet, gc2, bc2, cs, hs, h00,
      mask_arg, seed_arg, dhs, c0, c0)
    for o in outs:
        assert np.all(np.isfinite(np.asarray(o, np.float32)))


@pytest.mark.parametrize("arm", ["no_ln", "no_gates", "floor"])
def test_fwd_arm_kernels_run(arm):
    from scripts.probe_dec_bwd_split import make_fwd_kernel

    bf, wx, wh, gam, bet, gc2, bc2, xs, xb, c0 = _setup()
    seed = jnp.asarray(5, jnp.int32)
    bt = PF._batch_tile(B, H)
    mode, mask_arg, seed_arg = PF._mask_args(None, seed)
    step, tile, whole, mask_spec, seed_spec = PF._specs(
        bt, H, mode, mask_arg.shape)
    xb_mode, xb_arg, xb_spec = PF._xb_args(xb, bt, tile, whole)
    kern = functools.partial(make_fwd_kernel(arm), forget_bias=1.0,
                             mask_mode=mode, keep_prob=0.9,
                             xb_mode=xb_mode)
    outs = pl.pallas_call(
        kern,
        grid=(B // bt, T),
        in_specs=[step((bt, D)), xb_spec, whole(wx.shape),
                  whole(wh.shape), whole(gam.shape), whole(bet.shape),
                  whole(gc2.shape), whole(bc2.shape), tile((bt, H)),
                  tile((bt, H)), mask_spec, seed_spec],
        out_specs=(step((bt, H)), step((bt, H)), tile((bt, H)),
                   tile((bt, H))),
        out_shape=(
            jax.ShapeDtypeStruct((T, B, H), bf),
            jax.ShapeDtypeStruct((T, B, H), bf),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ),
        scratch_shapes=[pltpu.VMEM((bt, H), jnp.float32),
                        pltpu.VMEM((bt, H), jnp.float32)],
        interpret=True,
    )(xs, xb_arg, wx, wh, gam, bet, gc2, bc2, c0, c0,
      mask_arg, seed_arg)
    for o in outs:
        assert np.all(np.isfinite(np.asarray(o, np.float32)))


def test_enc_pocket_arms_trace():
    """Every probe_enc_pocket arm must build a differentiable loss over
    the production seq kernel at a tiny shape."""
    import scripts.probe_enc_pocket as PEP

    key = jax.random.key(0)
    Hs, Ds, NZ, Bs, Ts = 8, 5, 4, 8, 4
    bf = jnp.bfloat16

    def w(shape, scale, dtype=bf, k=1):
        return (scale * jax.random.normal(jax.random.fold_in(key, k),
                                          shape)).astype(dtype)

    ws = {
        "f": (w((Ds, 4 * Hs), 0.3, k=1),
              w((4 * Hs,), 0.05, jnp.float32, k=2),
              w((Hs, 4 * Hs), 0.05, k=3)),
        "b": (w((Ds, 4 * Hs), 0.3, k=4),
              w((4 * Hs,), 0.05, jnp.float32, k=5),
              w((Hs, 4 * Hs), 0.05, k=6)),
        "mu": w((2 * Hs, NZ), 0.1, k=7),
        "presig": w((2 * Hs, NZ), 0.1, k=8),
    }
    xs = w((Ts, Bs, Ds), 1.0, jnp.float32, k=9)
    # reuse the probe's loss builder via a tiny-shape monkey harness:
    # the probe module builds losses from module-level helpers, so we
    # just check the inline equivalents it uses are importable and the
    # seq kernel differentiates at this shape
    from sketch_rnn_tpu.ops.rnn import length_reverse_indices

    seq_len = jnp.full((Bs,), Ts, jnp.int32)
    rev_idx = length_reverse_indices(Ts, seq_len)
    c0 = jnp.zeros((Bs, Hs), jnp.float32)

    def loss(ws):
        xs_b = jnp.take_along_axis(xs, rev_idx[:, :, None], axis=0)
        hs_f = PF.fused_lstm_seq(xs, *ws["f"], c0, c0, 1.0, None,
                                 jnp.int32(3), 0.9, bf)
        hs_b = PF.fused_lstm_seq(xs_b, *ws["b"], c0, c0, 1.0, None,
                                 jnp.int32(5), 0.9, bf)
        h = jnp.concatenate([hs_f[-1], hs_b[-1]], axis=-1)
        return (jnp.sum(jnp.dot(h, ws["mu"],
                                preferred_element_type=jnp.float32))
                + jnp.sum(jnp.dot(h, ws["presig"],
                                  preferred_element_type=jnp.float32)))

    g = jax.grad(loss)(ws)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.all(np.isfinite(np.asarray(leaf, np.float32)))
