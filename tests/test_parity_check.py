"""End-to-end proof of the parity harness (VERDICT r3 #3).

The harness must work the day real data appears, with zero code
changes — so the whole path (synthetic npz on disk -> preset train ->
eval sweep -> table row -> reference comparison / exit code) is
exercised here on a generated corpus. Marked slow: it runs two tiny
trainings through the real ``train()`` loop.
"""

import json

import pytest

from scripts import parity_check
from sketch_rnn_tpu.data.loader import write_synthetic_npz

_TINY = ("batch_size=8,max_seq_len=32,enc_rnn_size=16,dec_rnn_size=16,"
         "z_size=4,num_mixture=2,enc_model=lstm,fused_rnn=false,"
         "compute_dtype=float32,save_every=2,eval_every=1000")


def _run(tmp_path, capsys, extra):
    data = tmp_path / "data"
    data.mkdir()
    write_synthetic_npz(str(data / "cat.npz"), num_train=24, num_valid=16,
                        num_test=16, max_len=28)
    rc = parity_check.main([
        "--data_dir", str(data), "--steps", "2", "--hparams", _TINY,
        "--workdir_root", str(tmp_path / "wd"), "--split", "valid",
        *extra])
    out = capsys.readouterr().out
    return rc, json.loads(out.strip().splitlines()[-1])


@pytest.mark.slow
def test_end_to_end_on_synthetic_npz(tmp_path, capsys):
    rc, table = _run(tmp_path, capsys, ["--configs", "uncond_lstm"])
    assert rc == 0
    (row,) = table["rows"]
    assert row["config"] == "uncond_lstm" and row["steps"] == 2
    assert row["recon"] > 0 and row["kl"] == 0.0  # unconditional: no KL
    assert "within_tol" not in row  # no reference metrics supplied


@pytest.mark.slow
def test_reference_comparison_gates_exit_code(tmp_path, capsys):
    """A reference table that cannot match (recon=0) must FAIL the run;
    resume makes the second config invocation reuse the first's
    checkpoint rather than retraining."""
    ref = tmp_path / "ref.json"
    ref.write_text(json.dumps({"vae": {"recon": 1e-9, "kl": 1e9}}))
    rc, table = _run(tmp_path, capsys,
                     ["--configs", "vae", "--reference_json", str(ref)])
    assert rc == 1
    (row,) = table["rows"]
    assert row["within_tol"] is False
    assert "d_recon_rel" in row and "d_kl_abs" in row


def test_compare_row_pure():
    row = {"config": "vae", "recon": 1.00, "kl": 0.40}
    ref = {"vae": {"recon": 1.02, "kl": 0.42}}
    out = parity_check.compare_row(row, ref, tol=0.05)
    assert out["within_tol"] is True
    assert out["d_recon_rel"] == pytest.approx(-0.02 / 1.02)
    assert out["d_kl_abs"] == pytest.approx(-0.02)
    # outside tolerance
    out = parity_check.compare_row(row, {"vae": {"recon": 2.0}}, tol=0.05)
    assert out["within_tol"] is False
    # unknown config: row passes through untouched
    out = parity_check.compare_row(row, {"other": {"recon": 1.0}}, 0.05)
    assert "within_tol" not in out or out["within_tol"] is None


def test_compare_row_corpus_mismatch():
    """ADVICE r5: a reference row recorded under a different corpus
    grid must fail loudly, not produce a quiet bogus delta."""
    row = {"config": "vae", "recon": 1.00, "kl": 0.40,
           "integer_grid": 255.0}
    ref = {"vae": {"recon": 1.00, "kl": 0.40, "integer_grid": None}}
    out = parity_check.compare_row(row, ref, tol=0.05)
    assert out["within_tol"] is False
    assert out["corpus_mismatch"] is True
    # matching grids compare normally
    ref = {"vae": {"recon": 1.00, "kl": 0.40, "integer_grid": 255.0}}
    out = parity_check.compare_row(row, ref, tol=0.05)
    assert out["within_tol"] is True and "corpus_mismatch" not in out
    # references without a grid record keep working (pre-this-PR refs)
    out = parity_check.compare_row(row, {"vae": {"recon": 1.0}}, 0.05)
    assert out["within_tol"] is True


def test_corpus_marker_guards_resume(tmp_path):
    """ADVICE r5: resuming a workdir onto a different corpus — or one
    whose corpus was never recorded — must fail loudly."""
    wd = str(tmp_path / "vae")
    marker = {"synthetic": True, "integer_grid": 255.0, "data_dir": ""}
    # fresh workdir: marker is written
    parity_check.check_corpus_marker(wd, marker)
    assert json.load(open(tmp_path / "vae" / "corpus.json")) == marker
    # same corpus: resume fine
    parity_check.check_corpus_marker(wd, marker)
    # different grid: refuse
    with pytest.raises(RuntimeError, match="mix corpora"):
        parity_check.check_corpus_marker(
            wd, {**marker, "integer_grid": None})
    # legacy workdir: checkpoints but no marker -> unknowable corpus
    wd2 = tmp_path / "old"
    wd2.mkdir()
    (wd2 / "ckpt_00000002.msgpack").write_bytes(b"")
    (wd2 / "ckpt_00000002.json").write_text(
        json.dumps({"step": 2, "format_version": 1}))
    with pytest.raises(RuntimeError, match="corpus.json"):
        parity_check.check_corpus_marker(str(wd2), marker)


def test_unknown_config_rejected(tmp_path, capsys):
    rc = parity_check.main(["--synthetic", "--configs", "nope"])
    assert rc == 2
    rc = parity_check.main(["--configs", "vae"])  # no data source
    assert rc == 2
