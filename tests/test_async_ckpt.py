"""Async-checkpoint tests: bitwise parity, crash safety, backpressure.

ISSUE 3 satellite: the background writer must be an invisible
optimization — same bytes on disk as the sync path, a death mid-write
never corrupts ``latest_checkpoint``, failures stop training (one save
late), and at most ONE save is ever in flight.
"""

import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sketch_rnn_tpu.train.async_ckpt as AC
from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.train import make_train_state
from sketch_rnn_tpu.train.async_ckpt import (
    AsyncCheckpointer,
    snapshot_device_state,
)
from sketch_rnn_tpu.train.checkpoint import (
    _paths,
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)

TINY = dict(batch_size=8, max_seq_len=16, enc_rnn_size=8, dec_rnn_size=12,
            z_size=4, num_mixture=2, hyper_rnn_size=8, hyper_embed_size=4)


def tiny_state(step=3, key=0):
    hps = HParams(**TINY)
    model = SketchRNN(hps)
    state = make_train_state(model, hps, jax.random.key(key))
    return hps, state._replace(step=jnp.asarray(step, jnp.int32))


def test_async_save_byte_identical_to_sync(tmp_path):
    """The async writer goes through the same write_checkpoint commit on
    the same host values — files must be byte-identical to a sync save
    of the same state, and restore bitwise-equal."""
    hps, state = tiny_state(step=7)
    d_sync = str(tmp_path / "sync")
    d_async = str(tmp_path / "async")
    save_checkpoint(d_sync, state, scale_factor=2.5, hps=hps)

    ckpt = AsyncCheckpointer(d_async)
    ckpt.save(state, 2.5, hps)
    ckpt.wait()
    assert latest_checkpoint(d_async) == 7
    for a, b in zip(_paths(d_sync, 7), _paths(d_async, 7)):
        assert open(a, "rb").read() == open(b, "rb").read()

    template = tiny_state(step=0, key=9)[1]
    rs, scale_s, _ = restore_checkpoint(d_sync, template)
    ra, scale_a, _ = restore_checkpoint(d_async, template)
    assert scale_s == scale_a == 2.5
    for a, b in zip(jax.tree_util.tree_leaves(rs),
                    jax.tree_util.tree_leaves(ra)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_snapshot_is_fresh_arrays_with_equal_values():
    """The device snapshot must hand the writer arrays the training loop
    can never donate: fresh buffers (distinct objects), same values."""
    _, state = tiny_state()
    snap = snapshot_device_state(state)
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(snap)):
        assert a is not b
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_crash_mid_write_keeps_previous_checkpoint(tmp_path, monkeypatch):
    """A writer death between the sidecar and the msgpack (the worst
    window: sidecar committed, data not) must leave latest_checkpoint on
    the previous COMPLETE step, and surface on the next wait()."""
    import sketch_rnn_tpu.train.checkpoint as C

    hps, state = tiny_state(step=3)
    d = str(tmp_path)
    save_checkpoint(d, state, 1.5, hps)
    assert latest_checkpoint(d) == 3

    # die during serialization: the sidecar json for step 5 is already
    # on disk, the msgpack never lands — the exact kill-mid-write shape
    def boom(_):
        raise OSError("disk died")

    monkeypatch.setattr(C.serialization, "to_bytes", boom)
    ckpt = AsyncCheckpointer(d)
    later = tiny_state(step=5)[1]
    ckpt.save(later, 1.5, hps)
    with pytest.raises(RuntimeError, match="async checkpoint"):
        ckpt.wait()
    monkeypatch.undo()

    assert os.path.exists(_paths(d, 5)[1])  # the orphan sidecar...
    assert latest_checkpoint(d) == 3        # ...is invisible to resume
    restored, scale, _ = restore_checkpoint(d, state)
    assert int(restored.step) == 3 and scale == 1.5


def test_failure_surfaces_on_next_save(tmp_path, monkeypatch):
    """The sync path's failure-stops-training semantics, one save late:
    a background write error re-raises from the NEXT save() call."""
    hps, state = tiny_state()
    calls = []

    def boom(*a, **k):
        calls.append(1)
        raise OSError("no space")

    monkeypatch.setattr(AC, "write_checkpoint", boom)
    ckpt = AsyncCheckpointer(str(tmp_path))
    ckpt.save(state, 1.0, hps)  # fails in the background
    with pytest.raises(RuntimeError, match="async checkpoint"):
        ckpt.save(state, 1.0, hps)
    assert len(calls) == 1  # the second save never spawned a writer
    ckpt.wait()  # the failure was consumed; wait() is clean again


def test_backpressure_caps_in_flight_at_one(tmp_path, monkeypatch):
    """save() must JOIN the pending writer before starting the next —
    two writers can never run concurrently (they would race _prune and
    interleave partial files)."""
    hps, state = tiny_state()
    gate = threading.Event()
    active = []
    max_active = []

    def slow_write(*a, **k):
        active.append(1)
        max_active.append(len(active))
        gate.wait(timeout=10)
        active.pop()
        return "path"

    monkeypatch.setattr(AC, "write_checkpoint", slow_write)
    ckpt = AsyncCheckpointer(str(tmp_path))
    ckpt.save(state, 1.0, hps)
    assert ckpt.in_flight

    # second save from another thread: must block in the join until the
    # first writer finishes, never spawning a concurrent one
    second_started = threading.Event()
    second_done = threading.Event()

    def second():
        second_started.set()
        ckpt.save(state, 1.0, hps)
        second_done.set()

    t = threading.Thread(target=second, daemon=True)
    t.start()
    second_started.wait(timeout=5)
    assert not second_done.wait(timeout=0.3)  # blocked on the join
    gate.set()
    t.join(timeout=10)
    assert second_done.is_set()
    ckpt.join()
    assert max(max_active) == 1
    assert ckpt.saves_started == 2


def test_join_never_raises_wait_does(tmp_path, monkeypatch):
    """join() is the finally-block primitive: it must swallow nothing
    permanently — the stored failure is peekable via .failure (for the
    loop's abnormal-exit warning) and still raises from wait()."""
    hps, state = tiny_state()
    monkeypatch.setattr(AC, "write_checkpoint",
                        lambda *a, **k: (_ for _ in ()).throw(OSError("x")))
    ckpt = AsyncCheckpointer(str(tmp_path))
    assert ckpt.failure is None
    ckpt.save(state, 1.0, hps)
    ckpt.join()  # no raise
    assert isinstance(ckpt.failure, OSError)  # peek does not clear
    with pytest.raises(RuntimeError, match="async checkpoint"):
        ckpt.wait()
    assert ckpt.failure is None  # wait() consumed it
