"""Fused cache-resident serve decode kernel (ISSUE 17 tentpole).

The contract under test, per ops/pallas_decode.py's documented parity
budget: UNCONDITIONAL models produce bitwise-identical chunk outputs
under ``decode_kernel=pallas`` (interpret mode — the CPU tier-1 path);
CONDITIONAL models agree within 1e-5 per stroke component (the hoisted
``extra @ wx`` matmul re-associates vs the scan body's concat-dot)
with step counts and pen states EQUAL. Masking semantics — pre-done
slots, mid-chunk caps, admission resets — are exercised across
consecutive chunks, the teacher-forced replay twin rides the same
budget, the hyper cell is refused by name, and the engine's
JitCompileProbe geometry key distinguishes kernel flavor and param
dtype (a scan->pallas or fp32->int8 swap is a NEW compile, never a
silent cache hit).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.ops.pallas_decode import (check_cell_kind,
                                              make_uniforms,
                                              modeled_chunk_bytes)
from sketch_rnn_tpu.sample.sampler import END_TOKEN
from sketch_rnn_tpu.serve.engine import (START_TOKEN, Request,
                                         ServeEngine, make_chunk_step)

TINY = dict(batch_size=4, max_seq_len=32, enc_rnn_size=12,
            dec_rnn_size=16, z_size=6, num_mixture=3, hyper_rnn_size=8,
            hyper_embed_size=4, serve_slots=4, serve_chunk=4)

CHUNK = 4
B = 4
COND_TOL = 1e-5


def _setup(cell, conditional, num_classes=0, seed=0):
    hps = HParams(**TINY).replace(dec_model=cell,
                                  conditional=conditional,
                                  num_classes=num_classes)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(seed))
    return hps, model, params


def _pool(hps, n=B, caps=None, seed=3):
    keys = jax.vmap(jax.random.fold_in,
                    (None, 0))(jax.random.key(seed), jnp.arange(n))
    z = (jax.random.normal(jax.random.key(seed + 1), (n, hps.z_size))
         if hps.conditional else None)
    labels = (jnp.arange(n, dtype=jnp.int32) % hps.num_classes
              if hps.num_classes > 0 else None)
    caps = (jnp.full((n,), 8 * CHUNK, jnp.int32) if caps is None
            else jnp.asarray(caps, jnp.int32))
    return (jax.vmap(jax.random.key_data)(keys), z, labels,
            jnp.full((n,), 0.7, jnp.float32), caps, None, None, None)


def _state0(hps, model, params, pool):
    z0 = jnp.zeros((B, hps.z_size)) if hps.conditional else None
    carry = model.decoder_initial_carry(params, z0, B)
    prev = jnp.broadcast_to(jnp.asarray(START_TOKEN, jnp.float32),
                            (B, 5))
    return (carry, prev, jnp.zeros((B,), jnp.int32),
            jnp.zeros((B,), bool), jnp.ones((B,), bool),
            jnp.arange(B, dtype=jnp.int32), pool)


def _flat(out):
    return jax.tree_util.tree_leaves(out)


@pytest.mark.parametrize("cell", ["lstm", "layer_norm"])
def test_chunk_bitwise_unconditional(cell):
    """decode_kernel=pallas is BITWISE the jitted scan chunk program
    for unconditional models: carry, prev, t, done and all K strokes."""
    hps, model, params = _setup(cell, conditional=False)
    state = _state0(hps, model, params, _pool(hps))
    outs = {k: jax.jit(make_chunk_step(model, hps, CHUNK, params,
                                       kernel=k))(*state)
            for k in ("scan", "pallas")}
    for a, b in zip(_flat(outs["scan"]), _flat(outs["pallas"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("cell,ncls", [("lstm", 0), ("lstm", 3),
                                       ("layer_norm", 0)])
def test_chunk_conditional_within_budget(cell, ncls):
    """Conditional models: strokes within the documented 1e-5 budget,
    pen columns, step counters and done flags EXACTLY equal (the
    divergence is FMA re-association of the hoisted extra matmul, not
    a semantic difference)."""
    hps, model, params = _setup(cell, conditional=True,
                                num_classes=ncls)
    state = _state0(hps, model, params, _pool(hps))
    outs = {k: jax.jit(make_chunk_step(model, hps, CHUNK, params,
                                       kernel=k))(*state)
            for k in ("scan", "pallas")}
    (c_s, p_s, t_s, d_s, s_s) = outs["scan"]
    (c_p, p_p, t_p, d_p, s_p) = outs["pallas"]
    np.testing.assert_array_equal(np.asarray(t_s), np.asarray(t_p))
    np.testing.assert_array_equal(np.asarray(d_s), np.asarray(d_p))
    np.testing.assert_array_equal(np.asarray(s_s)[..., 2:],
                                  np.asarray(s_p)[..., 2:])
    assert float(jnp.max(jnp.abs(s_s - s_p))) <= COND_TOL
    for a, b in zip(_flat(c_s), _flat(c_p)):
        assert float(jnp.max(jnp.abs(a - b))) <= COND_TOL


def test_masked_slot_semantics_across_chunks():
    """Done/reset masking across consecutive chunks: slots capped
    mid-chunk freeze (END_TOKEN strokes, carry/t held), pre-done slots
    stay frozen through the NEXT chunk, and both flavors agree
    bitwise (unconditional model)."""
    hps, model, params = _setup("lstm", conditional=False)
    # caps 2, 3, 9, 16: slots 0/1 finish mid-chunk-1, slot 2 mid-run
    pool = _pool(hps, caps=[2, 3, 9, 16])
    state = _state0(hps, model, params, pool)
    fns = {k: jax.jit(make_chunk_step(model, hps, CHUNK, params,
                                      kernel=k))
           for k in ("scan", "pallas")}
    prev_chunk = {k: state for k in fns}
    for step in range(3):  # 12 decode steps: every cap crossing
        outs = {}
        for k, fn in fns.items():
            carry, prev, t, done, _, slot_idx, _ = prev_chunk[k]
            no_reset = jnp.zeros((B,), bool) if step else state[4]
            outs[k] = fn(carry, prev, t, done, no_reset, slot_idx,
                         pool)
            prev_chunk[k] = (*outs[k][:4], no_reset, slot_idx, pool)
        for a, b in zip(_flat(outs["scan"]), _flat(outs["pallas"])):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))
    carry, prev, t, done, strokes = outs["pallas"]
    t, done = np.asarray(t), np.asarray(done)
    # caps are hard ceilings (a slot may also end naturally, earlier);
    # a slot is done iff it stopped before the 12 steps ran out
    assert np.all(t <= np.minimum([2, 3, 9, 16], 12))
    np.testing.assert_array_equal(done, t < 12)
    assert done[0] and t[0] <= 2  # slot 0 froze during chunk 1
    # a slot done for the whole last chunk emitted only END_TOKENs
    np.testing.assert_array_equal(
        np.asarray(strokes)[:, 0, :],
        np.broadcast_to(np.asarray(END_TOKEN, np.float32),
                        (CHUNK, 5)))


def test_make_uniforms_matches_inloop_draws():
    """``u[s, b] = uniform(fold_in(keys[b], t0[b] + s))`` — bitwise
    the engine's in-loop draw at every live step offset."""
    keys = jax.vmap(jax.random.fold_in,
                    (None, 0))(jax.random.key(9), jnp.arange(3))
    t0 = jnp.asarray([0, 5, 11], jnp.int32)
    u = make_uniforms(keys, t0, 4)
    assert u.shape == (4, 3, 4)
    for s in range(4):
        for b in range(3):
            want = jax.random.uniform(
                jax.random.fold_in(keys[b], t0[b] + s), (4,))
            np.testing.assert_array_equal(np.asarray(u[s, b]),
                                          np.asarray(want))


@pytest.mark.parametrize("cell", ["lstm", "layer_norm"])
def test_encode_replay_parity(cell):
    """The teacher-forced replay twin (serve_encode's carry): pallas
    vs scan within the conditional budget, mu/prev identical (they
    never enter the kernel)."""
    from sketch_rnn_tpu.serve.endpoints import make_encode_step

    hps, model, params = _setup(cell, conditional=True)
    edge = 6
    rng = np.random.default_rng(0)
    strokes = jnp.asarray(rng.normal(0, 2, (B, edge + 1, 5)),
                          jnp.float32)
    strokes = strokes.at[..., 2:].set(0).at[..., 2].set(1.0)
    seq_len = jnp.asarray([6, 2, 4, 1], jnp.int32)
    outs = {k: jax.jit(make_encode_step(model, hps, params, edge,
                                        kernel=k))(strokes, seq_len,
                                                   None)
            for k in ("scan", "pallas")}
    mu_s, carry_s, prev_s = outs["scan"]
    mu_p, carry_p, prev_p = outs["pallas"]
    np.testing.assert_array_equal(np.asarray(mu_s), np.asarray(mu_p))
    np.testing.assert_array_equal(np.asarray(prev_s),
                                  np.asarray(prev_p))
    assert float(jnp.max(jnp.abs(carry_s - carry_p))) <= COND_TOL


def test_hyper_cell_refused_by_name():
    """The hyper cell's nested carry stays on the scan path: the
    refusal names the cell and the fallback at every entry point."""
    hps, model, params = _setup("hyper", conditional=False)
    with pytest.raises(ValueError, match="hyper.*decode_kernel=scan"):
        check_cell_kind("hyper")
    with pytest.raises(ValueError, match="decode_kernel=scan"):
        make_chunk_step(model, hps, CHUNK, params, kernel="pallas")
    with pytest.raises(ValueError, match="decode_kernel=scan"):
        ServeEngine(model, hps, params, decode_kernel="pallas")


def test_config_validates_serving_knobs():
    with pytest.raises(ValueError, match="decode_kernel"):
        HParams(**TINY).replace(decode_kernel="fused").validate()
    with pytest.raises(ValueError, match="serve_quantize"):
        HParams(**TINY).replace(serve_quantize="int4").validate()


def test_probe_geometry_key_covers_kernel_and_dtype():
    """A scan->pallas or fp32->int8 swap changes the chunk program's
    probe geometry key — a new compile, never a silent cache hit at
    the same pool shape. Arming speculation or changing draft depth
    (ISSUE 18) is likewise its own geometry, and the (draft_on, D)
    fields sit BEFORE (kernel, dtype) so key[:-2] stays the
    flavor-independent comparison the pins rest on."""
    from sketch_rnn_tpu.models.draft import self_draft_params

    hps, model, params = _setup("lstm", conditional=True)
    pool = _pool(hps)
    args = (None, None, None, None, None, None, pool)
    keys = {}
    eng = ServeEngine(model, hps, params)
    keys[("scan", "float32")] = eng._chunk_fn._geom(args)
    eng.swap_params(params, param_dtype="int8")
    keys[("scan", "int8")] = eng._chunk_fn._geom(args)
    eng2 = ServeEngine(model, hps, params, decode_kernel="pallas")
    keys[("pallas", "float32")] = eng2._chunk_fn._geom(args)
    hps_d = hps.replace(draft_rnn_size=hps.dec_rnn_size,
                        draft_num_mixture=0)
    dp = self_draft_params(params, hps_d)
    for d in (4, 8):
        eng3 = ServeEngine(model, hps_d, params, draft_params=dp,
                           draft_depth=d)
        keys[("spec", d)] = eng3._chunk_fn._geom(args)
    assert len(set(keys.values())) == 5
    # the pool-shape part of the key is shared: only flavor/dtype/
    # draft-arming vary
    assert keys[("scan", "float32")][:-2] == \
        keys[("pallas", "float32")][:-2]
    shapes = tuple(tuple(p.shape) for p in pool if p is not None)
    for k in keys.values():
        assert k[:len(shapes)] == shapes
    # the (draft_on, D) fields are exactly the slice between the pool
    # shapes and the (kernel, dtype) tail
    assert keys[("scan", "float32")][:-2][len(shapes):] == (False, 0)
    assert keys[("spec", 4)][:-2][len(shapes):] == (True, 4)
    assert keys[("spec", 8)][:-2][len(shapes):] == (True, 8)


def test_engine_run_pallas_end_to_end():
    """A full engine burst under decode_kernel=pallas: step counts
    honor caps, strokes match the scan engine within the budget, pen
    states exactly."""
    hps, model, params = _setup("lstm", conditional=True)
    reqs = [Request(key=jax.random.key(100 + i),
                    z=np.asarray(
                        jax.random.normal(jax.random.key(i),
                                          (hps.z_size,))),
                    temperature=0.8, max_len=6, uid=i)
            for i in range(6)]
    outs = {}
    for k in ("scan", "pallas"):
        eng = ServeEngine(model, hps, params, decode_kernel=k)
        out = eng.run([dataclasses.replace(r) for r in reqs])
        outs[k] = {r.uid: r for r in out["results"]}
    for uid in outs["scan"]:
        a, b = outs["scan"][uid], outs["pallas"][uid]
        assert a.steps == b.steps
        sa, sb = np.asarray(a.strokes5), np.asarray(b.strokes5)
        np.testing.assert_array_equal(sa[..., 2:], sb[..., 2:])
        assert float(np.max(np.abs(sa - sb))) <= COND_TOL


def test_modeled_ledger_exceeds_acceptance_at_serve_geometry():
    """The box-constraint proof arm: at the committed smoke serve
    geometry (B=32 K=8 H=256 M=5) the modeled per-chunk HBM ratio
    clears the >= 2x acceptance with margin, and shrinks toward 1 as
    K -> 1 (the model is honest, not a constant)."""
    led = modeled_chunk_bytes(32, 8, 256, 13, 33, extra_dim=8)
    assert led["modeled_speedup"] >= 2.0
    assert led["fused_ops_per_step"] == 5
    led1 = modeled_chunk_bytes(32, 1, 256, 13, 33, extra_dim=8)
    assert led1["modeled_speedup"] < led["modeled_speedup"]
    assert led1["modeled_speedup"] == pytest.approx(
        led1["scan_chunk_bytes"] / led1["kernel_chunk_bytes"])
