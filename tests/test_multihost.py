"""Two-process multi-host data parallelism over CPU (SURVEY.md §2
component 18 DCN path; VERDICT r1 'missing' #3).

Spawns 2 real OS processes that form a ``jax.distributed`` cluster of
2x2 virtual CPU devices, each feeding its own host stripe of the corpus,
and asserts:

1. the run completes (collectives over the loopback DCN work),
2. parameters are bit-identical across the two processes (the replicated
   DP invariant), and
3. parameters match a single-process run of the same global computation
   (4-device mesh, same global batches) — the multi-process mechanics
   change nothing but the transport.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# interpret-mode / subprocess heavy: excluded from the quick loop
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _clean_env() -> dict:
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_ENABLE_X64")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.parametrize("fused", [False, True],
                         ids=["scan", "fused-production"])
def test_two_process_dp_matches_single_process(tmp_path, fused):
    nproc = 2
    coordinator = f"127.0.0.1:{_free_port()}"
    outdir = str(tmp_path)
    worker = os.path.join(REPO, "tests", "_multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(rank), str(nproc), coordinator, outdir,
         "1" if fused else "0"],
        env=_clean_env(), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for rank in range(nproc)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=540)
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {rank} failed:\n{out}"

    loaded = [np.load(os.path.join(outdir, f"params_{r}.npz"))
              for r in range(nproc)]
    keys = set(loaded[0].files)
    assert keys == set(loaded[1].files) and len(keys) > 4

    # (2) replicated params identical across processes, bitwise
    for k in keys:
        np.testing.assert_array_equal(loaded[0][k], loaded[1][k],
                                      err_msg=f"cross-process mismatch: {k}")

    # (3) equal to the same computation in ONE process (the in-process
    # 8-virtual-device platform from conftest.py; mesh restricted to 4
    # devices to match the cluster) feeding the concatenated global batch
    import jax

    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.parallel.mesh import make_mesh, shard_batch
    from sketch_rnn_tpu.train import make_train_state, make_train_step
    from tests._multihost_common import (
        HPS, dump_params, make_striped_loader, step_keys)

    hps = (HPS.replace(fused_rnn=True, fused_residual_dtype="bfloat16")
           if fused else HPS)
    lhps = hps.replace(batch_size=hps.batch_size // nproc)
    stripes = [make_striped_loader(lhps, host_id=r, num_hosts=nproc)
               for r in range(nproc)]
    model = SketchRNN(hps)
    mesh = make_mesh(hps, devices=jax.devices()[:4])
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh)
    for i, key in enumerate(step_keys(3)):
        locals_ = [s.get_batch(i % max(s.num_batches, 1)) for s in stripes]
        # multi-process global-array layout: process-local rows concatenate
        # in process order (mesh device order is [p0d0, p0d1, p1d0, p1d1])
        batch = {k: np.concatenate([lb[k] for lb in locals_])
                 for k in locals_[0]}
        state, _ = step(state, shard_batch(batch, mesh), key)
    ref_path = os.path.join(outdir, "params_ref.npz")
    dump_params(state.params, ref_path)
    ref = np.load(ref_path)

    for k in (set(keys) - {"__extra__/loss"}):
        np.testing.assert_allclose(
            loaded[0][k], ref[k], rtol=2e-6, atol=2e-7,
            err_msg=f"multi-process vs single-process mismatch: {k}")

    # (4) per-class eval across hosts: identical across processes
    # (bitwise — same global computation), and equal to a SINGLE-process
    # sweep over the unstriped corpus up to summation order (the striped
    # sweep's global batches interleave rows differently, so sums
    # reassociate; the deterministic non-conditional config makes that
    # the ONLY difference)
    from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
    from sketch_rnn_tpu.train import make_per_class_eval_step
    from sketch_rnn_tpu.train.loop import evaluate_per_class
    from tests._multihost_common import CORPUS_SIZE, PC_CLASSES

    pcs = [np.load(os.path.join(outdir, f"pc_{r}.npz"))
           for r in range(nproc)]
    assert set(pcs[0].files) == set(pcs[1].files) and len(pcs[0].files) > 3
    for k in pcs[0].files:
        np.testing.assert_array_equal(pcs[0][k], pcs[1][k],
                                      err_msg=f"pc cross-process: {k}")

    pc_hps = hps.replace(num_classes=PC_CLASSES, conditional=False)
    seqs, labels = make_synthetic_strokes(CORPUS_SIZE,
                                          num_classes=PC_CLASSES,
                                          min_len=8, max_len=20, seed=1)
    full_loader = DataLoader(seqs, pc_hps, labels=labels, seed=0)
    pc_model = SketchRNN(pc_hps)
    pc_params = pc_model.init_params(jax.random.key(7))
    pc_step = make_per_class_eval_step(pc_model, pc_hps, mesh)
    per_ref = evaluate_per_class(pc_params, full_loader, pc_step,
                                 PC_CLASSES, mesh)
    for k in pcs[0].files:
        c, metric = k.split("/", 1)
        if metric == "__none__":
            assert per_ref[int(c)] is None
            continue
        np.testing.assert_allclose(
            float(pcs[0][k]), per_ref[int(c)][metric], rtol=1e-5,
            err_msg=f"pc multi vs single: {k}")

    # (5) mesh-sharded sampler (VERDICT r3 #7): identical across
    # processes bitwise, and bitwise equal to a single-process run of
    # the same sampler over the same 4-device mesh (per-shard keys fold
    # in the mesh axis index, which is transport-independent; params
    # are a fixed deterministic init so no training noise enters)
    import jax.numpy as jnp

    from sketch_rnn_tpu.sample.sampler import make_sampler

    samples = [np.load(os.path.join(outdir, f"sample_{r}.npz"))
               for r in range(nproc)]
    np.testing.assert_array_equal(samples[0]["s5"], samples[1]["s5"],
                                  err_msg="sampler cross-process s5")
    np.testing.assert_array_equal(samples[0]["lengths"],
                                  samples[1]["lengths"])
    sample_params = model.init_params(jax.random.key(21))
    sampler = make_sampler(model, hps, mesh=mesh)
    n = hps.batch_size
    z = jax.random.normal(jax.random.key(11), (n, hps.z_size),
                          jnp.float32)
    s5_ref, len_ref = sampler(sample_params, jax.random.key(12), n, z,
                              None, 0.7)
    np.testing.assert_array_equal(samples[0]["lengths"],
                                  np.asarray(len_ref))
    np.testing.assert_array_equal(samples[0]["s5"], np.asarray(s5_ref),
                                  err_msg="sampler multi vs single")

    # (6) checkpoint save -> resume across processes (VERDICT r3 #7,
    # the shared-workdir contract): the primary's checkpoint restored
    # by BOTH processes, trained 2 more steps — params bitwise equal
    # across processes and equal (to transport reassociation) to a
    # single-process 5-step run
    resumed = [np.load(os.path.join(outdir, f"params_resumed_{r}.npz"))
               for r in range(nproc)]
    for k in resumed[0].files:
        np.testing.assert_array_equal(
            resumed[0][k], resumed[1][k],
            err_msg=f"resumed cross-process mismatch: {k}")
    from tests._multihost_common import step_keys
    state5 = state
    for i, key in list(enumerate(step_keys(5)))[3:]:
        locals_ = [s.get_batch(i % max(s.num_batches, 1)) for s in stripes]
        batch = {k: np.concatenate([lb[k] for lb in locals_])
                 for k in locals_[0]}
        state5, _ = step(state5, shard_batch(batch, mesh), key)
    ref5_path = os.path.join(outdir, "params_ref5.npz")
    dump_params(state5.params, ref5_path)
    ref5 = np.load(ref5_path)
    for k in resumed[0].files:
        np.testing.assert_allclose(
            resumed[0][k], ref5[k], rtol=2e-6, atol=2e-7,
            err_msg=f"resumed multi vs single: {k}")
