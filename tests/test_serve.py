"""Continuous-batching engine tests (ISSUE 2).

The load-bearing invariant: slot recycling changes SCHEDULING, never
OUTPUTS — a request's strokes are bitwise-identical whether it is
served solo, in a full batch, or admitted mid-flight into a recycled
slot, and regardless of chunk size or static/continuous mode. All
tests are tier-1 (CPU, tiny models, small B/K).
"""

import dataclasses

import jax
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.serve import Request, ServeEngine, generate_many

TINY = dict(batch_size=8, max_seq_len=24, enc_rnn_size=12,
            dec_rnn_size=16, z_size=6, num_mixture=3, hyper_rnn_size=8,
            hyper_embed_size=4, serve_slots=4, serve_chunk=2)


def tiny_hps(**kw) -> HParams:
    return HParams(**{**TINY, **kw})


@pytest.fixture(scope="module")
def cond_setup():
    """One conditional model + engine shared across tests (the chunk
    program compile is the expensive part)."""
    hps = tiny_hps()
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    return hps, model, params, ServeEngine(model, hps, params)


def _req(i: int, z_dim: int, cap: int = 0, temp: float = 0.8) -> Request:
    rng = np.random.default_rng(i)
    return Request(key=jax.random.key(1000 + i),
                   z=rng.standard_normal(z_dim).astype(np.float32),
                   temperature=temp, max_len=cap or None)


def _clone(req: Request) -> Request:
    return dataclasses.replace(req, uid=None)


def _by_uid(out):
    return {r.uid: r for r in out["results"]}


def test_engine_completes_all_and_output_shape(cond_setup):
    hps, model, params, eng = cond_setup
    reqs = [_req(i, hps.z_size, cap=4 + (3 * i) % 17) for i in range(10)]
    out = eng.run(list(reqs))
    m = out["metrics"]
    assert m["completed"] == 10
    assert 0 < m["slot_utilization"] <= 1
    assert m["sketches_per_sec"] > 0
    assert m["latency_p50_s"] <= m["latency_p95_s"] <= m["latency_p99_s"]
    for r in out["results"]:
        assert r.strokes5.shape == (r.steps, 5)
        assert np.isfinite(r.strokes5).all()
        # pen state is one-hot everywhere
        np.testing.assert_allclose(r.strokes5[:, 2:].sum(-1), 1.0)
        # length excludes the end-of-sketch row iff it was drawn
        assert r.length == r.steps - int(r.strokes5[-1, 4] > 0.5)
        assert r.steps <= (reqs[r.uid].max_len or hps.max_seq_len)
        assert r.queue_wait_s >= 0 and r.latency_s >= r.decode_s


def test_bitwise_invariance_solo_batch_midflight(cond_setup):
    """THE acceptance invariant: same request -> same strokes whether
    solo, in a full batch, or admitted mid-flight into a recycled
    slot."""
    hps, model, params, eng = cond_setup
    probe = _req(0, hps.z_size, cap=12)
    # full batch: probe rides slot 1 from the start
    fillers = [_req(10 + i, hps.z_size, cap=3 + i) for i in range(7)]
    batch = [fillers[0], _clone(probe)] + fillers[1:]
    ref = _by_uid(eng.run(batch))[1].strokes5
    # solo: engine otherwise empty
    solo = eng.run([_clone(probe)])["results"][0].strokes5
    np.testing.assert_array_equal(solo, ref)
    # mid-flight: 4 slots fill with short requests; the probe queues
    # and is admitted into whichever slot is recycled first
    short = [_req(20 + i, hps.z_size, cap=2) for i in range(4)]
    out = eng.run(short + [_clone(probe)])
    mid = _by_uid(out)[4].strokes5
    # really recycled: more requests than slots
    assert out["metrics"]["completed"] == 5
    np.testing.assert_array_equal(mid, ref)


def test_chunk_size_and_static_mode_invariance(cond_setup):
    """Chunk size K and the recycle/static scheduling policy change
    when work happens, never what is computed."""
    hps, model, params, eng = cond_setup
    reqs = [_req(i, hps.z_size, cap=3 + (5 * i) % 14) for i in range(9)]
    ref = _by_uid(eng.run([_clone(r) for r in reqs]))
    st = _by_uid(eng.run([_clone(r) for r in reqs], recycle=False))
    eng4 = ServeEngine(model, hps, params, chunk=4)
    k4 = _by_uid(eng4.run([_clone(r) for r in reqs]))
    for uid, r in ref.items():
        np.testing.assert_array_equal(st[uid].strokes5, r.strokes5)
        np.testing.assert_array_equal(k4[uid].strokes5, r.strokes5)


def test_run_is_repeatable(cond_setup):
    """Two runs of the same request list are bitwise identical (guards
    the async-dispatch aliasing race: the scheduler must not mutate
    arrays an in-flight chunk still reads)."""
    hps, model, params, eng = cond_setup
    reqs = [_req(i, hps.z_size, cap=3 + (5 * i) % 14) for i in range(9)]
    a = _by_uid(eng.run([_clone(r) for r in reqs]))
    b = _by_uid(eng.run([_clone(r) for r in reqs]))
    for uid, r in a.items():
        np.testing.assert_array_equal(b[uid].strokes5, r.strokes5)


def test_temperature_is_per_request(cond_setup):
    """Different temperatures in the same batch are honored per slot —
    and a request's output depends only on ITS temperature."""
    hps, model, params, eng = cond_setup
    base = _req(0, hps.z_size, cap=12)
    hot = dataclasses.replace(_clone(base), temperature=1.5)
    ref = eng.run([_clone(base)])["results"][0].strokes5
    mixed = _by_uid(eng.run([_clone(base), hot,
                             _req(5, hps.z_size, cap=6)]))
    np.testing.assert_array_equal(mixed[0].strokes5, ref)
    # the hot clone shares key/z but draws at another temperature
    assert not np.array_equal(mixed[1].strokes5, ref)


def test_unconditional_and_class_conditional():
    hps = tiny_hps(conditional=False, num_classes=3, serve_slots=2)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    reqs = [Request(key=jax.random.key(i), label=i % 3,
                    temperature=0.7, max_len=6) for i in range(4)]
    out = generate_many(model, params, hps, reqs)
    assert out["metrics"]["completed"] == 4
    # label must matter (same key, different class embedding)
    a = Request(key=jax.random.key(9), label=0, temperature=0.7,
                max_len=8)
    b = Request(key=jax.random.key(9), label=2, temperature=0.7,
                max_len=8)
    res = generate_many(model, params, hps, [a, b])["results"]
    res = {r.uid: r for r in res}
    assert not np.array_equal(res[0].strokes5, res[1].strokes5)


def test_request_validation(cond_setup):
    hps, model, params, eng = cond_setup
    with pytest.raises(ValueError, match="need z"):
        eng.run([Request(key=jax.random.key(0), z=None)])
    with pytest.raises(ValueError, match="exceed"):
        eng.run([_req(0, hps.z_size, cap=hps.max_seq_len + 1)])
    with pytest.raises(ValueError, match=">= 1"):
        ServeEngine(model, hps, params, slots=-1)  # 0 = hps default


def test_empty_request_list(cond_setup):
    hps, model, params, eng = cond_setup
    out = eng.run([])
    assert out["results"] == [] and out["metrics"]["completed"] == 0


def test_metrics_writer_rows(cond_setup, tmp_path):
    from sketch_rnn_tpu.train.metrics import MetricsWriter

    hps, model, params, eng = cond_setup
    reqs = [_req(i, hps.z_size, cap=4) for i in range(3)]
    eng.run(reqs, metrics_writer=MetricsWriter(str(tmp_path),
                                               name="serve"))
    import json
    lines = [json.loads(line) for line in
             open(tmp_path / "serve_metrics.jsonl")]
    assert len(lines) == 3
    assert {"uid", "steps", "length", "queue_wait_s", "decode_s",
            "latency_s"} <= set(lines[0])


def test_pool_pad_is_bitwise_invisible(cond_setup):
    """Fleet micro-bursts pad the request pool to a fixed size so every
    burst shares one compiled program; pad rows are inert and must not
    change any request's strokes (or be admitted as work)."""
    hps, model, params, eng = cond_setup
    reqs = [_req(i, hps.z_size, cap=3 + (5 * i) % 14) for i in range(5)]
    ref = _by_uid(eng.run([_clone(r) for r in reqs]))
    padded = _by_uid(eng.run([_clone(r) for r in reqs], pool_pad=12))
    assert len(padded) == 5
    for uid, r in ref.items():
        np.testing.assert_array_equal(padded[uid].strokes5, r.strokes5)


def test_enqueue_ts_backdates_latency_only(cond_setup):
    """A fleet-stamped arrival instant moves the latency clock's zero,
    never the strokes; unset, Results are bitwise as before (the
    satellite's keep-Result-fields-unchanged contract)."""
    import time

    hps, model, params, eng = cond_setup
    req = _req(0, hps.z_size, cap=8)
    ref = eng.run([_clone(req)])["results"][0]
    early = dataclasses.replace(_clone(req),
                                enqueue_ts=time.perf_counter() - 5.0)
    back = eng.run([early])["results"][0]
    np.testing.assert_array_equal(back.strokes5, ref.strokes5)
    assert back.queue_wait_s >= 5.0 and back.latency_s >= 5.0
    assert ref.queue_wait_s < 5.0


def test_placement_invariance_across_replicas_and_arrival_order():
    """ISSUE 9 acceptance invariant, extending the solo/batch/
    mid-flight suite: the same seeded request set produces
    bitwise-identical strokes at 1, 2 and 4 fleet replicas and under
    shuffled arrival order — replica placement is provably invisible
    to outputs."""
    from sketch_rnn_tpu.serve import ServeFleet

    hps = tiny_hps(serve_slots=2, serve_chunk=2)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    reqs = [_req(i, hps.z_size, cap=3 + (5 * i) % 9) for i in range(10)]
    # reference: the plain single engine (no fleet, no pool padding)
    eng = ServeEngine(model, hps, params)
    ref = _by_uid(eng.run([dataclasses.replace(r, uid=i)
                           for i, r in enumerate(reqs)]))

    def run_fleet(replicas, order=None):
        fleet = ServeFleet(model, hps, params, replicas=replicas)
        try:
            for i in (order if order is not None
                      else range(len(reqs))):
                fleet.submit(dataclasses.replace(reqs[i], uid=i))
            fleet.start()
            assert fleet.drain(timeout=120)
            return fleet.results
        finally:
            fleet.close()

    for replicas in (1, 2, 4):
        got = run_fleet(replicas)
        assert len(got) == len(reqs)
        replicas_used = {rec["replica"] for rec in got.values()}
        if replicas > 1:
            assert len(replicas_used) > 1  # really spread across devices
        for uid, r in ref.items():
            np.testing.assert_array_equal(
                got[uid]["result"].strokes5, r.strokes5,
                err_msg=f"uid {uid} diverged at {replicas} replicas")
    # shuffled arrival order on 2 replicas
    order = list(range(len(reqs)))
    np.random.default_rng(3).shuffle(order)
    got = run_fleet(2, order=order)
    for uid, r in ref.items():
        np.testing.assert_array_equal(
            got[uid]["result"].strokes5, r.strokes5,
            err_msg=f"uid {uid} diverged under shuffled arrival")


def test_complete_events_carry_admission_metadata():
    """ISSUE 9 satellite: fleet-served requests' telemetry complete
    events explain why they waited — class, fleet queue position,
    replica id — and the per-replica occupancy gauges + per-class
    latency histograms exist; Result latency fields stay the engine's
    exact floats."""
    from sketch_rnn_tpu.serve import ServeFleet
    from sketch_rnn_tpu.serve.admission import parse_admission_classes
    from sketch_rnn_tpu.utils import telemetry as tele

    hps = tiny_hps(serve_slots=2, serve_chunk=2)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    reqs = [_req(i, hps.z_size, cap=4) for i in range(6)]
    classes = parse_admission_classes(["interactive:p95<=5",
                                       "batch:p99<=30"])
    fleet = ServeFleet(model, hps, params, replicas=2, classes=classes)
    fleet.warm(reqs[0])
    tel = tele.configure(trace_dir=None)
    try:
        for i, r in enumerate(reqs):
            fleet.submit(dataclasses.replace(r, uid=i),
                         cls=("interactive", "batch")[i % 2])
        fleet.start()
        assert fleet.drain(timeout=120)
        results = fleet.results
        completes = [ev for ev in tel.events()
                     if ev["type"] == "instant"
                     and ev["name"] == "complete"]
        assert len(completes) == 6
        for ev in completes:
            args = ev["args"]
            assert args["class"] in ("interactive", "batch")
            assert args["replica"] in (0, 1)
            assert args["queue_pos"] >= 0
            # the event's floats ARE the Result's floats
            res = results[args["uid"]]["result"]
            assert args["latency_s"] == res.latency_s
            assert args["queue_wait_s"] == res.queue_wait_s
        counters = tel.counters()
        assert counters[("serve", "requests_admitted")] == 6
        gauge_names = {name for cat, name in counters
                       if cat == "serve" and name.startswith("slots_live")}
        assert {"slots_live_r00", "slots_live_r01"} <= gauge_names
        assert tel.histogram("latency_s_interactive",
                             cat="serve")["count"] == 3
    finally:
        fleet.close()
        tele.disable()


@pytest.mark.parametrize("dec", ["layer_norm", "hyper"])
def test_other_decoder_cells(dec):
    """The chunk program runs every decoder cell type (the carry pytree
    shape differs per cell — hyper nests the aux LSTM's)."""
    hps = tiny_hps(dec_model=dec, serve_slots=2, serve_chunk=3)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    reqs = [_req(i, hps.z_size, cap=5) for i in range(3)]
    out = generate_many(model, params, hps, reqs)
    assert out["metrics"]["completed"] == 3
    solo = generate_many(model, params, hps,
                         [_req(0, hps.z_size, cap=5)])
    np.testing.assert_array_equal(
        solo["results"][0].strokes5,
        {r.uid: r for r in out["results"]}[0].strokes5)


# -- cost attribution + critical-path tracing (ISSUE 11) ----------------------


def test_step_attribution_identity_and_determinism(cond_setup):
    """Per-request device-step cost is pure scheduling math: attributed
    + idle == dispatched EXACTLY (integers), the per-uid split is
    identical across repeat runs, and tracing on/off cannot change it
    (the invisibility contract extended to the new Result field)."""
    from sketch_rnn_tpu.utils import telemetry as tele

    hps, model, params, eng = cond_setup
    reqs = [_req(i, hps.z_size, cap=3 + (5 * i) % 14) for i in range(6)]

    def split(out):
        return {r.uid: r.attributed_steps for r in out["results"]}

    out1 = eng.run([_clone(r) for r in reqs])
    m = out1["metrics"]
    assert m["steps_attributed"] + m["steps_idle"] == m["device_steps"]
    assert sum(split(out1).values()) == m["steps_attributed"]
    # integer shares: a short request stuck in high slot indices can
    # legitimately round to 0 (chunk < n_live), but the run attributes
    assert m["steps_attributed"] > 0
    assert all(v >= 0 for v in split(out1).values())

    # repeatable: the same request list reproduces the exact split
    out2 = eng.run([_clone(r) for r in reqs])
    assert split(out2) == split(out1)
    assert out2["metrics"]["steps_attributed"] == m["steps_attributed"]

    # tracing-on run: identical split AND identical strokes
    tel = tele.configure(trace_dir=None)
    try:
        out3 = eng.run([_clone(r) for r in reqs])
    finally:
        tele.disable()
    assert split(out3) == split(out1)
    for a, b in zip(out1["results"], out3["results"]):
        np.testing.assert_array_equal(a.strokes5, b.strokes5)

    # and the run-level tail verdict is present either way
    assert out1["metrics"]["tail"]["dom"] in ("queue", "decode")


def test_complete_events_carry_exact_segments_and_cost(cond_setup):
    """Every traced complete event carries the critical-path segments
    (in-order float sum == latency_s BITWISE), the request's exact
    attributed_steps, and the run's cost counters close the
    attributed + idle == dispatched identity."""
    from sketch_rnn_tpu.utils import telemetry as tele

    hps, model, params, eng = cond_setup
    reqs = [_req(i, hps.z_size, cap=3 + (5 * i) % 14) for i in range(5)]
    tel = tele.configure(trace_dir=None)
    try:
        out = eng.run([_clone(r) for r in reqs])
        events = tel.events()
        counters = tel.counters()
    finally:
        tele.disable()
    by_uid = _by_uid(out)
    completes = [e for e in events if e.get("name") == "complete"]
    assert len(completes) == 5
    for ev in completes:
        args = ev["args"]
        res = by_uid[args["uid"]]
        total = 0.0
        for _, v in args["segments"]:
            total += v
        assert total == res.latency_s          # BITWISE
        assert args["attributed_steps"] == res.attributed_steps
        # causal stamp: complete hangs under the request root
        assert ev["trace"]["id"] == f"req-{args['uid']}"
        assert ev["trace"]["parent"] == f"request-{args['uid']}"
    m = out["metrics"]
    assert counters[("serve", "device_steps_attributed")] == \
        m["steps_attributed"]
    assert counters[("serve", "device_steps_dispatched")] == \
        m["device_steps"]
    assert counters[("serve", "device_steps_idle")] == m["steps_idle"]
    # per-request root/queue/decode spans exist for every uid
    for uid in by_uid:
        names = {e["name"] for e in events
                 if e.get("trace", {}).get("id") == f"req-{uid}"}
        assert {"enqueue", "admit", "request", "queue_wait",
                "decode", "complete"} <= names


# -- traffic shaping (ISSUE 12): trace replay, autoscaler, elasticity --------


def test_trace_replay_deterministic_in_seed():
    """ISSUE 12 acceptance: the same trace seed produces the IDENTICAL
    arrival schedule and repetition mapping, for every trace kind."""
    from sketch_rnn_tpu.serve import TraceSpec, make_trace

    for kind in ("poisson", "diurnal", "flash", "pareto"):
        spec = TraceSpec(kind=kind, n=128, rate_hz=200.0, seed=11,
                         unique=32)
        a, b = make_trace(spec), make_trace(spec)
        np.testing.assert_array_equal(a.arrivals, b.arrivals)
        np.testing.assert_array_equal(a.request_ids, b.request_ids)
        assert np.all(np.diff(a.arrivals) >= 0), kind
        assert a.request_ids.max() < 32 and a.request_ids.min() >= 0
        assert a.distinct() == len(np.unique(a.request_ids))
        other = make_trace(dataclasses.replace(spec, seed=12))
        assert not np.array_equal(a.arrivals, other.arrivals), kind
    # unique=0 (or >= n) means all-distinct: a cache sees zero repeats
    t = make_trace(TraceSpec(kind="poisson", n=16, rate_hz=50.0,
                             seed=0, unique=0))
    np.testing.assert_array_equal(t.request_ids, np.arange(16))


def test_trace_spec_validation():
    from sketch_rnn_tpu.serve import TraceSpec

    with pytest.raises(ValueError, match="unknown trace kind"):
        TraceSpec(kind="nope")
    with pytest.raises(ValueError, match="rate_hz"):
        TraceSpec(rate_hz=0.0)
    with pytest.raises(ValueError, match="diurnal_amp"):
        TraceSpec(kind="diurnal", diurnal_amp=1.5)
    with pytest.raises(ValueError, match="flash_mult"):
        TraceSpec(kind="flash", flash_mult=0.5)


def test_autoscaler_rule_up_cooldown_down():
    """The error-budget ladder: hot -> up, refractory cooldown, a
    quiet streak -> down, bounds always respected."""
    from sketch_rnn_tpu.serve import (AutoscalePolicy, Autoscaler,
                                      AutoscaleSignals)

    pol = AutoscalePolicy(min_replicas=1, max_replicas=3, up_wait_s=1.0,
                          down_wait_s=0.2, down_epochs=2,
                          cooldown_epochs=1)
    sc = Autoscaler(pol)
    hot = AutoscaleSignals(est_wait_s=5.0)
    quiet = AutoscaleSignals(est_wait_s=0.01)
    assert (sc.decide(hot).action, sc.replicas) == ("up", 2)
    # cooldown holds even under heat
    assert sc.decide(hot).action == "hold"
    assert sc.decide(hot).action == "up" and sc.replicas == 3
    # at max: hot can only hold
    sc.decide(hot)  # cooldown
    assert sc.decide(hot).action == "hold" and sc.replicas == 3
    # two quiet epochs retire one step
    assert sc.decide(quiet).action == "hold"
    d = sc.decide(quiet)
    assert d.action == "down" and d.target == 2
    # burn rate alone also triggers scale-up
    sc2 = Autoscaler(AutoscalePolicy(max_replicas=2, up_burn=1.0,
                                     cooldown_epochs=0))
    assert sc2.decide(AutoscaleSignals(est_wait_s=None,
                                       burn_rate=2.0)).action == "up"
    # a cold fleet (no signals at all) never scales — in EITHER
    # direction: a scaled-up fleet with est_wait=None (no service
    # estimate yet) must not count the signal gap as quiet and retire
    # capacity on zero evidence
    sc3 = Autoscaler(AutoscalePolicy(max_replicas=2))
    assert sc3.decide(AutoscaleSignals()).action == "hold"
    sc4 = Autoscaler(AutoscalePolicy(max_replicas=3, down_epochs=1,
                                     cooldown_epochs=0), replicas=3)
    for _ in range(5):
        assert sc4.decide(AutoscaleSignals()).action == "hold"
    assert sc4.replicas == 3
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscalePolicy(min_replicas=3, max_replicas=2)


def test_scale_plan_reproducible_from_trace_seed():
    """ISSUE 12 acceptance: the whole decision sequence is a pure
    function of (trace seed, policy) — two independent realizations
    agree decision-for-decision, and the fluid simulator's shed masks
    and modeled waits are bitwise too."""
    from sketch_rnn_tpu.serve import (AutoscalePolicy, TraceSpec,
                                      make_trace, plan_decisions,
                                      simulate_traffic)

    spec = TraceSpec(kind="flash", n=96, rate_hz=150.0, seed=5,
                     flash_at_s=0.1, flash_dur_s=0.2, flash_mult=6.0,
                     unique=24)
    pol = AutoscalePolicy(max_replicas=4, up_wait_s=0.1,
                          down_wait_s=0.03, epoch_s=0.04,
                          rate_hint_steps_per_s=900.0)
    work = np.full(24, 6.0)
    runs = []
    for _ in range(2):
        tr = make_trace(spec)
        plan = plan_decisions(tr.arrivals, work[tr.request_ids], pol)
        sim = simulate_traffic(tr.arrivals, tr.request_ids, work, pol,
                               cache=False, autoscale=True,
                               shed_wait_s=0.2)
        runs.append((plan, sim))
    (p1, s1), (p2, s2) = runs
    assert p1 == p2
    assert s1["decisions"] == s2["decisions"]
    np.testing.assert_array_equal(s1["admitted"], s2["admitted"])
    np.testing.assert_array_equal(s1["wait_s"], s2["wait_s"])
    assert any(d.action == "up" for d in p1)  # the flash actually bit
    # the autoscaled arm sheds strictly less than the fixed fleet
    fixed = simulate_traffic(make_trace(spec).arrivals,
                             make_trace(spec).request_ids, work, pol,
                             cache=False, autoscale=False,
                             shed_wait_s=0.2)
    assert fixed["shed_frac"] > s1["shed_frac"]
    # and a cache arm saves device steps deterministically
    cached = simulate_traffic(make_trace(spec).arrivals,
                              make_trace(spec).request_ids, work, pol,
                              cache=True, autoscale=False,
                              shed_wait_s=0.2)
    assert cached["device_steps"] < fixed["device_steps"]
    assert cached["hit_frac"] > 0


def test_strokes_bitwise_independent_of_midrun_resizes():
    """ISSUE 12 acceptance pin, extending the placement-invariance
    suite: a fleet that spawns and retires replicas MID-RUN still
    produces bitwise-identical strokes — elasticity changes WHERE a
    request runs, never WHAT it returns. Also pins the scale_log
    lifecycle record and the health surface's `scaling` phase."""
    from sketch_rnn_tpu.serve import ServeFleet
    from sketch_rnn_tpu.serve.metrics_http import health_payload
    from sketch_rnn_tpu.utils.telemetry import get_telemetry

    hps = tiny_hps(serve_slots=2, serve_chunk=2)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    reqs = [_req(i, hps.z_size, cap=3 + (5 * i) % 9) for i in range(12)]
    eng = ServeEngine(model, hps, params)
    ref = _by_uid(eng.run([dataclasses.replace(r, uid=i)
                           for i, r in enumerate(reqs)]))

    fleet = ServeFleet(model, hps, params, replicas=1, max_replicas=3)
    try:
        assert fleet.n_live == 1 and fleet.n_replicas == 3
        fleet.start()
        for i in range(4):
            fleet.submit(dataclasses.replace(reqs[i], uid=i))
        fleet.add_replica(reason="test")
        fleet.add_replica(reason="test")
        assert fleet.n_live == 3
        for i in range(4, 8):
            fleet.submit(dataclasses.replace(reqs[i], uid=i))
        assert fleet.drain(timeout=120)
        fleet.retire_replica(reason="test")
        assert fleet.n_live == 2
        for i in range(8, 12):
            fleet.submit(dataclasses.replace(reqs[i], uid=i))
        assert fleet.drain(timeout=120)
        s = fleet.summary()
        got = fleet.results
        health = fleet.health()
    finally:
        fleet.close()
    assert s["completed"] == 12
    for uid, r in ref.items():
        np.testing.assert_array_equal(
            got[uid]["result"].strokes5, r.strokes5,
            err_msg=f"uid {uid} diverged under mid-run resizes")
    # the lifecycle record: every action landed, n_live tracked
    assert [(e["action"], e["n_live"]) for e in s["scale_log"]] == [
        ("spawn", 2), ("spawn", 3), ("retire", 2)]
    assert s["replicas_live"] == 2 and s["replicas_retired"] == 1
    # a drained retire is done scaling: /healthz is ok, not degraded
    assert health["healthy"] and not health["scaling"]
    assert health_payload(get_telemetry(), None,
                          lambda: health)["status"] == "ok"
    # an in-flight resize reports `scaling` (not ok/degraded flapping)
    mid = dict(health, scaling=True)
    assert health_payload(get_telemetry(), None,
                          lambda: mid)["status"] == "scaling"


def test_elastic_lifecycle_guards():
    """add/retire validation: no headroom -> actionable error; the
    last live replica is irremovable; set_target clamps to what was
    built."""
    from sketch_rnn_tpu.serve import ServeFleet

    hps = tiny_hps(serve_slots=2, serve_chunk=2)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    fleet = ServeFleet(model, hps, params, replicas=1, max_replicas=2)
    try:
        with pytest.raises(RuntimeError, match="last live replica"):
            fleet.retire_replica()
        fleet.add_replica()
        with pytest.raises(RuntimeError, match="no retired replica"):
            fleet.add_replica()
        # set_target walks and clamps; scale_log records each action
        actions = fleet.set_target_replicas(99)
        assert actions == [] and fleet.n_live == 2
        actions = fleet.set_target_replicas(1)
        assert [a["action"] for a in actions] == ["retire"]
        assert fleet.n_live == 1
    finally:
        fleet.close()
    with pytest.raises(RuntimeError, match="closed"):
        fleet.add_replica()


def test_fleet_signals_extracts_live_measurements():
    """The live integration path: fleet_signals pulls the WORST tracked
    SLO's window burn (infinite burns capped so the controller still
    acts) plus admission's least-loaded estimated wait into the same
    signal shape the deterministic planner feeds."""
    import math

    from sketch_rnn_tpu.serve import fleet_signals
    from sketch_rnn_tpu.serve.admission import (AdmissionController,
                                                parse_admission_classes)
    from sketch_rnn_tpu.serve.slo import SLOTracker, parse_slo

    classes = parse_admission_classes(["interactive:p95<=0.5"])
    adm = AdmissionController(classes, n_replicas=2, slots=2)
    # cold: no completions -> est_wait is None, burn 0 on an empty SLO
    trk = SLOTracker([parse_slo("interactive:latency_s:p50<=0.1")])
    sig = fleet_signals(trk, adm, n_live=2)
    assert sig.est_wait_s is None and sig.burn_rate == 0.0
    assert sig.backlog == 0 and sig.n_live == 2
    # load + a calibrated estimate: least-loaded wait, summed backlog
    for _ in range(4):
        adm.place("interactive")
    adm.note_done(0, decode_s=0.2)     # replica 0: backlog 1, r1: 2
    sig = fleet_signals(trk, adm, n_live=2)
    assert sig.backlog == 3
    assert sig.est_wait_s == pytest.approx(min(
        adm.est_wait_s(0), adm.est_wait_s(1)))
    # breaches: the worst SLO's window burn feeds through
    for lat in (0.2, 0.3, 0.4, 0.5):
        trk.observe("interactive", {"latency_s": lat})
    worst = max(rec["burn_rate"] for rec in trk.summary().values())
    assert math.isfinite(worst)
    assert fleet_signals(trk, adm, n_live=2).burn_rate == worst
    # an infinite burn (p100-style zero budget) is capped, not NaN'd
    trk2 = SLOTracker([parse_slo("interactive:latency_s:p100<=0.1")])
    trk2.observe("interactive", {"latency_s": 0.5})
    assert fleet_signals(trk2, adm, n_live=1).burn_rate == 1e9
    # retired replicas are excluded from the wait signal entirely
    adm.retire(1)
    assert fleet_signals(None, adm, n_live=1).est_wait_s == \
        pytest.approx(adm.est_wait_s(0))
