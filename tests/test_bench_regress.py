"""bench_regress.py tests (ISSUE 7): the perf-claim gate.

The gate's job is an exit code a driver can trust, so the pins are
behavioral: regressions under the cell's own noise band exit 1,
in-band noise exits 0, thin/new cells never gate, and the --smoke
self-check stays green against the COMMITTED smoke history (the
tier-1 wiring the satellite asks for — if a future bench round
commits an out-of-band tail row, this test is the tripwire).
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts import bench_regress

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _train_row(v, **kw):
    return {"kind": "train", "dec_model": "lstm", "batch_size": 4096,
            "seq_len": 250, "dtype": "bfloat16", "fused_rnn": True,
            "resid_dtype": "bfloat16", "steps_per_call": 5,
            "transfer_dtype": "int16", "steps": 25, "device_kind": "v5e",
            "strokes_per_sec_per_chip": v, **kw}


def _write(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return str(path)


def test_in_band_fresh_passes_and_regression_fails(tmp_path, capsys):
    hist = _write(tmp_path / "hist.jsonl",
                  [_train_row(v) for v in (100.0, 104.0, 98.0, 101.0)])
    ok = _write(tmp_path / "ok.jsonl", [_train_row(97.0)])
    bad = _write(tmp_path / "bad.jsonl", [_train_row(50.0)])

    assert bench_regress.main(
        ["--fresh", ok, "--history", hist]) == 0
    out = capsys.readouterr().out
    assert "ok" in out and "REGRESS" not in out

    assert bench_regress.main(
        ["--fresh", bad, "--history", hist, "--json"]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep["regressions"] == 1
    (row,) = rep["rows"]
    assert row["verdict"] == "REGRESS" and row["fresh"] == 50.0
    # band: history spread (98/104 ~ 6%) floored at 10%, slack 5%:
    # floor = 104 * 0.9 * 0.95
    assert row["floor"] == pytest.approx(104 * 0.9 * 0.95)


def test_record_and_band_from_noisy_history(tmp_path, capsys):
    # noisy cell: spread 50% -> the band widens to the observed spread
    hist = _write(tmp_path / "h.jsonl",
                  [_train_row(v) for v in (200.0, 100.0, 180.0)])
    fresh = _write(tmp_path / "f.jsonl", [_train_row(110.0)])
    assert bench_regress.main(
        ["--fresh", fresh, "--history", hist, "--json"]) == 0
    (row,) = json.loads(capsys.readouterr().out)["rows"]
    assert row["verdict"] == "ok" and row["band"] == 0.5

    rec = _write(tmp_path / "r.jsonl", [_train_row(250.0)])
    assert bench_regress.main(
        ["--fresh", rec, "--history", hist, "--json"]) == 0
    (row,) = json.loads(capsys.readouterr().out)["rows"]
    assert row["verdict"] == "record"


def test_thin_and_new_cells_never_gate(tmp_path, capsys):
    hist = _write(tmp_path / "h.jsonl", [_train_row(100.0)])
    fresh = _write(tmp_path / "f.jsonl", [
        _train_row(1.0),                       # thin: 1 prior row
        {**_train_row(1.0), "dec_model": "hyper"},  # new: no history
    ])
    assert bench_regress.main(
        ["--fresh", fresh, "--history", hist, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    verdicts = sorted(r["verdict"] for r in rep["rows"])
    assert verdicts == ["new", "thin"]


def test_implausible_and_unavailable_rows_excluded(tmp_path, capsys):
    hist = _write(tmp_path / "h.jsonl", [
        _train_row(100.0), _train_row(101.0), _train_row(99.0),
        # a slow-window record must not lower the band's floor, and an
        # outage marker must not judge at all
        _train_row(10.0, plausible=False),
        {"kind": "unavailable", "dec_model": "lstm"},
    ])
    fresh = _write(tmp_path / "f.jsonl", [
        _train_row(80.0),
        _train_row(1.0, plausible=False),      # not judged
    ])
    assert bench_regress.main(
        ["--fresh", fresh, "--history", hist, "--json"]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert len(rep["rows"]) == 1               # implausible fresh skipped
    assert rep["rows"][0]["n_hist"] == 3       # implausible hist skipped
    assert rep["rows"][0]["verdict"] == "REGRESS"


def test_serve_and_bucket_rows_gate_on_their_headline(tmp_path, capsys):
    serve = {"kind": "serve_bench", "dec_model": "lstm", "slots": 8,
             "chunk": 8, "n_requests": 48, "len_dist": "bimodal",
             "device_kind": "cpu"}
    bucket = {"kind": "bucket_bench", "dec_model": "lstm",
              "batch_size": 32, "max_seq_len": 128,
              "bucket_edges": [16, 32], "device_kind": "cpu"}
    hist = _write(tmp_path / "h.jsonl", [
        {**serve, "engine_sketches_per_sec": v} for v in (300, 320, 310)
    ] + [
        {**bucket, "speedup_steps_per_sec": v} for v in (3.0, 3.2, 3.1)
    ])
    fresh = _write(tmp_path / "f.jsonl", [
        {**serve, "engine_sketches_per_sec": 305.0},
        {**bucket, "speedup_steps_per_sec": 1.1},
    ])
    assert bench_regress.main(
        ["--fresh", fresh, "--history", hist, "--json"]) == 1
    rep = json.loads(capsys.readouterr().out)
    by_kind = {r["key"][0]: r for r in rep["rows"]}
    assert by_kind["serve"]["verdict"] == "ok"
    assert by_kind["bucket"]["verdict"] == "REGRESS"


def test_usage_errors_are_one_liners(tmp_path, capsys):
    assert bench_regress.main([]) == 2
    assert "--fresh" in capsys.readouterr().err
    assert bench_regress.main(
        ["--fresh", str(tmp_path / "missing.jsonl")]) == 2
    assert "not found" in capsys.readouterr().err
    empty = _write(tmp_path / "empty.jsonl", [])
    assert bench_regress.main(["--fresh", empty]) == 2
    assert "no gateable rows" in capsys.readouterr().err


def test_smoke_self_check_against_committed_history(capsys):
    """THE tier-1 wiring: the committed smoke history's tail rows sit
    inside their own cells' noise bands. A future round that commits a
    regressed tail row fails here — the perf claim becomes checkable
    at test time, with no bench run needed."""
    assert os.path.exists(os.path.join(ROOT, "BENCH_SMOKE_HISTORY.jsonl"))
    assert bench_regress.main(["--smoke"]) == 0
    out = capsys.readouterr().out
    assert "0 regression(s)" in out
    assert "cell(s) judged" in out


def test_smoke_streamed_log_with_echo_lines_tolerated(tmp_path, capsys):
    """Driver-captured stdout (streamed rows + '# ' echoes + chatter)
    judges the same as a clean history file."""
    log = tmp_path / "captured.log"
    with open(log, "w") as f:
        f.write("# bench starting\n")
        f.write("# " + json.dumps(_train_row(100.0)) + "\n")
        f.write(json.dumps(_train_row(102.0)) + "\n")
        f.write('{"metric": "train_strokes_per_sec_per_chip", '
                '"value": 102.0}\n')   # summary line: no kind, skipped
        f.write('{"torn...\n')
    hist = _write(tmp_path / "h.jsonl",
                  [_train_row(v) for v in (100.0, 101.0, 99.0)])
    assert bench_regress.main(
        ["--fresh", str(log), "--history", hist, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert len(rep["rows"]) == 2
    assert sorted(r["verdict"] for r in rep["rows"]) == ["ok", "record"]


def test_fleet_rows_gate_per_replica_and_rate_cell(tmp_path, capsys):
    """ISSUE 9 satellite: serve_fleet rows gate on their realized
    sketches/sec, keyed by (replicas, offered rate) — a fresh
    2-replica regression fires against the 2-replica history while the
    1-replica cell of the same round stays ok."""
    base = {"kind": "serve_fleet", "dec_model": "lstm", "slots": 32,
            "chunk": 8, "n_requests": 512, "len_dist": "bimodal",
            "device_kind": "cpu", "offered_rate": 0.0}
    hist = _write(tmp_path / "h.jsonl", [
        {**base, "replicas": 2, "sketches_per_sec": v}
        for v in (360.0, 380.0, 370.0)
    ] + [
        {**base, "replicas": 1, "sketches_per_sec": v}
        for v in (250.0, 260.0, 255.0)
    ])
    fresh = _write(tmp_path / "f.jsonl", [
        {**base, "replicas": 2, "sketches_per_sec": 150.0},
        {**base, "replicas": 1, "sketches_per_sec": 252.0},
    ])
    assert bench_regress.main(
        ["--fresh", fresh, "--history", hist, "--json"]) == 1
    rep = json.loads(capsys.readouterr().out)
    by_r = {r["key"][2].split()[0]: r for r in rep["rows"]}
    assert by_r["R=2"]["verdict"] == "REGRESS"
    assert by_r["R=1"]["verdict"] == "ok"


def _cost_row(ok, replicas=2, **kw):
    return {"kind": "serve_cost", "dec_model": "lstm", "slots": 32,
            "chunk": 8, "n_requests": 512, "len_dist": "bimodal",
            "device_kind": "cpu", "smoke": True, "replicas": replicas,
            "ok": ok, "steps_by_class": {"batch": 900,
                                         "interactive": 700},
            "steps_attributed": 1600, "steps_idle": 32,
            "steps_dispatched": 1632 if ok else 1700,
            "p99_dom": "queue", "p99_dom_frac": 0.8, **kw}


def test_serve_cost_rows_gate_binary_exactness(tmp_path, capsys):
    """ISSUE 11 satellite: the per-class cost-attribution cells gate
    like the resilience cells — binary ok metric keyed per replica
    count, any fresh exactness miss is a REGRESS, and a RECORDED miss
    never poisons the baseline (the band would blow to 1.0 and disable
    the gate forever)."""
    from scripts.bench_summary import key_of, metric_of

    assert metric_of(_cost_row(True)) == 1.0
    assert metric_of(_cost_row(False)) == 0.0
    assert key_of(_cost_row(True)) == key_of(_cost_row(False))
    assert key_of(_cost_row(True)) != key_of(_cost_row(True,
                                                       replicas=1))
    # serve_cost cells never pool with the fleet throughput cells
    assert key_of(_cost_row(True))[0] == "servecost"

    hist = _write(tmp_path / "h.jsonl",
                  [_cost_row(True) for _ in range(4)])
    ok_fresh = _write(tmp_path / "ok.jsonl", [_cost_row(True)])
    bad_fresh = _write(tmp_path / "bad.jsonl", [_cost_row(False)])
    assert bench_regress.main(
        ["--fresh", ok_fresh, "--history", hist]) == 0
    capsys.readouterr()
    assert bench_regress.main(
        ["--fresh", bad_fresh, "--history", hist]) == 1
    assert "REGRESS" in capsys.readouterr().out
    # a recorded failure is evidence, not a baseline
    poisoned = _write(tmp_path / "p.jsonl",
                      [_cost_row(True) for _ in range(4)]
                      + [_cost_row(False)])
    assert bench_regress.main(
        ["--fresh", bad_fresh, "--history", poisoned]) == 1
    capsys.readouterr()


def _tenant_row(ok, tenant="tn0", n_tenants=4, **kw):
    return {"kind": "serve_tenant", "dec_model": "lstm", "slots": 4,
            "chunk": 2, "n_requests": 48, "n_tenants": n_tenants,
            "device_kind": "cpu", "smoke": True,
            "tenant": tenant, "ckpt_id": f"seed0+{tenant}",
            "adapter_pages": 2, "adapter_bytes": 709,
            "completed": 10 if ok else 7, "shed": 0,
            "bitwise_isolated": ok, "ok": ok, **kw}


def _prefix_row(ok, **kw):
    return {"kind": "serve_prefix", "dec_model": "lstm", "slots": 4,
            "chunk": 2, "n_requests": 48, "n_tenants": 4,
            "device_kind": "cpu", "smoke": True, "encode_jobs": 37,
            "computes": 26, "reuses": 11 if ok else 0,
            "distinct": 26, "predicted_distinct": 26 if ok else 30,
            "tenant_swaps": 41, "window_compiles": 0 if ok else 3,
            "ok": ok, **kw}


def test_tenant_and_prefix_rows_gate_binary(tmp_path, capsys):
    """ISSUE 19 satellite: the multi-tenant cells gate like the other
    binary kinds — serve_tenant keyed per (tenant, fleet shape),
    serve_prefix one cell per fleet run, any fresh isolation/ledger
    miss is a REGRESS, and a recorded miss never poisons the
    baseline."""
    from scripts.bench_summary import key_of, metric_of

    for row in (_tenant_row, _prefix_row):
        assert metric_of(row(True)) == 1.0
        assert metric_of(row(False)) == 0.0
        assert key_of(row(True)) == key_of(row(False))
    assert key_of(_tenant_row(True))[0] == "servetenant"
    assert key_of(_prefix_row(True))[0] == "serveprefix"
    # one cell per tenant, and tenant cells never pool across fleet
    # shapes (a different tenant count is a different paging workload)
    assert key_of(_tenant_row(True)) != key_of(
        _tenant_row(True, tenant="tn1"))
    assert key_of(_tenant_row(True)) != key_of(
        _tenant_row(True, n_tenants=2))
    assert key_of(_tenant_row(True)) != key_of(_prefix_row(True))

    hist = _write(tmp_path / "h.jsonl",
                  [_tenant_row(True) for _ in range(4)]
                  + [_prefix_row(True) for _ in range(4)])
    ok_fresh = _write(tmp_path / "ok.jsonl",
                      [_tenant_row(True), _prefix_row(True)])
    bad_fresh = _write(tmp_path / "bad.jsonl", [_prefix_row(False)])
    assert bench_regress.main(
        ["--fresh", ok_fresh, "--history", hist]) == 0
    capsys.readouterr()
    assert bench_regress.main(
        ["--fresh", bad_fresh, "--history", hist]) == 1
    assert "REGRESS" in capsys.readouterr().out
    # a recorded isolation failure is evidence, not a baseline
    poisoned = _write(tmp_path / "p.jsonl",
                      [_prefix_row(True) for _ in range(4)]
                      + [_prefix_row(False)])
    assert bench_regress.main(
        ["--fresh", bad_fresh, "--history", poisoned]) == 1
