"""Length-bucketed execution (ISSUE 4): loader plan, dispatch, parity.

Covers the tentpole's contracts:

- seeded bucketed-loader determinism (same seed -> identical bucket
  sequence and batch contents) and exactly-once-per-epoch coverage,
- buckets-off (``bucket_edges=()``) is bit-for-bit the pre-bucketing
  feed AND training path — ``next_batch`` IS ``random_batch`` and a
  ``train()`` run equals a replica of the pre-PR loop (random_batch +
  single jitted step + the loop's key discipline) leaf-for-leaf,
- per-bucket compiled-step routing: one executable per (B, Tb)
  geometry in the jitted step's shape-keyed cache,
- masked eval is bitwise independent of bucketing, through the real
  eval step and the full ``evaluate`` sweep (incl. the chunked
  multi-eval path, which must break scan chunks at geometry changes),
- the guards: multi-host striping, steps_per_call > 1, stacked
  prefetch, config validation.
"""

import numpy as np
import pytest

import jax

from sketch_rnn_tpu.config import HParams, get_default_hparams
from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
from sketch_rnn_tpu.utils.profiling import PaddingLedger


def small_hps(**kw):
    base = dict(batch_size=8, max_seq_len=96, enc_rnn_size=16,
                dec_rnn_size=24, z_size=8, num_mixture=3,
                transfer_dtype="float32", eval_steps_per_call=1)
    base.update(kw)
    return get_default_hparams().replace(**base)


def corpus(n=60, seed=3, max_len=90):
    return make_synthetic_strokes(n, num_classes=2, min_len=4,
                                  max_len=max_len, seed=seed)


def make_loader_sorted(hps, n=60, seed=5, max_len=90):
    """Loader over a length-SORTED corpus: consecutive eval batches then
    hold same-length-scale rows, so eval bucketing actually engages."""
    seqs, labels = corpus(n, max_len=max_len)
    order = np.argsort([len(s) for s in seqs], kind="stable")
    return DataLoader([seqs[i].copy() for i in order], hps,
                      labels=labels[order], seed=seed)


@pytest.fixture
def bucket_hps():
    return small_hps(bucket_edges=(16, 32, 64))


def make_loader(hps, n=60, seed=5, max_len=90, **kw):
    seqs, labels = corpus(n, max_len=max_len)
    return DataLoader([s.copy() for s in seqs], hps, labels=labels,
                      seed=seed, **kw)


# -- plan / loader contracts ----------------------------------------------


def test_bucketed_plan_covers_every_sequence_exactly_once(bucket_hps):
    dl = make_loader(bucket_hps, n=83)
    plan = dl._plan_bucket_epoch(0)
    assert len(plan) == -(-83 // bucket_hps.batch_size)
    seen = []
    for tb, idx, w in plan:
        assert tb in dl.bucket_edges
        assert len(idx) == bucket_hps.batch_size
        # every row fits its batch's bucket edge
        assert dl._lengths[idx].max() <= tb
        seen.extend(idx.tolist() if w is None else idx[w > 0].tolist())
    # weight-1 rows are exactly the corpus, once each
    assert sorted(seen) == list(range(83))


def test_bucketed_plan_epochs_differ_but_both_cover(bucket_hps):
    dl = make_loader(bucket_hps, n=40)
    p0, p1 = dl._plan_bucket_epoch(0), dl._plan_bucket_epoch(1)
    flat = lambda p: [i for _, idx, w in p
                      for i in (idx.tolist() if w is None
                                else idx[w > 0].tolist())]
    assert sorted(flat(p0)) == sorted(flat(p1)) == list(range(40))
    assert flat(p0) != flat(p1)  # fresh permutation per epoch


def test_bucketed_stream_deterministic_across_loaders(bucket_hps):
    a = make_loader(bucket_hps, seed=5)
    b = make_loader(bucket_hps, seed=5)
    for _ in range(14):  # crosses an epoch boundary (8 batches/epoch)
        ba, bb = a.next_batch(), b.next_batch()
        assert ba["strokes"].shape == bb["strokes"].shape
        np.testing.assert_array_equal(ba["strokes"], bb["strokes"])
        np.testing.assert_array_equal(ba["seq_len"], bb["seq_len"])
        np.testing.assert_array_equal(ba["labels"], bb["labels"])
        assert ("weights" in ba) == ("weights" in bb)
    # and a different seed plans a different stream
    c, a2 = make_loader(bucket_hps, seed=6), make_loader(bucket_hps,
                                                         seed=5)
    diff = False
    for _ in range(8):
        x, y = c.next_batch(), a2.next_batch()
        if (x["strokes"].shape != y["strokes"].shape
                or not np.array_equal(x["strokes"], y["strokes"])):
            diff = True
            break
    assert diff


def test_bucketed_batches_pad_to_edges_only(bucket_hps):
    dl = make_loader(bucket_hps)
    for _ in range(10):
        b = dl.next_batch()
        tb = b["strokes"].shape[1] - 1
        assert tb in dl.bucket_edges
        assert b["seq_len"].max() <= tb
        # start token intact at the bucketed pad
        np.testing.assert_array_equal(
            b["strokes"][:, 0, :],
            np.tile([0, 0, 1, 0, 0], (bucket_hps.batch_size, 1)))


def test_windowed_shuffle_semantics(bucket_hps):
    """window=1 is the degenerate no-shuffle (emit in formation order);
    a window >= n is a full permutation; every window preserves the
    multiset. The plan's batch order must actually depend on the
    window (the anti-length-curriculum knob does something)."""
    from sketch_rnn_tpu.data.loader import _windowed_shuffle

    rng = np.random.default_rng(0)
    items = list(range(40))
    assert _windowed_shuffle(items, 1, rng) == items
    full = _windowed_shuffle(items, 1000, np.random.default_rng(1))
    assert sorted(full) == items and full != items
    small = _windowed_shuffle(items, 4, np.random.default_rng(2))
    assert sorted(small) == items
    # an item can travel at most (window - 1) positions EARLIER
    assert all(pos >= i - 3 for pos, i in
               ((small.index(i), i) for i in items))

    h1 = bucket_hps.replace(bucket_shuffle_window=1)
    dl = make_loader(h1, n=80)
    ordered = [tb for tb, _, _ in dl._plan_bucket_epoch(0)]
    dl2 = make_loader(bucket_hps, n=80)  # default window 256: full shuffle
    shuffled = [tb for tb, _, _ in dl2._plan_bucket_epoch(0)]
    assert sorted(ordered) == sorted(shuffled)
    assert ordered != shuffled


def test_buckets_off_next_batch_is_random_batch():
    hps = small_hps()
    a = make_loader(hps, seed=9)
    b = make_loader(hps, seed=9)
    for _ in range(5):
        x, y = a.next_batch(), b.random_batch()
        np.testing.assert_array_equal(x["strokes"], y["strokes"])
        np.testing.assert_array_equal(x["seq_len"], y["seq_len"])
        assert "weights" not in x


def test_buckets_off_prefetch_stream_unchanged():
    """The feeder path (prefetch_batches -> next_batch) must be
    bit-for-bit the pre-bucketing random_batch stream."""
    from sketch_rnn_tpu.data.prefetch import prefetch_batches

    hps = small_hps()
    a = make_loader(hps, seed=11)
    b = make_loader(hps, seed=11)
    feeder = prefetch_batches(a, mesh=None, depth=2)
    try:
        for _ in range(4):
            x, y = feeder.get(), b.random_batch()
            np.testing.assert_array_equal(np.asarray(x["strokes"]),
                                          y["strokes"])
    finally:
        feeder.close()


def test_bucketed_loader_rejects_uncoordinated_host_striping():
    """ISSUE 14 narrows the old single-host-only guard: bucketing on a
    LEGACY striped loader (each host planning its own schedule) still
    refuses — pointing at the coordinated global plan, which is the
    mode that lifts it (tests/test_elastic.py pins that path)."""
    seqs, labels = corpus(30)
    with pytest.raises(RuntimeError, match="coordinated"):
        DataLoader(seqs[0::2], small_hps(bucket_edges=(32, 64)),
                   labels=labels[0::2], global_size=30, num_hosts=2)


def test_prefetch_stack_feeds_bucketed_runs():
    """ISSUE 5: stacked prefetch over a bucketed loader is no longer
    refused — each get() is one geometry-run prefix ``[k, B, Tb+1, 5]``
    with k <= stack, and the concatenated micro-batch stream equals the
    plain next_batch stream of an identically-seeded loader."""
    from sketch_rnn_tpu.data.prefetch import prefetch_batches

    hps = small_hps(bucket_edges=(16, 32, 64))
    a = make_loader(hps, seed=21)
    b = make_loader(hps, seed=21)
    feeder = prefetch_batches(a, mesh=None, depth=2, stack=4)
    micro = 0
    try:
        while micro < 12:
            stk = feeder.get()
            k = stk["strokes"].shape[0]
            assert 1 <= k <= 4
            assert stk["strokes"].ndim == 4  # [k, B, Tb+1, 5]
            for i in range(k):
                ref = b.next_batch()
                np.testing.assert_array_equal(
                    np.asarray(stk["strokes"][i]), ref["strokes"])
                np.testing.assert_array_equal(
                    np.asarray(stk["seq_len"][i]), ref["seq_len"])
                assert ("weights" in stk) == ("weights" in ref)
                micro += 1
    finally:
        feeder.close()


def test_config_validates_bucket_edges():
    for bad in ((0, 16), (32, 16), (16, 16), (16, 200)):
        with pytest.raises(ValueError):
            small_hps(bucket_edges=bad)
    # ISSUE 5: bucketing + steps_per_call=K is now a supported
    # combination (the bucket-run scheduler), not a config error
    assert small_hps(bucket_edges=(16, 32),
                     steps_per_call=4).steps_per_call == 4
    with pytest.raises(ValueError, match="bucket_shuffle_window"):
        small_hps(bucket_shuffle_window=0)
    with pytest.raises(ValueError, match="bucket_run_len"):
        small_hps(bucket_run_len=-1)
    # terminal edge implied: loader appends max_seq_len
    dl = make_loader(small_hps(bucket_edges=(16, 32)))
    assert dl.bucket_edges == (16, 32, 96)
    # edges ending AT max_seq_len are kept as-is
    dl2 = make_loader(small_hps(bucket_edges=(16, 96)))
    assert dl2.bucket_edges == (16, 96)


def test_hparams_parse_bucket_edges_coerces_ints():
    hps = get_default_hparams().parse("bucket_edges=64;128;250")
    assert hps.bucket_edges == (64, 128, 250)
    # round-trips through json too
    assert HParams.from_json(hps.to_json()).bucket_edges == (64, 128, 250)
    # and mesh_axes (string tuple) coercion is untouched
    assert get_default_hparams().parse(
        "mesh_axes=data").mesh_axes == ("data",)


def test_padding_ledger_math():
    led = PaddingLedger((16, 64))
    first = led.window()
    assert set(first) == {"padded_frac", "bucket_T16_n", "bucket_T64_n",
                          "runs_per_epoch", "mean_run_len",
                          "dispatches_saved"}
    led.record(16, 8, 100)        # 128 dispatched, 100 true
    led.record(64, 8, 256)        # 512 dispatched, 256 true
    win = led.window()
    assert win["bucket_T16_n"] == 1 and win["bucket_T64_n"] == 1
    assert win["padded_frac"] == pytest.approx(1 - 356 / 640, abs=1e-6)
    # window is incremental; summary is cumulative
    assert led.window()["padded_frac"] == 0.0
    led.record(16, 8, 128)        # zero waste
    assert led.window()["padded_frac"] == 0.0
    s = led.summary()
    assert s["dispatched_timesteps"] == 768 and s["true_timesteps"] == 484
    assert s["bucket_T16_n"] == 2


def test_padding_ledger_dispatch_amortization_columns():
    """ISSUE 5: plan-level run structure + realized dispatch savings.

    ``note_epoch_plan`` pins runs_per_epoch/mean_run_len to the latest
    plan; ``record_dispatch`` accrues micro-steps vs dispatches, and
    ``dispatches_saved`` windows like the padding counters."""
    led = PaddingLedger((16, 64))
    w0 = led.window()
    assert w0["runs_per_epoch"] == 0 and w0["mean_run_len"] == 0.0
    assert w0["dispatches_saved"] == 0
    led.note_epoch_plan(5, 12)
    led.record_dispatch(4, 1)   # one full K=4 stack
    led.record_dispatch(3, 3)   # a run-remainder replay
    win = led.window()
    assert win["runs_per_epoch"] == 5
    assert win["mean_run_len"] == pytest.approx(12 / 5, abs=1e-3)
    assert win["dispatches_saved"] == 3
    # windowed: the next window starts at zero saved
    assert led.window()["dispatches_saved"] == 0
    s = led.summary()
    assert s["micro_steps"] == 7 and s["dispatches"] == 4
    assert s["dispatches_saved"] == 3


# -- compiled-step routing / training -------------------------------------


def test_train_step_compiles_one_executable_per_geometry(bucket_hps):
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train import make_train_state
    from sketch_rnn_tpu.train.step import (batch_geometry,
                                           geometry_cache_size,
                                           make_train_step)

    dl = make_loader(bucket_hps)
    model = SketchRNN(bucket_hps)
    state = make_train_state(model, bucket_hps, jax.random.key(0))
    step = make_train_step(model, bucket_hps, mesh=None)
    key = jax.random.key(1)
    seen = {}
    for i in range(10):
        batch = dl.next_batch()
        geom = batch_geometry(batch) + ("weights" in batch,)
        state, metrics = step(state, batch, jax.random.fold_in(key, i))
        seen[geom] = seen.get(geom, 0) + 1
        assert np.isfinite(float(metrics["loss"]))
    assert len(seen) >= 2  # the skewed corpus fills >1 bucket
    cache = geometry_cache_size(step)
    if cache is not None:
        # one executable per distinct geometry — NOT one per step
        assert cache == len(seen)


def test_weighted_tail_batch_trains_under_mesh():
    """The epoch tail's zero-weighted wrap rows must flow through the
    sharded step (weights shard over the data axis like every leaf)."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.parallel.mesh import make_mesh
    from sketch_rnn_tpu.train import make_train_state
    from sketch_rnn_tpu.train.step import make_train_step

    hps = small_hps(bucket_edges=(16, 32, 64))
    dl = make_loader(hps, n=60)
    tail = next(b for b in (dl.next_batch() for _ in range(16))
                if "weights" in b)
    assert tail["weights"].sum() < hps.batch_size
    model = SketchRNN(hps)
    mesh = make_mesh(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh)
    state, metrics = step(state, tail, jax.random.key(1))
    assert np.isfinite(float(metrics["loss"]))


def test_buckets_off_train_bitwise_matches_pre_bucketing_replica():
    """Tier-1 parity: a buckets-off ``train()`` run must be bitwise
    identical to the pre-PR loop — replicated here as random_batch +
    the single jitted step + the loop's exact key discipline (root key
    split for init, fold_in(root, step) per step)."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train import make_train_state
    from sketch_rnn_tpu.train.loop import train
    from sketch_rnn_tpu.train.step import make_train_step

    hps = small_hps(num_steps=4, log_every=2, eval_every=10 ** 9,
                    save_every=10 ** 9, prefetch_depth=2)
    state = train(hps, make_loader(hps, seed=7), workdir=None,
                  use_mesh=False, seed=3)

    model = SketchRNN(hps)
    root = jax.random.key(3)
    root, init_key = jax.random.split(root)
    replica = make_train_state(model, hps, init_key)
    step_fn = make_train_step(model, hps, mesh=None)
    dl = make_loader(hps, seed=7)
    for step in range(4):
        replica, _ = step_fn(replica, dl.random_batch(),
                             jax.random.fold_in(root, step))
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(replica.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bucketed_train_loop_logs_padding_columns(tmp_path):
    import json
    import os

    from sketch_rnn_tpu.train.loop import train

    hps = small_hps(bucket_edges=(16, 32), max_seq_len=64, num_steps=4,
                    log_every=2, eval_every=10 ** 9, save_every=10 ** 9)
    dl = make_loader(hps, n=40, max_len=60)
    train(hps, dl, workdir=str(tmp_path), use_mesh=False, seed=1)
    rows = [json.loads(l) for l in
            open(os.path.join(tmp_path, "train_metrics.jsonl"))]
    for col in ("padded_frac", "bucket_T16_n", "bucket_T32_n",
                "bucket_T64_n", "runs_per_epoch", "mean_run_len",
                "dispatches_saved"):
        assert all(col in r for r in rows), col
    assert any(r["padded_frac"] > 0 for r in rows)
    assert all(r["runs_per_epoch"] > 0 for r in rows)
    # the CSV header carries the bucket columns from row one
    header = open(os.path.join(tmp_path,
                               "train_metrics.csv")).readline()
    assert "bucket_T16_n" in header and "padded_frac" in header


# -- eval parity -----------------------------------------------------------


def test_masked_eval_sweep_bitwise_independent_of_bucketing():
    """Tier-1 acceptance: bucketing never changes masked eval loss —
    the full evaluate() sweep over bucket-padded batches equals the
    fixed-T sweep EXACTLY, metric for metric."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train.loop import evaluate
    from sketch_rnn_tpu.train.step import make_eval_step

    hps = small_hps()
    hb = hps.replace(bucket_edges=(16, 32, 64))
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    eval_step = make_eval_step(model, hps, mesh=None)
    rf = evaluate(params, make_loader(hps, n=40), eval_step,
                  key=jax.random.key(5))
    rb = evaluate(params, make_loader(hb, n=40), eval_step,
                  key=jax.random.key(5))
    assert set(rf) == set(rb)
    for k in rf:
        assert rf[k] == rb[k], (k, rf[k], rb[k])


def test_bucketed_eval_batches_use_bucket_pads():
    hps = small_hps(bucket_edges=(16, 32, 64))
    dl = make_loader_sorted(hps, n=40)
    pads = set()
    for i in range(dl.num_eval_batches):
        b = dl.get_batch(i)
        tb = b["strokes"].shape[1] - 1
        assert tb == dl.eval_pad_len(i)
        assert tb in dl.bucket_edges
        assert b["seq_len"].max() <= tb
        pads.add(tb)
    # the corpus actually exercises short pads, not just the terminal one
    assert min(pads) < hps.max_seq_len


# -- bucket-run scheduler (ISSUE 5) ----------------------------------------


def test_bucket_plan_independent_of_steps_per_call_and_pure():
    """The epoch plan must be a pure function of (seed, epoch): equal
    across loader instances, across repeated planning calls, and across
    hps that differ ONLY in steps_per_call (K never reaches the plan)."""
    h1 = small_hps(bucket_edges=(16, 32, 64))
    h4 = h1.replace(steps_per_call=4)
    h8 = h1.replace(steps_per_call=8)
    plans = [make_loader(h, n=83, seed=5)._plan_bucket_epoch(2)
             for h in (h1, h1, h4, h8)]
    ref = plans[0]
    for p in plans[1:]:
        assert len(p) == len(ref)
        for (tb_a, idx_a, w_a), (tb_b, idx_b, w_b) in zip(p, ref):
            assert tb_a == tb_b
            np.testing.assert_array_equal(idx_a, idx_b)
            assert (w_a is None) == (w_b is None)
    # ...and a different epoch plans a different order (same coverage)
    other = make_loader(h1, n=83, seed=5)._plan_bucket_epoch(3)
    assert [tb for tb, _, _ in other] != [tb for tb, _, _ in ref] or any(
        not np.array_equal(a[1], b[1]) for a, b in zip(other, ref))


@pytest.mark.parametrize("k_max", [1, 3, 4, 8])
def test_next_stack_stream_equals_next_batch_stream(k_max):
    """The stacked stream is micro-batch-for-micro-batch the next_batch
    stream at every K — so coverage (every example exactly once per
    epoch) holds at all K because it holds for next_batch; stacks never
    mix geometries and never cross a weighted/unweighted boundary."""
    hps = small_hps(bucket_edges=(16, 32, 64))
    a = make_loader(hps, n=83, seed=13)
    b = make_loader(hps, n=83, seed=13)
    micro = 0
    while micro < 26:  # crosses an epoch refill (11 batches/epoch)
        stk = a.next_stack(k_max)
        k = stk["strokes"].shape[0]
        assert 1 <= k <= k_max
        tb = stk["strokes"].shape[2] - 1
        assert tb in a.bucket_edges  # one geometry per stack
        for i in range(k):
            ref = b.next_batch()
            np.testing.assert_array_equal(stk["strokes"][i],
                                          ref["strokes"])
            np.testing.assert_array_equal(stk["seq_len"][i],
                                          ref["seq_len"])
            np.testing.assert_array_equal(stk["labels"][i], ref["labels"])
            assert ("weights" in stk) == ("weights" in ref)
            if "weights" in stk:
                np.testing.assert_array_equal(stk["weights"][i],
                                              ref["weights"])
            micro += 1


def test_next_stack_guards():
    dl = make_loader(small_hps())  # buckets off
    with pytest.raises(ValueError, match="next_stack"):
        dl.next_stack(4)
    dlb = make_loader(small_hps(bucket_edges=(16, 32)))
    with pytest.raises(ValueError, match="k_max"):
        dlb.next_stack(0)


def test_run_aware_shuffle_preserves_runs():
    """bucket_run_len > 0 shuffles runs as units: the plan holds
    consecutive same-geometry sequences ~run_len long (vs the per-batch
    shuffle, whose expected run length is ~1), with the same batch
    multiset either way."""
    base = small_hps(bucket_edges=(16, 32, 64))
    run_on = make_loader(base.replace(bucket_run_len=4), n=200, seed=3)
    run_off = make_loader(base.replace(bucket_run_len=0), n=200, seed=3)
    p_on, p_off = (dl._plan_bucket_epoch(0) for dl in (run_on, run_off))
    assert sorted(tb for tb, _, _ in p_on) == sorted(
        tb for tb, _, _ in p_off)
    runs_on = run_on._count_geometry_runs(p_on)
    runs_off = run_off._count_geometry_runs(p_off)
    assert len(p_on) == len(p_off)
    # run-aware plans have FEWER, longer runs
    assert runs_on < runs_off
    assert len(p_on) / runs_on >= 2.0


def test_multi_step_key_by_global_step_matches_k1_keys():
    """The scheduler's K-scan must be step-for-step RNG-identical to
    K single-step calls keyed fold_in(root, global_step) — the K=1
    loop's exact discipline (NOT the fixed-T fold_in(call_key, i))."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train import make_train_state
    from sketch_rnn_tpu.train.step import (make_multi_train_step,
                                           make_train_step)

    hps = small_hps(bucket_edges=(16, 32, 64), steps_per_call=3,
                    use_recurrent_dropout=True)
    model = SketchRNN(hps)
    dl = make_loader(hps, n=60, seed=5)
    # a full 3-stack of one geometry (the RNG-identity contract is about
    # keys, not data, so stacking three distinct same-bucket batches or
    # constructing one directly is equivalent; build it from the stream)
    parts = [dl.next_batch() for _ in range(8)]
    tmpl = next(p for p in parts if "weights" not in p)
    same = [tmpl] * 3
    stk = {k: np.stack([p[k] for p in same]) for k in same[0]}
    root = jax.random.key(11)

    s_multi = make_train_state(model, hps, jax.random.key(0))
    multi = make_multi_train_step(model, hps, mesh=None,
                                  key_by_global_step=True)
    s_multi, _ = multi(s_multi, stk, root)

    s_single = make_train_state(model, hps, jax.random.key(0))
    single = make_train_step(model, hps, mesh=None)
    for i in range(3):
        b = jax.tree_util.tree_map(lambda x: x[i], stk)
        s_single, _ = single(s_single, b, jax.random.fold_in(root, i))

    assert int(s_multi.step) == int(s_single.step) == 3
    for a, b in zip(jax.tree_util.tree_leaves(s_multi.params),
                    jax.tree_util.tree_leaves(s_single.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)


def test_dispatch_stack_replay_accumulates_grad_norm_max():
    """The shared scheduler contract (train.loop.dispatch_stack): a run
    remainder replayed per micro-step must report grad_norm_max as the
    MAX over the replayed micro-steps (the scan path's spike-surfacing
    guarantee), not the last micro-step's value."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train import make_train_state
    from sketch_rnn_tpu.train.loop import dispatch_stack
    from sketch_rnn_tpu.train.step import (make_multi_train_step,
                                           make_train_step)

    hps = small_hps(bucket_edges=(16, 32, 64), steps_per_call=4)
    model = SketchRNN(hps)
    dl = make_loader(hps, n=60, seed=5)
    tmpl = next(b for b in (dl.next_batch() for _ in range(8))
                if "weights" not in b)
    stk = {k: np.stack([v] * 2) for k, v in tmpl.items()}  # k=2 < K=4
    root = jax.random.key(9)
    single = make_train_step(model, hps, mesh=None)
    multi = make_multi_train_step(model, hps, mesh=None,
                                  key_by_global_step=True)

    state = make_train_state(model, hps, jax.random.key(0))
    state, metrics, use, n_disp = dispatch_stack(single, multi, state,
                                                 stk, 0, 10, root, 4)
    assert use == 2 and n_disp == 2 and int(state.step) == 2

    # replicate the two replayed micro-steps to get their metrics
    ref = make_train_state(model, hps, jax.random.key(0))
    norms, losses, lrs = [], [], []
    for i in range(2):
        b = jax.tree_util.tree_map(lambda x: x[i], stk)
        ref, m = single(ref, b, jax.random.fold_in(root, i))
        norms.append(float(m["grad_norm"]))
        losses.append(float(m["loss"]))
        lrs.append(float(m["lr"]))
    assert float(metrics["grad_norm_max"]) == pytest.approx(max(norms),
                                                            rel=1e-6)
    # scan-matching semantics: window MEAN, last schedule value
    assert float(metrics["grad_norm"]) == pytest.approx(
        np.mean(norms), rel=1e-6)
    assert float(metrics["loss"]) == pytest.approx(np.mean(losses),
                                                   rel=1e-6)
    assert float(metrics["lr"]) == pytest.approx(lrs[-1], rel=1e-6)

    # a full stack routes through the scan (one dispatch, K steps)
    full = {k: np.stack([v] * 4) for k, v in tmpl.items()}
    state2 = make_train_state(model, hps, jax.random.key(0))
    state2, m2, use2, n2 = dispatch_stack(single, multi, state2, full,
                                          0, 10, root, 4)
    assert use2 == 4 and n2 == 1 and int(state2.step) == 4
    assert "grad_norm_max" in m2
    # end-of-training truncation: remaining < k replays only remaining
    state3 = make_train_state(model, hps, jax.random.key(0))
    state3, _, use3, n3 = dispatch_stack(single, multi, state3, full,
                                         0, 3, root, 4)
    assert use3 == 3 and n3 == 3 and int(state3.step) == 3


def test_stacked_bucketed_train_matches_unstacked(tmp_path):
    """Tier-1 scheduler acceptance: train() with bucketing at K=4 is
    step-for-step RNG-identical to K=1 — same plan (K-independent),
    same per-step keys (fold_in(root, global_step) both ways, full
    stacks via the scan, run remainders via single-step replay) — so
    the final states agree to scan-reassociation tolerance and the
    logged metric VALUES are identical streams."""
    from sketch_rnn_tpu.train.loop import train

    h1 = small_hps(bucket_edges=(16, 32, 64), num_steps=13, log_every=4,
                   eval_every=10 ** 9, save_every=10 ** 9)
    h4 = h1.replace(steps_per_call=4)
    s1 = train(h1, make_loader(h1, seed=7), workdir=None,
               use_mesh=False, seed=3)
    s4 = train(h4, make_loader(h4, seed=7), workdir=None,
               use_mesh=False, seed=3)
    assert int(s1.step) == int(s4.step) == 13
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s4.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-6, atol=5e-6)


def test_stacked_bucketed_train_runs_under_mesh(tmp_path):
    """The composed mode — buckets + steps_per_call + mesh — must
    dispatch stacked [k, B, Tb+1, 5] geometry runs through shard_map
    (Tb replicated shape metadata, only B sharded) and log the
    dispatch-amortization columns."""
    import json
    import os

    from sketch_rnn_tpu.train.loop import train

    hps = small_hps(bucket_edges=(16, 32), max_seq_len=64,
                    steps_per_call=3, num_steps=9, log_every=3,
                    eval_every=10 ** 9, save_every=10 ** 9)
    dl = make_loader(hps, n=64, max_len=60)
    state = train(hps, dl, workdir=str(tmp_path), use_mesh=True, seed=1)
    assert int(state.step) == 9
    rows = [json.loads(l) for l in
            open(os.path.join(tmp_path, "train_metrics.jsonl"))]
    assert rows and all("dispatches_saved" in r
                        and "mean_run_len" in r for r in rows)


def test_stacked_bucketed_weighted_tail_replays(tmp_path):
    """A weighted wrap-tail batch forms its own (short) run, so it must
    reach the model via the remainder replay path mid-run without
    disturbing the stream — covered by driving enough steps to cross
    the epoch tail under K=4."""
    from sketch_rnn_tpu.train.loop import train

    hps = small_hps(bucket_edges=(16, 32, 64), steps_per_call=4,
                    num_steps=12, log_every=4, eval_every=10 ** 9,
                    save_every=10 ** 9)
    dl = make_loader(hps, n=60, seed=5)  # 8 batches/epoch incl. a tail
    state = train(hps, dl, workdir=None, use_mesh=False, seed=2)
    assert int(state.step) == 12


def test_multi_eval_chunks_break_at_geometry_changes():
    """The chunked (K-batch scan) eval path must group only
    same-geometry runs under bucketing and still agree with the
    per-batch sweep to scan-reassociation tolerance; with buckets off
    its chunk schedule is the pre-bucketing one."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train.loop import evaluate
    from sketch_rnn_tpu.train.step import (make_eval_step,
                                           make_multi_eval_step)

    hps = small_hps(bucket_edges=(16, 32, 64))
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    dl = make_loader_sorted(hps, n=48)
    # mixed geometries across the sweep, so chunking must split
    pads = [dl.eval_pad_len(i) for i in range(dl.num_eval_batches)]
    assert len(set(pads)) > 1
    eval_step = make_eval_step(model, hps, mesh=None)
    multi = (make_multi_eval_step(model, hps, mesh=None), 3)
    r1 = evaluate(params, dl, eval_step, key=jax.random.key(5))
    r2 = evaluate(params, dl, eval_step, key=jax.random.key(5),
                  multi=multi)
    for k in r1:
        assert r1[k] == pytest.approx(r2[k], rel=3e-5, abs=1e-6), k
