"""Length-bucketed execution (ISSUE 4): loader plan, dispatch, parity.

Covers the tentpole's contracts:

- seeded bucketed-loader determinism (same seed -> identical bucket
  sequence and batch contents) and exactly-once-per-epoch coverage,
- buckets-off (``bucket_edges=()``) is bit-for-bit the pre-bucketing
  feed AND training path — ``next_batch`` IS ``random_batch`` and a
  ``train()`` run equals a replica of the pre-PR loop (random_batch +
  single jitted step + the loop's key discipline) leaf-for-leaf,
- per-bucket compiled-step routing: one executable per (B, Tb)
  geometry in the jitted step's shape-keyed cache,
- masked eval is bitwise independent of bucketing, through the real
  eval step and the full ``evaluate`` sweep (incl. the chunked
  multi-eval path, which must break scan chunks at geometry changes),
- the guards: multi-host striping, steps_per_call > 1, stacked
  prefetch, config validation.
"""

import numpy as np
import pytest

import jax

from sketch_rnn_tpu.config import HParams, get_default_hparams
from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
from sketch_rnn_tpu.utils.profiling import PaddingLedger


def small_hps(**kw):
    base = dict(batch_size=8, max_seq_len=96, enc_rnn_size=16,
                dec_rnn_size=24, z_size=8, num_mixture=3,
                transfer_dtype="float32", eval_steps_per_call=1)
    base.update(kw)
    return get_default_hparams().replace(**base)


def corpus(n=60, seed=3, max_len=90):
    return make_synthetic_strokes(n, num_classes=2, min_len=4,
                                  max_len=max_len, seed=seed)


def make_loader_sorted(hps, n=60, seed=5, max_len=90):
    """Loader over a length-SORTED corpus: consecutive eval batches then
    hold same-length-scale rows, so eval bucketing actually engages."""
    seqs, labels = corpus(n, max_len=max_len)
    order = np.argsort([len(s) for s in seqs], kind="stable")
    return DataLoader([seqs[i].copy() for i in order], hps,
                      labels=labels[order], seed=seed)


@pytest.fixture
def bucket_hps():
    return small_hps(bucket_edges=(16, 32, 64))


def make_loader(hps, n=60, seed=5, max_len=90, **kw):
    seqs, labels = corpus(n, max_len=max_len)
    return DataLoader([s.copy() for s in seqs], hps, labels=labels,
                      seed=seed, **kw)


# -- plan / loader contracts ----------------------------------------------


def test_bucketed_plan_covers_every_sequence_exactly_once(bucket_hps):
    dl = make_loader(bucket_hps, n=83)
    plan = dl._plan_bucket_epoch(0)
    assert len(plan) == -(-83 // bucket_hps.batch_size)
    seen = []
    for tb, idx, w in plan:
        assert tb in dl.bucket_edges
        assert len(idx) == bucket_hps.batch_size
        # every row fits its batch's bucket edge
        assert dl._lengths[idx].max() <= tb
        seen.extend(idx.tolist() if w is None else idx[w > 0].tolist())
    # weight-1 rows are exactly the corpus, once each
    assert sorted(seen) == list(range(83))


def test_bucketed_plan_epochs_differ_but_both_cover(bucket_hps):
    dl = make_loader(bucket_hps, n=40)
    p0, p1 = dl._plan_bucket_epoch(0), dl._plan_bucket_epoch(1)
    flat = lambda p: [i for _, idx, w in p
                      for i in (idx.tolist() if w is None
                                else idx[w > 0].tolist())]
    assert sorted(flat(p0)) == sorted(flat(p1)) == list(range(40))
    assert flat(p0) != flat(p1)  # fresh permutation per epoch


def test_bucketed_stream_deterministic_across_loaders(bucket_hps):
    a = make_loader(bucket_hps, seed=5)
    b = make_loader(bucket_hps, seed=5)
    for _ in range(14):  # crosses an epoch boundary (8 batches/epoch)
        ba, bb = a.next_batch(), b.next_batch()
        assert ba["strokes"].shape == bb["strokes"].shape
        np.testing.assert_array_equal(ba["strokes"], bb["strokes"])
        np.testing.assert_array_equal(ba["seq_len"], bb["seq_len"])
        np.testing.assert_array_equal(ba["labels"], bb["labels"])
        assert ("weights" in ba) == ("weights" in bb)
    # and a different seed plans a different stream
    c, a2 = make_loader(bucket_hps, seed=6), make_loader(bucket_hps,
                                                         seed=5)
    diff = False
    for _ in range(8):
        x, y = c.next_batch(), a2.next_batch()
        if (x["strokes"].shape != y["strokes"].shape
                or not np.array_equal(x["strokes"], y["strokes"])):
            diff = True
            break
    assert diff


def test_bucketed_batches_pad_to_edges_only(bucket_hps):
    dl = make_loader(bucket_hps)
    for _ in range(10):
        b = dl.next_batch()
        tb = b["strokes"].shape[1] - 1
        assert tb in dl.bucket_edges
        assert b["seq_len"].max() <= tb
        # start token intact at the bucketed pad
        np.testing.assert_array_equal(
            b["strokes"][:, 0, :],
            np.tile([0, 0, 1, 0, 0], (bucket_hps.batch_size, 1)))


def test_windowed_shuffle_semantics(bucket_hps):
    """window=1 is the degenerate no-shuffle (emit in formation order);
    a window >= n is a full permutation; every window preserves the
    multiset. The plan's batch order must actually depend on the
    window (the anti-length-curriculum knob does something)."""
    from sketch_rnn_tpu.data.loader import _windowed_shuffle

    rng = np.random.default_rng(0)
    items = list(range(40))
    assert _windowed_shuffle(items, 1, rng) == items
    full = _windowed_shuffle(items, 1000, np.random.default_rng(1))
    assert sorted(full) == items and full != items
    small = _windowed_shuffle(items, 4, np.random.default_rng(2))
    assert sorted(small) == items
    # an item can travel at most (window - 1) positions EARLIER
    assert all(pos >= i - 3 for pos, i in
               ((small.index(i), i) for i in items))

    h1 = bucket_hps.replace(bucket_shuffle_window=1)
    dl = make_loader(h1, n=80)
    ordered = [tb for tb, _, _ in dl._plan_bucket_epoch(0)]
    dl2 = make_loader(bucket_hps, n=80)  # default window 256: full shuffle
    shuffled = [tb for tb, _, _ in dl2._plan_bucket_epoch(0)]
    assert sorted(ordered) == sorted(shuffled)
    assert ordered != shuffled


def test_buckets_off_next_batch_is_random_batch():
    hps = small_hps()
    a = make_loader(hps, seed=9)
    b = make_loader(hps, seed=9)
    for _ in range(5):
        x, y = a.next_batch(), b.random_batch()
        np.testing.assert_array_equal(x["strokes"], y["strokes"])
        np.testing.assert_array_equal(x["seq_len"], y["seq_len"])
        assert "weights" not in x


def test_buckets_off_prefetch_stream_unchanged():
    """The feeder path (prefetch_batches -> next_batch) must be
    bit-for-bit the pre-bucketing random_batch stream."""
    from sketch_rnn_tpu.data.prefetch import prefetch_batches

    hps = small_hps()
    a = make_loader(hps, seed=11)
    b = make_loader(hps, seed=11)
    feeder = prefetch_batches(a, mesh=None, depth=2)
    try:
        for _ in range(4):
            x, y = feeder.get(), b.random_batch()
            np.testing.assert_array_equal(np.asarray(x["strokes"]),
                                          y["strokes"])
    finally:
        feeder.close()


def test_bucketed_loader_rejects_host_striping():
    seqs, labels = corpus(30)
    with pytest.raises(RuntimeError, match="single-host"):
        DataLoader(seqs[0::2], small_hps(bucket_edges=(32, 64)),
                   labels=labels[0::2], global_size=30, num_hosts=2)


def test_prefetch_stack_rejects_bucketed_loader():
    from sketch_rnn_tpu.data.prefetch import prefetch_batches

    dl = make_loader(small_hps(bucket_edges=(32, 64)))
    with pytest.raises(ValueError, match="bucket"):
        prefetch_batches(dl, mesh=None, depth=0, stack=4)


def test_config_validates_bucket_edges():
    for bad in ((0, 16), (32, 16), (16, 16), (16, 200)):
        with pytest.raises(ValueError):
            small_hps(bucket_edges=bad)
    with pytest.raises(ValueError, match="steps_per_call"):
        small_hps(bucket_edges=(16, 32), steps_per_call=4)
    with pytest.raises(ValueError, match="bucket_shuffle_window"):
        small_hps(bucket_shuffle_window=0)
    # terminal edge implied: loader appends max_seq_len
    dl = make_loader(small_hps(bucket_edges=(16, 32)))
    assert dl.bucket_edges == (16, 32, 96)
    # edges ending AT max_seq_len are kept as-is
    dl2 = make_loader(small_hps(bucket_edges=(16, 96)))
    assert dl2.bucket_edges == (16, 96)


def test_hparams_parse_bucket_edges_coerces_ints():
    hps = get_default_hparams().parse("bucket_edges=64;128;250")
    assert hps.bucket_edges == (64, 128, 250)
    # round-trips through json too
    assert HParams.from_json(hps.to_json()).bucket_edges == (64, 128, 250)
    # and mesh_axes (string tuple) coercion is untouched
    assert get_default_hparams().parse(
        "mesh_axes=data").mesh_axes == ("data",)


def test_padding_ledger_math():
    led = PaddingLedger((16, 64))
    first = led.window()
    assert set(first) == {"padded_frac", "bucket_T16_n", "bucket_T64_n"}
    led.record(16, 8, 100)        # 128 dispatched, 100 true
    led.record(64, 8, 256)        # 512 dispatched, 256 true
    win = led.window()
    assert win["bucket_T16_n"] == 1 and win["bucket_T64_n"] == 1
    assert win["padded_frac"] == pytest.approx(1 - 356 / 640, abs=1e-6)
    # window is incremental; summary is cumulative
    assert led.window()["padded_frac"] == 0.0
    led.record(16, 8, 128)        # zero waste
    assert led.window()["padded_frac"] == 0.0
    s = led.summary()
    assert s["dispatched_timesteps"] == 768 and s["true_timesteps"] == 484
    assert s["bucket_T16_n"] == 2


# -- compiled-step routing / training -------------------------------------


def test_train_step_compiles_one_executable_per_geometry(bucket_hps):
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train import make_train_state
    from sketch_rnn_tpu.train.step import (batch_geometry,
                                           geometry_cache_size,
                                           make_train_step)

    dl = make_loader(bucket_hps)
    model = SketchRNN(bucket_hps)
    state = make_train_state(model, bucket_hps, jax.random.key(0))
    step = make_train_step(model, bucket_hps, mesh=None)
    key = jax.random.key(1)
    seen = {}
    for i in range(10):
        batch = dl.next_batch()
        geom = batch_geometry(batch) + ("weights" in batch,)
        state, metrics = step(state, batch, jax.random.fold_in(key, i))
        seen[geom] = seen.get(geom, 0) + 1
        assert np.isfinite(float(metrics["loss"]))
    assert len(seen) >= 2  # the skewed corpus fills >1 bucket
    cache = geometry_cache_size(step)
    if cache is not None:
        # one executable per distinct geometry — NOT one per step
        assert cache == len(seen)


def test_weighted_tail_batch_trains_under_mesh():
    """The epoch tail's zero-weighted wrap rows must flow through the
    sharded step (weights shard over the data axis like every leaf)."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.parallel.mesh import make_mesh
    from sketch_rnn_tpu.train import make_train_state
    from sketch_rnn_tpu.train.step import make_train_step

    hps = small_hps(bucket_edges=(16, 32, 64))
    dl = make_loader(hps, n=60)
    tail = next(b for b in (dl.next_batch() for _ in range(16))
                if "weights" in b)
    assert tail["weights"].sum() < hps.batch_size
    model = SketchRNN(hps)
    mesh = make_mesh(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh)
    state, metrics = step(state, tail, jax.random.key(1))
    assert np.isfinite(float(metrics["loss"]))


def test_buckets_off_train_bitwise_matches_pre_bucketing_replica():
    """Tier-1 parity: a buckets-off ``train()`` run must be bitwise
    identical to the pre-PR loop — replicated here as random_batch +
    the single jitted step + the loop's exact key discipline (root key
    split for init, fold_in(root, step) per step)."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train import make_train_state
    from sketch_rnn_tpu.train.loop import train
    from sketch_rnn_tpu.train.step import make_train_step

    hps = small_hps(num_steps=4, log_every=2, eval_every=10 ** 9,
                    save_every=10 ** 9, prefetch_depth=2)
    state = train(hps, make_loader(hps, seed=7), workdir=None,
                  use_mesh=False, seed=3)

    model = SketchRNN(hps)
    root = jax.random.key(3)
    root, init_key = jax.random.split(root)
    replica = make_train_state(model, hps, init_key)
    step_fn = make_train_step(model, hps, mesh=None)
    dl = make_loader(hps, seed=7)
    for step in range(4):
        replica, _ = step_fn(replica, dl.random_batch(),
                             jax.random.fold_in(root, step))
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(replica.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bucketed_train_loop_logs_padding_columns(tmp_path):
    import json
    import os

    from sketch_rnn_tpu.train.loop import train

    hps = small_hps(bucket_edges=(16, 32), max_seq_len=64, num_steps=4,
                    log_every=2, eval_every=10 ** 9, save_every=10 ** 9)
    dl = make_loader(hps, n=40, max_len=60)
    train(hps, dl, workdir=str(tmp_path), use_mesh=False, seed=1)
    rows = [json.loads(l) for l in
            open(os.path.join(tmp_path, "train_metrics.jsonl"))]
    for col in ("padded_frac", "bucket_T16_n", "bucket_T32_n",
                "bucket_T64_n"):
        assert all(col in r for r in rows), col
    assert any(r["padded_frac"] > 0 for r in rows)
    # the CSV header carries the bucket columns from row one
    header = open(os.path.join(tmp_path,
                               "train_metrics.csv")).readline()
    assert "bucket_T16_n" in header and "padded_frac" in header


# -- eval parity -----------------------------------------------------------


def test_masked_eval_sweep_bitwise_independent_of_bucketing():
    """Tier-1 acceptance: bucketing never changes masked eval loss —
    the full evaluate() sweep over bucket-padded batches equals the
    fixed-T sweep EXACTLY, metric for metric."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train.loop import evaluate
    from sketch_rnn_tpu.train.step import make_eval_step

    hps = small_hps()
    hb = hps.replace(bucket_edges=(16, 32, 64))
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    eval_step = make_eval_step(model, hps, mesh=None)
    rf = evaluate(params, make_loader(hps, n=40), eval_step,
                  key=jax.random.key(5))
    rb = evaluate(params, make_loader(hb, n=40), eval_step,
                  key=jax.random.key(5))
    assert set(rf) == set(rb)
    for k in rf:
        assert rf[k] == rb[k], (k, rf[k], rb[k])


def test_bucketed_eval_batches_use_bucket_pads():
    hps = small_hps(bucket_edges=(16, 32, 64))
    dl = make_loader_sorted(hps, n=40)
    pads = set()
    for i in range(dl.num_eval_batches):
        b = dl.get_batch(i)
        tb = b["strokes"].shape[1] - 1
        assert tb == dl.eval_pad_len(i)
        assert tb in dl.bucket_edges
        assert b["seq_len"].max() <= tb
        pads.add(tb)
    # the corpus actually exercises short pads, not just the terminal one
    assert min(pads) < hps.max_seq_len


def test_multi_eval_chunks_break_at_geometry_changes():
    """The chunked (K-batch scan) eval path must group only
    same-geometry runs under bucketing and still agree with the
    per-batch sweep to scan-reassociation tolerance; with buckets off
    its chunk schedule is the pre-bucketing one."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train.loop import evaluate
    from sketch_rnn_tpu.train.step import (make_eval_step,
                                           make_multi_eval_step)

    hps = small_hps(bucket_edges=(16, 32, 64))
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    dl = make_loader_sorted(hps, n=48)
    # mixed geometries across the sweep, so chunking must split
    pads = [dl.eval_pad_len(i) for i in range(dl.num_eval_batches)]
    assert len(set(pads)) > 1
    eval_step = make_eval_step(model, hps, mesh=None)
    multi = (make_multi_eval_step(model, hps, mesh=None), 3)
    r1 = evaluate(params, dl, eval_step, key=jax.random.key(5))
    r2 = evaluate(params, dl, eval_step, key=jax.random.key(5),
                  multi=multi)
    for k in r1:
        assert r1[k] == pytest.approx(r2[k], rel=3e-5, abs=1e-6), k
