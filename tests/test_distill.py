"""Draft-decoder distillation tests (ISSUE 18 satellite).

``DistillModel`` must drive the UNCHANGED production train loop (the
loss contract: canonical metric keys, deterministic per batch, grads
into the draft tree only), ``distill()`` must leave a paired draft
checkpoint with its teacher lineage in RUN.json and resume like any
train run — and the artifact it writes must load straight into a
speculative serve engine whose output stays bitwise the legacy one
(a truncated-mixture draft head included).
"""

import os

import jax
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
from sketch_rnn_tpu.models.draft import DraftDecoder
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.train import (DistillModel, distill, draft_dir_of,
                                  latest_checkpoint, make_train_state,
                                  restore_checkpoint)
from sketch_rnn_tpu.utils import runinfo

TINY = dict(batch_size=8, max_seq_len=24, enc_rnn_size=12,
            dec_rnn_size=16, z_size=6, num_mixture=3, draft_rnn_size=8,
            draft_num_mixture=2, eval_every=10**9, save_every=2,
            log_every=2)

METRIC_KEYS = {"loss", "recon", "offset_nll", "pen_ce", "pen_distill",
               "kl", "kl_raw", "kl_weight"}


def _hps(**kw) -> HParams:
    return HParams(**{**TINY, **kw})


def _loader(hps, n=32, seed=0):
    seqs, labels = make_synthetic_strokes(
        n, num_classes=1, min_len=8, max_len=hps.max_seq_len - 2,
        seed=seed)
    return DataLoader(seqs, hps, labels=labels, seed=seed)


@pytest.fixture(scope="module")
def setup():
    hps = _hps()
    teacher = SketchRNN(hps)
    tparams = teacher.init_params(jax.random.key(0))
    return hps, teacher, tparams


def test_distill_loss_contract(setup):
    """Canonical train-loop metric keys (zero KL — the draft has no
    latent), a deterministic loss per batch, and gradients that are
    finite and land in every draft leaf."""
    hps, _, tparams = setup
    dm = DistillModel(hps, tparams)
    params = dm.init_params(jax.random.key(1))
    assert all(k.startswith("draft_") for k in params)
    batch = _loader(hps).get_batch(0)
    key = jax.random.key(2)
    jloss = jax.jit(lambda p: dm.loss(p, batch, key, kl_weight=0.5))
    loss1, m1 = jloss(params)
    loss2, m2 = jloss(params)
    assert set(m1) == METRIC_KEYS
    assert float(m1["kl"]) == float(m1["kl_raw"]) == 0.0
    assert float(m1["loss"]) == pytest.approx(
        float(m1["recon"]) + float(m1["pen_distill"]))
    # deterministic (the teacher conditions on its posterior MEAN z)
    assert float(loss1) == float(loss2)
    grads = jax.jit(
        jax.grad(lambda p: dm.loss(p, batch, key, 0.0)[0]))(params)
    for leaf in jax.tree_util.tree_leaves(grads):
        a = np.asarray(leaf)
        assert np.all(np.isfinite(a))
        assert np.any(a != 0.0)


def test_distill_end_to_end_lineage_resume_and_serving(setup, tmp_path):
    """``distill()`` through the real loop: draft checkpoints under
    <workdir>/draft, teacher lineage in that RUN.json, resume continues
    rather than restarts — and the distilled (truncated-head) draft
    loads into a speculative engine that stays bitwise the legacy
    engine."""
    hps, teacher, tparams = setup
    wd = str(tmp_path)
    loader = _loader(hps)
    state = distill(hps.replace(num_steps=2), tparams, loader, wd,
                    seed=3, teacher_ckpt_id="ckpt_00000002",
                    use_mesh=False)
    ddir = draft_dir_of(wd)
    assert ddir.startswith(wd)
    assert int(state.step) == 2
    assert latest_checkpoint(ddir) == 2
    man = runinfo.read_manifest(ddir)
    assert man["kind"] == "distill"
    lin = man["distill"]
    assert lin["teacher_ckpt_id"] == "ckpt_00000002"
    assert lin["teacher_workdir"] == os.path.abspath(wd)
    assert lin["draft_rnn_size"] == hps.draft_rnn_size
    assert lin["draft_num_mixture"] == 2
    assert lin["steps"] == 2
    # resume: two more steps continue from the saved draft state
    state2 = distill(hps.replace(num_steps=4), tparams, loader, wd,
                     seed=3, teacher_ckpt_id="ckpt_00000002",
                     use_mesh=False)
    assert int(state2.step) == 4
    assert runinfo.read_manifest(ddir)["distill"]["steps"] == 4
    # the checkpoint restores into the draft template (draft shapes,
    # draft_-prefixed keys — never confusable with the teacher's tree)
    template = make_train_state(DraftDecoder(hps), hps,
                                jax.random.key(0))
    rstate, _, _ = restore_checkpoint(ddir, template)
    assert int(rstate.step) == 4
    assert all(k.startswith("draft_") for k in rstate.params)
    for a, b in zip(jax.tree_util.tree_leaves(rstate.params),
                    jax.tree_util.tree_leaves(state2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and it serves: the distilled draft's engine is bitwise legacy
    from sketch_rnn_tpu.serve.engine import Request, ServeEngine

    reqs = lambda: [  # noqa: E731
        Request(key=jax.random.key(500 + i),
                z=np.asarray(jax.random.normal(jax.random.key(i),
                                               (hps.z_size,))),
                temperature=0.8, max_len=10, uid=i)
        for i in range(4)]
    legacy = ServeEngine(teacher, hps, tparams, slots=2, chunk=2)
    spec = ServeEngine(teacher, hps, tparams, slots=2, chunk=2,
                       draft_params=rstate.params, draft_depth=3)
    ref = {r.uid: r.strokes5 for r in legacy.run(reqs())["results"]}
    out = spec.run(reqs())
    got = {r.uid: r.strokes5 for r in out["results"]}
    for u in ref:
        np.testing.assert_array_equal(ref[u], got[u])
    assert out["metrics"]["speculative"]["draft_steps_proposed"] > 0
