"""goodput_bench tests (tier-1-safe: a shrunken smoke).

Wall-clock overheads are noise-prone on a shared CI box (and on CPU the
writer thread shares cores with the "device"), so the tier-1 regression
signal is the DETERMINISTIC part: the record shape, the save/row
accounting, and above all the PARITY block — sync vs overlapped through
the real train() must stay byte-identical in checkpoints and identical
in logged metric values. The timing acceptance (async within a few
percent of no-checkpoint baseline at an aggressive cadence) is the
full-config run's job on the real chip.
"""

import json

from scripts import goodput_bench


def test_goodput_bench_smoke_end_to_end(tmp_path):
    out = tmp_path / "GOODPUT.json"
    rc = goodput_bench.main([
        "--smoke", "--steps", "8", "--save_every", "2", "--log_every",
        "2", "--trials", "1", "--workdir", str(tmp_path / "scratch"),
        "--out", str(out)])
    assert rc == 0
    rec = json.load(open(out))
    assert rec["kind"] == "goodput_bench" and rec["smoke"] is True
    assert set(rec["configs"]) == {"baseline", "async_ckpt", "sync_ckpt",
                                   "eager_metrics", "sync_both"}
    for name, r in rec["configs"].items():
        assert r["wall_s"] > 0, name
        assert r["rows"] == 4, name  # 8 steps / log_every 2
        want_saves = 4 if "ckpt" in name or name == "sync_both" else 0
        assert r["saves"] == want_saves, name
    assert rec["configs"]["baseline"]["overhead_vs_baseline"] == 0.0
    # the semantics contract: every parity boolean true
    parity = rec["parity"]
    assert parity["final_step_equal"] is True
    assert parity["ckpt_bytes_equal"] is True
    assert parity["mid_ckpt_bytes_equal"] is True  # async-written file
    assert parity["state_bitwise_equal"] is True
    assert parity["metrics_identical"] is True
    assert parity["logged_rows"] > 0
