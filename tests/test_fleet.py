"""Fleet scheduler unit tests (ISSUE 9): admission classes, least-loaded
placement, shed-on-overload, the open-loop load generator, and fleet
lifecycle/shed behavior. The bitwise placement-invariance acceptance
suite lives in tests/test_serve.py (it extends the engine invariance
tests); everything here is the host-side scheduling layer, so most
tests never touch jax.
"""

import math
import threading
import time

import numpy as np
import pytest

from sketch_rnn_tpu.serve.admission import (
    DEFAULT_CLASS,
    AdmissionController,
    parse_admission_classes,
)
from sketch_rnn_tpu.serve.loadgen import (
    OpenLoopLoadGen,
    live_generators,
    poisson_arrivals,
)


# -- admission classes -------------------------------------------------------


def test_parse_admission_classes_grammar_and_priority():
    classes = parse_admission_classes(
        ["interactive:p95<=250ms", "batch:latency_s:p99<=2"])
    assert list(classes) == ["interactive", "batch"]
    inter = classes["interactive"]
    assert inter.deadline_s == 0.25 and inter.priority == 0
    assert inter.slo.target == 0.95
    assert classes["batch"].deadline_s == 2.0
    assert classes["batch"].priority == 1


def test_parse_admission_classes_default_and_errors():
    classes = parse_admission_classes([])
    assert list(classes) == [DEFAULT_CLASS]
    assert math.isinf(classes[DEFAULT_CLASS].deadline_s)
    with pytest.raises(ValueError, match="duplicate"):
        parse_admission_classes(["a:p95<=1", "a:p99<=2"])
    with pytest.raises(ValueError, match="bad SLO"):
        parse_admission_classes(["nope"])


# -- the admission controller ------------------------------------------------


def _controller(**kw):
    classes = parse_admission_classes(
        kw.pop("specs", ["interactive:p95<=0.5", "batch:p99<=10"]))
    return AdmissionController(classes, **{
        "n_replicas": 2, "slots": 4, **kw})


def test_least_loaded_placement_is_deterministic():
    c = _controller()
    placements = [c.place("batch").replica for _ in range(6)]
    # backlog-balanced, ties to the lowest index
    assert placements == [0, 1, 0, 1, 0, 1]
    assert c.backlog == [3, 3]
    # a completion frees replica 1 -> next arrival routes there
    c.note_done(1, decode_s=0.01)
    assert c.place("batch").replica == 1


def test_queue_pos_reports_requests_ahead():
    c = _controller()
    assert c.place("batch").queue_pos == 0
    assert c.place("batch").queue_pos == 0  # other replica
    assert c.place("batch").queue_pos == 1


def test_hard_queue_cap_sheds():
    c = _controller(queue_cap=2)
    for _ in range(4):
        assert not c.place("batch").shed
    p = c.place("batch")
    assert p.shed and p.shed_reason == "queue_full"
    assert c.shed_total == 1 and c.shed["batch"] == 1
    assert c.admitted == 4


def test_deadline_shed_needs_service_estimate():
    """A cold controller (no completions) must not shed on deadline —
    only the hard cap can refuse before the estimate is calibrated."""
    c = _controller()
    for _ in range(50):
        assert not c.place("interactive").shed
    # calibrate: 0.2s per request at 4 slots -> est wait for backlog 25
    # is 25 * 0.2 / 4 = 1.25s > the 0.5s interactive deadline
    c.note_done(0, decode_s=0.2)
    p = c.place("interactive")
    assert p.shed and p.shed_reason == "deadline"
    assert p.est_wait_s > 0.5
    # the lax batch deadline (10s) still admits
    assert not c.place("batch").shed


def test_note_done_detects_desync():
    c = _controller()
    with pytest.raises(RuntimeError, match="desync"):
        c.note_done(0, decode_s=0.1)


def test_controller_summary_shape():
    c = _controller()
    c.place("batch")
    s = c.summary()
    assert s["admitted"] == 1 and s["shed_total"] == 0
    assert s["classes"]["interactive"]["deadline_s"] == 0.5
    assert s["classes"]["interactive"]["priority"] == 0


# -- the open-loop load generator --------------------------------------------


def test_poisson_arrivals_deterministic_and_rate():
    a = poisson_arrivals(2000, 100.0, seed=7)
    b = poisson_arrivals(2000, 100.0, seed=7)
    np.testing.assert_array_equal(a, b)
    assert np.all(np.diff(a) >= 0)
    # mean inter-arrival ~ 1/rate
    assert 0.8 / 100 < np.diff(a).mean() < 1.2 / 100
    assert not np.array_equal(a, poisson_arrivals(2000, 100.0, seed=8))
    # closed burst: everything at t=0
    np.testing.assert_array_equal(poisson_arrivals(5, 0.0, seed=0),
                                  np.zeros(5))


def test_loadgen_replays_schedule_open_loop():
    got = []
    lock = threading.Lock()

    def submit(i):
        with lock:
            got.append(i)
        time.sleep(0.002)  # a "slow server" must not slow arrivals

    gen = OpenLoopLoadGen(poisson_arrivals(40, 2000.0, seed=0), submit)
    t0 = time.perf_counter()
    gen.start()
    assert gen.join(timeout=30)
    wall = time.perf_counter() - t0
    assert got == list(range(40))
    assert gen.submitted == 40
    # open-loop: 40 arrivals at 2000/s finish in ~20ms of schedule;
    # even with the sleeping submit the replay is schedule-paced (plus
    # submit time), nowhere near 40 * (sleep + gap) closed-loop pacing
    assert wall < 5.0
    assert gen.max_lag_s >= 0.0
    assert gen not in live_generators()


def test_loadgen_stop_abandons_remaining():
    gen = OpenLoopLoadGen([0.0, 60.0], lambda i: None).start()
    deadline = time.perf_counter() + 5
    while gen.submitted < 1 and time.perf_counter() < deadline:
        time.sleep(0.005)
    gen.stop()
    assert gen.submitted == 1
    assert gen not in live_generators()


def test_loadgen_rejects_unsorted_schedule():
    with pytest.raises(ValueError, match="non-decreasing"):
        OpenLoopLoadGen([1.0, 0.5], lambda i: None)


# -- fleet lifecycle (one tiny jax model) ------------------------------------


@pytest.fixture(scope="module")
def tiny_fleet_setup():
    import jax

    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.models.vae import SketchRNN

    hps = HParams(batch_size=8, max_seq_len=24, enc_rnn_size=12,
                  dec_rnn_size=16, z_size=6, num_mixture=3,
                  serve_slots=2, serve_chunk=2)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    return hps, model, params


def _req(i, z_dim, cap=4):
    import jax

    rng = np.random.default_rng(i)
    from sketch_rnn_tpu.serve import Request
    return Request(key=jax.random.key(1000 + i),
                   z=rng.standard_normal(z_dim).astype(np.float32),
                   temperature=0.8, max_len=cap, uid=i)


def test_fleet_sheds_on_queue_cap_and_counts(tiny_fleet_setup):
    from sketch_rnn_tpu.serve import ServeFleet
    from sketch_rnn_tpu.utils import telemetry as tele

    hps, model, params = tiny_fleet_setup
    fleet = ServeFleet(model, hps, params, replicas=1, queue_cap=3)
    tel = tele.configure(trace_dir=None)
    try:
        admitted = [fleet.submit(_req(i, hps.z_size)) for i in range(8)]
        # workers not started: backlog only grows, cap must bite
        assert admitted == [True] * 3 + [False] * 5
        fleet.start()
        assert fleet.drain(timeout=120)
        s = fleet.summary()
        assert s["completed"] == 3 and s["shed"] == 5
        assert s["shed_frac"] == round(5 / 8, 4)
        assert s["shed_by_class"] == {DEFAULT_CLASS: 5}
        assert {x["uid"] for x in fleet.shed} == {3, 4, 5, 6, 7}
        counters = tel.counters()
        assert counters[("serve", "requests_shed")] == 5
        assert counters[("serve", "requests_shed_default")] == 5
        assert counters[("serve", "requests_admitted")] == 3
    finally:
        fleet.close()
        tele.disable()


def test_fleet_reset_requires_idle_and_clears(tiny_fleet_setup):
    from sketch_rnn_tpu.serve import ServeFleet

    hps, model, params = tiny_fleet_setup
    fleet = ServeFleet(model, hps, params, replicas=1)
    try:
        fleet.submit(_req(0, hps.z_size))
        with pytest.raises(RuntimeError, match="queued work"):
            fleet.reset()
        fleet.start()
        assert fleet.drain(timeout=120)
        assert fleet.summary()["completed"] == 1
        fleet.reset()
        s = fleet.summary()
        assert s["completed"] == 0 and s["submitted"] == 0
        assert s["total_device_steps"] == 0
        # and it serves again after the reset
        fleet.submit(_req(1, hps.z_size))
        assert fleet.drain(timeout=120)
        assert fleet.summary()["completed"] == 1
    finally:
        fleet.close()


def test_fleet_validation_errors(tiny_fleet_setup):
    import jax

    from sketch_rnn_tpu.serve import ServeFleet

    hps, model, params = tiny_fleet_setup
    with pytest.raises(ValueError, match="devices"):
        ServeFleet(model, hps, params,
                   replicas=len(jax.devices()) + 1)
    fleet = ServeFleet(model, hps, params, replicas=1)
    fleet.close()
    with pytest.raises(RuntimeError, match="closed"):
        fleet.submit(_req(0, hps.z_size))


def test_force_place_skips_shed_checks():
    """The bench's parity/capacity arms submit with force=True: same
    least-loaded placement, shed checks skipped — a calibrated
    estimator or a full queue can never drop a request those arms must
    complete."""
    c = _controller(queue_cap=1)
    assert not c.place("interactive").shed      # replica 0 fills
    assert not c.place("interactive").shed      # replica 1 fills
    assert c.place("interactive").shed          # cap bites normally
    p = c.place("interactive", force=True)      # ...but not under force
    assert not p.shed and p.replica in (0, 1)
    c.note_done(0, decode_s=100.0)              # absurd service time
    assert c.place("interactive").shed          # deadline sheds
    assert not c.place("interactive", force=True).shed


def test_fleet_rejects_duplicate_uids(tiny_fleet_setup):
    """A duplicate uid would overwrite its twin's result record and
    wedge drain() forever — refused at the door instead."""
    from sketch_rnn_tpu.serve import ServeFleet

    hps, model, params = tiny_fleet_setup
    fleet = ServeFleet(model, hps, params, replicas=1)
    try:
        fleet.submit(_req(0, hps.z_size))
        with pytest.raises(ValueError, match="duplicate request uid"):
            fleet.submit(_req(0, hps.z_size))
    finally:
        fleet.close()


def test_drain_raises_when_closed_underneath(tiny_fleet_setup):
    """close() abandons queued work; a concurrent (or subsequent)
    drain must fail loudly instead of waiting forever for requests
    that can no longer complete."""
    from sketch_rnn_tpu.serve import ServeFleet

    hps, model, params = tiny_fleet_setup
    fleet = ServeFleet(model, hps, params, replicas=1)
    fleet.submit(_req(0, hps.z_size))   # queued, workers never started
    fleet.close()
    with pytest.raises(RuntimeError, match="closed while draining"):
        fleet.drain(timeout=5)


# -- failover (ISSUE 10) -----------------------------------------------------


def test_admission_mark_dead_shrinks_capacity():
    c = _controller()
    c.place("batch"), c.place("batch"), c.place("batch")
    assert c.backlog == [2, 1]
    dropped = c.mark_dead(0)
    assert dropped == 2 and c.dead == [0] and c.live_replicas == [1]
    assert c.backlog == [0, 1]          # dead backlog dropped
    assert c.mark_dead(0) == 0          # idempotent
    # placement only ever chooses survivors now
    assert all(c.place("batch").replica == 1 for _ in range(4))
    s = c.summary()
    assert s["dead_replicas"] == [0] and s["live_replicas"] == 1
    c.mark_dead(1)
    with pytest.raises(RuntimeError, match="no live replicas"):
        c.place("batch")


def test_fleet_failover_completes_with_chaos_parity(tiny_fleet_setup):
    """THE acceptance pin: a replica killed mid-burst -> its requests
    fail over, drain() completes, /healthz degrades, and every
    completed request's strokes are BITWISE identical to the no-fault
    run (the placement-invariance guarantee extended to failure)."""
    from sketch_rnn_tpu.serve import ServeFleet
    from sketch_rnn_tpu.serve.metrics_http import health_payload
    from sketch_rnn_tpu.utils import faults
    from sketch_rnn_tpu.utils.telemetry import get_telemetry

    hps, model, params = tiny_fleet_setup
    n = 6

    def run(plan):
        if plan:
            faults.configure(plan)
        try:
            fleet = ServeFleet(model, hps, params, replicas=2,
                               retry_backoff_s=0.0)
            for i in range(n):
                fleet.submit(_req(i, hps.z_size))
            with fleet:
                assert fleet.drain(timeout=120)
                return (fleet.results, fleet.summary(), fleet.health())
        finally:
            faults.disable()

    res0, sum0, health0 = run(None)
    res1, sum1, health1 = run("fleet.worker.r0@0")
    # the no-fault run is healthy; the faulted one is degraded but DONE
    assert health0["healthy"] and not health1["healthy"]
    assert sum1["completed"] == n and sum1["failed"] == 0
    assert sum1["replicas_dead"] == 1 and sum1["requeues"] > 0
    assert [r["dead"] for r in sum1["per_replica"]] == [True, False]
    # every requeued request landed on the survivor
    assert all(rec["replica"] == 1 for rec in res1.values())
    # requeues never re-count admission: admitted == what arrived
    assert sum1["admission"]["admitted"] == n
    assert sum1["admission"]["dead_replicas"] == [0]
    # chaos parity: strokes bitwise identical to the no-fault run
    assert sorted(res0) == sorted(res1) == list(range(n))
    for uid in res0:
        assert np.array_equal(res0[uid]["result"].strokes5,
                              res1[uid]["result"].strokes5)
    # /healthz flips to degraded on the fleet's verdict
    payload = health_payload(get_telemetry(), None, lambda: health1)
    assert payload["status"] == "degraded"
    assert payload["fleet"]["replicas_dead"][0]["replica"] == 0
    assert health_payload(get_telemetry(), None,
                          lambda: health0)["status"] == "ok"


def test_fleet_failover_last_replica_death_is_fatal(tiny_fleet_setup):
    from sketch_rnn_tpu.serve import ServeFleet
    from sketch_rnn_tpu.utils import faults

    hps, model, params = tiny_fleet_setup
    faults.configure("fleet.worker.r0@0")
    try:
        fleet = ServeFleet(model, hps, params, replicas=1)
        fleet.submit(_req(0, hps.z_size))
        with fleet:
            with pytest.raises(RuntimeError, match="fleet worker failed"):
                fleet.drain(timeout=60)
        assert not fleet.health()["healthy"]
    finally:
        faults.disable()


def test_fleet_failover_budget_exhausted_fails_requests(tiny_fleet_setup):
    """retry_budget=0: a dead replica's requests are recorded as failed
    (never silently dropped) and drain() still completes — the fleet
    reports the damage instead of hanging or lying."""
    from sketch_rnn_tpu.serve import ServeFleet
    from sketch_rnn_tpu.utils import faults

    hps, model, params = tiny_fleet_setup
    faults.configure("fleet.worker.r0@0")
    try:
        fleet = ServeFleet(model, hps, params, replicas=2,
                           retry_budget=0, retry_backoff_s=0.0)
        for i in range(6):
            fleet.submit(_req(i, hps.z_size))
        with fleet:
            assert fleet.drain(timeout=120)
            s = fleet.summary()
            failed = fleet.failed
            results = fleet.results
    finally:
        faults.disable()
    # replica 0's pre-start share died with it; the rest completed
    assert s["failed"] == len(failed) > 0
    assert s["completed"] == 6 - s["failed"]
    assert set(failed) | set(results) == set(range(6))
    for rec in failed.values():
        assert "retry budget" in rec["reason"]
        assert rec["retries"] == 0
    # reset refuses a degraded fleet (its worker thread is gone)
    with pytest.raises(RuntimeError, match="degraded"):
        fleet.reset()


def test_fleet_failover_counters_and_close_reports(tiny_fleet_setup):
    from sketch_rnn_tpu.serve import ServeFleet
    from sketch_rnn_tpu.utils import faults
    from sketch_rnn_tpu.utils import telemetry as tele

    hps, model, params = tiny_fleet_setup
    tel = tele.configure(trace_dir=None)
    faults.configure("fleet.worker.r1@0")
    try:
        fleet = ServeFleet(model, hps, params, replicas=2,
                           retry_backoff_s=0.0)
        for i in range(4):
            fleet.submit(_req(i, hps.z_size))
        fleet.start()
        assert fleet.drain(timeout=120)
        assert fleet.close() == []       # clean join, no stragglers
        counters = tel.counters()
        assert counters[("serve", "replica_deaths")] == 1
        assert counters[("serve", "requests_requeued")] > 0
        assert counters[("faults", "faults_injected")] == 1
        assert counters[("faults",
                         "faults_injected_fleet_worker_r1")] == 1
    finally:
        faults.disable()
        tele.disable()


def test_failover_queue_wait_clock_base_survives_requeue(tiny_fleet_setup):
    """ISSUE 11 satellite pin: a fleet-retried request's queue_wait_s
    is still measured from the ORIGINAL arrival. Both runs submit with
    an enqueue_ts backdated 50s; if the failover requeue rebased the
    clock, the retried requests' queue_wait_s would collapse to
    sub-second while the no-fault run keeps the 50s base."""
    import jax

    from sketch_rnn_tpu.serve import Request, ServeFleet
    from sketch_rnn_tpu.utils import faults

    hps, model, params = tiny_fleet_setup
    BACKDATE = 50.0
    n = 6

    def run(plan):
        if plan:
            faults.configure(plan)
        try:
            fleet = ServeFleet(model, hps, params, replicas=2,
                               retry_backoff_s=0.0)
            base = time.perf_counter() - BACKDATE
            for i in range(n):
                rng = np.random.default_rng(i)
                fleet.submit(Request(
                    key=jax.random.key(1000 + i),
                    z=rng.standard_normal(hps.z_size).astype(np.float32),
                    temperature=0.8, max_len=4, uid=i,
                    enqueue_ts=base))
            with fleet:
                assert fleet.drain(timeout=120)
                s = fleet.summary()
                return ({uid: rec["result"]
                         for uid, rec in fleet.results.items()}, s)
        finally:
            faults.disable()

    res0, _ = run(None)
    res1, sum1 = run("fleet.worker.r0@0")
    assert sum1["requeues"] > 0 and sum1["completed"] == n
    for uid in range(n):
        # clock base held in BOTH runs: the backdated 50s dominates
        # the sub-second serving time, retried or not
        assert res1[uid].queue_wait_s > BACKDATE - 1.0, uid
        assert res0[uid].queue_wait_s > BACKDATE - 1.0, uid
        # and the two runs' clock bases agree to serving-time noise —
        # a rebased requeue clock would differ by ~50s
        assert abs(res1[uid].queue_wait_s
                   - res0[uid].queue_wait_s) < 5.0, uid
        assert res1[uid].latency_s >= res1[uid].queue_wait_s


def test_closed_fleet_restarts_and_replays_identical_cost(
        tiny_fleet_setup):
    """ISSUE 11: a cleanly-closed fleet can start() again, and a
    replayed deterministic pre-start schedule — all requests queued
    before the workers run — reproduces the ENTIRE cost block
    (per-class split, attributed, idle, dispatched) and the per-request
    attributed steps bitwise: attribution is scheduling math, not
    timing. (Submitting into live workers races the burst chop, which
    is why serve_bench's trials replay pre-start.)"""
    import jax

    from sketch_rnn_tpu.serve import Request, ServeFleet
    from sketch_rnn_tpu.serve.admission import parse_admission_classes

    hps, model, params = tiny_fleet_setup
    classes = parse_admission_classes(
        ["interactive:p95<=5", "batch:p99<=30"])
    fleet = ServeFleet(model, hps, params, replicas=2, classes=classes)
    fleet.warm(Request(key=jax.random.key(0),
                       z=np.zeros(hps.z_size, np.float32),
                       temperature=0.8, max_len=2))

    def run_once():
        for i in range(8):
            rng = np.random.default_rng(i)
            fleet.submit(Request(
                key=jax.random.key(1000 + i),
                z=rng.standard_normal(hps.z_size).astype(np.float32),
                temperature=0.8, max_len=2 + i % 5, uid=i),
                cls=("interactive", "batch")[i % 2])
        fleet.start()
        assert fleet.drain(timeout=120)
        s = fleet.summary()
        per_req = {uid: rec["result"].attributed_steps
                   for uid, rec in fleet.results.items()}
        assert fleet.close() == []
        fleet.reset()
        return s, per_req

    s1, per1 = run_once()
    s2, per2 = run_once()   # the restart: same pre-start schedule
    assert s1["completed"] == s2["completed"] == 8
    assert s1["cost"]["exact"] and s2["cost"]["exact"]
    assert s1["cost"] == s2["cost"]
    assert per1 == per2
    assert sum(per1.values()) == s1["cost"]["steps_attributed"]


def test_loadgen_arrival_stamps_request_trace():
    """ISSUE 11: under an enabled core the loadgen stamps each arrival
    as a SELF-ROOTED span of the request's trace (the terminal span
    may be `request` or `shed`, so it parents under neither), keyed by
    uid_of (default: uid == arrival index)."""
    from sketch_rnn_tpu.utils import telemetry as tele

    tel = tele.configure(trace_dir=None)
    try:
        gen = OpenLoopLoadGen([0.0, 0.0], lambda i: None,
                              uid_of=lambda i: 100 + i).start()
        assert gen.join(timeout=10)
        evs = [e for e in tel.events()
               if e.get("name") == "loadgen_dispatch"]
    finally:
        tele.disable()
    assert [e["trace"] for e in evs] == [
        {"id": "req-100", "span": "arrival-100"},
        {"id": "req-101", "span": "arrival-101"}]
    assert all("parent" not in e["trace"] for e in evs)
    assert [e["args"]["index"] for e in evs] == [0, 1]


def test_warm_under_enabled_telemetry_emits_no_request_spans(
        tiny_fleet_setup):
    """ISSUE 11 fix: warm()'s 1-step clone (auto-assigned uid 0) must
    not emit a req-0 span tree when telemetry was configured BEFORE
    the fleet was built — it would collide with the real request 0's
    trace and break trace_query's event/counter reconciliation."""
    import jax

    from sketch_rnn_tpu.serve import Request, ServeFleet
    from sketch_rnn_tpu.utils import telemetry as tele

    hps, model, params = tiny_fleet_setup
    tel = tele.configure(trace_dir=None)
    try:
        fleet = ServeFleet(model, hps, params, replicas=1)
        fleet.warm(Request(key=jax.random.key(0),
                           z=np.zeros(hps.z_size, np.float32),
                           temperature=0.8, max_len=2))
        assert [e for e in tel.events() if e.get("cat") == "serve"] == []
        fleet.submit(Request(key=jax.random.key(1000),
                             z=np.zeros(hps.z_size, np.float32),
                             temperature=0.8, max_len=2, uid=0))
        with fleet:
            assert fleet.drain(timeout=60)
        # exactly ONE complete event for uid 0 — the real request's
        completes = [e for e in tel.events()
                     if e.get("name") == "complete"]
        assert len(completes) == 1
        assert completes[0]["args"]["uid"] == 0
        assert completes[0]["args"]["steps"] == \
            fleet.results[0]["result"].steps
    finally:
        tele.disable()


# -- elastic scaling telemetry (ISSUE 12) ------------------------------------


def test_elastic_actions_emit_spans_counters_and_gauge(tiny_fleet_setup):
    """The scale timeline is observable: spawn/retire tick counters,
    ride lifecycle spans, and move the fleet_replicas gauge (what
    /metrics renders as sketch_rnn_serve_fleet_replicas)."""
    from sketch_rnn_tpu.serve import ServeFleet
    from sketch_rnn_tpu.utils import telemetry as tele

    hps, model, params = tiny_fleet_setup
    tel = tele.configure(trace_dir=None)
    try:
        fleet = ServeFleet(model, hps, params, replicas=1,
                           max_replicas=2)
        fleet.start()
        fleet.add_replica(reason="load")
        fleet.submit(_req(0, hps.z_size))
        assert fleet.drain(timeout=120)
        fleet.retire_replica(reason="quiet")
        deadline = time.time() + 10      # retire drains asynchronously
        while fleet.health()["scaling"] and time.time() < deadline:
            time.sleep(0.01)
        fleet.close()
        counters = tel.counters()
        events = tel.events()
    finally:
        tele.disable()
    assert counters[("serve", "replica_spawns")] == 1
    assert counters[("serve", "replica_retires")] == 1
    assert counters[("serve", "fleet_replicas")] == 1  # gauge: latest
    spawn = [e for e in events if e.get("name") == "replica_spawn"]
    retire = [e for e in events if e.get("name") == "replica_retire"]
    assert len(spawn) == 1 and spawn[0]["args"]["replica"] == 1
    assert spawn[0]["args"]["reason"] == "load"
    assert len(retire) == 1 and retire[0]["args"]["replica"] == 1


def test_last_live_replica_death_rejoins_retired_spare(tiny_fleet_setup):
    """ISSUE 12 x PR 10 composition pin: the ONLY placed replica dies
    while a pre-warmed retired spare exists — the fleet self-heals by
    rejoining the spare (the spawn path, recorded in scale_log) and
    fails the stranded requests over to it: drain() completes, strokes
    stay bitwise, and a later scale-up clamps to the SURVIVING build
    (a dead replica can never rejoin) instead of raising."""
    from sketch_rnn_tpu.serve import ServeFleet
    from sketch_rnn_tpu.utils import faults

    hps, model, params = tiny_fleet_setup
    n = 4

    def run(plan, **kw):
        if plan:
            faults.configure(plan)
        try:
            fleet = ServeFleet(model, hps, params, replicas=1,
                               retry_backoff_s=0.0, **kw)
            for i in range(n):
                fleet.submit(_req(i, hps.z_size))
            with fleet:
                assert fleet.drain(timeout=120)
                # scaling up post-crash tops out at the living build
                acts = fleet.set_target_replicas(2)
                return (fleet.results, fleet.summary(), acts)
        finally:
            faults.disable()

    res0, _, _ = run(None)
    res1, s1, acts = run("fleet.worker.r0@0", max_replicas=2)
    assert s1["completed"] == n and s1["failed"] == 0
    assert s1["replicas_dead"] == 1
    # every request failed over to the rejoined spare
    assert all(rec["replica"] == 1 for rec in res1.values())
    heal = [e for e in s1["scale_log"] if e["action"] == "spawn"]
    assert len(heal) == 1 and heal[0]["replica"] == 1
    assert "failover" in heal[0]["reason"]
    # bitwise: the self-healed run matches the no-fault run
    for uid in range(n):
        np.testing.assert_array_equal(res1[uid]["result"].strokes5,
                                      res0[uid]["result"].strokes5)
    # the clamp: target 2 > the 1 surviving replica -> no action
    assert acts == [] and s1["replicas_live"] == 1
