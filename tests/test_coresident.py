"""Co-resident train-and-serve (ISSUE 20 tentpole, phase 2).

The contract: ``coresident_train`` / ``cli train --serve_fleet N``
trains while a live fleet in the SAME process serves the same model;
every async checkpoint the loop saves rolls out to the fleet through
the PR 16 validated/canaried path, ``/healthz`` never reports
``degraded``, a post-swap request is bitwise a cold fleet started from
the same checkpoint, the serving lineage lands in RUN.json next to
training's manifest, and completed requests stream back into
``stream_batches`` as training data (the continual-learning loop).
"""

import dataclasses
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.cli import main
from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.data.native_batcher import stream_batches
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.runtime.coresident import (CoResident,
                                               coresident_train,
                                               stroke5_to_stroke3)
from sketch_rnn_tpu.serve import Request, ServeFleet
from sketch_rnn_tpu.train.checkpoint import ckpt_id_of, save_checkpoint
from sketch_rnn_tpu.train.state import make_train_state
from sketch_rnn_tpu.train.step import make_train_step

TINY = dict(batch_size=8, max_seq_len=24, enc_rnn_size=12,
            dec_rnn_size=16, z_size=6, num_mixture=3, hyper_rnn_size=8,
            hyper_embed_size=4, serve_slots=2, serve_chunk=2)

OK_STATUSES = {"ok", "rolling", "scaling"}


def _req(i, z_dim, cap=4):
    rng = np.random.default_rng(i)
    return Request(key=jax.random.key(1000 + i),
                   z=rng.standard_normal(z_dim).astype(np.float32),
                   temperature=0.8, max_len=cap)


def _loader(hps, n=48, seed=0):
    from sketch_rnn_tpu.data.loader import (DataLoader,
                                            make_synthetic_strokes)

    seqs, labels = make_synthetic_strokes(
        n, num_classes=max(hps.num_classes, 1), min_len=3,
        max_len=hps.max_seq_len - 2, seed=seed)
    return DataLoader(seqs, hps, labels=labels, augment=False,
                      seed=seed)


@pytest.fixture(scope="module")
def env():
    hps = HParams(**TINY)
    model = SketchRNN(hps)
    state_old = make_train_state(
        model, hps, jax.random.key(0))._replace(
            step=jnp.asarray(10, jnp.int32))
    state_new = make_train_state(
        model, hps, jax.random.key(7))._replace(
            step=jnp.asarray(20, jnp.int32))
    return dict(hps=hps, model=model, state_old=state_old,
                state_new=state_new)


@pytest.fixture(scope="module")
def corun(tmp_path_factory):
    """ONE co-resident training run shared by the assertion tests:
    6 steps, checkpoints at 3 and 6, a 2-replica fleet serving 6
    requests throughout."""
    hps = HParams(**TINY, num_steps=6, save_every=3, log_every=3,
                  eval_every=10**9)
    wd = str(tmp_path_factory.mktemp("coresident"))
    reqs = [_req(i, hps.z_size) for i in range(6)]
    state, summary = coresident_train(
        hps, _loader(hps), workdir=wd, seed=0, replicas=2,
        poll_s=0.05, loadgen=reqs, use_mesh=False)
    return dict(hps=hps, workdir=wd, state=state, summary=summary)


def test_trains_and_rolls_live(corun):
    """Training completes, BOTH its checkpoints rolled out live, the
    fleet served every request, and /healthz never said degraded."""
    assert int(corun["state"].step) == 6
    s = corun["summary"]
    rolled = [e for e in s["rollouts"] if e.get("ok")]
    assert len(rolled) == 2  # steps 3 and 6, oldest first
    assert s["serving_ckpt_id"] == ckpt_id_of(6)
    assert s["requests_completed"] == 6
    assert s["health_samples"] > 0
    assert s["health_degraded"] == 0


def test_lineage_lands_in_run_json(corun):
    """RUN.json carries the serving lineage next to training's
    manifest: ordered checkpoint windows ending on the final step."""
    path = os.path.join(corun["workdir"], "RUN.json")
    assert os.path.exists(path)
    doc = json.load(open(path))
    serving = doc["serving"]
    lineage = serving["lineage"]
    assert lineage[-1]["ckpt_id"] == ckpt_id_of(6)
    assert lineage[-1]["to_uid"] is None  # the open serving window
    assert [w["ckpt_id"] for w in lineage] == \
        ["", ckpt_id_of(3), ckpt_id_of(6)]
    assert serving["replicas"] == 2
    assert serving["health_degraded"] == 0


def test_post_swap_bitwise_cold_fleet(env, tmp_path):
    """A checkpoint appearing while the fleet serves rolls out live,
    and a post-swap request is bitwise what a COLD fleet started from
    that checkpoint computes."""
    hps, model = env["hps"], env["model"]
    wd = str(tmp_path)
    co = CoResident(model, hps, env["state_old"].params, wd,
                    replicas=2, ckpt_id=ckpt_id_of(10), poll_s=0.05,
                    health_period_s=0.02)
    try:
        save_checkpoint(wd, env["state_new"], 1.0, hps)
        deadline = time.monotonic() + 30.0
        while (co.fleet.serving_ckpt_id != ckpt_id_of(20)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert co.fleet.serving_ckpt_id == ckpt_id_of(20)
        probe = _req(77, hps.z_size, cap=6)
        co.fleet.submit(dataclasses.replace(probe), force=True)
        assert co.fleet.drain(timeout=30.0)
        (rec,) = co.fleet.results.values()
        live = np.asarray(rec["result"].strokes5)
        assert rec["result"].ckpt_id == ckpt_id_of(20)
        statuses = set(co.health_statuses())
        assert statuses and statuses <= OK_STATUSES
        lineage = co.lineage()
        assert lineage[-1]["ckpt_id"] == ckpt_id_of(20)
    finally:
        co.close()
    cold = ServeFleet(model, hps, env["state_new"].params, replicas=2,
                      ckpt_id=ckpt_id_of(20))
    try:
        cold.warm(_req(0, hps.z_size))
        cold.start()
        cold.submit(dataclasses.replace(probe), force=True)
        assert cold.drain(timeout=30.0)
        (crec,) = cold.results.values()
        np.testing.assert_array_equal(live,
                                      np.asarray(crec["result"].strokes5))
    finally:
        cold.close()


def test_continual_learning_smoke(env):
    """The loop closes: the fleet's completed-request corpus streams
    back through ``stream_batches`` and the model trains on what it
    served."""
    hps, model = env["hps"], env["model"]
    co = CoResident(model, hps, env["state_old"].params, "/nonexistent",
                    replicas=2, poll_s=0.2)
    try:
        co.start_loadgen([_req(200 + i, hps.z_size, cap=6)
                          for i in range(10)])
        assert co.drain(timeout=60.0)
        corpus = co.corpus()
    finally:
        co.close()
    assert len(corpus) == 10
    for s3 in corpus:
        assert s3.ndim == 2 and s3.shape[1] == 3
        assert s3[-1, 2] == 1.0  # the final stroke is closed
    batches = list(stream_batches(iter(corpus), hps.batch_size,
                                  hps.max_seq_len))
    assert batches and batches[0]["strokes"].shape == \
        (hps.batch_size, hps.max_seq_len + 1, 5)
    state = make_train_state(model, hps, jax.random.key(3))
    step = make_train_step(model, hps)
    for i in range(2):
        state, metrics = step(state, batches[0], jax.random.key(i))
        assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 2


def test_stroke5_to_stroke3_roundtrip_shape():
    s5 = np.zeros((5, 5), np.float32)
    s5[:, 0] = np.arange(5)
    s5[2, 3] = 1.0       # pen lift mid-sketch
    s5[:, 2] = 1.0
    s3 = stroke5_to_stroke3(s5, length=4)  # EOS row dropped
    assert s3.shape == (4, 3)
    np.testing.assert_array_equal(s3[:, 0], [0, 1, 2, 3])
    assert s3[2, 2] == 1.0 and s3[1, 2] == 0.0
    assert s3[-1, 2] == 1.0  # final row closes its stroke
    # degenerate length never yields an empty sequence
    assert stroke5_to_stroke3(s5, length=0).shape == (1, 3)


def test_cli_serve_fleet_usage_validation(tmp_path, capsys):
    """Bad co-resident flags fail fast with one actionable line,
    before any data/model work."""
    wd = str(tmp_path)
    assert main(["train", "--synthetic", f"--workdir={wd}",
                 "--serve_fleet=1"]) == 2
    assert "N >= 2" in capsys.readouterr().err
    assert main(["train", "--synthetic", "--workdir=",
                 "--serve_fleet=2"]) == 2
    assert "--workdir" in capsys.readouterr().err
    assert main(["train", "--synthetic", f"--workdir={wd}",
                 "--serve_fleet=2", "--elastic_hosts=2",
                 f"--rendezvous={wd}"]) == 2
    assert "--elastic_hosts" in capsys.readouterr().err


@pytest.mark.slow
def test_cli_train_serve_fleet_e2e(tmp_path, capsys):
    """The full CLI path: train --serve_fleet 2 on synthetic data;
    lineage in RUN.json, co-resident summary on stdout."""
    wd = str(tmp_path / "work")
    hp = ("batch_size=8,max_seq_len=24,enc_rnn_size=12,dec_rnn_size=16,"
          "z_size=6,num_mixture=3,hyper_rnn_size=8,hyper_embed_size=4,"
          "serve_slots=2,serve_chunk=2,num_steps=4,save_every=2,"
          "eval_every=50,log_every=2")
    assert main(["train", "--synthetic", f"--workdir={wd}",
                 f"--hparams={hp}", "--serve_fleet=2",
                 "--serve_poll=0.05"]) == 0
    out = capsys.readouterr().out
    assert "co-resident fleet" in out
    doc = json.load(open(os.path.join(wd, "RUN.json")))
    assert doc["serving"]["lineage"][-1]["ckpt_id"] == ckpt_id_of(4)
    assert doc["serving"]["health_degraded"] == 0
