"""Int8/bf16 inference quantization + quantized rollout admission.

ISSUE 17 satellite: the quantization round-trip honors its DOCUMENTED
error budget (every element within ``scale/2`` for int8, ``2^-8``
relative for bf16; the per-tensor report rows agree), tensors already
on the int8 grid transfer EXACTLY (the loader's scale_factor idiom one
octave coarser), ``quantize_for_serving`` is a true identity at
float32, ``stamp_ckpt_id`` marks the serving precision — and a fleet
rolled to a checkpoint under ``serve_quantize=int8`` serves strokes
bitwise equal to the offline reference on the QUANTIZED weights, every
Result stamped ``<ckpt_id>:int8``.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.serve import Request, ServeFleet
from sketch_rnn_tpu.serve.endpoints import serve_requests
from sketch_rnn_tpu.serve.quantize import (QTensor, check_mode,
                                           dequantize_params,
                                           max_error_bound,
                                           quantize_for_serving,
                                           quantize_params,
                                           stamp_ckpt_id)
from sketch_rnn_tpu.serve.rollout import RolloutController
from sketch_rnn_tpu.train.checkpoint import ckpt_id_of, save_checkpoint
from sketch_rnn_tpu.train.state import make_train_state

# ------------------------------------------------------------ round-trip


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": rng.normal(0, 1.7, (8, 16)).astype(np.float32),
        "b": rng.normal(0, 0.02, (16,)).astype(np.float32),
        "nested": {"k": rng.normal(0, 40.0, (3, 5)).astype(np.float32)},
        "step": 7,                       # int scalar: passthrough
        "scale": np.float32(1.25),       # 0-d float: passthrough
        "idx": np.arange(4),             # int array: passthrough
        "zero": np.zeros((4, 4), np.float32),
    }


@pytest.mark.parametrize("mode", ["int8", "bfloat16"])
def test_round_trip_error_within_budget(mode):
    """Element-wise |w - dequant| <= the documented per-tensor bound,
    and every report row's measured max_err <= its own bound."""
    tree = _tree()
    packed, report = quantize_params(tree, mode)
    out = dequantize_params(packed)
    quant_paths = {r["path"] for r in report}
    assert quant_paths == {"w", "b", "nested/k", "zero"}
    for r in report:
        assert r["max_err"] <= r["bound"] + 1e-12, r
    for path, w in [("w", tree["w"]), ("b", tree["b"]),
                    ("nested/k", tree["nested"]["k"])]:
        node = out
        for part in path.split("/"):
            node = node[part]
        bound = max_error_bound(w, mode)
        assert bound > 0
        np.testing.assert_allclose(node, w, atol=bound, rtol=0)
        assert node.dtype == np.float32
    # passthrough leaves are untouched (same object where possible)
    assert out["step"] == 7 and float(out["scale"]) == 1.25
    np.testing.assert_array_equal(out["idx"], tree["idx"])
    # all-zero tensor: scale 1.0, exact zero round-trip
    zrow = next(r for r in report if r["path"] == "zero")
    assert zrow["scale"] == 1.0 and zrow["max_err"] == 0.0
    np.testing.assert_array_equal(out["zero"], tree["zero"])


def test_int8_grid_values_transfer_exactly():
    """Values already on the int8 grid scale*{-127..127} round-trip
    BITWISE — the loader's int16 exact-transfer idiom, one octave
    coarser."""
    scale = 0.03125  # power of two: q*scale exact in f32
    q = np.asarray([[-127, -3, 0, 1, 64, 127]], np.float32)
    w = (q * scale).astype(np.float32)
    packed, report = quantize_params({"g": w}, "int8")
    assert isinstance(packed["g"], QTensor)
    np.testing.assert_array_equal(packed["g"].q, q.astype(np.int8))
    np.testing.assert_array_equal(dequantize_params(packed)["g"], w)
    assert report[0]["max_err"] == 0.0


def test_bfloat16_is_round_through():
    w = _tree(3)["w"]
    out, _ = quantize_for_serving({"w": w}, "bfloat16")
    want = np.asarray(jnp.asarray(w, jnp.bfloat16), np.float32)
    np.testing.assert_array_equal(out["w"], want)


def test_float32_is_identity_and_modes_validate():
    tree = _tree(1)
    out, report = quantize_for_serving(tree, "float32")
    assert out is tree and report == []
    with pytest.raises(ValueError, match="int4"):
        check_mode("int4")
    with pytest.raises(ValueError, match="fp8"):
        quantize_for_serving(tree, "fp8")


def test_stamp_ckpt_id():
    assert stamp_ckpt_id("ckpt_00000020", "int8") == \
        "ckpt_00000020:int8"
    assert stamp_ckpt_id("ckpt_00000020", "bfloat16") == \
        "ckpt_00000020:bf16"
    assert stamp_ckpt_id("ckpt_00000020", "float32") == \
        "ckpt_00000020"
    assert stamp_ckpt_id("", "int8") == ""
    with pytest.raises(ValueError):
        stamp_ckpt_id("x", "int9")


def test_model_params_quantize_with_bounded_error():
    """The real param tree: every matrix/bias quantizes, the serving
    tree keeps structure + dtypes, report rows all within budget."""
    hps = HParams(batch_size=4, max_seq_len=16, enc_rnn_size=12,
                  dec_rnn_size=16, z_size=6, num_mixture=3)
    params = SketchRNN(hps).init_params(jax.random.key(0))
    served, report = quantize_for_serving(params, "int8")
    assert jax.tree_util.tree_structure(served) == \
        jax.tree_util.tree_structure(params)
    n_arrays = sum(np.asarray(p).ndim >= 1
                   for p in jax.tree_util.tree_leaves(params))
    assert len(report) == n_arrays
    for r in report:
        assert 0 <= r["max_err"] <= r["bound"] + 1e-12, r


# ------------------------------------------------- quantized admission


TINY = dict(batch_size=8, max_seq_len=24, enc_rnn_size=12,
            dec_rnn_size=16, z_size=6, num_mixture=3, hyper_rnn_size=8,
            hyper_embed_size=4, serve_slots=2, serve_chunk=2)


def _req(i, z_dim, cap=6):
    rng = np.random.default_rng(i)
    return Request(key=jax.random.key(1000 + i),
                   z=rng.standard_normal(z_dim).astype(np.float32),
                   temperature=0.8, max_len=cap)


def test_rollout_admits_quantized_checkpoint(tmp_path):
    """serve_quantize=int8: the admitted checkpoint is quantized at
    the rollout boundary, the fleet's serving identity is the STAMPED
    id, and every post-roll Result is bitwise the offline reference on
    the dequantized-int8 weights — the canary gate proved the
    quantized bits, not the full-precision ones."""
    hps = HParams(**TINY).replace(serve_quantize="int8")
    model = SketchRNN(hps)
    state_old = make_train_state(model, hps, jax.random.key(0))._replace(
        step=jnp.asarray(10, jnp.int32))
    state_new = make_train_state(model, hps, jax.random.key(7))._replace(
        step=jnp.asarray(20, jnp.int32))
    d = str(tmp_path / "ckpts")
    os.makedirs(d, exist_ok=True)
    p_new = save_checkpoint(d, state_new, 1.0, hps)
    stamped = stamp_ckpt_id(ckpt_id_of(20), "int8")
    assert stamped == "ckpt_00000020:int8"

    fleet = ServeFleet(model, hps, state_old.params, replicas=2,
                       ckpt_id=ckpt_id_of(10))
    fleet.warm(_req(0, hps.z_size))
    fleet.start()
    try:
        canary = [_req(900 + i, hps.z_size, cap=4) for i in range(3)]
        ctl = RolloutController(fleet, model, hps, state_old, canary)
        rpt = ctl.roll_to(p_new)
        assert rpt["ok"], rpt
        assert fleet.serving_ckpt_id == stamped
        events = [e["event"] for e in ctl.rollout_log]
        assert "quantize" in events

        uids = list(range(6))
        for r in [dataclasses.replace(_req(i, hps.z_size), uid=i)
                  for i in uids]:
            fleet.submit(r)
        assert fleet.drain(timeout=120)

        qparams, qreport = quantize_for_serving(state_new.params,
                                                "int8")
        assert qreport  # the admission really had something to round
        ref = serve_requests(
            model, hps, qparams,
            [dataclasses.replace(_req(i, hps.z_size), uid=i)
             for i in uids],
            slots=hps.serve_slots, chunk=hps.serve_chunk,
            pool_pad=max(fleet.pool_cap, len(uids)))
        ref = {r.uid: r.strokes5 for r in ref["results"]}
        for uid in uids:
            res = fleet.results[uid]["result"]
            np.testing.assert_array_equal(res.strokes5, ref[uid])
            assert res.ckpt_id == stamped
    finally:
        fleet.close()
