"""Tests for aux subsystems: throughput counter, NaN guards."""

import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.utils import Throughput, check_finite, find_nonfinite


def test_throughput_counter():
    tp = Throughput(strokes_per_step=100, num_chips=4)
    assert tp.update(0) is None
    import time
    time.sleep(0.01)
    rates = tp.update(10)
    assert rates is not None
    assert rates["strokes_per_sec"] == pytest.approx(
        rates["steps_per_sec"] * 100)
    assert rates["strokes_per_sec_per_chip"] == pytest.approx(
        rates["strokes_per_sec"] / 4)
    # non-advancing step resets instead of dividing by zero
    assert tp.update(10) is None


def test_check_finite_passes_and_raises():
    check_finite({"loss": 1.0, "kl": 0.2}, step=5)
    with pytest.raises(FloatingPointError, match="loss"):
        check_finite({"loss": float("nan"), "kl": 0.2}, step=5)
    with pytest.raises(FloatingPointError, match="step 7"):
        check_finite({"g": float("inf")}, step=7)


def test_find_nonfinite_paths():
    tree = {"a": jnp.ones((3,)),
            "b": {"c": jnp.array([1.0, np.nan]),
                  "d": jnp.array([2, 3])}}  # int leaf ignored
    bad = find_nonfinite(tree)
    assert len(bad) == 1 and "'b'" in bad[0] and "'c'" in bad[0]
    assert find_nonfinite({"x": jnp.zeros(2)}) == []
