"""Tests for aux subsystems: throughput counter, goodput ledger, span
timer, NaN guards, metrics drain/writer."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.utils import (
    GoodputLedger,
    SpanTimer,
    Throughput,
    check_finite,
    find_nonfinite,
)


def test_throughput_counter():
    tp = Throughput(strokes_per_step=100, num_chips=4)
    assert tp.update(0) is None
    import time
    time.sleep(0.01)
    rates = tp.update(10)
    assert rates is not None
    assert rates["strokes_per_sec"] == pytest.approx(
        rates["steps_per_sec"] * 100)
    assert rates["strokes_per_sec_per_chip"] == pytest.approx(
        rates["strokes_per_sec"] / 4)
    # non-advancing step resets instead of dividing by zero
    assert tp.update(10) is None


def test_throughput_zero_dt_guard_and_rearm(monkeypatch):
    """A zero-elapsed window returns None WITHOUT advancing the mark, so
    the next real window still measures from the last good mark (the
    untested edge in utils/profiling.py, ISSUE 6 satellite)."""
    from sketch_rnn_tpu.utils import profiling

    t = [100.0]
    monkeypatch.setattr(profiling.time, "perf_counter", lambda: t[0])
    tp = Throughput(strokes_per_step=10, num_chips=2)
    assert tp.update(0) is None          # first call arms
    assert tp.update(5) is None          # dt == 0: no division, None
    t[0] = 101.0
    r = tp.update(10)                    # measures 10 steps over 1 s
    assert r["steps_per_sec"] == pytest.approx(10.0)
    assert r["strokes_per_sec"] == pytest.approx(100.0)
    assert r["strokes_per_sec_per_chip"] == pytest.approx(50.0)


def test_throughput_step_regression_resets(monkeypatch):
    """A step that goes BACKWARDS (restart/resume) re-arms instead of
    reporting a negative rate."""
    from sketch_rnn_tpu.utils import profiling

    t = [0.0]
    monkeypatch.setattr(profiling.time, "perf_counter", lambda: t[0])
    tp = Throughput(strokes_per_step=1, num_chips=1)
    tp.update(10)
    t[0] = 1.0
    assert tp.update(3) is None          # regression: reset, not -7/s
    t[0] = 2.0
    assert tp.update(5)["steps_per_sec"] == pytest.approx(2.0)


def test_throughput_default_num_chips_is_device_count():
    tp = Throughput(strokes_per_step=1)
    assert tp.num_chips == jax.device_count()


def test_span_timer_thread_safe_concurrent_closes():
    """ISSUE 6 satellite regression: the serve engine's depth-1
    pipelined dispatch interleaves span closes across threads; the
    unlocked read-modify-write lost increments. Hammer one name from
    many threads and demand an exact count/total."""
    st = SpanTimer()
    n, threads = 2000, 8

    def work():
        for _ in range(n):
            with st.span("chunk"):
                pass

    ts = [threading.Thread(target=work) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    s = st.summary()
    assert s["chunk"]["count"] == n * threads
    assert s["chunk"]["total_s"] >= 0


def test_goodput_ledger_window_mark_semantics():
    """ISSUE 6 satellite: pin the mark bookkeeping edges — marks only
    advance via window(); summary() never disturbs them; a phase that
    FIRST fires mid-run reports its full total in its first window; a
    custom prefix does not fork the mark state."""
    import time

    led = GoodputLedger(("dispatch",))
    with led.span("dispatch"):
        time.sleep(0.001)
    led.summary()                        # reads totals, must not mark
    w1 = led.window()
    assert w1["t_dispatch_s"] >= 0.001   # summary() did not consume it

    with led.span("late_phase"):         # joins after the first window
        time.sleep(0.001)
    w2 = led.window()
    assert w2["t_dispatch_s"] == 0.0
    assert w2["t_late_phase_s"] >= 0.001  # FULL total in first window

    with led.span("dispatch"):
        time.sleep(0.001)
    w3 = led.window(prefix="x_")         # same marks, renamed keys
    assert w3["x_dispatch_s"] >= 0.001
    assert led.window()["t_dispatch_s"] == 0.0  # prefix didn't fork


def test_goodput_ledger_windows_and_totals():
    led = GoodputLedger(("dispatch", "ckpt_wait"))
    # pre-declared phases appear in the FIRST window even before any
    # span fires (CSV header stability) and summary tolerates count 0
    w0 = led.window()
    assert w0 == {"t_dispatch_s": 0.0, "t_ckpt_wait_s": 0.0}
    assert led.summary()["ckpt_wait"]["mean_ms"] == 0.0

    import time
    with led.span("dispatch"):
        time.sleep(0.01)
    with led.span("dispatch"):
        pass
    w1 = led.window()
    assert w1["t_dispatch_s"] >= 0.01
    assert w1["t_ckpt_wait_s"] == 0.0
    # windows are DELTAS: a second call without new spans reads ~zero
    assert led.window()["t_dispatch_s"] == 0.0
    # totals keep accumulating across windows
    s = led.summary()
    assert s["dispatch"]["count"] == 2
    assert s["dispatch"]["total_s"] >= 0.01
    # an undeclared phase joins the ledger on first use
    with led.span("eval"):
        pass
    assert "t_eval_s" in led.window()


def test_metrics_drain_one_window_deferral():
    from sketch_rnn_tpu.train.metrics import MetricsDrain

    class Rec:
        def __init__(self):
            self.rows = []

        def write(self, step, scalars):
            self.rows.append((step, scalars))

        def log_console(self, *a, **k):
            pass

    rec = Rec()
    checked = []
    d = MetricsDrain(rec, defer=True,
                     check=lambda s, step: checked.append(step))
    d.push(2, {"loss": jnp.float32(1.0)}, {"rate": 5.0})
    assert rec.rows == []          # held: one-window deferral
    d.push(4, {"loss": jnp.float32(2.0)})
    assert rec.rows == [(2, {"loss": 1.0, "rate": 5.0})]
    assert checked == [2]          # guard ran on the drained window
    d.flush()
    assert rec.rows[-1] == (4, {"loss": 2.0})
    d.flush()                      # idempotent on an empty queue
    assert len(rec.rows) == 2

    # defer=False is the synchronous path: emit inside push
    rec2 = Rec()
    d2 = MetricsDrain(rec2, defer=False)
    d2.push(2, {"loss": jnp.float32(3.0)})
    assert rec2.rows == [(2, {"loss": 3.0})]


def test_metrics_drain_check_raise_after_persist():
    """A failing check (divergence) must raise AFTER the row is written
    — the record survives for post-mortem."""
    from sketch_rnn_tpu.train.metrics import MetricsDrain

    rows = []

    class Rec:
        def write(self, step, scalars):
            rows.append(step)

        def log_console(self, *a, **k):
            pass

    d = MetricsDrain(Rec(), defer=True, check=check_finite)
    d.push(2, {"loss": jnp.float32(float("nan"))})
    with pytest.raises(FloatingPointError, match="step 2"):
        d.push(4, {"loss": jnp.float32(1.0)})
    assert rows == [2]


def test_metrics_writer_warns_once_per_dropped_key(tmp_path, capsys):
    """ISSUE 6 satellite: the CSV resume-alignment rule silently
    dropped scalar keys absent from the first row's header — now it
    warns, exactly once per key, and the JSONL keeps the full row."""
    import csv
    import json

    from sketch_rnn_tpu.train.metrics import MetricsWriter

    w = MetricsWriter(str(tmp_path), "train")
    w.write(1, {"a": 1.0})
    w.write(2, {"a": 2.0, "b": 3.0})   # b not in header: warn
    w.write(3, {"a": 3.0, "b": 4.0})   # same key: NO second warning
    w.write(4, {"a": 4.0, "c": 5.0})   # new key: warn again
    err = capsys.readouterr().err
    assert err.count("drops keys") == 2
    assert "'b'" in err and "'c'" in err
    # CSV stays aligned to its header; JSONL kept everything
    with open(tmp_path / "train_metrics.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    assert [r["a"] for r in rows] == ["1.0", "2.0", "3.0", "4.0"]
    assert all("b" not in r for r in rows)
    with open(tmp_path / "train_metrics.jsonl") as f:
        jrows = [json.loads(l) for l in f]
    assert jrows[1]["b"] == 3.0 and jrows[3]["c"] == 5.0


def test_metrics_writer_no_warning_when_keys_stable(tmp_path, capsys):
    from sketch_rnn_tpu.train.metrics import MetricsWriter

    w = MetricsWriter(str(tmp_path), "train")
    for s in (1, 2, 3):
        w.write(s, {"a": float(s), "b": float(s)})
    assert "drops keys" not in capsys.readouterr().err


def test_check_finite_passes_and_raises():
    check_finite({"loss": 1.0, "kl": 0.2}, step=5)
    with pytest.raises(FloatingPointError, match="loss"):
        check_finite({"loss": float("nan"), "kl": 0.2}, step=5)
    with pytest.raises(FloatingPointError, match="step 7"):
        check_finite({"g": float("inf")}, step=7)


def test_find_nonfinite_paths():
    tree = {"a": jnp.ones((3,)),
            "b": {"c": jnp.array([1.0, np.nan]),
                  "d": jnp.array([2, 3])}}  # int leaf ignored
    bad = find_nonfinite(tree)
    assert len(bad) == 1 and "'b'" in bad[0] and "'c'" in bad[0]
    assert find_nonfinite({"x": jnp.zeros(2)}) == []
