"""Tests for aux subsystems: throughput counter, goodput ledger, NaN
guards, metrics drain."""

import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.utils import (
    GoodputLedger,
    Throughput,
    check_finite,
    find_nonfinite,
)


def test_throughput_counter():
    tp = Throughput(strokes_per_step=100, num_chips=4)
    assert tp.update(0) is None
    import time
    time.sleep(0.01)
    rates = tp.update(10)
    assert rates is not None
    assert rates["strokes_per_sec"] == pytest.approx(
        rates["steps_per_sec"] * 100)
    assert rates["strokes_per_sec_per_chip"] == pytest.approx(
        rates["strokes_per_sec"] / 4)
    # non-advancing step resets instead of dividing by zero
    assert tp.update(10) is None


def test_goodput_ledger_windows_and_totals():
    led = GoodputLedger(("dispatch", "ckpt_wait"))
    # pre-declared phases appear in the FIRST window even before any
    # span fires (CSV header stability) and summary tolerates count 0
    w0 = led.window()
    assert w0 == {"t_dispatch_s": 0.0, "t_ckpt_wait_s": 0.0}
    assert led.summary()["ckpt_wait"]["mean_ms"] == 0.0

    import time
    with led.span("dispatch"):
        time.sleep(0.01)
    with led.span("dispatch"):
        pass
    w1 = led.window()
    assert w1["t_dispatch_s"] >= 0.01
    assert w1["t_ckpt_wait_s"] == 0.0
    # windows are DELTAS: a second call without new spans reads ~zero
    assert led.window()["t_dispatch_s"] == 0.0
    # totals keep accumulating across windows
    s = led.summary()
    assert s["dispatch"]["count"] == 2
    assert s["dispatch"]["total_s"] >= 0.01
    # an undeclared phase joins the ledger on first use
    with led.span("eval"):
        pass
    assert "t_eval_s" in led.window()


def test_metrics_drain_one_window_deferral():
    from sketch_rnn_tpu.train.metrics import MetricsDrain

    class Rec:
        def __init__(self):
            self.rows = []

        def write(self, step, scalars):
            self.rows.append((step, scalars))

        def log_console(self, *a, **k):
            pass

    rec = Rec()
    checked = []
    d = MetricsDrain(rec, defer=True,
                     check=lambda s, step: checked.append(step))
    d.push(2, {"loss": jnp.float32(1.0)}, {"rate": 5.0})
    assert rec.rows == []          # held: one-window deferral
    d.push(4, {"loss": jnp.float32(2.0)})
    assert rec.rows == [(2, {"loss": 1.0, "rate": 5.0})]
    assert checked == [2]          # guard ran on the drained window
    d.flush()
    assert rec.rows[-1] == (4, {"loss": 2.0})
    d.flush()                      # idempotent on an empty queue
    assert len(rec.rows) == 2

    # defer=False is the synchronous path: emit inside push
    rec2 = Rec()
    d2 = MetricsDrain(rec2, defer=False)
    d2.push(2, {"loss": jnp.float32(3.0)})
    assert rec2.rows == [(2, {"loss": 3.0})]


def test_metrics_drain_check_raise_after_persist():
    """A failing check (divergence) must raise AFTER the row is written
    — the record survives for post-mortem."""
    from sketch_rnn_tpu.train.metrics import MetricsDrain

    rows = []

    class Rec:
        def write(self, step, scalars):
            rows.append(step)

        def log_console(self, *a, **k):
            pass

    d = MetricsDrain(Rec(), defer=True, check=check_finite)
    d.push(2, {"loss": jnp.float32(float("nan"))})
    with pytest.raises(FloatingPointError, match="step 2"):
        d.push(4, {"loss": jnp.float32(1.0)})
    assert rows == [2]


def test_check_finite_passes_and_raises():
    check_finite({"loss": 1.0, "kl": 0.2}, step=5)
    with pytest.raises(FloatingPointError, match="loss"):
        check_finite({"loss": float("nan"), "kl": 0.2}, step=5)
    with pytest.raises(FloatingPointError, match="step 7"):
        check_finite({"g": float("inf")}, step=7)


def test_find_nonfinite_paths():
    tree = {"a": jnp.ones((3,)),
            "b": {"c": jnp.array([1.0, np.nan]),
                  "d": jnp.array([2, 3])}}  # int leaf ignored
    bad = find_nonfinite(tree)
    assert len(bad) == 1 and "'b'" in bad[0] and "'c'" in bad[0]
    assert find_nonfinite({"x": jnp.zeros(2)}) == []
