"""Overlapped input pipeline tests (SURVEY.md §7 'input pipeline').

The contract: prefetching changes throughput, never results — the
prefetched batch sequence is bit-identical to a synchronous feed, and a
training run with prefetch on equals one with it off.
"""

import time

import jax
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
from sketch_rnn_tpu.data.prefetch import Prefetcher, prefetch_batches

TINY = dict(batch_size=8, max_seq_len=32, enc_rnn_size=12, dec_rnn_size=16,
            z_size=6, num_mixture=3, hyper_rnn_size=8, hyper_embed_size=4)


def make_loader(seed=0):
    hps = HParams(**TINY)
    seqs, labels = make_synthetic_strokes(40, min_len=8, max_len=30,
                                          seed=seed)
    return DataLoader(seqs, hps, labels=labels, seed=seed), hps


def test_prefetch_matches_synchronous_sequence():
    sync_loader, _ = make_loader(seed=3)
    pre_loader, _ = make_loader(seed=3)
    want = [sync_loader.random_batch() for _ in range(12)]
    with prefetch_batches(pre_loader, mesh=None, depth=3) as feeder:
        got = [feeder.get() for _ in range(12)]
    for w, g in zip(want, got):
        for k in w:
            np.testing.assert_array_equal(w[k], g[k])


def test_prefetch_device_put_sequence():
    # with a mesh the producer thread also does the sharded transfer;
    # values must still match the host sequence exactly
    from sketch_rnn_tpu.parallel.mesh import make_mesh
    sync_loader, hps = make_loader(seed=5)
    pre_loader, _ = make_loader(seed=5)
    mesh = make_mesh(hps)
    want = [sync_loader.random_batch() for _ in range(4)]
    with prefetch_batches(pre_loader, mesh=mesh, depth=2) as feeder:
        for w in want:
            g = feeder.get()
            assert isinstance(g["strokes"], jax.Array)
            for k in w:
                np.testing.assert_array_equal(w[k], np.asarray(g[k]))


def test_prefetch_propagates_producer_error():
    calls = {"n": 0}

    def producer():
        calls["n"] += 1
        if calls["n"] > 2:
            raise RuntimeError("loader exploded")
        return calls["n"]

    with Prefetcher(producer, depth=1) as feeder:
        assert feeder.get() == 1
        assert feeder.get() == 2
        with pytest.raises(RuntimeError, match="loader exploded"):
            feeder.get()


def test_prefetch_close_unblocks_full_queue():
    feeder = Prefetcher(lambda: 0, depth=1)
    assert feeder.get() == 0
    t0 = time.perf_counter()
    feeder.close()  # producer may be blocked on a full queue; must not hang
    assert time.perf_counter() - t0 < 5.0
    feeder.close()  # idempotent
    with pytest.raises(RuntimeError, match="closed"):
        feeder.get()


@pytest.mark.slow
def test_train_with_and_without_prefetch_identical():
    from sketch_rnn_tpu.train.loop import train
    hps = HParams(**TINY, num_steps=4, save_every=100, eval_every=100,
                  log_every=2)

    def run(depth):
        seqs, labels = make_synthetic_strokes(32, min_len=8, max_len=30,
                                              seed=1)
        loader = DataLoader(seqs, hps.replace(prefetch_depth=depth),
                            labels=labels, seed=1)
        return train(hps.replace(prefetch_depth=depth), loader,
                     use_mesh=True, seed=0)

    a, b = run(0), run(2)
    for x, y in zip(jax.tree_util.tree_leaves(a.params),
                    jax.tree_util.tree_leaves(b.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_prefetch_stack_matches_sequential_batches():
    """stack=K yields the same batches (same loader RNG order) as K
    sequential random_batch() calls, stacked on a new leading axis."""
    stacked_loader, _ = make_loader(seed=5)
    seq_loader, _ = make_loader(seed=5)
    feeder = prefetch_batches(stacked_loader, mesh=None, depth=1, stack=3)
    try:
        got = feeder.get()
    finally:
        feeder.close()
    want = [seq_loader.random_batch() for _ in range(3)]
    for k in want[0]:
        assert got[k].shape == (3,) + want[0][k].shape
        for i in range(3):
            np.testing.assert_array_equal(np.asarray(got[k][i]), want[i][k])


def test_prefetch_stack_rejects_bad_k():
    loader, _ = make_loader()
    with pytest.raises(ValueError, match="stack"):
        prefetch_batches(loader, mesh=None, depth=1, stack=0)


def test_prefetch_transfer_dtype_casts_strokes_only():
    import jax.numpy as jnp

    loader, _ = make_loader(seed=7)
    ref_loader, _ = make_loader(seed=7)
    feeder = prefetch_batches(loader, mesh=None, depth=1,
                              transfer_dtype="bfloat16")
    try:
        got = feeder.get()
    finally:
        feeder.close()
    want = ref_loader.random_batch()
    assert got["strokes"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(got["strokes"], np.float32),
        want["strokes"].astype(jnp.bfloat16).astype(np.float32))
    # non-stroke fields keep their exact dtype/values
    assert got["seq_len"].dtype == want["seq_len"].dtype
    np.testing.assert_array_equal(np.asarray(got["seq_len"]),
                                  want["seq_len"])


def _integer_origin_loader(seed=0, scale=17.5):
    """Loader whose stroke offsets are INTEGERS before normalization —
    the QuickDraw shape (raw deltas are int16 at origin)."""
    hps = HParams(**TINY)
    rng = np.random.default_rng(seed)
    seqs = []
    for _ in range(40):
        n = int(rng.integers(8, 30))
        s = np.zeros((n, 3), np.float32)
        s[:, :2] = rng.integers(-300, 300, size=(n, 2)).astype(np.float32)
        s[rng.integers(0, n, 3), 2] = 1
        seqs.append(s)
    loader = DataLoader(seqs, hps, seed=seed)
    loader.normalize(scale)
    return loader, hps


def test_prefetch_int16_exact_for_integer_origin():
    """int16 transfer must be EXACT end-to-end for integer-origin data:
    dequantizing (int / scale) reproduces the host-normalized float32
    batch bit-for-bit — the no-rounding-trade claim (VERDICT r3 #2)."""
    loader, _ = _integer_origin_loader(seed=5)
    ref_loader, _ = _integer_origin_loader(seed=5)
    with prefetch_batches(loader, mesh=None, depth=1,
                          transfer_dtype="int16") as feeder:
        got = feeder.get()
    want = ref_loader.random_batch()
    assert got["strokes"].dtype == np.int16
    sc = np.asarray(got["transfer_scale"])
    assert sc.shape == (want["strokes"].shape[0],)
    deq = got["strokes"].astype(np.float32)
    deq[..., :2] /= sc[:, None, None]
    np.testing.assert_array_equal(deq, want["strokes"])
    # pen bits travel untouched
    np.testing.assert_array_equal(got["strokes"][..., 2:],
                                  want["strokes"][..., 2:].astype(np.int16))


def test_prefetch_int16_stacked_and_bounded_error():
    """Stacked (K-step) int16 batches carry a [K, B] scale leaf; for a
    NON-integer corpus the quantization error is bounded by half a data
    unit per offset (0.5 / scale in normalized units)."""
    loader, _ = make_loader(seed=9)
    loader.normalize(8.0)
    ref_loader, _ = make_loader(seed=9)
    ref_loader.normalize(8.0)
    with prefetch_batches(loader, mesh=None, depth=1, stack=3,
                          transfer_dtype="int16") as feeder:
        got = feeder.get()
    want = np.stack([ref_loader.random_batch()["strokes"]
                     for _ in range(3)])
    sc = np.asarray(got["transfer_scale"])
    assert sc.shape == (3, want.shape[1])
    deq = got["strokes"].astype(np.float32)
    deq[..., :2] /= sc[..., None, None]
    err = np.abs(deq[..., :2] - want[..., :2])
    assert err.max() <= 0.5 / 8.0 + 1e-6
    np.testing.assert_array_equal(deq[..., 2:], want[..., 2:])


def test_train_step_int16_transfer_bitwise_for_integer_origin():
    """A jitted train step fed int16-transferred strokes must produce
    BITWISE the loss of the float32-fed step on an integer-origin
    corpus (the exactness that bfloat16 transfer cannot offer)."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train import make_train_state, make_train_step

    loader, hps = _integer_origin_loader(seed=11)
    ref_loader, _ = _integer_origin_loader(seed=11)
    hps = hps.replace(use_recurrent_dropout=False)
    model = SketchRNN(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh=None)
    with prefetch_batches(loader, mesh=None, depth=1,
                          transfer_dtype="int16") as feeder:
        b_q = feeder.get()
    b_f = ref_loader.random_batch()
    key = jax.random.key(1)
    _, m_q = step(state, b_q, key)
    state2 = make_train_state(model, hps, jax.random.key(0))
    _, m_f = step(state2, b_f, key)
    assert float(m_q["loss"]) == float(m_f["loss"])


def test_train_step_int16_transfer_on_mesh():
    """int16 batches must flow through the sharded (shard_map) train
    step: the transfer_scale [B] leaf shards over the data axis like
    every other batch leaf, and the loss matches the f32 feed."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.parallel.mesh import make_mesh, shard_batch
    from sketch_rnn_tpu.train import make_train_state, make_train_step

    loader, hps = _integer_origin_loader(seed=13)
    ref_loader, _ = _integer_origin_loader(seed=13)
    hps = hps.replace(use_recurrent_dropout=False)
    model = SketchRNN(hps)
    mesh = make_mesh(hps)
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh=mesh)
    b_q = loader.random_batch(int16_scale=loader.scale_factor)
    b_f = ref_loader.random_batch()
    key = jax.random.key(1)
    _, m_q = step(state, shard_batch(b_q, mesh), key)
    state2 = make_train_state(model, hps, jax.random.key(0))
    _, m_f = step(state2, shard_batch(b_f, mesh), key)
    assert float(m_q["loss"]) == float(m_f["loss"])


def test_prefetch_int16_refuses_float_natured_corpus():
    """A corpus whose normalization scale makes 1 raw unit coarse (the
    synthetic corpus: scale ~0.24) must be REFUSED, not silently
    rounded to nothing (r4 review finding: the bench briefly trained
    on strokes quantized to almost-all-zero offsets)."""
    loader, _ = make_loader(seed=2)   # never normalized: scale 1.0
    with pytest.raises(ValueError, match="integer-origin"):
        prefetch_batches(loader, mesh=None, depth=1,
                         transfer_dtype="int16")

    class NoScale:
        pass

    with pytest.raises(ValueError, match="integer-origin"):
        prefetch_batches(NoScale(), mesh=None, depth=1,
                         transfer_dtype="int16")
