"""serve_bench load-generator tests (tier-1-safe: a shrunken smoke).

The wall-clock speedup is noise-prone on a shared 2-core CI box, so
the tier-1 regression signal is the DETERMINISTIC part: the skewed
length mixes, the request accounting, and the device-step ratio (the
scheduling advantage — chunks x K vs sum of per-batch maxima), which
slot recycling must keep well above 1 regardless of timing. The full
``--smoke`` config's >= 2x wall-clock acceptance run stays a script
invocation (seconds, but too timing-sensitive for CI assertion).
"""

import json

import numpy as np
import pytest

from scripts import serve_bench


def test_skewed_lengths_deterministic_and_skewed():
    a = serve_bench.skewed_lengths(256, 4, 160, seed=0)
    b = serve_bench.skewed_lengths(256, 4, 160, seed=0)
    np.testing.assert_array_equal(a, b)
    assert a.min() >= 4 and a.max() <= 160
    # the ISSUE's mix: max ~ 4x mean
    assert 3.0 <= a.max() / a.mean() <= 5.0
    assert not np.array_equal(a, serve_bench.skewed_lengths(
        256, 4, 160, seed=1))


def test_bimodal_lengths_max_4x_mean():
    a = serve_bench.skewed_lengths(1000, 10, 160, seed=0,
                                   mode="bimodal")
    assert set(np.unique(a)) == {10, 160}
    # 20% long / 80% short at lmax/16: max = 4x mean by construction
    assert 3.5 <= a.max() / a.mean() <= 4.5


@pytest.mark.parametrize("dist", ["power", "bimodal"])
def test_serve_bench_end_to_end_small(tmp_path, capsys, dist):
    """A shrunken smoke run: both paths execute, the record is
    well-formed, the step counts verify, and recycling wins the
    deterministic device-step comparison."""
    out = tmp_path / "SB.json"
    rc = serve_bench.main([
        "--smoke", "--slots", "8", "--chunk", "4", "--requests", "64",
        "--min_len", "3", "--max_len", "48", "--len_dist", dist,
        "--out", str(out)])
    assert rc == 0
    rec = json.load(open(out))
    assert rec["kind"] == "serve_bench" and rec["smoke"] is True
    assert rec["n_requests"] == 64 and rec["len_dist"] == dist
    assert rec["engine_sketches_per_sec"] > 0
    assert rec["baseline_sketches_per_sec"] > 0
    assert rec["speedup"] > 0
    # the deterministic scheduling advantage: freeze-until-batch-done
    # burns sum(per-batch max) steps, recycling ~ sum(len)/B — with
    # max/mean >= 4 skew it must stay clearly above 1 even at this
    # shrunken scale (the full smoke config measures ~2.7)
    assert rec["device_step_ratio"] > 1.3
    assert 0 < rec["engine_slot_utilization"] <= 1
