"""serve_bench load-generator tests (tier-1-safe: a shrunken smoke).

The wall-clock speedup is noise-prone on a shared 2-core CI box, so
the tier-1 regression signal is the DETERMINISTIC part: the skewed
length mixes, the request accounting, and the device-step ratio (the
scheduling advantage — chunks x K vs sum of per-batch maxima), which
slot recycling must keep well above 1 regardless of timing. The full
``--smoke`` config's >= 2x wall-clock acceptance run stays a script
invocation (seconds, but too timing-sensitive for CI assertion).
"""

import json

import numpy as np
import pytest

from scripts import serve_bench


def test_skewed_lengths_deterministic_and_skewed():
    a = serve_bench.skewed_lengths(256, 4, 160, seed=0)
    b = serve_bench.skewed_lengths(256, 4, 160, seed=0)
    np.testing.assert_array_equal(a, b)
    assert a.min() >= 4 and a.max() <= 160
    # the ISSUE's mix: max ~ 4x mean
    assert 3.0 <= a.max() / a.mean() <= 5.0
    assert not np.array_equal(a, serve_bench.skewed_lengths(
        256, 4, 160, seed=1))


def test_bimodal_lengths_max_4x_mean():
    a = serve_bench.skewed_lengths(1000, 10, 160, seed=0,
                                   mode="bimodal")
    assert set(np.unique(a)) == {10, 160}
    # 20% long / 80% short at lmax/16: max = 4x mean by construction
    assert 3.5 <= a.max() / a.mean() <= 4.5


def test_serve_bench_fleet_end_to_end_small(tmp_path, capsys):
    """A shrunken fleet sweep (ISSUE 9): curves land per
    (replicas, rate) cell, the in-run parity block passes (bitwise
    placement + arrival invariance), the deterministic step-parallel
    speedup clears the scheduling-math bar at 2 replicas, and the
    existing engine record in --out is PRESERVED (the fleet record
    lands under its own key)."""
    out = tmp_path / "SB.json"
    out.write_text(json.dumps(
        {"kind": "serve_bench", "engine_sketches_per_sec": 123.0}))
    rc = serve_bench.main([
        "--smoke", "--fleet", "--slots", "4", "--chunk", "2",
        "--requests", "48", "--min_len", "2", "--max_len", "16",
        "--replicas", "1,2", "--rates", "0,400", "--out", str(out)])
    assert rc == 0
    doc = json.load(open(out))
    # the pre-existing engine record survived the merge
    assert doc["kind"] == "serve_bench"
    assert doc["engine_sketches_per_sec"] == 123.0
    f = doc["fleet"]
    assert f["kind"] == "serve_fleet" and f["smoke"] is True
    cells = {(c["replicas"], c["offered_rate"]) for c in f["curves"]}
    assert cells == {(1, 0.0), (1, 400.0), (2, 0.0), (2, 400.0)}
    # the parity block ran and passed (a failure raises in-run)
    assert f["parity"]["placement_invariant"] is True
    assert f["parity"]["arrival_invariant"] is True
    assert f["parity"]["replicas_checked"] == [2]
    # the deterministic scheduling-math scaling signal: the fleet's
    # critical path in device steps must drop ~2x at 2 replicas
    # (least-loaded placement splits the skewed mix)
    assert f["scaling"]["2"]["step_parallel"] >= 1.7
    # per-class SLA surface present on every curve point, plus the
    # ISSUE 11 tail-attribution verdict and the exact cost identity
    for c in f["curves"]:
        assert {"interactive", "batch"} == set(c["by_class"])
        assert c["latency_p50_s"] <= c["latency_p99_s"]
        assert c["p99_dom"] in ("queue", "decode")
        cost = c["cost"]
        assert cost["exact"] is True
        assert (cost["steps_attributed"] + cost["steps_idle"]
                == cost["steps_dispatched"])
        assert set(cost["steps_by_class"]) == {"interactive", "batch"}
    assert f["host_parallel_ceiling"] > 0
    # one binary serve_cost history row per capacity arm (ISSUE 11):
    # the exactness signal bench_regress gates; routed to the hermetic
    # smoke history (same tmp_path as the conftest redirect)
    hist = tmp_path / "BENCH_SMOKE_HISTORY.jsonl"
    cost_rows = [r for r in map(json.loads, open(hist))
                 if r.get("kind") == "serve_cost"]
    assert {r["replicas"] for r in cost_rows} == {1, 2}
    for r in cost_rows:
        assert r["ok"] is True
        assert sum(r["steps_by_class"].values()) == r["steps_attributed"]
        assert (r["steps_attributed"] + r["steps_idle"]
                == r["steps_dispatched"])


def test_serve_bench_endpoints_end_to_end_small(tmp_path):
    """A shrunken mixed-endpoint bench (ISSUE 15): all four endpoints
    serve through the endpoint-routed fleet, the offline-parity /
    cost-determinism / compile-accounting blocks hold (a failure
    raises after streaming the rows), per-endpoint latency columns and
    per-class SLO verdicts land in --out under 'endpoints', one binary
    serve_endpoint row per endpoint streams to the hermetic smoke
    history, and pre-existing records in --out are preserved."""
    out = tmp_path / "SB.json"
    out.write_text(json.dumps(
        {"kind": "serve_bench", "engine_sketches_per_sec": 123.0}))
    rc = serve_bench.main([
        "--endpoints", "--smoke", "--slots", "4", "--chunk", "2",
        "--requests", "48", "--unique", "16", "--min_len", "2",
        "--max_len", "10", "--frames", "3", "--out", str(out)])
    assert rc == 0
    doc = json.load(open(out))
    assert doc["engine_sketches_per_sec"] == 123.0  # merge preserved
    e = doc["endpoints"]
    assert e["kind"] == "serve_endpoints" and e["smoke"] is True
    assert set(e["realized_mix"]) == {"generate", "complete",
                                      "reconstruct", "interpolate"}
    assert sum(e["realized_mix"].values()) == 48
    # the deterministic acceptance blocks all held
    p = e["parity"]
    assert p["offline_bitwise"] and p["arrival_invariant"]
    assert p["cost_deterministic"] and not p["failures"]
    assert p["replicas_checked"] == [1, 2]
    c = e["compile"]
    # exactly one encode compile per (pool rows, prefix edge), none on
    # repeat, ZERO compiles of any kind in the measured window
    assert c["encode_compiles"] == len(c["edges"])
    assert len(set(c["geometries"])) == c["encode_compiles"]
    assert c["recompiles_on_repeat"] == 0
    assert c["measured_window"]["jit_cache_miss"] == 0
    assert c["measured_window"]["compile_spans"] == 0
    # per-endpoint latency columns + per-class SLO verdicts
    for ep, cnt in e["realized_mix"].items():
        cell = e["per_endpoint_capacity"][ep]
        assert cell["completed"] == cnt
        assert cell["p50_s"] <= cell["p99_s"]
    assert set(e["slo"]) == {"interactive:latency_s:p95",
                             "batch:latency_s:p99"}
    assert e["cost"]["exact"] is True
    # one binary serve_endpoint row per endpoint, all ok, in the
    # hermetic smoke history
    hist = tmp_path / "BENCH_SMOKE_HISTORY.jsonl"
    rows = [r for r in map(json.loads, open(hist))
            if r.get("kind") == "serve_endpoint"]
    assert {r["endpoint"] for r in rows} == {"generate", "complete",
                                             "reconstruct",
                                             "interpolate"}
    for r in rows:
        assert r["ok"] is True
        assert r["completed"] == e["realized_mix"][r["endpoint"]]
        assert r["class"] in ("interactive", "batch")


def test_serve_bench_speculative_end_to_end_small(tmp_path):
    """A shrunken speculative bench (ISSUE 18): all four arm kinds run
    (legacy baseline, noisy self-draft, exact self-draft, random
    draft), every arm's strokes stay bitwise the legacy engine's, the
    accept/reject sequence replays deterministically, the commit-rate
    gate clears > 1.5 on the bimodal mix, one binary serve_spec row per
    (cell, D) streams to the hermetic smoke history, and pre-existing
    records in --out are preserved."""
    out = tmp_path / "SB.json"
    out.write_text(json.dumps(
        {"kind": "serve_bench", "engine_sketches_per_sec": 123.0}))
    rc = serve_bench.main([
        "--speculative", "--smoke", "--slots", "4", "--chunk", "4",
        "--requests", "16", "--min_len", "4", "--max_len", "32",
        "--depths", "16", "--draft_noise", "0.002", "--out", str(out)])
    assert rc == 0
    doc = json.load(open(out))
    assert doc["engine_sketches_per_sec"] == 123.0  # merge preserved
    s = doc["speculative"]
    assert s["kind"] == "serve_speculative" and s["smoke"] is True
    # the deterministic acceptance signals all held (a failure raises
    # AFTER streaming the rows)
    p = s["parity"]
    assert p["bitwise_vs_legacy"] and p["replay_deterministic"]
    assert not p["failures"]
    # the ISSUE 18 throughput gate: accepted-steps/device-step > 1.5
    assert s["gate"]["metric"] == "accepted_steps_per_device_step"
    assert s["gate"]["pass"] and s["gate"]["best"] > 1.5
    arms = {(a["dec_model"], a["draft"]): a for a in s["arms"]}
    assert set(arms) == {("lstm", "self+noise"), ("lstm", "self"),
                         ("layer_norm", "random")}
    # exact self-draft: the acceptance-1.0 accounting pin, and it
    # saves device steps vs its cell's baseline
    exact = arms[("lstm", "self")]
    assert exact["acceptance_rate"] == 1.0
    assert exact["device_steps_saved"] > 0
    assert (exact["device_steps"]
            < s["baseline"]["lstm"]["device_steps"])
    # random draft: near-zero acceptance, outputs still bitwise (ok)
    assert arms[("layer_norm", "random")]["acceptance_rate"] < 0.5
    for a in s["arms"]:
        assert a["ok"] is True
        assert a["n_requests"] == 16 and a["draft_depth"] == 16
    # legacy baselines can never exceed 1 emitted row per device step
    for b in s["baseline"].values():
        assert b["accepted_steps_per_device_step"] <= 1.0
    # one binary serve_spec row per (cell, D) in the hermetic history
    hist = tmp_path / "BENCH_SMOKE_HISTORY.jsonl"
    rows = [r for r in map(json.loads, open(hist))
            if r.get("kind") == "serve_spec"]
    assert len(rows) == 3
    assert all(r["ok"] is True and r["smoke"] is True for r in rows)
    assert {(r["dec_model"], r["draft"]) for r in rows} == set(arms)


def test_serve_bench_tenants_end_to_end_small(tmp_path):
    """A shrunken multi-tenant bench (ISSUE 19): T delta-paged tenants
    interleave through ONE value-paged fleet with ZERO compiles in the
    measured window (tenant swaps > 0), the shared-prefix radix index
    reports encode computes == distinct keys EXACTLY (and reused rows
    recheck bitwise against a fresh encode), every tenant is bitwise a
    single-tenant fleet on its own checkpoint (shuffled arrival +
    failover-requeue replay included), binary serve_tenant/serve_prefix
    rows stream to the hermetic smoke history, and pre-existing
    records in --out are preserved."""
    out = tmp_path / "SB.json"
    out.write_text(json.dumps(
        {"kind": "serve_bench", "engine_sketches_per_sec": 123.0}))
    # --tenant_mix without the base stream: one fewer single-tenant
    # reference fleet to build — the committed T=4 bench covers the
    # base tenant; this tier-1 pin budgets compiles, not coverage
    rc = serve_bench.main([
        "--tenants", "2", "--smoke", "--slots", "4", "--chunk", "2",
        "--requests", "16", "--unique", "4", "--min_len", "2",
        "--max_len", "8", "--tenant_mix", "tn0:1,tn1:1",
        "--out", str(out)])
    assert rc == 0
    doc = json.load(open(out))
    assert doc["engine_sketches_per_sec"] == 123.0  # merge preserved
    t = doc["tenants"]
    assert t["kind"] == "serve_tenants" and t["smoke"] is True
    assert t["n_tenants"] == 2
    assert sum(t["realized_tenants"].values()) == 16
    assert set(t["realized_tenants"]) == {"tn0", "tn1"}
    # the deterministic acceptance blocks all held (a failure raises
    # AFTER streaming the rows)
    p = t["parity"]
    assert not p["failures"]
    assert all(p["bitwise_by_tenant"].values())
    assert p["shuffle_failover_bitwise"] is True
    assert p["replicas_dead_in_failover_arm"] == 1
    # zero compiles in the measured window while tenants actually flip
    cap = t["capacity"]
    assert cap["tenant_swaps"] > 0
    assert cap["measured_window"]["jit_cache_miss"] == 0
    assert cap["measured_window"]["compile_spans"] == 0
    assert cap["cost"]["exact"] is True
    # the exact encode-reuse ledger: computes == distinct == predicted,
    # nothing encoded twice, and the reuse recheck ran per tenant
    er = t["encode_reuse"]
    assert er["computes"] == er["distinct"] == er["predicted_distinct"]
    assert er["computes"] + er["reuses"] == er["encode_jobs"]
    assert er["rechecked_bitwise"] == len(t["realized_tenants"])
    # paged adapters: tn0 is the zero-delta proof, tn1 the full delta
    assert t["adapters"]["tn0"]["pages"] == 0
    assert t["adapters"]["tn1"]["pages"] > 0
    assert t["memory"]["resident_bytes"] < t["memory"]["full_bytes"]
    # per-tenant SLO attainment + shed reported separately per tenant
    assert set(t["load_arm"]["slo_by_tenant"]) == {"tn0", "tn1"}
    # one binary serve_tenant row per tenant + one serve_prefix row in
    # the hermetic smoke history, streamed before any raise
    hist = tmp_path / "BENCH_SMOKE_HISTORY.jsonl"
    rows = [json.loads(line) for line in open(hist)]
    trows = [r for r in rows if r.get("kind") == "serve_tenant"]
    assert {r["tenant"] for r in trows} == {"tn0", "tn1"}
    assert all(r["ok"] and r["bitwise_isolated"] for r in trows)
    prows = [r for r in rows if r.get("kind") == "serve_prefix"]
    assert len(prows) == 1 and prows[0]["ok"] is True
    assert prows[0]["window_compiles"] == 0


@pytest.mark.parametrize("dist", ["power", "bimodal"])
def test_serve_bench_end_to_end_small(tmp_path, capsys, dist):
    """A shrunken smoke run: both paths execute, the record is
    well-formed, the step counts verify, and recycling wins the
    deterministic device-step comparison."""
    out = tmp_path / "SB.json"
    rc = serve_bench.main([
        "--smoke", "--slots", "8", "--chunk", "4", "--requests", "64",
        "--min_len", "3", "--max_len", "48", "--len_dist", dist,
        "--out", str(out)])
    assert rc == 0
    rec = json.load(open(out))
    assert rec["kind"] == "serve_bench" and rec["smoke"] is True
    assert rec["n_requests"] == 64 and rec["len_dist"] == dist
    assert rec["engine_sketches_per_sec"] > 0
    assert rec["baseline_sketches_per_sec"] > 0
    assert rec["speedup"] > 0
    # the deterministic scheduling advantage: freeze-until-batch-done
    # burns sum(per-batch max) steps, recycling ~ sum(len)/B — with
    # max/mean >= 4 skew it must stay clearly above 1 even at this
    # shrunken scale (the full smoke config measures ~2.7)
    assert rec["device_step_ratio"] > 1.3
    assert 0 < rec["engine_slot_utilization"] <= 1


def test_serve_bench_traffic_end_to_end_small(tmp_path):
    """A shrunken traffic grid (ISSUE 12): all four cached-vs-uncached
    x fixed-vs-autoscaled arms run in-process, the parity block holds
    (cache hits bitwise == recomputation, strokes invariant under
    mid-run resizes, fixed arms deterministic across replays), the
    modeled curves land per (rate, cache, autoscale) cell, the grid's
    serve_cache/serve_autoscale rows stream to the hermetic smoke
    history, the scale-decision timeline is reproducible from the
    trace seed and lands in RUN.json, and the existing records in
    --out are preserved."""
    out = tmp_path / "SB.json"
    out.write_text(json.dumps(
        {"kind": "serve_bench", "engine_sketches_per_sec": 123.0,
         "fleet": {"kind": "serve_fleet"}}))
    rc = serve_bench.main([
        "--traffic", "--smoke", "--slots", "4", "--chunk", "2",
        "--requests", "96", "--unique", "24", "--min_len", "2",
        "--max_len", "10", "--rate_mults", "1,2",
        "--out", str(out), "--manifest_dir", str(tmp_path)])
    assert rc == 0
    doc = json.load(open(out))
    # pre-existing records survived the merge
    assert doc["engine_sketches_per_sec"] == 123.0
    assert doc["fleet"]["kind"] == "serve_fleet"
    t = doc["traffic"]
    assert t["kind"] == "serve_traffic" and t["smoke"] is True
    assert t["trace"] == "flash" and t["distinct"] <= 24
    # the parity block: every deterministic acceptance signal held
    # (a failure would also have raised after streaming the rows)
    p = t["parity"]
    assert p["cache_bitwise"] and p["resize_invariant"]
    assert p["fixed_arm_deterministic"] and not p["failures"]
    assert p["steps_saved_fixed"] > 0
    assert p["steps_saved_autoscaled"] > 0
    assert t["plan_reproducible"] is True
    # modeled curves: one row per (rate_mult, cache, autoscale) cell
    cells = {(c["rate_mult"], c["cache"], c["autoscale"])
             for c in t["curves"]}
    assert cells == {(m, c, a) for m in (1.0, 2.0)
                     for c in (False, True) for a in (False, True)}
    # the flash-crowd acceptance: autoscaled shed strictly below the
    # fixed fleet's on the uncached base-rate pair
    base = {(c["cache"], c["autoscale"]): c for c in t["curves"]
            if c["rate_mult"] == 1.0}
    assert (base[(False, True)]["shed_frac"]
            < base[(False, False)]["shed_frac"])
    assert base[(False, True)]["fleet_size_max"] > 1
    # cache-on arms: strictly fewer device steps at equal completion
    n = t["n_requests"]
    meas = {(m["cache"], m["autoscale"]): m for m in t["measured"]}
    assert all(m["completed"] == n for m in t["measured"])
    for auto in (False, True):
        assert (meas[(True, auto)]["device_steps"]
                < meas[(False, auto)]["device_steps"])
        # hit rate is exact scheduling math: (n - distinct) / n
        assert meas[(True, auto)]["hit_rate"] == round(
            (n - t["distinct"]) / n, 4)
    # the autoscaled arm really resized and realized its plan
    auto_arm = meas[(False, True)]
    assert auto_arm["scale_log"]
    assert auto_arm["planned_actions"]
    # history rows: one serve_cache per autoscale arm, one
    # serve_autoscale per cache arm, all ok (the bench_regress gate's
    # binary signal), routed to the hermetic smoke history
    hist = tmp_path / "BENCH_SMOKE_HISTORY.jsonl"
    rows = [r for r in map(json.loads, open(hist))]
    cache_rows = [r for r in rows if r.get("kind") == "serve_cache"]
    scale_rows = [r for r in rows if r.get("kind") == "serve_autoscale"]
    assert {r["autoscale"] for r in cache_rows} == {False, True}
    assert {r["cache"] for r in scale_rows} == {False, True}
    for r in cache_rows:
        assert r["ok"] is True and r["steps_saved"] > 0
    for r in scale_rows:
        assert r["ok"] is True and r["plan_reproducible"] is True
    # RUN.json records the scale-decision timeline (ISSUE 12 contract)
    man = json.load(open(tmp_path / "RUN.json"))
    assert man["kind"] == "serve_traffic"
    tm = man["traffic"]
    assert tm["plan_reproducible"] is True
    assert tm["actions"] and tm["fleet_size_by_epoch"]
    assert max(tm["fleet_size_by_epoch"]) > 1
    assert tm["max_replicas_reached"] > 1
    assert [a["action"] for a in tm["actions"]].count("up") > 0
    assert tm["n_actions"] == len(tm["actions"])
