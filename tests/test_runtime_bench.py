"""Unified dispatch runtime bench wiring (ISSUE 20 satellite: CI).

``test_runtime_smoke`` runs the REAL six-arm matrix at tiny geometry —
the tier-1 proof that the ``GeometryRunScheduler`` is bitwise the five
legacy schedules it replaced and that buffer donation aliases the train
state / serve carry into the compiled programs. The gate tests are
pure: they pin that ``kind=runtime`` rows are a binary kind (keyed per
scheduler site, metric 1.0/0.0 from ``ok``) and that a future
``ok: false`` row actually gates via bench_regress.
"""

import json

import scripts.bench_regress as bench_regress
import scripts.runtime_bench as runtime_bench
from scripts.bench_summary import key_of, metric_of


def test_runtime_smoke(capsys):
    rc = runtime_bench.main(["--smoke"])
    assert rc == 0
    rows = [json.loads(l) for l in capsys.readouterr().out.splitlines()
            if l.startswith("{")]
    by_site = {r["site"]: r for r in rows}
    assert set(by_site) == set(runtime_bench.ARMS)
    assert all(r["ok"] is True and r["kind"] == "runtime"
               and r["smoke"] is True for r in rows)
    # the five port pins: each site's unified schedule bitwise legacy
    assert by_site["train_stack"]["state_bitwise"] is True
    assert by_site["train_stack"]["metrics_bitwise"] is True
    assert by_site["train_stack"]["ledger_exact"] is True
    assert by_site["train_stack"]["no_recompile"] is True
    assert by_site["eval_sweep"]["spans_bitwise"] is True
    assert by_site["eval_sweep"]["rows_bitwise"] is True
    ep = by_site["engine_pipeline"]
    assert ep["counts_exact"] is True and ep["solo_bitwise"] is True
    # zero host syncs between dispatches: exactly one sync per chunk
    assert ep["host_syncs"] == ep["chunks"] == ep["dispatches"]
    assert ep["dispatches_saved"] > 0
    assert by_site["fleet_burst"]["configs"] >= 4
    eb = by_site["encode_burst"]
    assert eb["schedule_bitwise"] is True and eb["edges"] >= 2
    # donation machinery: buffers really aliased, effective peak drops
    don = by_site["donation"]
    assert don["train_donated_alias_bytes"] > 0
    assert don["serve_chunk_donated_alias_bytes"] > 0
    assert don["train_effective_reduction"] > 0


def _row(ok, site="train_stack"):
    return {"kind": "runtime", "site": site, "device_kind": "cpu",
            "smoke": True, "ok": ok}


def test_runtime_rows_key_and_gate_like_binary_kinds(tmp_path, capsys):
    a = _row(True)
    assert key_of(a) == key_of(_row(False))
    assert key_of(a) != key_of(_row(True, site="engine_pipeline"))
    # never pools with the other binary kinds
    assert key_of(a) != key_of({"kind": "rollout", "site": "train_stack",
                                "device_kind": "cpu", "ok": True})
    assert metric_of(a) == 1.0
    assert metric_of(_row(False)) == 0.0
    hist = tmp_path / "hist.jsonl"
    hist.write_text("".join(json.dumps(_row(True)) + "\n"
                            for _ in range(4)))
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps(_row(False)) + "\n")
    assert bench_regress.main([f"--fresh={bad}",
                               f"--history={hist}"]) == 1
    assert "REGRESS" in capsys.readouterr().out


def test_committed_runtime_rows_in_band():
    """The committed smoke history holds the runtime rows this PR
    landed and they end in-band (the bench_regress --smoke self-check
    covers them like every other binary kind)."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "BENCH_SMOKE_HISTORY.jsonl")) as f:
        rows = [json.loads(l) for l in f if '"runtime"' in l]
    rows = [r for r in rows if r.get("kind") == "runtime"]
    assert len(rows) >= 4
    assert {r["site"] for r in rows} >= set(runtime_bench.ARMS)
    last = {}
    for r in rows:
        last[r["site"]] = r
    assert all(r["ok"] is True for r in last.values())
