"""Result-cache tests (ISSUE 12): content addressing, keyspace
isolation, LRU determinism, bitwise hit parity and the trace-link
contract.

The load-bearing invariant mirrors the serving suite's: the cache
changes WHERE bytes come from (host store vs device), never WHAT —
a hit is the origin computation's strokes bitwise, keyed by request
CONTENT only (scheduling metadata must never fragment the keyspace,
and different checkpoints/configs must never collide).
"""

import dataclasses

import jax
import numpy as np
import pytest

from sketch_rnn_tpu.serve import Request, ResultCache, request_fingerprint
from sketch_rnn_tpu.serve.cache import CacheEntry


def _req(i: int, z_dim: int = 6, cap: int = 4, **kw) -> Request:
    rng = np.random.default_rng(i)
    return Request(key=jax.random.key(1000 + i),
                   z=rng.standard_normal(z_dim).astype(np.float32),
                   temperature=0.8, max_len=cap, **kw)


def _entry(nbytes: int = 40, uid: int = 0) -> CacheEntry:
    return CacheEntry(np.zeros((nbytes // 20, 5), np.float32),
                      length=1, steps=1, origin_uid=uid)


# -- content addressing ------------------------------------------------------


def test_fingerprint_is_content_only():
    """Scheduling metadata (uid, class, queue_pos, enqueue_ts, attempt)
    changes WHEN a sketch is computed, never WHAT — it must not enter
    the fingerprint."""
    a = _req(0, uid=1)
    b = dataclasses.replace(_req(0), uid=99, cls="interactive",
                            queue_pos=7, enqueue_ts=123.0, attempt=2)
    assert request_fingerprint(a) == request_fingerprint(b)


def test_fingerprint_covers_every_content_field():
    base = _req(0)
    fp = request_fingerprint(base)
    variants = [
        dataclasses.replace(base, key=jax.random.key(2)),
        dataclasses.replace(base, z=base.z + 1.0),
        dataclasses.replace(base, z=None),
        dataclasses.replace(base, label=3),
        dataclasses.replace(base, temperature=0.9),
        dataclasses.replace(base, max_len=5),
    ]
    fps = [request_fingerprint(v) for v in variants]
    assert all(f != fp for f in fps)
    assert len(set(fps)) == len(fps)


def test_keyspace_isolation_across_checkpoint_and_config():
    """ISSUE 12 acceptance: a different checkpoint or config_hash can
    NEVER collide — the namespace is inside the hash."""
    r = _req(0)
    fps = {request_fingerprint(r, config_hash=c, ckpt_id=k)
           for c in ("", "cfgA", "cfgB") for k in ("", "ck1", "ck2")}
    assert len(fps) == 9
    # and the namespace split is unambiguous (no concat collision)
    assert (request_fingerprint(r, config_hash="ab", ckpt_id="c")
            != request_fingerprint(r, config_hash="a", ckpt_id="bc"))


def test_generate_fingerprints_unchanged_by_endpoint_extension():
    """ISSUE 15 satellite: a plain generate request's fingerprint is
    BYTE-IDENTICAL to the pre-endpoint algorithm — the cache-key
    extension can never cold-start the existing keyspace. The old
    algorithm is re-implemented inline as the pin."""
    import hashlib

    def legacy_fingerprint(req, config_hash="", ckpt_id=""):
        h = hashlib.blake2b(digest_size=16)
        h.update(config_hash.encode())
        h.update(b"\x00")
        h.update(ckpt_id.encode())
        h.update(b"\x00")
        key_data = np.asarray(jax.random.key_data(req.key))
        h.update(str(key_data.dtype).encode() + b"|")
        h.update(key_data.tobytes())
        if req.z is None:
            h.update(b"z:none")
        else:
            h.update(np.asarray(req.z, np.float32).tobytes())
        h.update(f"|{int(req.label)}|{float(req.temperature)!r}|"
                 f"{req.max_len}".encode())
        return h.digest()

    for req in (_req(0), _req(1, cap=9),
                dataclasses.replace(_req(2), z=None),
                dataclasses.replace(_req(3), label=4,
                                    temperature=1.25)):
        assert request_fingerprint(req, "cfg", "ck") == \
            legacy_fingerprint(req, "cfg", "ck")


def _pfx(i, n=4):
    rng = np.random.default_rng(700 + i)
    p = rng.standard_normal((n, 3)).astype(np.float32)
    p[-1, 2] = 1.0
    return p


def test_endpoint_prefix_fields_are_collision_proof():
    """ISSUE 15: (endpoint, prefix bytes, frames) live inside the hash
    — two endpoints sharing content, two prefixes differing in one
    byte, swapped interpolation order, or a different frame count can
    never collide; scheduling metadata still never fragments the
    keyspace, and the planner-DERIVED decode state (z / init_carry) is
    deliberately excluded."""
    base = dataclasses.replace(_req(0), z=None, endpoint="complete",
                               prefix=_pfx(0))
    fps = [request_fingerprint(base)]
    variants = [
        dataclasses.replace(base, endpoint="reconstruct"),
        dataclasses.replace(base, prefix=_pfx(1)),
        dataclasses.replace(base, prefix=_pfx(0)[:3]),
        dataclasses.replace(_req(0), z=None),   # plain generate
        dataclasses.replace(base, endpoint="interpolate",
                            prefix=(_pfx(0), _pfx(1)), frames=4),
        dataclasses.replace(base, endpoint="interpolate",
                            prefix=(_pfx(1), _pfx(0)), frames=4),
        dataclasses.replace(base, endpoint="interpolate",
                            prefix=(_pfx(0), _pfx(1)), frames=5),
    ]
    fps += [request_fingerprint(v) for v in variants]
    assert len(set(fps)) == len(fps)
    # prefix content differing by ONE value differs
    tweaked = _pfx(0).copy()
    tweaked[1, 0] += 1.0
    assert request_fingerprint(
        dataclasses.replace(base, prefix=tweaked)) != fps[0]
    # scheduling metadata: still excluded
    assert request_fingerprint(dataclasses.replace(
        base, uid=99, cls="interactive", queue_pos=3, attempt=2,
        enqueue_ts=1.0)) == fps[0]
    # planner-derived state: excluded (stamping z/init_carry after the
    # encode phase must not change the content identity)
    stamped = dataclasses.replace(
        base, z=np.ones((6,), np.float32),
        init_carry=np.ones((32,), np.float32),
        init_prev=np.ones((5,), np.float32))
    assert request_fingerprint(stamped) == fps[0]


# -- bounded LRU -------------------------------------------------------------


def test_lru_eviction_order_is_deterministic():
    def run():
        cache = ResultCache(max_entries=3)
        fps = [bytes([i]) for i in range(5)]
        for i in range(4):
            cache.put(fps[i], type("R", (), {
                "strokes5": np.zeros((2, 5), np.float32),
                "length": 2, "steps": 2, "uid": i})())
        # 0 evicted (oldest); touching 1 makes 2 the next victim
        assert cache.get(fps[0]) is None
        assert cache.get(fps[1]) is not None
        cache.put(fps[4], type("R", (), {
            "strokes5": np.zeros((2, 5), np.float32),
            "length": 2, "steps": 2, "uid": 4})())
        return list(cache.keys()), cache.evictions

    keys1, ev1 = run()
    keys2, ev2 = run()
    assert keys1 == keys2 == [bytes([3]), bytes([1]), bytes([4])]
    assert ev1 == ev2 == 2


def test_byte_bound_evicts_and_counts():
    cache = ResultCache(max_bytes=100)
    for i in range(4):  # 40B entries: the 3rd insert evicts the 1st
        cache.put(bytes([i]), type("R", (), {
            "strokes5": np.zeros((2, 5), np.float32),
            "length": 2, "steps": 2, "uid": i})())
    assert len(cache) == 2 and cache.bytes == 80
    assert cache.evictions == 2
    assert cache.stats()["bytes"] == 80


def test_put_keeps_first_on_duplicate_fingerprint():
    cache = ResultCache()
    first = type("R", (), {"strokes5": np.ones((2, 5), np.float32),
                           "length": 2, "steps": 2, "uid": 7})()
    second = type("R", (), {"strokes5": np.zeros((2, 5), np.float32),
                            "length": 2, "steps": 2, "uid": 8})()
    cache.put(b"fp", first)
    cache.put(b"fp", second)
    entry = cache.get(b"fp")
    assert entry.origin_uid == 7
    np.testing.assert_array_equal(entry.strokes5, first.strokes5)


def test_stats_hit_rate_counts_coalesced_as_served():
    cache = ResultCache()
    cache.put(b"a", type("R", (), {
        "strokes5": np.zeros((2, 5), np.float32),
        "length": 2, "steps": 2, "uid": 0})())
    assert cache.get(b"a") is not None      # hit
    assert cache.get(b"b") is None          # miss
    cache.note_coalesced()                  # a repeat that coalesced
    s = cache.stats()
    assert s["hits"] == 1 and s["misses"] == 1 and s["coalesced"] == 1
    assert s["lookups"] == 2
    assert s["hit_rate"] == round(2 / 2, 4)  # (hits+coalesced)/lookups


def test_bounds_validation_and_clear():
    with pytest.raises(ValueError, match="bounds"):
        ResultCache(max_entries=-1)
    cache = ResultCache()
    cache.put(b"a", type("R", (), {
        "strokes5": np.zeros((2, 5), np.float32),
        "length": 2, "steps": 2, "uid": 0})())
    cache.get(b"a")
    cache.clear()
    s = cache.stats()
    assert s["entries"] == s["hits"] == s["misses"] == 0
    assert cache.bytes == 0


def test_cache_counters_mirror_into_telemetry():
    """The ledger-as-view discipline: the exact internal counters are
    authoritative; an enabled core mirrors them as cat=serve counters
    (which /metrics renders as sketch_rnn_serve_cache_* for free)."""
    from sketch_rnn_tpu.utils import telemetry as tele

    tel = tele.configure(trace_dir=None)
    try:
        cache = ResultCache(max_entries=1)
        mk = lambda u: type("R", (), {  # noqa: E731
            "strokes5": np.zeros((2, 5), np.float32),
            "length": 2, "steps": 2, "uid": u})()
        cache.put(b"a", mk(0))
        cache.get(b"a")
        cache.get(b"b")
        cache.note_coalesced()
        cache.put(b"b", mk(1))          # evicts a
        counters = tel.counters()
        assert counters[("serve", "cache_hit")] == 1
        assert counters[("serve", "cache_miss")] == 1
        assert counters[("serve", "cache_coalesced")] == 1
        assert counters[("serve", "cache_evict")] == 1
        # the gauge holds its latest sample in the counters store
        assert counters[("serve", "cache_bytes")] == 40.0
    finally:
        tele.disable()


# -- the live hit path (one tiny jax model) ----------------------------------


@pytest.fixture(scope="module")
def tiny_setup():
    from sketch_rnn_tpu.config import HParams
    from sketch_rnn_tpu.models.vae import SketchRNN

    hps = HParams(batch_size=8, max_seq_len=24, enc_rnn_size=12,
                  dec_rnn_size=16, z_size=6, num_mixture=3,
                  serve_slots=2, serve_chunk=2)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    return hps, model, params


def test_hit_is_bitwise_recomputation_and_zero_steps(tiny_setup):
    """THE cache acceptance pin: a store hit and a coalesced repeat
    both return the origin computation's strokes bitwise, marked
    cached=True with zero attributed device steps — and a cache-less
    recomputation of the same content produces the identical bytes."""
    from sketch_rnn_tpu.serve import ServeFleet

    hps, model, params = tiny_setup
    cache = ResultCache(config_hash="cfg", ckpt_id="ck")
    fleet = ServeFleet(model, hps, params, replicas=1, cache=cache)
    try:
        fleet.submit(dataclasses.replace(_req(7), uid=0))
        fleet.submit(dataclasses.replace(_req(7), uid=1))  # coalesces
        fleet.start()
        assert fleet.drain(timeout=120)
        fleet.submit(dataclasses.replace(_req(7), uid=2))  # store hit
        assert fleet.drain(timeout=120)
        res = fleet.results
        st = cache.stats()
        steps_cached = fleet.summary()["total_device_steps"]
    finally:
        fleet.close()
    # the coalesced repeat ticked a store miss before attaching (the
    # documented stats semantics), so misses = primary + coalesced
    assert st["hits"] == 1 and st["coalesced"] == 1 and st["misses"] == 2
    assert st["hit_rate"] == round(2 / 3, 4)
    for uid in (1, 2):
        r = res[uid]["result"]
        assert r.cached and r.attributed_steps == 0
        np.testing.assert_array_equal(r.strokes5,
                                      res[0]["result"].strokes5)
        assert res[uid]["origin_uid"] == 0
    assert not res[0]["result"].cached
    # recomputation without a cache: identical bytes, more device work
    fleet2 = ServeFleet(model, hps, params, replicas=1)
    try:
        for uid in range(3):
            fleet2.submit(dataclasses.replace(_req(7), uid=uid))
        fleet2.start()
        assert fleet2.drain(timeout=120)
        for uid in range(3):
            np.testing.assert_array_equal(
                fleet2.results[uid]["result"].strokes5,
                res[uid]["result"].strokes5)
        assert fleet2.summary()["total_device_steps"] > steps_cached
    finally:
        fleet2.close()


def test_draft_on_and_draft_off_share_the_cache(tiny_setup):
    """ISSUE 18 satellite: speculative decoding changes how FAST rows
    are produced, never WHAT — draft config is engine state, not
    request content, so a draft-on fleet's fills hit for a draft-off
    fleet at the SAME fingerprints, bitwise, with zero attributed
    device steps (fingerprints never hash draft config, so the
    reverse direction shares them by construction)."""
    from sketch_rnn_tpu.models.draft import self_draft_params
    from sketch_rnn_tpu.serve import ServeFleet

    hps, model, params = tiny_setup
    hps = hps.replace(draft_rnn_size=hps.dec_rnn_size)
    dp = self_draft_params(params, hps, key=jax.random.key(9),
                           noise=0.05)
    cache = ResultCache(config_hash="cfg", ckpt_id="ck")
    fleet = ServeFleet(model, hps, params, replicas=1, cache=cache,
                       draft_params=dp, draft_depth=4)
    try:
        fleet.submit(dataclasses.replace(_req(11), uid=0))
        fleet.start()
        assert fleet.drain(timeout=120)
        fill = fleet.results[0]["result"]
    finally:
        fleet.close()
    assert not fill.cached
    fleet2 = ServeFleet(model, hps, params, replicas=1, cache=cache)
    try:
        fleet2.submit(dataclasses.replace(_req(11), uid=1))
        fleet2.start()
        assert fleet2.drain(timeout=120)
        hit = fleet2.results[1]["result"]
        origin = fleet2.results[1]["origin_uid"]
    finally:
        fleet2.close()
    assert hit.cached and hit.attributed_steps == 0
    assert origin == 0
    np.testing.assert_array_equal(hit.strokes5, fill.strokes5)
    assert cache.stats()["hits"] == 1


def test_cached_request_carries_trace_link_to_origin(tiny_setup):
    """ISSUE 12 trace contract: a cached request's tree is fresh (its
    own trace id, a root span over its own clock) and its cache_hit
    instant names the ORIGIN computation's uid and trace id."""
    from sketch_rnn_tpu.serve import ServeFleet
    from sketch_rnn_tpu.utils import telemetry as tele
    from sketch_rnn_tpu.utils.telemetry import request_trace_id

    hps, model, params = tiny_setup
    cache = ResultCache()
    fleet = ServeFleet(model, hps, params, replicas=1, cache=cache)
    tel = tele.configure(trace_dir=None)
    try:
        fleet.submit(dataclasses.replace(_req(3), uid=0))
        fleet.start()
        assert fleet.drain(timeout=120)
        fleet.submit(dataclasses.replace(_req(3), uid=1))  # store hit
        assert fleet.drain(timeout=120)
        evs = tel.events()
    finally:
        fleet.close()
        tele.disable()
    hits = [e for e in evs if e.get("name") == "cache_hit"
            and e.get("type") == "instant"]  # not the mirrored counter
    assert len(hits) == 1
    hit = hits[0]
    assert hit["args"]["uid"] == 1
    assert hit["args"]["origin_uid"] == 0
    assert hit["args"]["origin_trace"] == request_trace_id(0)
    # the hit rides the CACHED request's own (fresh) trace tree
    assert hit["trace"]["id"] == request_trace_id(1)
    roots = [e for e in evs if e.get("name") == "request"
             and e.get("trace", {}).get("id") == request_trace_id(1)]
    assert len(roots) == 1 and roots[0]["args"]["cached"] is True
    # and the cached complete event reports zero attributed steps
    comp = [e for e in evs if e.get("name") == "complete"
            and e["args"]["uid"] == 1]
    assert comp[0]["args"]["cached"] is True
    assert comp[0]["args"]["attributed_steps"] == 0


def test_failed_primary_fails_coalesced_waiters(tiny_setup):
    """A coalesced repeat whose primary exhausts its retry budget must
    land in `failed` WITH it (drain completes and reports honestly),
    never wait forever on a fill that cannot come."""
    from sketch_rnn_tpu.serve import ServeFleet
    from sketch_rnn_tpu.utils import faults

    hps, model, params = tiny_setup
    cache = ResultCache()
    faults.configure("fleet.worker.r0@0")
    try:
        fleet = ServeFleet(model, hps, params, replicas=2,
                           retry_budget=0, retry_backoff_s=0.0,
                           cache=cache)
        # two contents, one repeat each — one primary lands on the
        # doomed replica 0, and its waiter must fail with it
        for uid, content in ((0, 5), (1, 6), (2, 5), (3, 6)):
            fleet.submit(dataclasses.replace(_req(content), uid=uid))
        with fleet:
            assert fleet.drain(timeout=120)
            failed = fleet.failed
            results = fleet.results
    finally:
        faults.disable()
    assert set(failed) | set(results) == {0, 1, 2, 3}
    assert failed  # replica 0's primary (and its waiter) died
    waiter_reasons = [rec["reason"] for rec in failed.values()
                     if "coalesced onto failed" in rec["reason"]]
    primary_reasons = [rec["reason"] for rec in failed.values()
                      if "retry budget" in rec["reason"]]
    assert len(waiter_reasons) == len(primary_reasons)
    # completed repeats (the surviving replica's pair) stayed bitwise
    for uid, rec in results.items():
        if rec.get("cached"):
            origin = rec["origin_uid"]
            np.testing.assert_array_equal(
                rec["result"].strokes5,
                results[origin]["result"].strokes5)


def test_tenant_namespaces_are_collision_proof(tiny_setup):
    """ISSUE 19 satellite: two tenants submitting BYTE-IDENTICAL
    requests occupy two distinct cache fingerprints (the ckpt_id
    namespace), so one tenant can never be served another tenant's
    strokes — and a tenant's store hit is bitwise the computation its
    OWN adapter produced."""
    from sketch_rnn_tpu.serve import ServeFleet, TenantStore

    hps, model, params = tiny_setup
    base = jax.tree_util.tree_map(np.asarray, params)
    store = TenantStore(base, base_ckpt_id="ck")
    rng = np.random.default_rng(5)
    tuned = dict(base)
    tuned["out_w"] = (base["out_w"] + 0.05 * rng.standard_normal(
        base["out_w"].shape)).astype(np.float32)
    store.register("acme", tuned)

    # unit pin: identical content, distinct namespaces
    r = _req(7)
    cache = ResultCache(config_hash="cfg")
    assert (cache.fingerprint(r, ckpt_id=store.ckpt_id_of(""))
            != cache.fingerprint(r, ckpt_id=store.ckpt_id_of("acme")))

    fleet = ServeFleet(model, hps, base, replicas=1, cache=cache,
                       tenants=store)
    try:
        # same bytes, different tenants: BOTH must compute (miss)
        fleet.submit(dataclasses.replace(_req(7), uid=0, tenant=""))
        fleet.submit(dataclasses.replace(_req(7), uid=1,
                                         tenant="acme"))
        fleet.start()
        assert fleet.drain(timeout=120)
        assert cache.stats()["hits"] == 0
        assert cache.stats()["misses"] == 2
        # the repeat hits ITS OWN tenant's fill, bitwise
        fleet.submit(dataclasses.replace(_req(7), uid=2,
                                         tenant="acme"))
        assert fleet.drain(timeout=120)
        res = fleet.results
    finally:
        fleet.close()
    assert cache.stats()["hits"] == 1
    hit = res[2]["result"]
    assert hit.cached and res[2]["origin_uid"] == 1
    np.testing.assert_array_equal(hit.strokes5,
                                  res[1]["result"].strokes5)
    assert hit.ckpt_id == "ck+acme"
    assert res[0]["result"].ckpt_id == "ck"
    # the adapter really changed the computation the namespaces guard
    assert (res[0]["result"].strokes5.tobytes()
            != res[1]["result"].strokes5.tobytes())
