"""Golden-value tests: each cell vs an independent numpy reference
(SURVEY.md §4 test strategy)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sketch_rnn_tpu.ops import (
    HyperLSTMCell, LSTMCell, LayerNormLSTMCell, bidirectional_rnn,
    make_cell, make_dropout_masks, run_rnn)
from sketch_rnn_tpu.ops.rnn import final_hidden


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def np_layer_norm(x, gamma, beta, eps=1e-6):
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    return (x - mean) / np.sqrt(var + eps) * gamma + beta


def np_lstm_step(p, c, h, x, forget_bias=1.0, mask=None):
    pre = x @ p["wx"] + h @ p["wh"] + p["b"]
    i, g, f, o = np.split(pre, 4, axis=-1)
    g = np.tanh(g)
    if mask is not None:
        g = g * mask
    new_c = c * sigmoid(f + forget_bias) + sigmoid(i) * g
    new_h = np.tanh(new_c) * sigmoid(o)
    return new_c, new_h


def np_ln_lstm_step(p, c, h, x, forget_bias=1.0):
    pre = x @ p["wx"] + h @ p["wh"]
    chunks = np.split(pre, 4, axis=-1)
    gates = [np_layer_norm(chunks[j], p["ln_gamma"][j], p["ln_beta"][j])
             for j in range(4)]
    i, g, f, o = gates
    new_c = c * sigmoid(f + forget_bias) + sigmoid(i) * np.tanh(g)
    normed = np_layer_norm(new_c, p["lnc_gamma"], p["lnc_beta"])
    new_h = np.tanh(normed) * sigmoid(o)
    return new_c, new_h


def np_hyper_scales(p, hyper_h, path, e):
    z = hyper_h @ p[f"w_hz_{path}"]
    if path != "b":
        z = z + p[f"b_hz_{path}"]
    z = z.reshape(z.shape[0], 4, e)
    return np.einsum("bje,jeh->bjh", z, p[f"w_zd_{path}"])


def np_hyper_step(p, carry, x, e, forget_bias=1.0):
    (c, h), (hc, hh_state) = carry
    hyper_in = np.concatenate([x, h], -1)
    hc, hh_state = np_lstm_step(p["hyper"], hc, hh_state, hyper_in,
                                forget_bias)
    hyper_h = hh_state
    hdim = c.shape[-1]
    xh = (x @ p["wx"]).reshape(x.shape[0], 4, hdim)
    hhp = (h @ p["wh"]).reshape(x.shape[0], 4, hdim)
    b4 = p["b"].reshape(4, hdim)
    pre = (np_hyper_scales(p, hyper_h, "x", e) * xh
           + np_hyper_scales(p, hyper_h, "h", e) * hhp
           + np_hyper_scales(p, hyper_h, "b", e) + b4)
    gates = [np_layer_norm(pre[:, j], p["ln_gamma"][j], p["ln_beta"][j])
             for j in range(4)]
    i, g, f, o = gates
    new_c = c * sigmoid(f + forget_bias) + sigmoid(i) * np.tanh(g)
    normed = np_layer_norm(new_c, p["lnc_gamma"], p["lnc_beta"])
    new_h = np.tanh(normed) * sigmoid(o)
    return ((new_c, new_h), (hc, hh_state)), new_h


def _np_params(params):
    return jax.tree.map(np.asarray, params)


B, T, D, H = 3, 6, 5, 8


@pytest.fixture
def xs():
    return np.random.default_rng(0).normal(size=(T, B, D)).astype(np.float32)


def test_lstm_matches_numpy(xs):
    cell = LSTMCell(H)
    params = cell.init_params(jax.random.key(1), D)
    _, hs = run_rnn(cell, params, jnp.asarray(xs))
    p = _np_params(params)
    c = h = np.zeros((B, H), np.float32)
    for t in range(T):
        c, h = np_lstm_step(p, c, h, xs[t])
        np.testing.assert_allclose(np.asarray(hs[t]), h, atol=1e-5)


def test_layer_norm_lstm_matches_numpy(xs):
    cell = LayerNormLSTMCell(H)
    params = cell.init_params(jax.random.key(2), D)
    _, hs = run_rnn(cell, params, jnp.asarray(xs))
    p = _np_params(params)
    c = h = np.zeros((B, H), np.float32)
    for t in range(T):
        c, h = np_ln_lstm_step(p, c, h, xs[t])
        np.testing.assert_allclose(np.asarray(hs[t]), h, atol=1e-5)


def test_hyper_lstm_matches_numpy(xs):
    cell = HyperLSTMCell(H, hyper_size=7, embed_size=4)
    params = cell.init_params(jax.random.key(3), D)
    # perturb the zero-init hyper projections so the test is non-trivial
    rng = np.random.default_rng(5)
    params = jax.tree.map(
        lambda a: jnp.asarray(np.asarray(a)
                              + 0.05 * rng.normal(size=a.shape)), params)
    _, hs = run_rnn(cell, params, jnp.asarray(xs))
    p = _np_params(params)
    z = np.zeros((B, H), np.float32)
    zh = np.zeros((B, 7), np.float32)
    carry = ((z, z), (zh, zh))
    for t in range(T):
        carry, h = np_hyper_step(p, carry, xs[t], e=4)
        np.testing.assert_allclose(np.asarray(hs[t]), h, atol=2e-5)


def test_hyper_init_scales_start_at_point_one():
    cell = HyperLSTMCell(H, hyper_size=7, embed_size=4)
    params = cell.init_params(jax.random.key(0), D)
    hyper_h = jnp.ones((B, 7))
    sx = cell._scales(params, hyper_h, "x")
    np.testing.assert_allclose(np.asarray(sx), 0.1, atol=1e-6)
    sb = cell._scales(params, hyper_h, "b")
    np.testing.assert_allclose(np.asarray(sb), 0.0, atol=1e-6)


def test_recurrent_dropout_masks(xs):
    masks = make_dropout_masks(jax.random.key(0), 0.9, T, B, H)
    assert masks.shape == (T, B, H)
    m = np.asarray(masks)
    assert np.all(np.isclose(m, 0.0) | np.isclose(m, 1 / 0.9))
    assert 0.0 < m.mean() < 1 / 0.9  # both values actually occur
    # masked run differs from unmasked but stays finite
    cell = LSTMCell(H)
    params = cell.init_params(jax.random.key(1), D)
    _, hs_drop = run_rnn(cell, params, jnp.asarray(xs), rdrop_masks=masks)
    _, hs_plain = run_rnn(cell, params, jnp.asarray(xs))
    assert np.all(np.isfinite(np.asarray(hs_drop)))
    assert not np.allclose(np.asarray(hs_drop), np.asarray(hs_plain))


def test_reverse_scan_order():
    cell = LSTMCell(H)
    params = cell.init_params(jax.random.key(1), D)
    xs = np.random.default_rng(2).normal(size=(T, B, D)).astype(np.float32)
    _, hs_rev = run_rnn(cell, params, jnp.asarray(xs), reverse=True)
    _, hs_flip = run_rnn(cell, params, jnp.asarray(xs[::-1].copy()))
    # reverse=True == scanning the flipped sequence, with outputs flipped back
    np.testing.assert_allclose(np.asarray(hs_rev), np.asarray(hs_flip)[::-1],
                               atol=1e-6)


def test_bidirectional_final_state_respects_seq_len():
    cell_f, cell_b = LSTMCell(H), LSTMCell(H)
    pf = cell_f.init_params(jax.random.key(1), D)
    pb = cell_b.init_params(jax.random.key(2), D)
    rng = np.random.default_rng(3)
    xs = rng.normal(size=(T, B, D)).astype(np.float32)
    lens = np.array([3, 6, 1], np.int32)
    for i, n in enumerate(lens):
        xs[n:, i] = 0.0  # zero padding after true length
    h_final, hs = bidirectional_rnn(cell_f, cell_b, pf, pb, jnp.asarray(xs),
                                    seq_len=jnp.asarray(lens))
    assert h_final.shape == (B, 2 * H)
    assert hs.shape == (T, B, 2 * H)
    # per-example check against single-sequence scans over the valid prefix
    for i, n in enumerate(lens):
        seq = jnp.asarray(xs[:n, i:i + 1])
        fc, _ = run_rnn(cell_f, pf, seq)
        bc, _ = run_rnn(cell_b, pb, seq, reverse=True)
        np.testing.assert_allclose(np.asarray(h_final[i, :H]),
                                   np.asarray(final_hidden(cell_f, fc))[0],
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(h_final[i, H:]),
                                   np.asarray(final_hidden(cell_b, bc))[0],
                                   atol=1e-5)


def test_make_cell_factory():
    assert isinstance(make_cell("lstm", 8), LSTMCell)
    assert isinstance(make_cell("layer_norm", 8), LayerNormLSTMCell)
    hyper = make_cell("hyper", 8, hyper_size=16, hyper_embed_size=4)
    assert isinstance(hyper, HyperLSTMCell)
    assert hyper.hyper_size == 16 and hyper.embed_size == 4
    with pytest.raises(ValueError):
        make_cell("gru", 8)


def test_cells_differentiable_and_jittable():
    for kind in ("lstm", "layer_norm", "hyper"):
        cell = make_cell(kind, H, hyper_size=7, hyper_embed_size=4)
        params = cell.init_params(jax.random.key(0), D)
        xs = jnp.asarray(
            np.random.default_rng(1).normal(size=(T, B, D)), jnp.float32)

        @jax.jit
        def loss(p, xs=xs, cell=cell):
            _, hs = run_rnn(cell, p, xs)
            return jnp.sum(hs ** 2)

        g = jax.grad(loss)(params)
        flat = jax.tree.leaves(jax.tree.map(lambda a: np.all(np.isfinite(a)),
                                            g))
        assert all(flat), kind


def test_bf16_compute_close_to_f32():
    cell32 = LSTMCell(H)
    cell16 = LSTMCell(H, compute_dtype=jnp.bfloat16)
    params = cell32.init_params(jax.random.key(4), D)
    xs = jnp.asarray(
        np.random.default_rng(7).normal(size=(T, B, D)), jnp.float32)
    _, h32 = run_rnn(cell32, params, xs)
    _, h16 = run_rnn(cell16, params, xs)
    assert h16.dtype == jnp.float32  # f32 accumulate/carry contract
    np.testing.assert_allclose(np.asarray(h32), np.asarray(h16), atol=0.05)


# -- hoisted-input (cuDNN-style) path equivalence ---------------------------


@pytest.mark.parametrize("kind", ["lstm", "layer_norm", "hyper"])
def test_hoisted_scan_matches_per_step(kind):
    """run_rnn(hoist=True) must be numerically identical to the naive
    per-step path for every cell type (with and without dropout masks)."""
    from sketch_rnn_tpu.ops.rnn import make_dropout_masks, run_rnn

    t, b, d, h = 7, 4, 5, 12
    cell = make_cell(kind, h, hyper_size=6, hyper_embed_size=3)
    key = jax.random.key(0)
    params = cell.init_params(key, d)
    xs = jax.random.normal(jax.random.key(1), (t, b, d))

    f1, hs1 = run_rnn(cell, params, xs, hoist=True)
    f2, hs2 = run_rnn(cell, params, xs, hoist=False)
    np.testing.assert_allclose(np.asarray(hs1), np.asarray(hs2),
                               rtol=1e-5, atol=1e-6)
    for a, b_ in zip(jax.tree_util.tree_leaves(f1),
                     jax.tree_util.tree_leaves(f2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-6)

    masks = make_dropout_masks(jax.random.key(2), 0.8, t, b, h)
    _, hs3 = run_rnn(cell, params, xs, rdrop_masks=masks, hoist=True)
    _, hs4 = run_rnn(cell, params, xs, rdrop_masks=masks, hoist=False)
    np.testing.assert_allclose(np.asarray(hs3), np.asarray(hs4),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("kind", ["lstm", "layer_norm", "hyper"])
def test_hoisted_reverse_matches(kind):
    from sketch_rnn_tpu.ops.rnn import run_rnn

    t, b, d, h = 6, 3, 4, 8
    cell = make_cell(kind, h, hyper_size=6, hyper_embed_size=3)
    params = cell.init_params(jax.random.key(0), d)
    xs = jax.random.normal(jax.random.key(1), (t, b, d))
    _, hs1 = run_rnn(cell, params, xs, reverse=True, hoist=True)
    _, hs2 = run_rnn(cell, params, xs, reverse=True, hoist=False)
    np.testing.assert_allclose(np.asarray(hs1), np.asarray(hs2),
                               rtol=1e-5, atol=1e-6)


def test_remat_scan_identical_values_and_grads():
    """jax.checkpoint on the scan step must not change values or grads."""
    from sketch_rnn_tpu.ops.rnn import run_rnn

    t, b, d, h = 8, 4, 5, 12
    cell = make_cell("layer_norm", h)
    params = cell.init_params(jax.random.key(0), d)
    xs = jax.random.normal(jax.random.key(1), (t, b, d))
    gen = (jax.random.key(2), 0.85)

    def loss(params, remat):
        _, hs = run_rnn(cell, params, xs, rdrop_gen=gen, remat=remat)
        return jnp.mean(hs ** 2)

    v1, g1 = jax.value_and_grad(lambda p: loss(p, False))(params)
    v2, g2 = jax.value_and_grad(lambda p: loss(p, True))(params)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)
    for a, b_ in zip(jax.tree_util.tree_leaves(g1),
                     jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-7)


def test_rdrop_gen_deterministic_and_masking():
    from sketch_rnn_tpu.ops.rnn import run_rnn

    t, b, d, h = 6, 3, 4, 8
    cell = make_cell("lstm", h)
    params = cell.init_params(jax.random.key(0), d)
    xs = jax.random.normal(jax.random.key(1), (t, b, d))
    gen = (jax.random.key(2), 0.7)
    _, hs1 = run_rnn(cell, params, xs, rdrop_gen=gen)
    _, hs2 = run_rnn(cell, params, xs, rdrop_gen=gen)
    np.testing.assert_array_equal(np.asarray(hs1), np.asarray(hs2))
    _, hs_none = run_rnn(cell, params, xs)
    assert not np.allclose(np.asarray(hs1), np.asarray(hs_none))
    with pytest.raises(ValueError, match="not both"):
        run_rnn(cell, params, xs, rdrop_gen=gen,
                rdrop_masks=jnp.ones((t, b, h)))
