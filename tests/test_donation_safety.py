"""Donation safety (ISSUE 20 satellite): donated buffers fail LOUDLY
on reuse, never silently; the async-checkpoint snapshot reads
pre-donation state; scheduling/telemetry stay bitwise-invisible.

Buffer donation (``make_train_step(donate=True)``, the serve chunk
programs' carry/prev aliasing) is a memory optimization with one
failure mode worth pinning: a caller holding a stale reference to a
donated input. XLA's contract is the safe one — the stale array is
DELETED and any use raises — and these tests pin that the error is the
loud kind (a raise naming donation), not silent garbage.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.serve.engine import Request, ServeEngine
from sketch_rnn_tpu.train.checkpoint import restore_checkpoint
from sketch_rnn_tpu.train.loop import train
from sketch_rnn_tpu.train.state import make_train_state
from sketch_rnn_tpu.train.step import make_train_step
from sketch_rnn_tpu.utils import telemetry as tele

TINY = dict(batch_size=4, max_seq_len=16, enc_rnn_size=12,
            dec_rnn_size=16, z_size=6, num_mixture=3, hyper_rnn_size=8,
            hyper_embed_size=4, serve_slots=2, serve_chunk=2)


def tiny_hps(**kw) -> HParams:
    return HParams(**{**TINY, **kw})


def make_loader(hps, n=16, seed=0):
    seqs, labels = make_synthetic_strokes(
        n, num_classes=max(hps.num_classes, 1), min_len=5,
        max_len=hps.max_seq_len - 2, seed=seed)
    return DataLoader(seqs, hps, labels=labels, augment=False,
                      seed=seed)


def _assert_trees_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.fixture(scope="module")
def setup():
    hps = tiny_hps()
    model = SketchRNN(hps)
    loader = make_loader(hps)
    return hps, model, loader


def test_donated_state_reuse_raises_loudly(setup):
    """The donation contract's failure mode: a stale reference to the
    donated train state RAISES on any use — reading a leaf and
    re-dispatching the step both name the deletion/donation. Silent
    reuse of freed memory is the one outcome that must be
    impossible."""
    hps, model, loader = setup
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, donate=True)
    batch = loader.get_batch(0)
    stale = state
    state, _ = step(state, batch, jax.random.key(1))
    leaf = jax.tree_util.tree_leaves(stale.params)[0]
    assert leaf.is_deleted()
    with pytest.raises(RuntimeError, match="deleted"):
        jnp.sum(leaf).block_until_ready()
    with pytest.raises(Exception, match="deleted or donated"):
        step(stale, batch, jax.random.key(2))
    # the LIVE state keeps stepping fine — donation consumed only the
    # stale generation
    state, metrics = step(state, batch, jax.random.key(3))
    assert np.isfinite(float(metrics["loss"]))


def test_donation_is_bitwise_invisible_to_training(setup):
    """donate=True is a memory optimization ONLY: three steps with and
    without donation produce bitwise-identical states and metrics."""
    hps, model, loader = setup
    batch = loader.get_batch(0)
    finals = []
    for donate in (False, True):
        state = make_train_state(model, hps, jax.random.key(0))
        step = make_train_step(model, hps, donate=donate)
        for i in range(3):
            state, metrics = step(state, batch, jax.random.key(i))
        finals.append((jax.device_get(state.params),
                       float(metrics["loss"])))
    _assert_trees_equal(finals[0][0], finals[1][0])
    assert finals[0][1] == finals[1][1]


def test_host_snapshot_survives_donation(setup):
    """The async-checkpoint pattern in miniature: a host snapshot
    (``device_get``) taken BEFORE the donated dispatch stays readable
    and equal to the pre-step values after the device buffers are
    donated away — what the ckpt-writer thread relies on."""
    hps, model, loader = setup
    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, donate=True)
    snapshot = jax.device_get(state.params)
    reference = jax.tree_util.tree_map(np.array, snapshot)
    state, _ = step(state, loader.get_batch(0), jax.random.key(1))
    # the donated device generation is gone; the host snapshot is not
    assert jax.tree_util.tree_leaves(state.params)[0] is not None
    _assert_trees_equal(snapshot, reference)


def test_async_checkpoint_reads_pre_donation_state(setup, tmp_path):
    """Loop-level: with the donating train step, the async checkpoint
    writer snapshots each saved step's state before the next donated
    dispatch consumes it — async and sync checkpointing restore
    bitwise-identical states."""
    hps0, model, _ = setup
    restored = []
    for async_ckpt in (True, False):
        hps = tiny_hps(num_steps=4, save_every=2, eval_every=10**9,
                       log_every=10**9, async_checkpoint=async_ckpt)
        wd = str(tmp_path / f"async_{async_ckpt}")
        train(hps, make_loader(hps), workdir=wd, use_mesh=False)
        target = make_train_state(SketchRNN(hps), hps,
                                  jax.random.key(9))
        per_step = []
        for step_n in (2, 4):
            st, _, _ = restore_checkpoint(wd, target, step=step_n)
            per_step.append(jax.device_get(st.params))
        restored.append(per_step)
    for a, b in zip(restored[0], restored[1]):
        _assert_trees_equal(a, b)


def test_serve_strokes_bitwise_invariant_to_telemetry(setup):
    """Telemetry (and the scheduler ledger feeding it) moves WHEN
    things are observed, never WHAT is computed: the same requests
    served with the core disabled and enabled produce bitwise-equal
    strokes and identical dispatch/host-sync counts."""
    hps, model, _ = setup

    def serve_once():
        params = model.init_params(jax.random.key(0))
        eng = ServeEngine(model, hps, params)
        rng = np.random.default_rng(5)
        reqs = [Request(key=jax.random.key(500 + i),
                        z=rng.standard_normal(hps.z_size)
                        .astype(np.float32),
                        temperature=0.7, max_len=4)
                for i in range(4)]
        out = eng.run(reqs)
        strokes = [np.asarray(r.strokes5) for r in
                   sorted(out["results"], key=lambda r: r.uid)]
        m = out["metrics"]
        return strokes, (m["dispatches"], m["host_syncs"])

    base_strokes, base_counts = serve_once()
    tele.configure(trace_dir=None)
    try:
        traced_strokes, traced_counts = serve_once()
    finally:
        tele.disable()
    assert base_counts == traced_counts
    for a, b in zip(base_strokes, traced_strokes):
        np.testing.assert_array_equal(a, b)
