"""Zero-downtime rollout: admission gate, canary, walk, rollback.

ISSUE 16. The contract under test: a live fleet upgrades to a new
checkpoint replica-by-replica with /healthz never leaving ok/rolling,
every Result is bitwise ONE version (the one its ``ckpt_id`` stamp
names), the cache never serves a v1 hit for a v2 request, a bad
candidate is quarantined without touching the serving params, and any
mid-walk failure rolls the fleet back bitwise to the pre-rollout
fleet. Bitwise means bitwise: references come from ``serve_requests``
(the offline canonical path) at the fleet's pool geometry.
"""

import dataclasses
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.cli import main
from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.serve import Request, ServeFleet
from sketch_rnn_tpu.serve.cache import ResultCache
from sketch_rnn_tpu.serve.endpoints import serve_requests
from sketch_rnn_tpu.serve.metrics_http import health_payload
from sketch_rnn_tpu.serve.rollout import (CheckpointWatcher,
                                          RolloutController)
from sketch_rnn_tpu.train.checkpoint import (CheckpointValidationError,
                                             ckpt_id_of,
                                             save_checkpoint,
                                             validate_checkpoint)
from sketch_rnn_tpu.train.state import make_train_state
from sketch_rnn_tpu.utils import faults
from sketch_rnn_tpu.utils.telemetry import get_telemetry

TINY = dict(batch_size=8, max_seq_len=24, enc_rnn_size=12,
            dec_rnn_size=16, z_size=6, num_mixture=3, hyper_rnn_size=8,
            hyper_embed_size=4, serve_slots=2, serve_chunk=2)

OLD, NEW = ckpt_id_of(10), ckpt_id_of(20)


@pytest.fixture(scope="module")
def env():
    hps = HParams(**TINY)
    model = SketchRNN(hps)
    state_old = make_train_state(model, hps, jax.random.key(0))._replace(
        step=jnp.asarray(10, jnp.int32))
    state_new = make_train_state(model, hps, jax.random.key(7))._replace(
        step=jnp.asarray(20, jnp.int32))
    return dict(hps=hps, model=model, state_old=state_old,
                state_new=state_new)


def _req(i, z_dim, cap=6):
    rng = np.random.default_rng(i)
    return Request(key=jax.random.key(1000 + i),
                   z=rng.standard_normal(z_dim).astype(np.float32),
                   temperature=0.8, max_len=cap)


def _reqs(env, uids, cap=6):
    return [dataclasses.replace(_req(i, env["hps"].z_size, cap), uid=i)
            for i in uids]


def _canary(env):
    return [_req(900 + i, env["hps"].z_size, cap=4) for i in range(3)]


def _ckpts(env, tmp_path):
    """Write both checkpoints into a fresh dir; return (dir, p_new)."""
    d = str(tmp_path / "ckpts")
    os.makedirs(d, exist_ok=True)
    save_checkpoint(d, env["state_old"], 1.0, env["hps"])
    p_new = save_checkpoint(d, env["state_new"], 1.0, env["hps"])
    return d, p_new


def _fleet(env, replicas=2, **kw):
    fleet = ServeFleet(env["model"], env["hps"],
                       env["state_old"].params, replicas=replicas,
                       ckpt_id=OLD, **kw)
    fleet.warm(_req(0, env["hps"].z_size))
    fleet.start()
    return fleet


def _reference(env, params, uids, pool_pad):
    uids = list(uids)
    # pad is strokes-invariant (the invariance suite pins it) but must
    # cover the burst
    out = serve_requests(env["model"], env["hps"], params,
                         _reqs(env, uids), slots=env["hps"].serve_slots,
                         chunk=env["hps"].serve_chunk,
                         pool_pad=max(pool_pad, len(uids)))
    return {r.uid: r.strokes5 for r in out["results"]}


# ---------------------------------------------------------------- admit


def test_validate_checkpoint_rejects_bad_candidates(env, tmp_path):
    """The admission gate's one-line reasons: torn file, missing
    sidecar, non-finite leaf, shape mismatch — each a
    CheckpointValidationError, none a partial restore."""
    d, p_new = _ckpts(env, tmp_path)
    tmpl = env["state_old"]
    # the good path round-trips
    state, scale, meta = validate_checkpoint(p_new, tmpl)
    assert int(state.step) == 20 and scale == 1.0
    assert int(meta["step"]) == 20

    # torn payload
    torn = str(tmp_path / "torn.msgpack")
    with open(p_new, "rb") as f:
        blob = f.read()
    with open(torn, "wb") as f:
        f.write(blob[: len(blob) // 2])
    with open(str(tmp_path / "torn.json"), "w") as f:
        with open(p_new[: -len(".msgpack")] + ".json") as g:
            f.write(g.read())
    with pytest.raises(CheckpointValidationError, match="cannot restore"):
        validate_checkpoint(torn, tmpl)

    # missing sidecar
    lone = str(tmp_path / "lone.msgpack")
    with open(lone, "wb") as f:
        f.write(blob)
    with pytest.raises(CheckpointValidationError, match="sidecar"):
        validate_checkpoint(lone, tmpl)

    # non-finite leaf
    bad = tmpl._replace(params=jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, jnp.nan), tmpl.params))
    nan_dir = str(tmp_path / "nan")
    p_nan = save_checkpoint(nan_dir, bad, 1.0, env["hps"])
    with pytest.raises(CheckpointValidationError, match="finite"):
        validate_checkpoint(p_nan, tmpl)
    # ...but the gate is optional for trusted callers
    validate_checkpoint(p_nan, tmpl, check_finite=False)

    # shape mismatch vs the compiled geometry
    other_hps = HParams(**{**TINY, "dec_rnn_size": 20})
    other = SketchRNN(other_hps)
    wrong = make_train_state(other, other_hps, jax.random.key(1))
    with pytest.raises(CheckpointValidationError):
        validate_checkpoint(p_new, wrong)


def test_corrupt_candidate_quarantined_fleet_unharmed(env, tmp_path):
    """ckpt.load.corrupt at admit: the candidate is MOVED to
    quarantine/ with a one-line reason, the walk never starts, and the
    fleet keeps serving the old version bitwise."""
    d, p_new = _ckpts(env, tmp_path)
    fleet = _fleet(env)
    try:
        ref_old = _reference(env, env["state_old"].params, range(4),
                             fleet.pool_cap)
        ctl = RolloutController(fleet, env["model"], env["hps"],
                                env["state_old"], _canary(env))
        faults.configure("ckpt.load.corrupt@0", seed=0)
        try:
            rpt = ctl.roll_to(p_new)
        finally:
            faults.disable()
        assert not rpt["ok"] and rpt["phase"] == "admit"
        assert not rpt.get("rolled_back")
        # candidate moved out of the ckpt dir -> can never retrigger
        qdir = os.path.join(d, "quarantine")
        assert not os.path.exists(p_new)
        names = sorted(os.listdir(qdir))
        assert any(n.endswith(".msgpack") for n in names)
        assert any(n.endswith(".json") for n in names)
        reason = [n for n in names if n.endswith(".reason.txt")]
        assert len(reason) == 1
        with open(os.path.join(qdir, reason[0])) as f:
            body = f.read().strip()
        assert body and "\n" not in body
        # lineage untouched, fleet still serves the old version bitwise
        assert fleet.serving_ckpt_id == OLD
        assert ctl.lineage()[-1]["ckpt_id"] == OLD
        for r in _reqs(env, range(4)):
            fleet.submit(r)
        assert fleet.drain(timeout=120)
        for uid in range(4):
            res = fleet.results[uid]["result"]
            np.testing.assert_array_equal(res.strokes5, ref_old[uid])
            assert res.ckpt_id == OLD
    finally:
        fleet.close()


# ----------------------------------------------------------- the walk


def test_rollout_promote_bitwise_with_spare(env, tmp_path):
    """The happy path: canary on the retired spare, rolling walk over
    the live replicas, promote. Post-swap strokes are bitwise the
    offline reference on the NEW params, every Result is stamped with
    the version that computed it, lineage closes the old window at the
    promote watermark, and /healthz never reports degraded."""
    d, p_new = _ckpts(env, tmp_path)
    fleet = _fleet(env, replicas=2, max_replicas=3)
    try:
        ctl = RolloutController(fleet, env["model"], env["hps"],
                                env["state_old"], _canary(env))
        for r in _reqs(env, range(4)):
            fleet.submit(r)
        statuses = set()
        stop = threading.Event()

        def _poll():
            while not stop.is_set():
                statuses.add(health_payload(
                    get_telemetry(), None, fleet.health)["status"])
                time.sleep(0.01)

        poller = threading.Thread(target=_poll, name="rollout-poller",
                                  daemon=True)
        poller.start()
        try:
            rpt = ctl.roll_to(p_new)
        finally:
            stop.set()
            poller.join(timeout=10)
        assert rpt["ok"] and rpt["phase"] == "promote"
        assert rpt["from"] == OLD and rpt["to"] == NEW
        assert rpt["swapped"] == 3  # 2 live + the spare
        assert statuses <= {"ok", "rolling"}, statuses
        assert fleet.serving_ckpt_id == NEW
        events = [e["event"] for e in ctl.rollout_log]
        assert events[0] == "admit_ok" and events[-1] == "promote"
        assert "canary_ok" in events and events.count("swap") == 3

        for r in _reqs(env, range(4, 10)):
            fleet.submit(r)
        assert fleet.drain(timeout=120)
        h = fleet.health()
        assert h["healthy"] and not h["rolling"]
        assert h["serving_ckpt_id"] == NEW
        ref_new = _reference(env, env["state_new"].params,
                             range(4, 10), fleet.pool_cap)
        for uid in range(4, 10):
            res = fleet.results[uid]["result"]
            np.testing.assert_array_equal(res.strokes5, ref_new[uid])
            assert res.ckpt_id == NEW
        # lineage: old window closed at the promote watermark, new
        # window open-ended
        lin = ctl.lineage()
        assert [w["ckpt_id"] for w in lin] == [OLD, NEW]
        assert lin[0]["from_uid"] == 0 and lin[0]["to_uid"] is not None
        assert lin[1]["from_uid"] == lin[0]["to_uid"] + 1
        assert lin[1]["to_uid"] is None
    finally:
        fleet.close()


def test_mixed_version_results_are_never_blended(env, tmp_path):
    """Traffic in flight DURING the walk: every Result's strokes are
    bitwise the version its ckpt_id stamp names — never a blend, never
    a stamp that disagrees with the bits."""
    d, p_new = _ckpts(env, tmp_path)
    fleet = _fleet(env)
    try:
        ctl = RolloutController(fleet, env["model"], env["hps"],
                                env["state_old"], _canary(env))
        uids = list(range(12))
        ref_old = _reference(env, env["state_old"].params, uids,
                             fleet.pool_cap)
        ref_new = _reference(env, env["state_new"].params, uids,
                             fleet.pool_cap)
        for r in _reqs(env, range(4)):
            fleet.submit(r)
        rpt_box = {}

        def _roll():
            rpt_box["rpt"] = ctl.roll_to(p_new)

        roller = threading.Thread(target=_roll, name="rollout-test",
                                  daemon=True)
        roller.start()
        for r in _reqs(env, range(4, 12)):
            fleet.submit(r)
            time.sleep(0.02)
        roller.join(timeout=300)
        assert not roller.is_alive()
        assert rpt_box["rpt"]["ok"], rpt_box["rpt"]
        assert fleet.drain(timeout=120)
        for uid in uids:
            res = fleet.results[uid]["result"]
            assert res.ckpt_id in (OLD, NEW), res.ckpt_id
            want = ref_old if res.ckpt_id == OLD else ref_new
            np.testing.assert_array_equal(res.strokes5, want[uid])
    finally:
        fleet.close()


def test_cache_respects_version_namespace(env, tmp_path):
    """A v1 hit can never serve a v2 request: same request content
    across a rollout recomputes under the new version instead of
    serving the stale entry, and entries carry their producing
    version."""
    cache = ResultCache(config_hash="h", ckpt_id=OLD)
    probe = _req(0, env["hps"].z_size)
    assert cache.fingerprint(probe, ckpt_id="v1") != \
        cache.fingerprint(probe, ckpt_id="v2")

    d, p_new = _ckpts(env, tmp_path)
    fleet = _fleet(env, cache=ResultCache(config_hash="h", ckpt_id=OLD))
    try:
        base = _req(5, env["hps"].z_size)
        fleet.submit(dataclasses.replace(base, uid=0))
        assert fleet.drain(timeout=120)
        # identical content -> a hit under the old version
        fleet.submit(dataclasses.replace(base, uid=1))
        assert fleet.drain(timeout=120)
        st = fleet.cache.stats()
        assert st["hits"] == 1 and st["misses"] == 1

        ctl = RolloutController(fleet, env["model"], env["hps"],
                                env["state_old"], _canary(env))
        rpt = ctl.roll_to(p_new)
        assert rpt["ok"], rpt
        # identical content again -> MISS (new namespace), new bits
        fleet.submit(dataclasses.replace(base, uid=2))
        assert fleet.drain(timeout=120)
        st = fleet.cache.stats()
        assert st["misses"] == 2 and st["hits"] == 1
        r_old = fleet.results[0]["result"]
        r_new = fleet.results[2]["result"]
        assert r_old.ckpt_id == OLD and r_new.ckpt_id == NEW
        assert not np.array_equal(r_old.strokes5, r_new.strokes5)
        # and the new entry hits for the next v2 request
        fleet.submit(dataclasses.replace(base, uid=3))
        assert fleet.drain(timeout=120)
        assert fleet.cache.stats()["hits"] == 2
        res = fleet.results[3]["result"]
        assert res.ckpt_id == NEW
        np.testing.assert_array_equal(res.strokes5, r_new.strokes5)
    finally:
        fleet.close()


# ----------------------------------------------------------- rollback


def test_canary_failure_rolls_back_bitwise(env, tmp_path):
    """A canary that fails never touches the serving set: rollback is
    recorded, the fleet's strokes stay bitwise the pre-rollout fleet,
    and the stamps stay at the old version."""
    d, p_new = _ckpts(env, tmp_path)
    fleet = _fleet(env)
    try:
        ref_old = _reference(env, env["state_old"].params, range(8),
                             fleet.pool_cap)
        ctl = RolloutController(fleet, env["model"], env["hps"],
                                env["state_old"], _canary(env))
        for r in _reqs(env, range(4)):
            fleet.submit(r)
        faults.configure("rollout.canary@0", seed=0)
        try:
            rpt = ctl.roll_to(p_new)
        finally:
            faults.disable()
        assert not rpt["ok"] and rpt["phase"] == "rollback"
        assert rpt["rolled_back"] and "rollout.canary" in rpt["reason"]
        assert fleet.serving_ckpt_id == OLD
        assert fleet.n_live == 2
        assert any(e["event"] == "rollback" for e in ctl.rollout_log)
        for r in _reqs(env, range(4, 8)):
            fleet.submit(r)
        assert fleet.drain(timeout=120)
        h = fleet.health()
        assert h["healthy"] and not h["rolling"]
        for uid in range(8):
            res = fleet.results[uid]["result"]
            np.testing.assert_array_equal(res.strokes5, ref_old[uid])
            assert res.ckpt_id == OLD
    finally:
        fleet.close()


def test_swap_fault_mid_walk_rolls_back_bitwise(env, tmp_path):
    """A fault at the per-replica swap site after the canary passed:
    the already-swapped replicas are walked BACK to the old params and
    the fleet is bitwise the pre-rollout fleet again."""
    d, p_new = _ckpts(env, tmp_path)
    fleet = _fleet(env)
    try:
        ref_old = _reference(env, env["state_old"].params, range(4),
                             fleet.pool_cap)
        ctl = RolloutController(fleet, env["model"], env["hps"],
                                env["state_old"], _canary(env))
        faults.configure("rollout.swap.r0@0", seed=0)
        try:
            rpt = ctl.roll_to(p_new)
        finally:
            faults.disable()
        assert not rpt["ok"] and rpt["rolled_back"]
        assert fleet.serving_ckpt_id == OLD
        assert fleet.n_live == 2
        for r in _reqs(env, range(4)):
            fleet.submit(r)
        assert fleet.drain(timeout=120)
        for uid in range(4):
            res = fleet.results[uid]["result"]
            np.testing.assert_array_equal(res.strokes5, ref_old[uid])
            assert res.ckpt_id == OLD
    finally:
        fleet.close()


def test_armed_never_firing_plan_is_bitwise_invisible(env, tmp_path):
    """A rollout fault plan that is armed but never fires changes
    nothing: the walk promotes and the strokes are bitwise the
    offline reference — scheduling changes WHEN, never WHAT."""
    d, p_new = _ckpts(env, tmp_path)
    fleet = _fleet(env)
    try:
        ctl = RolloutController(fleet, env["model"], env["hps"],
                                env["state_old"], _canary(env))
        faults.configure(
            "rollout.swap.r7@0,rollout.canary@3,ckpt.load.corrupt@5",
            seed=0)
        try:
            rpt = ctl.roll_to(p_new)
        finally:
            faults.disable()
        assert rpt["ok"] and rpt["phase"] == "promote"
        for r in _reqs(env, range(4)):
            fleet.submit(r)
        assert fleet.drain(timeout=120)
        ref_new = _reference(env, env["state_new"].params, range(4),
                             fleet.pool_cap)
        for uid in range(4):
            res = fleet.results[uid]["result"]
            np.testing.assert_array_equal(res.strokes5, ref_new[uid])
            assert res.ckpt_id == NEW
    finally:
        fleet.close()


# ------------------------------------------------------ watcher + CLI


def test_checkpoint_watcher_only_rolls_new_steps(env, tmp_path):
    """The watcher's high-water mark: steps present at construction
    never trigger; a step saved afterwards rolls the fleet exactly
    once (poll_once is the test seam — no thread needed)."""
    d = str(tmp_path / "ckpts")
    os.makedirs(d)
    save_checkpoint(d, env["state_old"], 1.0, env["hps"])
    fleet = _fleet(env)
    try:
        ctl = RolloutController(fleet, env["model"], env["hps"],
                                env["state_old"], _canary(env))
        watcher = CheckpointWatcher(ctl, d, poll_s=0.05)
        assert watcher.poll_once() is None  # old step pre-seen
        save_checkpoint(d, env["state_new"], 1.0, env["hps"])
        rpt = watcher.poll_once()
        assert rpt is not None and rpt["ok"], rpt
        assert fleet.serving_ckpt_id == NEW
        assert watcher.poll_once() is None  # served, not re-rolled
        assert watcher.reports == [rpt]
    finally:
        fleet.close()


def test_fleet_close_joins_inflight_rollout(env, tmp_path):
    """fleet.close() during a watched rollout: the walk completes (or
    rolls back) BEFORE workers retire — never a half-swapped fleet,
    and the watcher thread is gone."""
    d = str(tmp_path / "ckpts")
    os.makedirs(d)
    save_checkpoint(d, env["state_old"], 1.0, env["hps"])
    fleet = _fleet(env)
    ctl = RolloutController(fleet, env["model"], env["hps"],
                            env["state_old"], _canary(env))
    watcher = ctl.watch(d, poll_s=0.02)
    save_checkpoint(d, env["state_new"], 1.0, env["hps"])
    time.sleep(0.3)  # let the watcher pick the walk up (racing close)
    fleet.close()
    assert not watcher._thread.is_alive()
    assert not ctl.evidence()["active"]
    # uniform version across every engine: all-old (close won the
    # race) or all-new (the walk completed) — never a mix
    ids = {rep.engine.ckpt_id for rep in fleet._replicas
           if rep.engine is not None}
    assert ids == {OLD} or ids == {NEW}, ids
    assert fleet.serving_ckpt_id in (OLD, NEW)


def test_cli_watch_ckpt_requires_fleet(tmp_path, capsys):
    # the walk retires one replica at a time; a 1-replica fleet would
    # stop serving — reject before any compile
    assert main(["serve-bench", "--random_init",
                 "--watch_ckpt", str(tmp_path),
                 f"--workdir={tmp_path}"]) == 2
    assert "--fleet" in capsys.readouterr().err
    assert main(["serve-bench", "--random_init", "--fleet", "1",
                 "--watch_ckpt", str(tmp_path),
                 f"--workdir={tmp_path}"]) == 2
    assert "--fleet" in capsys.readouterr().err
