"""Elastic multi-host training (ISSUE 14): coordinated bucket plans,
host-death survival, topology-change-equivalent resume.

The acceptance pins:

- per-host ``(B, Tb)`` run schedules identical across ``num_hosts`` in
  {2, 4}, and the two-host GLOBAL micro-batch stream bitwise equal to
  the single-host stream (the coordinated plan contract that lifts the
  ``data/loader.py`` multi-host bucketing guard);
- ``fast_forward`` on host-striped loaders partitions the global
  stream exactly and deterministically at every host count (what makes
  a resume at a DIFFERENT topology replay the same global stream);
- host death detected via barrier + stale heartbeat, survivors commit
  a consistent checkpoint and recover to a final state leaf-bitwise
  equal to an uninterrupted run at the surviving topology (in-process
  here through the real ``host.kill.hNN`` fault site; the two-real-
  subprocess version is scripts/resilience_bench.py's chaos cell);
- the elastic machinery with ``num_hosts=1`` and armed-but-never-
  firing host-kill plans are bitwise invisible.
"""

import json
import os

import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.data.loader import DataLoader, synthetic_loader
from sketch_rnn_tpu.parallel import multihost as mh
from sketch_rnn_tpu.train import elastic as EL
from sketch_rnn_tpu.utils import faults

BUCKET_HPS = HParams(batch_size=8, max_seq_len=24, bucket_edges=(12,),
                     enc_rnn_size=8, dec_rnn_size=12, z_size=4,
                     num_mixture=2, use_recurrent_dropout=False,
                     prefetch_depth=0)


def coord_loaders(hps_global, n_hosts, emit_global=False, seed=7,
                  num=40, augment=True):
    lhps = hps_global.replace(
        batch_size=hps_global.batch_size // n_hosts)
    return [synthetic_loader(lhps, num, seed=seed, augment=augment,
                             host_id=h, num_hosts=n_hosts,
                             coordinated=True,
                             emit_global=emit_global)[0]
            for h in range(n_hosts)]


# -- coordinated plan: schedules + global stream (tentpole piece 1) ---------


@pytest.mark.parametrize("n_hosts", [2, 4])
def test_per_host_geometry_schedules_identical_and_stream_partitions(
        n_hosts):
    """THE guard-lift acceptance: every host's (B, Tb) schedule is
    identical (so SPMD collectives can never see mismatched programs),
    and the concatenation of the per-host slices reproduces the
    single-host global stream BITWISE — augmentation included, across
    an epoch refill."""
    hosts = coord_loaders(BUCKET_HPS, n_hosts)
    single = coord_loaders(BUCKET_HPS, 1)[0]
    b_local = BUCKET_HPS.batch_size // n_hosts
    for step in range(12):  # 40 examples / gbatch 8 -> crosses epochs
        batches = [dl.next_batch() for dl in hosts]
        ref = single.next_batch()
        shapes = {x["strokes"].shape for x in batches}
        assert len(shapes) == 1, f"step {step}: per-host geometry " \
                                 f"diverged: {shapes}"
        (bs, t, five), = shapes
        assert (bs, t) == (b_local, ref["strokes"].shape[1])
        for key in ref:
            np.testing.assert_array_equal(
                np.concatenate([x[key] for x in batches]), ref[key],
                err_msg=f"step {step} leaf {key}")


def test_plan_fingerprint_detects_same_size_corpus_divergence():
    """Review fix: the fingerprint hashes corpus CONTENT, not just its
    length — a stale same-sized corpus on one host must fail the
    start-barrier divergence check, never silently train apart."""
    a = coord_loaders(BUCKET_HPS, 1, seed=7)[0]
    b = coord_loaders(BUCKET_HPS, 1, seed=7)[0]
    assert a.plan_fingerprint(0) == b.plan_fingerprint(0)
    b.strokes[3][0, 0] += 1.0  # one value of one sequence diverges
    assert a.plan_fingerprint(0) != b.plan_fingerprint(0)


def test_coordinated_plan_identical_across_hosts_and_topologies():
    """The plan is a pure function of (seed, epoch, global corpus,
    B_global) — NEVER of num_hosts: fingerprints agree across hosts
    and across topologies sharing the global batch."""
    two = coord_loaders(BUCKET_HPS, 2)
    four = coord_loaders(BUCKET_HPS, 4)
    one = coord_loaders(BUCKET_HPS, 1)[0]
    fps = {dl.plan_fingerprint(0) for dl in two + four + [one]}
    assert len(fps) == 1
    assert one.plan_fingerprint(1) not in fps  # epochs differ
    # and the guard really is lifted only for the coordinated mode
    with pytest.raises(RuntimeError, match="coordinated"):
        seqs = [np.ones((5, 3), np.float32)] * 10
        DataLoader(seqs, BUCKET_HPS.replace(batch_size=4),
                   global_size=20, num_hosts=2)


@pytest.mark.parametrize("k_max", [3, 4])
def test_next_stack_runs_host_striped(k_max):
    """Bucketed K-step stacks on a host-striped loader (the lifted
    next_stack guard): every host pops same-length stacks of the same
    (B, Tb) run, and the stacked micro-batch stream equals the
    next_batch stream."""
    hps = BUCKET_HPS.replace(bucket_run_len=4)
    a0, a1 = coord_loaders(hps, 2)
    b0, b1 = coord_loaders(hps, 2)
    for _ in range(6):
        s0, s1 = a0.next_stack(k_max), a1.next_stack(k_max)
        assert s0["strokes"].shape == s1["strokes"].shape
        for i in range(s0["strokes"].shape[0]):
            r0, r1 = b0.next_batch(), b1.next_batch()
            np.testing.assert_array_equal(s0["strokes"][i],
                                          r0["strokes"])
            np.testing.assert_array_equal(s1["strokes"][i],
                                          r1["strokes"])


@pytest.mark.parametrize("bucketed", [True, False],
                         ids=["bucketed", "random-feed"])
@pytest.mark.parametrize("n_hosts", [1, 2, 4])
def test_fast_forward_partitions_global_stream(n_hosts, bucketed):
    """ISSUE 14 satellite: per-host replay streams at num_hosts in
    {1, 2, 4} partition the global stream exactly and deterministically
    — fast_forward(R) lands every host at the same stream position a
    batch-by-batch consumption reaches, so a topology-change resume
    replays the identical global stream under the new striping."""
    hps = BUCKET_HPS if bucketed else BUCKET_HPS.replace(bucket_edges=())
    ffwd = coord_loaders(hps, n_hosts)
    consumed = coord_loaders(hps, n_hosts)
    single = coord_loaders(hps, 1)[0]
    for dl in ffwd:
        dl.fast_forward(7)
    for dl in consumed:
        for _ in range(7):
            dl.next_batch()
    for _ in range(7):
        single.next_batch()
    for _ in range(3):
        ref = single.next_batch()
        got = [dl.next_batch() for dl in ffwd]
        alt = [dl.next_batch() for dl in consumed]
        for key in ref:
            np.testing.assert_array_equal(
                np.concatenate([x[key] for x in got]), ref[key])
            np.testing.assert_array_equal(
                np.concatenate([x[key] for x in alt]), ref[key])


def test_emit_global_and_eval_batches_topology_invariant():
    """emit_global (the light-mode replicated feed) returns the same
    global batches on every host; eval sweeps keep identical batch
    counts and contents across hosts."""
    g0, g1 = coord_loaders(BUCKET_HPS, 2, emit_global=True)
    single = coord_loaders(BUCKET_HPS, 1)[0]
    for _ in range(4):
        x0, x1, ref = g0.next_batch(), g1.next_batch(), \
            single.next_batch()
        np.testing.assert_array_equal(x0["strokes"], x1["strokes"])
        np.testing.assert_array_equal(x0["strokes"], ref["strokes"])
    s0, s1 = coord_loaders(BUCKET_HPS, 2, augment=False)
    assert s0.num_eval_batches == s1.num_eval_batches > 0
    e0, e1 = s0.get_batch(0), s1.get_batch(0)
    assert e0["strokes"].shape == e1["strokes"].shape
    # the two hosts hold DISJOINT row slices of one global eval batch
    full = coord_loaders(BUCKET_HPS, 1, augment=False)[0].get_batch(0)
    np.testing.assert_array_equal(
        np.concatenate([e0["strokes"], e1["strokes"]]), full["strokes"])


# -- failure detection (tentpole piece 2) -----------------------------------


def test_rendezvous_detects_stale_host_and_waits_for_fresh(tmp_path):
    """Barrier semantics: a missing host with a FRESH heartbeat is
    waited for; one whose heartbeat goes stale is declared dead with
    the correct survivor set and new-primary verdict."""
    d = str(tmp_path)
    hb0 = mh.HostHeartbeat(d, 0, interval_s=0.05).start()
    try:
        # host 1 heartbeats once, then "dies" (no thread ever runs)
        mh._atomic_json(mh.heartbeat_path(d, 1),
                        {"host": 1, "count": 1, "time": 0.0})
        rdv = mh.FleetRendezvous(d, 0, [0, 1], stale_s=0.5,
                                 timeout_s=10.0)
        with pytest.raises(mh.HostDeathDetected) as ei:
            rdv.barrier("step00000003", step=3)
        assert ei.value.dead == [1] and ei.value.survivors == [0]
        assert ei.value.step == 3 and ei.value.new_primary
    finally:
        hb0.stop()
    # a fresh-heartbeat straggler is NOT dead: the barrier keeps
    # waiting until its hard timeout, then raises the loud non-death
    hb1 = mh.HostHeartbeat(d, 1, interval_s=0.05).start()
    try:
        rdv = mh.FleetRendezvous(d, 0, [0, 1], stale_s=5.0,
                                 timeout_s=0.6)
        with pytest.raises(RuntimeError, match="timed out"):
            rdv.barrier("step00000004", step=4)
    finally:
        hb1.stop()


def test_unbooted_peer_is_waited_for_not_killed(tmp_path):
    """A peer with NO heartbeat file has not launched yet (clean stops
    delete the file): the barrier must wait toward its hard timeout
    and raise the loud launch-failure error, never declare death —
    launch skew / reused rendezvous dirs cannot false-kill."""
    d = str(tmp_path)
    hb0 = mh.HostHeartbeat(d, 0, interval_s=0.05).start()
    try:
        rdv = mh.FleetRendezvous(d, 0, [0, 1], stale_s=0.2,
                                 timeout_s=0.8)
        with pytest.raises(RuntimeError, match="never heartbeated"):
            rdv.barrier("step00000000", step=0)
    finally:
        hb0.stop()


def test_clean_stop_removes_heartbeat_crash_leaves_it(tmp_path):
    d = str(tmp_path)
    hb = mh.HostHeartbeat(d, 3, interval_s=0.05).start()
    hb.stop()  # crash-path default: frozen file stays (the evidence)
    assert os.path.exists(mh.heartbeat_path(d, 3))
    hb2 = mh.HostHeartbeat(d, 3, interval_s=0.05).start()
    hb2.stop(remove=True)  # clean completion: no corpse left behind
    assert not os.path.exists(mh.heartbeat_path(d, 3))


def test_barrier_prunes_own_previous_arrival_files(tmp_path):
    """A long run must not leave one arrival file per host per step."""
    d = str(tmp_path)
    rdv = mh.FleetRendezvous(d, 0, [0], stale_s=1.0, timeout_s=5.0)
    for s in range(5):
        rdv.barrier(f"step{s:08d}", step=s)
    left = [n for n in os.listdir(d) if n.startswith("bar_")]
    assert len(left) == 1  # only the latest barrier's own file


def test_external_heartbeat_survives_coordinator_stop(tmp_path):
    """Review fix: elastic_train's cross-generation heartbeat must keep
    beating through a generation teardown — freezing it during the
    regroup (loader rebuild) would let a faster peer declare a healthy
    survivor dead."""
    d = str(tmp_path)
    hb = mh.HostHeartbeat(d, 0, interval_s=0.05).start()
    try:
        co = EL.ElasticCoordinator(d, 0, [0], heartbeat=hb)
        co.start()
        co.stop()  # generation teardown: external heartbeat untouched
        assert hb._thread.is_alive()
        t0 = mh._read_json(mh.heartbeat_path(d, 0))["time"]
        import time

        time.sleep(0.2)
        assert mh._read_json(mh.heartbeat_path(d, 0))["time"] > t0
    finally:
        hb.stop()


def test_relaunch_reuses_live_telemetry_core(tmp_path, plain_baseline):
    """Review fix: a post-death relaunch must not configure a fresh
    core — both generations export to ONE shard path, so the pre-death
    events must survive into the final export."""
    from sketch_rnn_tpu.train import train
    from sketch_rnn_tpu.utils import telemetry as tele

    tdir = str(tmp_path / "trace")
    # "generation 0": the live core already holds events
    tele.configure(trace_dir=tdir, process_index=0, host_count=2)
    tele.get_telemetry().instant("gen0_marker", cat="train")
    co = EL.ElasticCoordinator(str(tmp_path / "rdv"), 0, [0],
                               fleet_size=2,
                               heartbeat_interval_s=0.05)
    co.start()
    try:
        dl, _, _, scale = _make_loaders(TRAIN_HPS, 0, 1)
        train(TRAIN_HPS, dl, scale_factor=scale, workdir=None, seed=0,
              use_mesh=False, trace_dir=tdir, coordinator=co)
    finally:
        co.stop()
    stream = open(tmp_path / "trace" / "telemetry.p0000.jsonl").read()
    assert '"gen0_marker"' in stream  # pre-relaunch events survived


def test_elastic_trace_dir_shards_per_host(tmp_path, plain_baseline):
    """ISSUE 14 review fix: under a coordinator, telemetry is stamped
    with the COORDINATOR's fleet coordinate (original host id, gen-0
    fleet size), not jax's (0, 1) — so light-mode hosts sharing a
    trace_dir write distinct shards and a dead host reads as a missing
    shard of the declared fleet."""
    from sketch_rnn_tpu.train import train

    tdir = str(tmp_path / "trace")
    co = EL.ElasticCoordinator(str(tmp_path / "rdv"), host_id=1,
                               hosts=[1], fleet_size=2,
                               heartbeat_interval_s=0.05)
    co.start()
    try:
        dl, _, _, scale = _make_loaders(TRAIN_HPS, 0, 1)
        train(TRAIN_HPS, dl, scale_factor=scale, workdir=None, seed=0,
              use_mesh=False, trace_dir=tdir, coordinator=co)
    finally:
        co.stop()
    shard = tmp_path / "trace" / "telemetry.p0001.jsonl"
    assert shard.exists()
    meta = json.loads(open(shard).readline())
    assert meta["process_index"] == 1 and meta["host_count"] == 2


def test_coordinator_rejects_diverged_plan(tmp_path):
    """The gen-start barrier exchanges plan fingerprints: a host whose
    loader planned a different global schedule fails loudly."""
    import threading

    d = str(tmp_path)
    errs = {}

    def run_host(h, fp):
        co = EL.ElasticCoordinator(d, h, [0, 1], stale_s=5.0,
                                   timeout_s=10.0,
                                   heartbeat_interval_s=0.05)
        try:
            co.start(plan_fingerprint=fp, config_hash="cfg")
        except RuntimeError as e:
            errs[h] = e
        finally:
            co.stop()

    ts = [threading.Thread(target=run_host, args=(h, fp))
          for h, fp in ((0, "aaaa"), (1, "bbbb"))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert errs and all("divergence" in str(e) for e in errs.values())


# -- host-death survival + invisibility pins (tentpole pieces 2-3) ----------


TRAIN_HPS = BUCKET_HPS.replace(num_steps=8, save_every=4, log_every=4,
                               eval_every=10 ** 9,
                               ckpt_retry_backoff_s=0.0)


def _make_loaders(lhps, rank, n):
    dl, scale = synthetic_loader(lhps, 40, seed=7, augment=True,
                                 host_id=rank, num_hosts=n,
                                 coordinated=True, emit_global=True)
    return dl, None, None, scale


def _leaves(state):
    import jax

    return [np.asarray(x) for x in
            jax.tree_util.tree_leaves(jax.device_get(state))]


@pytest.fixture(scope="module")
def plain_baseline():
    from sketch_rnn_tpu.train import train

    dl, _, _, scale = _make_loaders(TRAIN_HPS, 0, 1)
    state = train(TRAIN_HPS, dl, scale_factor=scale, workdir=None,
                  seed=0, use_mesh=False)
    return _leaves(state)


def test_elastic_single_host_bitwise_invisible(tmp_path, plain_baseline):
    """Acceptance pin: the whole elastic machinery at num_hosts=1 —
    coordinator, heartbeat, barriers, coordinated loader — reproduces
    a plain train() leaf-bitwise."""
    state = EL.elastic_train(
        TRAIN_HPS, _make_loaders, rendezvous_dir=str(tmp_path / "rdv"),
        host_id=0, num_hosts=1, workdir=str(tmp_path / "w"), seed=0,
        use_mesh=False, heartbeat_interval_s=0.05)
    assert all(np.array_equal(a, b)
               for a, b in zip(plain_baseline, _leaves(state)))


def test_armed_never_firing_host_kill_plan_invisible(tmp_path,
                                                     plain_baseline):
    """Acceptance pin: an armed host-kill / dcn-collective plan that
    never fires is bitwise invisible (the injector hashes, it never
    draws)."""
    faults.configure(
        "host.kill.h0@999999:kind=exit,dcn.collective@888888")
    try:
        state = EL.elastic_train(
            TRAIN_HPS, _make_loaders,
            rendezvous_dir=str(tmp_path / "rdv"), host_id=0,
            num_hosts=1, workdir=str(tmp_path / "w"), seed=0,
            use_mesh=False, heartbeat_interval_s=0.05)
    finally:
        faults.disable()
    assert all(np.array_equal(a, b)
               for a, b in zip(plain_baseline, _leaves(state)))


def test_host_death_recovery_bitwise(tmp_path, plain_baseline):
    """The in-process version of the resilience chaos cell: host 1 of
    a 2-host fleet dies at step 5 through the REAL host.kill.h1 fault
    site; host 0 detects it, commits a consistent checkpoint AT the
    death step (zero device steps lost), rewrites RUN.json with the
    surviving topology, and recovers to the plain single-host final
    state leaf-bitwise."""
    import threading

    from sketch_rnn_tpu.utils.runinfo import read_manifest

    rdir, wdir = str(tmp_path / "rdv"), str(tmp_path / "w")
    # kind=raise: the injected fault crashes host 1's thread (its
    # coordinator/heartbeat stop on the way out), which IS a host
    # death as far as host 0's detector can tell. kind=exit is the
    # subprocess cell's job (it would kill the whole test process).
    faults.configure("host.kill.h1@5")
    results = {}

    def run_host(h):
        try:
            results[h] = EL.elastic_train(
                TRAIN_HPS, _make_loaders, rendezvous_dir=rdir,
                host_id=h, num_hosts=2, workdir=wdir, seed=0,
                use_mesh=False, stale_s=2.0,
                heartbeat_interval_s=0.05)
        except BaseException as e:  # noqa: BLE001 — recorded, asserted
            results[h] = e

    try:
        ts = [threading.Thread(target=run_host, args=(h,))
              for h in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(180)
    finally:
        faults.disable()
    assert isinstance(results[1], faults.InjectedFault)
    state = results[0]
    assert not isinstance(state, BaseException), state
    assert all(np.array_equal(a, b)
               for a, b in zip(plain_baseline, _leaves(state)))
    # restart protocol artifacts: topology generation + RUN.json ledger
    topo = json.load(open(EL.topology_path(rdir, 1)))
    assert topo["hosts"] == [0] and topo["dead"] == [1]
    assert topo["at_step"] == 5 and topo["resumed_from"] == 5
    man = read_manifest(wdir)
    assert man["elastic"]["hosts"] == [0]
    assert man["elastic"]["events"][0]["dead"] == [1]
    # zero device steps re-executed: the consistent checkpoint landed
    # AT the detection step
    ev = man["elastic"]["events"][0]
    assert ev["at_step"] - ev["resumed_from"] == 0


def test_divisible_prefix_picks_largest_workable_survivor_set():
    """Review fix: 3 survivors at global batch 8 cannot be striped —
    the fleet keeps the largest divisible prefix (never crashes every
    healthy host on the re-striping ValueError) and the prefix always
    contains the new primary."""
    assert EL.divisible_prefix([0, 1, 2], 8) == [0, 1]
    assert EL.divisible_prefix([0, 2, 3, 5], 8) == [0, 2, 3, 5]
    assert EL.divisible_prefix([1, 4, 6], 7) == [1]
    assert EL.divisible_prefix([3], 5) == [3]


def test_commit_topology_distinguishes_retired_from_excluded(tmp_path):
    """A host named in the topology's ``retired`` list accepts the doc
    (clean exit); one excluded with NO retirement record was falsely
    declared dead and must refuse."""
    d1 = str(tmp_path / "a")
    pri = EL.ElasticCoordinator(d1, 0, [0, 1, 2, 3], gen=0,
                                timeout_s=5.0)
    doc = pri.commit_topology([0, 1], 10, [3], 10, retired=[2])
    assert doc["hosts"] == [0, 1] and doc["retired"] == [2]
    got = EL.ElasticCoordinator(d1, 2, [0, 1, 2, 3], gen=0,
                                timeout_s=5.0).commit_topology(
        [0, 1], 10, [3], None, retired=[2])
    assert got["retired"] == [2]
    d2 = str(tmp_path / "b")
    EL.ElasticCoordinator(d2, 0, [0, 1, 2], gen=0,
                          timeout_s=5.0).commit_topology(
        [0, 1], 10, [2], 10)
    with pytest.raises(RuntimeError, match="excluded"):
        EL.ElasticCoordinator(d2, 2, [0, 1, 2], gen=0,
                              timeout_s=5.0).commit_topology(
            [0, 1], 10, [], None)


def test_dead_host_cannot_rejoin(tmp_path):
    """Generations only shrink: a host missing from the current
    topology is refused at elastic_train entry."""
    rdir = str(tmp_path)
    mh._atomic_json(EL.topology_path(rdir, 1),
                    {"generation": 1, "hosts": [0], "dead": [1],
                     "at_step": 5, "resumed_from": 5})
    with pytest.raises(RuntimeError, match="do not rejoin"):
        EL.elastic_train(TRAIN_HPS, _make_loaders, rendezvous_dir=rdir,
                         host_id=1, num_hosts=2,
                         workdir=str(tmp_path / "w"))


# -- cli usage ---------------------------------------------------------------


def test_cli_elastic_usage_errors(capsys):
    from sketch_rnn_tpu.cli import main

    base = ["train", "--synthetic", "--hparams=batch_size=8"]
    assert main(base + ["--elastic_hosts=2"]) == 2
    assert "--rendezvous" in capsys.readouterr().err
    assert main(base + ["--elastic_hosts=2", "--rendezvous=/tmp/x",
                        "--elastic_host_id=5"]) == 2
    assert "out of range" in capsys.readouterr().err
    assert main(base + ["--elastic_hosts=3", "--rendezvous=/tmp/x"]) == 2
    assert "not divisible" in capsys.readouterr().err
    assert main(base + ["--rendezvous=/tmp/x"]) == 2
    assert "--elastic_hosts" in capsys.readouterr().err


def test_run_wall_time_is_one_stamp_per_process(tmp_path, monkeypatch):
    """ISSUE 14 satellite: every history row of one invocation carries
    the SAME wall_time — the run-manifest clock — so committed smoke
    rows diff cleanly across re-runs."""
    import bench
    from sketch_rnn_tpu.utils import runinfo

    monkeypatch.setattr(bench, "_smoke_hist_path",
                        lambda: str(tmp_path / "smoke.jsonl"))
    a = bench._hist_append({"kind": "resilience", "smoke": True,
                            "site": "x", "ok": True})
    b = bench._hist_append({"kind": "resilience", "smoke": True,
                            "site": "y", "ok": True})
    assert a["wall_time"] == b["wall_time"] == runinfo.run_wall_time()
    rows = [json.loads(l) for l in
            open(tmp_path / "smoke.jsonl").read().splitlines()]
    assert {r["wall_time"] for r in rows} == {runinfo.run_wall_time()}
