"""Telemetry runtime tests (ISSUE 6).

Two load-bearing contracts:

1. **Off is invisible** (the default): a telemetry-off train smoke
   produces metrics rows key-for-key identical to the pre-PR schema,
   with every deterministic column bitwise equal to a traced run's —
   tracing can never change what is trained or logged, only observe it.
2. **Views reconcile**: the ledgers (SpanTimer/GoodputLedger/
   PaddingLedger) keep their exact public ``window()``/``summary()``
   behavior while mirroring into the process core, whose exported
   totals equal the ledger totals (same floats, same order).
"""

import json
import os
import threading

import jax
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.utils import telemetry as tele
from sketch_rnn_tpu.utils.profiling import (
    GoodputLedger,
    PaddingLedger,
    SpanTimer,
)
from sketch_rnn_tpu.utils.telemetry import Histogram, Telemetry

# keep in sync with tests/test_train.py TINY so jitted train steps are
# shared through the process-wide executable cache across test modules
TINY = dict(batch_size=16, max_seq_len=32, enc_rnn_size=16, dec_rnn_size=24,
            z_size=8, num_mixture=3, hyper_rnn_size=8, hyper_embed_size=4)

# the pre-PR train-smoke CSV schema for the TINY config (captured at the
# PR-5 tree): telemetry-off runs must reproduce it KEY-FOR-KEY — new
# telemetry may never leak columns into the default metrics contract
PRE_PR_HEADER = [
    "step", "wall_time", "bucket_T32_n", "dispatches_saved", "grad_norm",
    "kl", "kl_raw", "kl_weight", "loss", "lr", "mean_run_len",
    "offset_nll", "padded_frac", "pen_ce", "recon", "runs_per_epoch",
    "steps_per_sec", "strokes_per_sec", "strokes_per_sec_per_chip",
    "t_ckpt_wait_s", "t_dispatch_s", "t_eval_s", "t_feeder_wait_s",
    "t_metrics_drain_s",
]


def tiny_hps(**kw) -> HParams:
    return HParams(**{**TINY, **kw})


def make_loader(hps, n=64, seed=0):
    from sketch_rnn_tpu.data.loader import DataLoader, make_synthetic_strokes

    seqs, labels = make_synthetic_strokes(
        n, num_classes=max(hps.num_classes, 1),
        min_len=10, max_len=hps.max_seq_len - 2, seed=seed)
    return DataLoader(seqs, hps, labels=labels, seed=seed)


# -- histogram ---------------------------------------------------------------


def test_histogram_streaming_quantiles_within_bucket_error():
    """Log-bucket quantiles track np.percentile within the geometric
    bucket's relative error bound (~4.5%), with exact count/mean/
    min/max — at any scale (microseconds to seconds)."""
    rng = np.random.default_rng(0)
    for scale in (1e-6, 1e-3, 10.0):
        xs = rng.lognormal(mean=0.0, sigma=1.0, size=5000) * scale
        h = Histogram()
        for x in xs:
            h.observe(float(x))
        s = h.summary()
        assert s["count"] == 5000
        assert s["mean"] == pytest.approx(xs.mean())
        assert s["min"] == xs.min() and s["max"] == xs.max()
        for q, key in ((50, "p50"), (95, "p95"), (99, "p99")):
            exact = np.percentile(xs, q)
            assert s[key] == pytest.approx(exact, rel=0.05), (scale, q)


def test_histogram_empty_zero_and_singleton():
    h = Histogram()
    assert h.summary() == {"count": 0, "mean": 0.0, "min": 0.0,
                           "max": 0.0, "p50": 0.0, "p95": 0.0, "p99": 0.0}
    h.observe(0.0)   # clock underflow on a zero-length wait
    h.observe(-1e-9)
    assert h.quantile(0.5) == 0.0
    h2 = Histogram()
    h2.observe(0.25)
    # a single observation answers every quantile with (clamped) itself
    assert h2.quantile(0.0) == h2.quantile(0.99) == 0.25
    # out-of-range q clamps instead of mis-ranking (ISSUE 7 satellite);
    # empty/single bucket exposition is well-defined too
    assert h2.quantile(-1.0) == h2.quantile(2.0) == 0.25
    assert Histogram().buckets() == []
    assert h2.buckets() == [(pytest.approx(Histogram.GROWTH ** (
        int(np.floor(np.log(0.25) / np.log(Histogram.GROWTH))) + 1)), 1)]


def test_snapshot_separates_gauges_and_is_consistent():
    tel = Telemetry()
    tel.counter("reqs", 2.0, cat="serve")
    tel.gauge("slots_live", 5, cat="serve")
    tel.observe("lat", 0.5, cat="serve")
    with tel.span("work", cat="train"):
        pass
    snap = tel.snapshot()
    assert snap["counters"] == {("serve", "reqs"): 2.0}
    assert snap["gauges"] == {("serve", "slots_live"): 5.0}
    assert snap["aggregates"][("train", "work")][0] == 1
    h = snap["hists"][("serve", "lat")]
    assert h["summary"]["count"] == 1 and h["total"] == 0.5
    assert h["buckets"][-1][1] == 1
    assert snap["dropped"] == 0


# -- core recording ----------------------------------------------------------


def test_disabled_core_records_nothing_and_is_default():
    tel = tele.get_telemetry()
    assert not tel.enabled  # process default: off
    with tel.span("x", cat="t"):
        pass
    tel.counter("c")
    tel.gauge("g", 3)
    tel.observe("h", 0.5)
    tel.instant("i")
    assert tel.events() == []
    assert tel.aggregates() == {} and tel.counters() == {}
    assert tel.histogram("h") is None


def test_span_agg_counter_gauge_instant_roundtrip():
    tel = Telemetry()
    with tel.span("work", cat="train", args={"k": 1}):
        pass
    tel.counter("n_batches", 2.0, cat="data")
    tel.counter("n_batches", 3.0, cat="data")
    tel.gauge("slots_live", 7, cat="serve")
    tel.instant("enqueue", cat="serve", args={"uid": 4})
    evs = tel.events()
    assert [e["type"] for e in evs] == ["span", "counter", "counter",
                                       "counter", "instant"]
    span = evs[0]
    assert span["name"] == "work" and span["cat"] == "train"
    assert span["dur"] >= 0 and span["args"] == {"k": 1}
    assert span["tid"] == threading.current_thread().name
    # counters accumulate; the ring records the running total
    assert tel.counters()[("data", "n_batches")] == 5.0
    assert evs[2]["value"] == 5.0
    # gauges record the sample itself
    assert tel.counters()[("serve", "slots_live")] == 7.0
    (count, total) = tel.aggregates()[("train", "work")]
    assert count == 1 and total == span["dur"]


def test_ring_buffer_bounded_but_aggregates_exact():
    tel = Telemetry(capacity=10)
    for i in range(25):
        tel.emit_span("s", "c", 0.0, 1.0)
    assert len(tel.events()) == 10
    assert tel.dropped == 15
    # the agg store is independent of the ring: totals stay exact
    assert tel.aggregates()[("c", "s")] == (25, 25.0)


def test_core_thread_safety_under_concurrent_emission():
    tel = Telemetry(capacity=1 << 14)
    n, threads = 500, 8

    def work(t):
        for i in range(n):
            with tel.span("s", cat="x"):
                pass
            tel.counter("c", 1.0, cat="x")
            tel.observe("h", 0.001 * (i + 1), cat="x")

    ts = [threading.Thread(target=work, args=(t,)) for t in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert tel.aggregates()[("x", "s")][0] == n * threads
    assert tel.counters()[("x", "c")] == n * threads
    assert tel.histogram("h", cat="x")["count"] == n * threads


def test_configure_swaps_in_fresh_core(tmp_path):
    a = tele.configure(trace_dir=str(tmp_path))
    with a.span("old"):
        pass
    b = tele.configure(trace_dir=str(tmp_path))
    assert tele.get_telemetry() is b and b.events() == []  # no leak
    tele.disable()
    assert not tele.get_telemetry().enabled


# -- exporters ---------------------------------------------------------------


def _populated_core(tmp_path) -> Telemetry:
    tel = tele.configure(trace_dir=str(tmp_path))
    with tel.span("dispatch", cat="train"):
        pass
    tel.gauge("slots_live", 3, cat="serve")
    tel.instant("complete", cat="serve", args={"uid": 0, "latency_s": 0.5})
    tel.observe("latency_s", 0.5, cat="serve")
    return tel


def test_export_jsonl_schema(tmp_path):
    tel = _populated_core(tmp_path)
    paths = tel.export()
    lines = [json.loads(l) for l in open(paths["jsonl"])]
    assert lines[0]["type"] == "meta"
    assert lines[0]["dropped"] == 0 and lines[0]["pid"] == os.getpid()
    types = [l["type"] for l in lines]
    assert types.count("span") == 1 and types.count("instant") == 1
    agg = next(l for l in lines if l["type"] == "agg")
    assert (agg["cat"], agg["name"], agg["count"]) == ("train",
                                                      "dispatch", 1)
    hist = next(l for l in lines if l["type"] == "hist")
    assert hist["name"] == "latency_s" and hist["count"] == 1


def test_export_chrome_trace_loads_and_is_wellformed(tmp_path):
    tel = _populated_core(tmp_path)
    paths = tel.export()
    doc = json.load(open(paths["chrome"]))
    evs = doc["traceEvents"]
    assert evs, "empty trace"
    phases = {e["ph"] for e in evs}
    assert {"X", "C", "i", "M"} <= phases
    for e in evs:
        assert "pid" in e and "tid" in e and "name" in e
        if e["ph"] == "X":
            assert e["dur"] >= 0 and "ts" in e
    # thread-name metadata makes named tracks in Perfetto
    meta = [e for e in evs if e["ph"] == "M"]
    assert any(e["args"]["name"] == threading.current_thread().name
               for e in meta)


def test_device_trace_noop_when_disabled_or_dirless(tmp_path):
    with tele.get_telemetry().device_trace():   # disabled: pure no-op
        pass
    tel = Telemetry(enabled=True, trace_dir=None)
    with tel.device_trace():                    # no dir: no-op
        pass
    assert tel.events() == []


# -- ledger views ------------------------------------------------------------


def test_span_timer_emits_into_core_and_totals_reconcile(tmp_path):
    tel = tele.configure(trace_dir=str(tmp_path))
    st = SpanTimer(category="serve")
    for _ in range(5):
        with st.span("fetch"):
            pass
    with st.span("collect"):
        pass
    agg = tel.aggregates()
    local = st.summary()
    for name in ("fetch", "collect"):
        count, total = agg[("serve", name)]
        assert count == local[name]["count"]
        # identical floats accumulated in identical order: the exported
        # totals equal the ledger totals exactly (rounding aside)
        assert round(total, 6) == local[name]["total_s"]


def test_goodput_ledger_reconciles_and_rows_unchanged(tmp_path):
    tel = tele.configure(trace_dir=str(tmp_path))
    led = GoodputLedger(("dispatch", "ckpt_wait"))
    import time
    with led.span("dispatch"):
        time.sleep(0.002)
    with led.span("eval"):
        pass
    # row contract unchanged under telemetry: pre-declared + fired
    w = led.window()
    assert set(w) == {"t_dispatch_s", "t_ckpt_wait_s", "t_eval_s"}
    s = led.summary()
    for name in ("dispatch", "eval"):
        count, total = tel.aggregates()[("train", name)]
        assert count == s[name]["count"]
        assert round(total, 6) == s[name]["total_s"]
    # phases with no closed span (ckpt_wait) never hit the core
    assert ("train", "ckpt_wait") not in tel.aggregates()


def test_goodput_ledger_values_identical_with_telemetry_off():
    """The view must not change ledger math: a ledger driven with the
    core disabled accumulates the same structure it always did."""
    assert not tele.get_telemetry().enabled
    led = GoodputLedger(("dispatch",))
    with led.span("dispatch"):
        pass
    s = led.summary()
    assert set(s) == {"dispatch"}
    assert s["dispatch"]["count"] == 1


def test_padding_ledger_routes_counters_through_core(tmp_path):
    tel = tele.configure(trace_dir=str(tmp_path))
    led = PaddingLedger(edges=(16, 32))
    led.record(16, rows=4, true_steps=40)
    led.record(32, rows=4, true_steps=100)
    led.record_dispatch(4, 1)
    led.note_epoch_plan(3, 24)
    c = tel.counters()
    assert c[("data", "dispatched_timesteps")] == 4 * 16 + 4 * 32
    assert c[("data", "true_timesteps")] == 140
    assert c[("data", "bucket_T16_n")] == 1
    assert c[("data", "micro_steps")] == 4
    assert c[("data", "dispatches")] == 1
    assert c[("data", "runs_per_epoch")] == 3
    # the ledger's own window is untouched by the mirroring
    w = led.window()
    assert w["padded_frac"] == pytest.approx(1 - 140 / 192, abs=1e-6)
    assert w["dispatches_saved"] == 3


# -- train integration: off is invisible, on exports --------------------------


def _run_smoke(tmp_path, name, trace_dir):
    from sketch_rnn_tpu.train.loop import train

    hps = tiny_hps(num_steps=4, log_every=2, save_every=10**9,
                   eval_every=10**9)
    d = str(tmp_path / name)
    train(hps, make_loader(hps), workdir=d, use_mesh=False,
          resume=False, trace_dir=trace_dir)
    import csv
    with open(os.path.join(d, "train_metrics.csv")) as f:
        header = next(csv.reader(f))
    with open(os.path.join(d, "train_metrics.jsonl")) as f:
        rows = [json.loads(l) for l in f]
    return header, rows


def test_telemetry_off_train_smoke_bitwise_invisible(tmp_path):
    """THE tier-1 invisibility pin: the default (telemetry-off) smoke
    reproduces the pre-PR CSV schema key-for-key, every deterministic
    column is bitwise identical to a traced run of the same seed, and
    no telemetry file appears anywhere in the off run's workdir."""
    header_off, rows_off = _run_smoke(tmp_path, "off", None)
    trace_dir = str(tmp_path / "trace")
    header_on, rows_on = _run_smoke(tmp_path, "on", trace_dir)

    assert header_off == PRE_PR_HEADER     # schema pinned to pre-PR
    assert header_on == PRE_PR_HEADER      # tracing adds NO columns
    # the pin extends to ISSUE 8's artifacts: no telemetry files, no
    # shard files, and no RUN.json manifest in a telemetry-off run
    assert not any("telemetry" in f or f.startswith("trace")
                   or f == "RUN.json"
                   for f in os.listdir(tmp_path / "off"))
    assert os.path.exists(os.path.join(trace_dir, "telemetry.jsonl"))
    assert os.path.exists(os.path.join(trace_dir, "trace.json"))

    # every non-wall-clock column bitwise equal between off and on
    timing = {"wall_time", "steps_per_sec", "strokes_per_sec",
              "strokes_per_sec_per_chip"}
    assert len(rows_off) == len(rows_on) == 2
    for ro, rn in zip(rows_off, rows_on):
        assert set(ro) == set(rn)
        for k, v in ro.items():
            if k in timing or k.startswith("t_"):
                continue
            assert v == rn[k], k


def test_traced_train_run_exports_wellformed_and_reconciles(tmp_path):
    """A --trace_dir train smoke emits a JSONL whose exact span totals
    match the summed t_<phase>_s CSV columns (the GoodputLedger window
    stream) for phases fully covered by windows, and a Chrome trace
    that loads with span/counter events on named threads."""
    trace_dir = str(tmp_path / "trace")
    _, rows = _run_smoke(tmp_path, "run", trace_dir)

    lines = [json.loads(l) for l in open(
        os.path.join(trace_dir, "telemetry.jsonl"))]
    agg = {(l["cat"], l["name"]): l for l in lines if l["type"] == "agg"}
    # dispatch/feeder_wait spans all close before their window is read,
    # so CSV window sums == exported exact totals (within the 6-dp
    # rounding of each window value)
    for phase in ("dispatch", "feeder_wait"):
        csv_sum = sum(r[f"t_{phase}_s"] for r in rows)
        assert agg[("train", phase)]["total_s"] == pytest.approx(
            csv_sum, abs=1e-5)
    # the feeder thread's assembly spans ride under cat "data" from the
    # producer thread — visible as a separate named track
    assert ("data", "assemble") in agg
    span_tids = {e["tid"] for e in lines if e.get("type") == "span"}
    assert "batch-prefetch" in span_tids
    # the prefetch look-ahead gauge (ISSUE 8) samples queue depth per
    # consumed batch, flagged as a gauge in the export
    depth = [l for l in lines if l.get("type") == "counter_total"
             and (l["cat"], l["name"]) == ("data", "prefetch_queue_depth")]
    assert depth and depth[0].get("gauge") is True

    doc = json.load(open(os.path.join(trace_dir, "trace.json")))
    assert any(e["ph"] == "X" for e in doc["traceEvents"])


def test_traced_serve_run_live_histograms_and_events(tmp_path):
    """Per-request serving telemetry streams LIVE: during/after a run
    the core's histograms hold every completed request, and the event
    stream carries the full enqueue -> admit -> complete lifecycle
    with exact latencies in the complete args."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.serve import Request, ServeEngine

    hps = tiny_hps(batch_size=8, max_seq_len=24, enc_rnn_size=12,
                   dec_rnn_size=16, z_size=6, serve_slots=4,
                   serve_chunk=2)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    eng = ServeEngine(model, hps, params)

    def req(i, cap):
        rng = np.random.default_rng(i)
        return Request(key=jax.random.key(1000 + i),
                       z=rng.standard_normal(hps.z_size).astype(np.float32),
                       temperature=0.8, max_len=cap)

    reqs = [req(i, 4 + (3 * i) % 15) for i in range(10)]
    tel = tele.configure(trace_dir=str(tmp_path))
    out = eng.run(list(reqs))
    m = out["metrics"]

    h = tel.histogram("latency_s", cat="serve")
    assert h["count"] == 10
    assert h["p50"] == pytest.approx(m["latency_p50_s"], rel=0.10)
    evs = tel.events()
    names = [e["name"] for e in evs if e["type"] == "instant"]
    assert names.count("enqueue") == 10
    assert names.count("admit") == 10
    assert names.count("complete") == 10
    comp = {e["args"]["uid"]: e["args"] for e in evs
            if e["type"] == "instant" and e["name"] == "complete"}
    by_uid = {r.uid: r for r in out["results"]}
    for uid, r in by_uid.items():
        assert comp[uid]["latency_s"] == r.latency_s
        assert comp[uid]["steps"] == r.steps
    # exact percentiles recomputed from events match run()'s summary
    lats = np.array([c["latency_s"] for c in comp.values()])
    assert round(float(np.percentile(lats, 99)), 6) == m["latency_p99_s"]
    # occupancy gauge sampled once per collected chunk
    gauges = [e for e in evs if e["type"] == "counter"
              and e["name"] == "slots_live"]
    assert gauges and all(0 <= g["value"] <= hps.serve_slots
                          for g in gauges)


def test_traced_engine_serves_two_burst_sizes(tmp_path):
    """Regression (ISSUE 8 review): the chunk program is shape-
    specialized on the request-pool size N, so the compile probe must
    key on the pool shapes — a traced engine serving a second,
    different-sized burst needs its own executable, not the first
    burst's (which would crash on the aval mismatch)."""
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.serve import Request, ServeEngine

    hps = tiny_hps(batch_size=8, max_seq_len=16, enc_rnn_size=12,
                   dec_rnn_size=16, z_size=6, serve_slots=2,
                   serve_chunk=2)
    model = SketchRNN(hps)
    eng = ServeEngine(model, hps, model.init_params(jax.random.key(0)))
    tel = tele.configure(trace_dir=str(tmp_path))

    def burst(n):
        rng = np.random.default_rng(n)
        return [Request(key=jax.random.key(100 * n + i),
                        z=rng.standard_normal(hps.z_size)
                        .astype(np.float32), max_len=4)
                for i in range(n)]

    assert eng.run(burst(3))["metrics"]["completed"] == 3
    assert eng.run(burst(5))["metrics"]["completed"] == 5
    # two pool geometries -> two compile spans, distinct N labels
    spans = [e for e in tel.events() if e["type"] == "span"
             and e["cat"] == "compile" and e["name"] == "serve_chunk"]
    assert len(spans) == 2
    # r17: the key carries the decode-kernel flavor + param dtype
    assert {s["args"]["geometry"] for s in spans} == {
        "(B2,K2,N3,scan,float32)", "(B2,K2,N5,scan,float32)"}


# -- compile & memory accounting (ISSUE 8) -----------------------------------


def test_compile_probe_bucketed_one_compile_per_geometry(tmp_path):
    """THE compile-accounting acceptance pin: a traced bucketed smoke
    run records exactly ONE compile span per dispatched (B, Tb)
    geometry (then jit-cache hits), each span carrying the
    executable's cost/memory stats (flops + peak device bytes)."""
    from sketch_rnn_tpu.train.loop import train

    hps = tiny_hps(bucket_edges=(16, 32), num_steps=6, log_every=3,
                   save_every=10**9, eval_every=10**9)
    trace_dir = str(tmp_path / "trace")
    train(hps, make_loader(hps), workdir=str(tmp_path / "wd"),
          use_mesh=False, resume=False, trace_dir=trace_dir)

    lines = [json.loads(l) for l in open(
        os.path.join(trace_dir, "telemetry.jsonl"))]
    spans = [l for l in lines if l.get("type") == "span"
             and l["cat"] == "compile" and l["name"] == "train_step"]
    geoms = [s["args"]["geometry"] for s in spans]
    assert len(spans) >= 2          # both bucket edges dispatched
    assert len(geoms) == len(set(geoms))  # exactly one per geometry
    for s in spans:
        # per-executable stats read off the compiled program (the AOT
        # path works on the CPU backend, so the pin is exact here)
        assert s["args"]["flops"] > 0
        assert s["args"]["peak_bytes"] > 0
        assert s["dur"] > 0
    counters = {(l["cat"], l["name"]): l["value"] for l in lines
                if l.get("type") == "counter_total"}
    # 6 dispatches total: one miss per geometry, hits for the rest
    assert counters[("compile", "jit_cache_miss")] == len(spans)
    assert counters[("compile", "jit_cache_hit")] == 6 - len(spans)
    # the latest-compile peak rides as a /metrics-visible gauge
    gauge_lines = [l for l in lines if l.get("type") == "counter_total"
                   and l.get("gauge")]
    assert any(l["name"] == "train_step_peak_bytes" for l in gauge_lines)


def test_compile_probe_off_is_passthrough_and_counts_through(tmp_path):
    """With telemetry off the probe forwards to the inner jit (its
    cache; geometry_cache_size counts through), and a LATER-enabled
    core reports the warm geometry as a hit instead of recompiling —
    the serve-bench warmup-then-configure order."""
    import jax

    from sketch_rnn_tpu.utils.telemetry import JitCompileProbe

    calls = []

    probe = JitCompileProbe(
        jax.jit(lambda x: x * 2), "f",
        key_of=lambda a: tuple(a[0].shape))
    assert not tele.get_telemetry().enabled
    x = np.ones((4,), np.float32)
    np.testing.assert_array_equal(np.asarray(probe(x)), x * 2)
    assert probe._cache_size() == 1   # inner jit compiled it
    tel = tele.configure(trace_dir=str(tmp_path))
    np.testing.assert_array_equal(np.asarray(probe(x)), x * 2)
    c = tel.counters()
    assert c[("compile", "jit_cache_hit")] == 1
    assert ("compile", "jit_cache_miss") not in c
    assert not [e for e in tel.events() if e["type"] == "span"]
    # a NEW geometry while enabled: miss + compile span + AOT cache
    y = np.ones((8,), np.float32)
    np.testing.assert_array_equal(np.asarray(probe(y)), y * 2)
    assert tel.counters()[("compile", "jit_cache_miss")] == 1
    spans = [e for e in tel.events() if e["type"] == "span"]
    assert len(spans) == 1 and spans[0]["name"] == "f"
    assert probe._cache_size() == 2
    del calls


def test_memory_sampler_gauges_phases_and_registry(tmp_path):
    tel = tele.configure(trace_dir=str(tmp_path))
    feed = {"v": 100.0}
    sampler = tele.MemorySampler(
        interval_s=10.0,
        stats_fn=lambda: {"bytes_in_use": feed["v"],
                          "peak_bytes_in_use": feed["v"] * 2})
    sampler.phase = "train"
    assert sampler.sample() == {"bytes_in_use": 100.0,
                                "peak_bytes_in_use": 200.0}
    feed["v"] = 50.0
    sampler.sample()
    snap = tel.snapshot()
    assert snap["gauges"][("memory", "device_bytes_in_use")] == 50.0
    assert snap["gauges"][("memory", "device_peak_bytes")] == 100.0
    # per-phase peak holds the max LIVE bytes seen in that phase
    assert snap["gauges"][("memory", "phase_peak_bytes_train")] == 100.0
    sampler.phase = "eval"
    feed["v"] = 70.0
    sampler.sample()
    snap = tel.snapshot()
    assert snap["gauges"][("memory", "phase_peak_bytes_eval")] == 70.0
    assert snap["gauges"][("memory", "phase_peak_bytes_train")] == 100.0
    # thread lifecycle + the process-wide registry the conftest guard
    # drains: start registers, stop_all names and stops leakers
    sampler.start()
    assert sampler in tele.live_samplers()
    names = tele.stop_all_samplers()
    assert len(names) == 1 and "MemorySampler" in names[0]
    assert tele.live_samplers() == ()


def test_memory_sampler_noop_without_backend_stats(tmp_path):
    tele.configure(trace_dir=str(tmp_path))
    sampler = tele.MemorySampler(stats_fn=lambda: None)
    assert sampler.sample() is None
    assert tele.get_telemetry().snapshot()["gauges"] == {}
    # disabled core: nothing recorded either
    tele.disable()
    s2 = tele.MemorySampler(
        stats_fn=lambda: {"bytes_in_use": 1, "peak_bytes_in_use": 1})
    assert s2.sample() is None


def test_traced_train_writes_run_manifest_and_memory_gauges(tmp_path):
    """A traced train run leaves RUN.json beside its trace: run_id
    matches the telemetry meta line, artifacts index the metrics files
    and the (single-host) shard names."""
    from sketch_rnn_tpu.utils import runinfo

    trace_dir = str(tmp_path / "trace")
    _run_smoke(tmp_path, "wd", trace_dir)
    man = runinfo.read_manifest(trace_dir)
    assert man is not None
    assert man["kind"] == "train" and man["config_hash"]
    assert man["artifacts"]["telemetry_shards"] == ["telemetry.jsonl"]
    meta = json.loads(open(
        os.path.join(trace_dir, "telemetry.jsonl")).readline())
    assert meta["run_id"] == man["run_id"]
    assert meta["process_index"] == 0 and meta["host_count"] == 1
    csvs = [p for p in man["artifacts"]["metrics"] if p.endswith(".csv")]
    assert any(os.path.exists(p) for p in csvs)


# -- causal trace context + critical-path math (ISSUE 11) ---------------------


def test_span_link_and_request_id_contract():
    """The propagation helper and the deterministic span-id naming:
    pure functions of (uid, hop, attempt), attempt 0 keeps bare names
    so healthy traces read identically to pre-failover ones."""
    link = tele.span_link("req-3", "queue-3", "request-3")
    assert link == {"id": "req-3", "span": "queue-3",
                    "parent": "request-3"}
    assert "parent" not in tele.span_link("req-3", "request-3")

    assert tele.request_trace_id(7) == "req-7"
    assert tele.request_span_id("queue", 7) == "queue-7"
    assert tele.request_span_id("queue", 7, attempt=2) == "queue-7-a2"
    # attempt 0 hops hang under the root; attempt N under the retry
    assert tele.request_parent_id(7) == "request-7"
    assert tele.request_parent_id(7, 2) == "retry-7-a2"


def test_critical_path_segments_sum_bitwise():
    """The decomposition's in-order float sum equals latency_s
    BITWISE — including adversarial float pairs where the naive
    latency - queue remainder is an ulp off."""
    rng = np.random.default_rng(11)
    for _ in range(2000):
        q = float(rng.uniform(0, 1e3) * 10.0 ** rng.integers(-9, 3))
        lat = q + float(rng.uniform(0, 1e3)
                        * 10.0 ** rng.integers(-9, 3))
        segs = tele.critical_path_segments(q, lat)
        assert [s[0] for s in segs] == ["queue_wait_s", "decode_s"]
        assert tele.segments_sum(segs) == lat
    # degenerate clocks still sum exactly
    assert tele.segments_sum(tele.critical_path_segments(0.0, 0.0)) == 0.0
    for segs in (tele.critical_path_segments(0.5, 0.5),
                 tele.critical_path_segments(1e-300, 1.0)):
        assert tele.segments_sum(segs) == segs[0][1] + segs[1][1]


def test_attribute_chunk_steps_exact_integer_split():
    """Each chunk's steps split deterministically over its live slots:
    shares sum EXACTLY, remainder goes to the lowest slot indices."""
    assert tele.attribute_chunk_steps(8, 4) == [2, 2, 2, 2]
    assert tele.attribute_chunk_steps(7, 3) == [3, 2, 2]
    assert tele.attribute_chunk_steps(2, 5) == [1, 1, 0, 0, 0]
    for chunk in (1, 2, 7, 64):
        for n in range(1, 9):
            shares = tele.attribute_chunk_steps(chunk, n)
            assert sum(shares) == chunk
            assert max(shares) - min(shares) <= 1
    with pytest.raises(ValueError, match="n_live"):
        tele.attribute_chunk_steps(4, 0)


def test_tail_attribution_verdicts():
    """Queue- vs decode-dominated tails, deterministic ties, empty
    input -> None."""
    assert tele.tail_attribution([]) is None
    qrows = [(lat, [("queue_wait_s", lat * 0.9),
                    ("decode_s", lat * 0.1)])
             for lat in (0.1, 0.2, 0.3, 1.0)]
    t = tele.tail_attribution(qrows)
    assert t["dom"] == "queue" and t["dom_frac"] == pytest.approx(0.9)
    assert t["tail_n"] >= 1
    drows = [(lat, [("queue_wait_s", lat * 0.2),
                    ("decode_s", lat * 0.8)])
             for lat in (0.1, 0.2, 0.3, 1.0)]
    assert tele.tail_attribution(drows)["dom"] == "decode"
    # exact tie breaks in segment order (queue first) — deterministic
    tie = [(1.0, [("queue_wait_s", 0.5), ("decode_s", 0.5)])]
    assert tele.tail_attribution(tie)["dom"] == "queue"


def test_chrome_flow_events_chain_per_trace():
    """Flow events chain each trace's hops in time order (s -> t ->
    f); single-event traces draw no arrow."""
    flows = tele.chrome_flow_events([
        ("req-1", 30.0, 0, 2),   # out of order on purpose
        ("req-1", 10.0, 0, 1),
        ("req-1", 20.0, 0, 2),
        ("req-2", 5.0, 0, 1),    # lone event: no arrow
    ])
    assert [f["ph"] for f in flows] == ["s", "t", "f"]
    assert [f["ts"] for f in flows] == [10.0, 20.0, 30.0]
    assert all(f["name"] == "req-1" for f in flows)
    assert flows[-1]["bp"] == "e"
    ids = {f["id"] for f in flows}
    assert len(ids) == 1


def test_trace_stamped_events_ride_exporters(tmp_path):
    """A trace= stamp rides the event into both exporters: the JSONL
    event carries `trace` verbatim; the Chrome trace puts it in args
    and chains flow arrows across the stamped events."""
    tel = tele.configure(trace_dir=str(tmp_path))
    link_a = tele.span_link("req-1", "enqueue-1", "request-1")
    link_b = tele.span_link("req-1", "complete-1", "request-1")
    tel.instant("enqueue", cat="serve", args={"uid": 1}, trace=link_a)
    t0 = tel.origin_perf
    tel.emit_span("decode", "serve", t0, t0 + 0.01, args={"uid": 1})
    tel.instant("complete", cat="serve", args={"uid": 1}, trace=link_b)
    paths = tel.export()
    tele.disable()

    evs = [json.loads(l) for l in open(paths["jsonl"])]
    stamped = [e for e in evs if e.get("trace")]
    assert [e["trace"] for e in stamped] == [link_a, link_b]
    # unstamped events stay clean — no trace key at all
    decode = next(e for e in evs if e.get("name") == "decode")
    assert "trace" not in decode

    chrome = json.load(open(paths["chrome"]))["traceEvents"]
    args_traces = [e["args"]["trace"] for e in chrome
                   if e.get("args", {}).get("trace")]
    assert args_traces == [link_a, link_b]
    flows = [e for e in chrome if e["ph"] in ("s", "t", "f")]
    assert [f["ph"] for f in flows] == ["s", "f"]
    assert all(f["name"] == "req-1" for f in flows)
