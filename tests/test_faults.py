"""Fault-injection layer tests (ISSUE 10): the plan grammar, the pure
firing decision, the retry helper, and every wired site — including the
crash-mid-save pin (torn commit between sidecar and msgpack) and the
watchdog's injection->detection evidence loop. The end-to-end matrix
(crash+resume bitwise equivalence, fleet failover parity) lives in
tests/test_resilience_bench.py and tests/test_fleet.py."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.utils import faults
from sketch_rnn_tpu.utils.faults import (
    FaultSpec,
    InjectedFault,
    backoff_s,
    parse_plan,
    retry_call,
)


# -- plan grammar ------------------------------------------------------------


def test_parse_plan_grammar():
    plan = parse_plan("a@3,b:every=2,c:p=0.5,d@0:kind=exit,"
                      "e@1:times=3,f:p=1.0:kind=nan")
    assert plan["a"].at == 3 and plan["a"].max_fires == 1
    assert plan["b"].every == 2 and plan["b"].max_fires is None
    assert plan["c"].p == 0.5
    assert plan["d"].kind == "exit"
    assert plan["e"].times == 3 and plan["e"].max_fires == 3
    assert plan["f"].kind == "nan"
    assert parse_plan("") == {} and parse_plan(None) == {}


@pytest.mark.parametrize("bad", [
    "a@x", "a", "a@1:every=2", "a:kind=boom", "a@1:wat=2", "@1",
    "a:every=0", "a:p=0", "a:p=1.5", "a@1,a@2", "a:nokey",
])
def test_parse_plan_rejects_bad_specs(bad):
    with pytest.raises(ValueError):
        parse_plan(bad)


def test_firing_decision_pure_and_deterministic():
    spec = FaultSpec(site="s", p=0.3)
    draws = [spec.due(7, n) for n in range(200)]
    assert draws == [spec.due(7, n) for n in range(200)]  # pure
    frac = sum(draws) / len(draws)
    assert 0.15 < frac < 0.45          # roughly p, never exact
    # a different seed fires a different (but equally deterministic) set
    assert draws != [spec.due(8, n) for n in range(200)]
    at = FaultSpec(site="s", at=5)
    assert [at.due(0, n) for n in range(8)] == [False] * 5 + [True,
                                                              False,
                                                              False]
    ev = FaultSpec(site="s", every=3)
    assert [ev.due(0, n) for n in range(7)] == [True, False, False,
                                               True, False, False,
                                               True]


def test_injector_counts_caps_and_summary():
    inj = faults.configure("a:every=1:times=2", seed=3)
    fired = 0
    for _ in range(5):
        try:
            inj.hit("a")
        except InjectedFault:
            fired += 1
    assert fired == 2                   # times cap
    assert inj.count("a") == 5
    s = inj.summary()
    assert [f["invocation"] for f in s["fired"]] == [0, 1]
    assert s["plan"]["a"]["every"] == 1
    faults.disable()
    assert faults.get_injector() is None


def test_disabled_sites_are_noops():
    faults.disable()
    faults.fault_point("anything")       # no raise
    assert faults.corrupt_value("anything", 2.5) == 2.5


def test_nan_kind_only_fires_at_value_sites():
    faults.configure("v@0:kind=nan")
    try:
        faults.fault_point("v")          # raising site ignores nan spec
        assert np.isnan(faults.corrupt_value("v", 1.0))
        assert faults.corrupt_value("v", 1.0) == 1.0  # at=0 spent
    finally:
        faults.disable()


def test_injected_faults_tick_telemetry_counters():
    from sketch_rnn_tpu.utils import telemetry as tele

    tel = tele.configure(trace_dir=None)
    faults.configure("ckpt.commit@0")
    try:
        with pytest.raises(InjectedFault):
            faults.fault_point("ckpt.commit")
        counters = tel.counters()
        assert counters[("faults", "faults_injected")] == 1
        assert counters[("faults", "faults_injected_ckpt_commit")] == 1
    finally:
        faults.disable()
        tele.disable()


# -- retry helper ------------------------------------------------------------


def test_backoff_schedule_is_deterministic():
    assert backoff_s(0.0, 5) == 0.0
    assert backoff_s(0.1, 0) == pytest.approx(0.1)
    assert backoff_s(0.1, 3) == pytest.approx(0.8)
    assert backoff_s(0.1, 30) == 2.0    # capped


def test_retry_call_bounded():
    calls = []

    def flaky(fail_times):
        def fn():
            calls.append(1)
            if len(calls) <= fail_times:
                raise OSError("disk hiccup")
            return "ok"
        return fn

    assert retry_call(flaky(2), retries=2) == "ok"
    assert len(calls) == 3
    calls.clear()
    with pytest.raises(OSError, match="hiccup"):
        retry_call(flaky(99), retries=2)
    assert len(calls) == 3              # bounded: 1 + 2 retries
    with pytest.raises(ValueError, match="retries"):
        retry_call(lambda: None, retries=-1)


# -- wired sites -------------------------------------------------------------


def _tiny_state():
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train.state import make_train_state

    hps = HParams(batch_size=4, max_seq_len=8, enc_rnn_size=8,
                  dec_rnn_size=8, z_size=4, num_mixture=2,
                  ckpt_retry_backoff_s=0.0)
    model = SketchRNN(hps)
    return hps, make_train_state(model, hps, jax.random.key(0))


def test_ckpt_commit_transient_retried_bitwise(tmp_path):
    """A transient commit failure is retried and the retried file is
    byte-identical to an unfaulted save's."""
    from sketch_rnn_tpu.train.checkpoint import save_checkpoint

    hps, state = _tiny_state()
    clean = save_checkpoint(str(tmp_path / "clean"), state, 1.0, hps)
    faults.configure("ckpt.commit@0")
    try:
        path = save_checkpoint(str(tmp_path / "faulted"), state, 1.0,
                               hps, retries=2, retry_backoff_s=0.0)
    finally:
        faults.disable()
    assert open(path, "rb").read() == open(clean, "rb").read()
    # without a retry budget the same fault stops the save loudly
    faults.configure("ckpt.commit@0")
    try:
        with pytest.raises(InjectedFault):
            save_checkpoint(str(tmp_path / "nofretry"), state, 1.0, hps)
    finally:
        faults.disable()


def test_crash_mid_save_torn_commit_pins_previous_checkpoint(tmp_path):
    """ISSUE 10 satellite: kill the commit BETWEEN the sidecar and
    msgpack writes (the documented torn-write window, now exercised
    under injection) — latest_checkpoint, _prune and resume must all
    agree on the previous COMPLETE checkpoint."""
    from sketch_rnn_tpu.train.checkpoint import (
        _prune,
        latest_checkpoint,
        restore_checkpoint,
        save_checkpoint,
    )

    hps, state = _tiny_state()
    d = str(tmp_path)
    save_checkpoint(d, state._replace(step=jnp.asarray(3, jnp.int32)),
                    1.5, hps)
    faults.configure("ckpt.torn@0")
    try:
        with pytest.raises(InjectedFault):
            save_checkpoint(
                d, state._replace(step=jnp.asarray(6, jnp.int32)), 1.5,
                hps)
    finally:
        faults.disable()
    # the torn save left only the step-6 sidecar: an orphan, not a
    # checkpoint
    names = sorted(os.listdir(d))
    assert "ckpt_00000006.json" in names
    assert "ckpt_00000006.msgpack" not in names
    assert latest_checkpoint(d) == 3
    restored, scale, _ = restore_checkpoint(d, state)
    assert int(restored.step) == 3 and scale == 1.5
    # cleanup agrees with resume: the orphan is pruned, step 3 kept
    _prune(d, keep=3)
    names = sorted(os.listdir(d))
    assert "ckpt_00000006.json" not in names
    assert latest_checkpoint(d) == 3
    # and a retried torn commit self-heals: the commit is idempotent
    faults.configure("ckpt.torn@0")
    try:
        save_checkpoint(
            d, state._replace(step=jnp.asarray(9, jnp.int32)), 1.5, hps,
            retries=1, retry_backoff_s=0.0)
    finally:
        faults.disable()
    assert latest_checkpoint(d) == 9


def test_data_batch_fault_site_fires_in_assembly():
    from sketch_rnn_tpu.data.loader import DataLoader, \
        make_synthetic_strokes

    hps = HParams(batch_size=4, max_seq_len=16)
    seqs, labels = make_synthetic_strokes(8, max_len=12, seed=0)
    loader = DataLoader(seqs, hps, labels=labels, seed=0)
    faults.configure("data.batch@1")
    try:
        loader.random_batch()            # invocation 0 passes
        with pytest.raises(InjectedFault, match="data.batch"):
            loader.random_batch()
        loader.random_batch()            # one-shot: the stream survives
    finally:
        faults.disable()


def test_metrics_sites_write_and_nan_row(tmp_path):
    from sketch_rnn_tpu.train.metrics import MetricsDrain, MetricsWriter

    w = MetricsWriter(str(tmp_path), "train")
    faults.configure("metrics.write@0")
    try:
        with pytest.raises(InjectedFault, match="metrics.write"):
            w.write(1, {"loss": 1.0})
    finally:
        faults.disable()
    # the value-corruption site NaNs a drained row's loss (and ONLY
    # the planned invocation)
    faults.configure("metrics.row@1:kind=nan")
    try:
        drain = MetricsDrain(w, defer=False)
        drain.push(1, {"loss": 1.0})
        drain.push(2, {"loss": 2.0})
        drain.push(3, {"loss": 3.0})
    finally:
        faults.disable()
    rows = [json.loads(line) for line in
            open(tmp_path / "train_metrics.jsonl")]
    assert [r["loss"] for r in rows][0] == 1.0
    assert np.isnan(rows[1]["loss"]) and rows[2]["loss"] == 3.0


def test_async_writer_fault_raises_one_save_late(tmp_path):
    from sketch_rnn_tpu.train.async_ckpt import AsyncCheckpointer

    hps, state = _tiny_state()
    ckpt = AsyncCheckpointer(str(tmp_path))
    faults.configure("ckpt.writer@0")
    try:
        ckpt.save(state, 1.0, hps)       # writer dies in background
        ckpt.join()
        assert isinstance(ckpt.failure, InjectedFault)
        with pytest.raises(RuntimeError, match="async checkpoint"):
            ckpt.save(state, 1.0, hps)   # surfaces one save late
    finally:
        faults.disable()
        ckpt.join()


def test_async_commit_transient_retried_in_background(tmp_path):
    """The writer thread's commit rides the same bounded retry: a
    transient failure never surfaces to the loop at all."""
    from sketch_rnn_tpu.train.async_ckpt import AsyncCheckpointer
    from sketch_rnn_tpu.train.checkpoint import latest_checkpoint

    hps, state = _tiny_state()          # ckpt_retries=2 default
    ckpt = AsyncCheckpointer(str(tmp_path))
    faults.configure("ckpt.commit@0")
    try:
        ckpt.save(state, 1.0, hps)
        ckpt.wait()                      # no raise: the retry absorbed it
    finally:
        faults.disable()
    assert latest_checkpoint(str(tmp_path)) == 0


def test_watchdog_incident_records_fault_evidence(tmp_path):
    """ISSUE 10 satellite: an incident written while a chaos plan is
    armed embeds the injector's fired log — the triggering fault site
    is in the post-mortem's evidence."""
    from sketch_rnn_tpu.train.watchdog import WatchdogMonitor

    inj = faults.configure("metrics.row@0:kind=nan")
    try:
        bad = inj.corrupt("metrics.row", 1.0)
        assert np.isnan(bad)
        mon = WatchdogMonitor(str(tmp_path)).arm()
        try:
            mon({"loss": bad}, step=4)
        finally:
            mon.disarm()
    finally:
        faults.disable()
    inc = json.load(open(tmp_path / "incident.json"))
    assert inc["anomalies"][0]["kind"] == "nonfinite"
    assert [f["site"] for f in inc["faults"]["fired"]] == ["metrics.row"]
    assert inc["faults"]["plan"]["metrics.row"]["kind"] == "nan"


# -- loader / ndjson hardening (ISSUE 10 satellite) --------------------------


def test_corrupt_npz_record_fails_with_one_line_error(tmp_path):
    from sketch_rnn_tpu.data.loader import load_dataset, \
        make_synthetic_strokes

    seqs, _ = make_synthetic_strokes(30, max_len=12, seed=0)
    sets = {}
    for split, lo, hi in (("train", 0, 20), ("valid", 20, 25),
                          ("test", 25, 30)):
        arr = np.empty(hi - lo, dtype=object)
        arr[:] = seqs[lo:hi]
        sets[split] = arr
    sets["train"][3] = np.zeros((4, 7), np.float32)   # wrong columns
    path = tmp_path / "cat.npz"
    np.savez_compressed(path, **sets)
    hps = HParams(batch_size=2, max_seq_len=16, data_set=("cat.npz",),
                  data_dir=str(tmp_path))
    with pytest.raises(ValueError) as ei:
        load_dataset(hps)
    msg = str(ei.value)
    assert "cat.npz[train] record 3" in msg and "\n" not in msg

    # under the explicit flag the record is skipped and counted
    from sketch_rnn_tpu.utils import telemetry as tele

    tel = tele.configure(trace_dir=None)
    try:
        train_l, _, _, _ = load_dataset(hps, skip_bad_records=True)
        assert len(train_l) == 19
        assert tel.counters()[("data", "records_skipped")] == 1
    finally:
        tele.disable()


def test_unreadable_npz_fails_with_file_name(tmp_path):
    from sketch_rnn_tpu.data.loader import load_dataset

    path = tmp_path / "cat.npz"
    path.write_bytes(b"PK\x03\x04 truncated garbage")
    hps = HParams(batch_size=2, max_seq_len=16, data_set=("cat.npz",),
                  data_dir=str(tmp_path))
    with pytest.raises(RuntimeError, match="cat.npz"):
        load_dataset(hps)


def test_corrupt_ndjson_line_named_or_skipped():
    from sketch_rnn_tpu.data.quickdraw import iter_ndjson

    good = json.dumps({"word": "cat", "recognized": True,
                       "drawing": [[[0, 1, 2], [0, 1, 0]]]})
    lines = [good, '{"torn": tru', good, '{"word": "x"}']
    with pytest.raises(ValueError) as ei:
        list(iter_ndjson(lines, source="cat.ndjson"))
    assert "cat.ndjson line 2" in str(ei.value)
    assert "\n" not in str(ei.value)
    out = list(iter_ndjson(lines, source="cat.ndjson", skip_bad=True))
    assert len(out) == 2                # both bad lines skipped


# -- off-by-default invisibility ---------------------------------------------


def test_armed_never_firing_plan_is_bitwise_invisible(tmp_path):
    """An armed plan whose sites never fire must not change training at
    all: metrics files and final state bitwise equal a faults-off run
    (the decision hashes — it never draws from any RNG stream)."""
    from sketch_rnn_tpu.data.loader import synthetic_loader
    from sketch_rnn_tpu.train.loop import train

    hps = HParams(batch_size=4, max_seq_len=16, enc_rnn_size=8,
                  dec_rnn_size=8, z_size=4, num_mixture=2,
                  num_steps=4, save_every=10 ** 9, log_every=2,
                  eval_every=10 ** 9, prefetch_depth=0)

    def run(sub, plan):
        loader, scale = synthetic_loader(hps, 16, seed=1, augment=True)
        if plan:
            faults.configure(plan)
        try:
            state = train(hps, loader, scale_factor=scale,
                          workdir=str(tmp_path / sub), seed=0,
                          use_mesh=False, resume=False)
        finally:
            faults.disable()
        return state

    s_off = run("off", None)
    s_armed = run("armed", "train.step@999999,ckpt.commit@999999")
    for a, b in zip(jax.tree_util.tree_leaves(s_off),
                    jax.tree_util.tree_leaves(s_armed)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    csv_off = (tmp_path / "off" / "train_metrics.csv").read_text()
    csv_armed = (tmp_path / "armed" / "train_metrics.csv").read_text()

    def strip_wall(text):
        import csv as _csv
        import io
        rows = list(_csv.DictReader(io.StringIO(text)))
        for r in rows:
            r.pop("wall_time", None)
            for k in list(r):
                if k.startswith("t_") or "per_sec" in k:
                    r.pop(k)
        return rows

    assert strip_wall(csv_off) == strip_wall(csv_armed)
