"""Benchmark: flagship training-step throughput in strokes/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The metric is BASELINE.json's "QuickDraw strokes/sec/chip": stroke points
processed per second of training (global batch x padded seq len per step),
divided by chip count. ``vs_baseline`` is 1.0 because the reference
published no number (BASELINE.json "published": {}); when an A100 baseline
becomes available, set the BENCH_BASELINE env var to it.

Env knobs: BENCH_STEPS (timed steps, default 20), BENCH_BATCH,
BENCH_SEQ_LEN, BENCH_DEC (decoder cell), BENCH_DTYPE (float32|bfloat16),
BENCH_REMAT (0|1).

Defaults are the measured-best v5e config (see ops/rnn.py docstring and
the sweep recorded in PROGRESS notes): bfloat16 matmuls, global batch
2048/chip, jax.checkpoint'd scans — 2.56M strokes/sec/chip vs 1.29M for
the first float32 batch-128 configuration.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import numpy as np


def main() -> int:
    from sketch_rnn_tpu.config import get_default_hparams
    from sketch_rnn_tpu.data.loader import synthetic_loader
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.parallel.mesh import make_mesh, shard_batch
    from sketch_rnn_tpu.train import make_train_state, make_train_step

    n_chips = jax.device_count()
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    batch = int(os.environ.get("BENCH_BATCH", "2048")) * n_chips
    hps = get_default_hparams().replace(
        dec_model=os.environ.get("BENCH_DEC", "layer_norm"),
        batch_size=batch,
        max_seq_len=int(os.environ.get("BENCH_SEQ_LEN", "250")),
        compute_dtype=os.environ.get("BENCH_DTYPE", "bfloat16"),
        remat=os.environ.get("BENCH_REMAT", "1") == "1",
    )

    model = SketchRNN(hps)
    mesh = make_mesh(hps)
    loader, _ = synthetic_loader(hps, min(batch, 2048), seed=0)
    host_batch = loader.random_batch()

    state = make_train_state(model, hps, jax.random.key(0))
    step = make_train_step(model, hps, mesh)
    dev_batch = shard_batch(host_batch, mesh)
    key = jax.random.key(1)

    # warmup: both compiles (initial-sharding + donated steady state) and a
    # settled step; sync via host value fetch — under the axon runtime,
    # block_until_ready alone does not reliably drain the remote pipeline
    for i in range(3):
        state, metrics = step(state, dev_batch, jax.random.fold_in(key, i))
        float(metrics["loss"])

    best = float("inf")
    for trial in range(3):
        t0 = time.perf_counter()
        for i in range(steps):
            state, metrics = step(state, dev_batch,
                                  jax.random.fold_in(key, 100 + i))
        float(metrics["loss"])  # drains the chained steps
        best = min(best, time.perf_counter() - t0)
    dt = best

    strokes_per_sec = steps * hps.batch_size * hps.max_seq_len / dt
    per_chip = strokes_per_sec / n_chips
    baseline = float(os.environ.get("BENCH_BASELINE", "0") or 0)
    out = {
        "metric": "train_strokes_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "strokes/sec/chip",
        "vs_baseline": round(per_chip / baseline, 3) if baseline else 1.0,
    }
    print(json.dumps(out))
    print(f"# {n_chips} chip(s), dec={hps.dec_model}, "
          f"batch={hps.batch_size}, seq={hps.max_seq_len}, "
          f"dtype={hps.compute_dtype}, remat={hps.remat}, "
          f"{steps} steps in {dt:.2f}s, "
          f"loss={float(metrics['loss']):.4f}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
