"""Benchmark: flagship training-step throughput in strokes/sec/chip.

Streaming emission (VERDICT r5 weak #1): every per-config result row is
printed to STDOUT as its own JSON line THE MOMENT the cell completes, so
a backend outage or driver timeout mid-matrix still leaves parseable
partial results in the driver's captured stdout
(``scripts/bench_summary.py`` aggregates such partial/streamed logs).
The final line remains the flagship summary
{"metric", "value", "unit", "vs_baseline"} — consumers that read only
the last line are unaffected.

History routing (VERDICT r5 weak #4): records land in
BENCH_HISTORY.jsonl, EXCEPT smoke/CPU rows (``--smoke`` runs,
``device_kind == "cpu"``), which go to BENCH_SMOKE_HISTORY.jsonl — the
canonical history only accumulates accelerator rows, so best-of /
plausibility lookups never compare against a laptop run.

The metric is BASELINE.json's "QuickDraw strokes/sec/chip": stroke points
processed per second of training (global batch x padded seq len per step),
divided by chip count. ``vs_baseline`` is 1.0 because the reference
published no number (BASELINE.json "published": {}); when an A100 baseline
becomes available, set the BENCH_BASELINE env var to it.

Honest feeding: every timed step consumes a FRESH batch assembled on the
host and transferred through the overlapped input pipeline
(data/prefetch.py) — host batch-assembly cost is inside the measurement,
unlike a cached-device-batch bench (VERDICT r1 "what's weak" #3).

Each run also reports MFU against the chip's analytic roofline
(utils/flops.py) on stderr and appends a full record to
BENCH_HISTORY.jsonl so round-over-round regressions are visible.

Timing note: the prefetch queue may hold up to ``depth`` pre-assembled
gets when a timed trial starts, so at most ``depth / (steps/K)`` of the
host-assembly cost escapes the window — 40% at the defaults (depth 2,
25 steps, K=5). The steady-state overlap it reflects is exactly how the
training loop runs (the producer thread keeps pace with consumption;
C++ batch assembly is ~69x faster than the step itself), but treat the
assembly-cost component as partially amortized, not fully measured.

Recorded-number policy (VERDICT r2 #1): the adaptive trial loop reads
this config's best from BENCH_HISTORY.jsonl at startup and refuses to
honor its no-improvement early-stop while best-of-trials sits below 70%
of that historical best — in a uniformly slow tunnel window it keeps
trialing until BENCH_TIME_BUDGET is actually spent, because the
early-stop otherwise quits fastest exactly when retrying matters most
(the r02 record under-reported the build 3.5x this way).

Env knobs: BENCH_STEPS (timed steps, default 25 — short trials fit ~2x
more retries into a slow window's budget), BENCH_BATCH,
BENCH_SEQ_LEN, BENCH_DEC (decoder cell), BENCH_DTYPE (float32|bfloat16),
BENCH_REMAT (0|1), BENCH_PREFETCH (depth, default 2; 0 = synchronous
feed), BENCH_FUSED (default 1: Pallas recompute-backward kernels for
all three cells), BENCH_RESID (fused kernels' residual storage dtype,
default bfloat16 — halves residual HBM; float32 for exact-AD runs),
BENCH_MATRIX=1 (bench all three decoder cells; flagship line is still
the one JSON line printed), BENCH_SAMPLER=1 (also bench the on-device
sampler at B in {1, 64, 1024}), BENCH_SPC (steps_per_call: optimizer
steps per jitted call, default 5 — K fresh batches ride one stacked
transfer + one dispatch, so a tunnel-latency stall costs at most one
K-step window, not one per step; every timed step still consumes a
fresh host-assembled batch), BENCH_TRANSFER (strokes transfer dtype,
default int16 — the recommended real-data mode, now the bench default
(r5 decision) since the integer-origin corpus makes it both runnable
and EXACT: one flagship-scale train step is loss-BITWISE-equal to an
f32 feed (BENCH_HISTORY probe_int16_exact_flagship), throughput is at
parity with bfloat16 (same-window A/B/A int16/f32/int16 2026-07-31:
6.17M / 5.08M / 6.18M — f32 moves 2x the bytes and loses ~17%;
int16-vs-bf16 parity measured twice: 5.04/4.99/5.03M r4,
6.17-vs-6.12M r5). bfloat16 remains for float-natured corpora
(BENCH_GRID=0), float32 for exact-AD runs), BENCH_GRID
(integer-grid scale of the synthetic corpus,
default 255 — the corpus is integer-origin like QuickDraw, scale
factor ~17-65 depending on the class mix, so int16 transfer trains
with meaningful loss here;
0 restores the legacy float-natured corpus, which int16 refuses),
BENCH_CELL_DEADLINE (per-cell wall budget in seconds, default 900:
retry backoffs are capped by the remaining deadline and a cell whose
backoff no longer fits records an ``unavailable`` row instead of
running the matrix into the driver's outer timeout).

Defaults are the measured-best v5e config: bfloat16 matmuls, global batch
4096/chip (amortizes the per-step dispatch/feed overhead — measured
+45% over 2048 under the axon tunnel; 8192 exceeds the 16G HBM),
fused Pallas kernels, jax.checkpoint'd scans.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import numpy as np


def _hist_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_HISTORY.jsonl")


def _smoke_hist_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_SMOKE_HISTORY.jsonl")


def _is_smoke_record(record: dict) -> bool:
    """Smoke/CPU rows must not pollute the canonical accelerator history
    (VERDICT r5 weak #4): a ``--smoke`` run's numbers are plumbing
    checks, and a CPU row in BENCH_HISTORY.jsonl reads as a catastrophic
    regression in round-over-round triage."""
    return bool(record.get("smoke")) or record.get("device_kind") == "cpu"


def _run_stamp() -> dict:
    """run_id + host topology for every history row (ISSUE 8): the key
    that joins a bench row to the trace shards / RUN.json of the same
    invocation, and the fleet coordinate that makes a multi-host row
    interpretable. Old rows simply lack the fields — every consumer
    (bench_summary.key_of, bench_regress) reads keys positionally and
    tolerates extras, tier-1-tested."""
    from sketch_rnn_tpu.utils import runinfo

    stamp = {"run_id": runinfo.get_run_id()}
    try:
        stamp["host_count"] = int(jax.process_count())
        stamp["process_index"] = int(jax.process_index())
    except Exception:  # noqa: BLE001 — stamping must never fail a bench
        pass
    return stamp


def _hist_append(record: dict) -> dict:
    """Stamp, route, append; returns the stamped record so streaming
    emitters print the SAME row the history holds (a captured stdout
    log may be the only surviving record — it must carry wall_time).

    ``wall_time`` is the run-manifest clock (runinfo.run_wall_time):
    ONE stamp per invocation, shared by every row the run emits and by
    its RUN.json — committed history rows then diff cleanly across
    re-runs instead of churning a fresh time.time() per row (ISSUE 14
    satellite)."""
    from sketch_rnn_tpu.utils import runinfo

    record = {"wall_time": runinfo.run_wall_time(), **_run_stamp(),
              **record}
    path = _smoke_hist_path() if _is_smoke_record(record) else _hist_path()
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")
    return record


def _hist_best_strokes(dec_model: str, batch: int, seq_len: int,
                       dtype: str, remat: bool, fused: bool,
                       resid_dtype: str, device_kind: str,
                       n_chips: int, prefetch_depth: int,
                       steps: int) -> float | None:
    """Best recorded strokes/sec/chip for this *physical* config.

    Pools across steps_per_call and transfer_dtype (dispatch-
    amortization knobs — near-neutral for sustained wall-clock in good
    windows), so the pooled best is the demanding steady-state target
    the retry policy holds the current window against. It does NOT pool
    across prefetch_depth: depth 0 is the documented synchronous
    strawman whose throughput is legitimately far below the overlapped
    pipeline's — gating it against depth-2 history would disable the
    early-stop forever and tag every accurate record implausible.
    (bench_summary keys on all the feed knobs for best/latest
    reporting — different purpose.)

    Keys on ``steps`` (VERDICT r4 #7, by construction): shorter trials
    let more of the host-assembly cost escape the timed window (up to
    ``depth/(steps/K)`` — ~40% at 25 steps vs ~20% at the pre-r3 50),
    so a pooled cross-``steps`` best would gate plausibility a few
    percent unlike-for-unlike. Every train row records ``steps``.
    """
    try:
        f = open(_hist_path())
    except OSError:
        return None
    best = None
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if (r.get("kind") != "train"
                    or r.get("dec_model") != dec_model
                    or r.get("batch_size") != batch
                    or r.get("seq_len") != seq_len
                    or r.get("dtype") != dtype
                    or bool(r.get("remat")) != remat
                    or bool(r.get("fused_rnn")) != fused
                    # rows predating the resid_dtype knob ran the then-
                    # default float32 residuals; treating the missing key
                    # as that default keeps legacy records arming the
                    # plausibility gate (ADVICE r3). On the non-fused
                    # (scan) path the knob is inert — residual storage is
                    # the fused kernels' concern — so it must not key the
                    # gate there: a bfloat16-labelled scan row and a
                    # float32 one are the same physical workload.
                    or (fused
                        and r.get("resid_dtype", "float32") != resid_dtype)
                    # a row from a different accelerator generation or
                    # chip count would set an unreachable (or uselessly
                    # low) target: batch_size is GLOBAL, so the same
                    # global batch at a different n_chips is a different
                    # per-chip workload
                    or r.get("device_kind") != device_kind
                    or r.get("n_chips") != n_chips
                    or r.get("prefetch_depth") != prefetch_depth
                    or r.get("steps") != steps):
                continue
            v = r.get("strokes_per_sec_per_chip")
            if v is not None and (best is None or v > best):
                best = v
    return best


def _unavailable(err: BaseException) -> bool:
    """Classify a failure as backend-unavailable (the 2x120s-backoff
    retry class for genuine tunnel/backend outages).

    Matches on exception TYPE plus anchored phrasing, not a bare
    'UNAVAILABLE' substring (ADVICE r5): XLA status errors surface as
    ``XlaRuntimeError`` with the status code as the message PREFIX
    ('UNAVAILABLE: ...'), and jax backend-init failures raise
    RuntimeError messages STARTING with 'Unable to initialize
    backend'. An unrelated error that merely quotes the word
    UNAVAILABLE somewhere in its text (e.g. an XLA status string
    embedded in a wrapped exception) stays in the quick-retry class.
    """
    msg = str(err)
    # walk the type hierarchy by name: XlaRuntimeError's import path
    # moved across jaxlib versions, but the name is stable
    is_xla = any(t.__name__ == "XlaRuntimeError"
                 for t in type(err).__mro__)
    if is_xla and msg.startswith("UNAVAILABLE"):
        return True
    return msg.startswith("Unable to initialize backend")


# minimum useful remainder of a cell's deadline: a retry must leave room
# for the sleep plus compile + warmup + a couple of trials, otherwise the
# cell should record its outage instead of running into the outer timeout
_RETRY_MARGIN_S = 60.0


def _retry_decision(used: dict, cls: str, elapsed: float,
                    deadline: float) -> tuple:
    """Per-failure retry decision for one bench cell (pure, unit-tested).

    Returns ``(action, sleep_s)``: ``"retry"`` (sleep ``sleep_s`` then
    re-run), ``"raise"`` (this class's retry budget is exhausted), or
    ``"give_up"`` (the remaining cell deadline cannot fit the backoff
    plus a meaningful attempt — the cell must emit its ``_unavailable``
    row NOW, while there is still budget to emit anything). Sleeps are
    capped by the remaining deadline: BENCH_r05 recorded rc=124 with
    ``parsed: null`` because an uncapped 120s unavailable backoff ran
    the matrix into the driver's outer ``timeout`` mid-retry, losing the
    whole round's record.
    """
    budget, delay = (2, 120.0) if cls == "unavail" else (1, 10.0)
    if used.get(cls, 0) >= budget:
        return "raise", 0.0
    remaining = deadline - elapsed
    if remaining <= _RETRY_MARGIN_S:
        return "give_up", 0.0
    return "retry", min(delay, remaining - _RETRY_MARGIN_S)


def _unavailable_row(cell: str, err: BaseException, used: dict,
                     elapsed: float) -> dict:
    """The cell's outage record: streamed and history-appended in place
    of a result row so a dead backend window still leaves a parseable,
    attributable trace (consumers key on ``kind`` and ignore it for
    best-of/plausibility)."""
    return {
        "kind": "unavailable",
        "dec_model": cell,
        "error": repr(err)[:300],
        "unavail_retries": used.get("unavail", 0),
        "other_retries": used.get("other", 0),
        "elapsed_s": round(elapsed, 1),
    }


def _should_stop(trial: int, no_improve: int, best_t: float,
                 plaus_t: float, elapsed: float, budget_s: float,
                 max_trials: int) -> str | None:
    """Stop decision for the adaptive trial loop (pure, unit-tested).

    ``trial`` counts COMPLETED trials. The no-improvement early-stop and
    the trial cap are honored only while best-of is PLAUSIBLE (within
    70% of the config's historical best, encoded as ``best_t <=
    plaus_t``); in the implausible regime the wall-clock budget is the
    only stop, so a uniformly slow window keeps retrying instead of
    recording a number 3.5x under the build's speed (the r02 failure).
    Returns a reason string to stop, else None.
    """
    plausible = best_t <= plaus_t
    if plausible and trial >= 4 and no_improve >= 3:
        return "early-stop"
    if plausible and trial >= max_trials:
        return "max-trials"
    if trial >= 2 and elapsed > budget_s:
        return "budget-implausible" if not plausible else "budget"
    return None


def bench_train(dec_model: str, steps: int, batch_per_chip: int,
                seq_len: int, dtype: str, remat: bool,
                prefetch_depth: int, fused: bool = False,
                resid_dtype: str = "float32",
                steps_per_call: int = 1,
                transfer_dtype: str = "float32",
                corpus_grid: float | None = 255.0) -> dict:
    """Measure train-step throughput for one decoder cell; fresh batch
    per timed step via the prefetch pipeline. ``steps_per_call=K`` runs
    K optimizer steps per jitted call (lax.scan; one dispatch + one
    stacked transfer per K fresh batches) — the training loop's
    host-loop-amortization mode, which insulates the measurement from
    the tunneled runtime's per-launch latency stalls."""
    if steps_per_call < 1 or steps % steps_per_call != 0:
        raise ValueError(
            f"steps={steps} must be a positive multiple of "
            f"steps_per_call={steps_per_call}; throughput is computed "
            f"over `steps` so a silent floor-division would inflate it")

    from sketch_rnn_tpu.config import get_default_hparams
    from sketch_rnn_tpu.data.loader import synthetic_loader
    from sketch_rnn_tpu.data.prefetch import prefetch_batches
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.parallel.mesh import make_mesh
    from sketch_rnn_tpu.train import make_train_state
    from sketch_rnn_tpu.train.step import make_multi_train_step
    from sketch_rnn_tpu.utils import flops as F

    n_chips = jax.device_count()
    batch = batch_per_chip * n_chips
    hps = get_default_hparams().replace(
        dec_model=dec_model, batch_size=batch, max_seq_len=seq_len,
        compute_dtype=dtype, remat=remat, prefetch_depth=prefetch_depth,
        fused_rnn=fused, fused_residual_dtype=resid_dtype,
        steps_per_call=steps_per_call, transfer_dtype=transfer_dtype)

    model = SketchRNN(hps)
    mesh = make_mesh(hps)
    # corpus smaller than the batch: random_batch samples with replacement,
    # so assembly cost is the real per-step cost while corpus memory stays
    # bounded. Integer-origin by default (VERDICT r4 #2): scale
    # factor > 5, so transfer_dtype="int16" trains with meaningful
    # loss here
    # instead of refusing. The corpus does not key the history gate —
    # dense TPU compute is data-independent (measured A/B/A parity),
    # so throughput rows stay comparable across corpora; `loss` values
    # across the corpus change are NOT comparable (corpus_grid in the
    # row marks which corpus produced each).
    loader, _ = synthetic_loader(hps, min(batch, 4096), seed=0,
                                 integer_grid=corpus_grid)

    state = make_train_state(model, hps, jax.random.key(0))
    step = make_multi_train_step(model, hps, mesh)  # single step when K=1
    key = jax.random.key(1)
    calls = steps // steps_per_call

    # depth 0 = the synchronous strawman the pipeline is measured against
    feeder = prefetch_batches(loader, mesh, depth=prefetch_depth,
                              stack=steps_per_call,
                              transfer_dtype=transfer_dtype)
    try:
        # warmup: both compiles (initial-sharding + donated steady state)
        # and a settled step; sync via host value fetch — under the axon
        # runtime, block_until_ready alone does not reliably drain the
        # remote pipeline
        for i in range(3):
            state, metrics = step(state, feeder.get(),
                                  jax.random.fold_in(key, i))
            float(metrics["loss"])

        best = float("inf")
        # adaptive best-of-n: the tunneled chip shows WINDOW-scale (minutes)
        # slowdowns of up to 2x that hit whole trials, not single steps —
        # keep trialing until 3 consecutive trials stop improving the best
        # by >2%, so one bad window cannot set the record. BUT the r02
        # postmortem (VERDICT r2 #1) showed the converse failure: in a
        # UNIFORMLY slow window every trial is "non-improving", the
        # early-stop fires fastest exactly when retrying matters most, and
        # the recorded number under-reports the build 3.5x. So the
        # early-stop is only honored once best-of-trials is PLAUSIBLE —
        # within 70% of this config's best in BENCH_HISTORY.jsonl; below
        # that, keep trialing until BENCH_TIME_BUDGET is truly spent,
        # waiting out the slow window. The budget (checked after >=2
        # trials) is the only stop in the implausible regime, so a dead
        # window still yields a record rather than a timeout.
        kind = jax.devices()[0].device_kind
        hist_best = _hist_best_strokes(dec_model, batch, seq_len, dtype,
                                       remat, fused, resid_dtype, kind,
                                       n_chips, prefetch_depth, steps)
        strokes_per_trial = steps * hps.batch_size * hps.max_seq_len
        # time_s above which best-of is implausibly slow vs history:
        # per_chip = strokes_per_trial / t / n_chips, solved for t at
        # per_chip = 0.7 * hist_best
        plaus_t = (strokes_per_trial / (0.7 * hist_best * n_chips)
                   if hist_best else float("inf"))
        if hist_best:
            print(f"#   history best for this config: {hist_best:,.0f} "
                  f"strokes/s/chip; early-stop honored only under "
                  f"{plaus_t:.1f}s/trial", file=sys.stderr)
        max_trials = int(os.environ.get("BENCH_TRIALS", "8"))
        budget_s = float(os.environ.get("BENCH_TIME_BUDGET", "480"))
        no_improve = 0
        trial = 0
        loop_t0 = time.perf_counter()
        while True:
            t0 = time.perf_counter()
            for i in range(calls):
                state, metrics = step(state, feeder.get(),
                                      jax.random.fold_in(key, 100 + i))
            float(metrics["loss"])  # drains the chained steps
            t = time.perf_counter() - t0
            print(f"#   trial {trial}: {t:.3f}s", file=sys.stderr)
            if t < best * 0.98:
                best, no_improve = t, 0
            else:
                best = min(best, t)
                no_improve += 1
            trial += 1
            reason = _should_stop(trial, no_improve, best, plaus_t,
                                  time.perf_counter() - loop_t0, budget_s,
                                  max_trials)
            if reason == "budget-implausible":
                print(f"#   budget ({budget_s:.0f}s) spent with "
                      f"best-of still below 70% of history best "
                      f"({hist_best:,.0f}); slow window recorded",
                      file=sys.stderr)
            elif reason == "budget":
                print(f"#   time budget ({budget_s:.0f}s) spent after "
                      f"trial {trial - 1}; stopping", file=sys.stderr)
            if reason:
                break
    finally:
        feeder.close()

    strokes_per_sec = steps * hps.batch_size * hps.max_seq_len / best
    per_chip = strokes_per_sec / n_chips
    mfu = F.mfu(per_chip, hps, kind, train=True)
    return {
        # False = the run never reached 70% of this config's historical
        # best (slow-window record): summaries and regression triage must
        # not read it as the build's speed
        "plausible": best <= plaus_t,
        "kind": "train",
        "fused_rnn": fused,
        "resid_dtype": resid_dtype,
        "dec_model": dec_model,
        "batch_size": batch,
        "seq_len": seq_len,
        "dtype": dtype,
        "remat": remat,
        "prefetch_depth": prefetch_depth,
        "steps_per_call": steps_per_call,
        "transfer_dtype": transfer_dtype,
        "steps": steps,
        "corpus_grid": corpus_grid,
        "time_s": round(best, 4),
        "strokes_per_sec_per_chip": round(per_chip, 1),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "device_kind": kind,
        "n_chips": n_chips,
        "loss": round(float(metrics["loss"]), 4),
    }


def bench_sampler(batch_sizes=(1, 64, 1024), max_len: int = 250) -> list:
    """Measure the on-device sampler: sketches/sec and steps/sec.

    The end-of-sketch pen logit is suppressed so the while_loop provably
    runs all ``max_len`` steps (an untrained model otherwise draws the
    end state within a few steps and the early-exit fires — pre-r3
    sampler history rows measured those few-step runs, overstating
    steps/sec up to ~15x; rows with ``"full_len": true`` are the honest
    series). Every sketch is then a worst-case full-length generation:
    steps/sec is the true per-step cost floor and sketches/sec its
    full-length lower bound (BASELINE north-star: generation needs no
    host sync — this records that it is also fast).
    """
    from sketch_rnn_tpu.config import get_default_hparams
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.sample.sampler import make_sampler

    hps = get_default_hparams().replace(
        dec_model=os.environ.get("BENCH_DEC", "layer_norm"),
        max_seq_len=max_len)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(0))
    params["out_b"] = params["out_b"].at[2].set(-1e9)
    out = []
    for b in batch_sizes:
        sampler = make_sampler(model, hps)
        z = jax.random.normal(jax.random.key(1), (b, hps.z_size))
        s5, lengths = sampler(params, jax.random.key(2), b, z, None, 0.7)
        executed = int(np.min(np.asarray(lengths)))  # warmup + drain
        if executed != max_len:
            # RuntimeError, not assert: under `python -O` an assert
            # vanishes and an early-exit run would be recorded with
            # full_len=true — the exact overstatement this check exists
            # to prevent (ADVICE r3)
            raise RuntimeError(
                f"sampler early-exited at {executed}/{max_len} steps "
                f"despite the suppressed pen-end logit; refusing to "
                f"record a full_len row")
        reps = 3 if b >= 1024 else 10
        t0 = time.perf_counter()
        for i in range(reps):
            s5, lengths = sampler(params, jax.random.fold_in(
                jax.random.key(3), i), b, z, None, 0.7)
        np.asarray(lengths)
        dt = (time.perf_counter() - t0) / reps
        out.append({
            "kind": "sampler",
            "batch_size": b,
            "max_len": max_len,
            "full_len": True,
            "dec_model": hps.dec_model,
            "time_per_call_s": round(dt, 5),
            "sketches_per_sec": round(b / dt, 2),
            "stroke_steps_per_sec": round(b * max_len / dt, 1),
            "device_kind": jax.devices()[0].device_kind,
        })
    return out


def main() -> int:
    steps = int(os.environ.get("BENCH_STEPS", "25"))
    batch_per_chip = int(os.environ.get("BENCH_BATCH", "4096"))
    seq_len = int(os.environ.get("BENCH_SEQ_LEN", "250"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    remat = os.environ.get("BENCH_REMAT", "1") == "1"
    depth = int(os.environ.get("BENCH_PREFETCH", "2"))
    fused = os.environ.get("BENCH_FUSED", "1") == "1"
    resid = os.environ.get("BENCH_RESID", "bfloat16")
    spc = int(os.environ.get("BENCH_SPC", "5"))
    transfer = os.environ.get("BENCH_TRANSFER", "int16")
    if spc < 1 or steps % spc != 0:
        # config error, not a transient — fail fast, don't retry
        print(f"BENCH_STEPS={steps} must be a positive multiple of "
              f"BENCH_SPC={spc}", file=sys.stderr)
        return 2
    if transfer not in ("float32", "bfloat16", "int16"):
        print(f"BENCH_TRANSFER={transfer!r} must be float32, bfloat16 "
              f"or int16", file=sys.stderr)
        return 2
    grid = float(os.environ.get("BENCH_GRID", "255"))
    corpus_grid = grid if grid > 0 else None  # 0 = legacy float corpus
    if transfer == "int16" and corpus_grid is None:
        print("BENCH_TRANSFER=int16 needs the integer-origin corpus; "
              "unset BENCH_GRID=0", file=sys.stderr)
        return 2
    flagship = os.environ.get("BENCH_DEC", "layer_norm")

    cells = (("lstm", "layer_norm", "hyper")
             if os.environ.get("BENCH_MATRIX") == "1" else (flagship,))
    if flagship not in cells:
        print(f"BENCH_DEC={flagship!r} is not a known cell {cells}",
              file=sys.stderr)
        return 2
    results = {}
    for cell in cells:
        # hyper carries [T, B, 2*hyper_size] extra residual streams; with
        # f32 residuals (or the scan path, which always saves f32 carries)
        # batch 4096 exceeds the 16G HBM — only bf16 fused residuals fit
        cell_batch = batch_per_chip
        if cell == "hyper" and (resid == "float32" or not fused):
            cell_batch = min(batch_per_chip, 2048)
        cell_t0 = time.perf_counter()
        # the per-cell wall budget retries must fit inside; the driver's
        # outer `timeout` should comfortably exceed n_cells * this
        deadline_s = float(os.environ.get("BENCH_CELL_DEADLINE", "900"))
        try:
            r = bench_train(cell, steps, cell_batch, seq_len, dtype,
                            remat, depth, fused=fused, resid_dtype=resid,
                            steps_per_call=spc, transfer_dtype=transfer,
                            corpus_grid=corpus_grid)
        except (ValueError, TypeError):
            # deterministic config/shape errors fail identically on
            # retry — re-raise and keep the round's 480s budget for
            # real (transient) retries (VERDICT r3 #8)
            raise
        except Exception as e:  # transient tunnel/compile hiccups: the
            # driver runs this once per round, so retries are cheap
            # insurance against losing the round's record. A wedged
            # tunnel surfaces as backend-init UNAVAILABLE (observed: a
            # multi-hour outage mid-round-5) — that class gets two
            # longer-backoff retries; other transients get one quick
            # one. The class is re-decided per failure so an outage
            # first surfacing as a generic error still earns the long
            # backoff, and deterministic errors (ValueError/TypeError)
            # keep failing fast even when raised by a retry. Sleeps are
            # capped by the cell deadline: when the backoff no longer
            # fits, the cell records an `unavailable` row instead of
            # running the matrix into the driver's outer timeout
            # (BENCH_r05: rc=124, parsed null, round record lost).
            last = e
            used = {"unavail": 0, "other": 0}   # per-class budgets
            while True:
                cls = "unavail" if _unavailable(last) else "other"
                action, delay = _retry_decision(
                    used, cls, time.perf_counter() - cell_t0, deadline_s)
                if action == "raise":
                    raise last
                if action == "give_up":
                    r = _unavailable_row(
                        cell, last, used,
                        time.perf_counter() - cell_t0)
                    print(f"# bench_train({cell}) giving up "
                          f"({deadline_s:.0f}s cell deadline cannot fit "
                          f"another {cls} backoff); recording "
                          f"unavailable row", file=sys.stderr)
                    break
                used[cls] += 1
                print(f"# bench_train({cell}) failed ({last!r}); "
                      f"{cls} retry {used[cls]} in {delay:.0f}s",
                      file=sys.stderr)
                time.sleep(delay)
                try:
                    r = bench_train(cell, steps, cell_batch, seq_len,
                                    dtype, remat, depth, fused=fused,
                                    resid_dtype=resid,
                                    steps_per_call=spc,
                                    transfer_dtype=transfer,
                                    corpus_grid=corpus_grid)
                    break
                except (ValueError, TypeError):
                    raise  # deterministic: identical on retry
                except Exception as e2:  # noqa: PERF203
                    last = e2
        results[cell] = r
        stamped = _hist_append(r)
        # streaming emission: the row is driver-visible the moment this
        # cell completes — an outage in a later cell can no longer lose
        # the whole matrix (stdout, flushed; stderr keeps the human copy)
        print(json.dumps(stamped), flush=True)
        print(f"# {json.dumps(stamped)}", file=sys.stderr)

    if os.environ.get("BENCH_SAMPLER") == "1":
        for r in bench_sampler():
            stamped = _hist_append(r)
            print(json.dumps(stamped), flush=True)
            print(f"# {json.dumps(stamped)}", file=sys.stderr)

    flag = results[flagship]
    baseline = float(os.environ.get("BENCH_BASELINE", "0") or 0)
    if flag.get("kind") == "unavailable":
        # the flagship cell never produced a number this round; the
        # summary line stays parseable (value null) and rc=1 flags the
        # degraded round — far better than the outer-timeout rc=124
        # that loses every streamed row after it
        print(json.dumps({
            "metric": "train_strokes_per_sec_per_chip",
            "value": None,
            "unit": "strokes/sec/chip",
            "vs_baseline": None,
            "unavailable": True,
        }))
        return 1
    per_chip = flag["strokes_per_sec_per_chip"]
    print(json.dumps({
        "metric": "train_strokes_per_sec_per_chip",
        "value": per_chip,
        "unit": "strokes/sec/chip",
        "vs_baseline": round(per_chip / baseline, 3) if baseline else 1.0,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
