"""Microbench: fused Pallas kernels vs lax.scan at the flagship decoder
shape (VERDICT r1 next #3: beat the 53.0 ms scan fwd+bwd baseline at
T=250 B=128 H=512, and cover the layer_norm cell).

Run on a real TPU:  python scripts/bench_kernel.py
Env: KB_T, KB_B, KB_H, KB_D, KB_DTYPE (float32|bfloat16), KB_STEPS.
"""

import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sketch_rnn_tpu.ops.cells import (HyperLSTMCell, LayerNormLSTMCell,
                                      LSTMCell)
from sketch_rnn_tpu.ops.pallas_fused import fused_lstm, fused_ln_lstm
from sketch_rnn_tpu.ops.rnn import run_rnn

T = int(os.environ.get("KB_T", "250"))
B = int(os.environ.get("KB_B", "128"))
H = int(os.environ.get("KB_H", "512"))
D = int(os.environ.get("KB_D", "133"))
DT = os.environ.get("KB_DTYPE", "float32")
STEPS = int(os.environ.get("KB_STEPS", "20"))
CD = jnp.bfloat16 if DT == "bfloat16" else None


def timeit(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / STEPS)
    return best * 1e3  # ms


def main():
    results = {}
    for name, cell_cls in (("lstm", LSTMCell), ("layer_norm",
                                                LayerNormLSTMCell)):
        cell = cell_cls(H, compute_dtype=CD)
        params = cell.init_params(jax.random.key(0), D)
        xs = jax.random.normal(jax.random.key(1), (T, B, D))
        c0 = jnp.zeros((B, H))
        h0 = jnp.zeros((B, H))

        def scan_loss(params_, xs_):
            _, hs = run_rnn(cell, params_, xs_, carry0=(c0, h0))
            return jnp.mean(hs ** 2)

        if name == "lstm":
            def fused_loss(params_, xs_):
                wx = params_["wx"].astype(CD) if CD else params_["wx"]
                wh = params_["wh"].astype(CD) if CD else params_["wh"]
                hs, _ = fused_lstm(xs_, wx, params_["b"], wh, c0, h0, 1.0)
                return jnp.mean(hs ** 2)
        else:
            def fused_loss(params_, xs_):
                wx = params_["wx"].astype(CD) if CD else params_["wx"]
                wh = params_["wh"].astype(CD) if CD else params_["wh"]
                hs, _ = fused_ln_lstm(xs_, wx, wh,
                                      params_["ln_gamma"],
                                      params_["ln_beta"],
                                      params_["lnc_gamma"],
                                      params_["lnc_beta"], c0, h0, 1.0)
                return jnp.mean(hs ** 2)

        for label, loss in (("scan", scan_loss), ("fused", fused_loss)):
            fwd = jax.jit(loss)
            fwdbwd = jax.jit(jax.grad(loss, argnums=(0, 1)))
            r = {
                "fwd_ms": round(timeit(fwd, params, xs), 2),
                "fwdbwd_ms": round(timeit(fwdbwd, params, xs), 2),
            }
            results[f"{name}/{label}"] = r
            print(f"{name:10s} {label:6s} fwd {r['fwd_ms']:8.2f} ms   "
                  f"fwd+bwd {r['fwdbwd_ms']:8.2f} ms", flush=True)

    # hyper cell: nested carry, dispatched through run_rnn(fused=...) —
    # the same path the model uses (flagship hyper sizes 256/32)
    cell = HyperLSTMCell(H, hyper_size=256, embed_size=32, compute_dtype=CD)
    params = cell.init_params(jax.random.key(0), D)
    xs = jax.random.normal(jax.random.key(1), (T, B, D))
    carry0 = cell.initial_carry(B)

    def hyper_loss(fused):
        def f(params_, xs_):
            _, hs = run_rnn(cell, params_, xs_, carry0=carry0, fused=fused)
            return jnp.mean(hs ** 2)
        return f

    for label, fused in (("scan", False), ("fused", True)):
        loss = hyper_loss(fused)
        fwd = jax.jit(loss)
        fwdbwd = jax.jit(jax.grad(loss, argnums=(0, 1)))
        r = {
            "fwd_ms": round(timeit(fwd, params, xs), 2),
            "fwdbwd_ms": round(timeit(fwdbwd, params, xs), 2),
        }
        results[f"hyper/{label}"] = r
        print(f"{'hyper':10s} {label:6s} fwd {r['fwd_ms']:8.2f} ms   "
              f"fwd+bwd {r['fwdbwd_ms']:8.2f} ms", flush=True)

    print(json.dumps({"shape": [T, B, H, D], "dtype": DT, **results}))


if __name__ == "__main__":
    main()
