"""Microbench: fused Pallas kernels vs lax.scan at the flagship decoder
shape (VERDICT r1 next #3: beat the 53.0 ms scan fwd+bwd baseline at
T=250 B=128 H=512, and cover the layer_norm cell).

Run on a real TPU:  python scripts/bench_kernel.py
Env: KB_T, KB_B, KB_H, KB_D, KB_DTYPE (float32|bfloat16), KB_STEPS.

``--mode serve_decode`` (ISSUE 17) benches the SERVING chunk program
instead: the engine's scan chunk vs the fused cache-resident Pallas
decode kernel (`ops/pallas_decode.py`) at the serve geometry, plus the
deterministic per-chunk HBM byte ledger (`modeled_chunk_bytes`) — the
box-constraint proof arm. Emits one ``kind=serve_kernel`` row to the
bench history (``ok`` = modeled_speedup >= 2.0, the ISSUE 17
acceptance floor; wall-clock columns are informational off a real
mesh — interpret mode compiles the kernel to plain XLA on CPU).
"""

import argparse
import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sketch_rnn_tpu.ops.cells import (HyperLSTMCell, LayerNormLSTMCell,
                                      LSTMCell)
from sketch_rnn_tpu.ops.pallas_fused import fused_lstm, fused_ln_lstm
from sketch_rnn_tpu.ops.rnn import run_rnn

T = int(os.environ.get("KB_T", "250"))
B = int(os.environ.get("KB_B", "128"))
H = int(os.environ.get("KB_H", "512"))
D = int(os.environ.get("KB_D", "133"))
DT = os.environ.get("KB_DTYPE", "float32")
STEPS = int(os.environ.get("KB_STEPS", "20"))
CD = jnp.bfloat16 if DT == "bfloat16" else None


def timeit(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / STEPS)
    return best * 1e3  # ms


def main():
    results = {}
    for name, cell_cls in (("lstm", LSTMCell), ("layer_norm",
                                                LayerNormLSTMCell)):
        cell = cell_cls(H, compute_dtype=CD)
        params = cell.init_params(jax.random.key(0), D)
        xs = jax.random.normal(jax.random.key(1), (T, B, D))
        c0 = jnp.zeros((B, H))
        h0 = jnp.zeros((B, H))

        def scan_loss(params_, xs_):
            _, hs = run_rnn(cell, params_, xs_, carry0=(c0, h0))
            return jnp.mean(hs ** 2)

        if name == "lstm":
            def fused_loss(params_, xs_):
                wx = params_["wx"].astype(CD) if CD else params_["wx"]
                wh = params_["wh"].astype(CD) if CD else params_["wh"]
                hs, _ = fused_lstm(xs_, wx, params_["b"], wh, c0, h0, 1.0)
                return jnp.mean(hs ** 2)
        else:
            def fused_loss(params_, xs_):
                wx = params_["wx"].astype(CD) if CD else params_["wx"]
                wh = params_["wh"].astype(CD) if CD else params_["wh"]
                hs, _ = fused_ln_lstm(xs_, wx, wh,
                                      params_["ln_gamma"],
                                      params_["ln_beta"],
                                      params_["lnc_gamma"],
                                      params_["lnc_beta"], c0, h0, 1.0)
                return jnp.mean(hs ** 2)

        for label, loss in (("scan", scan_loss), ("fused", fused_loss)):
            fwd = jax.jit(loss)
            fwdbwd = jax.jit(jax.grad(loss, argnums=(0, 1)))
            r = {
                "fwd_ms": round(timeit(fwd, params, xs), 2),
                "fwdbwd_ms": round(timeit(fwdbwd, params, xs), 2),
            }
            results[f"{name}/{label}"] = r
            print(f"{name:10s} {label:6s} fwd {r['fwd_ms']:8.2f} ms   "
                  f"fwd+bwd {r['fwdbwd_ms']:8.2f} ms", flush=True)

    # hyper cell: nested carry, dispatched through run_rnn(fused=...) —
    # the same path the model uses (flagship hyper sizes 256/32)
    cell = HyperLSTMCell(H, hyper_size=256, embed_size=32, compute_dtype=CD)
    params = cell.init_params(jax.random.key(0), D)
    xs = jax.random.normal(jax.random.key(1), (T, B, D))
    carry0 = cell.initial_carry(B)

    def hyper_loss(fused):
        def f(params_, xs_):
            _, hs = run_rnn(cell, params_, xs_, carry0=carry0, fused=fused)
            return jnp.mean(hs ** 2)
        return f

    for label, fused in (("scan", False), ("fused", True)):
        loss = hyper_loss(fused)
        fwd = jax.jit(loss)
        fwdbwd = jax.jit(jax.grad(loss, argnums=(0, 1)))
        r = {
            "fwd_ms": round(timeit(fwd, params, xs), 2),
            "fwdbwd_ms": round(timeit(fwdbwd, params, xs), 2),
        }
        results[f"hyper/{label}"] = r
        print(f"{'hyper':10s} {label:6s} fwd {r['fwd_ms']:8.2f} ms   "
              f"fwd+bwd {r['fwdbwd_ms']:8.2f} ms", flush=True)

    print(json.dumps({"shape": [T, B, H, D], "dtype": DT, **results}))


def serve_decode_main(args) -> int:
    """The serving arm: scan chunk program vs fused decode kernel at
    the serve geometry, + the modeled HBM byte ledger."""
    import numpy as np

    from scripts._measure import hist_append
    from sketch_rnn_tpu.config import get_default_hparams
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.ops.pallas_decode import modeled_chunk_bytes
    from sketch_rnn_tpu.serve.engine import START_TOKEN, make_chunk_step

    slots, chunk = args.slots, args.chunk
    hps = get_default_hparams().replace(
        dec_model=args.dec_model, dec_rnn_size=args.dec_rnn_size,
        enc_rnn_size=16, z_size=8, num_mixture=5,
        max_seq_len=max(chunk * 4, 32), serve_slots=slots,
        serve_chunk=chunk, conditional=args.conditional)
    model = SketchRNN(hps)
    params = model.init_params(jax.random.key(args.seed))

    # one steady-state pool: every slot live, uniform caps far past
    # the bench window — both flavors run identical, deterministic work
    n = slots
    keys = jax.vmap(jax.random.fold_in,
                    (None, 0))(jax.random.key(args.seed + 1),
                               jnp.arange(n))
    pool = (jax.vmap(jax.random.key_data)(keys),
            (jax.random.normal(jax.random.key(2), (n, hps.z_size))
             if hps.conditional else None),
            None,
            jnp.full((n,), 0.7, jnp.float32),
            jnp.full((n,), 10 * chunk, jnp.int32),
            None, None, None)
    carry = model.decoder_initial_carry(
        params, jnp.zeros((slots, hps.z_size)), slots)
    prev = jnp.broadcast_to(jnp.asarray(START_TOKEN, jnp.float32),
                            (slots, 5))
    t = jnp.zeros((slots,), jnp.int32)
    done = jnp.zeros((slots,), bool)
    reset = jnp.ones((slots,), bool)
    slot_idx = jnp.arange(slots, dtype=jnp.int32)
    state = (carry, prev, t, done, reset, slot_idx, pool)

    outs = {}
    times = {}
    for kernel in ("scan", "pallas"):
        fn = jax.jit(make_chunk_step(model, hps, chunk, params,
                                     kernel=kernel))
        outs[kernel] = fn(*state)
        times[kernel] = timeit(lambda: fn(*state))
    parity = float(jnp.max(jnp.abs(outs["scan"][4]
                                   - outs["pallas"][4])))

    extra = model._decoder_extra(params, pool[1], pool[2])
    extra_dim = 0 if extra is None else int(extra.shape[-1])
    ledger = modeled_chunk_bytes(
        slots, chunk, hps.dec_rnn_size, 5 + extra_dim,
        3 + 6 * hps.num_mixture, extra_dim=extra_dim)

    dev = jax.devices()[0].device_kind
    rec = {
        "kind": "serve_kernel",
        "smoke": dev == "cpu",
        "device_kind": dev,
        "dec_model": hps.dec_model,
        "conditional": bool(hps.conditional),
        "slots": slots,
        "chunk": chunk,
        "dec_rnn_size": hps.dec_rnn_size,
        "num_mixture": hps.num_mixture,
        "scan_chunk_ms": round(times["scan"], 3),
        "pallas_chunk_ms": round(times["pallas"], 3),
        "measured_ratio": round(times["scan"] / times["pallas"], 3),
        "parity_max_diff": parity,
        **{k: (round(v, 3) if isinstance(v, float) else v)
           for k, v in ledger.items()},
        # the deterministic acceptance signal (ISSUE 17): the modeled
        # per-chunk HBM traffic ratio — wall-clock stays informational
        # until a real mesh runs this
        "ok": ledger["modeled_speedup"] >= 2.0,
    }
    print(f"# scan {times['scan']:.3f} ms/chunk, pallas "
          f"{times['pallas']:.3f} ms/chunk, modeled HBM ratio "
          f"{ledger['modeled_speedup']:.2f}x "
          f"({ledger['scan_chunk_bytes']:,} -> "
          f"{ledger['kernel_chunk_bytes']:,} bytes/chunk), parity "
          f"{parity:.2e}", file=sys.stderr)
    print(json.dumps(hist_append(rec)))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("train", "serve_decode"),
                    default="train",
                    help="train = the fwd/bwd training-kernel bench "
                         "(default; KB_* env knobs); serve_decode = "
                         "the ISSUE 17 serving chunk bench")
    ap.add_argument("--slots", type=int, default=32,
                    help="serve_decode: engine slot count B")
    ap.add_argument("--chunk", type=int, default=8,
                    help="serve_decode: decode steps per dispatch K")
    ap.add_argument("--dec_rnn_size", type=int, default=256,
                    help="serve_decode: decoder width H")
    ap.add_argument("--dec_model", choices=("lstm", "layer_norm"),
                    default="lstm",
                    help="serve_decode: cell kind (the fused kernel's "
                         "supported set)")
    ap.add_argument("--conditional", action="store_true",
                    help="serve_decode: z-conditional decode (adds the "
                         "hoisted extra operand)")
    ap.add_argument("--seed", type=int, default=0)
    a = ap.parse_args()
    if a.mode == "serve_decode":
        sys.exit(serve_decode_main(a))
    main()
