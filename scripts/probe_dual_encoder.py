"""Probe: dual-chain encoder kernel — both bi-LSTM directions per grid step.

Hypothesis (from the r3 step-time breakdown + NOTES' negative results):
the encoder kernels are bound by the SERIAL dependency chain of the
per-step ``h @ wh`` matmul — each grid step's recurrent matmul waits on
the previous step's result, so the MXU idles most of each ~15-20 us grid
step (the matmul itself is ~3 us at tile 1024 x H 256). Time-unrolling
two DEPENDENT steps per program measured SLOWER (NOTES: 51.1 vs
45.7 ms) because it lengthens the in-body serial chain. But the
encoder's forward and backward DIRECTIONS are two INDEPENDENT chains
over the same data — interleaving them in one kernel lets each
direction's matmul issue while the other's is still in flight, for up
to 2x on a latency-bound kernel at unchanged tile size.

This probe times the forward pass (sequence-only contract, no dropout):
  A. two ``fused_lstm_seq``-style single-direction calls (production)
  B. one dual-chain call doing both directions per grid step
interleaved A/B/A/B in one process so a tunnel window shift cannot bias
the comparison, checks numerical parity, and prints the verdict.

Results land in NOTES.md / BENCH_HISTORY (kind=probe_dual_encoder).
Usage: python scripts/probe_dual_encoder.py [--reps 7]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import statistics
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts._measure import drain, hist_append  # noqa: E402
from sketch_rnn_tpu.ops.pallas_fused import (  # noqa: E402
    _batch_tile_seq,
    _cast,
    _interpret_default,
    _lstm_gates,
    _sds,
)


def _dual_seq_fwd_kernel(xf_ref, xb_ref, wxf_ref, bf_ref, whf_ref,
                         wxb_ref, bb_ref, whb_ref,
                         hsf_ref, csf_ref, hsb_ref, csb_ref,
                         cf_scr, hf_scr, cb_scr, hb_scr, *, forget_bias):
    """One grid step advances BOTH directions one time step.

    The two directions' recurrent matmuls are data-independent, so the
    second can issue while the first is in flight — the point of the
    probe. Zero initial carries (encoder contract)."""
    it = pl.program_id(1)

    @pl.when(it == 0)
    def _():
        cf_scr[:] = jnp.zeros_like(cf_scr)
        hf_scr[:] = jnp.zeros_like(hf_scr)
        cb_scr[:] = jnp.zeros_like(cb_scr)
        hb_scr[:] = jnp.zeros_like(hb_scr)

    def one(x_ref, wx_ref, b_ref, wh_ref, c_scr, h_scr, hs_ref, cs_ref):
        c, h = c_scr[:], h_scr[:]
        pre = (jnp.dot(_cast(x_ref[0], wx_ref), wx_ref[:],
                       preferred_element_type=jnp.float32)
               + b_ref[0]
               + jnp.dot(_cast(h, wh_ref), wh_ref[:],
                         preferred_element_type=jnp.float32))
        _, _, _, o, new_c = _lstm_gates(pre, c, None,
                                        forget_bias=forget_bias)
        new_h = jnp.tanh(new_c) * o
        cs_ref[0] = c.astype(cs_ref.dtype)
        c_scr[:] = new_c
        h_scr[:] = new_h
        hs_ref[0] = new_h.astype(hs_ref.dtype)

    one(xf_ref, wxf_ref, bf_ref, whf_ref, cf_scr, hf_scr, hsf_ref, csf_ref)
    one(xb_ref, wxb_ref, bb_ref, whb_ref, cb_scr, hb_scr, hsb_ref, csb_ref)


def dual_seq_fwd(xs_f, xs_b, wx_f, b_f, wh_f, wx_b, b_b, wh_b,
                 forget_bias=1.0, residual_dtype=jnp.bfloat16, bt=None):
    t, bsz, d = xs_f.shape
    h = wh_f.shape[0]
    bt = bt or _batch_tile_seq(bsz, h)
    b2f = b_f.reshape(1, -1).astype(jnp.float32)
    b2b = b_b.reshape(1, -1).astype(jnp.float32)
    step = lambda s: pl.BlockSpec((1, *s), lambda ib, it: (it, ib, 0))
    whole = lambda s: pl.BlockSpec(s, lambda ib, it: (0,) * len(s))

    kernel = functools.partial(_dual_seq_fwd_kernel,
                               forget_bias=forget_bias)
    outs = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[step((bt, d)), step((bt, d)),
                  whole(wx_f.shape), whole(b2f.shape), whole(wh_f.shape),
                  whole(wx_b.shape), whole(b2b.shape), whole(wh_b.shape)],
        out_specs=(step((bt, h)), step((bt, h)),
                   step((bt, h)), step((bt, h))),
        out_shape=tuple(_sds((t, bsz, h), residual_dtype, xs_f)
                        for _ in range(4)),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32)
                        for _ in range(4)],
        interpret=_interpret_default(),
    )(xs_f, xs_b, wx_f, b2f, wh_f, wx_b, b2b, wh_b)
    return outs  # hs_f, cs_f, hs_b, cs_b


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=7)
    ap.add_argument("--t", type=int, default=250)
    ap.add_argument("--b", type=int, default=4096)
    ap.add_argument("--h", type=int, default=256)
    ap.add_argument("--d", type=int, default=5)
    ap.add_argument("--tile", type=int, default=0,
                    help="dual-kernel batch tile override (0 = same as "
                         "the single kernel's _batch_tile_seq)")
    args = ap.parse_args()
    from sketch_rnn_tpu.ops.pallas_fused import fused_lstm_seq

    T, B, H, D = args.t, args.b, args.h, args.d
    K = 8  # kernel calls per jit dispatch: the tunnel's per-call latency
    # (up to ~130 ms in slow windows) would otherwise swamp the ~20 ms
    # arm difference being measured; K distinct input slices prevent CSE
    k = jax.random.split(jax.random.key(0), 8)
    xs_k = jax.random.normal(k[0], (K, T, B, D), jnp.float32)
    xs_f = xs_k[0]
    xs_b = jnp.flip(xs_f, axis=0)
    mk = lambda key, s: (jax.random.normal(key, s, jnp.float32)
                         * 0.1).astype(jnp.bfloat16)
    wx_f, wx_b = mk(k[1], (D, 4 * H)), mk(k[2], (D, 4 * H))
    wh_f, wh_b = mk(k[3], (H, 4 * H)), mk(k[4], (H, 4 * H))
    b_f = jnp.zeros((4 * H,), jnp.float32)
    b_b = jnp.zeros((4 * H,), jnp.float32)
    zc = jnp.zeros((B, H), jnp.float32)
    bt = args.tile or None

    @jax.jit
    def single_k():
        def body(_, xf):
            xb = jnp.flip(xf, axis=0)
            hf = fused_lstm_seq(xf, wx_f, b_f, wh_f, zc, zc, 1.0, None,
                                None, 1.0, jnp.bfloat16)
            hb = fused_lstm_seq(xb, wx_b, b_b, wh_b, zc, zc, 1.0, None,
                                None, 1.0, jnp.bfloat16)
            return 0.0, (hf[0, 0, 0] + hb[0, 0, 0]).astype(jnp.float32)
        _, outs = jax.lax.scan(body, 0.0, xs_k)
        return outs

    @jax.jit
    def dual_k():
        def body(_, xf):
            xb = jnp.flip(xf, axis=0)
            hf, _, hb, _ = dual_seq_fwd(xf, xb, wx_f, b_f, wh_f,
                                        wx_b, b_b, wh_b, bt=bt)
            return 0.0, (hf[0, 0, 0] + hb[0, 0, 0]).astype(jnp.float32)
        _, outs = jax.lax.scan(body, 0.0, xs_k)
        return outs

    # parity first (single unscanned calls)
    hf_s = fused_lstm_seq(xs_f, wx_f, b_f, wh_f, zc, zc, 1.0, None, None,
                          1.0, jnp.bfloat16)
    hb_s = fused_lstm_seq(xs_b, wx_b, b_b, wh_b, zc, zc, 1.0, None, None,
                          1.0, jnp.bfloat16)
    hf_d, _, hb_d, _ = dual_seq_fwd(xs_f, xs_b, wx_f, b_f, wh_f,
                                    wx_b, b_b, wh_b, bt=bt)
    np.testing.assert_allclose(np.asarray(hf_d, np.float32),
                               np.asarray(hf_s, np.float32),
                               atol=1e-2, rtol=1e-2)
    np.testing.assert_allclose(np.asarray(hb_d, np.float32),
                               np.asarray(hb_s, np.float32),
                               atol=1e-2, rtol=1e-2)
    print("# parity OK", file=sys.stderr)

    def timed(fn):
        t0 = time.perf_counter()
        drain(fn())
        return time.perf_counter() - t0

    # interleaved A/B so a window shift hits both arms equally
    ts_s, ts_d = [], []
    timed(single_k), timed(dual_k)  # settle
    for _ in range(args.reps):
        ts_s.append(timed(single_k))
        ts_d.append(timed(dual_k))
    ms = statistics.median(ts_s) * 1e3 / K
    md = statistics.median(ts_d) * 1e3 / K
    rec = {
        "kind": "probe_dual_encoder",
        "T": T, "B": B, "H": H, "D": D,
        "tile": args.tile or _batch_tile_seq(B, H),
        "reps": args.reps,
        "calls_per_dispatch": K,
        "single_2calls_ms": round(ms, 2),
        "dual_ms": round(md, 2),
        "speedup": round(ms / md, 3),
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(rec, indent=2))
    hist_append(rec)
    return 0


if __name__ == "__main__":
    sys.exit(main())
